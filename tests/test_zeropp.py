"""ZeRO++ quantized collectives (SURVEY §2.2): qwZ int8 param all-gather and
qgZ int8 gradient reduce-scatter.

Oracles: the explicit (non-quantized) gather path must be numerically
transparent; the quantized paths must stay within int8 rounding error of the
dense collectives and must put ~4x fewer bytes on the wire (comm-hook
byte accounting)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import collectives
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.config import DeepSpeedConfigError
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.zero.quantized import (
    gather_dim_and_axes,
    make_quantized_gather,
)


def _topo(n=8):
    comm.destroy_process_group()
    topo = MeshTopology(ParallelDims(dp=n), devices=jax.devices()[:n])
    comm.set_topology(topo)
    return topo


def test_gather_dim_and_axes():
    assert gather_dim_and_axes(P("dp", "tp"), P(None, "tp"), 2) == (0, ("dp",))
    assert gather_dim_and_axes(P(None, ("dp", "fsdp")), P(), 2) == (
        1,
        ("dp", "fsdp"),
    )
    assert gather_dim_and_axes(P(None, "tp"), P(None, "tp"), 2) is None


def _gather_fixture(topo, quant_weights, quant_grads, shape=(16, 8)):
    w = jnp.asarray(np.random.RandomState(0).randn(*shape), jnp.float32)
    pspec, tpspec = P("dp"), P()
    w_sharded = jax.device_put(w, NamedSharding(topo.mesh, pspec))
    gather = make_quantized_gather(
        topo,
        {"w": pspec},
        {"w": tpspec},
        {"w": jax.ShapeDtypeStruct(shape, jnp.float32)},
        quant_weights,
        quant_grads,
    )
    return w, w_sharded, gather


def test_explicit_gather_exact(devices8):
    """qw=False qg=False path is numerically transparent (no quantization)."""
    topo = _topo()
    w, w_sharded, gather = _gather_fixture(topo, False, False)
    out = jax.jit(lambda p: gather(p)["w"])(({"w": w_sharded}))
    np.testing.assert_array_equal(np.asarray(out), np.asarray(w))


def test_quantized_gather_roundtrip(devices8):
    """qwZ: gathered weights match within int8 rounding (amax/127 per lane)."""
    topo = _topo()
    w, w_sharded, gather = _gather_fixture(topo, True, False)
    out = np.asarray(jax.jit(lambda p: gather(p)["w"])({"w": w_sharded}))
    # per-lane tolerance: each shard chunk quantized against its own amax
    tol = np.abs(np.asarray(w)).max() / 127.0 + 1e-6
    assert np.abs(out - np.asarray(w)).max() <= tol


@pytest.mark.parametrize("quant_grads", [False, True])
def test_gather_backward_is_reduce_scatter(quant_grads, devices8):
    """Backward of the gather == gradient reduce-scatter: grad wrt the local
    shard equals the corresponding slice of the full upstream gradient."""
    topo = _topo()
    w, w_sharded, gather = _gather_fixture(topo, False, quant_grads)
    c = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)

    def loss(p):
        return jnp.sum(gather(p)["w"] * c)

    g = jax.jit(jax.grad(loss))({"w": w_sharded})["w"]
    got = np.asarray(g)
    want = np.asarray(c)  # d(sum(w*c))/dw = c, scattered == same layout
    if quant_grads:
        tol = np.abs(want).max() / 127.0 + 1e-6
        assert np.abs(got - want).max() <= tol
    else:
        np.testing.assert_allclose(got, want, rtol=1e-6)


BASE = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "steps_per_print": 100,
}


def _run(cfg_extra, steps=3, hook=None):
    comm.destroy_process_group()
    if hook is not None:
        collectives.register_comm_hook(hook)
    try:
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
            config=dict(BASE, **cfg_extra),
            rng=jax.random.PRNGKey(7),
        )
        data = {
            "input_ids": np.random.RandomState(0).randint(0, 128, size=(16, 16))
        }
        return [float(engine.train_batch(batch=data)) for _ in range(steps)]
    finally:
        if hook is not None:
            collectives.unregister_comm_hook(hook)


def test_zeropp_trains_close_to_dense(devices8):
    zero3 = {"zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 1}}
    zeropp = {
        "zero_optimization": {
            "stage": 3,
            "stage3_param_persistence_threshold": 1,
            "zero_quantized_weights": True,
            "zero_quantized_gradients": True,
        }
    }
    dense = _run(zero3)
    quant = _run(zeropp)
    assert quant[-1] < quant[0], quant  # still learns
    # int8-lossy but tracks the dense trajectory
    assert abs(quant[0] - dense[0]) / dense[0] < 0.03, (dense, quant)
    assert abs(quant[-1] - dense[-1]) / dense[-1] < 0.10, (dense, quant)


def test_zeropp_reduces_wire_bytes(devices8):
    records = []
    dense_records = []
    _run(
        {
            "zero_optimization": {
                "stage": 3,
                "stage3_param_persistence_threshold": 1,
                "zero_quantized_weights": True,
                "zero_quantized_gradients": True,
            }
        },
        steps=1,
        hook=lambda op, axis, nbytes: records.append((op, nbytes)),
    )
    gathers = [b for op, b in records if op == "all_gather"]
    a2a = [b for op, b in records if op == "all_to_all"]
    assert gathers, "quantized all-gather never recorded"
    assert a2a, "quantized grad all-to-all never recorded"

    _run(
        {"zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 1}},
        steps=1,
        hook=lambda op, axis, nbytes: dense_records.append((op, nbytes)),
    )
    # dense path gathers implicitly (XLA) → no explicit records; compare
    # against the fp32 leaf sizes instead: int8+scale < 1/2 of fp32 bytes
    comm.destroy_process_group()
    model = gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16)
    shapes = jax.eval_shape(lambda k: model.init(k), jax.random.PRNGKey(0))
    biggest = max(
        int(np.prod(s.shape)) * 4 for s in jax.tree_util.tree_leaves(shapes)
    )
    assert max(gathers) < biggest / 2, (max(gathers), biggest)


def test_config_rejects_quantized_below_stage3():
    from deepspeed_tpu.config import DeepSpeedConfig

    with pytest.raises(DeepSpeedConfigError, match="ZeRO\\+\\+"):
        DeepSpeedConfig(
            dict(
                BASE,
                zero_optimization={"stage": 2, "zero_quantized_weights": True},
            )
        )
