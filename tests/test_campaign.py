"""Campaign machinery (ISSUE 16): device-kind gen detection, the
per-(gen, topology, model-class) knob-default table, ``"auto"``
resolution with the parity/staleness gates, drift-tag separation, and
the end-to-end CPU campaign with its bitwise closing oracle."""

import json
import os

import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.analysis.cost import drift
from deepspeed_tpu.analysis.cost import hardware as hw
from deepspeed_tpu.config import (
    AUTO,
    DeepSpeedConfig,
    _jax_major_minor,
    resolve_auto_knobs,
)


def tiny_llama(num_layers=2):
    from deepspeed_tpu.models import llama

    return llama(
        "llama-tiny", vocab_size=128, max_seq_len=32, hidden_size=64,
        num_layers=num_layers, num_heads=4, num_kv_heads=4, head_dim=16,
        intermediate_size=128,
    )


def table_row(knobs, gen="cpu", topo="dp8", mclass="unknown",
              jax_mm=None, evidence=None):
    """A well-formed table row with fresh evidence for every knob unless
    overridden."""
    ev = {path: {"predicted_step_s": 1.0, "measured_step_s": 1.0,
                 "parity": "test"}
          for path in knobs}
    ev.update(evidence or {})
    return {
        "gen": gen, "topology": topo, "model_class": mclass,
        "knobs": dict(knobs), "evidence": ev,
        "jax": jax_mm if jax_mm is not None else _jax_major_minor(),
        "winner": "test", "created": 0.0,
    }


def base_cfg_dict(**over):
    d = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-4}},
    }
    d.update(over)
    return d


# ------------------------------------------------------- gen detection
@pytest.mark.parametrize("kind,gen", [
    ("TPU v4", "v4"),
    ("TPU v5e", "v5e"),
    ("TPU v5 lite", "v5e"),
    ("TPU v5litepod-16", "v5e"),
    ("TPU v5p", "v5p"),
    ("TPU v5", "v5p"),
    ("TPU v6e", "v6e"),
    ("TPU v6 lite", "v6e"),
])
def test_gen_from_device_kind(kind, gen):
    assert hw.gen_from_device_kind(kind) == gen


@pytest.mark.parametrize("kind", [None, "", "TPU v3", "Interpreter",
                                  "future-chip-x9"])
def test_gen_from_device_kind_unknown(kind):
    assert hw.gen_from_device_kind(kind) is None


def test_detect_gen_env_pin(monkeypatch):
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "v6e")
    assert hw.detect_gen() == "v6e"


def test_detect_gen_cpu_backend(monkeypatch):
    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    assert hw.detect_gen() == "cpu"  # the test mesh is the CPU backend


def test_detect_gen_mocked_tpu_kind(monkeypatch):
    import jax

    class FakeDev:
        device_kind = "TPU v5p"

    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    assert hw.detect_gen() == "v5p"
    assert hw.HardwareModel.detect().gen == "v5p"


def test_detect_gen_unknown_kind_falls_back_v5e_warns_once(monkeypatch):
    import jax

    class FakeDev:
        device_kind = "TPU v99 prototype"

    monkeypatch.delenv("PALLAS_AXON_TPU_GEN", raising=False)
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    monkeypatch.setattr(jax, "devices", lambda: [FakeDev()])
    assert hw.detect_gen() == "v5e"
    assert "TPU v99 prototype" in hw._WARNED_KINDS
    assert hw.detect_gen() == "v5e"  # second call: no re-warn, same answer


# --------------------------------------------------------- table lookup
def test_lookup_hit():
    row = table_row({"tensor_parallel.overlap_comm": True})
    table = {"version": 1, "entries": [row]}
    got, prov = hw.lookup_knob_row(table, "cpu", "dp8", "unknown")
    assert got is row
    assert prov == "table:cpu/dp8/unknown"


def test_lookup_gen_fallback_v6e_to_v5e():
    row = table_row({"zero_optimization.stage3_layer_prefetch": True},
                    gen="v5e")
    table = {"version": 1, "entries": [row]}
    got, prov = hw.lookup_knob_row(table, "v6e", "dp8", "unknown")
    assert got is row
    assert prov == "table:v5e/dp8/unknown"


def test_lookup_miss_and_cpu_never_borrows_tpu_rows():
    row = table_row({"serving.paged": True}, gen="v5e")
    table = {"version": 1, "entries": [row]}
    assert hw.lookup_knob_row(table, "v4", "other-topo", "unknown") == \
        (None, "miss")
    # cpu has an empty fallback chain: plumbing evidence only
    assert hw.lookup_knob_row(table, "cpu", "dp8", "unknown") == \
        (None, "miss")


def test_load_knob_table_corrupt_is_empty(tmp_path):
    p = tmp_path / "bad.json"
    p.write_text("{not json")
    assert hw.load_knob_table(str(p)) == {"version": 1, "entries": []}
    assert hw.load_knob_table(str(tmp_path / "absent.json")) == \
        {"version": 1, "entries": []}


def test_topology_key_orders_axes():
    class Topo:
        sizes = {"tp": 2, "dp": 4, "ep": 1}
        world_size = 8

    assert hw.topology_key(Topo()) == "dp4xtp2"
    assert hw.topology_key(None).startswith("dp")


# ----------------------------------------------------------- resolution
def test_resolve_hit_flips_knob_on():
    row = table_row({"tensor_parallel.overlap_comm": True})
    table = {"version": 1, "entries": [row]}
    cfg = DeepSpeedConfig(base_cfg_dict(
        tensor_parallel={"tp_size": 2, "overlap_comm": AUTO}))
    assert cfg.tensor_parallel.overlap_comm.enabled == AUTO
    report = resolve_auto_knobs(cfg, table=table)
    assert cfg.tensor_parallel.overlap_comm.enabled is True
    assert report["tensor_parallel.overlap_comm"] == {
        "value": True, "source": "table:cpu/dp8/unknown"}


def test_resolve_miss_is_conservative_off():
    cfg = DeepSpeedConfig(base_cfg_dict(
        tensor_parallel={"tp_size": 2, "overlap_comm": AUTO}))
    report = resolve_auto_knobs(cfg, table={"version": 1, "entries": []})
    assert cfg.tensor_parallel.overlap_comm.enabled is False
    assert report["tensor_parallel.overlap_comm"]["source"] == \
        "off-default:miss"


def test_resolve_inapplicable_never_consults_table():
    # tp=1: the knob cannot apply no matter what the table says
    row = table_row({"tensor_parallel.overlap_comm": True})
    cfg = DeepSpeedConfig(base_cfg_dict(
        tensor_parallel={"tp_size": 1, "overlap_comm": AUTO}))
    report = resolve_auto_knobs(cfg, table={"version": 1, "entries": [row]})
    assert cfg.tensor_parallel.overlap_comm.enabled is False
    assert report["tensor_parallel.overlap_comm"]["source"] == "inapplicable"


def test_resolve_stale_jax_invalidates():
    row = table_row({"zero_optimization.stage3_layer_prefetch": True},
                    jax_mm="0.1")
    cfg = DeepSpeedConfig(base_cfg_dict(
        zero_optimization={"stage": 3, "stage3_layer_prefetch": AUTO}))
    report = resolve_auto_knobs(cfg, table={"version": 1, "entries": [row]})
    assert cfg.zero_config.stage3_layer_prefetch is False
    assert report["zero_optimization.stage3_layer_prefetch"]["source"] == \
        "off-default:stale-jax:table:cpu/dp8/unknown"


def test_resolve_stale_band_invalidates():
    # evidence ratio 1/100 is outside even the forgiving cpu band —
    # the row is invalidated, the conservative off default resolves
    path = "zero_optimization.stage3_layer_prefetch"
    row = table_row({path: True}, evidence={
        path: {"predicted_step_s": 1.0, "measured_step_s": 100.0}})
    cfg = DeepSpeedConfig(base_cfg_dict(
        zero_optimization={"stage": 3, "stage3_layer_prefetch": AUTO}))
    report = resolve_auto_knobs(cfg, table={"version": 1, "entries": [row]})
    assert cfg.zero_config.stage3_layer_prefetch is False
    assert report[path]["source"] == \
        "off-default:stale-band:table:cpu/dp8/unknown"


def test_resolve_explicit_values_untouched():
    row = table_row({"tensor_parallel.overlap_comm": True,
                     "serving.paged": True})
    cfg = DeepSpeedConfig(base_cfg_dict(
        tensor_parallel={"tp_size": 2, "overlap_comm": False}))
    report = resolve_auto_knobs(cfg, table={"version": 1, "entries": [row]})
    assert cfg.tensor_parallel.overlap_comm.enabled is False
    assert "tensor_parallel.overlap_comm" not in report  # explicit wins
    assert cfg.serving.paged is False


def test_resolve_wire_codec_from_table():
    row = table_row({"zero_optimization.param_wire": "int8"})
    cfg = DeepSpeedConfig(base_cfg_dict(
        zero_optimization={"stage": 3, "param_wire": AUTO}))
    resolve_auto_knobs(cfg, table={"version": 1, "entries": [row]})
    assert cfg.zero_config.param_wire == "int8"


def test_resolve_wire_codec_miss_keeps_legacy_auto():
    cfg = DeepSpeedConfig(base_cfg_dict(
        zero_optimization={"stage": 3, "param_wire": AUTO}))
    report = resolve_auto_knobs(cfg, table={"version": 1, "entries": []})
    assert cfg.zero_config.param_wire == AUTO  # downstream resolution owns it
    assert report["zero_optimization.param_wire"]["source"] == "legacy-auto"


# --------------------------------------- "auto" through candidate patches
def test_auto_survives_planner_candidate_patches():
    """A base config spelling knobs "auto" must round-trip through every
    planner candidate patch: the candidate's own axes overwrite their
    knobs, every OTHER "auto" survives, and the patched dict still
    validates as a DeepSpeedConfig."""
    from deepspeed_tpu.autotuning import PlannerSearch

    model = tiny_llama()
    base = base_cfg_dict(
        tensor_parallel={"tp_size": 2, "overlap_comm": AUTO},
        zero_optimization={"stage": 3, "offload_double_buffer": AUTO,
                           "stage3_layer_prefetch": AUTO},
        autotuning={"max_train_micro_batch_size_per_gpu": 1},
    )
    search = PlannerSearch(model, base, remat_policies=("none",))
    cands = search.candidates()
    assert len(cands) >= 3
    patched = 0
    for cand in cands:
        cfg_dict = search._candidate_config(cand)
        ds = DeepSpeedConfig(cfg_dict)  # "auto" spellings still validate
        # offload_double_buffer is on no candidate axis: always survives
        assert ds.zero_config.offload_double_buffer == AUTO
        if cand.tp_overlap is not None:
            assert ds.tensor_parallel.overlap_comm.enabled is bool(
                cand.tp_overlap)
            patched += 1
    assert patched > 0


# --------------------------------------------------- drift tag separation
def _pair(ratio, tag=None, source="x"):
    e = {"source": source, "gen": "cpu", "predicted_step_s": ratio,
         "measured_step_s": 1.0, "ratio": ratio, "bound": "flops"}
    if tag:
        e["tag"] = tag
    return e


def test_entry_tag_and_by_tag():
    entries = [_pair(1.0), _pair(1.1, tag="campaign"), _pair(0.9)]
    assert drift.entry_tag(entries[0]) == "adhoc"
    assert drift.entry_tag(entries[1]) == "campaign"
    groups = drift.by_tag(entries)
    assert [len(groups["adhoc"]), len(groups["campaign"])] == [2, 1]


def test_check_spread_judged_per_tag():
    # ad-hoc pairs tight, campaign pairs deliberately heterogeneous
    # (>3x apart but inside the cpu band): only the campaign group may
    # flag spread, and it must say which group drifted
    entries = [_pair(1.0), _pair(1.1),
               _pair(1.0, tag="campaign"), _pair(10.0, tag="campaign")]
    ok, problems = drift.check(entries)
    assert not ok
    assert any("[campaign]" in p for p in problems)
    assert not any("[adhoc]" in p for p in problems)
    # pooled the other way: tight campaign pairs never pay for ad-hoc
    ok2, problems2 = drift.check([_pair(1.0), _pair(10.0),
                                  _pair(1.0, tag="campaign"),
                                  _pair(1.1, tag="campaign")])
    assert any("[adhoc]" in p for p in problems2)
    assert not any("[campaign]" in p for p in problems2)


def test_ledger_load_tag_filter(tmp_path):
    ledger = drift.DriftLedger(str(tmp_path / "d.jsonl"))
    ledger.append(_pair(1.0, source="a"))
    ledger.append(_pair(1.0, tag="campaign", source="b"))
    ledger.append(_pair(1.0, tag="campaign", source="c"))
    assert len(ledger.load()) == 3
    tagged = ledger.load(tag="campaign")
    assert [e["source"] for e in tagged] == ["b", "c"]
    assert [e["source"] for e in ledger.load(tag="adhoc")] == ["a"]


# ------------------------------------------------ bitwise closing oracle
def _one_loss(model, cfg_dict, data):
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg_dict)
    try:
        return float(engine.train_batch(batch=data))
    finally:
        engine.destroy()


def test_resolved_on_knob_bitwise_equals_explicit(tmp_path, monkeypatch):
    """A knob flipped on by table resolution trains bitwise-identically
    to the same knob spelled explicitly on — resolution changes where
    the decision comes from, never what program runs."""
    model = tiny_llama()
    path = "zero_optimization.stage3_layer_prefetch"
    row = table_row({path: True}, topo="dp8",
                    mclass=hw.model_class(model.config))
    tpath = tmp_path / "knob_defaults.json"
    tpath.write_text(json.dumps({"version": 1, "entries": [row]}))
    monkeypatch.setenv(hw.KNOB_TABLE_ENV, str(tpath))
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "cpu")

    data = {"input_ids": np.random.RandomState(0).randint(
        0, 128, size=(8, 32))}

    def cfg(prefetch):
        return base_cfg_dict(zero_optimization={
            "stage": 3, "stage3_layer_prefetch": prefetch})

    loss_auto = _one_loss(model, cfg(AUTO), data)
    loss_explicit = _one_loss(model, cfg(True), data)
    loss_off = _one_loss(model, cfg(False), data)
    assert loss_auto == loss_explicit  # bitwise: the same program ran
    assert loss_off == pytest.approx(loss_auto)  # prefetch is layout-only


def test_engine_resolution_report_names_the_table(tmp_path, monkeypatch):
    model = tiny_llama()
    path = "zero_optimization.stage3_layer_prefetch"
    row = table_row({path: True}, topo="dp8",
                    mclass=hw.model_class(model.config))
    tpath = tmp_path / "knob_defaults.json"
    tpath.write_text(json.dumps({"version": 1, "entries": [row]}))
    monkeypatch.setenv(hw.KNOB_TABLE_ENV, str(tpath))
    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "cpu")
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=base_cfg_dict(zero_optimization={
            "stage": 3, "stage3_layer_prefetch": AUTO}))
    try:
        rep = engine.config.auto_resolution
        assert rep[path]["value"] is True
        assert rep[path]["source"].startswith("table:cpu/")
    finally:
        engine.destroy()


# ----------------------------------------------------- e2e CPU campaign
@pytest.mark.slow
def test_campaign_end_to_end_cpu(tmp_path, monkeypatch):
    """The whole chain in-process on the tiny model: enumerate ≥ 3 knob
    axes, compile ≤ top-k, bank campaign-tagged pairs, emit a row, and
    re-resolve a fresh all-"auto" config onto the winner."""
    from deepspeed_tpu.autotuning import (
        emit_table, run_campaign, verify_roundtrip,
    )
    from deepspeed_tpu.autotuning.campaign import candidate_knobs

    monkeypatch.setenv("PALLAS_AXON_TPU_GEN", "cpu")
    model = tiny_llama()
    rng = np.random.RandomState(0)

    def sample_batch(global_batch):
        return {"input_ids": rng.randint(0, 128, size=(global_batch, 32))}

    base = base_cfg_dict(
        zero_optimization={"stage": 3},
        autotuning={"max_train_micro_batch_size_per_gpu": 1, "top_k": 2,
                    "trials": 1, "start_profile_step": 1,
                    "end_profile_step": 2},
    )
    ledger_path = str(tmp_path / "drift.jsonl")
    out = run_campaign(model, base, sample_batch_fn=sample_batch,
                       top_k=2, drift_ledger_path=ledger_path)
    result = out["search"]
    axes = set()
    for pc in result.planned:
        axes.update(candidate_knobs(pc.cand))
    assert len(axes) >= 3, axes
    assert out["banked"] >= 1
    tagged = drift.DriftLedger(ledger_path).load(tag="campaign")
    assert len(tagged) == out["banked"]
    assert all(e["source"].startswith("campaign:") for e in tagged)

    row = out["row"]
    assert row is not None and row["gen"] == "cpu"
    tpath = str(tmp_path / "table.json")
    emit_table([row], tpath)
    rt = verify_roundtrip(base, tpath, model=model)
    for path, want in row["knobs"].items():
        if isinstance(want, bool):
            assert rt["resolved"][path] is want, (path, rt["resolved"])
