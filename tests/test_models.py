"""Model family tests. Oracle style: numpy/manual references (reference
model: tests/unit/model_parallelism + megatron model tests)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.models import bloom, gpt2, llama, make_lm_batch, mixtral
from deepspeed_tpu.models.transformer import alibi_slopes
from deepspeed_tpu.ops.attention import xla_attention

FAMILIES = {
    "gpt2": lambda: gpt2("gpt2-tiny", vocab_size=128, max_seq_len=32),
    "llama": lambda: llama("llama-tiny", vocab_size=128, max_seq_len=32),
    "bloom": lambda: bloom("bloom-tiny", vocab_size=128, max_seq_len=32),
    "mixtral": lambda: mixtral("mixtral-tiny", vocab_size=128, max_seq_len=32),
}


@pytest.fixture(scope="module")
def rng():
    return jax.random.PRNGKey(0)


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_forward_loss_grads(family, rng):
    m = FAMILIES[family]()
    params = m.init(rng)
    ids = jax.random.randint(rng, (2, 16), 0, 128)
    batch = make_lm_batch(ids)
    loss, metrics = m.loss(params, batch, rng=rng)
    assert np.isfinite(float(loss))
    assert 3.0 < float(loss) < 8.0  # ~ln(128)=4.85 at init
    grads = jax.grad(lambda p: m.loss(p, batch, rng=rng)[0])(params)
    gnorm = sum(float(jnp.sum(jnp.square(g))) for g in jax.tree_util.tree_leaves(grads))
    assert gnorm > 0.0


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_analytic_param_count(family, rng):
    m = FAMILIES[family]()
    params = m.init(rng)
    actual = sum(int(x.size) for x in jax.tree_util.tree_leaves(params))
    assert actual == m.num_params()


@pytest.mark.parametrize("family", sorted(FAMILIES))
def test_partition_spec_tree_matches_params(family, rng):
    m = FAMILIES[family]()
    params = m.init(rng)
    specs = m.partition_specs()
    # same tree structure, and every spec rank == param rank
    jax.tree_util.tree_map(
        lambda p, s: None
        if len(s) <= p.ndim
        else pytest.fail(f"spec {s} too long for shape {p.shape}"),
        params,
        specs,
    )


def test_remat_matches_no_remat(rng):
    m = FAMILIES["llama"]()
    params = m.init(rng)
    batch = make_lm_batch(jax.random.randint(rng, (2, 16), 0, 128))
    l1, _ = m.loss(params, batch, rng=rng)
    l2, _ = m.loss(params, batch, rng=rng, remat_policy="full")
    l3, _ = m.loss(params, batch, rng=rng, remat_policy="dots_saveable")
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)
    np.testing.assert_allclose(float(l1), float(l3), rtol=1e-5)


def test_causality(rng):
    """Future tokens must not affect earlier logits."""
    m = FAMILIES["llama"]()
    params = m.init(rng)
    ids = jax.random.randint(rng, (1, 16), 0, 128)
    logits1, _ = m.apply(params, ids, dtype=jnp.float32)
    ids2 = ids.at[0, 10:].set(7)  # perturb the tail
    logits2, _ = m.apply(params, ids2, dtype=jnp.float32)
    np.testing.assert_allclose(
        np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-4
    )


def test_attention_matches_manual_reference(rng):
    B, S, H, hd = 2, 8, 4, 16
    q = jax.random.normal(rng, (B, S, H, hd))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, S, H, hd))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, S, H, hd))
    out = xla_attention(q, k, v, causal=True)
    # manual per-position loop oracle
    qn, kn, vn = map(np.asarray, (q, k, v))
    expected = np.zeros_like(qn)
    for b in range(B):
        for h in range(H):
            for i in range(S):
                scores = qn[b, i, h] @ kn[b, : i + 1, h].T / np.sqrt(hd)
                w = np.exp(scores - scores.max())
                w /= w.sum()
                expected[b, i, h] = w @ vn[b, : i + 1, h]
    np.testing.assert_allclose(np.asarray(out), expected, atol=1e-5)


def test_gqa_equals_repeated_kv(rng):
    B, S, H, KV, hd = 1, 8, 4, 2, 16
    q = jax.random.normal(rng, (B, S, H, hd))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (B, S, KV, hd))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (B, S, KV, hd))
    out_gqa = xla_attention(q, k, v, causal=True)
    out_mha = xla_attention(
        q, jnp.repeat(k, H // KV, axis=2), jnp.repeat(v, H // KV, axis=2), causal=True
    )
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha), atol=1e-6)


def test_alibi_slopes_power_of_two():
    s = alibi_slopes(8)
    np.testing.assert_allclose(s, [2 ** (-(i + 1)) for i in range(8)], rtol=1e-6)
    assert len(alibi_slopes(12)) == 12  # non-power-of-two path


def test_tied_embeddings_share_gradient(rng):
    m = FAMILIES["gpt2"]()
    params = m.init(rng)
    assert "lm_head" not in params
    batch = make_lm_batch(jax.random.randint(rng, (1, 8), 0, 128))
    grads = jax.grad(lambda p: m.loss(p, batch)[0])(params)
    # embedding grad receives both embed and lm-head contributions => nonzero
    assert float(jnp.sum(jnp.abs(grads["embed"]["tok"]))) > 0
