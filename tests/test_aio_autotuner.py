"""C++ aio backend, tensor swapper, autotuner (SURVEY §2.2, §2.7)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import gpt2


def test_aio_write_read_roundtrip(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(num_threads=2)
    r = np.random.RandomState(0)
    data = r.randn(1000).astype(np.float32)
    path = str(tmp_path / "x.bin")
    req = h.submit_write(path, data)
    h.wait(req)
    assert os.path.getsize(path) == data.nbytes

    out = np.empty_like(data)
    h.wait(h.submit_read(path, out))
    np.testing.assert_array_equal(out, data)
    h.close()


def test_aio_many_concurrent(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(num_threads=4)
    r = np.random.RandomState(1)
    arrays = [r.randn(256 + i).astype(np.float64) for i in range(20)]
    reqs = [
        h.submit_write(str(tmp_path / f"f{i}.bin"), a)
        for i, a in enumerate(arrays)
    ]
    h.wait_all()
    outs = [np.empty_like(a) for a in arrays]
    for i, o in enumerate(outs):
        h.wait(h.submit_read(str(tmp_path / f"f{i}.bin"), o))
    for a, o in zip(arrays, outs):
        np.testing.assert_array_equal(a, o)
    h.close()


def test_aio_read_missing_file_errors(tmp_path):
    from deepspeed_tpu.ops.aio import AsyncIOHandle

    h = AsyncIOHandle(num_threads=1)
    buf = np.empty(16, np.float32)
    with pytest.raises(OSError):
        h.wait(h.submit_read(str(tmp_path / "missing.bin"), buf))
    h.close()


def test_tensor_swapper_roundtrip(tmp_path):
    from deepspeed_tpu.runtime.swap_tensor import TensorSwapper

    sw = TensorSwapper(str(tmp_path), num_threads=2)
    tree = {
        "a": jnp.arange(32, dtype=jnp.float32).reshape(4, 8),
        "b": {"c": jnp.ones((3,), jnp.int32)},
    }
    sw.swap_out("opt", tree)
    back = sw.swap_in("opt")
    for x, y in zip(jax.tree_util.tree_leaves(tree), jax.tree_util.tree_leaves(back)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    sw.release("opt")
    assert not any(f.endswith(".bin") for f in os.listdir(tmp_path))
    sw.close()


def test_autotuner_picks_best():
    from deepspeed_tpu.autotuning import Autotuner

    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    topo = MeshTopology(dims=ParallelDims(dp=8))
    r = np.random.RandomState(0)

    def sample_batch(global_batch):
        return {"input_ids": r.randint(0, 64, size=(global_batch, 16))}

    tuner = Autotuner(
        model,
        {
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "autotuning": {
                "enabled": True,
                "max_train_micro_batch_size_per_gpu": 2,
                "start_profile_step": 1,
                "end_profile_step": 2,
                "trials": 1,  # CPU test: no pool noise to median away
            },
        },
        topology=topo,
        sample_batch_fn=sample_batch,
    )
    best = tuner.tune()
    assert best["micro_batch"] in (1, 2)
    # any searched policy can win a CPU timing race (observed: dots_flash
    # beating none under load) — the invariant with teeth is that the
    # returned winner IS the max-throughput record of the search
    top = max(tuner.results, key=lambda r: r["throughput"])
    assert best == top, (best, top)
    assert best["throughput"] > 0
    assert len(tuner.results) >= 2


def test_measure_grid_and_config_patch_roundtrip(tmp_path):
    """The operator sweep's contract: measure_grid records feed
    result_to_config_patch, and the patch merges straight back into a
    working ds_config (VERDICT r3 #7: one tuner engine, schema round-trip)."""
    import deepspeed_tpu
    from deepspeed_tpu.autotuning import Autotuner, result_to_config_patch

    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    topo = MeshTopology(dims=ParallelDims(dp=8))
    r = np.random.RandomState(0)

    def sample_batch(global_batch):
        assert global_batch == 16  # fixed_global_batch holds B constant
        return {"input_ids": r.randint(0, 64, size=(16, 16))}

    tuner = Autotuner(
        model,
        {
            "train_batch_size": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "autotuning": {"start_profile_step": 1, "end_profile_step": 2,
                           "trials": 1, "fixed_global_batch": True},
        },
        topology=topo,
        sample_batch_fn=sample_batch,
    )
    recs = tuner.measure_grid([(2, "none", (0, 0)), (1, "full", (0, 0))])
    assert [r_["micro_batch"] for r_ in recs] == [2, 1]
    assert all(r_.get("throughput", 0) > 0 for r_ in recs), recs
    # bad rung is recorded, not raised
    bad = tuner.measure_grid([(2, "no_such_policy", (0, 0))])
    assert "error" in bad[0]

    patch = result_to_config_patch(recs[0])
    cfg = {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    }
    cfg.update(patch)
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, topology=topo,
                                               config=cfg)
    loss = float(engine.train_batch(batch=sample_batch(16)))
    assert np.isfinite(loss)


def test_autotuner_zero_ladder_escalates_to_fit(monkeypatch):
    """VERDICT r4 #7: a model that OOMs below ZeRO-3+offload lands on the
    fitting stage without user input, the chosen section rides every
    record, and the config patch round-trips it."""
    from deepspeed_tpu.autotuning import Autotuner, result_to_config_patch

    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    tuner = Autotuner(
        model,
        {
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                           "trials": 1},
        },
        topology=MeshTopology(dims=ParallelDims(dp=8)),
        sample_batch_fn=lambda g: None,
    )
    assert tuner.tune_zero  # no zero section in base config → ladder on
    probes = []

    def fake_measure(mb, pol, blocks=(0, 0)):
        z = dict(tuner._zero_patch or {})
        probes.append((mb, pol, z))
        if z.get("stage", 0) < 3 or "offload_optimizer" not in z:
            return None  # "OOM": only stage 3 + offload fits
        return 100.0 + mb

    monkeypatch.setattr(tuner, "_measure", fake_measure)
    monkeypatch.setattr(tuner, "_flash_tunable", lambda: False)
    best = tuner.tune()
    # the ladder walked 0 → 1 → 2 → 3 → 3+offload at mb=1/full
    assert [p[2].get("stage", 0) for p in probes[:5]] == [0, 1, 2, 3, 3]
    assert best["zero_optimization"]["stage"] == 3
    assert best["zero_optimization"]["offload_optimizer"]["device"] == "cpu"
    # winner == max-throughput record, zero section included
    top = max(tuner.results, key=lambda r: r["throughput"])
    assert best == top
    patch = result_to_config_patch(best)
    assert patch["zero_optimization"]["stage"] == 3


def test_autotuner_ladder_rung_replaces_zero_section():
    """ADVICE r5: with tune_zero_stage forced on over an existing
    zero_optimization section, each phase-0 probe must measure the ladder
    rung EXACTLY — user keys like offload_optimizer must not dict.update-
    leak into lower-stage probes (stage 0 + cpu offload is a config the
    ladder never intends)."""
    from deepspeed_tpu.autotuning import Autotuner

    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    tuner = Autotuner(
        model,
        {
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 3,
                                  "offload_optimizer": {"device": "cpu"}},
            "autotuning": {"tune_zero_stage": True},
        },
        topology=MeshTopology(dims=ParallelDims(dp=8)),
        sample_batch_fn=lambda g: None,
    )
    assert tuner.tune_zero  # explicit override beats the section pin
    tuner._zero_patch = {"stage": 0}
    cfg = tuner._candidate_config(1, "full")
    assert cfg["zero_optimization"] == {"stage": 0}  # rung, nothing else
    tuner._zero_patch = {"stage": 3,
                         "offload_optimizer": {"device": "cpu"}}
    cfg = tuner._candidate_config(1, "full")
    assert cfg["zero_optimization"]["offload_optimizer"]["device"] == "cpu"
    # no patch active (phase 0 skipped/over): the user's section rides
    tuner._zero_patch = None
    cfg = tuner._candidate_config(1, "full")
    assert cfg["zero_optimization"]["stage"] == 3
    assert cfg["zero_optimization"]["offload_optimizer"]["device"] == "cpu"
    # once settled, later phases measure rung + the user's benign keys
    # (bucket sizes etc.) but NOT the user's stage/offload decisions
    tuner.base_config["zero_optimization"]["reduce_bucket_size"] = 12345
    settled = tuner._settled_zero({"stage": 1})
    assert settled == {"stage": 1, "reduce_bucket_size": 12345}


def test_autotuner_respects_pinned_zero_stage():
    """An explicit zero_optimization section disables phase 0 (the user's
    stage is a pin, not a starting point)."""
    from deepspeed_tpu.autotuning import Autotuner

    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    tuner = Autotuner(
        model,
        {"zero_optimization": {"stage": 1},
         "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}},
        topology=MeshTopology(dims=ParallelDims(dp=8)),
        sample_batch_fn=lambda g: None,
    )
    assert not tuner.tune_zero
    assert tuner._pick_zero_stage() is None


def test_autotuner_phase3_bwd_tiles(monkeypatch):
    """Phase 3 probes backward-only tile variants on the phase-2 winner and
    records/propagates the bwd keys (config patch included)."""
    from deepspeed_tpu.autotuning import Autotuner, result_to_config_patch
    from deepspeed_tpu.autotuning import autotuner as at_mod

    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    topo = MeshTopology(dims=ParallelDims(dp=8))
    r = np.random.RandomState(0)
    tuner = Autotuner(
        model,
        {
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                           "start_profile_step": 1, "end_profile_step": 2,
                           "trials": 1},
        },
        topology=topo,
        sample_batch_fn=lambda g: {
            "input_ids": r.randint(0, 64, size=(g, 16))
        },
    )
    monkeypatch.setattr(tuner, "_flash_tunable", lambda: True)
    # deterministic throughputs: a bwd variant wins
    scores = {(0, 0, 0, 0): 100.0, (256, 512, 0, 0): 110.0,
              (256, 512, 512, 256): 120.0}

    def fake_measure(mb, pol, blocks=(0, 0)):
        b4 = tuple(blocks) + (0,) * (4 - len(blocks))
        return scores.get(b4, 50.0)

    monkeypatch.setattr(tuner, "_measure", fake_measure)
    monkeypatch.setattr(at_mod, "FLASH_BLOCKS", ((0, 0), (256, 512)))
    monkeypatch.setattr(at_mod, "FLASH_BLOCKS_BWD", ((512, 256),))
    best = tuner.tune()
    assert best["flash_block_q_bwd"] == 512
    assert best["flash_block_k_bwd"] == 256
    assert best["throughput"] == 120.0
    patch = result_to_config_patch(best)
    tk = patch["tpu_kernels"]
    assert tk["flash_block_q_bwd"] == 512 and tk["flash_block_k_bwd"] == 256


def test_tensor_swapper_generation_pool_rotation(tmp_path):
    """The two-generation read-buffer pool (shardlint R4's host-layer
    twin): generation N's buffers are recycled only after generation N+1
    fully lands, and a buffer still referenced by an in-flight write is
    never handed back to the free pool."""
    import jax
    from jax.sharding import SingleDeviceSharding

    from deepspeed_tpu.runtime.swap_tensor import TensorSwapper

    sw = TensorSwapper(str(tmp_path), num_threads=1, reuse_buffers=True,
                       buffer_count=2)
    tree = {"m": jnp.arange(16, dtype=jnp.float32)}
    shardings = {"m": SingleDeviceSharding(jax.devices()[0])}
    sw.swap_out("opt", tree)
    assert sw.generation == 0
    t1 = sw.swap_in("opt", shardings=shardings)
    assert sw.generation == 1  # gen rotated; previous gen (empty) retired
    t2 = sw.swap_in("opt", shardings=shardings)
    assert sw.generation == 2
    np.testing.assert_array_equal(np.asarray(t1["m"]), np.asarray(t2["m"]))
    # un-pooled path (no shardings → raw aliasing return) never rotates
    sw.swap_in("opt")
    assert sw.generation == 2
    # planting a pending-write alias of a last-gen buffer must refuse the
    # recycle instead of corrupting the swap file
    sw._pending["bogus"] = ([], list(sw._last_gen))
    with pytest.raises(RuntimeError, match="read-after-overwrite"):
        sw._retire_gen([])
    sw._pending.pop("bogus")
    sw.close()
