"""launcher/elastic.py: the preemption-recovery supervisor.

The unit tests drive the REAL supervisor over trivial python workers
(no jax, no collectives) — round accounting, shrink-to-survivors, the
min_workers floor, max_rounds exhaustion. The end-to-end preemption
oracle (kill a jax.distributed worker mid-step, resume resharded,
bitwise loss trajectory) is tools/elastic_run.py --oracle: the `slow`
test here runs it in-process-count-degraded form locally and ci.yml's
``preemption`` job runs it on every push.
"""

import os
import subprocess
import sys

import pytest

from deepspeed_tpu.launcher.elastic import (
    ROUND_ENV,
    ElasticSupervisor,
    _rc,
    free_port,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def worker_argv(body: str):
    """A tiny rank script: sees DSTPU_PROCESS_ID + the round env."""
    return [
        sys.executable, "-c",
        "import os, sys\n"
        f"rank = int(os.environ['DSTPU_PROCESS_ID'])\n"
        f"rnd = int(os.environ['{ROUND_ENV}'])\n" + body,
    ]


def test_rc_maps_signals_to_128_plus():
    assert _rc(-15) == 143  # SIGTERM
    assert _rc(-9) == 137   # SIGKILL
    assert _rc(1) == 1
    assert _rc(0) == 0


def test_free_port_is_bindable_int():
    p = free_port()
    assert isinstance(p, int) and 0 < p < 65536


def test_clean_round_exits_zero():
    sup = ElasticSupervisor(worker_argv("sys.exit(0)"), num_workers=2)
    assert sup.run() == 0
    assert sup.rounds == [{"round": 0, "world": 2, "rc": 0, "dead": 0}]


def test_one_death_shrinks_world_and_resumes():
    """Rank 1 dies in round 0 only; round 1 runs the lone survivor."""
    sup = ElasticSupervisor(
        worker_argv("sys.exit(143 if rnd == 0 and rank == 1 else 0)"),
        num_workers=2,
    )
    assert sup.run() == 0
    assert [r["world"] for r in sup.rounds] == [2, 1]
    assert sup.rounds[0]["rc"] != 0 and sup.rounds[1]["rc"] == 0


def test_whole_job_preemption_respawns_at_floor():
    """Every rank dying at once must not end the job: the next round
    restarts at the min_workers floor."""
    sup = ElasticSupervisor(
        worker_argv("sys.exit(143 if rnd == 0 else 0)"), num_workers=2,
    )
    assert sup.run() == 0
    assert [r["world"] for r in sup.rounds] == [2, 1]


def test_max_rounds_exhaustion_propagates_failure():
    sup = ElasticSupervisor(
        worker_argv("sys.exit(7)"), num_workers=1, max_rounds=2,
    )
    assert sup.run() == 7
    assert len(sup.rounds) == 3  # initial + 2 recoveries
    assert all(r["rc"] == 7 for r in sup.rounds)


def test_round_env_reaches_workers(tmp_path):
    marker = os.path.join(str(tmp_path), "round_r{}.txt")
    sup = ElasticSupervisor(
        worker_argv(
            f"open({marker!r}.format(rnd), 'a').write(str(rank))\n"
            "sys.exit(143 if rnd == 0 and rank == 0 else 0)"
        ),
        num_workers=2,
    )
    assert sup.run() == 0
    assert os.path.exists(marker.format(0))
    assert os.path.exists(marker.format(1))


@pytest.mark.slow
def test_preemption_oracle_end_to_end(tmp_path):
    """The full oracle: baseline vs twice-preempted elastic run, bitwise
    loss trajectory, preemption-save resume point, validated
    postmortems. Self-degrades to single-worker rounds on legacy jax
    (no multi-process CPU collectives there); ci.yml runs the
    multi-worker resharding form."""
    env = {**os.environ, "PYTHONPATH": REPO, "JAX_PLATFORMS": "cpu"}
    rc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "elastic_run.py"),
         "--oracle", "--workdir", str(tmp_path)],
        env=env, capture_output=True, text=True, timeout=560,
    )
    assert rc.returncode == 0, rc.stdout + rc.stderr
    assert "ORACLE OK" in rc.stdout
