"""Tiered KV memory hierarchy (ISSUE 18): host-spill paging.

The oracle: a TIERED replay (fp32 spill codec) under forced demotion and
promotion mid-stream — paged, spec-on, tp=2 — must reproduce an untiered
replay of the same logical capacity token-for-token, with
``step_traces == 1`` across any spill/restore mix (page-in rides under
the decode step as a staged scatter, never as a second program). Plus:
HostPageStore unit behavior (capacity, put-before-free rollback, the
NVMe third tier), codec-at-rest round trips (fp32 bitwise, int8 within
``codec.bound``, int8-arena pages lossless), prefix chains that demote
to host instead of dying and re-attach on a cold session resume, the
cross-tier page-leak invariant, tiering metrics, oversubscription
absorbed where the untiered twin sheds, and tier-aware fleet routing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (Request, RequestStatus, ServingEngine,
                                   ServingMetrics)
from deepspeed_tpu.serving.paging import (STAGE_SLOTS, HostPageStore,
                                          PageSpiller, chain_hashes,
                                          decode_page, encode_page)


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=128, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


def _engine(model, **kw):
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("max_tokens", 96)
    kw.setdefault("rng", jax.random.PRNGKey(1))
    return deepspeed_tpu.init_inference(model, **kw)


def _serving(eng, **over):
    serving = {"max_slots": 2, "token_budget": 16, "max_tokens": 96,
               "paged": True, "page_size": 16, "request_timeout_s": 1e9}
    serving.update(over)
    return ServingEngine(engine=eng, serving=serving)


def _drain(srv, outs=None):
    outs = outs if outs is not None else {}
    for st in srv.run_until_idle(max_steps=4000):
        if st.status is RequestStatus.DONE:
            outs[st.request.request_id] = st.output().tolist()
    return outs


def _submit_all(srv, reqs):
    for r in reqs:
        srv.submit(r)


def _churn_requests(rng, n, sys_prompt=None, plen=(20, 28), new=8):
    """Prompts long enough that two concurrent slots oversubscribe a
    small pool (live-slot demotion + promotion, not just chain spills)."""
    reqs = []
    for i in range(n):
        tail = rng.randint(1, 128, size=rng.randint(*plen)).astype(np.int32)
        p = tail if sys_prompt is None else np.concatenate([sys_prompt, tail])
        reqs.append(Request(request_id=f"r{i}", prompt=p, max_new_tokens=new))
    return reqs


# ---------------------------------------------------------------------------
# the bitwise oracle: tiered == untiered, token for token, ONE trace
# ---------------------------------------------------------------------------
def test_tiered_equals_untiered_greedy_bitwise_with_churn():
    """Forced demotion AND promotion mid-stream: 4 slots can hold up to
    20 pages of KV but only 8 exist, so live slots spill to host and
    page back in continuously — every token must still match an
    untiered replay at the same LOGICAL capacity."""
    model = tiny_llama()
    eng = _engine(model)
    rng = np.random.RandomState(0)
    sys_prompt = rng.randint(1, 128, size=16).astype(np.int32)
    reqs = _churn_requests(rng, 8, sys_prompt, plen=(16, 25), new=20)

    tiered = _serving(eng, max_slots=4, num_pages=8, host_pages=72,
                      spill_codec="fp32")
    _submit_all(tiered, reqs)
    got = _drain(tiered)

    untiered = _serving(eng, max_slots=4, num_pages=80)
    _submit_all(untiered, reqs)
    want = _drain(untiered)

    m = tiered.metrics
    assert m.pages_spilled > 0, "the pool never demoted — no churn"
    assert m.pages_promoted > 0, "nothing paged back in — no promotion"
    assert got == want
    assert tiered.step_traces == 1
    assert untiered.step_traces == 1


def test_cold_session_resume_promotes_host_chain_bitwise():
    """A finished session's prefix chain, LRU-evicted to the host tier
    by filler traffic, re-attaches on an identical prompt: the resume
    pays a page-in (host prefix hit, pages promoted through the staging
    buffer) and reproduces the original greedy session exactly."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, num_pages=8, host_pages=12, spill_codec="fp32")
    rng = np.random.RandomState(0)
    pA = rng.randint(1, 128, size=40).astype(np.int32)  # 2 full pages

    outs = {}
    srv.submit(Request(request_id="a0", prompt=pA, max_new_tokens=6))
    _drain(srv, outs)
    for i in range(4):  # disjoint fillers pressure A's chain out of HBM
        pf = rng.randint(1, 128, size=50 + i).astype(np.int32)
        srv.submit(Request(request_id=f"f{i}", prompt=pf, max_new_tokens=6))
    _drain(srv, outs)
    assert srv.scheduler.prefix_cache.host_entries > 0
    srv.submit(Request(request_id="a1", prompt=pA, max_new_tokens=6))
    _drain(srv, outs)

    m = srv.metrics
    assert m.host_prefix_hits >= 1
    assert m.pages_promoted >= 1
    assert m.host_cached_prompt_tokens >= 16
    assert outs["a1"] == outs["a0"]
    assert srv.step_traces == 1


def test_tiered_spec_on_parity():
    """Speculative decoding over the tiered arena: a spec slot's verify
    window and the staged page-in share the one step; repetitive prompts
    land acceptances while pages churn through the host tier."""
    model = tiny_llama()
    eng = _engine(model)
    rng = np.random.RandomState(2)
    reqs = []
    for i in range(5):
        motif = rng.randint(1, 128, size=3)
        p = np.tile(motif, 12)[: 20 + i].astype(np.int32)
        reqs.append(Request(request_id=f"r{i}", prompt=p, max_new_tokens=10))
    spec = {"enabled": True, "max_draft": 3}

    tiered = _serving(eng, max_slots=3, num_pages=8, host_pages=40,
                      spill_codec="fp32", spec=spec)
    _submit_all(tiered, reqs)
    got = _drain(tiered)

    untiered = _serving(eng, max_slots=3, num_pages=48, spec=spec)
    _submit_all(untiered, reqs)
    want = _drain(untiered)

    assert tiered.metrics.pages_spilled > 0
    assert got == want
    assert tiered.step_traces == 1


def test_tiered_tp2_int8_arena_parity():
    """tp=2 mesh, int8-quantized pool: spilled pages carry raw int8
    codewords + fp32 scales (bitwise round trip), the staging buffers
    stay host-committed numpy (no sharding-induced retrace)."""
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    topo = MeshTopology(dims=ParallelDims(tp=2), devices=jax.devices()[:2])
    eng = _engine(model, topology=topo, kv_cache_dtype="int8",
                  rng=jax.random.PRNGKey(4))
    rng = np.random.RandomState(3)
    reqs = _churn_requests(rng, 6, plen=(24, 40), new=12)

    tiered = _serving(eng, max_slots=3, num_pages=8, host_pages=40,
                      spill_codec="fp32")
    _submit_all(tiered, reqs)
    got = _drain(tiered)

    untiered = _serving(eng, max_slots=3, num_pages=48)
    _submit_all(untiered, reqs)
    want = _drain(untiered)

    assert tiered.metrics.pages_spilled > 0
    assert got == want
    assert tiered.step_traces == 1


# ---------------------------------------------------------------------------
# codec at rest
# ---------------------------------------------------------------------------
def _fake_page(rng, L=2, ps=16, KV=2, hd=8, dtype=np.float32):
    return {
        "k": rng.standard_normal((L, 1, ps, KV, hd)).astype(dtype),
        "v": rng.standard_normal((L, 1, ps, KV, hd)).astype(dtype),
    }


def test_fp32_spill_codec_roundtrip_bitwise():
    from deepspeed_tpu.comm.wires import get_codec

    codec = get_codec("fp32")
    page = _fake_page(np.random.default_rng(0))
    out = decode_page(encode_page(page, codec), codec)
    for name, arr in page.items():
        np.testing.assert_array_equal(out[name], arr, err_msg=name)
        assert out[name].dtype == arr.dtype


def test_int8_spill_codec_within_stated_bound():
    """A lossy spill codec degrades restored KV by no more than the
    codec's DOCUMENTED wire bound — the same |decode(encode(x)) - x| <=
    codec.bound(x) contract every wire in comm/wires.py ships under."""
    from deepspeed_tpu.comm.wires import get_codec

    codec = get_codec("int8")
    page = _fake_page(np.random.default_rng(1))
    out = decode_page(encode_page(page, codec), codec)
    for name, arr in page.items():
        # encode_page's canonical codec operand: [layers, rows, lanes]
        blocks = arr.reshape(arr.shape[0], -1, arr.shape[-1])
        bound = np.broadcast_to(
            np.asarray(codec.bound(blocks)), blocks.shape
        ).reshape(arr.shape)
        err = np.abs(out[name].astype(np.float64) - arr.astype(np.float64))
        assert (err <= bound + 1e-12).all(), (name, err.max())


def test_int8_arena_page_spills_lossless():
    """Quantized-arena pages keep their raw int8 codewords at rest (only
    the fp32 scales ride the codec) — the round trip is bitwise, so an
    int8 arena never degrades by being demoted."""
    from deepspeed_tpu.comm.wires import get_codec

    rng = np.random.default_rng(2)
    codec = get_codec("fp32")
    page = {
        "k": rng.integers(-128, 128, (2, 1, 16, 2, 8), dtype=np.int8),
        "v": rng.integers(-128, 128, (2, 1, 16, 2, 8), dtype=np.int8),
        "k_scale": rng.standard_normal((2, 1, 2, 16, 8)).astype(np.float32),
        "v_scale": rng.standard_normal((2, 1, 2, 16, 8)).astype(np.float32),
    }
    out = decode_page(encode_page(page, codec), codec)
    for name, arr in page.items():
        np.testing.assert_array_equal(out[name], arr, err_msg=name)
        assert out[name].dtype == arr.dtype


# ---------------------------------------------------------------------------
# HostPageStore: capacity, rollback, the NVMe third tier
# ---------------------------------------------------------------------------
def test_host_store_capacity_and_spiller_rollback():
    from deepspeed_tpu.comm.wires import get_codec

    store = HostPageStore(capacity_pages=2, codec="fp32")
    rng = np.random.default_rng(3)
    pages = {i: _fake_page(rng) for i in range(3)}
    spiller = PageSpiller(store, lambda ids: pages[ids[0]])

    k0 = spiller.demote(0)
    k1 = spiller.demote(1)
    assert k0 is not None and k1 is not None
    assert store.resident_count == 2
    # put-before-free: a full store refuses, nothing was mutated
    assert spiller.demote(2) is None
    assert store.resident_count == 2
    assert sorted(store.keys()) == sorted([k0, k1])
    # load round-trips bitwise and reports at-rest bytes
    leaves, nbytes = spiller.load(k0)
    np.testing.assert_array_equal(leaves["k"], pages[0]["k"])
    assert nbytes > 0
    spiller.drop(k0)
    assert store.resident_count == 1
    assert spiller.demote(2) is not None  # freed capacity admits again


def test_host_store_nvme_third_tier_roundtrip(tmp_path):
    """With spill_dir set, host-tier overflow lands on disk behind the
    same put/get/drop interface and pages back bitwise."""
    store = HostPageStore(capacity_pages=1, codec="fp32",
                          spill_dir=str(tmp_path))
    rng = np.random.default_rng(4)
    blobs = {}
    keys = []
    for i in range(3):
        page = _fake_page(rng)
        blobs[i] = page
        keys.append(store.put(encode_page(page, store.codec)))
    assert all(k is not None for k in keys)
    assert store.host_count == 1
    assert store.disk_count == 2
    assert store.resident_count == 3
    for i, k in enumerate(keys):  # disk gets paid back through the codec
        out = decode_page(store.get(k), store.codec)
        np.testing.assert_array_equal(out["k"], blobs[i]["k"])
    for k in keys:
        store.drop(k)
    assert store.resident_count == 0
    store.close()


def test_engine_spill_dir_roundtrip(tmp_path):
    """End-to-end: a tiered engine whose host tier is 2 pages deep
    overflows to NVMe and still replays bitwise."""
    model = tiny_llama()
    eng = _engine(model)
    rng = np.random.RandomState(5)
    reqs = _churn_requests(rng, 6, plen=(20, 28), new=12)

    tiered = _serving(eng, max_slots=3, num_pages=8, host_pages=2,
                      spill_codec="fp32", spill_dir=str(tmp_path))
    _submit_all(tiered, reqs)
    got = _drain(tiered)
    untiered = _serving(eng, max_slots=3, num_pages=48)
    _submit_all(untiered, reqs)
    want = _drain(untiered)

    store = tiered._host_store
    assert tiered.metrics.pages_spilled > 0
    assert store.host_count + store.disk_count == store.resident_count
    assert got == want
    assert tiered.step_traces == 1


# ---------------------------------------------------------------------------
# cross-tier accounting
# ---------------------------------------------------------------------------
def test_cross_tier_leak_invariant_after_churn():
    """assert_page_invariants runs after EVERY tick; after a churn-heavy
    replay the explicit cross-tier ledger must close: HBM free + HBM
    live + host-resident == num_pages + live store keys."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, max_slots=4, num_pages=8, host_pages=40,
                   spill_codec="fp32")
    rng = np.random.RandomState(6)
    _submit_all(srv, _churn_requests(rng, 8, plen=(16, 25), new=16))
    _drain(srv)
    sch = srv.scheduler
    sch.assert_page_invariants()
    store = srv._host_store
    assert (sch.pool.free_count + sch.pool.live_count + store.resident_count
            == srv.num_pages + len(list(store.keys())))


def test_demotion_rollback_when_every_tier_is_full():
    """Mid-demotion failure (store full) rolls back to the plain-drop
    path: the victim keeps its pages, the invariants still close, the
    replay still finishes correct (forced evictions allowed — tiering
    degrades to the untiered policy, never corrupts)."""
    model = tiny_llama()
    eng = _engine(model)
    # a 1-page host tier saturates immediately under this churn
    srv = _serving(eng, max_slots=4, num_pages=8, host_pages=1,
                   spill_codec="fp32")
    rng = np.random.RandomState(7)
    reqs = _churn_requests(rng, 6, plen=(16, 25), new=12)
    _submit_all(srv, reqs)
    got = _drain(srv)
    srv.scheduler.assert_page_invariants()
    assert srv._host_store.resident_count <= 1
    untiered = _serving(eng, max_slots=4, num_pages=48)
    _submit_all(untiered, reqs)
    want = _drain(untiered)
    for rid, toks in got.items():  # evicted requests may be missing; the
        assert toks == want[rid]   # finished ones must still be bitwise
    assert srv.step_traces == 1


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------
def test_tiering_metrics_snapshot_keys_and_nan_hardening():
    m = ServingMetrics()
    m.configure(4, num_pages=8, host_pages=16)
    m.on_spill(1024)
    m.on_spill(float("nan"))          # NaN-hardened: counts the page,
    m.on_page_in(pages=2, nbytes=2048, stall_s=float("nan"))
    m.on_page_in(pages=1, nbytes=1024, stall_s=0.5)
    m.on_prefix_lookup(32, 64, host_tokens=16)
    snap = m.snapshot()
    for key in ("pages_spilled", "pages_promoted", "spill_bytes",
                "promote_bytes", "page_in_stall_s", "host_pages_resident",
                "host_prefix_hits", "host_cached_prompt_tokens",
                "host_prefix_hit_rate"):
        assert key in snap, key
        assert np.isfinite(snap[key]), key
    assert snap["pages_spilled"] == 2
    assert snap["pages_promoted"] == 3
    assert snap["spill_bytes"] == 1024   # the NaN byte count dropped
    assert snap["page_in_stall_s"] == pytest.approx(0.5)
    assert snap["host_prefix_hits"] == 1
    assert "kv tiering" in m.summary()


def test_untiered_snapshot_omits_tiering_keys():
    m = ServingMetrics()
    m.configure(4, num_pages=8)
    assert "pages_spilled" not in m.snapshot()


# ---------------------------------------------------------------------------
# oversubscription: the tier absorbs what the untiered pool sheds
# ---------------------------------------------------------------------------
def test_oversubscription_no_shed_where_untiered_sheds():
    model = tiny_llama()
    eng = _engine(model)
    rng = np.random.RandomState(8)
    reqs = _churn_requests(rng, 10, plen=(30, 40), new=20)

    tiered = _serving(eng, max_slots=4, num_pages=8, host_pages=72,
                      spill_codec="fp32")
    _submit_all(tiered, reqs)
    _drain(tiered)
    assert tiered.metrics.evict_reasons.get("page pool exhausted", 0) == 0
    assert tiered.metrics.finished == len(reqs)

    untiered = _serving(eng, max_slots=4, num_pages=8)
    _submit_all(untiered, reqs)
    _drain(untiered)
    assert untiered.metrics.evict_reasons.get("page pool exhausted", 0) > 0


# ---------------------------------------------------------------------------
# config + analysis surface
# ---------------------------------------------------------------------------
def test_host_pages_forces_paged_auto():
    from deepspeed_tpu.config import ServingConfig, resolve_auto_knobs

    cfg = ServingConfig(enabled=True, host_pages=8, paged="auto")
    report = resolve_auto_knobs(cfg)
    assert cfg.paged is True
    assert report["serving.paged"]["source"] == "forced:kv-tiering"


def test_host_pages_without_paged_rejected():
    from deepspeed_tpu.config import DeepSpeedConfigError, ServingConfig

    cfg = ServingConfig(enabled=True, host_pages=8, paged=False)
    with pytest.raises(DeepSpeedConfigError):
        cfg.validate()


def test_bad_spill_codec_rejected():
    from deepspeed_tpu.config import DeepSpeedConfigError, ServingConfig

    cfg = ServingConfig(enabled=True, paged=True, host_pages=8,
                        spill_codec="zstd")
    with pytest.raises(DeepSpeedConfigError):
        cfg.validate()


def test_tiered_step_lints_clean_and_declares_kv_spill():
    """The tiered step traces abstractly for shardlint: R1-R13 clean,
    the kv_spill stream declared (kind offload, overlapped, staged
    bytes), stage_dst in R11's required-traced manifest."""
    from deepspeed_tpu.analysis import lint_serving_config
    from deepspeed_tpu.serving.engine import trace_serving_step
    from deepspeed_tpu.config import DeepSpeedConfig

    model = tiny_llama()
    cfg = {"serving": {"enabled": True, "max_slots": 2, "token_budget": 8,
                       "max_tokens": 64, "paged": True, "page_size": 16,
                       "num_pages": 8, "host_pages": 16,
                       "spill_codec": "fp32"}}
    report = lint_serving_config(cfg, model=model)
    assert report.ok, report.format()

    ds = DeepSpeedConfig(dict(cfg))
    topo = MeshTopology(dims=ParallelDims(tp=1), devices=jax.devices()[:1])
    closed, shardings, streams, meta = trace_serving_step(model, ds, topo)
    assert "kv_spill" in streams
    spill = streams["kv_spill"]
    assert spill["kind"] == "offload"
    assert spill["overlapped"] is True
    assert spill["stage_slots"] == STAGE_SLOTS
    assert spill["bytes_per_step"] == pytest.approx(
        spill["page_bytes_at_rest"] * STAGE_SLOTS * 2
    )
    assert "stage_dst" in meta["required_traced"]


def test_engine_analytic_streams_declare_kv_spill():
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, num_pages=8, host_pages=16, spill_codec="int8")
    streams = srv.analytic_streams()
    assert "kv_spill" in streams
    assert streams["kv_spill"]["codec"] == "int8"
    untiered = _serving(eng, num_pages=8)
    assert "kv_spill" not in untiered.analytic_streams()


# ---------------------------------------------------------------------------
# fleet: tier-aware prefix routing
# ---------------------------------------------------------------------------
def test_fleet_tier_aware_routing_replay():
    """A session's chain demoted to replica 0's HOST tier still routes
    the resumed session to replica 0 (host hit > miss), which re-attaches
    and promotes — and an HBM-resident chain outranks a host one."""
    from deepspeed_tpu.serving.fleet import Router

    model = tiny_llama()
    router = Router(
        engine=_engine(model),
        serving={"max_slots": 2, "token_budget": 16, "max_tokens": 96,
                 "paged": True, "page_size": 16, "num_pages": 8,
                 "host_pages": 12, "spill_codec": "fp32",
                 "request_timeout_s": 1e9,
                 "fleet": {"enabled": True, "replicas": 2,
                           "routing": "prefix"}})
    rng = np.random.RandomState(0)
    pA = rng.randint(1, 128, size=40).astype(np.int32)
    router.submit(Request("a0", pA, max_new_tokens=6))
    router.run_until_idle()
    # churn until r0's pool pressure demotes A's chain to its host tier
    cache0 = router.replicas[0].engine.scheduler.prefix_cache
    fills = 0
    while cache0.host_entries == 0 and fills < 24:
        pf = rng.randint(1, 128, size=50 + fills % 4).astype(np.int32)
        router.submit(Request(f"f{fills}", pf, max_new_tokens=6))
        fills += 1
        if fills % 4 == 0:
            router.run_until_idle()
    router.run_until_idle()
    assert cache0.host_entries > 0, "replica 0 never demoted"

    idx = router.index
    hashes = chain_hashes(pA, 16)
    w0 = idx.weighted_chain(0, hashes)
    w1 = idx.weighted_chain(1, hashes)
    assert w0 > 0, "replica 0 lost A's chain entirely"
    assert w1 == 0.0
    rid, depth = idx.best(pA, [0, 1])
    assert rid == 0 and depth == w0

    pre = router.metrics.prefix_routed
    router.submit(Request("a1", pA, max_new_tokens=6))
    router.run_until_idle()
    assert router.metrics.prefix_routed == pre + 1
    m0 = router.replicas[0].engine.metrics
    assert m0.host_prefix_hits + m0.pages_promoted > 0
    assert router.step_traces[0] == 1
    assert all(t <= 1 for t in router.step_traces)


def test_index_weighted_chain_tiers():
    """Unit: HBM links score 1.0, host links HOST_TIER_WEIGHT, the walk
    breaks at the first block resident in neither tier."""
    from deepspeed_tpu.serving.fleet import (HOST_TIER_WEIGHT,
                                             GlobalPrefixIndex)
    from deepspeed_tpu.serving.paging import PagePool, PrefixCache

    idx = GlobalPrefixIndex(page_size=16)
    cache = PrefixCache(PagePool(8), 16)
    idx.attach(0, cache)
    listener = cache.listener
    listener("insert", "full", 101, 0)
    listener("insert", "host", 102, -1)
    listener("insert", "full", 103, 1)
    listener("insert", "host", 104, -1)
    assert idx.weighted_chain(0, [101, 102, 103]) == pytest.approx(
        1.0 + HOST_TIER_WEIGHT + 1.0
    )
    # break at the first miss: 999 is in neither tier
    assert idx.weighted_chain(0, [101, 999, 103]) == pytest.approx(1.0)
    # depth walk counts both tiers (the replica can attach through host)
    assert idx.longest_chain(0, [101, 102, 103, 104]) == 4
    listener("evict", "host", 102, -1)
    assert idx.weighted_chain(0, [101, 102]) == pytest.approx(1.0)
    assert idx.host_entries(0) == 1
