"""Block-paged, prefix-shared KV arena (ISSUE 6).

The oracle: the PAGED serving engine must reproduce the contiguous slot
arena BITWISE token-for-token (greedy and sampled, tp=2, int8-KV) — the
gathered per-slot views hold byte-for-byte what the dense arena holds at
every mapped position, so outputs cannot drift. Plus: prefix-cache reuse
(an identical prompt decodes with ZERO prefill chunks scheduled, its
pages shared read-only), copy-on-write on divergence, the page-pool leak
invariant after every scheduler tick, forced eviction under pool
exhaustion (liveness), the paged Pallas decode kernel, and the static
analysis surface (lint clean, R6 fires when --hbm-gb undercuts the pool,
paged KV traffic declared via analytic_streams).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (PagePool, PrefixCache, Request,
                                   RequestStatus, ServingEngine)


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


def _engine(model, **kw):
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("max_tokens", 64)
    kw.setdefault("rng", jax.random.PRNGKey(1))
    return deepspeed_tpu.init_inference(model, **kw)


def _serving(eng, paged, **over):
    serving = {"max_slots": 3, "token_budget": 8, "max_tokens": 64}
    if paged:
        serving.update({"paged": True, "page_size": 16})
    serving.update(over)
    return ServingEngine(engine=eng, serving=serving)


def _drive(srv, prompts, news, **req_kw):
    """One fixed staggered-arrival schedule, shared by both arenas."""
    states = []

    def sub(i):
        kw = {k: (v[i] if isinstance(v, list) else v)
              for k, v in req_kw.items()}
        states.append(srv.submit(Request(
            request_id=f"r{i}", prompt=prompts[i], max_new_tokens=news[i],
            **kw,
        )))

    sub(0)
    sub(1)
    srv.step()
    srv.step()
    for i in range(2, len(prompts)):
        sub(i)
    srv.run_until_idle()
    return states


# ---------------------------------------------------------------------------
# the bitwise oracle: paged == contiguous arena, token for token
# ---------------------------------------------------------------------------
def test_paged_equals_contiguous_greedy_bitwise():
    model = tiny_llama()
    eng = _engine(model)
    r = np.random.RandomState(0)
    prompts = [r.randint(0, 128, size=(n,)) for n in (3, 12, 7, 5, 9)]
    news = [6, 4, 8, 5, 3]
    dense = _drive(_serving(eng, paged=False), prompts, news)
    srv_p = _serving(eng, paged=True)
    paged = _drive(srv_p, prompts, news)
    for i, (d, p) in enumerate(zip(dense, paged)):
        assert d.status is RequestStatus.DONE
        assert p.status is RequestStatus.DONE
        np.testing.assert_array_equal(d.output(), p.output(),
                                      err_msg=f"r{i}")
        want = eng.generate(prompts[i][None, :], max_new_tokens=news[i],
                            temperature=0.0)
        np.testing.assert_array_equal(p.output(), want[0], err_msg=f"r{i}")
    # ONE trace for the whole ragged paged replay (zero recompiles)
    assert srv_p.step_traces == 1


def test_paged_equals_contiguous_sampled_tp2_int8_bitwise():
    """Sampled decoding with shared keys on a tp=2 mesh with an int8
    paged pool: the sharded gather/scatter path reproduces the dense
    arena bitwise across a temperature/top-k/top-p mix in one batch."""
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    topo = MeshTopology(dims=ParallelDims(tp=2), devices=jax.devices()[:2])
    eng = _engine(model, topology=topo, kv_cache_dtype="int8",
                  rng=jax.random.PRNGKey(4))
    r = np.random.RandomState(3)
    prompts = [r.randint(0, 128, size=(n,)) for n in (5, 11, 4)]
    news = [6, 5, 6]
    keys = [jax.random.PRNGKey(100 + i) for i in range(3)]
    cases = dict(
        temperature=[0.8, 0.0, 0.7],
        top_k=[10, 0, 0],
        top_p=[1.0, 1.0, 0.85],
        rng=keys,
    )
    dense = _drive(_serving(eng, paged=False), prompts, news, **cases)
    srv_p = _serving(eng, paged=True)
    paged = _drive(srv_p, prompts, news, **cases)
    for i, (d, p) in enumerate(zip(dense, paged)):
        np.testing.assert_array_equal(d.output(), p.output(),
                                      err_msg=f"r{i}")
    assert srv_p.step_traces == 1


# ---------------------------------------------------------------------------
# prefix cache + copy-on-write
# ---------------------------------------------------------------------------
def test_prefix_cache_skips_prefill_and_cow_diverges():
    """Two requests share a prompt: the second one's entire prompt (but
    the final sampling feed) comes from the cache — ZERO prefill chunks
    scheduled — and it emits identical tokens. Divergence happens inside
    a shared partial page, so the step copies-on-write instead of
    touching the shared page; a third identical request afterwards proves
    the shared pages were never corrupted."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, paged=True, max_slots=2)
    prompt = np.random.RandomState(5).randint(0, 128, size=(20,))
    want = eng.generate(prompt[None, :], max_new_tokens=6, temperature=0.0)

    a = srv.submit(Request(request_id="a", prompt=prompt, max_new_tokens=6))
    srv.run_until_idle()
    np.testing.assert_array_equal(a.output(), want[0])
    chunks_before = srv.metrics.prefill_chunks

    b = srv.submit(Request(request_id="b", prompt=prompt, max_new_tokens=6))
    srv.run_until_idle()
    assert b.status is RequestStatus.DONE
    np.testing.assert_array_equal(b.output(), want[0])
    # the entire prompt but its final token came from shared pages …
    assert b.cached_tokens == prompt.size - 1
    # … so NO prefill chunk was scheduled (only the cached-tail feed)
    assert srv.metrics.prefill_chunks == chunks_before
    assert srv.metrics.cached_tail_feeds >= 1
    assert srv.metrics.prefix_hits >= 1
    # b's first write landed inside a's shared partial page → COW fired
    assert srv.metrics.cow_copies >= 1

    # divergence safety: a third identical request still reproduces the
    # reference — b's copy-on-write never touched the shared pages
    c = srv.submit(Request(request_id="c", prompt=prompt, max_new_tokens=6))
    srv.run_until_idle()
    np.testing.assert_array_equal(c.output(), want[0])


def test_prefix_cache_partial_hit_then_divergent_suffix():
    """Requests sharing only a prefix: the common pages are reused, the
    divergent suffixes prefill independently, and BOTH reproduce the
    single-request reference bitwise."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, paged=True, max_slots=2)
    r = np.random.RandomState(6)
    common = r.randint(0, 128, size=(16,))  # exactly one full page
    tails = [r.randint(0, 128, size=(5,)), r.randint(0, 128, size=(7,))]
    prompts = [np.concatenate([common, t]) for t in tails]
    wants = [
        eng.generate(p[None, :], max_new_tokens=5, temperature=0.0)
        for p in prompts
    ]
    s0 = srv.submit(Request(request_id="p0", prompt=prompts[0],
                            max_new_tokens=5))
    srv.run_until_idle()
    s1 = srv.submit(Request(request_id="p1", prompt=prompts[1],
                            max_new_tokens=5))
    srv.run_until_idle()
    np.testing.assert_array_equal(s0.output(), wants[0][0])
    np.testing.assert_array_equal(s1.output(), wants[1][0])
    # the shared page covered at least the first full page of p1's prompt
    assert s1.cached_tokens >= 16


# ---------------------------------------------------------------------------
# page pool: leak invariant, exhaustion liveness, forced eviction
# ---------------------------------------------------------------------------
def test_page_pool_refcounts_and_leak_check():
    pool = PagePool(4)
    a, b = pool.alloc(), pool.alloc()
    pool.incref(a)
    assert pool.free_count == 2 and pool.live_count == 2
    pool.check_leaks({a: 2, b: 1})
    pool.decref(a)
    pool.decref(a)
    assert pool.free_count == 3
    with pytest.raises(AssertionError, match="dead page"):
        pool.decref(a)
    with pytest.raises(AssertionError, match="refcount drift"):
        pool.check_leaks({b: 2})


def test_prefix_cache_eviction_frees_pages():
    pool = PagePool(4)
    cache = PrefixCache(pool, page_size=4)
    pages = [pool.alloc(), pool.alloc()]
    toks = np.arange(6)  # one full page + a 2-token tail
    # 3 entries: the full-page hash, its partial-match run, and the tail
    assert cache.insert(toks, pages) == 3
    for p in pages:  # caller drops its own refs; cache keeps the pages
        pool.decref(p)
    assert pool.free_count == 2 and len(cache) == 3
    got, covered = cache.match(np.arange(6))
    assert covered == 6 and got == pages
    # mismatching tail: only the full page matches
    got, covered = cache.match(np.asarray([0, 1, 2, 3, 9, 9]))
    assert covered == 4 and got == pages[:1]
    while cache.evict_lru():
        pass
    assert pool.free_count == 4 and len(cache) == 0


def test_pool_exhaustion_evicts_newest_and_drains():
    """num_pages at the liveness floor: concurrent requests contend for
    pages; the scheduler force-evicts the newest under starvation and
    every surviving request still finishes with correct output. The leak
    invariant (checked after every tick inside the scheduler) holds."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, paged=True, max_slots=3, token_budget=8,
                   num_pages=5, prefix_cache=False)  # 5 == pages_per_slot
    r = np.random.RandomState(7)
    prompts = [r.randint(0, 128, size=(n,)) for n in (30, 30, 30)]
    states = [
        srv.submit(Request(request_id=f"x{i}", prompt=p, max_new_tokens=4))
        for i, p in enumerate(prompts)
    ]
    finished = srv.run_until_idle()
    assert any(s.status is RequestStatus.DONE for s in states)
    for s in states:
        if s.status is RequestStatus.DONE:
            want = eng.generate(s.request.prompt[None, :], max_new_tokens=4,
                                temperature=0.0)
            np.testing.assert_array_equal(s.output(), want[0])
        else:
            assert s.status is RequestStatus.EVICTED
            assert s.evict_reason == "page pool exhausted"
            assert s.retry_after is not None
    # pool fully drained once everything released
    assert srv.scheduler.pool.free_count == srv.scheduler.pool.num_pages
    assert len(finished) == sum(
        1 for s in states if s.status is RequestStatus.DONE
    )


def test_evicted_request_resubmits_and_reproduces():
    """A page-starved eviction rewinds the request; resubmission after
    the pool frees reproduces the deterministic output."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, paged=True, max_slots=2, num_pages=5,
                   prefix_cache=False)
    r = np.random.RandomState(8)
    p0, p1 = r.randint(0, 128, size=(30,)), r.randint(0, 128, size=(30,))
    # each request runs to 64 tokens = 4 pages; 5 pages for two slots
    # strands both mid-decode → forced eviction of the newest
    s0 = srv.submit(Request(request_id="k0", prompt=p0, max_new_tokens=34))
    s1 = srv.submit(Request(request_id="k1", prompt=p1, max_new_tokens=34))
    srv.run_until_idle()
    evicted = [s for s in (s0, s1) if s.status is RequestStatus.EVICTED]
    done = [s for s in (s0, s1) if s.status is RequestStatus.DONE]
    assert len(evicted) == 1 and len(done) == 1
    st = srv.scheduler.resubmit(evicted[0])
    srv.run_until_idle()
    assert st.status is RequestStatus.DONE
    want = eng.generate(st.request.prompt[None, :], max_new_tokens=34,
                        temperature=0.0)
    np.testing.assert_array_equal(st.output(), want[0])
    # the retry's TTFT was measured from ITS OWN first token (the
    # pre-eviction timestamp was cleared) — never negative
    assert all(t >= 0 for t in srv.metrics.ttft_s)


# ---------------------------------------------------------------------------
# the paged Pallas decode kernel
# ---------------------------------------------------------------------------
def test_paged_decode_attention_kernel_matches_reference():
    """Pages physically shuffled through the table, per-row frontiers:
    the scalar-prefetch paged kernel matches the masked fp32 reference."""
    from deepspeed_tpu.ops.pallas.decode_attention import (
        paged_decode_attention_kernel,
    )

    B, mp, ps, H, KV, hd = 3, 4, 16, 4, 2, 64
    P1 = 9  # 8 pages + NULL
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, 1, H, hd), jnp.float32)
    k_pool = jnp.asarray(r.randn(P1, ps, KV, hd), jnp.float32)
    v_pool = jnp.asarray(r.randn(P1, ps, KV, hd), jnp.float32)
    # shuffled physical pages; unmapped entries point at NULL (page 8)
    pt = np.full((B, mp), 8, np.int32)
    pt[0, :3] = [5, 2, 7]
    pt[1, :1] = [0]
    pt[2, :4] = [1, 3, 4, 6]
    lens = jnp.asarray([37, 3, 60], jnp.int32)
    out = paged_decode_attention_kernel(
        q, k_pool, v_pool, lens, jnp.asarray(pt)
    )
    # dense reference over the gathered views
    kc = np.asarray(k_pool)[pt].reshape(B, mp * ps, KV, hd)
    vc = np.asarray(v_pool)[pt].reshape(B, mp * ps, KV, hd)
    kf = np.repeat(kc, H // KV, axis=2)
    vf = np.repeat(vc, H // KV, axis=2)
    logits = np.einsum("bqhd,bkhd->bhqk", np.asarray(q), kf) / np.sqrt(hd)
    kpos = np.arange(mp * ps)[None, None, None, :]
    logits = np.where(kpos <= np.asarray(lens)[:, None, None, None],
                      logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    probs = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bkhd->bqhd", probs, vf)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


# ---------------------------------------------------------------------------
# config + static analysis surface
# ---------------------------------------------------------------------------
def test_serving_paged_config_validation():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    cfg = DeepSpeedConfig({
        "serving": {"enabled": True, "paged": True, "page_size": 16,
                    "num_pages": 64, "max_tokens": 64, "token_budget": 32},
    })
    assert cfg.serving.paged and cfg.serving.pages_per_slot() == 6
    # the engine-clamped max_tokens is authoritative for the page math
    assert cfg.serving.pages_per_slot(32) == 4
    with pytest.raises(DeepSpeedConfigError, match="page_size"):
        DeepSpeedConfig({"serving": {"page_size": 0}})
    # the num_pages liveness floor is enforced by the ENGINE (it knows
    # the model-clamped max_tokens; config validation alone does not)
    model = tiny_llama()
    eng = _engine(model)
    with pytest.raises(DeepSpeedConfigError, match="liveness floor"):
        ServingEngine(engine=eng, serving={
            "max_slots": 2, "token_budget": 8, "max_tokens": 64,
            "paged": True, "page_size": 16, "num_pages": 2,
        })


def test_prefix_cache_bypassed_for_repetition_penalty():
    """A penalized request's ``seen`` matrix is built from FED tokens, so
    it must never take a prefix-cache hit (sampling would depend on cache
    warmth): it re-prefills and still reproduces the oracle bitwise."""
    model = tiny_llama()
    eng = _engine(model)
    srv = _serving(eng, paged=True, max_slots=2)
    prompt = np.random.RandomState(11).randint(0, 128, size=(20,))
    a = srv.submit(Request(request_id="a", prompt=prompt, max_new_tokens=6))
    srv.run_until_idle()  # a's pages are now in the prefix cache
    kw = dict(max_new_tokens=6, temperature=0.9, repetition_penalty=1.3,
              rng=jax.random.PRNGKey(42))
    b = srv.submit(Request(request_id="b", prompt=prompt, **kw))
    srv.run_until_idle()
    assert b.cached_tokens == 0  # penalty bypasses the cache entirely
    want = eng.generate(prompt[None, :], **kw)
    np.testing.assert_array_equal(b.output(), want[0])


def test_lint_paged_serving_config_and_r6_page_budget():
    """The paged slot step traces abstractly on a tp=2 CPU mesh and lints
    clean; arming R6 with a budget the page pool cannot fit turns it into
    an error BEFORE anything compiles — the static page-budget gate."""
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.analysis import lint_serving_config

    comm.destroy_process_group()
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    cfg = {
        "tensor_parallel": {"tp_size": 2},
        "serving": {"enabled": True, "max_slots": 2, "token_budget": 8,
                    "max_tokens": 64, "kv_cache_dtype": "int8",
                    "paged": True, "page_size": 16, "num_pages": 12},
    }
    report = lint_serving_config(cfg, model=model, source="paged-unit")
    assert report.ok, report.format()
    # undercut the budget: params + the page pool cannot fit in 64 KiB
    tight = lint_serving_config(
        cfg, model=model, source="paged-tight", hbm_budget_bytes=64 * 1024,
    )
    assert any(f.rule == "R6" for f in tight.findings), tight.format()


def test_paged_analytic_stream_schema():
    """analytic_streams declares the paged KV traffic (R8 schema: hbm
    kind, per-device bytes) with the page geometry attached."""
    from deepspeed_tpu.profiling.comm_logger import CommsLogger

    model = tiny_llama()
    eng = _engine(model, rng=jax.random.PRNGKey(9))
    logger = CommsLogger()
    try:
        srv = _serving(eng, paged=True, max_slots=2)
        srv.comm_logger = logger
        srv.submit(Request(request_id="m0", prompt=np.arange(5) % 7,
                           max_new_tokens=3))
        srv.run_until_idle()
    finally:
        logger.stop()
    kv = srv.analytic_streams()["kv_cache"]
    assert kv["kind"] == "hbm" and kv["paged"] is True
    assert kv["bytes_per_step"] > 0 and kv["pool_bytes"] > 0
    assert kv["page_size"] == 16 and kv["num_pages"] == srv.num_pages
    assert kv["per_device_bytes_per_step"] <= kv["bytes_per_step"]
    assert logger.kv_steps == srv.metrics.steps > 0
