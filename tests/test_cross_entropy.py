"""Vocab-chunked fused cross-entropy vs the dense logits path."""

import jax
import jax.numpy as jnp
import numpy as np

import deepspeed_tpu
from deepspeed_tpu.ops.cross_entropy import chunked_masked_ce, fused_ce_scope


def _dense_ce(y, head, labels):
    logits = jnp.einsum(
        "...sd,dv->...sv", y, head.astype(y.dtype),
        preferred_element_type=jnp.float32,
    )
    mask = (labels >= 0).astype(jnp.float32)
    safe = jnp.maximum(labels, 0)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, safe[..., None], axis=-1)[..., 0]
    denom = jnp.maximum(mask.sum(), 1.0)
    return (((logz - gold) * mask).sum() / denom), denom


def test_chunked_ce_matches_dense_loss_and_grads():
    r = np.random.RandomState(0)
    B, S, d, V = 2, 16, 32, 256
    y = jnp.asarray(r.randn(B, S, d).astype(np.float32))
    head = jnp.asarray(r.randn(d, V).astype(np.float32) * 0.1)
    labels = r.randint(0, V, size=(B, S))
    labels[0, :3] = -100  # HF ignore-index rows
    labels = jnp.asarray(labels)

    ref, dref = _dense_ce(y, head, labels)
    got, dgot = chunked_masked_ce(y, head, labels, chunk=64)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    assert float(dgot) == float(dref)

    g_ref = jax.grad(lambda y, h: _dense_ce(y, h, labels)[0], argnums=(0, 1))(y, head)
    g_got = jax.grad(
        lambda y, h: chunked_masked_ce(y, h, labels, chunk=64)[0], argnums=(0, 1)
    )(y, head)
    for a, b in zip(g_got, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_chunked_ce_bf16_compute_close():
    """bf16 operands (the engine path) stay close to the fp32 dense loss."""
    r = np.random.RandomState(1)
    y = jnp.asarray(r.randn(4, 8, 32).astype(np.float32)).astype(jnp.bfloat16)
    head = jnp.asarray(r.randn(32, 128).astype(np.float32) * 0.1)
    labels = jnp.asarray(r.randint(0, 128, size=(4, 8)))
    ref, _ = _dense_ce(y, head, labels)
    got, _ = chunked_masked_ce(y, head, labels, chunk=32)
    np.testing.assert_allclose(float(got), float(ref), rtol=2e-2)


def _tiny_llama():
    from deepspeed_tpu.models import llama

    return llama(
        "llama-tiny", vocab_size=256, max_seq_len=64, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, head_dim=16,
        intermediate_size=128,
    )


def _run_trajectory(fused, steps=4, config_overrides=None, topology=None):
    """Loss trajectory of the tiny engine with fused CE on/off; every
    fused-vs-dense parity test in this file is this plus its overrides."""
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
        "zero_optimization": {"stage": 0},
        "tpu_kernels": {"fused_ce": fused, "ce_chunk": 64},
    }
    for k, v in (config_overrides or {}).items():
        cfg[k] = v
    kw = {} if topology is None else {"topology": topology}
    engine, *_ = deepspeed_tpu.initialize(
        model=_tiny_llama(), config=cfg, rng=jax.random.PRNGKey(0), **kw
    )
    batch = {"input_ids": np.random.RandomState(0).randint(0, 256, size=(8, 64))}
    return [float(engine.train_batch(batch=batch)) for _ in range(steps)]


def test_engine_trains_with_fused_ce_and_matches_dense_trajectory():
    """Same seed/data: fused-CE engine loss trajectory ~= dense-CE engine."""
    dense = _run_trajectory(False, steps=5)
    fused = _run_trajectory(True, steps=5)
    assert fused[-1] < fused[0]
    np.testing.assert_allclose(fused, dense, rtol=1e-3)


def test_fused_ce_gate_respects_tp():
    """tp>1 vocab-parallel meshes keep the dense path (gate returns False)."""
    from deepspeed_tpu.ops.cross_entropy import fused_ce_applicable

    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import MeshTopology, ParallelDims

    comm.destroy_process_group()
    topo = MeshTopology(ParallelDims(dp=4, tp=2), devices=jax.devices())
    assert not fused_ce_applicable(256, 64, topo)
    assert fused_ce_applicable(256, 64, None)
    assert fused_ce_applicable(250, 64, None)  # ragged tail supported
    assert not fused_ce_applicable(64, 64, None)  # single chunk: dense wins
    comm.destroy_process_group()


def test_chunked_ce_ragged_vocab_matches_dense():
    """Real vocab sizes (50257, 128256, ...) don't divide by the chunk: the
    static tail piece must reproduce the dense loss and grads exactly."""
    r = np.random.RandomState(2)
    B, S, d, V = 2, 8, 32, 250  # 250 = 3*64 + 58 tail
    y = jnp.asarray(r.randn(B, S, d).astype(np.float32))
    head = jnp.asarray(r.randn(d, V).astype(np.float32) * 0.1)
    labels = r.randint(0, V, size=(B, S))
    labels[0, 0] = V - 1  # land in the tail piece
    labels[1, 0] = -100
    labels = jnp.asarray(labels)

    ref, _ = _dense_ce(y, head, labels)
    got, _ = chunked_masked_ce(y, head, labels, chunk=64)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-6)
    g_ref = jax.grad(lambda y, h: _dense_ce(y, h, labels)[0], argnums=(0, 1))(y, head)
    g_got = jax.grad(
        lambda y, h: chunked_masked_ce(y, h, labels, chunk=64)[0], argnums=(0, 1)
    )(y, head)
    for a, b in zip(g_got, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5,
                                   atol=1e-6)


def test_fused_ce_with_fp16_loss_scaling():
    """The chunked-CE custom VJP must propagate the scaled-loss cotangent
    exactly like the dense path (fp16 dynamic loss scaling multiplies the
    loss before grad)."""
    overrides = {
        "fp16": {"enabled": True, "initial_scale_power": 8},
        "zero_optimization": {"stage": 1},
    }
    fused = _run_trajectory(True, config_overrides=overrides)
    dense = _run_trajectory(False, config_overrides=overrides)
    assert np.isfinite(fused).all()
    np.testing.assert_allclose(fused, dense, rtol=2e-3)


def test_fused_ce_zero3_matches_dense_on_mesh():
    """fused CE under ZeRO-3 dp x fsdp sharding (the default-on TPU path)
    must track the dense-loss engine trajectory on the same mesh."""
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import MeshTopology, ParallelDims

    overrides = {
        "zero_optimization": {
            "stage": 3, "stage3_param_persistence_threshold": 1,
        },
    }

    def run(fused):
        comm.destroy_process_group()
        topo = MeshTopology(ParallelDims(dp=4, fsdp=2), devices=jax.devices())
        comm.set_topology(topo)
        out = _run_trajectory(fused, config_overrides=overrides, topology=topo)
        comm.destroy_process_group()
        return out

    dense = run(False)
    fused = run(True)
    assert fused[-1] < fused[0]
    np.testing.assert_allclose(fused, dense, rtol=1e-3)
