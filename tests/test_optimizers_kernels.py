"""Optimizers (incl. 1-bit family) + Pallas fused-adam/rmsnorm kernels
(SURVEY §2.1, §2.4). Kernels run interpret=True on the CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.config import OptimizerConfig
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.ops.onebit import scale_by_onebit_adam
from deepspeed_tpu.ops.pallas.fused_adam import _fused_adam_flat
from deepspeed_tpu.ops.pallas.rmsnorm import rmsnorm as pallas_rmsnorm
from deepspeed_tpu.runtime.lr_schedules import build_schedule
from deepspeed_tpu.runtime.optimizers import build_optimizer


def _opt_cfg(name, **params):
    cfg = OptimizerConfig.__new__(OptimizerConfig)
    cfg.type = name
    cfg.params = {"lr": 1e-3, **params}
    return cfg


@pytest.mark.parametrize(
    "name", ["adamw", "lion", "adagrad", "lamb", "sgd", "onebitadam",
             "zerooneadam", "onebitlamb"]
)
def test_all_optimizers_step(name):
    cfg = _opt_cfg(name, momentum=0.9, freeze_step=2)
    sched = build_schedule(None, {}, 1e-3)
    tx = build_optimizer(cfg, sched)
    params = {"w": jnp.ones((4, 8)), "b": jnp.zeros((8,))}
    state = tx.init(params)
    for i in range(4):
        grads = jax.tree.map(lambda p: jnp.full_like(p, 0.1 * (i + 1)), params)
        updates, state = tx.update(grads, state, params)
        params = optax.apply_updates(params, updates)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree_util.tree_leaves(params))
    assert float(params["w"][0, 0]) != 1.0  # moved


def test_onebit_adam_matches_adam_before_freeze():
    """Warmup phase is exact Adam (reference parity)."""
    onebit = scale_by_onebit_adam(freeze_step=1000)
    adam = optax.scale_by_adam()
    params = {"w": jnp.ones((8,))}
    s1, s2 = onebit.init(params), adam.init(params)
    r = np.random.RandomState(0)
    for _ in range(5):
        g = {"w": jnp.asarray(r.randn(8), jnp.float32)}
        u1, s1 = onebit.update(g, s1, params)
        u2, s2 = adam.update(g, s2, params)
        np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                                   rtol=1e-5, atol=1e-6)


def test_onebit_adam_compressed_phase_freezes_variance():
    onebit = scale_by_onebit_adam(freeze_step=2)
    params = {"w": jnp.ones((8,))}
    s = onebit.init(params)
    r = np.random.RandomState(1)
    for _ in range(3):
        g = {"w": jnp.asarray(r.randn(8), jnp.float32)}
        _, s = onebit.update(g, s, params)
    nu_frozen = np.asarray(s.nu["w"])
    for _ in range(3):
        g = {"w": jnp.asarray(r.randn(8), jnp.float32)}
        u, s = onebit.update(g, s, params)
    np.testing.assert_array_equal(np.asarray(s.nu["w"]), nu_frozen)
    assert np.isfinite(np.asarray(u["w"])).all()


def test_onebit_engine_trains():
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                   num_layers=2, num_heads=2),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-3, "freeze_step": 2}},
            "zero_optimization": {"stage": 1},
            "steps_per_print": 100,
        },
        topology=MeshTopology(dims=ParallelDims(dp=8)),
    )
    r = np.random.RandomState(0)
    for _ in range(4):
        loss = engine.train_batch(
            batch={"input_ids": r.randint(0, 64, size=(8, 16))}
        )
        assert np.isfinite(float(loss))


def test_fused_adam_kernel_matches_reference():
    r = np.random.RandomState(0)
    n = 1000  # deliberately unaligned
    pad = (-n) % (128 * 8)
    g = jnp.asarray(np.pad(r.randn(n).astype(np.float32), (0, pad)))
    m = jnp.asarray(np.pad(r.randn(n).astype(np.float32) * 0.1, (0, pad)))
    v = jnp.asarray(np.pad(np.abs(r.randn(n)).astype(np.float32) * 0.01, (0, pad)))
    b1, b2, eps = 0.9, 0.999, 1e-8
    bc = jnp.asarray([1 - b1**3, 1 - b2**3], jnp.float32)
    out, m2, v2 = _fused_adam_flat(g, m, v, bc, b1=b1, b2=b2, eps=eps,
                                   interpret=True)
    m_ref = b1 * m + (1 - b1) * g
    v_ref = b2 * v + (1 - b2) * g * g
    out_ref = (m_ref / bc[0]) / (jnp.sqrt(v_ref / bc[1]) + eps)
    np.testing.assert_allclose(np.asarray(m2), np.asarray(m_ref), rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(v_ref), rtol=1e-4, atol=1e-7)
    np.testing.assert_allclose(np.asarray(out), np.asarray(out_ref), rtol=1e-4, atol=1e-6)


def test_pallas_adam_optimizer_trajectory():
    """scale_by_fused_adam (jnp fallback on CPU) == optax.scale_by_adam."""
    from deepspeed_tpu.ops.pallas.fused_adam import scale_by_fused_adam

    fused, ref = scale_by_fused_adam(), optax.scale_by_adam()
    params = {"w": jnp.ones((16, 8))}
    s1, s2 = fused.init(params), ref.init(params)
    r = np.random.RandomState(2)
    for _ in range(4):
        g = {"w": jnp.asarray(r.randn(16, 8), jnp.float32)}
        u1, s1 = fused.update(g, s1, params)
        u2, s2 = ref.update(g, s2, params)
        np.testing.assert_allclose(np.asarray(u1["w"]), np.asarray(u2["w"]),
                                   rtol=1e-5, atol=1e-6)


def test_pallas_rmsnorm_uneven_rows():
    """Rows not a multiple of the block: padding must not corrupt dscale."""
    r = np.random.RandomState(4)
    x = jnp.asarray(r.randn(300, 128).astype(np.float32))  # 300 % 256 != 0
    scale = jnp.asarray(r.randn(128).astype(np.float32))
    ref = x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-5) * scale
    got = pallas_rmsnorm(x, scale, 1e-5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda s: jnp.sum(pallas_rmsnorm(x, s, 1e-5) ** 2))(scale)
    g2 = jax.grad(lambda s: jnp.sum((x * jax.lax.rsqrt(jnp.mean(x * x, -1, keepdims=True) + 1e-5) * s) ** 2))(scale)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4, atol=1e-4)


def test_pallas_rmsnorm_fwd_bwd():
    r = np.random.RandomState(3)
    x = jnp.asarray(r.randn(4, 16, 128).astype(np.float32))
    scale = jnp.asarray(r.randn(128).astype(np.float32))

    def ref_fn(x, s):
        var = jnp.mean(jnp.square(x), axis=-1, keepdims=True)
        return jnp.sum((x * jax.lax.rsqrt(var + 1e-5) * s) ** 2)

    def pallas_fn(x, s):
        return jnp.sum(pallas_rmsnorm(x, s, 1e-5) ** 2)

    np.testing.assert_allclose(float(pallas_fn(x, scale)), float(ref_fn(x, scale)),
                               rtol=1e-5)
    g1 = jax.grad(pallas_fn, argnums=(0, 1))(x, scale)
    g2 = jax.grad(ref_fn, argnums=(0, 1))(x, scale)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_pallas_layernorm_fwd_bwd():
    from deepspeed_tpu.ops.pallas.layernorm import layernorm as pallas_layernorm

    r = np.random.RandomState(5)
    x = jnp.asarray(r.randn(4, 16, 128).astype(np.float32))
    scale = jnp.asarray(r.randn(128).astype(np.float32))
    bias = jnp.asarray(r.randn(128).astype(np.float32))

    def ref_fn(x, s, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return jnp.sum(((x - mean) * jax.lax.rsqrt(var + 1e-5) * s + b) ** 2)

    def pallas_fn(x, s, b):
        return jnp.sum(pallas_layernorm(x, s, b, 1e-5) ** 2)

    np.testing.assert_allclose(
        float(pallas_fn(x, scale, bias)), float(ref_fn(x, scale, bias)), rtol=1e-5
    )
    g1 = jax.grad(pallas_fn, argnums=(0, 1, 2))(x, scale, bias)
    g2 = jax.grad(ref_fn, argnums=(0, 1, 2))(x, scale, bias)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_pallas_layernorm_uneven_rows():
    """Rows not a multiple of the block: padding must not corrupt dscale/dbias."""
    from deepspeed_tpu.ops.pallas.layernorm import layernorm as pallas_layernorm

    r = np.random.RandomState(6)
    x = jnp.asarray(r.randn(300, 128).astype(np.float32))
    scale = jnp.asarray(r.randn(128).astype(np.float32))
    bias = jnp.asarray(r.randn(128).astype(np.float32))

    def ref(x, s, b):
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        return (x - mean) * jax.lax.rsqrt(var + 1e-5) * s + b

    np.testing.assert_allclose(
        np.asarray(pallas_layernorm(x, scale, bias, 1e-5)),
        np.asarray(ref(x, scale, bias)), rtol=1e-5, atol=1e-5,
    )
    g1 = jax.grad(
        lambda s, b: jnp.sum(pallas_layernorm(x, s, b, 1e-5) ** 2), argnums=(0, 1)
    )(scale, bias)
    g2 = jax.grad(
        lambda s, b: jnp.sum(ref(x, s, b) ** 2), argnums=(0, 1)
    )(scale, bias)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4)


def test_bloom_trains_with_fused_layernorm():
    """BLOOM (layernorm family) trains with tpu_kernels.fused_rmsnorm on —
    the knob routes layernorm through the Pallas kernel via the same scope."""
    import deepspeed_tpu
    from deepspeed_tpu.models import bloom

    model = bloom(
        "bloom-tiny", vocab_size=256, max_seq_len=64, hidden_size=64,
        num_layers=2, num_heads=4, intermediate_size=128,
    )
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
            "zero_optimization": {"stage": 0},
            "tpu_kernels": {"fused_rmsnorm": True},
        },
    )
    batch = {"input_ids": np.random.RandomState(0).randint(0, 256, size=(8, 64))}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
