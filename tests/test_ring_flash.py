"""Ring flash attention (SURVEY §2.3 long-context): the Pallas flash kernel
composed around the sp ring with global position offsets, vs the dense
single-device oracle — forward and grads, causal/GQA/ALiBi/segments."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from conftest import xfail_legacy_partial_manual
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.ops.attention import attention_impl, xla_attention
from deepspeed_tpu.parallel.sequence import ring_attention

B, S, HD = 1, 512, 64


def rand_qkv(H=4, KV=2, seed=0):
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(B, S, H, HD), jnp.float32)
    k = jnp.asarray(r.randn(B, S, KV, HD), jnp.float32)
    v = jnp.asarray(r.randn(B, S, KV, HD), jnp.float32)
    return q, k, v


def ring_flash(q, k, v, topo, **kw):
    with attention_impl("flash"):
        return ring_attention(q, k, v, topo=topo, **kw)


@xfail_legacy_partial_manual
@pytest.mark.parametrize("sp,causal", [(4, True), (4, False), (2, True)])
def test_ring_flash_matches_dense(sp, causal):
    q, k, v = rand_qkv()
    topo = MeshTopology(dims=ParallelDims(sp=sp, dp=8 // sp))
    ref = xla_attention(q, k, v, causal=causal)
    got = jax.jit(
        lambda a, b, c: ring_flash(a, b, c, topo, causal=causal)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@xfail_legacy_partial_manual
def test_ring_flash_grads_match_dense():
    q, k, v = rand_qkv(seed=1)
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))

    def loss_ring(q, k, v):
        return jnp.sum(ring_flash(q, k, v, topo, causal=True) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name}",
        )


@xfail_legacy_partial_manual
def test_ring_flash_alibi_global_positions():
    q, k, v = rand_qkv(seed=2)
    slopes = np.geomspace(1.0, 0.125, q.shape[2]).astype(np.float32)
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))
    ref = xla_attention(q, k, v, causal=True, alibi_slopes=slopes)
    got = jax.jit(
        lambda a, b, c: ring_flash(a, b, c, topo, causal=True,
                                   alibi_slopes=slopes)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@xfail_legacy_partial_manual
def test_ring_flash_segment_ids_cross_chunk():
    q, k, v = rand_qkv(seed=3)
    r = np.random.RandomState(3)
    # segments crossing the chunk boundaries: the visiting kv block's ids
    # differ from the local q block's ids
    seg = jnp.asarray(np.cumsum(r.rand(B, S) < 0.02, axis=1))
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))
    ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
    got = jax.jit(
        lambda a, b, c, s: ring_flash(a, b, c, topo, causal=True,
                                      segment_ids=s)
    )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_small_chunks_keep_dense_ring():
    """S_loc below the kernel tile keeps the (still-correct) dense ring."""
    r = np.random.RandomState(4)
    q = jnp.asarray(r.randn(1, 64, 4, 64), jnp.float32)
    topo = MeshTopology(dims=ParallelDims(sp=8))
    ref = xla_attention(q, q, q, causal=True)
    got = jax.jit(lambda a: ring_flash(a, a, a, topo, causal=True))(q)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


@xfail_legacy_partial_manual
def test_ring_flash_bwd_tiles_scope():
    """Scoped bwd tile overrides reach the ring path's dq/dkv kernels:
    sp=2 gives S_loc=256, so fwd tiles pinned at 128 and bwd tiles at 256
    genuinely differ — grads must match the default-tile run."""
    from deepspeed_tpu.ops.pallas.flash_attention import block_sizes_scope

    q, k, v = rand_qkv(seed=7)
    topo = MeshTopology(dims=ParallelDims(sp=2, dp=4))

    def loss(q, k, v):
        return jnp.sum(ring_flash(q, k, v, topo, causal=True) ** 2)

    g_base = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    with block_sizes_scope(128, 128, 256, 256):
        g_scoped = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for gb, gs, name in zip(g_base, g_scoped, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gb), np.asarray(gs), rtol=5e-4, atol=5e-4,
            err_msg=f"d{name}",
        )
