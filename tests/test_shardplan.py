"""shardplan (analysis/cost) validation: exactness, XLA cross-checks, CLI.

ISSUE 4 acceptance:
- planner param/opt byte counts match the materialized state EXACTLY
  (same shard shapes, same itemsizes);
- the activation/peak-HBM estimate lands within ±15% of XLA's own
  compiled accounting (``Compiled.memory_analysis()``) on the 410M
  CPU-mesh bench leg;
- planner FLOPs cross-check against the analytic flops_profiler;
- ``tools/shardplan.py`` exits 0 on shipped configs and 1 when
  ``--hbm-gb`` is set below a config's estimated peak (R6);
- the pipeline stash estimator (folded in from tools/pipe_memory.py)
  keeps the measured ordering and chunk law.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.analysis import lint_engine, plan_engine
from deepspeed_tpu.analysis.cost import (
    auto_chunk,
    pipeline_temp_bytes,
    stash_boundaries,
)
from deepspeed_tpu.analysis.shardlint import compiled_train_memory_peak
from deepspeed_tpu.models import gpt2

pytestmark = pytest.mark.shardlint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

BASE_CFG = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
}


def _engine(cfg, model=None, abstract=True):
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=model or gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        config=dict(cfg),
        abstract_init=abstract,
    )
    return engine


def _device0_bytes(tree):
    """Materialized per-device bytes: what device 0 actually holds."""
    dev0 = jax.devices()[0]
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        for sh in leaf.addressable_shards:
            if sh.device == dev0:
                total += sh.data.size * sh.data.dtype.itemsize
    return total


@pytest.mark.parametrize("stage", [0, 3])
def test_planner_state_bytes_exact_vs_materialized(stage, devices8):
    """param/opt byte columns == the bytes the real engine puts on a
    device, to the byte, across ZeRO stages (replicated AND sharded)."""
    engine = _engine(
        dict(BASE_CFG, zero_optimization={"stage": stage}), abstract=False
    )
    plan = plan_engine(engine, source=f"stage{stage}")
    assert plan.param_bytes == _device0_bytes(engine.state.params)
    assert plan.opt_bytes == _device0_bytes(engine.state.opt_state)
    engine.destroy()


def test_planner_abstract_equals_concrete_state_bytes(devices8):
    """The abstract_init shell plans the same bytes as a materialized
    engine — the whole point of OOM-checking before compile."""
    cfg = dict(BASE_CFG, zero_optimization={"stage": 3})
    abstract = plan_engine(_engine(cfg, abstract=True))
    concrete = plan_engine(_engine(cfg, abstract=False))
    assert abstract.param_bytes == concrete.param_bytes
    assert abstract.opt_bytes == concrete.opt_bytes


def test_planner_peak_within_10pct_of_xla_410m(devices8):
    """ISSUE 4 acceptance, re-tightened by ISSUE 7: peak-HBM estimate
    within ±10% of ``compiled.memory_analysis()`` on the CPU-mesh 410M
    bench leg (the exact program the lint traces — XLA CPU compiles it
    in seconds). Measured 1.04 with the fused-elementwise coalescing
    landed; the band leaves room for jax version drift only."""
    import bench

    name, model, cfg = bench.lint_targets(len(jax.devices()))[0]
    assert name == "bench-410m"
    engine = _engine(cfg, model=model)
    plan = plan_engine(engine, source=name)

    xla_peak, ma = compiled_train_memory_peak(engine)
    if xla_peak is None:
        pytest.skip("XLA does not report memory analysis on this backend")
    ratio = plan.peak_hbm_bytes / xla_peak
    assert 0.90 <= ratio <= 1.10, (
        f"plan {plan.peak_hbm_bytes / 2**30:.2f} GiB vs XLA "
        f"{xla_peak / 2**30:.2f} GiB (ratio {ratio:.3f})"
    )
    # and the state columns equal XLA's argument accounting (exactness
    # again, now against the compiler's own number — XLA's figure also
    # counts the batch/rng arguments, a fraction of a percent here)
    args_ratio = plan.state_bytes / ma.argument_size_in_bytes
    assert 0.97 <= args_ratio <= 1.0


def test_planner_flops_cross_check_vs_flops_profiler(devices8):
    """Planner MXU flops (counted dot-by-dot off the traced step, per
    device) agree with the analytic flops_profiler (fwd+bwd = 3x fwd,
    whole model) within 25% on a small dense decoder."""
    from deepspeed_tpu.profiling.flops_profiler import get_model_profile

    model = gpt2(
        "gpt2-tiny", vocab_size=512, max_seq_len=64, num_layers=4,
        num_heads=4, hidden_size=128, intermediate_size=512,
    )
    cfg = dict(
        BASE_CFG,
        train_batch_size=8,
        train_micro_batch_size_per_gpu=1,
        zero_optimization={"stage": 0},
    )
    engine = _engine(cfg, model=model)
    plan = plan_engine(engine)
    B, S = 8, 64
    analytic, _macs, _params = get_model_profile(model, B, S, fwd_only=False)
    counted = plan.flops * plan.n_devices  # planner is per-device
    assert 0.75 <= counted / analytic <= 1.25, (counted, analytic)


def test_plan_reports_offload_and_ring_streams(devices8):
    """The engine's declared analytic streams ride into the plan (and
    into R8): the double-buffered offload leg prices its host stream
    even on the CPU mesh (assumed), the tp-overlap leg its ring."""
    import bench

    targets = {n: (m, c) for n, m, c in bench.lint_targets(len(jax.devices()))}
    model, cfg = targets["bench-1b-offload-db"]
    plan = plan_engine(_engine(cfg, model=model), source="db")
    off = plan.streams["offload"]
    assert off["overlapped"] and off["assumed"] and off["kind"] == "offload"
    assert off["per_device_bytes_per_step"] > 0
    assert plan.offload_inflight_bytes > 0

    model, cfg = targets["bench-410m-tp-overlap"]
    plan = plan_engine(_engine(cfg, model=model), source="tp")
    ring = plan.streams["tp_ring"]
    assert ring["overlapped"] and ring["kind"] == "ici"
    assert plan.ici_bytes_total > 0  # the walk saw the ppermute hops

    # ISSUE-10: the MoE dispatch/combine exchange is declared on BOTH
    # paths (the serial GSPMD path moves the same logical bytes — R8
    # must see them either way), overlapped only with the knob on
    model, cfg = targets["bench-moe-a2a"]
    plan = plan_engine(_engine(cfg, model=model), source="moe")
    a2a = plan.streams["moe_a2a"]
    assert a2a["overlapped"] and a2a["kind"] == "ici"
    assert a2a["per_device_bytes_per_step"] > 0
    import copy

    cfg_off = copy.deepcopy(cfg)
    cfg_off["moe"]["overlap_a2a"]["enabled"] = False
    plan_off = plan_engine(_engine(cfg_off, model=model), source="moe-ser")
    a2a_off = plan_off.streams["moe_a2a"]
    assert not a2a_off["overlapped"]
    assert a2a_off["bytes_per_step"] == a2a["bytes_per_step"]

    model, cfg = targets["bench-410m-z3-prefetch"]
    plan = plan_engine(_engine(cfg, model=model), source="z3pf")
    z3 = plan.streams["zero3_prefetch"]
    assert z3["overlapped"] and z3["kind"] == "ici"
    assert z3["per_device_bytes_per_step"] > 0 and z3["slots"] == 2


def test_r6_fires_only_with_budget(devices8):
    """No budget → R6 silent; a budget below the estimated peak → R6
    error naming the breakdown."""
    engine = _engine(dict(BASE_CFG, zero_optimization={"stage": 0}))
    clean = lint_engine(engine, only=["R6"])
    assert clean.ok and not clean.findings
    engine2 = _engine(dict(BASE_CFG, zero_optimization={"stage": 0}))
    report = lint_engine(engine2, only=["R6"], hbm_budget_bytes=1024)
    assert [f.rule for f in report.findings] == ["R6"]
    assert "exceeds" in report.findings[0].message


def test_r7_flags_put_chain_and_gather_slice(devices8):
    """R7 unit coverage beyond the corpus pair: duplicate placement-cast
    chains and the degenerate all_gather-then-slice."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from deepspeed_tpu.analysis import lint_jaxpr
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    s = NamedSharding(mesh, P("dp"))

    def dup_put(x):
        return jax.device_put(jax.device_put(x, s), s) * 2.0

    closed = jax.make_jaxpr(dup_put)(jax.ShapeDtypeStruct((8, 4), jnp.float32))
    findings = lint_jaxpr(closed, mesh=mesh, source="dup-put")
    assert any(f.rule == "R7" for f in findings), [f.format() for f in findings]

    def gather_slice(x):
        def body(xs):
            full = jax.lax.all_gather(xs, "dp")           # [4, n, k]
            return jax.lax.dynamic_slice(
                full, (jax.lax.axis_index("dp"), 0, 0), (1,) + xs.shape
            )[0]

        fn = shard_map(
            body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            axis_names={"dp", "tp"}, check_vma=False,
        )
        return fn(x)

    closed = jax.make_jaxpr(gather_slice)(
        jax.ShapeDtypeStruct((8, 4), jnp.float32)
    )
    findings = lint_jaxpr(closed, mesh=mesh, source="gather-slice")
    assert any(f.rule == "R7" for f in findings), [f.format() for f in findings]

    # neighbor exchange — same shapes, but the slice fetches the NEXT
    # device's shard, so the gather is load-bearing and R7 must stay quiet
    def neighbor_slice(x):
        def body(xs):
            full = jax.lax.all_gather(xs, "dp")
            nxt = (jax.lax.axis_index("dp") + 1) % 4
            return jax.lax.dynamic_slice(
                full, (nxt, 0, 0), (1,) + xs.shape
            )[0]

        fn = shard_map(
            body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            axis_names={"dp", "tp"}, check_vma=False,
        )
        return fn(x)

    closed = jax.make_jaxpr(neighbor_slice)(
        jax.ShapeDtypeStruct((8, 4), jnp.float32)
    )
    findings = lint_jaxpr(closed, mesh=mesh, source="neighbor-slice")
    assert not any(f.rule == "R7" for f in findings), [
        f.format() for f in findings
    ]


def test_shardplan_cli_budget_exit_codes(devices8, tmp_path):
    """The CLI contract: exit 0 on a shipped config, exit 1 when
    --hbm-gb undercuts its estimated peak, plan table in the JSON."""
    cfg = os.path.join(REPO, "examples", "ds_config_zero3.json")
    out = tmp_path / "plan.json"
    t0 = time.time()
    ok = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shardplan.py"), cfg,
         "--json", str(out)],
        capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert ok.returncode == 0, ok.stdout + ok.stderr
    payload = json.loads(out.read_text())
    assert payload["ok"] and payload["plans"]
    row = payload["plans"][0]
    assert row["peak_hbm_bytes"] > 0 and row["est_step_s"] >= 0

    over = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shardplan.py"), cfg,
         "--hbm-gb", "0.0001"],
        capture_output=True, text=True, timeout=240, cwd=REPO,
    )
    assert over.returncode == 1, over.stdout + over.stderr
    assert "R6" in over.stdout
    assert time.time() - t0 < 120.0  # two cold CLI runs stay snappy


def test_walk_coalesces_fused_elementwise_chains(devices8):
    """ISSUE 7 satellite: a materializing producer whose single-use
    output feeds a reduction (through a single-use elementwise chain)
    fuses in XLA — the intermediate never moves through HBM, so the walk
    must not charge the producer's write AND the reducer's read."""
    from deepspeed_tpu.analysis.cost.walk import JaxprWalker

    def fused(x, w):
        h = jnp.einsum("bk,kn->bn", x, w)
        return (h * 2.0).sum()

    def materialized(x, w):
        h = jnp.einsum("bk,kn->bn", x, w)
        # h is multi-use: it really materializes, both charges stand
        return (h * 2.0).sum() + h[0, 0]

    x = jax.ShapeDtypeStruct((256, 512), jnp.float32)
    w = jax.ShapeDtypeStruct((512, 512), jnp.float32)

    def traffic(fn):
        closed = jax.make_jaxpr(fn)(x, w)
        walker = JaxprWalker({})
        walker.walk(closed.jaxpr, [(1, 1), (1, 1)])
        return walker.stats.hbm_bytes

    h_bytes = 256 * 512 * 4
    io_fused = traffic(fused)
    # fused triple: reads of x and w plus the scalar out — h uncharged
    assert io_fused == x.size * 4 + w.size * 4 + 4, io_fused
    # the multi-use twin keeps the write+read of h (plus the slice path)
    assert traffic(materialized) >= io_fused + 2 * h_bytes


def test_pipeline_estimator_laws():
    """The folded-in pipe-memory math: chunk law unchanged, no-remat
    grows fastest, the 1f1b chunked law beats the plain scan at scale,
    and byte scaling is linear in the boundary activation."""
    # auto_chunk mirrors the tool's historical formula
    for pp in (2, 4):
        for M in (2, 8, 32):
            ticks = M + pp - 1
            assert auto_chunk(pp, M) == max(pp, int(round((ticks / 2) ** 0.5)))
    for M in (8, 16, 32):
        none_ = stash_boundaries(2, M, "none")
        gpipe = stash_boundaries(2, M, "gpipe")
        chunked = stash_boundaries(2, M, "1f1b")
        assert none_ > gpipe
        assert chunked < none_
    # growth: gpipe is ~2/microbatch, 1f1b sub-linear beyond it
    g32 = stash_boundaries(4, 32, "gpipe") - stash_boundaries(4, 16, "gpipe")
    c32 = stash_boundaries(4, 32, "1f1b") - stash_boundaries(4, 16, "1f1b")
    assert c32 < g32
    assert pipeline_temp_bytes(2, 8, 2, 128, 64) == stash_boundaries(
        2, 8, "1f1b"
    ) * (2 * 128 * 64 * 4)
    with pytest.raises(ValueError):
        stash_boundaries(2, 8, "zigzag")


def test_pipeline_estimator_tracks_measured_row(devices8):
    """One live cross-check against XLA's compiled accounting (the
    pipe_memory tool's smallest leg): prediction within 2x — the
    estimator is a capacity-planning law, not a byte-exact oracle."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import pipe_memory

    try:
        t = pipe_memory.measure(2, 4, "full", mb=2, S=128, D=64,
                                tick_chunk=auto_chunk(2, 4))
    except NotImplementedError as e:  # legacy-jax partial-manual refusal
        pytest.skip(str(e).splitlines()[0])
    pred = pipeline_temp_bytes(2, 4, 2, 128, 64, policy="1f1b",
                               tick_chunk=auto_chunk(2, 4))
    assert 0.5 <= pred / t <= 2.0, (pred, t)
