"""LR schedule + loss-scaler unit tests. Model: reference
tests/unit/runtime/test_lr_schedulers.py + fp16 loss scaler tests."""

import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.config import FP16Config, OptimizerConfig
from deepspeed_tpu.runtime.lr_schedules import build_schedule
from deepspeed_tpu.runtime.optimizers import build_optimizer
from deepspeed_tpu.runtime.precision import (
    init_loss_scale,
    update_loss_scale,
)


def _lr(sched, step):
    return float(sched(jnp.asarray(step, jnp.int32)))


def test_warmup_lr_reaches_max():
    s = build_schedule("WarmupLR", {"warmup_max_lr": 1e-3, "warmup_num_steps": 10}, 1e-3)
    assert _lr(s, 0) < 1e-3
    np.testing.assert_allclose(_lr(s, 10), 1e-3, rtol=1e-5)
    np.testing.assert_allclose(_lr(s, 100), 1e-3, rtol=1e-5)


def test_warmup_decay_hits_zero():
    s = build_schedule(
        "WarmupDecayLR",
        {"warmup_max_lr": 1e-3, "warmup_num_steps": 10, "total_num_steps": 100},
        1e-3,
    )
    assert _lr(s, 50) < 1e-3
    np.testing.assert_allclose(_lr(s, 100), 0.0, atol=1e-9)


def test_warmup_cosine():
    s = build_schedule(
        "WarmupCosineLR", {"warmup_num_steps": 10, "total_num_steps": 110}, 1e-3
    )
    np.testing.assert_allclose(_lr(s, 10), 1e-3, rtol=1e-4)
    mid = _lr(s, 60)
    assert 4e-4 < mid < 6e-4  # half way through cosine ≈ lr/2
    assert _lr(s, 110) < 1e-6


def test_one_cycle_peak_at_first_step_size():
    s = build_schedule(
        "OneCycle",
        {"cycle_min_lr": 1e-4, "cycle_max_lr": 1e-3, "cycle_first_step_size": 10},
        1e-3,
    )
    np.testing.assert_allclose(_lr(s, 10), 1e-3, rtol=1e-5)
    np.testing.assert_allclose(_lr(s, 20), 1e-4, rtol=1e-5)


def test_lr_range_test_grows():
    s = build_schedule(
        "LRRangeTest",
        {"lr_range_test_min_lr": 1e-5, "lr_range_test_step_size": 10,
         "lr_range_test_step_rate": 1.0},
        1e-3,
    )
    assert _lr(s, 0) == pytest.approx(1e-5)
    assert _lr(s, 100) > _lr(s, 10) > _lr(s, 0)


def test_unknown_scheduler_raises():
    with pytest.raises(KeyError):
        build_schedule("NoSuchSched", {}, 1e-3)


# ---- loss scaler -------------------------------------------------------------
def _cfg(**kw):
    return FP16Config(enabled=True, **kw)


def test_scaler_halves_after_hysteresis():
    cfg = _cfg(initial_scale_power=16, hysteresis=2)
    st = init_loss_scale(cfg, True)
    st = update_loss_scale(st, jnp.asarray(True), cfg, True)  # hysteresis eats one
    assert float(st.scale) == 2.0**16
    st = update_loss_scale(st, jnp.asarray(True), cfg, True)
    assert float(st.scale) == 2.0**15


def test_scaler_grows_after_window():
    cfg = _cfg(initial_scale_power=10, loss_scale_window=3)
    st = init_loss_scale(cfg, True)
    for _ in range(3):
        st = update_loss_scale(st, jnp.asarray(False), cfg, True)
    assert float(st.scale) == 2.0**11


def test_scaler_respects_min_scale():
    cfg = _cfg(initial_scale_power=1, hysteresis=1, min_loss_scale=1.0)
    st = init_loss_scale(cfg, True)
    for _ in range(5):
        st = update_loss_scale(st, jnp.asarray(True), cfg, True)
    assert float(st.scale) == 1.0


def test_alternating_overflow_still_halves():
    """With consecutive_hysteresis=False, O,G,O,G must halve at the second
    overflow (hysteresis only refills at the growth window)."""
    cfg = _cfg(initial_scale_power=16, hysteresis=2, loss_scale_window=1000)
    st = init_loss_scale(cfg, True)
    st = update_loss_scale(st, jnp.asarray(True), cfg, True)  # O: hyst 2->1
    st = update_loss_scale(st, jnp.asarray(False), cfg, True)  # G: no refill
    st = update_loss_scale(st, jnp.asarray(True), cfg, True)  # O: halve
    assert float(st.scale) == 2.0**15


def test_consecutive_hysteresis_refills_on_good():
    cfg = _cfg(initial_scale_power=16, hysteresis=2, consecutive_hysteresis=True)
    st = init_loss_scale(cfg, True)
    st = update_loss_scale(st, jnp.asarray(True), cfg, True)  # O: hyst 2->1
    st = update_loss_scale(st, jnp.asarray(False), cfg, True)  # G: refill to 2
    st = update_loss_scale(st, jnp.asarray(True), cfg, True)  # O: hyst 2->1 again
    assert float(st.scale) == 2.0**16


def test_static_scale_never_changes():
    cfg = FP16Config(enabled=True, loss_scale=128.0)
    st = init_loss_scale(cfg, True)
    st2 = update_loss_scale(st, jnp.asarray(True), cfg, True)
    assert float(st2.scale) == 128.0


# ---- optimizer factory -------------------------------------------------------
@pytest.mark.parametrize(
    "name", ["adam", "adamw", "lion", "adagrad", "lamb", "sgd"]
)
def test_optimizer_factory_produces_updates(name):
    import jax

    cfg = OptimizerConfig(type=name, params={"lr": 1e-3, "momentum": 0.9})
    tx = build_optimizer(cfg, build_schedule(None, {}, 1e-3))
    params = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    state = tx.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    updates, state = tx.update(grads, state, params)
    leaves = jax.tree_util.tree_leaves(updates)
    assert all(np.isfinite(np.asarray(u)).all() for u in leaves)
    assert any(float(jnp.sum(jnp.abs(u))) > 0 for u in leaves)


def test_unknown_optimizer_raises():
    with pytest.raises(KeyError):
        build_optimizer(OptimizerConfig(type="rmsprop9000"), build_schedule(None, {}, 1e-3))
