"""Hazard fixture programs for the shardlint corpus.

Every builder returns ``(closed_jaxpr, lint_kwargs, expect_rule)`` —
trace-ready evidence of one statically-visible bug class:

- ``stacked_dim0_drift``    R2: the PR-1 bucketed-opt carry drift
- ``slot_cache_carry_drift`` R2: a serving slot-KV arena whose step
  carry re-puts the head partition onto the slot dim
- ``paged_pool_carry_drift`` R2: the block-paged pool carry (gather/
  scatter through a page table) whose write-back sharding drifts
- ``spec_frontier_mask_drift`` R2: the speculative verify step's
  multi-token frontier writes (a k+1-wide window per slot at its own
  frontier) whose arena carry-out sharding drifts
- ``missing_psum_grads``    R1: dp-local grads applied as if reduced
- ``broken_ppermute_ring``  R3: a pipeline ring with a stray edge
- ``moe_a2a_malformed_ring`` R3: a hand-rolled MoE dispatch-reduce ring
  whose ep cycle closes on the wrong member (the a2a-overlap hazard;
  the clean twin traces the real parallel/a2a_overlap.py program)
- ``moe_decode_ring_malformed`` R3: the serving engine's decode-shaped
  expert combine ride hand-rolled with a duplicate-destination ep perm
  (the clean twin traces the real moe_decode_a2a ring)
- ``read_after_donate``     R4: a rotating slot read after overwrite
- ``zero3_prefetch_stale_slot`` R4: a hand-rolled two-slot param-gather
  prefetch whose layer compute reads the pre-overwrite slot generation
  (the staleness the functional prefetch carry avoids by construction)
- ``truncated_master``      R5: f32 master rebuilt through bf16
- ``pinned_host_compute``   R5: host-resident bytes fed to compute
- ``grad_wire_truncates_master`` R5: an int8 grad wire whose dequantized
  blocks accumulate into the master through bf16 instead of f32 (the
  qgZ dequant-accumulate contract of comm/wires.py)
- ``hier_wire_bad_split``   R3: a hand-rolled hierarchical 2-hop wire
  whose intra-group ring permutation maps two members onto one (the
  clean twin traces the real comm/wires.py 2-hop reduce-scatter)
- ``hbm_over_budget``       R6: estimated peak exceeds the HBM budget
- ``autotuner_rung_oom``    R6: a fat-micro autotuner rung statically
  over the shared budget (the planner-search prune; the clean twin is
  the thin-micro rung under the SAME budget)
- ``reshard_transpose_pair`` R7: transpose∘reshard∘transpose identity
- ``unhideable_offload_stream`` R8: declared-overlapped stream bigger
  than the compute window
- ``rng_key_reuse``         R9: one per-slot key consumed by two
  sampling sites (the clean twin splits first — the serving chain rule)
- ``reassoc_accum_drift``   R10: a hand-rolled wire ring accumulating
  dequantized chunks in bf16 (the clean twin dequant-accumulates in
  f32, the qgZ contract)
- ``static_arg_per_tick``   R11: a slot step whose ``spec_len`` was
  baked as a python constant at trace time (the clean twin traces it)
- ``dcn_flat_ring``         R12: the flat joint-(dp, fsdp) wire ring on
  a hybrid mesh whose dp axis is DCN-tagged (the clean twin traces the
  hierarchical 2-hop form of the same wire)
- ``dcn_unbudgeted_stream`` R13: a declared-overlapped stream whose
  payload only fits the compute window at ICI speed, not on the
  DCN-tagged axis it crosses (the clean twin splits hierarchically and
  declares the shrunk inter hop)
- ``kv_spill_unbudgeted``   R8: the tiered serving step's kv_spill
  host-paging stream with a page too large for the staging window to
  hide on the host link (the clean twin is the shipped two-slot
  double-buffer over a real KiB-scale page)
- ``restore_drops_sharding`` R2: a checkpoint-restore writeback that
  rebuilds the optimizer carry from host arrays without re-putting to
  the donated carry's resting shardings (the clean twin is
  runtime/ckpt/reshard.py's explicit device_put to the destination
  sharding)

Each has a ``*_clean`` twin proving the rules don't fire on the fixed
form. All fixtures trace on the 8-device CPU mesh (no execution).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deepspeed_tpu.utils.jax_compat import shard_map


def corpus_mesh() -> Mesh:
    devs = np.array(jax.devices()[:8]).reshape(4, 2)
    return Mesh(devs, ("dp", "tp"))


# --------------------------------------------------------------------- R2
def _drift_scan(mesh, drift: bool):
    resting = NamedSharding(mesh, P("dp", None))
    # the drifted writeback loses the dim-0 partition — exactly what the
    # bucketed layer scan's drop-lead slice hooks did to a dp-sharded
    # stacked dim before the PR-2 resting re-put
    writeback = NamedSharding(mesh, P(None, "tp") if drift else P("dp", None))

    def step(x):
        x = lax.with_sharding_constraint(x, resting)

        def body(c, _):
            c = jax.device_put(c * 0.5 + 1.0, writeback)
            return c, ()

        y, _ = lax.scan(body, x, None, length=4)
        return y

    sds = jax.ShapeDtypeStruct((8, 2), jnp.float32)
    return jax.make_jaxpr(step)(sds)


def stacked_dim0_drift():
    mesh = corpus_mesh()
    return _drift_scan(mesh, True), {"mesh": mesh}, "R2"


def stacked_dim0_drift_clean():
    mesh = corpus_mesh()
    return _drift_scan(mesh, False), {"mesh": mesh}, "R2"


# ------------------------------------------------------------------ R2 bis
def _slot_cache_scan(mesh, drift: bool):
    """The serving engine's slot-KV-arena carry: the arena
    [slots, capacity, kv*hd] rests with cache heads over tp and is
    carried through the step loop (frontier writes via
    dynamic_update_slice). The drifted form re-puts the carry with the
    head partition swapped onto the slot dim — exactly the bug a serving
    step whose cache write loses its sharding constraint would compile
    to (per-step reshard of the whole arena on real ICI)."""
    resting = NamedSharding(mesh, P(None, None, "tp"))
    writeback = NamedSharding(
        mesh, P("dp", None, None) if drift else P(None, None, "tp")
    )

    def step(arena):
        arena = lax.with_sharding_constraint(arena, resting)

        def body(c, _):
            chunk = jnp.ones((4, 2, 16), c.dtype)  # one step's KV writes
            c = lax.dynamic_update_slice(c, chunk, (0, 0, 0))
            c = jax.device_put(c, writeback)  # the step's carry-out
            return c, ()

        y, _ = lax.scan(body, arena, None, length=3)
        return y

    sds = jax.ShapeDtypeStruct((4, 8, 16), jnp.float32)
    return jax.make_jaxpr(step)(sds)


def slot_cache_carry_drift():
    mesh = corpus_mesh()
    return _slot_cache_scan(mesh, True), {"mesh": mesh}, "R2"


def slot_cache_carry_drift_clean():
    mesh = corpus_mesh()
    return _slot_cache_scan(mesh, False), {"mesh": mesh}, "R2"


# ------------------------------------------------------------------ R2 ter
def _paged_pool_scan(mesh, drift: bool):
    """The PAGED serving arena's pool carry: a global page pool
    [num_pages, page_size, kv*hd] resting with cache heads over tp,
    addressed through a traced per-slot page table (gather for the
    per-slot views, scatter for the chunk write — the block-paged form of
    the slot arena). The drifted form re-puts the carried pool with the
    head partition moved onto the PAGE dim — the bug a paged step whose
    pool write-back loses its sharding constraint compiles to: the whole
    pool reshards over ICI every serving step."""
    resting = NamedSharding(mesh, P(None, None, "tp"))
    writeback = NamedSharding(
        mesh, P("dp", None, None) if drift else P(None, None, "tp")
    )

    def step(pool, page_table):
        pool = lax.with_sharding_constraint(pool, resting)

        def body(c, _):
            view = c[page_table]          # [slots, pages/slot, ps, kv*hd]
            chunk = view[:, 0, :2] + 1.0  # one step's per-slot writes
            c = c.at[page_table[:, 0], :2].set(chunk)
            c = jax.device_put(c, writeback)  # the step's carry-out
            return c, ()

        y, _ = lax.scan(body, pool, None, length=3)
        return y

    pool = jax.ShapeDtypeStruct((8, 4, 16), jnp.float32)
    pt = jnp.zeros((2, 3), jnp.int32)
    return jax.make_jaxpr(step)(pool, pt)


def paged_pool_carry_drift():
    mesh = corpus_mesh()
    return _paged_pool_scan(mesh, True), {"mesh": mesh}, "R2"


def paged_pool_carry_drift_clean():
    mesh = corpus_mesh()
    return _paged_pool_scan(mesh, False), {"mesh": mesh}, "R2"


# ---------------------------------------------------------------- R2 quater
def _spec_frontier_scan(mesh, drift: bool):
    """The SPECULATIVE serving step's arena carry: each slot writes a
    k+1-wide verify window (committed token + k drafts) at its own
    frontier — a vmapped per-row dynamic_update_slice, the multi-token
    form of the slot engine's frontier write — and the arena must keep
    its head partition through the carry. The drifted form re-puts the
    carry with the partition moved onto the slot dim: the bug a spec
    step whose masked window write-back loses its sharding constraint
    compiles to (the whole arena reshards over ICI every verify)."""
    resting = NamedSharding(mesh, P(None, None, "tp"))
    writeback = NamedSharding(
        mesh, P("dp", None, None) if drift else P(None, None, "tp")
    )

    def step(arena, frontier):
        arena = lax.with_sharding_constraint(arena, resting)

        def body(c, _):
            win = jnp.ones((4, 3, 16), c.dtype)  # k+1 = 3 verify rows/slot
            c = jax.vmap(
                lambda a, w, off: lax.dynamic_update_slice(a, w, (off, 0))
            )(c, win, frontier)
            c = jax.device_put(c, writeback)  # the step's carry-out
            return c, ()

        y, _ = lax.scan(body, arena, None, length=3)
        return y

    arena = jax.ShapeDtypeStruct((4, 8, 16), jnp.float32)
    frontier = jnp.zeros((4,), jnp.int32)
    return jax.make_jaxpr(step)(arena, frontier)


def spec_frontier_mask_drift():
    mesh = corpus_mesh()
    return _spec_frontier_scan(mesh, True), {"mesh": mesh}, "R2"


def spec_frontier_mask_drift_clean():
    mesh = corpus_mesh()
    return _spec_frontier_scan(mesh, False), {"mesh": mesh}, "R2"


# --------------------------------------------------------------------- R1
def _grad_step(mesh, reduce_grads: bool):
    def body(g, p):
        if reduce_grads:
            g = lax.pmean(g, "dp")
        return p - 0.1 * g  # claimed-replicated "updated params"

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=(P("dp"), P()),
        out_specs=P(),
        axis_names={"dp", "tp"},
        check_vma=False,
    )
    g = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    p = jax.ShapeDtypeStruct((2, 4), jnp.float32)
    return jax.make_jaxpr(lambda a, b: fn(a, b))(g, p)


def missing_psum_grads():
    mesh = corpus_mesh()
    return _grad_step(mesh, False), {"mesh": mesh}, "R1"


def missing_psum_grads_clean():
    mesh = corpus_mesh()
    return _grad_step(mesh, True), {"mesh": mesh}, "R1"


# --------------------------------------------------------------------- R3
def _pp_ring(mesh, perm):
    def body(x):
        return lax.psum(lax.ppermute(x, "dp", perm), "dp")

    fn = shard_map(
        body,
        mesh=mesh,
        in_specs=P("dp"),
        out_specs=P(),
        axis_names={"dp", "tp"},
        check_vma=False,
    )
    x = jax.ShapeDtypeStruct((8, 2), jnp.float32)
    return jax.make_jaxpr(fn)(x)


def broken_ppermute_ring():
    mesh = corpus_mesh()
    # ring 1→2→3→1 plus a stray 0→1 edge: duplicate destination — the
    # schedule hangs members on real ICI
    perm = [(1, 2), (2, 3), (3, 1), (0, 1)]
    return _pp_ring(mesh, perm), {"mesh": mesh}, "R3"


def broken_ppermute_ring_clean():
    mesh = corpus_mesh()
    perm = [(i, (i + 1) % 4) for i in range(4)]  # full single ring
    return _pp_ring(mesh, perm), {"mesh": mesh}, "R3"


# --------------------------------------------------------------------- R4
def _rotating_slot(stale_read: bool):
    def prog(slots, xs):
        def body(carry, x):
            buf = carry
            new = lax.dynamic_update_slice(buf, x[None], (0, 0))
            if stale_read:
                # reads the PRE-overwrite generation: the rotating slot
                # already holds the new bytes
                out = buf[0] + x
            else:
                out = new[0] + x
            return new, out

        return lax.scan(body, slots, xs)

    slots = jax.ShapeDtypeStruct((2, 4), jnp.float32)
    xs = jax.ShapeDtypeStruct((3, 4), jnp.float32)
    return jax.make_jaxpr(prog)(slots, xs)


def read_after_donate():
    return _rotating_slot(True), {}, "R4"


def read_after_donate_clean():
    return _rotating_slot(False), {}, "R4"


# --------------------------------------------------------------------- R5
def _master_update(truncate: bool):
    def prog(p, g):
        u = g.astype(jnp.float32) * -0.1
        if truncate:
            p = p.astype(jnp.bfloat16).astype(jnp.float32)
        return p + u

    p = jax.ShapeDtypeStruct((4, 4), jnp.float32)
    g = jax.ShapeDtypeStruct((4, 4), jnp.bfloat16)
    closed = jax.make_jaxpr(prog)(p, g)
    return closed, {"master_pairs": [(0, 0, "params")]}


def truncated_master():
    closed, kw = _master_update(True)
    return closed, kw, "R5"


def truncated_master_clean():
    closed, kw = _master_update(False)
    return closed, kw, "R5"


class _FakePinnedSharding:
    """Duck-typed pinned-host sharding: CPU devices expose no pinned_host
    memory space, so the corpus seeds the placement evidence directly —
    rules only read ``.spec`` / ``.memory_kind``."""

    memory_kind = "pinned_host"
    spec = P()


def _pinned_host(copy_first: bool):
    mesh = corpus_mesh()

    def prog(m):
        if copy_first:
            m = jax.device_put(m, NamedSharding(mesh, P()))
        return m * 2.0 + 1.0

    m = jax.ShapeDtypeStruct((4, 4), jnp.float32)
    closed = jax.make_jaxpr(prog)(m)
    # both twins start from a pinned-host master; the clean one copies to
    # device memory before any math touches it
    kw = {
        "mesh": mesh,
        "arg_shardings": {closed.jaxpr.invars[0]: _FakePinnedSharding()},
    }
    return closed, kw


def pinned_host_compute():
    closed, kw = _pinned_host(False)
    return closed, kw, "R5"


def pinned_host_compute_clean():
    closed, kw = _pinned_host(True)
    return closed, kw, "R5"


# --------------------------------------------------------------------- R3
# decomposed collective matmul (parallel/tensor_overlap.py): the clean twin
# traces the REAL ring program; the hazard is the same shape hand-rolled
# with a raw lax.ppermute and a malformed ring (bypassing the
# comm.collectives.permute construction-time contract — the exact mistake
# the hook exists to prevent, kept detectable at lint time)
def _overlap_topo():
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    return MeshTopology(dims=ParallelDims(dp=2, tp=4))


def tp_overlap_malformed_ring():
    topo = _overlap_topo()
    tp = 4
    # ring 0→1→2→3 closed back to 1 instead of 0: duplicate destination —
    # two members send to one, the ring hangs on real ICI
    perm = [(0, 1), (1, 2), (2, 3), (3, 1)]

    def body(x, w):
        i = lax.axis_index("tp")
        m = x.shape[1]
        out = jnp.zeros((x.shape[0], m * tp, w.shape[1]), x.dtype)
        chunk, src = x, i
        for s in range(tp):
            out = lax.dynamic_update_slice(
                out, jnp.einsum("bsk,kn->bsn", chunk, w), (0, src * m, 0)
            )
            if s < tp - 1:
                chunk = lax.ppermute(chunk, "tp", perm)
                src = (src - 1) % tp
        return out

    fn = shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(P(("dp",), "tp", None), P(None, "tp")),
        out_specs=P("dp", None, "tp"),
        axis_names=set(topo.mesh.axis_names),
        check_vma=False,
    )
    x = jax.ShapeDtypeStruct((2, 8, 16), jnp.float32)
    w = jax.ShapeDtypeStruct((16, 8), jnp.float32)
    return jax.make_jaxpr(fn)(x, w), {"mesh": topo.mesh}, "R3"


def tp_overlap_ring_clean():
    from deepspeed_tpu.parallel.tensor_overlap import allgather_matmul

    topo = _overlap_topo()

    def prog(x, w):
        return allgather_matmul(x, w, topo, chunks=2, bidirectional=True)

    x = jax.ShapeDtypeStruct((2, 8, 16), jnp.float32)
    w = jax.ShapeDtypeStruct((16, 8), jnp.float32)
    return jax.make_jaxpr(prog)(x, w), {"mesh": topo.mesh}, "R3"


# ------------------------------------------------------------------ R3 bis
# decomposed MoE all-to-all (parallel/a2a_overlap.py): the clean twin
# traces the REAL overlapped expert layer; the hazard is the same dispatch-
# reduce ring hand-rolled with a raw lax.ppermute whose ep ring closes on
# the wrong member (bypassing comm.collectives.permute's construction-time
# contract — the exact mistake the hook exists to prevent)
def _moe_topo():
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    return MeshTopology(dims=ParallelDims(dp=2, ep=4))


def moe_a2a_malformed_ring():
    topo = _moe_topo()
    ep, E_loc, C, D = 4, 1, 8, 16
    # ring 0→1→2→3 closed back to 1 instead of 0: duplicate destination —
    # two members send to one, the exchange hangs on real ICI
    perm = [(0, 1), (1, 2), (2, 3), (3, 1)]

    def body(disp, tok):
        i = lax.axis_index("ep")
        n = tok.shape[0]

        def part(blk):
            d = lax.dynamic_slice(disp, (0, blk * E_loc, 0), (n, E_loc, C))
            return jnp.einsum("nec,nd->ecd", d, tok)

        acc = part((i - 1) % ep)
        for s in range(1, ep):
            acc = lax.ppermute(acc, "ep", perm)
            acc = acc + part((i - 1 - s) % ep)
        return lax.psum(acc, ("dp",))

    fn = shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(P(("dp", "ep"), None, None), P(("dp", "ep"), None)),
        out_specs=P(None, None, None),
        axis_names=set(topo.mesh.axis_names),
        check_vma=False,
    )
    disp = jax.ShapeDtypeStruct((16, ep * E_loc, C), jnp.float32)
    tok = jax.ShapeDtypeStruct((16, D), jnp.float32)
    return jax.make_jaxpr(fn)(disp, tok), {"mesh": topo.mesh}, "R3"


def moe_a2a_ring_clean():
    from deepspeed_tpu.parallel.a2a_overlap import moe_a2a_ffn

    topo = _moe_topo()
    B, S, D, F, E, C = 2, 8, 16, 32, 4, 8

    def prog(x, disp, comb, wi, wg, wo):
        return moe_a2a_ffn(
            x, ("einsum", disp, comb), (wi, wg, wo), topo,
            chunks=2, bidirectional=True,
        )

    x = jax.ShapeDtypeStruct((B, S, D), jnp.float32)
    disp = jax.ShapeDtypeStruct((B, S, E, C), jnp.float32)
    comb = jax.ShapeDtypeStruct((B, S, E, C), jnp.float32)
    wi = jax.ShapeDtypeStruct((E, D, F), jnp.float32)
    wg = jax.ShapeDtypeStruct((E, D, F), jnp.float32)
    wo = jax.ShapeDtypeStruct((E, F, D), jnp.float32)
    return (
        jax.make_jaxpr(prog)(x, disp, comb, wi, wg, wo),
        {"mesh": topo.mesh},
        "R3",
    )


# ------------------------------------------------------------------ R3 ter
# decode-shaped MoE exchange (ISSUE 14, parallel/a2a_overlap.moe_decode_a2a
# — the serving engine's expert-parallel combine ride): the hazard is the
# same ride hand-rolled with a raw lax.ppermute whose ep cycle maps two
# members onto one destination (the exchange hangs on real ICI); the clean
# twin traces the REAL decode ring, whose every hop goes through
# comm.collectives.permute's construction-time R3 contract
def _moe_decode_topo():
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    return MeshTopology(dims=ParallelDims(ep=4), devices=jax.devices()[:4])


def moe_decode_ring_malformed():
    topo = _moe_decode_topo()
    ep, E_loc, C, D = 4, 1, 8, 16
    # ring 0→1→2→3 closed back to 1 instead of 0: duplicate destination —
    # two members send their expert-output block to one, the combine ride
    # hangs on real ICI
    perm = [(0, 1), (1, 2), (2, 3), (3, 1)]

    def body(eo_local):
        i = lax.axis_index("ep")
        full = jnp.zeros((ep * E_loc, C, D), eo_local.dtype)
        buf = eo_local
        for s in range(ep):
            blk = (i - s) % ep
            full = lax.dynamic_update_slice(full, buf, (blk * E_loc, 0, 0))
            if s < ep - 1:
                buf = lax.ppermute(buf, "ep", perm)
        return full

    fn = shard_map(
        body,
        mesh=topo.mesh,
        in_specs=(P("ep", None, None),),
        out_specs=P(None, None, None),
        axis_names=set(topo.mesh.axis_names),
        check_vma=False,
    )
    eo = jax.ShapeDtypeStruct((ep * E_loc, C, D), jnp.float32)
    return jax.make_jaxpr(fn)(eo), {"mesh": topo.mesh}, "R3"


def moe_decode_ring_clean():
    from deepspeed_tpu.parallel.a2a_overlap import moe_decode_a2a

    topo = _moe_decode_topo()
    N, D, F, E, C, K = 12, 16, 32, 4, 8, 2

    def prog(tokens, tok_of_slot, slot_valid, slot_of_tok, w_of_tok,
             wi, wg, wo):
        return moe_decode_a2a(
            tokens, tok_of_slot, slot_valid, slot_of_tok, w_of_tok,
            (wi, wg, wo), topo, chunks=2, bidirectional=True,
        )

    tokens = jax.ShapeDtypeStruct((N, D), jnp.float32)
    tof = jax.ShapeDtypeStruct((E, C), jnp.int32)
    sv = jax.ShapeDtypeStruct((E, C), jnp.bool_)
    sot = jax.ShapeDtypeStruct((N, K), jnp.int32)
    wt = jax.ShapeDtypeStruct((N, K), jnp.float32)
    wi = jax.ShapeDtypeStruct((E, D, F), jnp.float32)
    wg = jax.ShapeDtypeStruct((E, D, F), jnp.float32)
    wo = jax.ShapeDtypeStruct((E, F, D), jnp.float32)
    return (
        jax.make_jaxpr(prog)(tokens, tof, sv, sot, wt, wi, wg, wo),
        {"mesh": topo.mesh},
        "R3",
    )


# ------------------------------------------------------------------ R4 bis
def _prefetch_slots(stale_read: bool):
    """A hand-rolled two-slot ZeRO-3 gather prefetch: the rotating slot
    buffer [2, d, d] is overwritten with the next layer's gathered params
    via dynamic_update_slice each tick; the hazard reads the PRE-overwrite
    generation — the layer computes with layer i-2's weights (exactly the
    staleness the functional carry in runtime/zero/prefetch.py avoids by
    construction)."""

    def prog(slots, gathered):
        def body(carry, layer_w):
            buf = carry
            new = lax.dynamic_update_slice(buf, layer_w[None], (0, 0, 0))
            src = buf if stale_read else new
            out = jnp.tanh(src[0]) * 0.5
            return new, out

        return lax.scan(body, slots, gathered)

    slots = jax.ShapeDtypeStruct((2, 4, 4), jnp.float32)
    gathered = jax.ShapeDtypeStruct((3, 4, 4), jnp.float32)
    return jax.make_jaxpr(prog)(slots, gathered)


def zero3_prefetch_stale_slot():
    return _prefetch_slots(True), {}, "R4"


def zero3_prefetch_stale_slot_clean():
    return _prefetch_slots(False), {}, "R4"


# ------------------------------------------------------------------ R5 ter
def _grad_wire_update(truncate: bool):
    """The qgZ contract at the master update: an int8 grad wire is only
    sound when the dequantized blocks ACCUMULATE INTO THE MASTER IN F32
    (comm/wires.py decodes to f32 before any sum). The hazard books the
    wire-decoded gradient into the master through a bf16 accumulate —
    every path from the f32 master input to the f32 master output passes
    through a sub-32-bit float, the exact bf16-in-f32-clothing drift R5
    exists to catch. The clean twin is the dequant-accumulate-in-f32
    path the engine's wired reduction ships."""

    def prog(master, g):
        # the int8 wire leg (shared lane-wise scheme, fake-quant form)
        amax = jnp.max(jnp.abs(g), axis=0, keepdims=True)
        scale = jnp.maximum(amax, 1e-12) / 127.0
        q = jnp.clip(jnp.round(g / scale), -127, 127).astype(jnp.int8)
        deq = q.astype(jnp.float32) * scale
        if truncate:
            new = (
                master.astype(jnp.bfloat16)
                - 0.1 * deq.astype(jnp.bfloat16)
            ).astype(jnp.float32)
        else:
            new = master - 0.1 * deq
        return new

    m = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    g = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    closed = jax.make_jaxpr(prog)(m, g)
    return closed, {"master_pairs": [(0, 0, "master")]}


def grad_wire_truncates_master():
    closed, kw = _grad_wire_update(True)
    return closed, kw, "R5"


def grad_wire_truncates_master_clean():
    closed, kw = _grad_wire_update(False)
    return closed, kw, "R5"


# ------------------------------------------------------------------ R3 ter
# hierarchical 2-hop wire (comm/wires.py): the clean twin traces the REAL
# reduce_scatter_wire(hierarchical=True) program over a factored dp x fsdp
# mesh; the hazard is the same 2-hop shape hand-rolled with a raw
# lax.ppermute whose intra-group ring permutation maps two members onto
# one — a malformed group split that hangs the inner hop on real ICI
# (bypassing comm.collectives.permute's construction-time contract)
def _hier_topo():
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    return MeshTopology(dims=ParallelDims(dp=2, fsdp=4))


def hier_wire_bad_split():
    topo = _hier_topo()
    n_i = 4
    # inner "ring" 0→1→2→3 closed back to 1: duplicate destination — the
    # intra-group exchange desynchronizes and hangs members on real ICI
    perm = [(0, 1), (1, 2), (2, 3), (3, 1)]

    def body(x):
        # hand-rolled hop 1: ride-the-ring partial accumulation over fsdp
        i = lax.axis_index("fsdp")
        chunk = x.shape[0] // n_i

        def part(blk):
            return lax.dynamic_slice(
                x, (blk * chunk, 0), (chunk, x.shape[1])
            ).astype(jnp.float32)

        acc = part((i - 1) % n_i)
        for s in range(1, n_i):
            acc = lax.ppermute(acc, "fsdp", perm)
            acc = acc + part((i - 1 - s) % n_i)
        # hop 2: the inter-group reduction over dp
        return lax.psum(acc, "dp")

    fn = shard_map(
        body,
        mesh=topo.mesh,
        in_specs=P(("dp", "fsdp")),
        out_specs=P("fsdp"),
        axis_names=set(topo.mesh.axis_names),
        check_vma=False,
    )
    x = jax.ShapeDtypeStruct((32, 8), jnp.float32)
    return jax.make_jaxpr(fn)(x), {"mesh": topo.mesh}, "R3"


def hier_wire_bad_split_clean():
    from deepspeed_tpu.comm.wires import reduce_scatter_wire

    topo = _hier_topo()

    def prog(contribs):
        return reduce_scatter_wire(
            contribs, topo, ("dp", "fsdp"), "int8", hierarchical=True
        )

    contribs = jax.ShapeDtypeStruct((8, 32, 8), jnp.float32)
    return jax.make_jaxpr(prog)(contribs), {"mesh": topo.mesh}, "R3"


# --------------------------------------------------------------------- R6
def _budget_prog():
    mesh = corpus_mesh()

    def prog(x, w):
        h = jnp.einsum("bk,kn->bn", x, w)
        return (h * 2.0).sum()

    x = jax.ShapeDtypeStruct((256, 512), jnp.float32)
    w = jax.ShapeDtypeStruct((512, 512), jnp.float32)
    return jax.make_jaxpr(prog)(x, w), mesh


def hbm_over_budget():
    # x+w+h ≈ 1.8 MiB live — a 64 KiB per-device budget cannot hold it
    closed, mesh = _budget_prog()
    return closed, {"mesh": mesh, "hbm_budget_bytes": 64 * 1024}, "R6"


def hbm_over_budget_clean():
    closed, mesh = _budget_prog()
    return closed, {"mesh": mesh, "hbm_budget_bytes": 1 << 30}, "R6"


# ------------------------------------------------------------------ R6 bis
def _autotune_rung(micro: int):
    """An autotuner rung's shape: a per-device [micro, S, H] activation
    batch through a two-matmul block to a loss. The planner-driven
    search prices exactly this kind of program per (stage, remat, micro)
    rung; the hazard is the fat-micro rung whose activation live set
    statically exceeds the budget BOTH twins share — R6 prunes it before
    any compile, the thin rung passes (the prune-before-compile
    contract, docs/memory_planner.md)."""
    mesh = corpus_mesh()

    def prog(x, w1, w2):
        h = jnp.tanh(jnp.einsum("bsh,hk->bsk", x, w1))
        y = jnp.einsum("bsk,kh->bsh", h, w2)
        return ((y - x) ** 2).sum()

    x = jax.ShapeDtypeStruct((micro, 128, 256), jnp.float32)
    w1 = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    w2 = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    closed = jax.make_jaxpr(prog)(x, w1, w2)
    # 3 MiB/device: holds weights + the mb=1 rung's live set (~0.9 MiB)
    # with room, and is crossed by mb=16 (x alone is 2 MiB, h/y double it)
    kw = {"mesh": mesh, "hbm_budget_bytes": 3 * (1 << 20)}
    return closed, kw


def autotuner_rung_oom():
    closed, kw = _autotune_rung(16)
    return closed, kw, "R6"


def autotuner_rung_oom_clean():
    closed, kw = _autotune_rung(1)
    return closed, kw, "R6"


# --------------------------------------------------------------------- R7
def _reshard_pair(mesh, roundtrip: bool):
    # the hazard: transpose → reshard → transpose⁻¹, all single-use —
    # the placement cast pins both copies, so XLA cannot cancel the
    # pair; resharding the ORIGINAL value costs half the copies. The
    # clean twin does exactly that.
    cast = NamedSharding(mesh, P(None, "dp"))

    def prog(x):
        if roundtrip:
            y = jnp.transpose(x)
            y = lax.with_sharding_constraint(y, cast)
            z = jnp.transpose(y)
        else:
            z = lax.with_sharding_constraint(
                x, NamedSharding(mesh, P("dp", None))
            )
        return z * 1.5

    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    return jax.make_jaxpr(prog)(x)


def reshard_transpose_pair():
    mesh = corpus_mesh()
    return _reshard_pair(mesh, True), {"mesh": mesh}, "R7"


def reshard_transpose_pair_clean():
    mesh = corpus_mesh()
    return _reshard_pair(mesh, False), {"mesh": mesh}, "R7"


# --------------------------------------------------------------------- R8
def _declared_stream(nbytes: float):
    mesh = corpus_mesh()

    def prog(x, w):
        return jnp.einsum("bk,kn->bn", x, w).sum()

    x = jax.ShapeDtypeStruct((64, 128), jnp.float32)
    w = jax.ShapeDtypeStruct((128, 128), jnp.float32)
    closed = jax.make_jaxpr(prog)(x, w)
    kw = {
        "mesh": mesh,
        "streams": {
            "offload": {
                "kind": "offload",
                "bytes_per_step": nbytes,
                "per_device_bytes_per_step": nbytes,
                "overlapped": True,
            }
        },
    }
    return closed, kw


def unhideable_offload_stream():
    # 64 GiB/step over a 32 GB/s host link is ~2 s of DMA; the tiny
    # matmul's compute window is microseconds — the overlap claim is
    # statically false (the PERF_NOTES round-7 ceiling)
    closed, kw = _declared_stream(64 * (1 << 30))
    return closed, kw, "R8"


def unhideable_offload_stream_clean():
    closed, kw = _declared_stream(4 * 1024)  # 4 KiB hides under anything
    return closed, kw, "R8"


# --------------------------------------------------------------------- R9
def _slot_sampling(reuse: bool):
    """The serving sampler's key discipline: each slot's chain key is
    split, one subkey per draw. The hazard consumes ONE key at two
    sampling sites (the categorical draw and the top-p uniform) — the
    draws are correlated and the replay chain desynchronizes from the
    lockstep reference. The clean twin is the chain rule the slot
    engine ships: split first, consume each subkey once."""

    def prog(logits, key):
        if reuse:
            tok = jax.random.categorical(key, logits)
            u = jax.random.uniform(key, (logits.shape[0],))
        else:
            k1, k2 = jax.random.split(key)
            tok = jax.random.categorical(k1, logits)
            u = jax.random.uniform(k2, (logits.shape[0],))
        return tok, u

    logits = jax.ShapeDtypeStruct((4, 16), jnp.float32)
    return jax.make_jaxpr(prog)(logits, jax.random.PRNGKey(0))


def rng_key_reuse():
    return _slot_sampling(True), {}, "R9"


def rng_key_reuse_clean():
    return _slot_sampling(False), {}, "R9"


# ------------------------------------------------------------------ R10 bis
def _wire_ring_accum(narrow: bool):
    """A hand-rolled qgZ-style wire accumulate: int8 chunk payloads are
    dequantized (decode + lane-scale) and folded into a running
    accumulator chunk by chunk. The hazard runs the accumulator in
    bf16 — every grouping of the adds lands different rounding, so the
    declared-bitwise wire pair cannot hold. The clean twin accumulates
    in f32 and casts once at the end (comm/wires.py's contract)."""
    acc_dtype = jnp.bfloat16 if narrow else jnp.float32

    def prog(q, scales):
        acc = q[0].astype(acc_dtype) * scales[0].astype(acc_dtype)
        for s in range(1, 4):
            acc = acc + q[s].astype(acc_dtype) * scales[s].astype(acc_dtype)
        return acc.astype(jnp.bfloat16)

    q = jax.ShapeDtypeStruct((4, 8, 16), jnp.int8)
    scales = jax.ShapeDtypeStruct((4, 1, 16), jnp.float32)
    return jax.make_jaxpr(prog)(q, scales)


def reassoc_accum_drift():
    return _wire_ring_accum(True), {}, "R10"


def reassoc_accum_drift_clean():
    return _wire_ring_accum(False), {}, "R10"


# --------------------------------------------------------------------- R11
def _per_tick_step(baked: bool):
    """The slot step's trace-stability contract: per-tick scheduler
    state (here ``spec_len``) must be a TRACED input. The hazard bakes
    it as a python constant — the compiled program is specialized on
    one tick's value and every later tick retraces (or silently runs
    with the first tick's state). The lint kwargs carry the traced-args
    manifest exactly like serving.trace_serving_step supplies it."""
    BAKED_SPEC_LEN = 2

    def step_baked(tokens, num_new):
        window = tokens[:, :1 + BAKED_SPEC_LEN]
        return window.sum(axis=1) + num_new

    def step_traced(tokens, num_new, spec_len):
        mask = jnp.arange(tokens.shape[1])[None, :] <= spec_len[:, None]
        return (tokens * mask).sum(axis=1) + num_new

    tokens = jax.ShapeDtypeStruct((4, 8), jnp.int32)
    num_new = jax.ShapeDtypeStruct((4,), jnp.int32)
    spec_len = jax.ShapeDtypeStruct((4,), jnp.int32)
    if baked:
        closed = jax.make_jaxpr(step_baked)(tokens, num_new)
        manifest = {"tokens": (0, 1), "num_new": (1, 2)}
    else:
        closed = jax.make_jaxpr(step_traced)(tokens, num_new, spec_len)
        manifest = {"tokens": (0, 1), "num_new": (1, 2),
                    "spec_len": (2, 3)}
    kw = {
        "required_traced": ("num_new", "spec_len"),
        "traced_manifest": manifest,
    }
    return closed, kw


def static_arg_per_tick():
    closed, kw = _per_tick_step(True)
    return closed, kw, "R11"


def static_arg_per_tick_clean():
    closed, kw = _per_tick_step(False)
    return closed, kw, "R11"


# --------------------------------------------------------------------- R12
# flat vs 2-hop grad reduce-scatter on a HYBRID mesh (ISSUE 17): the
# hazard traces the real comm/wires.py FLAT form — one joint ring over
# ("dp", "fsdp") — on a mesh whose dp axis is DCN-tagged, so every hop of
# the full payload synchronizes on the slow inter-pod link; the clean
# twin traces the SAME wire hierarchical (intra-fsdp ring on ICI, then
# the 1/n_fsdp-sized inter hop over dp), the decomposition R12 names
def _dcn_topo():
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    return MeshTopology.hybrid(dims=ParallelDims(dp=2, fsdp=4))


def _dcn_ring(hierarchical: bool):
    from deepspeed_tpu.comm.wires import reduce_scatter_wire

    topo = _dcn_topo()

    def prog(contribs):
        return reduce_scatter_wire(
            contribs, topo, ("dp", "fsdp"), "int8",
            hierarchical=hierarchical,
        )

    # a wire-bucket-sized payload: past R12's latency-bound materiality
    # floor, so the joint flat ring flags on bandwidth grounds
    contribs = jax.ShapeDtypeStruct((8, 2048, 64), jnp.float32)
    kw = {"mesh": topo.mesh, "link_kinds": topo.link_kinds}
    return jax.make_jaxpr(prog)(contribs), kw


def dcn_flat_ring():
    closed, kw = _dcn_ring(hierarchical=False)
    return closed, kw, "R12"


def dcn_flat_ring_clean():
    closed, kw = _dcn_ring(hierarchical=True)
    return closed, kw, "R12"


# --------------------------------------------------------------------- R13
# overlap claims must hold at DCN bandwidth: the hazard declares an
# overlapped grad-wire stream over a DCN-tagged dp axis whose payload
# fits the compute window at ICI speed (R8 stays silent — its one wire
# speed IS the ICI figure) but takes ~80x the window on the inter-pod
# link; the clean twin is the hierarchical split of the same stream,
# whose declared inter_bytes_per_step hop is all that rides DCN
def _dcn_stream(hierarchical: bool):
    from deepspeed_tpu.analysis.cost import HardwareModel

    mesh = corpus_mesh()

    def prog(x, w):
        return jnp.einsum("bk,kn->bn", x, w).sum()

    x = jax.ShapeDtypeStruct((64, 128), jnp.float32)
    w = jax.ShapeDtypeStruct((128, 128), jnp.float32)
    closed = jax.make_jaxpr(prog)(x, w)
    # ~21 ms compute window; 16 MiB/step fits it at 1 GB/s ICI (~17 ms)
    # but not at the 0.01 GB/s DCN share (~1.7 s)
    stream = {
        "kind": "ici",
        "axes": ("dp",),
        "bytes_per_step": 16 * (1 << 20),
        "per_device_bytes_per_step": 16 * (1 << 20),
        "overlapped": True,
    }
    if hierarchical:
        stream["hierarchical"] = True
        stream["inter_bytes_per_step"] = 64 * 1024
    kw = {
        "mesh": mesh,
        "link_kinds": {"dp": "dcn"},
        "streams": {"grad_wire": stream},
        "hardware": HardwareModel(
            gen="test", peak_flops=1e8, hbm_bytes=1 << 30, hbm_bw=1e9,
            ici_bw=1e9, host_bw=1e9, dcn_bw=1e7,
        ),
    }
    return closed, kw


def dcn_unbudgeted_stream():
    closed, kw = _dcn_stream(hierarchical=False)
    return closed, kw, "R13"


def dcn_unbudgeted_stream_clean():
    closed, kw = _dcn_stream(hierarchical=True)
    return closed, kw, "R13"


# ------------------------------------------------------- R8 (kv tiering)
def _kv_spill_stream(page_bytes: float, stage_slots: int):
    """The tiered serving step's host-spill stream (serving/engine.py
    ``kv_spill_stream``): ``stage_slots`` pages in + ``stage_slots``
    pages out per step, declared overlapped because the staged-gather
    hides the page-in under decode. The hazard sizes a page so large
    the double-buffer window can never hide it on the host link; the
    clean twin is the shipped two-slot staging buffer over a real page."""
    mesh = corpus_mesh()

    def prog(x, w):
        return jnp.einsum("bk,kn->bn", x, w).sum()

    x = jax.ShapeDtypeStruct((64, 128), jnp.float32)
    w = jax.ShapeDtypeStruct((128, 128), jnp.float32)
    closed = jax.make_jaxpr(prog)(x, w)
    nbytes = float(page_bytes) * stage_slots * 2  # demote + promote
    kw = {
        "mesh": mesh,
        "streams": {
            "kv_spill": {
                "kind": "offload",
                "bytes_per_step": nbytes,
                "per_device_bytes_per_step": nbytes,
                "overlapped": True,
                "stage_slots": stage_slots,
                "page_bytes_at_rest": float(page_bytes),
                "codec": "fp32",
            }
        },
    }
    return closed, kw


def kv_spill_unbudgeted():
    # an 8 GiB page x 2 staging slots x 2 directions is ~1 s of host
    # DMA per step — no decode window hides it; the overlap claim is
    # statically false
    closed, kw = _kv_spill_stream(8 * (1 << 30), stage_slots=2)
    return closed, kw, "R8"


def kv_spill_unbudgeted_clean():
    # a real page (2 layers x 16 tok x 4 kv-heads x 8 hd x 4 B k+v) is
    # KiB-scale — the double-buffered window hides it under anything
    closed, kw = _kv_spill_stream(32 * 1024, stage_slots=2)
    return closed, kw, "R8"


# ------------------------------------------------------------- R2 (ckpt)
def _restore_scan(mesh, drift: bool):
    """runtime/ckpt restore discipline as a carry fixture: the optimizer
    pair (m, v) rests dp-sharded on dim 0 and is rebuilt from host
    rectangles at restore time. The hazard's writeback re-puts the
    rebuilt tree WITHOUT the resting partition — what a loader that
    skips reshard.py's final ``device_put(arr, sharding)`` compiles to —
    so the donated carry re-enters the step loop de-sharded. The clean
    twin re-puts to the resting sharding (reshard._resharded_leaf's
    last line)."""
    resting = NamedSharding(mesh, P("dp", None))
    restored = NamedSharding(mesh, P(None, "tp") if drift else P("dp", None))

    def step(m, v):
        m = lax.with_sharding_constraint(m, resting)
        v = lax.with_sharding_constraint(v, resting)

        def body(carry, _):
            cm, cv = carry
            # the restore writeback: the carry rebuilt from host shards
            cm = jax.device_put(cm * 0.9 + 0.1, restored)
            cv = jax.device_put(cv * 0.99 + 0.01, restored)
            return (cm, cv), ()

        (m, v), _ = lax.scan(body, (m, v), None, length=4)
        return m, v

    sds = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    return jax.make_jaxpr(step)(sds, sds)


def restore_drops_sharding():
    mesh = corpus_mesh()
    return _restore_scan(mesh, True), {"mesh": mesh}, "R2"


def restore_drops_sharding_clean():
    mesh = corpus_mesh()
    return _restore_scan(mesh, False), {"mesh": mesh}, "R2"


HAZARDS = [
    stacked_dim0_drift,
    slot_cache_carry_drift,
    paged_pool_carry_drift,
    spec_frontier_mask_drift,
    missing_psum_grads,
    broken_ppermute_ring,
    read_after_donate,
    truncated_master,
    pinned_host_compute,
    tp_overlap_malformed_ring,
    moe_a2a_malformed_ring,
    moe_decode_ring_malformed,
    zero3_prefetch_stale_slot,
    grad_wire_truncates_master,
    hier_wire_bad_split,
    hbm_over_budget,
    autotuner_rung_oom,
    reshard_transpose_pair,
    unhideable_offload_stream,
    rng_key_reuse,
    reassoc_accum_drift,
    static_arg_per_tick,
    dcn_flat_ring,
    dcn_unbudgeted_stream,
    kv_spill_unbudgeted,
    restore_drops_sharding,
]

CLEAN_TWINS = [
    stacked_dim0_drift_clean,
    slot_cache_carry_drift_clean,
    paged_pool_carry_drift_clean,
    spec_frontier_mask_drift_clean,
    missing_psum_grads_clean,
    broken_ppermute_ring_clean,
    read_after_donate_clean,
    truncated_master_clean,
    pinned_host_compute_clean,
    tp_overlap_ring_clean,
    moe_a2a_ring_clean,
    moe_decode_ring_clean,
    zero3_prefetch_stale_slot_clean,
    grad_wire_truncates_master_clean,
    hier_wire_bad_split_clean,
    hbm_over_budget_clean,
    autotuner_rung_oom_clean,
    reshard_transpose_pair_clean,
    unhideable_offload_stream_clean,
    rng_key_reuse_clean,
    reassoc_accum_drift_clean,
    static_arg_per_tick_clean,
    dcn_flat_ring_clean,
    dcn_unbudgeted_stream_clean,
    kv_spill_unbudgeted_clean,
    restore_drops_sharding_clean,
]
