"""Seeded-bug fixtures for the fleetcheck corpus.

Every builder returns ``(scenario, expect)`` — a fully-specified model
checking run and the violation id fleetcheck MUST report on it
(``None`` for the clean twins, which must come back green). The armed
scenarios carry their fault names in ``scenario.mutations``; the
faults themselves live behind test-only flags in serving/faults.py and
are compiled out of any run that does not arm them.

- ``promotion_livelock``       LIVELOCK: the PR 18 promotion planner
  with the stickiness guard removed (``promotion_unsticky``) — the
  promote-2/steal-2 rotation never returns any waiter to full
  residency, a zero-progress cycle the all-EOS drain cannot break
- ``promotion_livelock_clean`` the same scenario unarmed: the sticky
  planner heals one waiter per ceil(n/STAGE_SLOTS) ticks and every
  state quiesces
- ``handoff_leak``             H3: fleet handoff rollback that drops
  its dst-page cleanup on a deferred transfer (``handoff_leak``) —
  refcount-1 pages with no holder, pinned by the conservation sweep
- ``handoff_leak_clean``       the same prefill/decode split unarmed

These are the regression anchors for docs/modelcheck.md "seeded-bug
corpus": if a refactor makes any armed fixture come back clean, the
checker (or the fault seam) lost its teeth — fail the build, don't
relax the fixture.
"""

from deepspeed_tpu.analysis.modelcheck import MUTATIONS

__all__ = [
    "promotion_livelock", "promotion_livelock_clean",
    "handoff_leak", "handoff_leak_clean", "ALL",
]


def promotion_livelock():
    mut = MUTATIONS["promotion_livelock"]
    return mut.scenario(), mut.expect


def promotion_livelock_clean():
    return MUTATIONS["promotion_livelock"].clean(), None


def handoff_leak():
    mut = MUTATIONS["handoff_leak"]
    return mut.scenario(), mut.expect


def handoff_leak_clean():
    return MUTATIONS["handoff_leak"].clean(), None


ALL = {
    "promotion_livelock": promotion_livelock,
    "promotion_livelock_clean": promotion_livelock_clean,
    "handoff_leak": handoff_leak,
    "handoff_leak_clean": handoff_leak_clean,
}
