"""Seeded-bug corpus for shardlint (ISSUE 2 acceptance gate).

Each fixture in :mod:`fixtures` reintroduces one real hazard class from
this repo's history as a small traceable program; the shardlint suite
asserts every one is flagged by its rule — and that the clean twins are
not.
"""
