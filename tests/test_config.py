"""Config parsing/validation tests. Model: reference tests/unit/runtime/test_ds_config_dict.py."""

import json

import pytest

from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError


def test_batch_triangle_full():
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 32,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 4,
        },
        dp_world_size=4,
    )
    assert cfg.train_batch_size == 32
    assert cfg.gradient_accumulation_steps == 4


def test_batch_triangle_infer_accum():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "train_micro_batch_size_per_gpu": 2}, dp_world_size=4
    )
    assert cfg.gradient_accumulation_steps == 4


def test_batch_triangle_infer_micro():
    cfg = DeepSpeedConfig(
        {"train_batch_size": 32, "gradient_accumulation_steps": 2}, dp_world_size=4
    )
    assert cfg.train_micro_batch_size_per_gpu == 4


def test_batch_triangle_mismatch_raises():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            {
                "train_batch_size": 33,
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 4,
            },
            dp_world_size=4,
        )


def test_fp16_bf16_exclusive():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"fp16": {"enabled": True}, "bf16": {"enabled": True}})


def test_zero_section_defaults_and_offload():
    cfg = DeepSpeedConfig(
        {
            "zero_optimization": {
                "stage": 3,
                "offload_optimizer": {"device": "cpu"},
                "offload_param": {"device": "cpu"},
            }
        }
    )
    assert cfg.zero_config.stage == 3
    assert cfg.zero_config.offload_optimizer.enabled
    assert cfg.zero_config.offload_param.enabled
    assert cfg.zero_enabled


def test_offload_double_buffer_knob_and_alias():
    """offload_double_buffer defaults off (parity gate) and accepts the
    sub_group_prefetch alias spelling."""
    assert not DeepSpeedConfig(
        {"zero_optimization": {"stage": 3}}
    ).zero_config.offload_double_buffer
    assert DeepSpeedConfig(
        {"zero_optimization": {"stage": 3, "offload_double_buffer": True}}
    ).zero_config.offload_double_buffer
    assert DeepSpeedConfig(
        {"zero_optimization": {"stage": 3, "sub_group_prefetch": True}}
    ).zero_config.offload_double_buffer
    # explicit key wins over the alias
    assert not DeepSpeedConfig(
        {"zero_optimization": {"stage": 3, "sub_group_prefetch": True,
                               "offload_double_buffer": False}}
    ).zero_config.offload_double_buffer


def test_offload_param_requires_stage3():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            {"zero_optimization": {"stage": 2, "offload_param": {"device": "cpu"}}}
        )


def test_zero23_incompatible_with_pipeline():
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"zero_optimization": {"stage": 2}, "pipeline": {"stages": 2}})


def test_fp16_loss_scale_knobs():
    cfg = DeepSpeedConfig(
        {
            "fp16": {
                "enabled": True,
                "initial_scale_power": 8,
                "loss_scale_window": 100,
                "hysteresis": 3,
            }
        }
    )
    assert cfg.fp16.dynamic
    assert cfg.fp16.initial_scale == 256.0
    assert cfg.fp16.hysteresis == 3


def test_config_from_json_path(tmp_path):
    p = tmp_path / "ds_config.json"
    p.write_text(
        json.dumps(
            {
                "train_micro_batch_size_per_gpu": 4,
                "optimizer": {"type": "AdamW", "params": {"lr": 3e-4, "betas": [0.9, 0.95]}},
                "scheduler": {"type": "WarmupLR", "params": {"warmup_num_steps": 10}},
                "bf16": {"enabled": True},
                "gradient_clipping": 1.0,
            }
        )
    )
    cfg = DeepSpeedConfig(str(p), dp_world_size=2)
    assert cfg.train_batch_size == 8
    assert cfg.optimizer.type == "adamw"
    assert cfg.optimizer.lr == 3e-4
    assert cfg.optimizer.betas == (0.9, 0.95)
    assert cfg.scheduler.type == "WarmupLR"
    assert cfg.gradient_clipping == 1.0
    import jax.numpy as jnp

    assert cfg.compute_dtype == jnp.bfloat16


def test_unknown_keys_ignored():
    cfg = DeepSpeedConfig({"zero_optimization": {"stage": 1, "some_future_knob": 7}})
    assert cfg.zero_config.stage == 1


def test_auto_values_treated_as_unset():
    cfg = DeepSpeedConfig(
        {"train_batch_size": "auto", "train_micro_batch_size_per_gpu": 2}, dp_world_size=4
    )
    assert cfg.train_batch_size == 8
