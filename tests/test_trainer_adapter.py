"""TrainerStrategyAdapter: the Lightning-Strategy contract (SURVEY §2.8).

A simulated external trainer loop that touches ONLY the Strategy hook
surface — setup / training_step / backward / optimizer_step /
validation_step / save_checkpoint / load_checkpoint / barrier / rank
queries — proving a Lightning-style driver runs unchanged on the engine.
"""

import numpy as np

from deepspeed_tpu.comm import MeshTopology, ParallelDims
from deepspeed_tpu.integrations import TrainerStrategyAdapter
from deepspeed_tpu.models import gpt2

CONFIG = {
    "train_batch_size": 8,
    "optimizer": {"type": "adamw", "params": {"lr": 3e-3}},
    "zero_optimization": {"stage": 2},
}


def _model():
    return gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                num_layers=2, num_heads=2)


def _batch(r):
    return {"input_ids": r.randint(0, 64, size=(8, 16))}


def test_strategy_driven_loop_trains_and_resumes(tmp_path):
    topo = MeshTopology(dims=ParallelDims(dp=8))
    strategy = TrainerStrategyAdapter(_model(), CONFIG, topology=topo)
    strategy.setup()
    assert strategy.setup() is strategy  # idempotent per the Strategy contract
    assert strategy.world_size == 1 and strategy.is_global_zero

    r = np.random.RandomState(0)
    batch = _batch(r)
    losses = []
    for _ in range(6):
        loss = strategy.training_step(batch)
        strategy.backward(loss)          # recorded no-ops: the step fused them
        strategy.optimizer_step()
        strategy.lr_scheduler_step()
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert strategy.global_step == 6

    val = float(strategy.validation_step(batch))
    assert np.isfinite(val)

    # checkpoint IO hooks + exact resume through a fresh strategy
    strategy.save_checkpoint(str(tmp_path))
    strategy.barrier("after-save")
    after_save = float(strategy.training_step(batch))

    resumed = TrainerStrategyAdapter(_model(), CONFIG, topology=topo)
    resumed.load_checkpoint(str(tmp_path))  # setup() implied
    assert resumed.global_step == 6
    assert abs(float(resumed.training_step(batch)) - after_save) < 1e-5

    # engine fall-through keeps trainers that poke engine attrs working
    assert resumed.micro_steps == resumed.engine.micro_steps
    strategy.teardown()
    resumed.teardown()
    assert strategy.engine is None


def test_unbuilt_strategy_raises_attribute_error():
    strategy = TrainerStrategyAdapter(_model(), CONFIG)
    try:
        strategy.train_batch
    except AttributeError:
        pass
    else:
        raise AssertionError("expected AttributeError before setup()")
