"""Real 2-process ``jax.distributed`` integration through the launcher.

Parity: the reference's launcher (deepspeed/launcher/runner.py) is validated
by actual multi-rank jobs; its unit suite spawns real ranks for
torch.distributed paths. Here the ``local`` launcher backend spawns two OS
processes on this host, each with 2 virtual CPU devices, joined into one
4-device ``jax.distributed`` job (Gloo CPU collectives). This exercises for
real what single-process tests cannot:

- ``comm.init_distributed`` -> ``jax.distributed.initialize`` from the
  DSTPU_* env the launcher exports,
- cross-process sharded train steps (global arrays, non-addressable shards),
- ``checkpointing._barrier`` / ``_is_writer`` / per-process shard writes and
  the global sharded load,
- ``wait_and_propagate`` failure propagation and signal exit codes.
"""

import os
import socket
import subprocess
import sys
import time

from conftest import xfail_legacy_num_cpu_devices

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TRAIN_WORKER = r'''
import os, sys

# Fresh interpreter: claim 2 local CPU devices BEFORE any backend init.
flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(flags)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

import numpy as np
import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import ParallelDims

ckpt_dir = sys.argv[1]

# reads DSTPU_COORDINATOR/NUM_PROCESSES/PROCESS_ID exported by the launcher
topo = comm.init_distributed(dims=ParallelDims(dp=4))
assert jax.process_count() == 2, jax.process_count()
assert jax.device_count() == 4, jax.device_count()
pid = jax.process_index()

from deepspeed_tpu.models import llama
model = llama("llama-tiny", vocab_size=128, max_seq_len=32, hidden_size=32,
              num_layers=1, num_heads=2, num_kv_heads=2, intermediate_size=96)
engine, _, _, _ = deepspeed_tpu.initialize(model=model, topology=topo, config={
    "train_batch_size": 4,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "zero_optimization": {"stage": 3, "stage3_param_persistence_threshold": 1},
})
batch = {"input_ids": np.random.RandomState(0).randint(0, 128, size=(4, 16))}
l0 = float(engine.train_batch(batch=batch))
engine.save_checkpoint(ckpt_dir)          # per-process shard writes + barrier
l1 = float(engine.train_batch(batch=batch))  # advance past the saved state
engine.load_checkpoint(ckpt_dir)          # barrier + global sharded load
l1b = float(engine.train_batch(batch=batch))
assert abs(l1 - l1b) < 1e-5, (l1, l1b)    # bit-stable resume across processes
assert os.path.exists(os.path.join(ckpt_dir, "latest"))
print(f"WORKER {pid} OK l0={l0:.4f} resume_delta={abs(l1-l1b):.2e}", flush=True)
'''

COMPOSED_WORKER = r'''
import os, sys

flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
         if not f.startswith("--xla_force_host_platform_device_count")]
os.environ["XLA_FLAGS"] = " ".join(flags)
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)

import jax.numpy as jnp
import numpy as np
import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import ParallelDims

ckpt_dir = sys.argv[1]

# composed mesh: dp spans the two processes (outer axis), tp pairs devices
# within each — ZeRO-1 shards optimizer state over the cross-process dp
# axis while Megatron TP splits every projection within a process
topo = comm.init_distributed(dims=ParallelDims(dp=2, tp=2))
assert jax.process_count() == 2 and jax.device_count() == 4
pid = jax.process_index()

from deepspeed_tpu.models import llama
model = llama("llama-tiny", vocab_size=128, max_seq_len=32, hidden_size=32,
              num_layers=1, num_heads=2, num_kv_heads=2, intermediate_size=96)
engine, _, _, _ = deepspeed_tpu.initialize(model=model, topology=topo, config={
    "train_batch_size": 4,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "zero_optimization": {"stage": 1},
})
batch = {"input_ids": np.random.RandomState(0).randint(0, 128, size=(4, 16))}
l0 = float(engine.train_batch(batch=batch))
engine.save_checkpoint(ckpt_dir)
# replicated scalar both processes can read back — the parent compares it
# after loading this checkpoint at a DIFFERENT topology/process count
cksum = sum(
    float(jnp.sum(jnp.abs(l.astype(jnp.float32))))
    for l in jax.tree_util.tree_leaves(engine.state.params)
)
print(f"WORKER {pid} OK loss={l0:.4f} CKSUM={cksum:.6f}", flush=True)
'''

FAIL_WORKER = r'''
import os, sys, time
pid = int(os.environ["DSTPU_PROCESS_ID"])
mode = sys.argv[1]
if pid == 1:
    if mode == "exit3":
        sys.exit(3)
    os.kill(os.getpid(), 9)  # mode == "sigkill"
time.sleep(120)  # rank 0 wedges; the launcher must tear it down
'''


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch(tmp_path, script_body, script_args, timeout=420):
    script = tmp_path / "worker.py"
    script.write_text(script_body)
    hostfile = tmp_path / "hosts.txt"
    hostfile.write_text("rank0 slots=2\nrank1 slots=2\n")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO  # no relay plugin site dir in the workers
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "deepspeed_tpu.launcher.runner",
         "--hostfile", str(hostfile), "--launcher", "local",
         "--master_port", str(_free_port()), str(script), *script_args],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO,
    )
    return proc, time.monotonic() - t0


@xfail_legacy_num_cpu_devices
def test_two_process_train_and_sharded_checkpoint(tmp_path):
    ckpt = tmp_path / "ckpt"
    proc, _ = _launch(tmp_path, TRAIN_WORKER, [str(ckpt)])
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-3000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-3000:]
    # the sharded layout really is per-process rectangles: with ZeRO-3 over
    # dp=4 and 2 procs x 2 devices, params carry shards from both processes
    tag = (ckpt / "latest").read_text().strip()
    shards = [f for f in os.listdir(ckpt / tag / "params") if ".shard." in f]
    assert shards, os.listdir(ckpt / tag / "params")
    # metadata written once, by the writer process only
    assert (ckpt / tag / "metadata.json").exists()


@xfail_legacy_num_cpu_devices
def test_composed_mesh_save_then_load_at_different_process_count(tmp_path):
    """VERDICT r4 #8: a dp2xtp2 mesh across the 2-process boundary trains,
    ZeRO-1-shards, and checkpoints; the checkpoint then loads into THIS
    single process at a different topology (dp=2, tp=1, 8 devices) with
    the same logical state — the universal-checkpoint reshape across
    process counts."""
    import re

    import jax
    import jax.numpy as jnp
    import numpy as np

    import deepspeed_tpu
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
    from deepspeed_tpu.models import llama

    ckpt = tmp_path / "ckpt"
    proc, _ = _launch(tmp_path, COMPOSED_WORKER, [str(ckpt)])
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-3000:]
    assert "WORKER 0 OK" in out and "WORKER 1 OK" in out, out[-3000:]
    cksum = float(re.search(r"CKSUM=([0-9.]+)", out).group(1))

    model = llama("llama-tiny", vocab_size=128, max_seq_len=32,
                  hidden_size=32, num_layers=1, num_heads=2, num_kv_heads=2,
                  intermediate_size=96)
    engine, _, _, _ = deepspeed_tpu.initialize(
        model=model,
        topology=MeshTopology(dims=ParallelDims(dp=2),
                              devices=jax.devices()[:2]),
        config={
            "train_batch_size": 4,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 1},
        },
        rng=jax.random.PRNGKey(123),  # different init: load must overwrite
    )
    engine.load_checkpoint(str(ckpt))
    got = sum(
        float(jnp.sum(jnp.abs(l.astype(jnp.float32))))
        for l in jax.tree_util.tree_leaves(engine.state.params)
    )
    np.testing.assert_allclose(got, cksum, rtol=1e-5)
    # and the reloaded engine still trains at the new topology
    batch = {"input_ids": np.random.RandomState(1).randint(0, 128,
                                                           size=(4, 16))}
    assert np.isfinite(float(engine.train_batch(batch=batch)))


def test_rank_failure_propagates_exit_code(tmp_path):
    proc, dt = _launch(tmp_path, FAIL_WORKER, ["exit3"], timeout=90)
    assert proc.returncode == 3, (proc.returncode, proc.stderr[-1000:])
    assert dt < 60, f"launcher took {dt:.0f}s to tear down the healthy rank"


def test_rank_signal_death_maps_to_128_plus_sig(tmp_path):
    proc, dt = _launch(tmp_path, FAIL_WORKER, ["sigkill"], timeout=90)
    assert proc.returncode == 128 + 9, (proc.returncode, proc.stderr[-1000:])
    assert dt < 60, f"launcher took {dt:.0f}s to tear down the healthy rank"
