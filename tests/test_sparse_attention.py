"""Block-sparse attention vs masked-dense oracle (SURVEY §2.4; reference
csrc/sparse_attention + deepspeed/ops/sparse_attention). CPU interpret mode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.sparse_attention import (
    BigBirdSparsityConfig,
    BSLongformerSparsityConfig,
    DenseSparsityConfig,
    FixedSparsityConfig,
    VariableSparsityConfig,
    causal_trim,
    dense_blocksparse_reference,
    sparse_attention,
)


def _qkv(seed, B=2, S=512, H=2, D=64):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return (
        jax.random.normal(ks[0], (B, S, H, D)),
        jax.random.normal(ks[1], (B, S, H, D)),
        jax.random.normal(ks[2], (B, S, H, D)),
    )


CONFIGS = [
    DenseSparsityConfig(block=128),
    FixedSparsityConfig(block=128, num_local_blocks=2, num_global_blocks=1),
    BigBirdSparsityConfig(block=128, num_sliding_window_blocks=3,
                          num_global_blocks=1, num_random_blocks=1),
    BSLongformerSparsityConfig(block=128, num_sliding_window_blocks=3,
                               global_block_indices=[0]),
    VariableSparsityConfig(block=128, local_window_blocks=[1, 2],
                           global_block_indices=[0], num_random_blocks=1),
]


@pytest.mark.parametrize("cfg", CONFIGS, ids=lambda c: type(c).__name__)
@pytest.mark.parametrize("causal", [True, False])
def test_sparse_matches_masked_dense(cfg, causal):
    q, k, v = _qkv(0)
    out = sparse_attention(q, k, v, cfg, causal=causal)
    layout = cfg.make_layout(512)
    if causal:
        layout = causal_trim(layout)
    ref = dense_blocksparse_reference(q, k, v, layout, cfg.block, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sparse_grads_match_masked_dense():
    cfg = FixedSparsityConfig(block=128, num_local_blocks=2, num_global_blocks=1)
    q, k, v = _qkv(1, B=1, S=256)
    layout = causal_trim(cfg.make_layout(256))

    g_sp = jax.grad(
        lambda *a: jnp.sum(sparse_attention(*a, cfg, causal=True) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(
            dense_blocksparse_reference(*a, layout, cfg.block, causal=True) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gs, gr, name in zip(g_sp, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gs), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_layout_shapes_and_validation():
    cfg = FixedSparsityConfig(block=128, num_local_blocks=2)
    assert cfg.make_layout(512).shape == (4, 4)
    with pytest.raises(ValueError):
        cfg.make_layout(500)  # not block-divisible

    # kernel rejects a mismatched mask table
    q, k, v = _qkv(2, S=256)
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    with pytest.raises(ValueError):
        flash_attention(q, k, v, block_mask=np.ones((3, 3)), block_q=128,
                        block_k=128)


def test_fixed_layout_is_causal_friendly():
    """Every query block sees its own diagonal block (softmax never empty)."""
    for cfg in CONFIGS:
        layout = causal_trim(cfg.make_layout(512))
        assert (np.diag(layout) == 1).all(), type(cfg).__name__


def test_engine_sparse_attention_config(devices8, monkeypatch):
    """ds_config "sparse_attention" drives the train step: the flash kernel
    receives a block mask and training converges."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    import deepspeed_tpu.ops.pallas.flash_attention as fa
    from deepspeed_tpu.models import llama

    masks_seen = []
    orig = fa.flash_attention

    def spy(q, k, v, **kw):
        masks_seen.append(kw.get("block_mask") is not None)
        return orig(q, k, v, **kw)

    # the real sparse_attention imports flash_attention from the module at
    # call time, so this spy observes the genuine engine → sparse → kernel
    # path (no reimplementation in the test)
    monkeypatch.setattr(fa, "flash_attention", spy)

    comm.destroy_process_group()
    model = llama("llama-tiny", vocab_size=128, max_seq_len=256,
                  hidden_size=64, num_layers=2, num_heads=2, num_kv_heads=2,
                  intermediate_size=128)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "steps_per_print": 1000,
            "sparse_attention": {"mode": "fixed", "block": 128,
                                 "num_local_blocks": 1,
                                 "num_global_blocks": 1},
        },
        rng=jax.random.PRNGKey(0),
    )
    data = {"input_ids": np.random.RandomState(0).randint(0, 128, size=(8, 256))}
    losses = [float(engine.train_batch(batch=data)) for _ in range(10)]
    assert masks_seen and all(masks_seen), "block mask never reached the kernel"
    assert np.isfinite(losses).all() and losses[-1] < losses[0]


def test_sparse_attention_config_validation():
    import pytest as _pytest

    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    with _pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "sparse_attention": {"mode": "wat"}})
    with _pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "sparse_attention": {"mode": "fixed"},
                         "sequence_parallel": {"sp_size": 2}})
    with _pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"train_batch_size": 8,
                         "sparse_attention": {"mode": "fixed"},
                         "data_efficiency": {"data_routing": {"random_ltd": {
                             "enabled": True}}}})


def test_compaction_tables_pad_repeat_and_counts():
    """The DMA-skip tables: active columns ascending, padding repeats the
    last index (consecutive equal indices → Mosaic skips the re-fetch)."""
    import numpy as np

    from deepspeed_tpu.ops.pallas.flash_attention import _compact_rows

    layout = np.array([
        [1, 0, 1, 0],
        [0, 0, 0, 0],
        [1, 1, 1, 1],
        [0, 1, 0, 0],
    ])
    idx, counts = _compact_rows(layout)
    assert counts.tolist() == [2, 0, 4, 1]
    assert idx.shape == (4, 4)  # jmax = densest row
    assert idx[0].tolist() == [0, 2, 2, 2]  # pad repeats last active
    assert idx[1].tolist() == [0, 0, 0, 0]  # empty row: predicated off
    assert idx[2].tolist() == [0, 1, 2, 3]
    assert idx[3].tolist() == [1, 1, 1, 1]


def test_sparse_grid_is_compacted_not_dense():
    """The kernel grid's last dim is jmax (densest row), not nk — the
    structural evidence that masked tiles are skipped, not just predicated."""
    import numpy as np

    from deepspeed_tpu.ops.pallas.flash_attention import _compact_rows

    cfg = BSLongformerSparsityConfig(block=128, num_sliding_window_blocks=3)
    S = 128 * 16
    layout = causal_trim(cfg.make_layout(S))
    kcols, _ = _compact_rows(layout)
    nk = S // 128
    assert kcols.shape[1] < nk, (kcols.shape, nk)  # strictly fewer steps
    # and the window+global pattern bounds the row density independent of S
    assert kcols.shape[1] <= 2 + 1 + 1  # window(2 causal) + global col + row


def test_traced_block_mask_falls_back_with_reason():
    import jax
    import jax.numpy as jnp

    from deepspeed_tpu.ops.pallas import flash_attention as fa_mod
    from deepspeed_tpu.utils import logging as logging_mod
    from deepspeed_tpu.ops.pallas.flash_attention import flash_attention

    logging_mod.fallback_log_seen.clear()
    rng = jax.random.PRNGKey(0)
    q = jax.random.normal(rng, (1, 256, 2, 64))
    # non-trivial layout: dropping it would NOT reproduce dense attention
    layout = np.array([[1, 0], [0, 1]], np.int32)

    @jax.jit
    def run(q, mask):
        return flash_attention(q, q, q, causal=True, block_mask=mask,
                               block_q=128, block_k=128)

    out = run(q, jnp.asarray(layout))  # mask is a tracer inside jit
    ref = dense_blocksparse_reference(q, q, q, layout, 128, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    reasons = [r for key in logging_mod.fallback_log_seen
               for r in key[1]]
    assert any("trace-time static" in r for r in reasons), reasons
