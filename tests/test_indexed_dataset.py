"""Indexed .bin/.idx dataset: C++ mmap reader vs numpy fallback, builder
round-trip, batch gather semantics, dataloader integration (SURVEY data
pipeline; reference: Megatron MMapIndexedDataset + its C backend)."""

import numpy as np
import pytest

from deepspeed_tpu.data_pipeline import (
    IndexedDatasetBuilder,
    MMapIndexedDataset,
)
from deepspeed_tpu.data_pipeline import indexed_dataset as idx_mod


def build(tmp_path, docs, name="ds"):
    b = IndexedDatasetBuilder(str(tmp_path / name))
    for d in docs:
        b.add_document(d)
    b.finalize()
    return str(tmp_path / name)


DOCS = [
    [1, 2, 3, 4, 5],
    [10, 11],
    list(range(100, 140)),
    [7],
]


def test_roundtrip_and_lengths(tmp_path):
    prefix = build(tmp_path, DOCS)
    ds = MMapIndexedDataset(prefix)
    assert len(ds) == len(DOCS)
    for i, d in enumerate(DOCS):
        assert ds.seq_len(i) == len(d)
        np.testing.assert_array_equal(ds.get(i), np.asarray(d, np.int32))
    with pytest.raises(IndexError):
        ds.get(len(DOCS))
    ds.close()


def test_batch_gather_pad_truncate_start(tmp_path):
    prefix = build(tmp_path, DOCS)
    ds = MMapIndexedDataset(prefix)
    out = ds.get_batch([0, 1, 2], seqlen=8, pad_id=-1)
    np.testing.assert_array_equal(out[0], [1, 2, 3, 4, 5, -1, -1, -1])
    np.testing.assert_array_equal(out[1], [10, 11] + [-1] * 6)
    np.testing.assert_array_equal(out[2], list(range(100, 108)))
    # start offset: window [2, 10) of each doc
    out = ds.get_batch([2, 0], seqlen=8, start=2, pad_id=0)
    np.testing.assert_array_equal(out[0], list(range(102, 110)))
    np.testing.assert_array_equal(out[1], [3, 4, 5, 0, 0, 0, 0, 0])
    ds.close()


def test_u16_upgrade_to_i32(tmp_path):
    """Tokens >65535 upgrade the .bin in place; earlier docs survive."""
    prefix = build(tmp_path, [[1, 2, 3], [70000, 5]], name="big")
    ds = MMapIndexedDataset(prefix)
    np.testing.assert_array_equal(ds.get(0), [1, 2, 3])
    np.testing.assert_array_equal(ds.get(1), [70000, 5])
    ds.close()


def test_numpy_fallback_matches_cpp(tmp_path, monkeypatch):
    prefix = build(tmp_path, DOCS)
    ds_cpp = MMapIndexedDataset(prefix)
    ref = ds_cpp.get_batch([3, 2, 1, 0], seqlen=16, pad_id=9)
    ds_cpp.close()
    # force the fallback path
    monkeypatch.setattr(idx_mod, "_lib", lambda: None)
    ds_np = MMapIndexedDataset(prefix)
    assert ds_np._h is None
    np.testing.assert_array_equal(
        ds_np.get_batch([3, 2, 1, 0], seqlen=16, pad_id=9), ref
    )
    for i in range(len(DOCS)):
        np.testing.assert_array_equal(ds_np.get(i), DOCS[i])


def test_corrupt_index_rejected(tmp_path):
    prefix = build(tmp_path, DOCS, name="bad")
    with open(prefix + ".idx", "r+b") as f:
        f.write(b"XXXXXXXX")  # clobber the magic
    with pytest.raises(ValueError):
        MMapIndexedDataset(prefix)


def test_dataloader_integration(tmp_path):
    """seqlen mode feeds the engine dataloader: ds[i] = {'input_ids': row}
    and a few train steps run."""
    import jax

    import deepspeed_tpu

    docs = [np.random.RandomState(i).randint(0, 250, size=(np.random.RandomState(i).randint(5, 30),)).tolist()
            for i in range(16)]
    prefix = build(tmp_path, docs, name="train")
    ds = MMapIndexedDataset(prefix, seqlen=16, pad_id=0)
    from deepspeed_tpu.models import gpt2

    engine, _, loader, _ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=256, max_seq_len=16,
                   hidden_size=32, num_layers=2, num_heads=2),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        },
        training_data=ds,
    )
    it = iter(loader)
    l0 = float(engine.train_batch(data_iter=it))
    l1 = float(engine.train_batch(data_iter=it))
    assert np.isfinite(l0) and np.isfinite(l1)
    engine.destroy()


def test_empty_dataset_opens(tmp_path):
    """A zero-document (or all-empty-document) dataset the builder itself
    writes must open on both reader paths."""
    b = IndexedDatasetBuilder(str(tmp_path / "empty"))
    b.finalize()
    ds = MMapIndexedDataset(str(tmp_path / "empty"))
    assert len(ds) == 0
    ds.close()


def test_randomized_windows_match_numpy_oracle(tmp_path):
    """Fuzz: random docs, random gather windows — C++ reader vs a plain
    numpy reconstruction."""
    r = np.random.RandomState(42)
    docs = [r.randint(0, 70000, size=r.randint(1, 64)).tolist()
            for _ in range(40)]  # >65535 forces the i32 path too
    prefix = build(tmp_path, docs, name="fuzz")
    ds = MMapIndexedDataset(prefix)
    for _ in range(25):
        n = r.randint(1, 8)
        idx = r.randint(0, len(docs), size=n)
        seqlen = int(r.randint(1, 80))
        start = int(r.randint(0, 70))
        pad = int(r.randint(-2, 3))
        got = ds.get_batch(idx, seqlen, start=start, pad_id=pad)
        want = np.full((n, seqlen), pad, np.int32)
        for k, i in enumerate(idx):
            win = np.asarray(docs[i][start:start + seqlen], np.int32)
            want[k, : len(win)] = win
        np.testing.assert_array_equal(got, want)
    ds.close()
