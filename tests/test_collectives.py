"""Collective op tests on the virtual 8-device mesh.

Oracle: numpy reference reductions (model: reference tests/unit/comm/test_dist.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from deepspeed_tpu.utils.jax_compat import shard_map

import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import collectives as col
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims


def _mesh1d():
    return MeshTopology(ParallelDims()).mesh  # dp=8


def test_all_reduce_matches_numpy(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)

    f = shard_map(
        lambda a: col.all_reduce(a, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )
    out = jax.jit(f)(x)
    expected = np.tile(np.asarray(x).sum(axis=0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(out), expected)


def test_reduce_scatter_all_gather_roundtrip(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)

    def body(a):
        # a: [1, 16] per shard. rs over flattened vector of 16 -> 2 each, ag back.
        v = a.reshape(16)
        shard = col.reduce_scatter(v, "dp")  # [2]
        full = col.all_gather(shard, "dp")  # [16]
        return full.reshape(1, 16)

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    expected = np.tile(np.asarray(x).sum(axis=0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(out), expected)


def test_broadcast_from_src(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0

    out = jax.jit(
        shard_map(
            lambda a: col.broadcast(a, "dp", src=3),
            mesh=mesh,
            in_specs=P("dp"),
            out_specs=P("dp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 4.0))


def test_all_to_all_transpose(devices8):
    mesh = _mesh1d()
    # Each rank holds a row of 8 blocks; all_to_all swaps block-owner axis.
    x = jnp.arange(8 * 8, dtype=jnp.float32).reshape(8, 8)

    def body(a):
        v = a.reshape(8)  # row i
        swapped = col.all_to_all(v, "dp", split_axis=0, concat_axis=0)  # column i
        return swapped.reshape(1, 8)

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).T)


def test_send_forward_shifts(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    out = jax.jit(
        shard_map(
            lambda a: col.send_forward(a, "dp", 8),
            mesh=mesh,
            in_specs=P("dp"),
            out_specs=P("dp"),
        )
    )(x)
    expected = np.concatenate([[0.0], np.arange(7)]).reshape(8, 1)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_comm_hook_records_ops(devices8):
    mesh = _mesh1d()
    records = []
    col.register_comm_hook(lambda op, axis, nbytes: records.append((op, axis, nbytes)))
    x = jnp.ones((8, 4), jnp.float32)
    jax.jit(
        shard_map(lambda a: col.all_reduce(a, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    )(x)
    assert ("all_reduce", "dp", 16) in records  # 1x4 f32 per-shard view


def test_comm_module_api(devices8):
    topo = comm.init_distributed(dims=ParallelDims(tp=2))
    assert comm.get_world_size() == 8
    assert comm.get_world_size("tp") == 2
    assert comm.get_rank() == 0
    assert comm.is_initialized()


def test_permute_contract_rejects_malformed_rings(devices8):
    """permute() enforces the shardlint-R3 ring/chain contract at
    construction time (ISSUE 3 satellite): the decomposed-matmul rings are
    lint-guaranteed the moment they trace, not only when shardlint later
    walks the jaxpr."""
    import pytest

    mesh = _mesh1d()

    def run(perm, **kw):
        f = shard_map(
            lambda a: col.permute(a, "dp", perm, **kw),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        )
        return jax.jit(f)(jnp.arange(8.0))

    # legal: full ring, neighbor chain (the pipeline hop), empty perm
    run([(i, (i + 1) % 8) for i in range(8)])
    run([(i, i + 1) for i in range(7)])
    run([])
    # illegal shapes raise at trace time with the lint wording
    for perm in (
        [(0, 9)],                              # out of range
        [(0, 1), (0, 2)],                      # duplicate source
        [(0, 1), (2, 1)],                      # duplicate destination
        [(3, 3)],                              # self-loop
        [(0, 1), (1, 0), (2, 3), (3, 2)],      # disjoint sub-rings
        [(0, 1), (1, 0)],                      # partial ring
    ):
        with pytest.raises(ValueError, match="malformed ppermute"):
            run(perm)
    # validate=False bypasses (lint remains the backstop — the corpus
    # keeps the hazard class detectable)
    run([(0, 1), (1, 0)], validate=False)


def test_send_wrappers_satisfy_the_permute_contract(devices8):
    """send_forward/backward (wrap and no-wrap) ride the validated path —
    their perms are exactly the chain/ring shapes the contract allows."""
    mesh = _mesh1d()
    for fn in (col.send_forward, col.send_backward):
        for wrap in (False, True):
            f = shard_map(
                lambda a, _fn=fn, _w=wrap: _fn(a, "dp", 8, wrap=_w),
                mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            )
            jax.jit(f)(jnp.arange(8.0))
