"""Collective op tests on the virtual 8-device mesh.

Oracle: numpy reference reductions (model: reference tests/unit/comm/test_dist.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from deepspeed_tpu.utils.jax_compat import shard_map

import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import collectives as col
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims


def _mesh1d():
    return MeshTopology(ParallelDims()).mesh  # dp=8


def test_all_reduce_matches_numpy(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8 * 4, dtype=jnp.float32).reshape(8, 4)

    f = shard_map(
        lambda a: col.all_reduce(a, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P("dp")
    )
    out = jax.jit(f)(x)
    expected = np.tile(np.asarray(x).sum(axis=0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(out), expected)


def test_reduce_scatter_all_gather_roundtrip(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8 * 16, dtype=jnp.float32).reshape(8, 16)

    def body(a):
        # a: [1, 16] per shard. rs over flattened vector of 16 -> 2 each, ag back.
        v = a.reshape(16)
        shard = col.reduce_scatter(v, "dp")  # [2]
        full = col.all_gather(shard, "dp")  # [16]
        return full.reshape(1, 16)

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    expected = np.tile(np.asarray(x).sum(axis=0, keepdims=True), (8, 1))
    np.testing.assert_allclose(np.asarray(out), expected)


def test_broadcast_from_src(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1) + 1.0

    out = jax.jit(
        shard_map(
            lambda a: col.broadcast(a, "dp", src=3),
            mesh=mesh,
            in_specs=P("dp"),
            out_specs=P("dp"),
        )
    )(x)
    np.testing.assert_allclose(np.asarray(out), np.full((8, 1), 4.0))


def test_all_to_all_transpose(devices8):
    mesh = _mesh1d()
    # Each rank holds a row of 8 blocks; all_to_all swaps block-owner axis.
    x = jnp.arange(8 * 8, dtype=jnp.float32).reshape(8, 8)

    def body(a):
        v = a.reshape(8)  # row i
        swapped = col.all_to_all(v, "dp", split_axis=0, concat_axis=0)  # column i
        return swapped.reshape(1, 8)

    out = jax.jit(shard_map(body, mesh=mesh, in_specs=P("dp"), out_specs=P("dp")))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x).T)


def test_send_forward_shifts(devices8):
    mesh = _mesh1d()
    x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)

    out = jax.jit(
        shard_map(
            lambda a: col.send_forward(a, "dp", 8),
            mesh=mesh,
            in_specs=P("dp"),
            out_specs=P("dp"),
        )
    )(x)
    expected = np.concatenate([[0.0], np.arange(7)]).reshape(8, 1)
    np.testing.assert_allclose(np.asarray(out), expected)


def test_comm_hook_records_ops(devices8):
    mesh = _mesh1d()
    records = []
    col.register_comm_hook(lambda op, axis, nbytes: records.append((op, axis, nbytes)))
    x = jnp.ones((8, 4), jnp.float32)
    jax.jit(
        shard_map(lambda a: col.all_reduce(a, "dp"), mesh=mesh, in_specs=P("dp"), out_specs=P("dp"))
    )(x)
    assert ("all_reduce", "dp", 16) in records  # 1x4 f32 per-shard view


def test_comm_module_api(devices8):
    topo = comm.init_distributed(dims=ParallelDims(tp=2))
    assert comm.get_world_size() == 8
    assert comm.get_world_size("tp") == 2
    assert comm.get_rank() == 0
    assert comm.is_initialized()
