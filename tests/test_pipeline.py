"""Pipeline parallelism (SURVEY §2.3): pipelined output == sequential
output; pipeline engine training parity vs the plain engine.

Model: DeepSpeed tests/unit/runtime/pipe/ (pipeline output equivalence).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from conftest import xfail_legacy_partial_manual
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.models.transformer import apply_layer_stack, make_lm_batch
from deepspeed_tpu.runtime.pipe import (
    LayerSpec,
    PipelineModule,
    pipelined_stack,
)
from deepspeed_tpu.runtime.pipe.module import (
    partition_balanced,
    partition_uniform,
)


def tiny_model(num_layers=4):
    return gpt2(
        "gpt2-tiny",
        vocab_size=128,
        max_seq_len=16,
        hidden_size=32,
        num_layers=num_layers,
        num_heads=2,
    )


def test_partition_helpers():
    assert partition_uniform(8, 4) == [0, 2, 4, 6, 8]
    assert partition_uniform(7, 3) == [0, 3, 5, 7]
    # balanced: heavy head layer gets its own part
    bounds = partition_balanced([10, 1, 1, 1, 1], 2)
    assert bounds[0] == 0 and bounds[-1] == 5
    assert bounds[1] == 1  # the 10-weight layer alone


@xfail_legacy_partial_manual
def test_pipelined_stack_matches_sequential():
    model = tiny_model(num_layers=4)
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0), dtype=jnp.float32)
    topo = MeshTopology(dims=ParallelDims(pp=4, dp=2))

    M, mb, S = 4, 2, 8
    r = np.random.RandomState(0)
    ids = jnp.asarray(r.randint(0, 128, size=(M, mb, S)))
    x = params["embed"]["tok"][ids]  # [M, mb, S, D]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (M, mb, S))

    # sequential reference: each microbatch through the full stack
    ref = []
    for m in range(M):
        y, _ = apply_layer_stack(
            cfg, params["layers"], x[m], positions[m], None, None, False, None
        )
        ref.append(y)
    ref = jnp.stack(ref)

    got, aux = jax.jit(
        lambda layers, xx, pp: pipelined_stack(
            cfg, layers, xx, pp, None, topo, False, None, None
        )
    )(params["layers"], x, positions)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)
    assert float(aux) == 0.0


@xfail_legacy_partial_manual
def test_pipelined_stack_grads_match_sequential():
    model = tiny_model(num_layers=2)
    cfg = model.config
    params = model.init(jax.random.PRNGKey(1), dtype=jnp.float32)
    topo = MeshTopology(dims=ParallelDims(pp=2, dp=4))
    M, mb, S = 2, 2, 8
    r = np.random.RandomState(1)
    ids = jnp.asarray(r.randint(0, 128, size=(M, mb, S)))
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (M, mb, S))

    def pipe_loss(layers):
        x = params["embed"]["tok"][ids]
        y, _ = pipelined_stack(cfg, layers, x, positions, None, topo, False, None, None)
        return jnp.sum(y**2)

    def seq_loss(layers):
        x = params["embed"]["tok"][ids]
        total = 0.0
        for m in range(M):
            y, _ = apply_layer_stack(cfg, layers, x[m], positions[m], None, None, False, None)
            total = total + jnp.sum(y**2)
        return total

    g_pipe = jax.jit(jax.grad(pipe_loss))(params["layers"])
    g_seq = jax.jit(jax.grad(seq_loss))(params["layers"])
    for a, b in zip(jax.tree_util.tree_leaves(g_pipe), jax.tree_util.tree_leaves(g_seq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)


@xfail_legacy_partial_manual
@pytest.mark.parametrize("tick_chunk", [2, 3])
def test_pipelined_stack_tick_chunk_exact(tick_chunk):
    """The 1f1b chunked-remat schedule (VERDICT r4 #6) is numerically the
    SAME program: outputs and grads match the unchunked scan bit-for-bit,
    including a chunk that doesn't divide the tick count."""
    model = tiny_model(num_layers=2)
    cfg = model.config
    params = model.init(jax.random.PRNGKey(2), dtype=jnp.float32)
    topo = MeshTopology(dims=ParallelDims(pp=2, dp=4))
    M, mb, S = 4, 2, 8
    r = np.random.RandomState(2)
    ids = jnp.asarray(r.randint(0, 128, size=(M, mb, S)))
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (M, mb, S))

    def loss(layers, chunk):
        x = params["embed"]["tok"][ids]
        y, _ = pipelined_stack(cfg, layers, x, positions, None, topo, True,
                               None, "full", tick_chunk=chunk)
        return jnp.sum(y.astype(jnp.float32) ** 2)

    v0, g0 = jax.jit(jax.value_and_grad(lambda l: loss(l, None)))(
        params["layers"])
    v1, g1 = jax.jit(jax.value_and_grad(lambda l: loss(l, tick_chunk)))(
        params["layers"])
    np.testing.assert_allclose(np.asarray(v0), np.asarray(v1), rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(g0),
                    jax.tree_util.tree_leaves(g1)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-5, atol=1e-6)


@xfail_legacy_partial_manual
def test_pipelined_stack_tick_chunk_bounds_stash_growth():
    """Memory contract of the 1f1b schedule: the per-microbatch growth of
    compiled temp memory (XLA's own accounting — where grad-of-scan stashes
    residuals) is strictly below the unchunked scan's (measured 2 boundary
    activations per tick: tools/pipe_memory.py, docs/pipe_memory.md)."""
    model = tiny_model(num_layers=2)
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0), dtype=jnp.float32)
    topo = MeshTopology(dims=ParallelDims(pp=2, dp=4))
    mb, S, D = 2, 16, 32

    def temp_bytes(M, chunk):
        r = np.random.RandomState(0)
        x = jnp.asarray(r.randn(M, mb, S, D), jnp.float32)
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32),
                                     (M, mb, S))

        def loss(layers):
            y, _ = pipelined_stack(cfg, layers, x, positions, None, topo,
                                   True, None, "full", tick_chunk=chunk)
            return jnp.sum(y.astype(jnp.float32) ** 2)

        c = jax.jit(jax.grad(loss)).lower(params["layers"]).compile()
        return int(c.memory_analysis().temp_size_in_bytes)

    grow_plain = temp_bytes(24, None) - temp_bytes(8, None)
    grow_chunk = temp_bytes(24, 5) - temp_bytes(8, 3)
    assert grow_chunk < grow_plain, (grow_chunk, grow_plain)


def make_engines():
    """(pipeline pp=2 dp=2, dense dp=2) engines with identical init seeds."""
    base_cfg = {
        "train_batch_size": 8,
        "gradient_accumulation_steps": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "gradient_clipping": 1.0,
        "steps_per_print": 100,
    }
    dense, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config=dict(base_cfg),
        topology=MeshTopology(dims=ParallelDims(dp=2), devices=jax.devices()[:2]),
        rng=jax.random.PRNGKey(3),
    )
    pipe_cfg = dict(base_cfg)
    pipe_cfg["pipeline"] = {"stages": 2}
    piped, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config=pipe_cfg,
        topology=MeshTopology(
            dims=ParallelDims(pp=2, dp=2), devices=jax.devices()[:4]
        ),
        rng=jax.random.PRNGKey(3),
    )
    return piped, dense


_OLD_JAX = tuple(map(int, jax.__version__.split(".")[:2])) < (0, 5)


@pytest.mark.skipif(
    _OLD_JAX,
    reason="jaxlib 0.4.x's CPU compiler hard-aborts (SIGABRT, no Python "
    "error) on the compiled pipeline schedule, killing the whole pytest "
    "process and every test after it",
)
def test_pipeline_engine_parity_with_dense():
    piped, dense = make_engines()
    from deepspeed_tpu.runtime.pipe.engine import PipelineEngine

    assert isinstance(piped, PipelineEngine)
    r = np.random.RandomState(0)
    for i in range(3):
        batch = {"input_ids": r.randint(0, 128, size=(8, 16))}
        if i == 1:
            # ragged padding: per-microbatch CE normalization must match the
            # dense engine's mean-over-microbatches semantics
            labels = np.asarray(
                make_lm_batch(jnp.asarray(batch["input_ids"]))["labels"]
            ).copy()
            labels[:3, 5:] = -100
            batch["labels"] = labels
        lp = float(piped.train_batch(batch=dict(batch)))
        ld = float(dense.train_batch(batch=dict(batch)))
        assert abs(lp - ld) < 2e-3, f"step {i}: pipeline {lp} vs dense {ld}"
    # params stay in lockstep after 3 optimizer steps
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(piped.state.params)),
        jax.tree_util.tree_leaves(jax.device_get(dense.state.params)),
    ):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=5e-4)


@xfail_legacy_partial_manual
def test_pipelined_stack_segment_ids():
    """Packed sequences: segment mask must ride the pipeline with its mb."""
    model = tiny_model(num_layers=2)
    cfg = model.config
    params = model.init(jax.random.PRNGKey(2), dtype=jnp.float32)
    topo = MeshTopology(dims=ParallelDims(pp=2, dp=4))
    M, mb, S = 2, 2, 8
    r = np.random.RandomState(2)
    ids = jnp.asarray(r.randint(0, 128, size=(M, mb, S)))
    x = params["embed"]["tok"][ids]
    positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (M, mb, S))
    seg = jnp.asarray(r.randint(0, 2, size=(M, mb, S)).cumsum(-1))

    ref = jnp.stack([
        apply_layer_stack(cfg, params["layers"], x[m], positions[m], seg[m],
                          None, False, None)[0]
        for m in range(M)
    ])
    got, _ = jax.jit(
        lambda layers, xx, pp, ss: pipelined_stack(
            cfg, layers, xx, pp, ss, topo, False, None, None
        )
    )(params["layers"], x, positions, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_pipeline_module_api():
    model = tiny_model()
    pm = PipelineModule(model=model, num_stages=2)
    assert pm.stage_owner(0) == 0 and pm.stage_owner(3) == 1
    topo = MeshTopology(dims=ParallelDims(pp=2, dp=4))
    specs = pm.partition_specs(topo)
    # stacked layer dim 0 picks up the pp axis
    assert specs["layers"]["attn"]["wq"][0] == "pp"
    assert "pp" not in (specs["embed"]["tok"][0] or ())

    with pytest.raises(ValueError):
        PipelineModule(model=tiny_model(3), num_stages=2)

    ls = LayerSpec(tiny_model, 4)
    pm2 = PipelineModule(layers=[ls], num_stages=2)
    assert pm2.config.num_layers == 4


def test_zero2_plus_pipeline_rejected():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig(
            {
                "train_batch_size": 8,
                "zero_optimization": {"stage": 2},
                "pipeline": {"stages": 2},
            }
        )


@xfail_legacy_partial_manual
def test_pipeline_with_flash_kernel(devices8):
    """The flash kernel nests inside the pipeline's manual shard_map (r3:
    previously crashed with a mesh mismatch on real-TPU default config)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import MeshTopology, ParallelDims
    from deepspeed_tpu.models import llama

    def run(flash):
        comm.destroy_process_group()
        topo = MeshTopology(ParallelDims(dp=2, pp=2, tp=2), devices=jax.devices())
        comm.set_topology(topo)
        model = llama(
            "llama-tiny", vocab_size=512, max_seq_len=128, hidden_size=64,
            num_layers=4, num_heads=4, num_kv_heads=4, intermediate_size=176,
        )
        engine, *_ = deepspeed_tpu.initialize(
            model=model, topology=topo,
            config={
                "train_batch_size": 8,
                "train_micro_batch_size_per_gpu": 2,
                "gradient_accumulation_steps": 2,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "pipeline": {"stages": 2},
                "tpu_kernels": {"flash_attention": flash},
            },
            rng=jax.random.PRNGKey(0),
        )
        data = {
            "input_ids": np.random.RandomState(0).randint(0, 512, size=(8, 128))
        }
        return float(engine.train_batch(batch=data))

    l_flash = run(True)
    l_xla = run(False)
    assert abs(l_flash - l_xla) < 2e-3, (l_flash, l_xla)
