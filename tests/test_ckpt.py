"""runtime/ckpt (ISSUE 20): async snapshot pipeline, committed-manifest
atomicity, resharding-on-restore, preemption plumbing.

The contract under test, per docs/checkpointing.md:

- an ``async_save=True`` checkpoint is byte-identical to its sync twin
  and the fence never perturbs the step (step_traces unchanged);
- ``metadata.json`` is the commit record — a torn tag (shards present,
  manifest missing) is refused LOUDLY on explicit load and is invisible
  to latest-tag resolution;
- restoring onto a different ParallelDims/MeshTopology/ZeRO stage
  reassembles every leaf from overlapping source byte-ranges, and the
  resumed loss trajectory is BITWISE identical to an uninterrupted run
  (the cross-process version of the same oracle is ci.yml's
  ``preemption`` job via tools/elastic_run.py);
- SIGTERM commits a final sync save before the healthwatch postmortem
  chain exits.
"""

import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.checkpointing import list_checkpoints
from deepspeed_tpu.runtime.ckpt import (
    CheckpointGuard,
    UncommittedCheckpointError,
    is_committed,
    latest_committed_tag,
    reset_preempt_handler,
)


def tiny_model():
    return gpt2(
        "gpt2-tiny", vocab_size=256, max_seq_len=16, hidden_size=32,
        num_layers=1, num_heads=2,
    )


def flat(dp, ndev=None):
    return MeshTopology(
        dims=ParallelDims(dp=dp), devices=jax.devices()[: ndev or dp]
    )


def hybrid8():
    """8-way dp with the dp axis riding DCN: same shard layout as flat
    dp=8, different MeshTopology/link-kinds — the probe-verified
    bitwise cross-mesh restore target."""
    return MeshTopology.hybrid(ParallelDims(dp=8), dcn_axes=("dp",))


def make_engine(zero_stage=3, topo=None, seed=0, ckpt=None, hw=False):
    comm.destroy_process_group()
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": zero_stage},
        "seed": seed,
    }
    if ckpt:
        cfg["checkpoint"] = ckpt
    if hw:
        cfg["healthwatch"] = {"enabled": True}
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_model(), config=cfg, topology=topo or flat(8)
    )
    return engine


def batch(seed=0):
    r = np.random.RandomState(seed)
    return {"input_ids": r.randint(0, 256, size=(8, 16))}


def trees_equal(a, b):
    oks = jax.tree_util.tree_leaves(
        jax.tree.map(lambda x, y: bool(jnp.array_equal(x, y)), a, b)
    )
    return all(oks)


# --------------------------------------------------------- async writer
def test_async_save_exact_and_fence_is_invisible(tmp_path):
    """The async snapshot must capture the state of the step it fenced
    on — later training drift must not leak into the background write —
    and neither the save nor the fence may retrace the step."""
    engine = make_engine(ckpt={"async_save": True})
    engine.train_batch(batch=batch(1))
    engine.train_batch(batch=batch(2))
    want_params = jax.device_get(engine.state.params)
    want_opt = jax.device_get(engine.state.opt_state)
    traces = engine.step_traces

    engine.save_checkpoint(str(tmp_path))
    engine.train_batch(batch=batch(3))  # drift while the writer runs
    assert engine.step_traces == traces, "async save retraced the step"
    engine.destroy()  # drains the writer

    fresh = make_engine(seed=99)
    assert not trees_equal(want_params, fresh.state.params)
    fresh.load_checkpoint(str(tmp_path))
    assert fresh.global_steps == 2
    assert trees_equal(want_params, fresh.state.params)
    assert trees_equal(want_opt, fresh.state.opt_state)
    fresh.destroy()


def test_guard_surfaces_writer_exception_on_fence():
    """A failed background write must not be silent: the NEXT fence on
    the main thread re-raises it (and the failed tag never committed)."""
    guard = CheckpointGuard()

    def boom():
        raise OSError("disk full")

    guard.launch(boom)
    with pytest.raises(RuntimeError, match="did NOT commit"):
        guard.fence()
    guard.fence()  # the exception is consumed; the guard is reusable


def test_torn_save_refused_loudly(tmp_path):
    """Shards on disk without metadata.json = a torn save. Explicit-tag
    load must raise; latest-tag resolution must not see it."""
    engine = make_engine()
    engine.train_batch(batch=batch(1))
    engine.save_checkpoint(str(tmp_path), tag="t1")
    assert is_committed(str(tmp_path), "t1")
    os.remove(os.path.join(str(tmp_path), "t1", "metadata.json"))
    assert not is_committed(str(tmp_path), "t1")
    assert latest_committed_tag(str(tmp_path)) is None

    fresh = make_engine(seed=99)
    with pytest.raises(UncommittedCheckpointError):
        fresh.load_checkpoint(str(tmp_path), tag="t1")
    # tag=None keeps the current state instead of loading torn bytes
    path, client = fresh.load_checkpoint(str(tmp_path))
    assert path is None and client == {}
    fresh.destroy()
    engine.destroy()


def test_keep_last_prunes_committed_tags(tmp_path):
    engine = make_engine(ckpt={"keep_last": 2})
    for i in range(3):
        engine.train_batch(batch=batch(i))
        engine.save_checkpoint(str(tmp_path))
    assert list_checkpoints(str(tmp_path)) == ["global_step2", "global_step3"]
    assert latest_committed_tag(str(tmp_path)) == "global_step3"
    engine.destroy()


# ------------------------------------------------- resharding-on-restore
@pytest.fixture(scope="module")
def src_run(tmp_path_factory):
    """One stage-3 dp=8 source run shared by the resharding tests:
    train 2, save, then keep training — the SAME engine's continued
    losses ARE the uninterrupted reference trajectory (a save mutates
    nothing), so every restore leg below compares against it."""
    d = str(tmp_path_factory.mktemp("src_ckpt"))
    engine = make_engine(3)
    for i in range(2):
        engine.train_batch(batch=batch(100 + i))
    engine.save_checkpoint(d)
    params_at_save = jax.device_get(engine.state.params)
    ref = [float(engine.train_batch(batch=batch(100 + i))) for i in (2, 3)]
    engine.destroy()
    return d, params_at_save, ref


@pytest.mark.parametrize(
    "dst_stage,dst_topo",
    [
        pytest.param(3, hybrid8, id="dp8flat-to-dcn-hybrid"),
        pytest.param(1, lambda: flat(8), id="stage3-to-stage1"),
    ],
)
def test_resume_bitwise_across_mesh_and_stage(src_run, dst_stage, dst_topo):
    """Restore the stage-3 save onto a DIFFERENT topology/stage and
    continue: the trajectory must match the uninterrupted run bitwise —
    resharding is exact, not approximately-right."""
    d, _, ref = src_run
    dst = make_engine(dst_stage, topo=dst_topo(), seed=99)
    dst.load_checkpoint(d)
    got = [float(dst.train_batch(batch=batch(100 + i))) for i in (2, 3)]
    dst.destroy()
    assert got == ref, f"resumed trajectory diverged: {got} vs {ref}"


def test_restore_onto_fsdp_hybrid_layout_exact(src_run):
    """dp=8 flat -> dp=2(DCN)xfsdp=4(ICI): a genuinely different shard
    layout (fsdp partitions params). The restored logical state must be
    exact and the engine must still train."""
    d, params_at_save, _ = src_run
    dst = make_engine(
        3, topo=MeshTopology.hybrid(ParallelDims(dp=2, fsdp=4)), seed=99
    )
    dst.load_checkpoint(d)
    assert trees_equal(params_at_save, dst.state.params)
    dst.train_batch(batch=batch(8))
    dst.destroy()


def test_restore_onto_fewer_devices_exact_state(tmp_path):
    """dp=4 over 4 devices -> dp=2 over 2: each destination shard reads
    two source shards' byte-ranges. The restored STATE is exact; the
    continued trajectory is only ulp-close, not bitwise — shrinking the
    world changes the loss all-reduce tree, so float summation order
    legitimately differs. (The elastic oracle keeps the global device
    count constant across rounds for exactly this reason.)"""
    src = make_engine(2, topo=flat(4))
    src.train_batch(batch=batch(200))
    src.save_checkpoint(str(tmp_path))
    save_params = jax.device_get(src.state.params)
    ref = [float(src.train_batch(batch=batch(200 + i))) for i in (1, 2)]
    src.destroy()

    dst = make_engine(2, topo=flat(2), seed=99)
    dst.load_checkpoint(str(tmp_path))
    assert trees_equal(save_params, dst.state.params)
    got = [float(dst.train_batch(batch=batch(200 + i))) for i in (1, 2)]
    dst.destroy()
    np.testing.assert_allclose(got, ref, rtol=1e-5)


# --------------------------------------------- preemption + observability
def test_sigterm_commits_final_save(tmp_path):
    """The chained SIGTERM handler must commit a sync save before the
    exit: resume lands on the exact preempted step."""
    reset_preempt_handler()
    old = signal.getsignal(signal.SIGTERM)
    try:
        engine = make_engine(
            ckpt={"save_interval_steps": 100, "on_preempt": "save"}
        )
        engine.train_batch(batch=batch(1))
        engine.save_checkpoint(str(tmp_path), tag="boot")  # installs hook
        engine.train_batch(batch=batch(2))  # drift past the boot save
        handler = signal.getsignal(signal.SIGTERM)
        assert callable(handler) and handler is not old
        with pytest.raises(SystemExit) as e:
            handler(signal.SIGTERM, None)
        assert e.value.code == 128 + signal.SIGTERM
        assert latest_committed_tag(str(tmp_path)) == "global_step2"
        engine.destroy()
    finally:
        signal.signal(signal.SIGTERM, old)
        reset_preempt_handler()


def test_analytic_ckpt_snapshot_stream_amortized():
    """save_interval_steps declares the cadence; the planner stream
    prices snapshot bytes amortized over it and tags the checkpoint
    goodput bucket so healthwatch won't double-count it as comm."""
    engine = make_engine(
        ckpt={"async_save": True, "save_interval_steps": 4}
    )
    stream = engine.analytic_streams()["ckpt_snapshot"]
    assert stream["kind"] == "offload"
    assert stream["overlapped"] is True
    assert stream["goodput_bucket"] == "checkpoint"
    assert stream["interval_steps"] == 4
    assert stream["snapshot_bytes"] > 0
    assert stream["bytes_per_step"] == pytest.approx(
        stream["snapshot_bytes"] / 4
    )
    engine.destroy()

    off = make_engine()
    assert "ckpt_snapshot" not in off.analytic_streams()
    off.destroy()


def test_goodput_charges_fence_and_reports_writer_seconds(tmp_path):
    """The checkpoint goodput bucket charges only the in-step fence;
    the background writer's seconds surface separately as ckpt_write_s
    (and the checkpoint_stall rule is armed by default)."""
    from deepspeed_tpu.profiling.healthwatch import DEFAULT_RULES

    assert "checkpoint_stall" in DEFAULT_RULES
    engine = make_engine(
        ckpt={"async_save": True, "save_interval_steps": 2}, hw=True
    )
    engine.train_batch(batch=batch(1))
    engine.save_checkpoint(str(tmp_path))
    engine.train_batch(batch=batch(2))
    engine._ckpt_guard().fence()
    g = engine.healthwatch.goodput()
    assert g["ckpt_write_s"] > 0.0
    assert "checkpoint" in g["buckets"]
    engine.destroy()
