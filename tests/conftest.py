"""Test harness: force a virtual 8-device CPU mesh before jax initialises.

Mirrors the reference's unit-test strategy (tests/unit) of running
world_size>1 logic on a single box — here via XLA host-platform devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The container's sitecustomize imports jax with JAX_PLATFORMS=axon before
# conftest runs, so the env var alone is too late — force the config flag.
# Older jax builds lack some options (jax_num_cpu_devices landed after
# 0.4.x); there the XLA_FLAGS host-device-count path above already covers
# the 8-device mesh, so a missing option must not kill collection.
for _opt, _val in (
    ("jax_platforms", "cpu"),
    ("jax_num_cpu_devices", 8),
    ("jax_threefry_partitionable", True),
):
    try:
        jax.config.update(_opt, _val)
    except AttributeError:
        pass

import json  # noqa: E402

import pytest  # noqa: E402

# ---- legacy-jax tier-1 guards ----------------------------------------------
# Pre-existing failure classes on old 0.4.x images (NOT regressions —
# they pass on CI's jax >= 0.5): partial-manual shard_map legs refuse
# with NotImplementedError (utils/jax_compat.py), and the multiprocess
# workers set the jax_num_cpu_devices option that landed after 0.4.x.
# xfail(strict=False) keeps the tier-1 signal clean on legacy images
# without hiding anything on modern jax (there the condition is False).
LEGACY_JAX_PARTIAL_MANUAL = getattr(jax, "shard_map", None) is None
LEGACY_JAX_NO_NUM_CPU_DEVICES = not hasattr(jax.config,
                                            "jax_num_cpu_devices")

xfail_legacy_partial_manual = pytest.mark.xfail(
    LEGACY_JAX_PARTIAL_MANUAL,
    reason="legacy jax 0.4.x: partial-manual shard_map is refused "
           "(utils/jax_compat.py NotImplementedError; pre-existing, "
           "passes on jax >= 0.5)",
    raises=NotImplementedError,
    strict=False,
)
xfail_legacy_num_cpu_devices = pytest.mark.xfail(
    LEGACY_JAX_NO_NUM_CPU_DEVICES,
    reason="legacy jax 0.4.x: spawned workers set jax_num_cpu_devices, "
           "which landed after 0.4.x (pre-existing; passes on CI)",
    strict=False,
)

# ---- shardlint suite capture -----------------------------------------------
# Every engine the test suite constructs registers its (config, model) here
# (deduped); tests/test_shardlint_suite.py re-builds each as an abstract
# engine and lints it — "lint every engine config already constructed by
# the test suite" without re-running any real compute.
SHARDLINT_CAPTURE = []  # [(config_json, model, topology)]
_SHARDLINT_SEEN = set()


def _install_shardlint_capture():
    from deepspeed_tpu.runtime import engine as _engine_mod

    orig = _engine_mod.TpuEngine.__init__

    def spy(self, model, config, topology, **kw):
        out = orig(self, model=model, config=config, topology=topology, **kw)
        # record only AFTER a successful construction: configs that tests
        # build to be rejected mid-__init__ must not poison the registry
        if not kw.get("abstract_init"):
            try:
                key = (
                    json.dumps(config.raw, sort_keys=True, default=str),
                    str(getattr(model, "config", None)),
                    str(topology),
                )
                if key not in _SHARDLINT_SEEN:
                    _SHARDLINT_SEEN.add(key)
                    SHARDLINT_CAPTURE.append((config.raw, model, topology))
            except Exception:  # noqa: BLE001 — capture must never break a test
                pass
        return out

    _engine_mod.TpuEngine.__init__ = spy


_install_shardlint_capture()


@pytest.fixture(autouse=True)
def _reset_comm_state():
    yield
    import deepspeed_tpu.comm as comm

    comm.destroy_process_group()
    comm.collectives.clear_comm_hooks()


@pytest.fixture
def devices8():
    ds = jax.devices()
    assert len(ds) == 8, f"expected 8 virtual devices, got {len(ds)}"
    return ds
