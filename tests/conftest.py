"""Test harness: force a virtual 8-device CPU mesh before jax initialises.

Mirrors the reference's unit-test strategy (tests/unit) of running
world_size>1 logic on a single box — here via XLA host-platform devices.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = flags + " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

# The container's sitecustomize imports jax with JAX_PLATFORMS=axon before
# conftest runs, so the env var alone is too late — force the config flag.
# Older jax builds lack some options (jax_num_cpu_devices landed after
# 0.4.x); there the XLA_FLAGS host-device-count path above already covers
# the 8-device mesh, so a missing option must not kill collection.
for _opt, _val in (
    ("jax_platforms", "cpu"),
    ("jax_num_cpu_devices", 8),
    ("jax_threefry_partitionable", True),
):
    try:
        jax.config.update(_opt, _val)
    except AttributeError:
        pass

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _reset_comm_state():
    yield
    import deepspeed_tpu.comm as comm

    comm.destroy_process_group()
    comm.collectives.clear_comm_hooks()


@pytest.fixture
def devices8():
    ds = jax.devices()
    assert len(ds) == 8, f"expected 8 virtual devices, got {len(ds)}"
    return ds
