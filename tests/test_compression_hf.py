"""Compression transforms + HF Transformers bridge (SURVEY §2.7, §2.8).

HF parity oracle: logits from our imported params match the torch model's
logits on the same tokens (fp32, CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.compression import (
    apply_layer_reduction,
    head_pruning_mask,
    init_compression,
    row_pruning_mask,
    sparse_pruning_mask,
)
from deepspeed_tpu.config import CompressionConfig
from deepspeed_tpu.integrations.hf import import_hf_model
from deepspeed_tpu.models import gpt2


# ------------------------------------------------------------- compression
def test_sparse_pruning_mask_density():
    r = np.random.RandomState(0)
    w = jnp.asarray(r.randn(32, 64), jnp.float32)
    m = sparse_pruning_mask(w, 0.25)
    assert abs(float(m.mean()) - 0.25) < 0.02
    # highest-magnitude entries survive
    assert float(jnp.abs(w * m).max()) == float(jnp.abs(w).max())


def test_head_and_row_pruning_masks():
    r = np.random.RandomState(1)
    wo = jnp.asarray(r.randn(8 * 16, 32), jnp.float32)
    m = head_pruning_mask(wo, num_heads=8, ratio=0.5)
    assert m.shape == (128, 1)
    per_head = np.asarray(m).reshape(8, 16)
    assert set(per_head.min(1)) <= {0.0, 1.0}
    assert per_head.min(1).sum() == 4  # half the heads kept

    wi = jnp.asarray(r.randn(32, 64), jnp.float32)
    rm = row_pruning_mask(wi, 0.25)
    assert rm.shape == (1, 64) and int(rm.sum()) == 16


def test_layer_reduction():
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=4, num_heads=2)
    params = model.init(jax.random.PRNGKey(0))
    reduced = apply_layer_reduction(params, [0, 3])
    assert reduced["layers"]["attn"]["wq"].shape[0] == 2
    np.testing.assert_array_equal(
        np.asarray(reduced["layers"]["attn"]["wq"][1]),
        np.asarray(params["layers"]["attn"]["wq"][3]),
    )


def test_init_compression_full_config():
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    params = model.init(jax.random.PRNGKey(0))
    cc = CompressionConfig(
        weight_quantization={
            "shared_parameters": {"enabled": True},
            "different_groups": {"g1": {"params": {"target_bits": 8}}},
        },
        sparse_pruning={
            "shared_parameters": {"enabled": True},
            "different_groups": {"g1": {"params": {"dense_ratio": 0.5}}},
        },
        head_pruning={
            "shared_parameters": {"enabled": True},
            "different_groups": {"g1": {"params": {"dense_ratio": 0.5}}},
        },
        row_pruning={
            "shared_parameters": {"enabled": True},
            "different_groups": {"g1": {"params": {"dense_ratio": 0.5}}},
        },
    )
    new_params, masks = init_compression(params, cc, model.config)
    assert "head" in masks and "row" in masks and "sparse" in masks
    # model still runs and produces finite loss
    from deepspeed_tpu.models.transformer import make_lm_batch

    batch = make_lm_batch(jnp.asarray(
        np.random.RandomState(0).randint(0, 64, size=(2, 8))))
    loss, _ = model.loss(new_params, batch, dtype=jnp.float32)
    assert np.isfinite(float(loss))


# ----------------------------------------------------------------- HF parity
def _logit_parity(hf_model, ids, atol=2e-3):
    import torch

    model, params = import_hf_model(hf_model)
    with torch.no_grad():
        ref = hf_model(torch.tensor(ids)).logits.float().numpy()
    got, _ = model.apply(
        jax.tree.map(jnp.asarray, params), jnp.asarray(ids), dtype=jnp.float32
    )
    np.testing.assert_allclose(np.asarray(got), ref, rtol=1e-3, atol=atol)


def test_hf_gpt2_parity():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    torch.manual_seed(0)
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2
    )).eval()
    ids = np.random.RandomState(0).randint(0, 128, size=(2, 8))
    _logit_parity(hf, ids)


def test_hf_llama_parity():
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(1)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rms_norm_eps=1e-5,
    )).eval()
    ids = np.random.RandomState(1).randint(0, 128, size=(2, 8))
    _logit_parity(hf, ids)


def test_hf_bloom_parity():
    import torch
    from transformers import BloomConfig, BloomForCausalLM

    torch.manual_seed(2)
    hf = BloomForCausalLM(BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
        layer_norm_epsilon=1e-5,
    )).eval()
    ids = np.random.RandomState(2).randint(0, 128, size=(2, 8))
    _logit_parity(hf, ids, atol=5e-3)


def test_hf_mixtral_import_runs():
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(3)
    hf = MixtralForCausalLM(MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=32,
    )).eval()
    model, params = import_hf_model(hf)
    ids = np.random.RandomState(3).randint(0, 128, size=(2, 8))
    logits, _ = model.apply(
        jax.tree.map(jnp.asarray, params), jnp.asarray(ids), dtype=jnp.float32
    )
    assert np.isfinite(np.asarray(logits)).all()


def test_hf_engine_adapter_trains():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    from deepspeed_tpu.integrations.hf import HfEngineAdapter
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    torch.manual_seed(4)
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2
    ))
    adapter = HfEngineAdapter(
        hf,
        {"train_batch_size": 8,
         "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
         "zero_optimization": {"stage": 1}, "steps_per_print": 100},
        topology=MeshTopology(dims=ParallelDims(dp=8)),
    )
    loss = adapter.train_batch(
        batch={"input_ids": np.random.RandomState(4).randint(0, 128, size=(8, 16))}
    )
    assert np.isfinite(float(loss))


def test_engine_compression_hook(devices8):
    """Enabling compression_training in the engine config applies masks at
    init, keeps them enforced after optimizer steps, and runs QAT in the
    forward (ADVICE r1: previously a silent no-op)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm

    comm.destroy_process_group()
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    cfg = {
        "train_batch_size": 8,
        "train_micro_batch_size_per_gpu": 1,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "compression_training": {
            "sparse_pruning": {
                "shared_parameters": {"enabled": True},
                "different_groups": {"g1": {"params": {"dense_ratio": 0.5}}},
            },
            "weight_quantization": {
                "shared_parameters": {"enabled": True},
                "different_groups": {"g1": {"params": {"target_bits": 8}}},
            },
        },
    }
    engine, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
    assert engine.compression_masks and "sparse" in engine.compression_masks
    assert engine._qat == (8, 128)
    batch = {"input_ids": np.random.RandomState(0).randint(0, 64, size=(8, 16))}
    for _ in range(3):
        loss = engine.train_batch(batch=batch)
    assert np.isfinite(float(loss))
    # pruned positions stay exactly zero after optimizer updates
    def check(wleaf, m):
        if m is None:
            return wleaf
        gone = np.asarray(wleaf)[np.asarray(m) == 0]
        assert gone.size > 0 and np.all(gone == 0.0)
        return wleaf

    jax.tree.map(
        check,
        engine.state.params["layers"]["mlp"],
        engine.compression_masks["sparse"],
        is_leaf=lambda x: x is None or hasattr(x, "ndim"),
    )


def test_engine_rejects_layer_reduction():
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.config import DeepSpeedConfigError

    comm.destroy_process_group()
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                 num_layers=2, num_heads=2)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        "compression_training": {
            "layer_reduction": {"enabled": True, "keep_number": 1},
        },
    }
    with pytest.raises(DeepSpeedConfigError, match="layer_reduction"):
        deepspeed_tpu.initialize(model=model, config=cfg)


def test_safetensors_roundtrip_and_hf_checkpoint_load(tmp_path):
    """Dependency-free safetensors I/O: write → read bitwise equal, BF16
    decode, and load_hf_checkpoint drives import_hf_state_dict from files
    (sharded index layout)."""
    import json
    import os
    import struct

    from deepspeed_tpu.integrations.hf import (
        load_hf_checkpoint,
        read_safetensors,
        write_safetensors,
    )

    tensors = {
        "a": np.random.RandomState(0).randn(3, 4).astype(np.float32),
        "b": np.arange(6, dtype=np.int64).reshape(2, 3),
    }
    p = str(tmp_path / "t.safetensors")
    write_safetensors(p, tensors)
    back = read_safetensors(p)
    for k in tensors:
        np.testing.assert_array_equal(back[k], tensors[k])

    # BF16 decoding: hand-craft a file with one bf16 tensor
    vals = np.asarray([1.0, -2.5, 3.25], np.float32)
    bf16 = (vals.view(np.uint32) >> 16).astype(np.uint16)
    header = {
        "x": {"dtype": "BF16", "shape": [3], "data_offsets": [0, 6]}
    }
    hj = json.dumps(header).encode()
    with open(tmp_path / "bf16.safetensors", "wb") as f:
        f.write(struct.pack("<Q", len(hj)))
        f.write(hj)
        f.write(bf16.tobytes())
    x = read_safetensors(str(tmp_path / "bf16.safetensors"))["x"]
    np.testing.assert_array_equal(x, vals)

    # full checkpoint-from-files path: export a tiny HF llama's state dict
    # into two shards + index, load without torch in the loop
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(3)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32,
    )).eval()
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    keys = sorted(sd)
    half = len(keys) // 2
    ckdir = tmp_path / "ckpt"
    os.makedirs(ckdir)
    write_safetensors(str(ckdir / "model-00001.safetensors"),
                      {k: sd[k] for k in keys[:half]})
    write_safetensors(str(ckdir / "model-00002.safetensors"),
                      {k: sd[k] for k in keys[half:]})
    index = {"weight_map": {
        **{k: "model-00001.safetensors" for k in keys[:half]},
        **{k: "model-00002.safetensors" for k in keys[half:]},
    }}
    with open(ckdir / "model.safetensors.index.json", "w") as f:
        json.dump(index, f)

    from deepspeed_tpu.integrations.hf import config_from_hf
    from deepspeed_tpu.models.transformer import TransformerModel

    cfg = config_from_hf(hf.config)
    params = load_hf_checkpoint(str(ckdir), cfg)
    model = TransformerModel(cfg)
    ids = np.random.RandomState(2).randint(0, 64, size=(1, 8))
    ours, _ = model.apply(params, jnp.asarray(ids), dtype=jnp.float32)
    with torch.no_grad():
        theirs = hf(torch.asarray(ids)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-3)


def test_hf_export_roundtrip_llama():
    """export_hf_state_dict inverts import: HF -> pytree -> HF -> pytree is
    the identity, and the exported dict loads into a fresh HF model with
    matching logits."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from deepspeed_tpu.integrations.hf import (
        config_from_hf,
        export_hf_state_dict,
        import_hf_state_dict,
    )

    torch.manual_seed(2)
    hf = LlamaForCausalLM(LlamaConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rms_norm_eps=1e-5,
    )).eval()
    cfg = config_from_hf(hf.config)
    params = import_hf_state_dict(hf.state_dict(), cfg, family="llama")
    exported = export_hf_state_dict(params, cfg, family="llama")
    params2 = import_hf_state_dict(exported, cfg, family="llama")
    la = jax.tree_util.tree_leaves_with_path(params)
    lb = dict(
        (jax.tree_util.keystr(k), v)
        for k, v in jax.tree_util.tree_leaves_with_path(params2)
    )
    assert len(la) == len(lb)
    for k, a in la:
        np.testing.assert_array_equal(
            np.asarray(a), np.asarray(lb[jax.tree_util.keystr(k)])
        )

    # exported dict loads into a fresh HF model: logits identical
    hf2 = LlamaForCausalLM(hf.config).eval()
    missing, unexpected = hf2.load_state_dict(
        {k: torch.from_numpy(np.array(v)) for k, v in exported.items()},
        strict=False,
    )
    assert not unexpected, unexpected
    ids = torch.from_numpy(np.random.RandomState(2).randint(0, 128, size=(1, 8)))
    with torch.no_grad():
        l1 = hf(ids).logits.numpy()
        l2 = hf2(ids).logits.numpy()
    np.testing.assert_allclose(l2, l1, atol=1e-5)


def test_hf_export_roundtrip_gpt2():
    import torch
    from transformers import GPT2Config, GPT2LMHeadModel

    from deepspeed_tpu.integrations.hf import (
        config_from_hf,
        export_hf_state_dict,
        import_hf_state_dict,
    )

    torch.manual_seed(3)
    hf = GPT2LMHeadModel(GPT2Config(
        vocab_size=128, n_positions=32, n_embd=32, n_layer=2, n_head=2
    )).eval()
    cfg = config_from_hf(hf.config)
    params = import_hf_state_dict(hf.state_dict(), cfg, family="gpt2")
    exported = export_hf_state_dict(params, cfg, family="gpt2")
    params2 = import_hf_state_dict(exported, cfg, family="gpt2")
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # exported dict actually loads into a fresh HF model (keys carry the
    # transformer. wrapper prefix): logits identical
    hf2 = GPT2LMHeadModel(hf.config).eval()
    missing, unexpected = hf2.load_state_dict(
        {k: torch.from_numpy(np.array(v)) for k, v in exported.items()},
        strict=False,
    )
    assert not unexpected, unexpected
    ids = torch.from_numpy(np.random.RandomState(3).randint(0, 128, size=(1, 8)))
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(), atol=1e-5
        )


def test_hf_export_roundtrip_bloom():
    """bloom's fused [H, 3, hd, d] qkv interleave must re-fuse exactly."""
    import torch
    from transformers import BloomConfig, BloomForCausalLM

    from deepspeed_tpu.integrations.hf import (
        config_from_hf,
        export_hf_state_dict,
        import_hf_state_dict,
    )

    torch.manual_seed(4)
    hf = BloomForCausalLM(BloomConfig(
        vocab_size=128, hidden_size=32, n_layer=2, n_head=4,
    )).eval()
    cfg = config_from_hf(hf.config)
    params = import_hf_state_dict(hf.state_dict(), cfg, family="bloom")
    exported = export_hf_state_dict(params, cfg, family="bloom")
    params2 = import_hf_state_dict(exported, cfg, family="bloom")
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the exported fused qkv matches the original torch tensor bit-for-bit
    orig = hf.state_dict()["transformer.h.0.self_attention.query_key_value.weight"]
    np.testing.assert_array_equal(
        exported["transformer.h.0.self_attention.query_key_value.weight"],
        orig.numpy(),
    )

    # exported dict loads into a fresh BloomForCausalLM: logits identical
    hf2 = BloomForCausalLM(hf.config).eval()
    missing, unexpected = hf2.load_state_dict(
        {k: torch.from_numpy(np.array(v)) for k, v in exported.items()},
        strict=False,
    )
    assert not unexpected, unexpected
    ids = torch.from_numpy(np.random.RandomState(4).randint(0, 128, size=(1, 8)))
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(), atol=1e-5
        )


def test_hf_export_roundtrip_mixtral():
    """mixtral: per-expert w1/w2/w3 unstack + router, loads into a fresh
    MixtralForCausalLM with identical logits."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    from deepspeed_tpu.integrations.hf import (
        config_from_hf,
        export_hf_state_dict,
        import_hf_state_dict,
    )

    torch.manual_seed(5)
    hf = MixtralForCausalLM(MixtralConfig(
        vocab_size=128, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2,
        max_position_embeddings=32,
    )).eval()
    cfg = config_from_hf(hf.config)
    params = import_hf_state_dict(hf.state_dict(), cfg, family="mixtral")
    exported = export_hf_state_dict(params, cfg, family="mixtral")
    params2 = import_hf_state_dict(exported, cfg, family="mixtral")
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    hf2 = MixtralForCausalLM(hf.config).eval()
    missing, unexpected = hf2.load_state_dict(
        {k: torch.from_numpy(np.array(v)) for k, v in exported.items()},
        strict=False,
    )
    assert not unexpected, unexpected
    ids = torch.from_numpy(np.random.RandomState(5).randint(0, 128, size=(1, 8)))
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(), atol=1e-5
        )
