"""MoE-native serving: expert-parallel decode inside the ONE slot step
(ISSUE 14).

The oracle: an ep-sharded ServingEngine replays token-for-token BITWISE
equal to a dense-replicated engine of the same params across ragged
arrival/occupancy sweeps — greedy, sampled-with-shared-keys, paged,
spec-on and int8-expert mixes — with ``step_traces == 1`` on both sides,
for BOTH exchange forms (stock collectives and the decode-shaped
chunked-ppermute ring). Plus the null-expert gating contract, the static
capacity rule, the load-balance metrics, the serving moe-a2a planner
axis and the MoE serving lint example.

Heavy CPU-mesh legs are marked ``slow`` (out of the 1-core tier-1
budget) and everything here carries ``-m moe_serve``.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import mixtral
from deepspeed_tpu.serving import Request, ServingEngine, ServingMetrics

pytestmark = pytest.mark.moe_serve

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_mixtral(**kw):
    d = dict(vocab_size=64, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64,
             num_experts=4, moe_top_k=2)
    d.update(kw)
    return mixtral("mixtral-tiny", **d)


def _engine(ep=1, model=None, **kw):
    topo = None
    if ep > 1:
        topo = MeshTopology(
            dims=ParallelDims(ep=ep), devices=jax.devices()[:ep]
        )
    return deepspeed_tpu.init_inference(
        model or tiny_mixtral(), dtype=jnp.float32, max_tokens=64,
        topology=topo, rng=jax.random.PRNGKey(1), **kw
    )


def _replay(srv, cases, prompts):
    """Staggered ragged replay; returns per-request token lists."""
    states = []
    states.append(srv.submit(Request(request_id="r0", prompt=prompts[0],
                                     **cases[0])))
    states.append(srv.submit(Request(request_id="r1", prompt=prompts[1],
                                     **cases[1])))
    srv.step()
    srv.step()
    for i in range(2, len(cases)):
        states.append(srv.submit(Request(
            request_id=f"r{i}", prompt=prompts[i], **cases[i]
        )))
        srv.step()
    srv.run_until_idle()
    assert srv.step_traces == 1, srv.step_traces
    return [list(s.tokens) for s in states]


CASES = [
    dict(max_new_tokens=6),
    dict(max_new_tokens=4, temperature=0.8, top_k=10),
    dict(max_new_tokens=8),
    dict(max_new_tokens=5, temperature=0.7, top_p=0.9),
]


def _prompts(seed=0, vocab=64):
    r = np.random.RandomState(seed)
    return [r.randint(0, vocab, size=(n,)) for n in (3, 12, 7, 5)]


# ---------------------------------------------------------------------------
# the tentpole oracle: ep-sharded slot decode == dense-replicated decode
# ---------------------------------------------------------------------------
@pytest.mark.slow
@pytest.mark.parametrize("form", ["stock", "chunked"])
def test_ep_parity_greedy_and_sampled(form, devices8):
    serving = {"max_slots": 3, "token_budget": 8, "max_tokens": 64,
               "moe_a2a": form}
    dense = _replay(ServingEngine(engine=_engine(ep=1), serving=serving),
                    CASES, _prompts())
    srv = ServingEngine(engine=_engine(ep=2), serving=serving)
    assert srv.moe_a2a_form == form
    ep = _replay(srv, CASES, _prompts())
    assert ep == dense
    # load-balance counters rode along, NaN-free
    snap = srv.metrics.snapshot()
    assert snap["moe_steps"] > 0
    assert snap["moe_routed_tokens"] > 0
    assert all(np.isfinite(v) for v in snap.values())


@pytest.mark.slow
def test_ep_parity_paged_spec_int8kv(devices8):
    """The full mix: block-paged arena + speculative decoding + int8 KV
    cache, ep-sharded vs dense-replicated, bitwise."""
    serving = {
        "max_slots": 3, "token_budget": 12, "max_tokens": 48,
        "paged": True, "page_size": 8, "kv_cache_dtype": "int8",
        "spec": {"enabled": True, "max_draft": 3},
    }
    # repetitive prompts so the n-gram drafts land acceptances
    r = np.random.RandomState(3)
    prompts = [np.tile(r.randint(0, 64, size=(3,)), 6)[:n]
               for n in (9, 14, 11, 8)]
    cases = [dict(max_new_tokens=n) for n in (8, 6, 7, 5)]
    dense = _replay(
        ServingEngine(engine=_engine(ep=1), serving=serving), cases, prompts
    )
    ep = _replay(
        ServingEngine(engine=_engine(ep=2), serving=serving), cases, prompts
    )
    assert ep == dense


@pytest.mark.slow
def test_ep_parity_int8_experts_stream(devices8):
    """Packed int8 expert banks stream through the per-shard Pallas
    matvec (the PR-3 tp treatment applied to experts) and reproduce the
    dense-replicated packed engine bitwise."""
    from deepspeed_tpu.ops.pallas import quantized_matmul as qm
    from deepspeed_tpu.ops.quantizer import PackedWeight

    # lanes must tile (f % 128 == 0) for the kernel; capacity (= W here)
    # must fit the matvec row threshold
    model_kw = dict(hidden_size=256, intermediate_size=512)
    serving = {"max_slots": 2, "token_budget": 8, "max_tokens": 32}
    cases = [dict(max_new_tokens=4), dict(max_new_tokens=3),
             dict(max_new_tokens=5), dict(max_new_tokens=2)]
    prompts = _prompts(seed=5)

    qm.reset_streaming_trace_counts()
    eng_d = _engine(ep=1, model=tiny_mixtral(**model_kw), quantize_bits=8)
    dense = _replay(ServingEngine(engine=eng_d, serving=serving),
                    cases, prompts)
    assert qm.streaming_trace_counts()["expert_single"] > 0

    qm.reset_streaming_trace_counts()
    eng_e = _engine(ep=2, model=tiny_mixtral(**model_kw), quantize_bits=8)
    packed4 = [
        l for l in jax.tree_util.tree_leaves(
            eng_e.params, is_leaf=lambda a: isinstance(a, PackedWeight))
        if isinstance(l, PackedWeight) and len(l.shape) == 4
    ]
    assert packed4, "expert banks must pack"
    ep = _replay(ServingEngine(engine=eng_e, serving=serving),
                 cases, prompts)
    assert qm.streaming_trace_counts()["expert_sharded"] > 0
    assert ep == dense


@pytest.mark.slow
def test_serving_matches_lockstep_generate(devices8):
    """With the no-drop capacity rule (cap_factor·k >= E) per-token
    routing is batch-independent, so the MoE slot engine reproduces
    single-request lockstep generate token-for-token — the same oracle
    the dense serving tests pin."""
    eng = _engine(ep=2)
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 3, "token_budget": 8, "max_tokens": 64,
    })
    prompts = _prompts(seed=7)
    states = [srv.submit(Request(request_id=f"g{i}", prompt=p,
                                 max_new_tokens=n))
              for i, (p, n) in enumerate(zip(prompts, (6, 4, 8, 5)))]
    srv.run_until_idle()
    for st, p, n in zip(states, prompts, (6, 4, 8, 5)):
        want = eng.generate(p[None, :], max_new_tokens=n, temperature=0.0)
        np.testing.assert_array_equal(st.output(), want[0])


# ---------------------------------------------------------------------------
# satellites (light — these stay in tier-1)
# ---------------------------------------------------------------------------
def test_gating_valid_mask_null_expert():
    """Invalid rows occupy no capacity, shift no positions and carry
    zero weight — and real rows route identically whatever the
    occupancy mix (the zero-recompile/no-drift contract)."""
    from deepspeed_tpu.moe.sharded_moe import top_k_gating_indices

    logits = np.asarray(
        jax.random.normal(jax.random.PRNGKey(0), (8, 4)), np.float32
    )
    full = top_k_gating_indices(jnp.asarray(logits), 2, 8, None, False)
    valid = jnp.ones((8,), bool).at[3].set(False).at[6].set(False)
    masked = top_k_gating_indices(jnp.asarray(logits), 2, 8, None, False,
                                  valid=valid)
    tof, sv, sot, w, metrics = masked
    # invalid rows: zero combine weight
    assert float(jnp.abs(w[3]).sum()) == 0.0
    assert float(jnp.abs(w[6]).sum()) == 0.0
    # capacity accounting excludes them
    assert int(metrics["routed_tokens"]) == 6 * 2
    assert int(metrics["tokens_per_expert"].sum()) == 6 * 2
    assert float(metrics["drop_fraction"]) == 0.0
    # real rows keep their expert choice and weights bitwise
    full_w = np.asarray(full[3])
    for r in (0, 1, 2, 4, 5, 7):
        np.testing.assert_array_equal(np.asarray(w[r]), full_w[r])


def test_gating_eval_accepts_rng_none_bitwise():
    """ISSUE 14 satellite: gating at eval never consumes a key — with
    and without an rng the outputs are bitwise equal, so serving's
    deterministic per-request RNG discipline is untouched."""
    from deepspeed_tpu.moe.sharded_moe import top_k_gating

    logits = jax.random.normal(jax.random.PRNGKey(2), (16, 4))
    with_key = top_k_gating(logits, 2, 8, rng=jax.random.PRNGKey(3),
                            train=False, noise_std=0.1)
    without = top_k_gating(logits, 2, 8, rng=None, train=False,
                           noise_std=0.1)
    for a, b in zip(with_key[:2], without[:2]):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_gating_eval_keyfree_certified_statically():
    """The R9(d) arm on the REAL PR-14 surface: eval gating claims
    key-free bitwiseness (the runtime twin above proves it bitwise);
    tracing it with a key handed in and linting under
    ``claims_keyfree=True`` certifies statically that NO key-consuming
    site exists on the path — and a gating variant that sneaks eval
    noise back in (split + sample) is flagged."""
    import jax.numpy as jnp

    from deepspeed_tpu.analysis import lint_jaxpr
    from deepspeed_tpu.moe.sharded_moe import top_k_gating

    logits = jax.ShapeDtypeStruct((16, 4), jnp.float32)
    key = jax.random.PRNGKey(0)

    closed = jax.make_jaxpr(
        lambda lg, k: top_k_gating(lg, 2, 8, rng=k, train=False,
                                   noise_std=0.1)
    )(logits, key)
    findings = lint_jaxpr(closed, source="gating-eval",
                          claims_keyfree=True)
    assert findings == [], [f.format() for f in findings]

    def noisy_eval_gating(lg, k):
        k, sub = jax.random.split(k)
        noisy = lg + jax.random.normal(sub, lg.shape) * 0.1
        return top_k_gating(noisy, 2, 8, rng=None, train=False)

    closed = jax.make_jaxpr(noisy_eval_gating)(logits, key)
    findings = lint_jaxpr(closed, source="gating-eval-noisy",
                          claims_keyfree=True)
    assert any(f.rule == "R9" and "key-free" in f.message
               for f in findings), [f.format() for f in findings]


def test_eval_capacity_static_rule():
    from deepspeed_tpu.moe.sharded_moe import eval_capacity

    cfg = tiny_mixtral().config
    # max(cap_factor, 2.0) * k * W / E, floored at 4
    assert eval_capacity(cfg, 16) == 16  # 2.0 * 2 * 16 / 4
    assert eval_capacity(cfg, 1) == 4    # the floor
    # no-drop guarantee at this preset: capacity >= budget
    for w in (4, 8, 16, 64):
        assert eval_capacity(cfg, w) >= w


def test_metrics_on_moe_nan_hardened():
    m = ServingMetrics()
    m.on_moe([4, float("nan"), 3, 1], float("nan"), a2a_bytes=float("inf"))
    m.on_moe([1, 1, 1, 1], 0.25, a2a_bytes=1024)
    snap = m.snapshot()
    assert snap["moe_steps"] == 2
    assert snap["moe_dropped_fraction"] == 0.25
    assert snap["moe_a2a_bytes"] == 1024
    assert snap["moe_tokens_expert_1"] == 1  # the NaN became 0
    assert all(np.isfinite(v) for v in snap.values())
    assert "moe serving" in m.summary()
    assert m.moe_load_imbalance > 0


def test_serving_config_moe_a2a_validation():
    from deepspeed_tpu.config import DeepSpeedConfigError, ServingConfig

    ServingConfig(moe_a2a="chunked").validate()
    with pytest.raises(DeepSpeedConfigError):
        ServingConfig(moe_a2a="ring").validate()


def test_resolve_moe_a2a_form(devices8):
    from deepspeed_tpu.serving.engine import resolve_moe_a2a_form

    cfg = tiny_mixtral().config
    dense_topo = MeshTopology(devices=jax.devices()[:1])
    ep_topo = MeshTopology(dims=ParallelDims(ep=2),
                           devices=jax.devices()[:2])
    llama_cfg = type("C", (), {"is_moe": False})()
    assert resolve_moe_a2a_form("auto", llama_cfg, ep_topo, 8, 4) == "off"
    assert resolve_moe_a2a_form("chunked", cfg, dense_topo, 8, 4) == "stock"
    assert resolve_moe_a2a_form("chunked", cfg, ep_topo, 8, 4) == "chunked"
    # packed experts always take the stock exchange
    assert resolve_moe_a2a_form(
        "chunked", cfg, ep_topo, 8, 4, packed_experts=True
    ) == "stock"
    # auto: latency-bound small steps pick stock
    assert resolve_moe_a2a_form("auto", cfg, ep_topo, 8, 4) == "stock"
    # the slot grid must divide ep or the ring cannot run — the resolved
    # form must describe the exchange that actually executes (review
    # fix: a declared-chunked stream over an actually-stock program
    # would mis-price R8)
    assert resolve_moe_a2a_form(
        "chunked", cfg, ep_topo, 5, 4, max_slots=3
    ) == "stock"
    assert resolve_moe_a2a_form(
        "chunked", cfg, ep_topo, 8, 4, max_slots=3
    ) == "chunked"


def test_planner_axis_skipped_on_undividable_ep(devices8):
    """ep_size that does not divide the experts serves dense-replicated:
    the serving moe-a2a axis must collapse (identical duplicate plans
    otherwise — the PR-12 grad_wire-axis lesson)."""
    from deepspeed_tpu.autotuning.planner_search import PlannerSearch

    with open(os.path.join(REPO, "examples",
                           "ds_config_serving_moe.json")) as f:
        cfg = json.load(f)
    cfg["moe"]["ep_size"] = 3  # 4 experts % 3 != 0
    ps = PlannerSearch(tiny_mixtral(vocab_size=512), cfg,
                       token_budgets=(8,))
    labels = [c.label() for c in ps.candidates()]
    assert labels == ["serve-tb8"]
    # the gate reads the MODEL config (the source of truth), not the
    # config-side moe.num_experts — omitting it must not collapse the
    # axis (review fix)
    cfg["moe"]["ep_size"] = 2
    del cfg["moe"]["num_experts"]
    ps2 = PlannerSearch(tiny_mixtral(vocab_size=512), cfg,
                        token_budgets=(8,))
    assert sorted(c.label() for c in ps2.candidates()) == [
        "serve-tb8/a2achunk", "serve-tb8/a2astock",
    ]


def test_lint_serving_moe_example(devices8):
    """examples/ds_config_serving_moe.json lints CLEAN through
    lint_serving_config tracing the MoE slot step abstractly on the ep
    mesh (the chunked ring's perms pass R3; the moe_decode_a2a stream is
    declared for R8)."""
    from deepspeed_tpu.analysis import lint_config

    with open(os.path.join(REPO, "examples",
                           "ds_config_serving_moe.json")) as f:
        cfg = json.load(f)
    model = tiny_mixtral(vocab_size=512)
    report = lint_config(cfg, model=model)
    assert report.ok, report.format()


@pytest.mark.slow
def test_planner_serving_moe_a2a_axis(devices8):
    """The serving-side moe-a2a axis (stock vs chunked) enumerates on
    mixtral serving configs, statically only — no compile, and the
    PR-7 measurement refusal still stands for serving configs."""
    from deepspeed_tpu.autotuning.planner_search import PlannerSearch

    with open(os.path.join(REPO, "examples",
                           "ds_config_serving_moe.json")) as f:
        cfg = json.load(f)
    ps = PlannerSearch(tiny_mixtral(vocab_size=512), cfg,
                       token_budgets=(8, 16))
    res = ps.search()
    labels = [pc.cand.label() for pc in res.planned]
    assert sorted(labels) == sorted([
        "serve-tb8/a2astock", "serve-tb16/a2astock",
        "serve-tb8/a2achunk", "serve-tb16/a2achunk",
    ])
    assert len(res.survivors) == 4  # all traceable, none compiled
    with pytest.raises(NotImplementedError, match="static-only"):
        ps.tuner._tune_planner()


def test_moe_decode_stream_declared(devices8):
    """The serving engine declares the moe_decode_a2a analytic stream
    under ep > 1 (R8 prices it; the comms logger records it)."""
    srv = ServingEngine(engine=_engine(ep=2), serving={
        "max_slots": 2, "token_budget": 8, "max_tokens": 32,
    })
    streams = srv.analytic_streams()
    assert "moe_decode_a2a" in streams
    s = streams["moe_decode_a2a"]
    assert s["kind"] == "ici" and s["bytes_per_step"] > 0
    assert s["ep"] == 2 and s["form"] in ("stock", "chunked")
    # dense-replicated: no exchange on the wire
    srv1 = ServingEngine(engine=_engine(ep=1), serving={
        "max_slots": 2, "token_budget": 8, "max_tokens": 32,
    })
    assert "moe_decode_a2a" not in srv1.analytic_streams()
