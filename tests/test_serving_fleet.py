"""Fleet: disaggregated, replicated serving tier (ISSUE 13).

The routing oracle: ANY routing of a staggered-arrival trace across N
replicas — prefix-affinity routing, least-loaded spill, and at least one
prefill→decode KV handoff — reproduces a single-replica serial replay
token-for-token (greedy AND sampled-with-shared-keys, paged, spec-on),
with ``step_traces == 1`` per replica. Plus the host-side units: the
public ``PrefixCache.longest_chain`` lookup (collisions degrade to
misses), the global prefix index mirrored from cache events, page
export/import with the ``free + live == num_pages`` invariant on both
pools (forced mid-transfer LRU eviction included), fleet-level load
shedding / session affinity, config validation, and metrics
aggregation.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.config import DeepSpeedConfigError, ServingConfig, _parse_dc
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (PagePool, PrefixCache, Request,
                                   RequestStatus, Scheduler, ServingEngine,
                                   ServingMetrics, chain_hashes,
                                   export_pages, import_pages)
from deepspeed_tpu.serving.fleet import GlobalPrefixIndex, Router
from deepspeed_tpu.serving.metrics import FleetMetrics
from deepspeed_tpu.serving.paging import chain_hash


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


@pytest.fixture(scope="module")
def inference_engine():
    return deepspeed_tpu.init_inference(
        tiny_llama(), dtype=jnp.float32, max_tokens=64,
        rng=jax.random.PRNGKey(7),
    )


BASE_SERVING = {
    "max_slots": 3, "token_budget": 8, "max_tokens": 64,
    "paged": True, "page_size": 8,
}


def _serial_replay(engine, requests):
    """The oracle's right-hand side: the same requests through ONE
    ServingEngine, submitted up front (determinism makes arrival order
    irrelevant — every request's RNG chain is its own)."""
    srv = ServingEngine(engine=engine, serving=dict(BASE_SERVING))
    states = [srv.submit(r) for r in requests]
    srv.run_until_idle()
    assert srv.step_traces == 1
    return states


# ---------------------------------------------------------------------------
# the routing oracle
# ---------------------------------------------------------------------------
def test_fleet_oracle_2_replicas_greedy_and_sampled(inference_engine):
    """2 mixed replicas, staggered arrivals, greedy AND
    sampled-with-shared-keys in one trace == serial replay,
    token-for-token; step_traces == 1 per replica."""
    r = np.random.RandomState(0)
    keys = [jax.random.PRNGKey(100 + i) for i in range(3)]
    reqs = [
        Request("g0", r.randint(0, 128, size=(5,)), max_new_tokens=6),
        Request("g1", r.randint(0, 128, size=(11,)), max_new_tokens=4),
        Request("s0", r.randint(0, 128, size=(7,)), max_new_tokens=8,
                temperature=0.8, top_k=10, rng=keys[0]),
        Request("s1", r.randint(0, 128, size=(4,)), max_new_tokens=5,
                temperature=0.7, top_p=0.85, rng=keys[1]),
        Request("s2", r.randint(0, 128, size=(9,)), max_new_tokens=6,
                temperature=0.9, top_k=20, repetition_penalty=1.3,
                rng=keys[2]),
    ]

    router = Router(engine=inference_engine, serving={
        **BASE_SERVING, "fleet": {"enabled": True, "replicas": 2},
    })
    states = []
    # staggered: two up front, the rest while the fleet is running
    states.append(router.submit(reqs[0]))
    states.append(router.submit(reqs[1]))
    router.step()
    states.append(router.submit(reqs[2]))
    router.step()
    states.append(router.submit(reqs[3]))
    states.append(router.submit(reqs[4]))
    router.run_until_idle()

    want = _serial_replay(inference_engine, reqs)
    for st, ws in zip(states, want):
        assert st.status is RequestStatus.DONE
        np.testing.assert_array_equal(st.output(), ws.output(),
                                      err_msg=st.request.request_id)
    # zero recompiles after warmup, PER replica
    assert router.step_traces == [1, 1]
    assert router.metrics.snapshot()["finished"] == len(reqs)


def test_fleet_oracle_disaggregated_spec_handoff(inference_engine):
    """3 replicas (1 dedicated prefill, 2 decode), spec-on: every
    request's KV crosses a prefill→decode page handoff and the output
    still equals the serial replay token-for-token (spec-on is bitwise
    spec-off, so the serial leg runs spec too). The page-pool leak
    invariant is asserted inside every transfer."""
    serving = {
        **BASE_SERVING,
        "spec": {"enabled": True, "max_draft": 3},
        "fleet": {"enabled": True, "replicas": 3, "prefill_replicas": 1},
    }
    router = Router(engine=inference_engine, serving=serving)
    r = np.random.RandomState(3)
    reqs = [
        Request(f"h{i}", r.randint(0, 128, size=(n,)), max_new_tokens=new)
        for i, (n, new) in enumerate([(6, 8), (13, 5), (4, 7), (9, 6)])
    ]
    states = []
    for rq in reqs:
        states.append(router.submit(rq))
        router.step()
    router.run_until_idle()

    srv = ServingEngine(engine=inference_engine, serving={
        k: v for k, v in serving.items() if k != "fleet"
    })
    want = [srv.submit(rq) for rq in reqs]
    srv.run_until_idle()
    for st, ws in zip(states, want):
        assert st.status is RequestStatus.DONE
        np.testing.assert_array_equal(st.output(), ws.output(),
                                      err_msg=st.request.request_id)
    m = router.metrics
    assert m.handoffs >= 1, "no prefill→decode handoff ever ran"
    assert m.handoff_pages >= 1
    # every replica that stepped compiled exactly once
    stepped = [t for t in router.step_traces if t > 0]
    assert stepped and all(t == 1 for t in stepped), router.step_traces


def test_fleet_handoff_deferral_under_page_pressure(inference_engine):
    """Decode pools at the liveness floor: concurrent handoff candidates
    cannot all move — the transfer DEFERS (nothing changes on either
    side, invariants assert inside handoff), the request keeps decoding
    on the prefill replica, and outputs still match the serial replay."""
    serving = {
        "max_slots": 3, "token_budget": 8, "max_tokens": 64,
        "paged": True, "page_size": 8, "num_pages": 9,  # == pages_per_slot
        "fleet": {"enabled": True, "replicas": 3, "prefill_replicas": 1},
    }
    router = Router(engine=inference_engine, serving=serving)
    r = np.random.RandomState(5)
    reqs = [
        Request(f"p{i}", r.randint(0, 128, size=(n,)), max_new_tokens=new)
        for i, (n, new) in enumerate([(5, 8), (7, 8), (4, 6), (6, 7)])
    ]
    states = [router.submit(rq) for rq in reqs]
    router.run_until_idle()

    srv = ServingEngine(engine=inference_engine, serving={
        k: v for k, v in serving.items() if k != "fleet"
    })
    want = [srv.submit(rq) for rq in reqs]
    srv.run_until_idle()
    for st, ws in zip(states, want):
        np.testing.assert_array_equal(st.output(), ws.output(),
                                      err_msg=st.request.request_id)
    # with one-slot-deep decode pools and 3 prefill slots racing, at
    # least one transfer must have deferred — and none may leak
    assert router.metrics.handoffs >= 1
    for rep in router.replicas:
        rep.engine.scheduler.assert_page_invariants()


@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs >= 2 devices")
def test_fleet_oracle_disaggregated_tp2(inference_engine):
    """tp=2 disaggregated fleet: the KV pools are tp-sharded, so the
    page-payload import must land back on EXACTLY the sharding the step
    compiled against — a drifted carry would recompile (step_traces > 1)
    and a wrong transfer would break the token oracle."""
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    topology = MeshTopology(dims=ParallelDims(tp=2),
                            devices=jax.devices()[:2])
    eng = deepspeed_tpu.init_inference(
        tiny_llama(), dtype=jnp.float32, max_tokens=64, topology=topology,
        rng=jax.random.PRNGKey(11),
    )
    serving = {
        **BASE_SERVING,
        "fleet": {"enabled": True, "replicas": 2, "prefill_replicas": 1},
    }
    router = Router(engine=eng, serving=serving)
    r = np.random.RandomState(9)
    reqs = [Request(f"tp{i}", r.randint(0, 128, size=(n,)),
                    max_new_tokens=new)
            for i, (n, new) in enumerate([(7, 5), (4, 6)])]
    states = []
    for rq in reqs:
        states.append(router.submit(rq))
        router.step()
    router.run_until_idle()
    srv = ServingEngine(engine=eng, serving=dict(BASE_SERVING))
    want = [srv.submit(rq) for rq in reqs]
    srv.run_until_idle()
    for st, ws in zip(states, want):
        np.testing.assert_array_equal(st.output(), ws.output(),
                                      err_msg=st.request.request_id)
    assert router.metrics.handoffs >= 1
    stepped = [t for t in router.step_traces if t > 0]
    assert stepped and all(t == 1 for t in stepped), router.step_traces


@pytest.mark.slow
@pytest.mark.moe_serve
@pytest.mark.skipif(len(jax.devices()) < 2, reason="needs >= 2 devices")
def test_fleet_oracle_moe_ep2_replicas():
    """Fleet routing composed with MoE expert-parallel replicas (the
    PR-14 "untested together" follow-up): a Poisson-arrival trace
    routed across 2 replicas whose engine is ep-sharded (experts split
    over 2 devices, the decode exchange inside the ONE slot step) must
    replay token-for-token equal to a single-replica serial run —
    greedy AND sampled-with-shared-keys — with ``step_traces == 1`` per
    stepped replica. Marked slow: two mixtral compile cones on the
    1-core tier-1 box."""
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
    from deepspeed_tpu.models import mixtral

    model = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=64,
                    hidden_size=32, num_layers=2, num_heads=4,
                    num_kv_heads=2, intermediate_size=64, num_experts=4,
                    moe_top_k=2)
    topology = MeshTopology(dims=ParallelDims(ep=2),
                            devices=jax.devices()[:2])
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, topology=topology,
        rng=jax.random.PRNGKey(21),
    )
    serving = {
        "max_slots": 3, "token_budget": 8, "max_tokens": 64,
        "paged": True, "page_size": 8,
        "fleet": {"enabled": True, "replicas": 2},
    }
    r = np.random.RandomState(17)
    keys = [jax.random.PRNGKey(300 + i) for i in range(2)]
    reqs = [
        Request("m0", r.randint(0, 64, size=(5,)), max_new_tokens=6),
        Request("m1", r.randint(0, 64, size=(9,)), max_new_tokens=4),
        Request("m2", r.randint(0, 64, size=(4,)), max_new_tokens=7,
                temperature=0.8, top_k=10, rng=keys[0]),
        Request("m3", r.randint(0, 64, size=(7,)), max_new_tokens=5,
                temperature=0.7, top_p=0.9, rng=keys[1]),
        Request("m4", r.randint(0, 64, size=(6,)), max_new_tokens=6),
    ]

    router = Router(engine=eng, serving=serving)
    states = []
    # Poisson-distributed arrival gaps on the tick clock, drawn once;
    # each gap is spent as router steps (determinism makes the exact
    # schedule irrelevant to the oracle — only coverage of mixed
    # in-flight occupancy matters)
    gaps = np.clip(r.poisson(lam=1.5, size=len(reqs)), 0, 3)
    for rq, gap in zip(reqs, gaps):
        states.append(router.submit(rq))
        for _ in range(int(gap)):
            router.step()
    router.run_until_idle()

    srv = ServingEngine(engine=eng, serving={
        k: v for k, v in serving.items() if k != "fleet"
    })
    want = [srv.submit(rq) for rq in reqs]
    srv.run_until_idle()
    assert srv.step_traces == 1
    for st, ws in zip(states, want):
        assert st.status is RequestStatus.DONE
        np.testing.assert_array_equal(st.output(), ws.output(),
                                      err_msg=st.request.request_id)
    stepped = [t for t in router.step_traces if t > 0]
    assert stepped and all(t == 1 for t in stepped), router.step_traces
    # the routed fleet really exercised the expert-parallel path
    snaps = [rep.engine.metrics.snapshot() for rep in router.replicas]
    assert sum(s.get("moe_steps", 0) for s in snaps) > 0
    assert router.metrics.snapshot()["finished"] == len(reqs)


# ---------------------------------------------------------------------------
# longest_chain + collisions (satellite 1)
# ---------------------------------------------------------------------------
def test_longest_chain_public_lookup():
    pool = PagePool(8)
    cache = PrefixCache(pool, page_size=4)
    toks = np.arange(10, dtype=np.int32)  # 2 full pages + a 2-token tail
    pages = [pool.alloc() for _ in range(3)]
    cache.insert(toks, pages)
    hashes = chain_hashes(toks, 4)
    assert len(hashes) == 2
    assert cache.longest_chain(hashes) == 2
    assert cache.longest_chain(hashes[:1]) == 1
    # a diverging prompt chains differently from block 0 on
    other = chain_hashes(np.arange(100, 110, dtype=np.int32), 4)
    assert cache.longest_chain(other) == 0
    # a chain that matches block 0 but diverges in block 1
    mixed = [hashes[0], other[1]]
    assert cache.longest_chain(mixed) == 1
    # match() agrees with the hash walk when there is no collision
    pages_out, covered = cache.match(toks)
    assert covered == 10 and pages_out == pages


def test_longest_chain_collision_degrades_to_miss():
    """A forged crc32 collision (same chain hash, different tokens) may
    fool the hash-only lookups — longest_chain and the router's global
    index — but the token-verified match() path must degrade it to a
    miss, never to wrong KV."""
    pool = PagePool(8)
    cache = PrefixCache(pool, page_size=4)
    stored = np.arange(4, dtype=np.int32)
    page = pool.alloc()
    cache.insert(stored, [page])
    probe = np.arange(50, 54, dtype=np.int32)  # different tokens
    h_probe = chain_hashes(probe, 4)
    # forge the collision: rekey the stored entry under the probe's hash
    (_, (stored_page, stored_block)), = [
        (k, v) for k, v in cache._full.items()
    ]
    cache._full.clear()
    cache._full[h_probe[0]] = (stored_page, stored_block)
    # the hash walk overstates...
    assert cache.longest_chain(h_probe) == 1
    # ...and the global index mirror would too (hash-only by design)
    idx = GlobalPrefixIndex(page_size=4)
    idx._hashes[0] = {h_probe[0]}
    assert idx.longest_chain(0, h_probe) == 1
    # but the token-verified match treats it as a MISS
    pages_out, covered = cache.match(probe)
    assert covered == 0 and pages_out == []
    # and the true owner still matches its own tokens
    pages_out, covered = cache.match(stored)
    assert covered == 0 or covered == 4  # rekeyed entry: stored tokens
    #   now hash elsewhere, so either outcome is a miss or the (rekeyed)
    #   hash walk stopping at 0 — never wrong pages for the probe


def test_global_index_tracks_cache_events():
    pool = PagePool(8)
    cache = PrefixCache(pool, page_size=4)
    idx = GlobalPrefixIndex(page_size=4)
    idx.attach(1, cache)
    toks = np.arange(8, dtype=np.int32)
    pages = [pool.alloc(), pool.alloc()]
    cache.insert(toks, pages)
    hashes = chain_hashes(toks, 4)
    assert idx.longest_chain(1, hashes) == 2
    assert idx.best(toks, [1]) == (1, 2)
    # evicting the first link breaks the chain from the start
    while cache.evict_lru():
        pass
    assert idx.longest_chain(1, hashes) == 0
    assert idx.entries(1) == 0
    # page-size mismatch is rejected (keys would not be comparable)
    with pytest.raises(ValueError):
        GlobalPrefixIndex(page_size=8).attach(2, cache)


# ---------------------------------------------------------------------------
# export/import pages + the leak invariant (satellite 2)
# ---------------------------------------------------------------------------
def _toy_pool(num_pages, page_size=4, layers=2, kv=2, hd=3, seed=0):
    r = np.random.RandomState(seed)
    shape = (layers, num_pages + 1, page_size, kv, hd)
    return {
        "k": jnp.asarray(r.randn(*shape).astype(np.float32)),
        "v": jnp.asarray(r.randn(*shape).astype(np.float32)),
    }


def test_export_import_pages_roundtrip():
    src = _toy_pool(6, seed=1)
    dst = _toy_pool(6, seed=2)
    payload = export_pages(src, [4, 1])
    assert payload["k"].shape == (2, 2, 4, 2, 3)
    out = import_pages(dst, payload, [0, 5])
    np.testing.assert_array_equal(np.asarray(out["k"][:, 0]),
                                  np.asarray(src["k"][:, 4]))
    np.testing.assert_array_equal(np.asarray(out["v"][:, 5]),
                                  np.asarray(src["v"][:, 1]))
    # untouched pages keep the destination's bytes
    np.testing.assert_array_equal(np.asarray(out["k"][:, 2]),
                                  np.asarray(dst["k"][:, 2]))
    # shape / leaf mismatches are loud
    with pytest.raises(ValueError):
        import_pages(dst, payload, [0])
    with pytest.raises(KeyError):
        import_pages(dst, {"k": payload["k"]}, [0, 5])


def test_alloc_pages_forced_eviction_and_leak_invariant():
    """The destination half of a handoff under pressure: alloc_pages
    forces LRU prefix-cache eviction mid-transfer, and on true
    exhaustion rolls its partial allocation back — ``free + live ==
    num_pages`` holds either way."""
    sched = Scheduler(max_slots=2, token_budget=4, max_tokens=16,
                      page_size=4, num_pages=6, pages_per_slot=5,
                      prefix_cache=True)
    # fill the pool: 4 pages held by the prefix cache, 2 free
    held = [sched.pool.alloc() for _ in range(4)]
    sched.prefix_cache.insert(np.arange(16, dtype=np.int32), held)
    for p in held:
        sched.pool.decref(p)  # cache refs remain
    assert sched.pool.free_count == 2
    # needs 5: takes the 2 free + forcibly evicts cache entries
    got = sched.alloc_pages(5)
    assert got is not None and len(got) == 5
    sched.pool.check_leaks()
    for p in got:
        sched.pool.decref(p)
    sched.pool.check_leaks()
    # exhaustion: ask for more than the pool — partial alloc rolled back
    assert sched.alloc_pages(7) is None
    sched.pool.check_leaks()
    assert sched.pool.free_count + len(sched.prefix_cache.held_pages) >= 6


# ---------------------------------------------------------------------------
# shedding + affinity + config validation
# ---------------------------------------------------------------------------
def test_fleet_shedding_and_retry_after(inference_engine):
    """Fleet queue_limit lifts the bounded-queue semantics: past the
    bound, submit() returns an EVICTED state with exponential
    retry_after — no exception, no replica ever sees the request."""
    clock_t = [0.0]
    router = Router(engine=inference_engine, clock=lambda: clock_t[0],
                    serving={
                        "max_slots": 1, "token_budget": 8, "max_tokens": 64,
                        "eviction_backoff_s": 2.0,
                        "fleet": {"enabled": True, "replicas": 2,
                                  "queue_limit": 2},
                    })
    prompt = np.arange(4, dtype=np.int32)
    # 2 slots (1/replica) fill first; then 2 queued reaches the bound
    states = [router.submit(Request(f"q{i}", prompt, max_new_tokens=4))
              for i in range(4)]
    assert all(s.status is not RequestStatus.EVICTED for s in states)
    shed = router.submit(Request("q4", prompt, max_new_tokens=4))
    assert shed.status is RequestStatus.EVICTED
    assert "fleet queue full" in shed.evict_reason
    assert shed.retry_after == pytest.approx(2.0)  # backoff * 2**0
    # resubmission while still saturated doubles the backoff
    clock_t[0] = 3.0
    shed2 = router.resubmit(shed)
    assert shed2.status is RequestStatus.EVICTED
    assert shed2.retry_after == pytest.approx(3.0 + 4.0)  # backoff * 2**1
    assert router.metrics.shed == 2
    router.run_until_idle()
    # once drained, the resubmission routes normally
    clock_t[0] = 10.0
    ok = router.resubmit(shed2)
    assert ok.status is not RequestStatus.EVICTED
    router.run_until_idle()
    assert ok.status is RequestStatus.DONE


def test_fleet_session_affinity(inference_engine):
    router = Router(engine=inference_engine, serving={
        **BASE_SERVING,
        "fleet": {"enabled": True, "replicas": 3,
                  "routing": "round_robin"},
    })
    prompt = np.arange(6, dtype=np.int32)
    router.submit(Request("a0", prompt, max_new_tokens=2,
                          session_id="alice"))
    first = router._sessions["alice"]
    # round-robin would move on; affinity pins the session
    for i in range(1, 4):
        router.submit(Request(f"a{i}", prompt, max_new_tokens=2,
                              session_id="alice"))
        assert router._sessions["alice"] == first
    assert router.metrics.affinity_routed == 3
    # a different session lands elsewhere (round-robin advanced)
    router.submit(Request("b0", prompt, max_new_tokens=2,
                          session_id="bob"))
    assert router._sessions["bob"] != first
    router.run_until_idle()


def test_fleet_config_validation():
    # prefill_replicas >= replicas: every prefill needs a decode target
    with pytest.raises(DeepSpeedConfigError):
        _parse_dc(ServingConfig, {
            "enabled": True, "paged": True,
            "fleet": {"enabled": True, "replicas": 2,
                      "prefill_replicas": 2},
        }).validate()
    # disaggregation without the paged arena: no page transfer exists
    with pytest.raises(DeepSpeedConfigError):
        _parse_dc(ServingConfig, {
            "enabled": True, "paged": False,
            "fleet": {"enabled": True, "replicas": 3,
                      "prefill_replicas": 1},
        }).validate()
    with pytest.raises(DeepSpeedConfigError):
        _parse_dc(ServingConfig, {
            "fleet": {"enabled": True, "replicas": 0},
        }).validate()
    with pytest.raises(DeepSpeedConfigError):
        _parse_dc(ServingConfig, {
            "fleet": {"enabled": True, "routing": "random"},
        }).validate()
    with pytest.raises(DeepSpeedConfigError):
        _parse_dc(ServingConfig, {
            "fleet": {"enabled": True, "queue_limit": -1},
        }).validate()
    with pytest.raises(DeepSpeedConfigError):
        _parse_dc(ServingConfig, {
            "fleet": {"enabled": True, "prefix_balance_slack": -2},
        }).validate()
    # a valid section (the examples/ds_config_serving_fleet.json shape)
    cfg = _parse_dc(ServingConfig, {
        "enabled": True, "paged": True,
        "fleet": {"enabled": True, "replicas": 3, "prefill_replicas": 1,
                  "routing": "prefix", "affinity": True,
                  "queue_limit": 64},
    })
    cfg.validate()
    assert cfg.fleet.replicas == 3


def test_fleet_metrics_aggregation():
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    a, b = ServingMetrics(clock=clock), ServingMetrics(clock=clock)
    fm = FleetMetrics([a, b], clock=clock)
    a.tokens_out, b.tokens_out = 30, 10
    a.finished, b.finished = 3, 1
    a.queue_depth, b.queue_depth = 2, 1
    a.ttft_s.extend([0.1, 0.2])
    b.ttft_s.append(0.9)
    fm.on_route("prefix")
    fm.on_route("affinity")
    fm.on_handoff(True, pages=3)
    fm.on_handoff(False)
    fm.on_shed("fleet queue full")
    t[0] = 2.0
    s = fm.snapshot()
    assert s["tokens_out"] == 40 and s["finished"] == 4
    assert s["queue_depth"] == 3
    assert s["tokens_per_s"] == pytest.approx(20.0)
    assert s["ttft_p95_s"] == pytest.approx(0.9)  # merged samples
    assert s["handoffs"] == 1 and s["handoff_failures"] == 1
    assert s["handoff_pages"] == 3
    assert s["prefix_routed"] == 1 and s["affinity_routed"] == 1
    assert s["shed"] == 1
    assert fm.queue_depth == 3  # hw duck-type
    # the watchdog/shed window is COMPLETION-ordered and bounded, fed by
    # the router — not a replica-order concatenation (a trailing-window
    # read must never see only the last replica's history)
    fm.on_finish_ttft(0.1)
    fm.on_finish_ttft(0.9)
    fm.on_finish_ttft(0.2)
    assert fm.ttft_s == [0.1, 0.9, 0.2]
    assert "fleet metrics" in fm.summary()
    assert len(fm.per_replica()) == 2


def test_replica_serving_config_strips_fleet(inference_engine):
    """Replica engines must not recurse into fleet construction, and
    decode replicas drop their (dead-weight) prefix cache."""
    router = Router(engine=inference_engine, serving={
        **BASE_SERVING, "prefix_cache": True,
        "fleet": {"enabled": True, "replicas": 3, "prefill_replicas": 1},
    })
    for rep in router.replicas:
        assert not rep.engine.serving.fleet.enabled
    assert router.replicas[0].engine.scheduler.prefix_cache is not None
    assert router.replicas[1].engine.scheduler.prefix_cache is None
    assert router.replicas[2].engine.scheduler.prefix_cache is None
    # the index mirrors intake replicas only
    assert router.index is not None
    assert dataclasses.asdict(router.serving.fleet)["replicas"] == 3
