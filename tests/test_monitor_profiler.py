"""Monitor writers + comms logger + flops profiler (SURVEY §2.7)."""

import csv
import os

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.comm import collectives
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.monitor.monitor import MonitorMaster, csv_monitor
from deepspeed_tpu.profiling.comm_logger import CommsLogger, get_bw
from deepspeed_tpu.profiling.flops_profiler import (
    FlopsProfiler,
    get_model_profile,
)


def test_csv_monitor_writes(tmp_path):
    mon = csv_monitor(str(tmp_path), "job")
    mon.write_events([("Train/loss", 1.5, 1), ("Train/loss", 1.2, 2)])
    mon.close()
    with open(os.path.join(str(tmp_path), "job", "Train_loss.csv")) as f:
        rows = list(csv.reader(f))
    assert rows == [["1", "1.5"], ["2", "1.2"]]


def test_monitor_master_from_config(tmp_path):
    cfg = DeepSpeedConfig(
        {
            "train_batch_size": 8,
            "csv_monitor": {
                "enabled": True,
                "output_path": str(tmp_path),
                "job_name": "j",
            },
        }
    )
    assert cfg.monitor.enabled
    master = MonitorMaster(cfg.monitor)
    assert master.enabled
    master.write_events([("Train/lr", 0.1, 1)])
    assert os.path.exists(os.path.join(str(tmp_path), "j", "Train_lr.csv"))


def test_comms_logger_records_shard_map_ops():
    logger = CommsLogger()
    x = jnp.ones((8, 4), jnp.float32)
    from jax.sharding import Mesh, PartitionSpec as P
    from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.array(jax.devices()[:4]), ("dp",))
    f = shard_map(
        lambda a: collectives.all_reduce(a, "dp"),
        mesh=mesh,
        in_specs=P("dp"),
        out_specs=P(),
    )
    jax.jit(f)(x)
    logger.stop()
    assert logger.counts["all_reduce"] == 1
    # bytes recorded at trace time: per-shard payload
    assert logger.bytes["all_reduce"] == 2 * 4 * 4


def test_comms_logger_offload_stream_accounting():
    """The bucketed ZeRO-offload DMA stream is not a collective — the
    engine reports it per step; the logger must aggregate bytes, expose
    the in-flight (slots × slice) peak, and render an offload line in
    the summary."""
    logger = CommsLogger()
    try:
        logger.record_offload(100, 100, slots=2, slot_bytes=10, steps=3)
        assert logger.offload_steps == 3
        assert logger.offload_bytes_in == 300
        assert logger.offload_bytes_out == 300
        assert logger.offload_bytes_in_flight == 20
        s = logger.summary(duration_s=1.0)
        assert "offload stream" in s
        assert "2 slot(s)" in s
        # no offload recorded → no offload line
        assert "offload stream" not in CommsLogger().summary(duration_s=1.0)
    finally:
        logger.stop()
    # overlap-ratio arithmetic: (serial - overlapped) / dma, clamped [0,1]
    assert CommsLogger.offload_overlap_ratio(4.0, 3.0, 2.0) == 0.5
    assert CommsLogger.offload_overlap_ratio(4.0, 4.5, 2.0) == 0.0
    assert CommsLogger.offload_overlap_ratio(4.0, 1.0, 2.0) == 1.0
    assert CommsLogger.offload_overlap_ratio(4.0, 3.0, 0.0) == 0.0


def test_get_bw_formulas():
    alg, bus = get_bw("all_reduce", 1e9, 1.0, 4)
    assert abs(alg - 8.0) < 1e-9
    assert abs(bus - 8.0 * 1.5) < 1e-9  # 2(n-1)/n = 1.5
    alg, bus = get_bw("all_gather", 1e9, 1.0, 4)
    assert abs(bus - 8.0 * 0.75) < 1e-9


def test_flops_profiler_analytic():
    model = llama(
        "llama-tiny",
        vocab_size=512,
        max_seq_len=64,
        hidden_size=64,
        num_layers=2,
        num_heads=4,
        num_kv_heads=2,
        intermediate_size=128,
    )
    flops, macs, params = get_model_profile(model, batch=2, seq=32)
    assert flops > 0 and macs == flops / 2
    assert params == model.num_params()
    # dominated by matmuls: flops ≈ 2 * tokens * params for tiny seq
    approx = 2 * 2 * 32 * params
    assert 0.5 < flops / approx < 3.0


def test_flops_profiler_xla_cost_and_report(tmp_path):
    model = llama(
        "llama-tiny",
        vocab_size=128,
        max_seq_len=32,
        hidden_size=32,
        num_layers=1,
        num_heads=2,
        num_kv_heads=2,
        intermediate_size=64,
    )
    prof = FlopsProfiler(model)
    prof.start_profile()
    root = prof.profile_model(batch=1, seq=16)
    prof.stop_profile()
    params = model.init(jax.random.PRNGKey(0))
    ids = jnp.zeros((1, 16), jnp.int32)
    cost = prof.profile_compiled(lambda p, x: model.apply(p, x), params, ids)
    assert cost["flops"] > 0
    out = prof.print_model_profile(output_file=str(tmp_path / "prof.txt"))
    assert "lm_head" in out and "attention" in out
    assert os.path.exists(tmp_path / "prof.txt")
    assert prof.get_total_flops() == root.flops


def test_profile_step_writes_trace(tmp_path, devices8):
    """engine.profile_step dumps an xprof trace artifact (SURVEY §2.7
    tracing/debug; r2 verdict: no jax.profiler integration existed)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    import numpy as np
    from deepspeed_tpu.models import gpt2

    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "steps_per_print": 1000,
        },
    )
    data = {"input_ids": np.random.RandomState(0).randint(0, 64, size=(8, 16))}
    trace_dir = str(tmp_path / "trace")
    loss, out_dir = engine.profile_step(batch=data, trace_dir=trace_dir)
    assert np.isfinite(float(loss))
    files = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(out_dir)
        for f in fs
    ]
    assert files, "no trace artifact written"


def test_native_tfevents_writer_roundtrip(tmp_path):
    """The torch-free tfevents writer produces records TensorBoard can read:
    verify TFRecord framing (masked CRC32C) and the scalar payload."""
    import struct

    from deepspeed_tpu.monitor.tfevents import TfEventsWriter, _masked_crc

    w = TfEventsWriter(str(tmp_path))
    w.add_scalar("Train/loss", 2.5, 7)
    w.add_scalar("Train/lr", 1e-4, 7)
    w.close()

    files = [f for f in os.listdir(tmp_path) if f.startswith("events.out.tfevents")]
    assert len(files) == 1
    raw = open(os.path.join(tmp_path, files[0]), "rb").read()

    records = []
    off = 0
    while off < len(raw):
        (length,) = struct.unpack_from("<Q", raw, off)
        (hcrc,) = struct.unpack_from("<I", raw, off + 8)
        header = raw[off : off + 8]
        assert hcrc == _masked_crc(header)
        payload = raw[off + 12 : off + 12 + length]
        (pcrc,) = struct.unpack_from("<I", raw, off + 12 + length)
        assert pcrc == _masked_crc(payload)
        records.append(payload)
        off += 12 + length + 4
    assert len(records) == 3  # version event + 2 scalars
    assert b"brain.Event:2" in records[0]
    assert b"Train/loss" in records[1]
    # float 2.5 little-endian appears in the first scalar record
    assert struct.pack("<f", 2.5) in records[1]

    # if the real tensorboard reader is importable, cross-check with it
    try:
        from tensorboard.backend.event_processing.event_file_loader import (
            EventFileLoader,
        )
    except Exception:
        return
    events = list(EventFileLoader(os.path.join(tmp_path, files[0])).Load())
    scalars = {}
    for e in events:
        for v in e.summary.value:
            # loaders may migrate simple_value → scalar tensor proto
            scalars[v.tag] = (
                v.tensor.float_val[0]
                if v.HasField("tensor") and v.tensor.float_val
                else v.simple_value
            )
    assert abs(scalars["Train/loss"] - 2.5) < 1e-6
    assert scalars["Train/lr"] > 0


def test_monitor_bridge_csv_roundtrip_serve_namespace(tmp_path):
    """ISSUE 8 satellite: registry events from a traced serving replay
    land in the CSV backend under the documented ``serve/*`` names with
    monotone steps — ServingMetrics.write_to routes through the
    steptrace registry's single ``write_events`` bridge."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama as _llama
    from deepspeed_tpu.profiling import steptrace
    from deepspeed_tpu.serving import Request, ServingEngine

    steptrace.reset()
    try:
        model = _llama(
            "llama-tiny", vocab_size=128, max_seq_len=64, hidden_size=32,
            num_layers=2, num_heads=4, num_kv_heads=2, intermediate_size=64,
        )
        eng = deepspeed_tpu.init_inference(
            model, dtype=jnp.float32, max_tokens=64,
            rng=jax.random.PRNGKey(0),
        )
        srv = ServingEngine(engine=eng, serving={
            "max_slots": 2, "token_budget": 8, "max_tokens": 64,
        }, steptrace={"enabled": True})
        mon = csv_monitor(str(tmp_path), "serve_job")
        r = np.random.RandomState(0)
        for i in range(2):
            srv.submit(Request(request_id=f"r{i}",
                               prompt=r.randint(0, 128, size=(5,)),
                               max_new_tokens=2))
        while srv.scheduler.has_work:
            srv.step()
            srv.metrics.write_to(mon, step=srv.metrics.steps)
        mon.close()

        job = os.path.join(str(tmp_path), "serve_job")
        files = sorted(os.listdir(job))
        # documented serve/* namespace (tag / -> filename _), nothing
        # under the legacy Serving/ prefix
        assert all(f.startswith("serve_") for f in files)
        for key in ("serve_tokens_out", "serve_steps", "serve_ttft_p50_s"):
            assert f"{key}.csv" in files
        with open(os.path.join(job, "serve_steps.csv")) as f:
            rows = [(int(a), float(b)) for a, b in csv.reader(f)]
        steps = [a for a, _ in rows]
        assert steps == sorted(steps) and len(set(steps)) == len(steps), \
            "steps must be strictly monotone"
        assert [b for _, b in rows] == [float(s) for s in steps]
        # the bridge ALSO recorded every event into the registry
        reg = steptrace.get_registry()
        assert any(t.startswith("serve/") for t, *_ in reg.samples)
    finally:
        steptrace.reset()


def test_overlap_ratio_is_the_single_hardened_path():
    """ISSUE 4 satellite: the generic ``overlap_ratio`` IS the primary
    (one hardened zero/NaN/None path); ``offload_overlap_ratio`` is the
    same function under its legacy name, so the two can never drift."""
    assert CommsLogger.overlap_ratio is CommsLogger.offload_overlap_ratio
    r = CommsLogger.overlap_ratio
    # the generic name carries the full degenerate-input hardening
    assert r(4.0, 3.0, 2.0) == 0.5
    assert r(4.0, 1.0, 2.0) == 1.0           # clamped at fully-hidden
    assert r(4.0, 3.0, 0.0) == 0.0           # zero-byte stream
    assert r(float("nan"), 3.0, 2.0) == 0.0  # failed A/B leg
    assert r(None, 3.0, 2.0) == 0.0          # type junk
    assert r("x", 3.0, 2.0) == 0.0


def test_record_streams_shared_intake():
    """engine.analytic_streams() → comm_logger.record_streams: ONE
    accounting path for offload + ring streams; planner-only (assumed)
    streams are never recorded."""
    logger = CommsLogger()
    try:
        logger.record_streams({
            "offload": {
                "kind": "offload", "bytes_in": 100, "bytes_out": 60,
                "slots": 2, "slot_bytes": 10, "overlapped": True,
            },
            "tp_ring": {"kind": "ici", "bytes_per_step": 7, "overlapped": True},
            "ghost": {
                "kind": "offload", "bytes_in": 999, "bytes_out": 999,
                "assumed": True,  # CPU lint mesh pricing — planner-only
            },
        }, steps=3)
    finally:
        logger.stop()
    assert logger.offload_steps == 3
    assert logger.offload_bytes_in == 300 and logger.offload_bytes_out == 180
    assert logger.offload_bytes_in_flight == 20
    assert logger.ring_steps == 3 and logger.ring_bytes == 21


def test_offload_overlap_ratio_degenerate_inputs():
    """ISSUE 2 satellite: zero-duration / empty offload streams and failed
    A/B legs must report 0.0 overlap, never raise."""
    r = CommsLogger.offload_overlap_ratio
    assert r(0.0, 0.0, 0.0) == 0.0          # empty stream, nothing timed
    assert r(4.0, 3.0, 0.0) == 0.0          # zero-byte stream → no DMA
    assert r(0.0, 3.0, 2.0) == 0.0          # unmeasured serial leg
    assert r(4.0, 0.0, 2.0) == 0.0          # unmeasured overlapped leg
    assert r(-1.0, 3.0, 2.0) == 0.0         # negative wall time
    assert r(float("nan"), 3.0, 2.0) == 0.0  # failed A/B leg
    assert r(float("inf"), 3.0, 2.0) == 0.0
    assert r(None, 3.0, 2.0) == 0.0          # type junk survives too
    # the happy path is untouched by the guards
    assert r(4.0, 3.0, 2.0) == 0.5
    # empty-stream summary stays empty (no division by zero steps)
    logger = CommsLogger()
    try:
        assert logger.offload_summary(duration_s=0.0) == ""
        logger.record_offload(0, 0, slots=0, slot_bytes=0, steps=1)
        assert "0.00 GiB/step" in logger.offload_summary(duration_s=0.0)
    finally:
        logger.stop()
