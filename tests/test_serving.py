"""Continuous-batching serving runtime (ISSUE 5).

The oracle: with identical params and per-request RNG, the slot engine
must reproduce single-request ``InferenceEngine.generate`` outputs for
staggered arrivals — greedy bitwise, sampled with shared keys, including
tp>1 and int8 KV cache configs. Plus scheduler invariants under a fake
clock (admission rejection, timeout eviction with backoff, slot
recycling), the per-slot decode-attention kernel, and the recompile
counters (zero serving recompiles after warmup; one lockstep compile per
128-bucket).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (Request, RequestStatus, Scheduler,
                                   ServingEngine, ServingMetrics)


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _submit(srv, rid, prompt, **kw):
    return srv.submit(Request(request_id=rid, prompt=prompt, **kw))


# ---------------------------------------------------------------------------
# token-parity oracle: slot engine == N independent single-request runs
# ---------------------------------------------------------------------------
def test_greedy_parity_staggered_arrivals():
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(1)
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 3, "token_budget": 8, "max_tokens": 64,
    })
    r = np.random.RandomState(0)
    specs = [(3, 6), (12, 4), (7, 8), (5, 5), (9, 3)]
    prompts = [r.randint(0, 128, size=(n,)) for n, _ in specs]
    states = []
    # staggered: two up front, the rest arrive while the batch is running
    states.append(_submit(srv, "r0", prompts[0], max_new_tokens=specs[0][1]))
    states.append(_submit(srv, "r1", prompts[1], max_new_tokens=specs[1][1]))
    srv.step()
    srv.step()
    states.append(_submit(srv, "r2", prompts[2], max_new_tokens=specs[2][1]))
    srv.step()
    states.append(_submit(srv, "r3", prompts[3], max_new_tokens=specs[3][1]))
    states.append(_submit(srv, "r4", prompts[4], max_new_tokens=specs[4][1]))
    srv.run_until_idle()
    for st, p, (_, new) in zip(states, prompts, specs):
        assert st.status is RequestStatus.DONE
        want = eng.generate(p[None, :], max_new_tokens=new, temperature=0.0)
        np.testing.assert_array_equal(st.output(), want[0],
                                      err_msg=st.request.request_id)
    # zero recompiles after warmup: one trace for the whole ragged trace
    assert srv.step_traces == 1


def test_sampled_parity_shared_keys():
    """Sampled decoding with per-request keys: the slot engine's traced
    where-gates reproduce the lockstep sampler bitwise — same key, same
    tokens — across temperature/top-k/top-p/penalty mixes IN ONE BATCH."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(2)
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 3, "token_budget": 8, "max_tokens": 64,
    })
    r = np.random.RandomState(1)
    cases = [
        dict(temperature=0.8, top_k=10, top_p=1.0),
        dict(temperature=0.7, top_k=0, top_p=0.85),
        dict(temperature=0.9, top_k=20, top_p=0.9, repetition_penalty=1.3),
    ]
    prompts = [r.randint(0, 128, size=(n,)) for n in (6, 9, 4)]
    keys = [jax.random.PRNGKey(100 + i) for i in range(len(cases))]
    states = [
        _submit(srv, f"s{i}", p, max_new_tokens=8, rng=keys[i], **cases[i])
        for i, p in enumerate(prompts)
    ]
    srv.run_until_idle()
    for i, (st, p) in enumerate(zip(states, prompts)):
        want = eng.generate(p[None, :], max_new_tokens=8, rng=keys[i],
                            **cases[i])
        np.testing.assert_array_equal(st.output(), want[0], err_msg=f"s{i}")


def test_eos_parity_and_padding():
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(3)
    )
    prompt = np.random.RandomState(2).randint(0, 128, size=(4,))
    ref = eng.generate(prompt[None, :], max_new_tokens=8, temperature=0.0)
    eos = int(ref[0, 6])  # force eos mid-generation
    want = eng.generate(prompt[None, :], max_new_tokens=8, temperature=0.0,
                        eos_token_id=eos)
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 2, "token_budget": 8, "max_tokens": 64,
    })
    st = _submit(srv, "e0", prompt, max_new_tokens=8, eos_token_id=eos)
    srv.run_until_idle()
    assert st.status is RequestStatus.DONE
    np.testing.assert_array_equal(st.output(), want[0])


def test_tp_and_int8_kv_parity():
    """tp>1 + int8 KV arena: the sharded slot step (cache heads over tp,
    per-slot frontier vector through the shard-mapped decode kernel path)
    matches the tp-sharded single-request engine token-for-token."""
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    topo = MeshTopology(dims=ParallelDims(tp=2), devices=jax.devices()[:2])
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, topology=topo,
        kv_cache_dtype="int8", rng=jax.random.PRNGKey(4),
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 2, "token_budget": 8, "max_tokens": 64,
    })
    r = np.random.RandomState(3)
    prompts = [r.randint(0, 128, size=(n,)) for n in (5, 11)]
    states = [
        _submit(srv, f"q{i}", p, max_new_tokens=6)
        for i, p in enumerate(prompts)
    ]
    srv.run_until_idle()
    for i, (st, p) in enumerate(zip(states, prompts)):
        want = eng.generate(p[None, :], max_new_tokens=6, temperature=0.0)
        np.testing.assert_array_equal(st.output(), want[0], err_msg=f"q{i}")
    assert srv.step_traces == 1


def test_chunked_prefill_respects_token_budget():
    """Dynamic SplitFuse: a prompt longer than the budget prefills across
    steps (chunked), decodes interleave, and no step schedules more than
    token_budget real tokens."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(5)
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 2, "token_budget": 4, "max_tokens": 64,
    })
    r = np.random.RandomState(4)
    long_p = r.randint(0, 128, size=(11,))   # 3 chunks at budget 4
    short_p = r.randint(0, 128, size=(3,))
    st_long = _submit(srv, "long", long_p, max_new_tokens=4)
    st_short = _submit(srv, "short", short_p, max_new_tokens=6)
    per_step = []
    while srv.scheduler.has_work:
        before = srv.metrics.scheduled_tokens
        srv.step()
        per_step.append(srv.metrics.scheduled_tokens - before)
    assert max(per_step) <= 4
    assert st_long.status is RequestStatus.DONE
    assert st_short.status is RequestStatus.DONE
    for st, p, new in ((st_long, long_p, 4), (st_short, short_p, 6)):
        want = eng.generate(p[None, :], max_new_tokens=new, temperature=0.0)
        np.testing.assert_array_equal(st.output(), want[0])


# ---------------------------------------------------------------------------
# recompile counters
# ---------------------------------------------------------------------------
def test_lockstep_compile_cache_buckets_lengths():
    """Satellite: _build_decode programs are keyed on 128-bucketed
    (B, prompt, total) — a ragged length sweep compiles ONCE per bucket,
    observable via the new num_compiles counter."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(6)
    )
    r = np.random.RandomState(5)
    outs = {}
    for plen, new in [(4, 6), (7, 3), (11, 8), (5, 12), (9, 2)]:
        p = r.randint(0, 128, size=(1, plen))
        outs[(plen, new)] = eng.generate(p, max_new_tokens=new,
                                         temperature=0.0)
    assert eng.num_compiles == 1, eng.num_compiles  # one (1,128,128) bucket
    # greedy outputs still match the no-cache oracle for one of the legs
    p = r.randint(0, 128, size=(1, 6))
    out = eng.generate(p, max_new_tokens=5, temperature=0.0)
    ids = jnp.asarray(p)
    for _ in range(5):
        logits, _ = model.apply(eng.params, ids, dtype=jnp.float32)
        ids = jnp.concatenate(
            [ids, jnp.argmax(logits[:, -1], axis=-1)[:, None]], axis=1
        )
    np.testing.assert_array_equal(out, np.asarray(ids))
    assert eng.num_compiles == 1  # same bucket again


def test_spec_decode_compile_cache_buckets_lengths():
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, draft_model="ngram",
        rng=jax.random.PRNGKey(7),
    )
    plain = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, params=eng.params
    )
    r = np.random.RandomState(6)
    for plen, new in [(4, 8), (9, 5), (6, 10)]:
        p = r.randint(0, 128, size=(1, plen))
        got = eng.generate(p, max_new_tokens=new, num_draft_tokens=3)
        want = plain.generate(p, max_new_tokens=new, temperature=0.0)
        np.testing.assert_array_equal(got, want)
    assert eng.num_compiles == 1, eng.num_compiles


# ---------------------------------------------------------------------------
# scheduler invariants (fake clock, no device work)
# ---------------------------------------------------------------------------
def _sched(clock, **kw):
    d = dict(max_slots=2, token_budget=8, queue_limit=2,
             request_timeout_s=10.0, eviction_backoff_s=1.0, max_tokens=64,
             clock=clock, metrics=ServingMetrics(clock=clock))
    d.update(kw)
    return Scheduler(**d)


def _req(rid, plen=4, new=4, **kw):
    return Request(request_id=rid, prompt=np.arange(plen) % 7,
                   max_new_tokens=new, **kw)


def test_scheduler_admission_rejection_bounded_queue():
    clock = FakeClock()
    s = _sched(clock, max_slots=1, queue_limit=2)
    st0 = s.submit(_req("a"))
    assert s.plan() is not None        # admits "a" to the only slot
    st1 = s.submit(_req("b"))          # queue 1
    st2 = s.submit(_req("c"))          # queue 2 (the limit)
    st3 = s.submit(_req("d"))          # over the bound → graceful reject
    assert st0.status is RequestStatus.PREFILL
    assert st1.status is RequestStatus.QUEUED
    assert st2.status is RequestStatus.QUEUED
    assert st3.status is RequestStatus.EVICTED
    assert st3.evict_reason == "queue full"
    assert st3.retry_after == clock() + 1.0  # backoff hint, attempt 1
    assert s.metrics.rejected == 1


def test_scheduler_rejects_over_capacity_request():
    clock = FakeClock()
    s = _sched(clock, max_tokens=16)
    st = s.submit(_req("big", plen=14, new=8))  # 22 > 16
    assert st.status is RequestStatus.EVICTED
    assert "max_tokens" in st.evict_reason


def test_scheduler_timeout_eviction_with_backoff():
    clock = FakeClock()
    s = _sched(clock, max_slots=1, queue_limit=4, request_timeout_s=10.0)
    s.submit(_req("hog", new=30))
    assert s.plan() is not None        # hog takes the only slot
    st = s.submit(_req("waiter"))
    clock.advance(11.0)                # past request_timeout_s
    evicted = s.evict_timeouts()
    assert evicted == [st]
    assert st.status is RequestStatus.EVICTED
    assert st.evict_reason == "queue timeout"
    assert st.retry_after == pytest.approx(clock() + 1.0)
    # resubmission doubles the backoff (exponential)
    st2 = s.resubmit(st)
    assert st2 is st and st.status is RequestStatus.QUEUED
    assert st.attempts == 2
    clock.advance(11.0)
    s.evict_timeouts()
    assert st.status is RequestStatus.EVICTED
    assert st.retry_after == pytest.approx(clock() + 2.0)


def test_scheduler_slot_recycling():
    clock = FakeClock()
    s = _sched(clock, max_slots=1, queue_limit=4)
    st0 = s.submit(_req("first", plen=4, new=2))
    st1 = s.submit(_req("second", plen=3, new=2))
    slots_seen = []
    for _ in range(20):
        plan = s.plan()
        if plan is None:
            break
        clock.advance(0.01)
        for w in plan.work:
            slots_seen.append((w.state.request.request_id, w.slot))
        s.complete(plan, np.zeros(s.max_slots, np.int64))
    assert st0.status is RequestStatus.DONE
    assert st1.status is RequestStatus.DONE
    # both requests used the SAME recycled slot, one after the other
    assert {slot for _, slot in slots_seen} == {0}
    assert s.slots == [None] and len(s._free) == 1
    # the recycled slot arrives fresh both times (seen-row reset flag)
    first_steps = [r for r, _ in slots_seen]
    assert first_steps.index("second") > first_steps.index("first")


def test_scheduler_decode_round_robin_under_tight_budget():
    """token_budget < concurrent decodes: the rotating decode start must
    round-robin the budget so no slot starves (every request's token
    count keeps growing across a window of steps)."""
    clock = FakeClock()
    s = _sched(clock, max_slots=3, token_budget=1, queue_limit=8,
               max_tokens=64)
    # three slots mid-DECODE (fast-forward the lifecycle: prompt cached,
    # first token sampled) — the pure decode-contention scenario
    states = [s.submit(_req(f"d{i}", plen=2, new=20)) for i in range(3)]
    for st in states:
        assert st.status is RequestStatus.PREFILL  # eager admission
        st.prompt_pos = st.prompt_len
        st.transition(RequestStatus.DECODE)
        st.tokens.append(0)
    for _ in range(9):  # 3 full rotations of budget 1 over 3 decode slots
        plan = s.plan()
        assert plan is not None and plan.total_tokens == 1
        clock.advance(0.01)
        s.complete(plan, np.zeros(s.max_slots, np.int64))
    gains = [len(st.tokens) - 1 for st in states]
    assert gains == [3, 3, 3], gains  # perfectly fair, nobody starved


def test_overlap_budget_hbm_stream_window_excludes_hbm_roofline():
    """R8 for kind='hbm': an overlapped HBM stream shares the link that
    produces the HBM roofline term, so it may only hide under the MXU
    window — a stream that fits hbm_s but not compute_s must be flagged."""
    from deepspeed_tpu.analysis import lint_jaxpr

    def tiny(x):
        return (x * 2.0).sum()

    closed = jax.make_jaxpr(tiny)(jnp.zeros((8, 8), jnp.float32))
    # a tiny program's MXU window is ~0: any real HBM stream is exposed
    streams = {
        "kv": {"kind": "hbm", "bytes_per_step": 64 * (1 << 30),
               "overlapped": True},
    }
    findings = lint_jaxpr(closed, streams=streams, source="hbm-r8")
    assert any(f.rule == "R8" for f in findings), [f.format() for f in findings]
    # the serving engine's actual declaration (overlapped: False) is silent
    streams["kv"]["overlapped"] = False
    assert lint_jaxpr(closed, streams=streams, source="hbm-r8-off") == []


def test_request_lifecycle_rejects_illegal_transition():
    from deepspeed_tpu.serving.request import RequestState

    st = RequestState(request=_req("x"))
    with pytest.raises(ValueError, match="illegal transition"):
        st.transition(RequestStatus.DECODE)  # QUEUED -> DECODE skips PREFILL
    st.transition(RequestStatus.PREFILL)
    st.transition(RequestStatus.DECODE)
    st.transition(RequestStatus.DONE)
    with pytest.raises(ValueError, match="illegal transition"):
        st.transition(RequestStatus.QUEUED)


def test_request_rng_deterministic():
    from deepspeed_tpu.serving.request import request_rng

    k1 = np.asarray(request_rng("req-1"))
    k1b = np.asarray(request_rng("req-1"))
    k2 = np.asarray(request_rng("req-2"))
    np.testing.assert_array_equal(k1, k1b)
    assert (k1 != k2).any()


# ---------------------------------------------------------------------------
# per-slot kernel + sampling-hazard units
# ---------------------------------------------------------------------------
def test_decode_attention_kernel_per_slot_cache_len():
    """The kernel's [B] frontier vector: every row predicates at its own
    length — matches the per-row masked fp32 reference."""
    from deepspeed_tpu.ops.pallas.decode_attention import (
        decode_attention_kernel,
    )

    B, Smax, H, KV, hd = 3, 512, 4, 2, 64
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, 1, H, hd), jnp.float32)
    kc = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32)
    vc = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32)
    lens = jnp.asarray([5, 300, 0], jnp.int32)
    out = decode_attention_kernel(q, kc, vc, lens)
    kf = jnp.repeat(kc, H // KV, axis=2)
    vf = jnp.repeat(vc, H // KV, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(hd)
    kpos = jnp.arange(Smax)[None, None, None, :]
    logits = jnp.where(kpos <= lens[:, None, None, None], logits, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), vf)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ragged_forward_per_slot_cache_len_matches_scalar():
    """forward_with_cache with a [B] frontier == per-row scalar runs (the
    cross-cutting model change), incl. the int8 scale caches."""
    from deepspeed_tpu.models.decoding import forward_with_cache, init_cache

    model = tiny_llama()
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0), dtype=jnp.float32)
    r = np.random.RandomState(7)
    toks = jnp.asarray(r.randint(0, 128, size=(3, 4)))
    lens = [0, 5, 9]
    for quant in (False, True):
        # ragged: one batched call with per-row frontiers over a shared
        # pre-seeded cache
        seed = jnp.asarray(r.randint(0, 128, size=(3, 16)))
        cache = init_cache(cfg, 3, 32, jnp.float32, quantized=quant)
        _, cache = forward_with_cache(cfg, params, seed, cache, 0,
                                      dtype=jnp.float32)
        ragged_logits, _ = forward_with_cache(
            cfg, params, toks, cache, jnp.asarray(lens, jnp.int32),
            dtype=jnp.float32,
        )
        for b, ln in enumerate(lens):
            cache_b = init_cache(cfg, 1, 32, jnp.float32, quantized=quant)
            _, cache_b = forward_with_cache(
                cfg, params, seed[b:b + 1], cache_b, 0, dtype=jnp.float32
            )
            # traced scalar frontier: keeps the reference on the same
            # cache-read attention path as the ragged call (a python int 0
            # would take the fresh-prefill branch, which attends the exact
            # unquantized k/v instead of the int8 cache)
            row_logits, _ = forward_with_cache(
                cfg, params, toks[b:b + 1], cache_b,
                jnp.asarray(ln, jnp.int32), dtype=jnp.float32,
            )
            np.testing.assert_allclose(
                np.asarray(ragged_logits[b]), np.asarray(row_logits[0]),
                rtol=2e-4, atol=2e-4, err_msg=f"quant={quant} row={b}",
            )


def test_unscheduled_active_slot_never_clobbers_live_cache():
    """An ACTIVE slot the plan leaves idle (num_new=0) must not write its
    padded chunk over live cache rows: the engine repoints idle rows'
    start_pos at the dead tail margin. Guards future scheduling policies
    (preemption, priority) that may skip a live slot mid-flight."""
    from deepspeed_tpu.serving.scheduler import StepPlan

    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(9)
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 2, "token_budget": 4, "max_tokens": 64,
    })
    # one prefill chunk lands tokens at slot-0 positions 0..3
    _submit(srv, "p0", np.random.RandomState(8).randint(0, 128, (6,)),
            max_new_tokens=4)
    srv.step()
    live = srv.capacity - srv.token_budget
    before = np.asarray(srv._caches["k"])[:, 0, :live].copy()
    # adversarial plan: slot 0 is active but unscheduled (all zeros — the
    # plan-default start_pos of 0 would point straight at live rows)
    N, W = srv.max_slots, srv.token_budget
    idle = StepPlan(
        tokens=np.zeros((N, W), np.int32), num_new=np.zeros(N, np.int32),
        start_pos=np.zeros(N, np.int32), fresh=np.zeros(N, np.bool_),
        sample=np.zeros(N, np.bool_),
    )
    srv._run_plan(idle)
    after = np.asarray(srv._caches["k"])[:, 0, :live]
    np.testing.assert_array_equal(before, after)


def test_metrics_submitted_counts_rejections():
    """Every submission counts as submitted — including graceful
    rejections — so 'submitted >= rejected' always holds."""
    clock = FakeClock()
    s = _sched(clock, max_slots=1, queue_limit=1, max_tokens=16)
    s.submit(_req("a"))                      # straight to the slot
    s.submit(_req("b"))                      # queued (limit 1)
    s.submit(_req("c"))                      # queue full → rejected
    s.submit(_req("big", plen=14, new=8))    # over capacity → evicted
    m = s.metrics
    assert m.submitted == 4
    assert m.rejected == 1 and m.evicted == 2
    assert m.submitted >= m.rejected


def test_apply_repetition_penalty_active_mask():
    """Satellite: inactive/padded slots keep their logits untouched."""
    from deepspeed_tpu.inference.engine import apply_repetition_penalty

    logits = jnp.asarray([[2.0, -2.0], [2.0, -2.0]])
    seen = jnp.asarray([[True, True], [True, True]])
    out = np.asarray(apply_repetition_penalty(
        logits, seen, 2.0, active=jnp.asarray([True, False])
    ))
    np.testing.assert_allclose(out, [[1.0, -4.0], [2.0, -2.0]])


# ---------------------------------------------------------------------------
# config / metrics / analytic streams
# ---------------------------------------------------------------------------
def test_serving_config_section_parses_and_validates():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    cfg = DeepSpeedConfig({
        "serving": {"enabled": True, "max_slots": 4, "token_budget": 32,
                    "kv_cache_dtype": "int8"},
    })
    assert cfg.serving.enabled and cfg.serving.max_slots == 4
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"serving": {"token_budget": 0}})
    with pytest.raises(DeepSpeedConfigError):
        DeepSpeedConfig({"serving": {"kv_cache_dtype": "fp8"}})


def test_serving_metrics_and_kv_stream_intake():
    """Metrics TTFT/TPOT populate and the analytic KV stream flows
    through comm_logger.record_streams (the shared intake)."""
    from deepspeed_tpu.profiling.comm_logger import CommsLogger

    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(8)
    )
    logger = CommsLogger()
    try:
        srv = ServingEngine(engine=eng, comm_logger=logger, serving={
            "max_slots": 2, "token_budget": 8, "max_tokens": 64,
        })
        _submit(srv, "m0", np.arange(5) % 7, max_new_tokens=4)
        srv.run_until_idle()
    finally:
        logger.stop()
    m = srv.metrics.snapshot()
    assert m["finished"] == 1 and m["tokens_out"] == 4
    assert m["ttft_p50_s"] >= 0 and m["tpot_p50_s"] >= 0
    assert "tok/s" in srv.metrics.summary()
    # the KV arena stream was recorded per step through the ONE intake
    assert logger.kv_steps == srv.metrics.steps > 0
    assert logger.kv_bytes > 0
    assert "serving kv arena" in logger.summary()
    # the declared stream itself carries the schema the planner reads
    streams = srv.analytic_streams()
    kv = streams["kv_cache"]
    assert kv["kind"] == "hbm" and kv["bytes_per_step"] > 0
    assert kv["per_device_bytes_per_step"] <= kv["bytes_per_step"]


def test_lint_serving_config_traces_and_passes():
    """shardlint's serving branch: the slot step traces abstractly on a
    tp=2 CPU mesh and lints clean (R1–R8), with the KV stream attached."""
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.analysis import lint_config

    comm.destroy_process_group()
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    report = lint_config(
        {
            "tensor_parallel": {"tp_size": 2},
            "serving": {"enabled": True, "max_slots": 2, "token_budget": 8,
                        "max_tokens": 64, "kv_cache_dtype": "int8"},
        },
        model=model,
        source="serving-unit",
    )
    assert report.ok, report.format()
    assert report.sources and report.sources[0]["source"] == "serving-unit"
