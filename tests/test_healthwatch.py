"""healthwatch: goodput accounting, anomaly watchdogs, flight recorder
(ISSUE 11).

The tentpole contract: injected NaN loss, loss spike, forced recompile
and a serving queue breach are each detected within one step/tick and
produce a schema-valid postmortem containing the triggering step's
spans; disabled healthwatch allocates zero health state, performs zero
device-scalar taps, and reproduces the baseline loss trajectory
bitwise. Satellites ride along: drift.check_pair (ONE "drifted"
definition), serving-metrics empty-window hardening, and train/mfu
through the registry.
"""

import importlib.util
import json
import math
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.analysis.cost import drift
from deepspeed_tpu.models import llama
from deepspeed_tpu.profiling import healthwatch, steptrace
from deepspeed_tpu.profiling.healthwatch import HealthWatch, MetricsExporter
from deepspeed_tpu.serving import Request, ServingEngine
from deepspeed_tpu.serving.metrics import (FleetMetrics, ServingMetrics,
                                           percentile,
                                           recent_percentile)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "healthwatch_tool", os.path.join(REPO, "tools", "healthwatch.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_state():
    steptrace.reset()
    healthwatch.reset()
    yield
    steptrace.reset()
    healthwatch.reset()


def tiny_llama():
    return llama(
        "llama-tiny", vocab_size=64, max_seq_len=32, hidden_size=16,
        num_layers=1, num_heads=2, num_kv_heads=2, head_dim=8,
        intermediate_size=32,
    )


def tiny_engine(hw_section=None, **extra_cfg):
    cfg = {
        "train_batch_size": 8,  # divides the 8-device CPU test mesh
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "steps_per_print": 1000,
        **extra_cfg,
    }
    if hw_section is not None:
        cfg["healthwatch"] = hw_section
    engine, *_ = deepspeed_tpu.initialize(model=tiny_llama(), config=cfg)
    return engine


def train_data(seed=0, seq=32, batch=8):
    return {"input_ids": np.random.RandomState(seed).randint(
        0, 64, size=(batch, seq))}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def synthetic_hw(rules=None, source="train", **over):
    cfg = {"enabled": True, "ring_steps": over.pop("ring_steps", 32),
           "install_signal_handler": False,
           "rules": rules or {}, **over}
    clk = FakeClock()
    return HealthWatch(cfg, None, source=source, clock=clk), clk


# ---------------------------------------------------------------------------
# zero-overhead oracle (acceptance): disabled => no health state, no
# device taps, bitwise-identical loss trajectory
# ---------------------------------------------------------------------------
def test_disabled_is_zero_overhead_and_bitwise():
    data = train_data()

    def run(hw_section):
        healthwatch.reset()
        steptrace.reset()
        engine = tiny_engine(hw_section)
        losses = [np.asarray(engine.train_batch(batch=data))
                  for _ in range(3)]
        hw = engine.healthwatch
        engine.destroy()
        return losses, hw

    taps0 = healthwatch.device_taps()
    base, hw = run(None)                       # no healthwatch section
    assert hw is None
    assert healthwatch.device_taps() == taps0  # zero device-scalar taps
    assert steptrace.get_registry() is None    # zero spans allocated

    off, hw = run({"enabled": False})          # explicit disabled
    assert hw is None
    assert healthwatch.device_taps() == taps0
    assert steptrace.get_registry() is None

    on, hw = run({"enabled": True, "install_signal_handler": False})
    assert hw is not None and len(hw.ring) == 3
    assert healthwatch.device_taps() > taps0   # the watched run taps

    for a, b, c in zip(base, off, on):
        # the health layer never touches the compiled program: all three
        # trajectories are the same float32 bits
        assert a.tobytes() == b.tobytes() == c.tobytes()


# ---------------------------------------------------------------------------
# seeded-fault oracle: NaN + recompile on a real engine
# ---------------------------------------------------------------------------
def test_nan_and_recompile_detected_with_postmortem(tmp_path):
    pm_path = str(tmp_path / "pm.json")
    engine = tiny_engine({
        "enabled": True, "ring_steps": 8, "postmortem_path": pm_path,
        "install_signal_handler": False,
    })
    hw = engine.healthwatch
    data = train_data()
    for _ in range(2):
        engine.train_batch(batch=data)
    assert hw.events == []                     # clean warmup: no firing

    # forced recompile: a new input shape retraces the step program
    short = train_data(seed=1, seq=16)
    engine.train_batch(batch=short)
    fired = [e["rule"] for e in hw.events]
    assert "recompile" in fired                # detected within one step
    assert hw.ring[-1]["compiled"] >= 1

    # injected NaN loss: poison the params
    engine.state.params = jax.tree.map(
        lambda x: x * jnp.nan, engine.state.params
    )
    engine.train_batch(batch=short)
    fired = [e["rule"] for e in hw.events]
    assert "nonfinite_loss" in fired and "nonfinite_grad" in fired
    nan_ev = next(e for e in hw.events if e["rule"] == "nonfinite_loss")
    assert nan_ev["step"] == hw.ring[-1]["step"]  # within one step
    assert hw.ring[-1]["spans"], "triggering step must carry its spans"

    # the dump action left a schema-valid postmortem
    assert os.path.exists(pm_path)
    tool = _load_tool()
    kind, pm = tool.load(pm_path)
    assert kind == "postmortem"
    assert tool.validate_postmortem(pm) == []
    assert pm["reason"].startswith("watchdog:nonfinite_")
    assert tool.main(["--validate", pm_path]) == 0
    assert tool.main([pm_path]) == 0           # render table runs
    # health/* events landed in the registry (one namespace with
    # train/* — the monitor bridge sees them too)
    reg = steptrace.get_registry()
    tags = {t for t, _v, _s, _t in reg.samples}
    assert "health/nonfinite_loss" in tags and "health/goodput" in tags
    engine.destroy()


# ---------------------------------------------------------------------------
# synthetic watchdogs: spike / explosion / step-time / plan drift / ring
# ---------------------------------------------------------------------------
def test_loss_spike_and_grad_explosion():
    hw, clk = synthetic_hw(rules={
        "loss_spike": {"min_samples": 5, "zscore": 6.0},
        "grad_explosion": {"min_samples": 5, "factor": 10.0},
    })
    for i in range(8):
        hw.on_step_start()
        clk.advance(0.1)
        hw.on_train_step(step=i + 1, loss=2.0 + 0.01 * (i % 2),
                         grad_norm=1.0)
    assert hw.events == []
    hw.on_step_start()
    clk.advance(0.1)
    hw.on_train_step(step=9, loss=50.0, grad_norm=40.0)
    fired = [e["rule"] for e in hw.events]
    assert "loss_spike" in fired and "grad_explosion" in fired
    spike = next(e for e in hw.events if e["rule"] == "loss_spike")
    assert spike["step"] == 9                  # detected within one step


def test_step_time_regression_and_plan_drift():
    hw, clk = synthetic_hw(rules={
        "step_time_regression": {"min_samples": 3, "factor": 2.0},
        "plan_drift": {"min_samples": 3, "window": 4},
    })
    hw.set_prediction(0.1, "cpu")  # cpu band [1/25, 25] (check_pair)
    for i in range(4):
        hw.on_step_start()
        clk.advance(0.1)           # measured ~= predicted: drift ok
        hw.on_train_step(step=i + 1, loss=2.0, grad_norm=1.0)
    assert [e["rule"] for e in hw.events] == []
    # a 10x slower step trips the trailing-window regression
    hw.on_step_start()
    clk.advance(1.0)
    hw.on_train_step(step=5, loss=2.0, grad_norm=1.0)
    assert "step_time_regression" in [e["rule"] for e in hw.events]
    # drive measured far outside even the cpu band -> live drift alarm
    for i in range(6):
        hw.on_step_start()
        clk.advance(30.0)          # predicted/measured ~ 1/300 < 1/25
        hw.on_train_step(step=6 + i, loss=2.0, grad_norm=1.0)
    drift_ev = [e for e in hw.events if e["rule"] == "plan_drift"]
    assert drift_ev, "live drift alarm must fire outside the band"
    assert list(drift_ev[0]["threshold"]) == [
        pytest.approx(1 / 25.0), pytest.approx(25.0)
    ]


def test_ring_is_bounded_and_disabled_rules_stay_quiet():
    hw, clk = synthetic_hw(ring_steps=4, rules={
        "loss_spike": False,       # bool shorthand disables a rule
        "step_time_regression": {"enabled": False},
    })
    for i in range(10):
        hw.on_step_start()
        clk.advance(0.001 if i < 9 else 10.0)
        hw.on_train_step(step=i + 1, loss=1.0 if i < 9 else 1e9,
                         grad_norm=1.0)
    assert len(hw.ring) == 4                   # bounded flight recorder
    fired = {e["rule"] for e in hw.events}
    assert "loss_spike" not in fired
    assert "step_time_regression" not in fired


# ---------------------------------------------------------------------------
# goodput classification
# ---------------------------------------------------------------------------
def test_goodput_bucket_classification():
    reg = steptrace.MetricsRegistry()
    hw = HealthWatch({"enabled": True, "install_signal_handler": False},
                     reg, source="train")
    hw._comm_est_s = 0.4   # statically-priced unoverlapped wire seconds
    t0 = reg.clock()
    reg.add_span("train/device", "train", t0, t0 + 1.0)
    reg.add_span("train/dispatch", "train", t0, t0 + 0.5,
                 args={"traced": 1})
    reg.add_span("train/dispatch", "train", t0, t0 + 0.25)   # no retrace
    reg.add_span("train/input_wait", "train", t0, t0 + 0.2)
    reg.add_span("train/checkpoint", "train", t0, t0 + 0.3)
    reg.add_span("train/offload_swap_in", "train", t0, t0 + 0.1)
    hw.on_step_start()
    hw.on_train_step(step=1, loss=1.0, grad_norm=1.0)
    b = hw.goodput()["buckets"]
    assert b["compute"] == pytest.approx(0.6, abs=1e-6)       # 1.0 - comm
    assert b["comm_exposed"] == pytest.approx(0.5, abs=1e-6)  # 0.4 + swap
    assert b["compile"] == pytest.approx(0.5, abs=1e-6)       # traced only
    assert b["stall_on_data"] == pytest.approx(0.2, abs=1e-6)
    assert b["checkpoint"] == pytest.approx(0.3, abs=1e-6)
    assert 0.0 <= hw.goodput_fraction() <= 1.0


def test_comm_estimate_only_prices_unoverlapped_wire():
    hw, _clk = synthetic_hw()
    hw.set_comm_estimate_from_streams({
        "kv_cache": {"kind": "hbm", "overlapped": False,
                     "bytes_per_step": 1 << 30},   # compute traffic: no
        "tp_ring": {"kind": "ici", "overlapped": True,
                    "bytes_per_step": 1 << 30},    # hidden wire: no
        "moe_a2a": {"kind": "ici", "overlapped": False,
                    "bytes_per_step": 1 << 30},    # exposed wire: YES
    })
    assert hw._comm_est_s > 0
    only_hidden = synthetic_hw()[0]
    only_hidden.set_comm_estimate_from_streams({
        "kv_cache": {"kind": "hbm", "overlapped": False,
                     "bytes_per_step": 1 << 30},
        "tp_ring": {"kind": "ici", "overlapped": True,
                    "bytes_per_step": 1 << 30},
    })
    assert only_hidden._comm_est_s == 0.0


# ---------------------------------------------------------------------------
# serving: queue breach + goodput in the metrics surface
# ---------------------------------------------------------------------------
def test_serving_queue_breach_detected_and_postmortem(tmp_path):
    pm_path = str(tmp_path / "pm_serve.json")
    engine = deepspeed_tpu.init_inference(
        tiny_llama(), dtype=jnp.float32, max_tokens=32,
        rng=jax.random.PRNGKey(0),
    )
    srv = ServingEngine(
        engine=engine,
        serving={"max_slots": 2, "token_budget": 16, "queue_limit": 16,
                 "max_tokens": 32},
        healthwatch={
            "enabled": True, "ring_steps": 16,
            "postmortem_path": pm_path,
            "install_signal_handler": False,
            "rules": {"queue_depth_breach": {"threshold": 1,
                                             "action": "dump"}},
        },
    )
    for i in range(6):
        srv.submit(Request(request_id=f"r{i}",
                           prompt=np.arange(4) % 32,
                           max_new_tokens=3))
    finished = srv.run_until_idle()
    assert len(finished) == 6                  # the replay still drains
    assert srv.step_traces == 1                # and never recompiles
    hw = srv.healthwatch
    assert hw.counters.get("queue_depth_breach", 0) >= 1
    first = next(e for e in hw.events
                 if e["rule"] == "queue_depth_breach")
    assert first["step"] == 1                  # breach seen on tick one
    snap = srv.metrics.snapshot()
    assert "goodput" in snap and math.isfinite(snap["goodput"])
    assert "goodput=" in srv.metrics.summary()

    tool = _load_tool()
    kind, pm = tool.load(pm_path)
    assert kind == "postmortem"
    assert tool.validate_postmortem(pm) == []
    assert pm["reason"] == "watchdog:queue_depth_breach"
    assert pm["source"] == "serve"
    trig = next(r for r in pm["steps"] if r["step"] == first["step"])
    assert trig["spans"], "triggering tick must carry its spans"
    # dump is debounced: a breach persisting across consecutive ticks
    # writes one postmortem per episode, not one per tick
    assert hw.dump_count < hw.counters["queue_depth_breach"] \
        or hw.counters["queue_depth_breach"] == 1


# ---------------------------------------------------------------------------
# exporter
# ---------------------------------------------------------------------------
def test_exporter_prom_and_jsonl(tmp_path):
    reg = steptrace.MetricsRegistry()
    reg.sample("train/loss", 2.5, step=1)
    reg.sample("serve/tokens_per_s", 10.0, step=1)
    prom = MetricsExporter(str(tmp_path / "h.prom"), interval_s=0.0)
    prom.flush(reg, extra={"health/goodput": 0.5})
    text = open(tmp_path / "h.prom").read()
    assert "dstpu_train_loss 2.5" in text
    assert "dstpu_serve_tokens_per_s 10" in text
    assert "dstpu_health_goodput 0.5" in text
    # a second flush rewrites (textfile-collector contract), and the
    # incremental cursor picks up only NEW samples
    reg.sample("train/loss", 3.5, step=2)
    prom.flush(reg)
    text = open(tmp_path / "h.prom").read()
    assert "dstpu_train_loss 3.5" in text and "2.5" not in text

    jl = MetricsExporter(str(tmp_path / "h.jsonl"), interval_s=0.0)
    jl.flush(reg, extra={"health/goodput": 0.25})
    jl.flush(reg)
    rows = [json.loads(x) for x in open(tmp_path / "h.jsonl")]
    assert len(rows) == 2
    assert rows[-1]["metrics"]["train/loss"] == 3.5
    tool = _load_tool()
    kind, payload = tool.load(str(tmp_path / "h.jsonl"))
    assert kind == "metrics_jsonl" and len(payload) == 2
    assert tool.main([str(tmp_path / "h.jsonl")]) == 0
    kind, payload = tool.load(str(tmp_path / "h.prom"))
    assert kind == "metrics_prom"
    assert payload["dstpu_train_loss"] == 3.5


def test_saturated_registry_rotates_instead_of_freezing(tmp_path):
    # an always-on watch must keep seeing NEW spans and samples past the
    # bounded registry's cap — saturation reclaims the drained buffers
    reg = steptrace.MetricsRegistry(max_spans=8)
    hw = HealthWatch({"enabled": True, "install_signal_handler": False},
                     reg, source="train")
    for i in range(5):
        for _ in range(4):  # 4 spans/step > cap/steps: saturates fast
            reg.begin("train/device", "train").end()
        hw.on_step_start()
        hw.on_train_step(step=i + 1, loss=2.0, grad_norm=1.0)
    assert hw.rotations >= 1
    # compute kept accruing across the rotation — nothing froze
    assert hw.ring[-1]["spans"], "spans still drained after saturation"
    assert hw.buckets["compute"] > 0
    exp = MetricsExporter(str(tmp_path / "h.jsonl"), interval_s=0.0)
    for i in range(20):
        reg.sample("train/loss", float(i), step=i)
        exp.flush(reg)
    assert len(reg.samples) < reg.max_spans  # reclaimed, not frozen
    rows = [json.loads(x) for x in open(tmp_path / "h.jsonl")]
    assert rows[-1]["metrics"]["train/loss"] == 19.0  # latest, not stale


def test_sigterm_chain_respects_sig_ign():
    import signal

    assert healthwatch._on_sigterm.__module__  # sanity: import surface
    healthwatch._PREV_SIGTERM = signal.SIG_IGN
    try:
        # a process that deliberately ignored SIGTERM must keep ignoring
        # it after the evidence dump — no SystemExit
        healthwatch._on_sigterm(signal.SIGTERM, None)
        with pytest.raises(SystemExit):
            healthwatch._PREV_SIGTERM = signal.SIG_DFL
            healthwatch._on_sigterm(signal.SIGTERM, None)
    finally:
        healthwatch._PREV_SIGTERM = None


def test_exporter_interval_throttles(tmp_path):
    clk = FakeClock()
    exp = MetricsExporter(str(tmp_path / "h.jsonl"), interval_s=10.0,
                          clock=clk)
    assert exp.maybe_flush(None, extra={"a": 1.0})  # first always flushes
    assert not exp.maybe_flush(None, extra={"a": 2.0})  # inside interval
    clk.advance(11.0)
    assert exp.maybe_flush(None, extra={"a": 3.0})
    assert exp.flushes == 2


# ---------------------------------------------------------------------------
# postmortem handlers + validation gate
# ---------------------------------------------------------------------------
def test_sigterm_and_crash_dumps(tmp_path):
    pm_path = str(tmp_path / "pm.json")
    hw, clk = synthetic_hw(postmortem_path=pm_path)
    hw.on_step_start()
    clk.advance(0.1)
    hw.on_train_step(step=1, loss=2.0, grad_norm=1.0)
    healthwatch._dump_all("sigterm")
    pm = json.load(open(pm_path))
    assert pm["reason"] == "sigterm" and len(pm["steps"]) == 1
    # the chained excepthook dumps with a crash reason, then delegates
    healthwatch._excepthook(ValueError, ValueError("boom"), None)
    pm = json.load(open(pm_path))
    assert pm["reason"] == "crash:ValueError"
    tool = _load_tool()
    assert tool.validate_postmortem(pm) == []


def test_validate_rejects_truncated_and_malformed(tmp_path):
    tool = _load_tool()
    fixture = os.path.join(REPO, "tests", "fixtures",
                           "postmortem_truncated.json")
    assert tool.main(["--validate", fixture]) == 1  # truncated: exit 1
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": "healthwatch.postmortem.v1",
                               "reason": "explicit"}))
    assert tool.main(["--validate", str(bad)]) == 1  # missing sections
    # a watchdog reason without the substantiating anomaly/step fails
    hw, clk = synthetic_hw()
    pm = hw.postmortem("watchdog:nonfinite_loss")
    problems = tool.validate_postmortem(pm)
    assert any("nonfinite_loss" in p for p in problems)


def test_raise_action_dumps_then_raises(tmp_path):
    pm_path = str(tmp_path / "pm.json")
    hw, clk = synthetic_hw(
        postmortem_path=pm_path,
        rules={"nonfinite_loss": {"action": "raise"}},
    )
    hw.on_step_start()
    clk.advance(0.1)
    with pytest.raises(healthwatch.HealthwatchAnomaly):
        hw.on_train_step(step=1, loss=float("nan"), grad_norm=1.0)
    assert os.path.exists(pm_path)  # evidence first, then the crash


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------
def test_check_pair_is_the_one_drift_definition():
    ok = drift.check_pair(1.0, 1.0, "v5e")
    assert ok["ok"] and ok["ratio"] == 1.0
    assert ok["band"] == (0.5, 2.0)
    cpu = drift.check_pair(1.0, 20.0, "cpu")
    assert cpu["ok"]                          # cpu band is [1/25, 25]
    assert not drift.check_pair(1.0, 30.0, "cpu")["ok"]
    # unmeasurable pairs are drifted-by-definition, never a crash
    assert not drift.check_pair(1.0, 0.0, "v5e")["ok"]
    assert drift.check_pair(1.0, None, "v5e")["ratio"] is None
    # precomputed-ratio form (the ledger gate's path) agrees
    assert drift.check_pair(None, None, "v5e", ratio=1.9)["ok"]
    assert not drift.check_pair(None, None, "v5e", ratio=2.1)["ok"]
    # drift.check() consults the same predicate: a ratio inside the
    # band passes, outside fails
    ok_, problems = drift.check([{"source": "t", "gen": "v5e",
                                  "ratio": 1.9}])
    assert ok_ and not problems
    ok_, problems = drift.check([{"source": "t", "gen": "v5e",
                                  "ratio": 2.1}])
    assert not ok_ and "outside" in problems[0]


def test_serving_metrics_empty_window_never_nan():
    m = ServingMetrics(clock=lambda: 0.0)
    snap = m.snapshot()
    # no requests completed yet: every reported value is finite
    assert all(math.isfinite(float(v)) for v in snap.values())
    # integer counters keep their type (the snapshot JSON shape is
    # stable: "submitted": 0, not 0.0)
    assert isinstance(snap["submitted"], int)
    assert isinstance(snap["queue_depth"], int)
    assert isinstance(snap["slot_occupancy"], float)
    assert "nan" not in m.summary().lower()
    # the percentile helpers drop poisoned samples instead of
    # propagating them
    assert percentile([], 95) == 0.0
    assert percentile([float("nan"), float("inf"), 1.0], 95) == 1.0
    assert recent_percentile([], 95) is None
    assert recent_percentile([float("nan")], 95) is None
    assert recent_percentile([0.1] * 50 + [0.5], 95, window=4) == 0.5
    # a NaN that sneaks into a sample list cannot reach the bridge
    m.ttft_s.extend([float("nan"), 0.25])
    snap = m.snapshot()
    assert snap["ttft_p95_s"] == 0.25
    events = []

    class FakeMonitor:
        def write_events(self, evs):
            events.extend(evs)

    m.write_to(FakeMonitor(), step=1)
    assert events and all(math.isfinite(v) for _t, v, _s in events)


def test_train_mfu_reaches_registry():
    engine = tiny_engine(None, steptrace={"enabled": True},
                         steps_per_print=1)
    data = train_data()
    for _ in range(4):
        engine.train_batch(batch=data)
    reg = steptrace.get_registry()
    tags = {t for t, _v, _s, _t in reg.samples}
    # MFU rides the train/* namespace next to loss (and, with
    # healthwatch on, next to train/goodput) — one export
    assert "train/loss" in tags
    assert "train/mfu" in tags
    mfu = [v for t, v, _s, _t in reg.samples if t == "train/mfu"]
    assert all(0.0 <= v for v in mfu) and math.isfinite(mfu[-1])
    engine.destroy()


# ---------------------------------------------------------------------------
# serving: zero_progress livelock watchdog (the runtime twin of
# fleetcheck's LIVELOCK oracle — docs/modelcheck.md)
# ---------------------------------------------------------------------------
class _ServeMetrics:
    """Duck-typed metrics carrying exactly what on_serve_step reads."""

    def __init__(self):
        self.queue_depth = 0
        self.ttft_s = []
        self.tokens_out = 0
        self.scheduled_tokens = 0
        self.slot_occupancy = 1.0


def test_zero_progress_watchdog_on_fake_clock():
    hw, clk = synthetic_hw(
        rules={"zero_progress": {"window": 4}}, source="serve")
    m = _ServeMetrics()
    # progressing ticks: counters move -> streak never builds
    for step in range(6):
        m.tokens_out += 2
        hw.on_serve_step(step, metrics=m)
        clk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) == 0
    # prefill-only progress (scheduled but nothing emitted yet) is
    # still progress: no fire
    for step in range(6, 10):
        m.scheduled_tokens += 4
        hw.on_serve_step(step, metrics=m)
        clk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) == 0
    # frozen counters with occupied slots: fires once per full window
    for step in range(10, 19):
        hw.on_serve_step(step, metrics=m)
        clk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) == 2  # 8 stalls, w=4
    ev = next(e for e in hw.events if e["rule"] == "zero_progress")
    assert "livelock" in ev["detail"]
    assert ev["value"] == 4 and ev["threshold"] == 4


def test_zero_progress_ignores_idle_and_rearms():
    hw, clk = synthetic_hw(
        rules={"zero_progress": {"window": 3}}, source="serve")
    m = _ServeMetrics()
    m.slot_occupancy = 0.0
    # idle fleet: frozen counters with NO slotted work is not a stall
    for step in range(8):
        hw.on_serve_step(step, metrics=m)
        clk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) == 0
    # work appears and wedges -> fire; progress resumes -> streak drops
    m.slot_occupancy = 0.5
    for step in range(8, 12):
        hw.on_serve_step(step, metrics=m)
        clk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) == 1
    m.tokens_out += 1
    hw.on_serve_step(12, metrics=m)
    for step in range(13, 15):
        hw.on_serve_step(step, metrics=m)
        clk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) == 1  # streak restarted


def test_zero_progress_reads_fleet_metrics_ducktype():
    # FleetMetrics aggregates the zero_progress trio across replicas;
    # the watchdog must see fleet-wide freeze, not per-replica noise
    m0, m1 = ServingMetrics(), ServingMetrics()
    clk = FakeClock()
    fleet = FleetMetrics([m0, m1], clock=clk)
    assert fleet.tokens_out == 0 and fleet.scheduled_tokens == 0
    m0.tokens_out, m1.tokens_out = 3, 4
    m0.scheduled_tokens, m1.scheduled_tokens = 10, 0
    m0.slot_occupancy, m1.slot_occupancy = 1.0, 0.0
    assert fleet.tokens_out == 7
    assert fleet.scheduled_tokens == 10
    assert fleet.slot_occupancy == 0.5

    hw, hclk = synthetic_hw(
        rules={"zero_progress": {"window": 2}}, source="serve")
    for step in range(4):
        hw.on_serve_step(step, metrics=fleet)
        hclk.advance(0.01)
    assert hw.counters.get("zero_progress", 0) >= 1
