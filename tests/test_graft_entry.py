"""Keep the driver entry points working."""

import sys

import jax

sys.path.insert(0, ".")


def test_entry_compiles():
    import __graft_entry__

    fn, args = __graft_entry__.entry()
    loss = jax.jit(fn)(*args)
    assert float(loss) > 0


def test_dryrun_multichip(devices8):
    import __graft_entry__

    __graft_entry__.dryrun_multichip(8)
