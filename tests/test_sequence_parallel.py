"""Sequence parallelism (SURVEY §2.3): ring attention == dense reference;
Ulysses engine loss parity with a dp-only run."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from conftest import xfail_legacy_partial_manual
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import llama
from deepspeed_tpu.models.sharding import use_topology
from deepspeed_tpu.ops.attention import xla_attention
from deepspeed_tpu.parallel.sequence import (
    ring_attention,
    set_sp_mode,
    ulysses_attention,
)


def rand_qkv(B=2, S=32, H=4, KV=4, hd=8, seed=0):
    r = np.random.RandomState(seed)
    q = jnp.asarray(r.randn(B, S, H, hd), jnp.float32)
    k = jnp.asarray(r.randn(B, S, KV, hd), jnp.float32)
    v = jnp.asarray(r.randn(B, S, KV, hd), jnp.float32)
    return q, k, v


@xfail_legacy_partial_manual
@pytest.mark.parametrize("kv_heads", [4, 2])
def test_ring_attention_matches_dense(kv_heads):
    q, k, v = rand_qkv(KV=kv_heads)
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))
    ref = xla_attention(q, k, v, causal=True)
    got = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, causal=True, topo=topo)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_attention_non_causal():
    q, k, v = rand_qkv(seed=1)
    topo = MeshTopology(dims=ParallelDims(sp=8))
    ref = xla_attention(q, k, v, causal=False)
    got = jax.jit(
        lambda a, b, c: ring_attention(a, b, c, causal=False, topo=topo)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@xfail_legacy_partial_manual
def test_ring_attention_segment_ids():
    q, k, v = rand_qkv(seed=2)
    r = np.random.RandomState(2)
    seg = jnp.asarray(np.cumsum(r.rand(2, 32) < 0.2, axis=1))
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))
    ref = xla_attention(q, k, v, causal=True, segment_ids=seg)
    got = jax.jit(
        lambda a, b, c, s: ring_attention(a, b, c, causal=True, segment_ids=s, topo=topo)
    )(q, k, v, seg)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ulysses_matches_dense():
    q, k, v = rand_qkv(seed=3)
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))
    ref = xla_attention(q, k, v, causal=True)
    with use_topology(topo):
        got = jax.jit(lambda a, b, c: ulysses_attention(a, b, c, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


def tiny_llama():
    return llama(
        "llama-tiny", vocab_size=128, max_seq_len=32, hidden_size=32,
        num_layers=2, num_heads=4, num_kv_heads=4, intermediate_size=64,
    )


@xfail_legacy_partial_manual
@pytest.mark.parametrize("mode", ["ulysses", "ring"])
def test_sp_engine_parity_with_dp(mode):
    """Same data/seed: sp=4 engine loss tracks the dp-only engine loss."""
    cfg = {
        "train_batch_size": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 1},
        "steps_per_print": 100,
    }
    dense, *_ = deepspeed_tpu.initialize(
        model=tiny_llama(), config=dict(cfg),
        topology=MeshTopology(dims=ParallelDims(dp=2), devices=jax.devices()[:2]),
        rng=jax.random.PRNGKey(5),
    )
    sp_cfg = dict(cfg)
    sp_cfg["sequence_parallel"] = {"sp_size": 4, "mode": mode}
    sp_eng, *_ = deepspeed_tpu.initialize(
        model=tiny_llama(), config=sp_cfg,
        topology=MeshTopology(dims=ParallelDims(dp=2, sp=4)),
        rng=jax.random.PRNGKey(5),
    )
    r = np.random.RandomState(0)
    try:
        for i in range(2):
            batch = {"input_ids": r.randint(0, 128, size=(4, 32))}
            ld = float(dense.train_batch(batch=dict(batch)))
            ls = float(sp_eng.train_batch(batch=dict(batch)))
            assert abs(ld - ls) < 2e-3, f"step {i}: dense {ld} vs sp/{mode} {ls}"
    finally:
        set_sp_mode("ulysses")


@xfail_legacy_partial_manual
def test_ring_attention_alibi():
    """ALiBi slopes applied from global positions inside the ring (r3: the
    ring path no longer falls back to ulysses for BLOOM-style models)."""
    from deepspeed_tpu.models.transformer import alibi_slopes

    q, k, v = rand_qkv(seed=3)
    slopes = jnp.asarray(alibi_slopes(4))
    topo = MeshTopology(dims=ParallelDims(sp=4, dp=2))
    ref = xla_attention(q, k, v, causal=True, alibi_slopes=slopes)
    got = jax.jit(
        lambda a, b, c: ring_attention(
            a, b, c, causal=True, alibi_slopes=slopes, topo=topo
        )
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize(
    "kv_heads,dims,expect_ax",
    [
        # kv % (sp*tp) != 0 but kv % tp == 0 → tp-only shard, sp replicates
        (2, ParallelDims(sp=2, tp=2), "tp"),
        # tp=1: only the sp axis is live and kv % sp == 0 → sp shard
        (2, ParallelDims(dp=4, sp=2), "sp"),
        # kv=2 can't shard over sp=4 at all → fully replicated KV
        (2, ParallelDims(dp=2, sp=4), None),
        # MQA under sp*tp: nothing divides → replicated KV
        (1, ParallelDims(sp=2, tp=2), None),
    ],
)
def test_ulysses_gqa_small_kv_matches_dense(kv_heads, dims, expect_ax):
    """GQA with kv_heads < sp*tp: the KV constraint falls back to whatever
    axes divide (or replication) and results stay exact vs dense."""
    from deepspeed_tpu.models.sharding import use_topology
    from deepspeed_tpu.parallel.sequence import _kv_head_axes

    q, k, v = rand_qkv(KV=kv_heads, seed=7)
    topo = MeshTopology(dims=dims)
    ref = xla_attention(q, k, v, causal=True)
    with use_topology(topo):
        assert _kv_head_axes(kv_heads) == expect_ax
        got = jax.jit(
            lambda a, b, c: ulysses_attention(a, b, c, causal=True)
        )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5)
