"""steptrace: unified structured tracing + metrics registry (ISSUE 8).

The tentpole contract: host-side spans bracket dispatches (fencing via
block_until_ready at close), the serving replay produces CLOSED request
span trees (QUEUED→PREFILL chunk i→DECODE→DONE), every declared
analytic stream appears as a plan/* span carrying its shardplan
prediction, export is valid Chrome trace-event JSON
(tools/trace_report.py --validate), and disabled tracing allocates
ZERO spans. Satellites: the timer barrier fence fix and the hardened
drift-ledger append ride along here.
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.models import llama
from deepspeed_tpu.profiling import steptrace
from deepspeed_tpu.serving import Request, ServingEngine

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_trace_report():
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(REPO, "tools", "trace_report.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _fresh_registry():
    steptrace.reset()
    yield
    steptrace.reset()


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


# ---------------------------------------------------------------------------
# registry core
# ---------------------------------------------------------------------------
def test_registry_spans_nest_and_export_chrome(tmp_path):
    reg = steptrace.MetricsRegistry(max_spans=100)
    with reg.span("train/step", "train", {"step": 1}):
        with reg.span("train/dispatch", "train"):
            pass
    reg.sample("train/loss", 2.5, step=1)
    reg.async_begin("QUEUED", "serve.request", "r0")
    reg.async_end("QUEUED", "serve.request", "r0")
    reg.instant("DONE", "serve.request", "r0")
    out = reg.export(str(tmp_path / "t.json"))
    d = json.load(open(out))
    evs = d["traceEvents"]
    xs = {e["name"]: e for e in evs if e["ph"] == "X"}
    assert set(xs) == {"train/step", "train/dispatch"}
    for e in xs.values():
        assert e["dur"] >= 0 and e["ts"] >= 0
    # the child nests inside the parent on the export timeline
    p, c = xs["train/step"], xs["train/dispatch"]
    assert p["ts"] <= c["ts"] and c["ts"] + c["dur"] <= p["ts"] + p["dur"]
    assert p["args"] == {"step": 1}
    phs = {e["ph"] for e in evs}
    assert {"X", "b", "e", "i", "C"} <= phs


def test_registry_is_bounded_and_counts_drops():
    reg = steptrace.MetricsRegistry(max_spans=3)
    for i in range(5):
        reg.begin(f"s{i}", "train").end()
    assert len(reg.spans) == 3
    assert reg.dropped == 2


def test_disabled_config_gives_no_tracer_and_null_span():
    assert steptrace.tracer_from_config(None) is None
    assert steptrace.tracer_from_config({"enabled": False}) is None
    assert steptrace.get_registry() is None  # nothing configured globally
    # the shared no-op span: the disabled path allocates nothing per call
    with steptrace.NULL_SPAN as sp:
        sp.annotate(x=1)
        sp.end(fence=None)


def test_span_fence_blocks_on_device_value():
    reg = steptrace.MetricsRegistry()
    x = jnp.ones((64, 64))
    sp = reg.begin("train/device", "train")
    y = x @ x
    sp.end(fence=y)  # block_until_ready at close — must not raise
    assert reg.spans[-1]["name"] == "train/device"
    assert reg.spans[-1]["t1"] >= reg.spans[-1]["t0"]


def test_write_events_bridge_records_and_forwards():
    reg = steptrace.configure()

    class FakeMonitor:
        def __init__(self):
            self.events = []

        def write_events(self, evs):
            self.events.extend(evs)

    mon = FakeMonitor()
    steptrace.write_events(mon, [("serve/tokens_out", 3.0, 1)])
    assert mon.events == [("serve/tokens_out", 3.0, 1)]
    assert reg.samples[0][:3] == ("serve/tokens_out", 3.0, 1)
    # registry-less bridge still forwards (and survives monitor=None)
    steptrace.reset()
    steptrace.write_events(mon, [("comm/x_bytes", 1.0, 2)])
    steptrace.write_events(None, [("comm/x_bytes", 1.0, 3)])
    assert mon.events[-1] == ("comm/x_bytes", 1.0, 2)


def test_stream_span_args_price_by_kind():
    class HW:
        gen = "test"
        host_bw, ici_bw, hbm_bw = 10.0, 5.0, 2.0

    a = steptrace.stream_span_args(
        {"kind": "offload", "bytes_per_step": 100,
         "per_device_bytes_per_step": 50, "overlapped": True}, hardware=HW
    )
    assert a["predicted_s_per_step"] == 5.0      # 50 / host_bw
    assert a["predicted_bytes_per_step"] == 100
    assert a["overlapped"] is True
    a = steptrace.stream_span_args({"kind": "hbm", "bytes_per_step": 8},
                                   hardware=HW)
    assert a["predicted_s_per_step"] == 4.0      # 8 / hbm_bw


# ---------------------------------------------------------------------------
# the acceptance replay: traced serving run -> valid trace, closed trees
# ---------------------------------------------------------------------------
def test_traced_serving_replay_valid_closed_annotated(tmp_path):
    eng = deepspeed_tpu.init_inference(
        tiny_llama(), dtype=jnp.float32, max_tokens=64,
        rng=jax.random.PRNGKey(1),
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 2, "token_budget": 8, "max_tokens": 64,
    }, steptrace={"enabled": True})
    assert srv.tracer is not None
    r = np.random.RandomState(0)
    for i in range(3):
        srv.submit(Request(request_id=f"r{i}",
                           prompt=r.randint(0, 128, size=(9,)),
                           max_new_tokens=3))
    srv.run_until_idle()
    path = srv.trace_export(str(tmp_path / "serve.json"))
    events = json.load(open(path))["traceEvents"]

    tr = _load_trace_report()
    problems = tr.validate(events)
    assert problems == [], problems

    # every request's span tree is closed: QUEUED..DONE per id, with at
    # least one PREFILL chunk (9-token prompts at budget 8 need two)
    req = [e for e in events if e.get("cat") == "serve.request"]
    ids = {e["id"] for e in req}
    assert ids == {"r0", "r1", "r2"}
    for rid in ids:
        names = [e["name"] for e in req if e["id"] == rid]
        assert "QUEUED" in names and "DONE" in names
        assert "DECODE" in names
        assert any(n.startswith("PREFILL chunk") for n in names)

    # every analytic stream appears as a plan/* span with its prediction
    plan = {e["name"]: e for e in events if e.get("cat") == "plan"}
    for name in srv.analytic_streams():
        e = plan[f"plan/{name}"]
        assert e["args"]["predicted_bytes_per_step"] > 0
        assert e["args"]["predicted_s_per_step"] > 0
        assert e["args"]["measured_step_s"] > 0

    # per-step phase self-times within 10% of the step wall clock is the
    # validate() contract already asserted above; spot-check one step
    xs = [e for e in events if e["ph"] == "X" and e["name"] == "serve/step"]
    assert xs, "no serve/step spans recorded"

    # the report renders (smoke of the CLI's analysis path)
    text = tr.report(events)
    assert "serve/step" in text and "plan/kv_cache" in text


def test_serving_disabled_tracing_allocates_zero_spans():
    eng = deepspeed_tpu.init_inference(
        tiny_llama(), dtype=jnp.float32, max_tokens=64,
        rng=jax.random.PRNGKey(1),
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 2, "token_budget": 8, "max_tokens": 64,
    })
    assert srv.tracer is None and srv.metrics.tracer is None
    srv.submit(Request(request_id="r0",
                       prompt=np.arange(4, dtype=np.int64) + 1,
                       max_new_tokens=2))
    srv.run_until_idle()
    assert steptrace.get_registry() is None  # nothing ever configured
    with pytest.raises(RuntimeError, match="steptrace is not enabled"):
        srv.trace_export("/tmp/never.json")


# ---------------------------------------------------------------------------
# train engine: config gate, spans, namespaced monitor events
# ---------------------------------------------------------------------------
def test_train_engine_traced_step_and_namespace(tmp_path, devices8):
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.models import gpt2

    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "steps_per_print": 1,
            "steptrace": {"enabled": True,
                          "export_path": str(tmp_path / "train.json")},
            "csv_monitor": {"enabled": True,
                            "output_path": str(tmp_path / "mon"),
                            "job_name": "j"},
        },
    )
    assert engine.tracer is not None
    data = {"input_ids": np.random.RandomState(0).randint(0, 64,
                                                          size=(8, 16))}
    engine.train_batch(batch=data)
    names = {s["name"] for s in engine.tracer.spans}
    assert {"train/step", "train/batch_prep", "train/dispatch",
            "train/device"} <= names
    # the device span carries real fenced time and nests in the step
    step = engine.tracer.spans_named("train/step")[0]
    for child in ("train/batch_prep", "train/dispatch", "train/device"):
        c = engine.tracer.spans_named(child)[0]
        assert step["t0"] <= c["t0"] and c["t1"] <= step["t1"]
    # monitor events landed under the documented train/* namespace
    job = tmp_path / "mon" / "j"
    assert (job / "train_loss.csv").exists()
    assert (job / "train_lr.csv").exists()
    # export (config export_path default) passes the schema gate
    out = engine.trace_export()
    events = json.load(open(out))["traceEvents"]
    assert _load_trace_report().validate(events) == []


def test_steptrace_config_validation():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    cfg = DeepSpeedConfig({"train_batch_size": 8,
                           "steptrace": {"enabled": True,
                                         "max_spans": 7}})
    assert cfg.steptrace.enabled and cfg.steptrace.max_spans == 7
    with pytest.raises(DeepSpeedConfigError, match="max_spans"):
        DeepSpeedConfig({"train_batch_size": 8,
                         "steptrace": {"max_spans": 0}})


# ---------------------------------------------------------------------------
# trace_report --validate catches the documented violations
# ---------------------------------------------------------------------------
def test_trace_report_flags_violations(tmp_path):
    tr = _load_trace_report()
    # negative duration
    assert any("negative duration" in p for p in tr.validate([
        {"name": "x", "ph": "X", "ts": 0.0, "dur": -1.0, "tid": 1},
    ]))
    # unclosed request tree: QUEUED begun, never ended, no terminal
    probs = tr.validate([
        {"name": "QUEUED", "ph": "b", "ts": 0.0, "cat": "serve.request",
         "id": "r9"},
    ])
    assert any("unclosed" in p for p in probs)
    assert any("not closed" in p for p in probs)
    # phase-coverage drift: a step whose phases cover less than 90%
    assert any("phase self-times" in p for p in tr.validate([
        {"name": "serve/step", "ph": "X", "ts": 0.0, "dur": 100_000.0,
         "tid": 1},
        {"name": "serve/dispatch", "ph": "X", "ts": 0.0, "dur": 10_000.0,
         "tid": 1},
    ]))
    # CLI round-trip on a valid file
    reg = steptrace.MetricsRegistry()
    reg.begin("train/x", "train").end()
    p = reg.export(str(tmp_path / "ok.json"))
    assert tr.main([p]) == 0
    assert tr.main(["--validate", p]) == 0


# ---------------------------------------------------------------------------
# satellites: timer barrier fix, drift-ledger hardening
# ---------------------------------------------------------------------------
def test_timer_stop_fences_on_block_on_and_warns_on_bare_barrier(
        caplog, monkeypatch):
    import logging

    from deepspeed_tpu.utils import timer as timer_mod
    from deepspeed_tpu.utils.logging import logger as ds_logger

    t = timer_mod._Timer("t")
    t.start()
    x = jnp.ones((32, 32))
    t.stop(barrier=True, block_on=x @ x)  # the actual fence path
    assert t.count == 1 and t.elapsed_total > 0
    # bare barrier=True: host clock only — warns ONCE per process
    monkeypatch.setattr(ds_logger, "propagate", True)  # caplog visibility
    timer_mod._bare_barrier_warned = False
    with caplog.at_level(logging.WARNING):
        t.start()
        t.stop(barrier=True)
        t.start()
        t.stop(barrier=True)
    warns = [r for r in caplog.records if "cannot fence" in r.getMessage()]
    assert len(warns) == 1
    assert t.count == 3


def test_drift_ledger_unwritable_path_warns_not_raises(
        tmp_path, caplog, monkeypatch):
    import logging

    from deepspeed_tpu.analysis.cost.drift import DriftLedger
    from deepspeed_tpu.utils.logging import logger as ds_logger

    monkeypatch.setattr(ds_logger, "propagate", True)  # caplog visibility
    blocker = tmp_path / "blocker"
    blocker.write_text("a file, not a dir")
    # the ledger path's parent is a FILE -> makedirs raises OSError;
    # append must log a warning and continue (read-only CI checkouts)
    ledger = DriftLedger(str(blocker / "perf" / "drift.jsonl"))
    with caplog.at_level(logging.WARNING):
        ledger.append({"ratio": 1.0})  # must NOT raise
    assert any("drift ledger unwritable" in r.getMessage()
               for r in caplog.records)
    assert ledger.load() == []  # nothing written, nothing lost but entry
    # the happy path still writes
    ok = DriftLedger(str(tmp_path / "perf" / "drift.jsonl"))
    ok.append({"ratio": 1.0})
    assert ok.load() == [{"ratio": 1.0}]
