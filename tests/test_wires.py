"""Wire codecs + wire collectives (ISSUE 12, comm/wires.py).

Oracles pinned here:

- per-codec round trips respect the DOCUMENTED error bound
  ``|decode(encode(x)) - x| <= codec.bound(x)`` — including zero lanes,
  denormal lanes and odd (int4-padded) row counts; fp32 is bitwise, bf16
  is bitwise on bf16 inputs;
- CPU-mesh collectives: the codec reduce-scatter / all-gather match the
  full-width forms within the codec's stated bound (bitwise for the fp32
  wire) on odd AND even member counts, single-hop and hierarchical 2-hop;
- engine-level: the stage-1/2 wired gradient reduction tracks the dense
  trajectory within codec tolerance, the wire spelling of ZeRO++
  (grad_wire/param_wire) is BITWISE the legacy zero_quantized_* path,
  f32 masters stay f32 with shardlint R5 clean (the one-untruncated-
  master-path contract), the prefetch composition moves codec bytes, and
  every wire's bytes appear in ``analytic_streams()``.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import wires
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.models import gpt2


def _special_blocks(rng, b, r, lanes):
    """Random blocks with the adversarial lanes the bounds must survive:
    an all-zero lane, a denormal lane, and a huge-dynamic-range lane."""
    x = rng.randn(b, r, lanes).astype(np.float32) * 3.0
    x[:, :, 0] = 0.0                     # zero lane
    x[:, :, 1] = 1e-40                   # denormal lane
    if lanes > 2:
        x[:, :, 2] *= 1e4                # big lane
    return jnp.asarray(x)


# ------------------------------------------------------------------ codecs
@pytest.mark.parametrize("name", wires.WIRE_NAMES)
@pytest.mark.parametrize("rows", [8, 7])  # even and odd (int4 pack pad)
def test_codec_roundtrip_respects_stated_bound(name, rows):
    rng = np.random.RandomState(0)
    x = _special_blocks(rng, 2, rows, 5)
    codec = wires.get_codec(name)
    y = codec.decode(codec.encode(x), rows, jnp.float32)
    err = np.abs(np.asarray(y) - np.asarray(x))
    bound = np.broadcast_to(np.asarray(codec.bound(x)), x.shape)
    assert (err <= bound + 1e-12).all(), (
        name, err.max(), bound[err > bound + 1e-12],
    )
    if name == "fp32":
        assert np.array_equal(np.asarray(y), np.asarray(x))


def test_bf16_codec_is_identity_on_bf16_inputs():
    x = jnp.asarray(
        np.random.RandomState(1).randn(1, 8, 4), jnp.bfloat16
    ).astype(jnp.float32)  # exactly-representable values
    codec = wires.get_codec("bf16")
    y = codec.decode(codec.encode(x), 8, jnp.float32)
    assert np.array_equal(np.asarray(y), np.asarray(x))


def test_int4_packs_two_codes_per_byte():
    x = jnp.asarray(np.random.RandomState(2).randn(1, 10, 6), jnp.float32)
    p = wires.get_codec("int4").encode(x)
    assert p["q"].shape == (1, 5, 6) and p["q"].dtype == jnp.int8
    # declared wire bytes: payload + fp32 lane scales
    assert wires.get_codec("int4").payload_nbytes(1, 10, 6) == 5 * 6 + 6 * 4
    assert wires.get_codec("int8").payload_nbytes(1, 10, 6) == 10 * 6 + 6 * 4
    assert wires.get_codec("bf16").payload_nbytes(1, 10, 6) == 10 * 6 * 2
    assert wires.get_codec("fp32").payload_nbytes(1, 10, 6, 4) == 10 * 6 * 4


def test_unknown_codec_rejected():
    with pytest.raises(ValueError, match="unknown wire codec"):
        wires.get_codec("int3")


def test_shared_lanewise_entry_matches_int8_codec():
    """quantize_lanewise (the TP-ring / ZeRO++ entry) IS the int8 codec."""
    x = jnp.asarray(np.random.RandomState(3).randn(16, 8), jnp.float32)
    q, scale = wires.quantize_lanewise(x)
    p = wires.get_codec("int8").encode(x[None])
    assert np.array_equal(np.asarray(q), np.asarray(p["q"][0]))
    assert np.array_equal(np.asarray(scale), np.asarray(p["scale"][0]))


# -------------------------------------------------------- mesh collectives
def _topo(n=8, **dims):
    comm.destroy_process_group()
    topo = MeshTopology(
        ParallelDims(**dims) if dims else ParallelDims(dp=n),
        devices=jax.devices()[:n],
    )
    comm.set_topology(topo)
    return topo


def _rs_bound(contribs, n, codec):
    """Exact accumulated bound: each member's blocks quantize once, the
    f32 sum adds their per-block bounds elementwise."""
    c = wires.get_codec(codec)
    d = contribs.shape[1]
    total = np.zeros((n, d // n, contribs.shape[2]), np.float32)
    for m in range(n):
        x3 = jnp.asarray(contribs[m]).reshape(n, d // n, -1)
        total += np.broadcast_to(np.asarray(c.bound(x3)), total.shape)
    return total


@pytest.mark.parametrize("n", [8, 5])   # even and odd member counts
@pytest.mark.parametrize("codec", ["fp32", "bf16", "int8", "int4"])
def test_reduce_scatter_wire_matches_fullwidth(n, codec, devices8):
    topo = _topo(n)
    rng = np.random.RandomState(4)
    d, lanes = 5 * n, 6   # odd per-block row count (5): int4 pack padding
    contribs = np.asarray(
        _special_blocks(rng, n, d, lanes), np.float32
    )
    out = wires.reduce_scatter_wire(
        jnp.asarray(contribs), topo, ("dp",), codec
    )
    # pinned member-order f32 sum, computed through XLA (the wire's adds
    # run inside XLA, which flushes denormals on CPU — a numpy reference
    # would disagree on the denormal lane only)
    import functools

    ref = np.asarray(functools.reduce(
        jnp.add, [jnp.asarray(contribs[m]) for m in range(n)]
    ))
    got = np.asarray(out).reshape(d, lanes)
    if codec == "fp32":
        assert np.array_equal(got, ref)
        return
    bound = _rs_bound(contribs, n, codec).reshape(d, lanes)
    assert (np.abs(got - ref) <= bound + 1e-6).all(), (
        codec, np.abs(got - ref).max(), bound.max(),
    )


@pytest.mark.parametrize("n", [8, 5])
@pytest.mark.parametrize("codec", ["fp32", "bf16", "int8", "int4"])
def test_all_gather_wire_matches_fullwidth(n, codec, devices8):
    topo = _topo(n)
    rng = np.random.RandomState(5)
    shards = np.asarray(_special_blocks(rng, n, 3, 5), np.float32)
    out = np.asarray(
        wires.all_gather_wire(jnp.asarray(shards), topo, ("dp",), codec)
    )
    full = shards.reshape(n * 3, 5)
    if codec == "fp32":
        assert np.array_equal(out, full)
        return
    c = wires.get_codec(codec)
    bounds = np.concatenate([
        np.broadcast_to(
            np.asarray(c.bound(jnp.asarray(shards[m][None]))),
            (1, 3, 5),
        )[0]
        for m in range(n)
    ])
    assert (np.abs(out - full) <= bounds + 1e-6).all()


@pytest.mark.parametrize("dims", [dict(dp=2, fsdp=4), dict(dp=4, fsdp=2)])
def test_hierarchical_wire_oracle(dims, devices8):
    """2-hop == single-hop full-width within the INTER-hop codec bound
    (quantization happens at most once, on the group partials); the fp32
    2-hop wire is bitwise the 2-hop-ordered host sum, and the block
    layout is outer-major (the P((dp, fsdp)) contract)."""
    topo = _topo(8, **dims)
    n_o, n_i = dims["dp"], dims["fsdp"]
    n = n_o * n_i
    rng = np.random.RandomState(6)
    d, lanes = 2 * n, 4
    contribs = np.asarray(_special_blocks(rng, n, d, lanes), np.float32)
    x = jnp.asarray(contribs)

    h32 = np.asarray(wires.reduce_scatter_wire(
        x, topo, ("dp", "fsdp"), "fp32", hierarchical=True
    )).reshape(d, lanes)
    # 2-hop-ordered reference: inner (group) sums first, then the outer
    # member-order sum of the group partials — bitwise. Computed through
    # XLA (CPU flushes denormals; numpy would disagree on that lane).
    import functools

    groups = contribs.reshape(n_o, n_i, d, lanes)
    partials = np.stack([
        np.asarray(functools.reduce(
            jnp.add, [jnp.asarray(groups[g, i]) for i in range(n_i)]
        ))
        for g in range(n_o)
    ])                                             # [n_o, d, lanes]
    ref2 = np.asarray(functools.reduce(
        jnp.add, [jnp.asarray(partials[g]) for g in range(n_o)]
    ))                                             # outer member order
    assert np.array_equal(h32, ref2)

    h8 = np.asarray(wires.reduce_scatter_wire(
        x, topo, ("dp", "fsdp"), "int8", hierarchical=True
    )).reshape(d, lanes)
    # inter-hop bound: each group's partial y quantizes once per block;
    # the envelope sums every group's per-block bound elementwise (a
    # strictly-larger bound than the exact per-final-block sum)
    codec = wires.get_codec("int8")
    env = np.zeros((n, d // n, lanes), np.float32)
    for g in range(n_o):
        y3 = jnp.asarray(partials[g]).reshape(n, d // n, lanes)
        env += np.broadcast_to(np.asarray(codec.bound(y3)), env.shape)
    assert (np.abs(h8 - ref2) <= env.reshape(d, lanes) + 1e-6).all()

    # hierarchical all-gather: outer-major layout, fp32 bitwise
    shards = jnp.asarray(contribs[:, :3])
    hg = np.asarray(wires.all_gather_wire(
        shards, topo, ("dp", "fsdp"), "fp32", hierarchical=True
    ))
    assert np.array_equal(hg, np.asarray(shards).reshape(n * 3, lanes))


# ------------------------------------------------------------- engine level
BASE = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "steps_per_print": 100,
}
DATA = {
    "input_ids": np.random.RandomState(0).randint(0, 128, size=(16, 16))
}


def _run(zero, steps=3, dims=None):
    comm.destroy_process_group()
    kw = {}
    if dims is not None:
        topo = MeshTopology(dims)
        comm.set_topology(topo)
        kw["topology"] = topo
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        config=dict(BASE, zero_optimization=zero),
        rng=jax.random.PRNGKey(7),
        **kw,
    )
    losses = [float(engine.train_batch(batch=DATA)) for _ in range(steps)]
    streams = engine.analytic_streams()
    params = engine.state.params
    engine.destroy()
    return losses, streams, params


def test_stage2_grad_wire_trains_and_declares_stream(devices8):
    dense, s_dense, _ = _run({"stage": 2})
    wired, s_wired, params = _run({"stage": 2, "grad_wire": "int8"})
    assert wired[-1] < wired[0]  # still learns
    for a, b in zip(dense, wired):
        assert abs(a - b) / abs(a) < 0.02, (dense, wired)
    assert "grad_wire" not in s_dense
    gw = s_wired["grad_wire"]
    assert gw["bytes_per_step"] > 0 and gw["kind"] == "ici"
    assert gw["codec"] == "int8" and not gw["overlapped"]
    # f32 masters stay f32 through the wired update
    assert all(
        leaf.dtype == jnp.float32
        for leaf in jax.tree_util.tree_leaves(params)
    )


def test_wire_spelling_is_bitwise_the_legacy_zeropp_path(devices8):
    """grad_wire/param_wire int8 IS zero_quantized_* (same codecs, same
    programs) — trajectories match bitwise."""
    legacy, _, _ = _run({
        "stage": 3, "stage3_param_persistence_threshold": 1,
        "zero_quantized_weights": True, "zero_quantized_gradients": True,
    })
    wired, streams, _ = _run({
        "stage": 3, "stage3_param_persistence_threshold": 1,
        "grad_wire": "int8", "param_wire": "int8",
    })
    assert legacy == wired, (legacy, wired)
    assert streams["grad_wire"]["bytes_per_step"] > 0
    assert streams["param_wire"]["bytes_per_step"] > 0


def test_prefetch_composes_with_param_wire(devices8):
    """stage3_layer_prefetch + param_wire: the prefetched gather moves
    codec bytes (the zero3_prefetch stream shrinks and carries the codec
    name; the stacked layers are never double-counted in the wire
    streams) and the engine still trains."""
    full, s_full, _ = _run({
        "stage": 3, "stage3_param_persistence_threshold": 1,
        "stage3_layer_prefetch": True,
    })
    wired, s_wired, _ = _run({
        "stage": 3, "stage3_param_persistence_threshold": 1,
        "stage3_layer_prefetch": True,
        "grad_wire": "int8", "param_wire": "int8",
    })
    assert wired[-1] < wired[0]
    assert abs(wired[0] - full[0]) / abs(full[0]) < 0.02
    z_full, z_wired = s_full["zero3_prefetch"], s_wired["zero3_prefetch"]
    assert z_wired["param_wire"] == "int8"
    assert z_wired["bytes_per_step"] < z_full["bytes_per_step"]
    # non-layers leaves ride the wire streams; the stacked layers group
    # is priced by zero3_prefetch only
    nopf, s_nopf, _ = _run({
        "stage": 3, "stage3_param_persistence_threshold": 1,
        "grad_wire": "int8", "param_wire": "int8",
    }, steps=1)
    assert (s_wired["param_wire"]["bytes_per_step"]
            < s_nopf["param_wire"]["bytes_per_step"])


def test_hierarchical_wire_engine_runs_on_factored_mesh(devices8):
    wired, streams, _ = _run(
        {"stage": 2, "grad_wire": "int8", "hierarchical_wire": True},
        dims=ParallelDims(dp=2, fsdp=4),
    )
    assert wired[-1] < wired[0]
    gw = streams["grad_wire"]
    assert gw["hierarchical"]
    assert gw["intra_bytes_per_step"] > 0 and gw["inter_bytes_per_step"] > 0
    # flat mesh: the knob logs + degrades to single hop
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        config=dict(BASE, zero_optimization={
            "stage": 2, "grad_wire": "int8", "hierarchical_wire": True,
        }),
    )
    assert engine._hier_wire is False
    engine.destroy()


def test_wired_engine_lints_clean_R5(devices8):
    """The acceptance contract shardlint R5 keeps honest: an int8 grad
    wire leaves ONE untruncated f32 path from master input to master
    output (dequant-accumulate in f32). The abstract trace of the wired
    step must carry no R5 findings."""
    from deepspeed_tpu.analysis import lint_config

    comm.destroy_process_group()
    report = lint_config(
        dict(BASE, zero_optimization={
            "stage": 3, "stage3_param_persistence_threshold": 1,
            "grad_wire": "int8", "param_wire": "int8",
        }),
        model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        only=["R5"],
        source="wired-engine",
    )
    assert not report.findings, [f.message for f in report.findings]


# ------------------------------------------------------------------ config
def test_config_validation_and_legacy_mapping():
    with pytest.raises(DeepSpeedConfigError, match="grad_wire"):
        DeepSpeedConfig(dict(BASE, zero_optimization={
            "stage": 2, "grad_wire": "int3",
        }))
    with pytest.raises(DeepSpeedConfigError, match="stage 3"):
        DeepSpeedConfig(dict(BASE, zero_optimization={
            "stage": 2, "param_wire": "int8",
        }))
    with pytest.raises(DeepSpeedConfigError, match="stage >= 1"):
        DeepSpeedConfig(dict(BASE, zero_optimization={
            "stage": 0, "grad_wire": "bf16",
        }))
    zc = DeepSpeedConfig(dict(BASE, zero_optimization={
        "stage": 3, "zero_quantized_weights": True,
        "zero_quantized_gradients": True,
    })).zero_config
    assert zc.resolved_param_wire() == "int8"
    assert zc.resolved_grad_wire() == "int8"
    zc2 = DeepSpeedConfig(dict(BASE, zero_optimization={
        "stage": 3, "grad_wire": "int4", "param_wire": "bf16",
    })).zero_config
    assert zc2.resolved_grad_wire() == "int4"
    assert zc2.resolved_param_wire() == "bf16"


# ----------------------------------------------------------------- planner
def test_planner_wire_axis_prices_codecs(devices8):
    """The wire-codec axis (stage x grad_wire x param_wire) reaches the
    built candidate config and the abstract plan declares the wire
    streams — priced before any compile."""
    from deepspeed_tpu.autotuning import PlannerSearch

    base = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 1},
        "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                       "tune_zero": False},
    }
    comm.destroy_process_group()
    search = PlannerSearch(
        gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
             num_layers=2, num_heads=2),
        base, None, top_k=1,
    )
    cands = search.candidates()
    combos = {(c.grad_wire, c.param_wire) for c in cands}
    assert combos == {
        ("fp32", "fp32"), ("fp32", "int8"),
        ("int8", "fp32"), ("int8", "int8"),
    }
    on = next(c for c in cands
              if c.grad_wire == "int8" and c.param_wire == "int8"
              and not c.z3_prefetch and c.remat == "none")
    cfg = search._candidate_config(on)
    assert cfg["zero_optimization"]["grad_wire"] == "int8"
    assert cfg["zero_optimization"]["param_wire"] == "int8"
    assert "gw-int8" in on.label() and "pw-int8" in on.label()
    pc = search._plan_one(on)
    assert pc.plan is not None, pc.reason
    assert pc.plan.streams["grad_wire"]["bytes_per_step"] > 0
    assert pc.plan.streams["param_wire"]["bytes_per_step"] > 0
