"""Flash attention kernel vs XLA reference (fwd + grads), interpret mode on CPU."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu.ops.attention import xla_attention
from deepspeed_tpu.ops.pallas.flash_attention import flash_attention


def _qkv(rng, B=2, S=256, H=4, KV=None, D=64, dtype=jnp.float32):
    KV = KV or H
    ks = jax.random.split(rng, 3)
    q = jax.random.normal(ks[0], (B, S, H, D), dtype)
    k = jax.random.normal(ks[1], (B, S, KV, D), dtype)
    v = jax.random.normal(ks[2], (B, S, KV, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
def test_forward_matches_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(0))
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    ref = xla_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_gqa_forward():
    q, k, v = _qkv(jax.random.PRNGKey(1), H=8, KV=2)
    out = flash_attention(q, k, v, causal=True, block_q=128, block_k=128)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_grads_match_reference(causal):
    q, k, v = _qkv(jax.random.PRNGKey(2), B=1, S=256, H=2, D=64)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal, block_q=128, block_k=128) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=causal) ** 2)

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_gqa_grads():
    q, k, v = _qkv(jax.random.PRNGKey(3), B=1, S=128, H=4, KV=2, D=64)

    g_flash = jax.grad(
        lambda *a: jnp.sum(flash_attention(*a, causal=True, block_q=128, block_k=128) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(xla_attention(*a, causal=True) ** 2), argnums=(0, 1, 2)
    )(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_unsupported_falls_back():
    # unaligned seq length (not a multiple of 128) → fallback to XLA path
    rng = jax.random.PRNGKey(4)
    q = jax.random.normal(rng, (1, 100, 2, 64))
    out = flash_attention(q, q, q, causal=True)
    ref = xla_attention(q, q, q, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


def test_cross_length_falls_back():
    # Sq != Sk (decode-style) must NOT silently truncate keys
    rng = jax.random.PRNGKey(5)
    q = jax.random.normal(rng, (1, 128, 2, 64))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 256, 2, 64))
    v = jax.random.normal(jax.random.fold_in(rng, 2), (1, 256, 2, 64))
    out = flash_attention(q, k, v, causal=False)
    ref = xla_attention(q, k, v, causal=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_sharded_flash_matches_reference(devices8):
    """Under a >1-device topology, flash runs in shard_map and must agree."""
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import ParallelDims
    from deepspeed_tpu.models.sharding import use_topology

    topo = comm.init_distributed(dims=ParallelDims(dp=4, tp=2))
    q, k, v = _qkv(jax.random.PRNGKey(6), B=4, S=256, H=4, KV=2, D=64)
    ref = xla_attention(q, k, v, causal=True)
    with use_topology(topo):
        out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    # grads flow through the shard_mapped kernel too
    with use_topology(topo):
        g = jax.jit(
            jax.grad(lambda q, k, v: jnp.sum(flash_attention(q, k, v) ** 2), argnums=0)
        )(q, k, v)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(xla_attention(q, k, v, causal=True) ** 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-4)


def test_registered_as_attention_impl():
    from deepspeed_tpu.ops.attention import _IMPLS

    assert "flash" in _IMPLS


# ---------------------------------------------------------------------------
# r3: in-kernel segment masking, ALiBi slopes, sp composition
# ---------------------------------------------------------------------------
def _segments(B, S, n=3, seed=7):
    """Sorted segment ids (packed-sequence style) [B, S]."""
    r = np.random.RandomState(seed)
    out = np.zeros((B, S), np.int32)
    for b in range(B):
        cuts = np.sort(r.choice(np.arange(1, S), size=n - 1, replace=False))
        out[b] = np.searchsorted(cuts, np.arange(S), side="right")
    return jnp.asarray(out)


@pytest.mark.parametrize("causal", [True, False])
def test_segment_ids_in_kernel(causal):
    """segment_ids must take the Pallas kernel (no fallback) and match XLA."""
    q, k, v = _qkv(jax.random.PRNGKey(8), B=2, S=256, H=2, D=64)
    seg = _segments(2, 256)
    called = {}
    import deepspeed_tpu.ops.pallas.flash_attention as fa

    orig = fa._flash_fwd

    def spy(*a, **kw):
        called["yes"] = True
        return orig(*a, **kw)

    fa._flash_fwd, orig_saved = spy, orig
    try:
        out = flash_attention(q, k, v, causal=causal, segment_ids=seg,
                              block_q=128, block_k=128)
    finally:
        fa._flash_fwd = orig_saved
    assert called.get("yes"), "segment_ids fell back to XLA"
    ref = xla_attention(q, k, v, causal=causal, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_segment_ids_grads():
    q, k, v = _qkv(jax.random.PRNGKey(9), B=1, S=256, H=2, D=64)
    seg = _segments(1, 256)
    g_flash = jax.grad(
        lambda *a: jnp.sum(
            flash_attention(*a, causal=True, segment_ids=seg,
                            block_q=128, block_k=128) ** 2
        ),
        argnums=(0, 1, 2),
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(xla_attention(*a, causal=True, segment_ids=seg) ** 2),
        argnums=(0, 1, 2),
    )(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-4, err_msg=f"d{name}"
        )


def test_alibi_slopes_in_kernel():
    """ALiBi via per-head slopes matches the dense-bias XLA reference,
    forward and backward, without materializing [B,H,S,S]."""
    from deepspeed_tpu.models.transformer import alibi_slopes as make_slopes

    H = 4
    q, k, v = _qkv(jax.random.PRNGKey(10), B=2, S=256, H=H, D=64)
    slopes = jnp.asarray(make_slopes(H))
    out = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                          block_q=128, block_k=128)
    ref = xla_attention(q, k, v, causal=True, alibi_slopes=slopes)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    g = jax.grad(
        lambda *a: jnp.sum(
            flash_attention(*a, causal=True, alibi_slopes=slopes,
                            block_q=128, block_k=128) ** 2
        )
    )(q, k, v)
    g_ref = jax.grad(
        lambda *a: jnp.sum(xla_attention(*a, causal=True, alibi_slopes=slopes) ** 2)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=5e-4)


def test_alibi_plus_segments_in_kernel():
    from deepspeed_tpu.models.transformer import alibi_slopes as make_slopes

    H = 2
    q, k, v = _qkv(jax.random.PRNGKey(11), B=2, S=256, H=H, D=64)
    slopes = jnp.asarray(make_slopes(H))
    seg = _segments(2, 256)
    out = flash_attention(q, k, v, causal=True, alibi_slopes=slopes,
                          segment_ids=seg, block_q=128, block_k=128)
    ref = xla_attention(q, k, v, causal=True, alibi_slopes=slopes,
                        segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_flash_under_sp_mesh(devices8):
    """sp>1 (Ulysses layout: heads over tp×sp) must take the kernel."""
    import deepspeed_tpu.comm as comm
    import deepspeed_tpu.ops.pallas.flash_attention as fa
    from deepspeed_tpu.comm import ParallelDims
    from deepspeed_tpu.models.sharding import use_topology

    comm.destroy_process_group()
    topo = comm.init_distributed(dims=ParallelDims(dp=2, sp=2, tp=2))
    q, k, v = _qkv(jax.random.PRNGKey(12), B=2, S=256, H=4, KV=4, D=64)
    ref = xla_attention(q, k, v, causal=True)
    called = {}
    orig = fa._flash_fwd

    def spy(*a, **kw):
        called["yes"] = True
        return orig(*a, **kw)

    fa._flash_fwd = spy
    try:
        with use_topology(topo):
            out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=True))(
                q, k, v
            )
    finally:
        fa._flash_fwd = orig
    assert called.get("yes"), "sp>1 fell back to XLA"
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    comm.destroy_process_group()


def test_flash_inside_manual_context_all_axes_manual(devices8):
    """pp-only topology: inside the pipeline's manual region no Auto axes
    remain — flash must run the kernel directly (axis_names=set() crashes
    shard_map)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import MeshTopology, ParallelDims
    from deepspeed_tpu.models import llama

    comm.destroy_process_group()
    topo = MeshTopology(ParallelDims(pp=2), devices=jax.devices()[:2])
    comm.set_topology(topo)
    model = llama("llama-tiny", vocab_size=256, max_seq_len=128,
                  hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=4,
                  intermediate_size=128)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, topology=topo,
        config={
            "train_batch_size": 4,
            "train_micro_batch_size_per_gpu": 2,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "pipeline": {"stages": 2},
            "tpu_kernels": {"flash_attention": True},
        },
        rng=jax.random.PRNGKey(0),
    )
    loss = engine.train_batch(
        batch={"input_ids": np.random.RandomState(0).randint(0, 256, size=(4, 128))}
    )
    assert np.isfinite(float(loss))
    comm.destroy_process_group()


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="legacy jax can't compile the partial-manual wire shard_map "
    "(the engine degrades to the numerics-only 1-bit variant there)",
)
def test_flash_under_onebit_stacked_grads(devices8):
    """1-bit wire path manualizes the dp axis; flash's nested shard_map must
    only map the still-Auto axes (r3 review repro)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import MeshTopology, ParallelDims
    from deepspeed_tpu.models import llama

    comm.destroy_process_group()
    topo = MeshTopology(ParallelDims(dp=4, tp=2), devices=jax.devices())
    comm.set_topology(topo)
    model = llama("llama-tiny", vocab_size=256, max_seq_len=128,
                  hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=4,
                  intermediate_size=128)
    engine, *_ = deepspeed_tpu.initialize(
        model=model, topology=topo,
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "OneBitAdam",
                          "params": {"lr": 1e-3, "freeze_step": 2}},
            "zero_optimization": {"stage": 1},
            "tpu_kernels": {"flash_attention": True},
        },
        rng=jax.random.PRNGKey(0),
    )
    assert engine._stacked_grads_axes  # the wire path is actually active
    losses = [
        float(engine.train_batch(
            batch={"input_ids": np.random.RandomState(i).randint(0, 256, size=(8, 128))}
        ))
        for i in range(3)
    ]
    assert np.isfinite(losses).all()
    comm.destroy_process_group()


def _count_pallas_calls(closed_jaxpr):
    """Recursively count pallas_call eqns (remat-recompute detector)."""
    n = 0
    seen = set()

    def walk(j):
        nonlocal n
        if id(j) in seen:
            return
        seen.add(id(j))
        for eqn in j.eqns:
            if "pallas" in str(eqn.primitive):
                n += 1
            for v in eqn.params.values():
                for x in v if isinstance(v, (tuple, list)) else [v]:
                    if hasattr(x, "jaxpr"):
                        walk(x.jaxpr)
                    elif hasattr(x, "eqns"):
                        walk(x)

    walk(closed_jaxpr.jaxpr)
    return n


def test_dots_flash_policy_skips_fwd_recompute():
    """The dots_flash remat policy saves the kernel outputs (checkpoint_name
    tags in _fa_fwd), so backward must NOT re-run the forward kernel:
    3 pallas calls (fwd, dq, dkv) vs dots_saveable's 4 (+fwd recompute)."""
    from deepspeed_tpu.runtime.activation_checkpointing import policy_by_name

    q, k, v = _qkv(jax.random.PRNGKey(3), B=1, S=256, H=2, D=64)

    def counts(policy_name):
        f = jax.checkpoint(
            lambda q, k, v: flash_attention(q, k, v, interpret=True).sum(),
            policy=policy_by_name(policy_name),
            prevent_cse=False,
        )
        return _count_pallas_calls(jax.make_jaxpr(jax.grad(f))(q, k, v))

    assert counts("dots_saveable") == 4
    assert counts("dots_flash") == 3


def test_dots_flash_policy_grads_match():
    from deepspeed_tpu.runtime.activation_checkpointing import policy_by_name

    q, k, v = _qkv(jax.random.PRNGKey(4), B=1, S=256, H=2, D=64)

    def loss(q, k, v):
        return (flash_attention(q, k, v, interpret=True) ** 2).sum()

    ref = jax.grad(loss)(q, k, v)
    got = jax.grad(
        jax.checkpoint(loss, policy=policy_by_name("dots_flash"),
                       prevent_cse=False)
    )(q, k, v)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=1e-5)


# ---------------------------------------------------------------------------
# dense additive bias in-kernel (VERDICT r3 missing #5)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("bias_bh", [(2, 4), (1, 4), (2, 1), (1, 1)])
def test_dense_bias_in_kernel_forward(causal, bias_bh):
    B, S, H, D = 2, 256, 4, 64
    q, k, v = _qkv(jax.random.PRNGKey(10), B=B, S=S, H=H, D=D)
    bias = 0.5 * jax.random.normal(jax.random.PRNGKey(11), (*bias_bh, S, S))
    out = flash_attention(q, k, v, causal=causal, bias=bias,
                          block_q=128, block_k=128)
    ref = xla_attention(q, k, v, causal=causal, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


@pytest.mark.parametrize("bias_bh,kv_heads", [
    # B=2 so the broadcast accumulation over batch is a real reduction
    # (full-shape (2,H) takes the inline dq-kernel dbias path; the three
    # broadcast shapes take the dedicated accumulation kernel); the last
    # case composes the accumulation kernel with GQA head grouping
    ((2, 2), 2), ((1, 2), 2), ((2, 1), 2), ((1, 1), 2), ((1, 4), 2),
])
def test_dense_bias_grads_including_dbias(bias_bh, kv_heads):
    B, S, D = 2, 256, 64
    H = bias_bh[1] if bias_bh[1] > 1 else 2
    q, k, v = _qkv(jax.random.PRNGKey(12), B=B, S=S, H=H, KV=kv_heads, D=D)
    bias = 0.3 * jax.random.normal(jax.random.PRNGKey(13), (*bias_bh, S, S))

    def loss(fn):
        return lambda q, k, v, b: jnp.sum(
            fn(q, k, v, causal=True, bias=b) ** 2
        )

    g_flash = jax.grad(
        loss(lambda q, k, v, causal, bias: flash_attention(
            q, k, v, causal=causal, bias=bias, block_q=128, block_k=128)),
        argnums=(0, 1, 2, 3),
    )(q, k, v, bias)
    g_ref = jax.grad(loss(xla_attention), argnums=(0, 1, 2, 3))(q, k, v, bias)
    for gf, gr, name in zip(g_flash, g_ref, ["q", "k", "v", "bias"]):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=1e-3, err_msg=f"d{name}"
        )


def test_dense_bias_with_gqa_and_segments():
    B, S, H, KV, D = 2, 256, 4, 2, 64
    q, k, v = _qkv(jax.random.PRNGKey(14), B=B, S=S, H=H, KV=KV, D=D)
    bias = 0.5 * jax.random.normal(jax.random.PRNGKey(15), (1, H, S, S))
    seg = jnp.concatenate(
        [jnp.zeros((B, S // 2), jnp.int32), jnp.ones((B, S - S // 2), jnp.int32)],
        axis=1,
    )
    out = flash_attention(q, k, v, causal=True, bias=bias, segment_ids=seg,
                          block_q=128, block_k=128)
    ref = xla_attention(q, k, v, causal=True, bias=bias, segment_ids=seg)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-5)


class _LogCapture:
    """The deepspeed_tpu logger sets propagate=False, so caplog can't see
    it; attach a handler directly."""

    def __enter__(self):
        import logging

        from deepspeed_tpu.utils.logging import logger

        self.records = []
        outer = self

        class H(logging.Handler):
            def emit(self, record):
                outer.records.append(record)

        self._handler = H()
        self._logger = logger
        logger.addHandler(self._handler)
        return self

    def __exit__(self, *exc):
        self._logger.removeHandler(self._handler)

    def messages(self):
        return [r.getMessage() for r in self.records]


def test_ineligible_bias_falls_back_with_log():
    from deepspeed_tpu.ops.pallas import flash_attention as fa_mod
    from deepspeed_tpu.utils import logging as logging_mod

    logging_mod.fallback_log_seen.clear()
    q, k, v = _qkv(jax.random.PRNGKey(16), B=2, S=256, H=4, D=64)
    # per-head bias missing the batch dim → not in-kernel-eligible → XLA
    # fallback, with exactly ONE log line naming the reason
    bias = 0.1 * jax.random.normal(jax.random.PRNGKey(17), (4, 256, 256))
    with _LogCapture() as cap:
        out = flash_attention(q, k, v, causal=True, bias=bias)
        _ = flash_attention(q, k, v, causal=True, bias=bias)
    ref = xla_attention(q, k, v, causal=True, bias=bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    hits = [m for m in cap.messages() if "falling back" in m]
    assert len(hits) == 1, cap.messages()
    assert "dense bias shape" in hits[0]


def test_unaligned_seq_fallback_names_reason():
    from deepspeed_tpu.ops.pallas import flash_attention as fa_mod
    from deepspeed_tpu.utils import logging as logging_mod

    logging_mod.fallback_log_seen.clear()
    rng = jax.random.PRNGKey(18)
    q = jax.random.normal(rng, (1, 100, 2, 64))
    with _LogCapture() as cap:
        flash_attention(q, q, q, causal=True)
    hits = [m for m in cap.messages() if "falling back" in m]
    assert len(hits) == 1 and "128-aligned" in hits[0]


def test_causal_dma_skip_bitmatches_dense_grid(monkeypatch):
    """Causal runs ride the compaction (DMA-skip) path by default; the
    k-blocks process in the same ascending order as the dense grid, so the
    two paths are bit-identical — and the kill-switch restores the dense
    grid."""
    from deepspeed_tpu.ops.pallas import flash_attention as fa_mod

    assert fa_mod._CAUSAL_DMA_SKIP  # default on
    q, k, v = _qkv(jax.random.PRNGKey(21), B=1, S=256, H=2, D=64)
    out_skip = fa_mod.flash_attention(q, k, v, causal=True,
                                      block_q=128, block_k=128)
    g_skip = jax.grad(lambda a: jnp.sum(fa_mod.flash_attention(
        a, k, v, causal=True, block_q=128, block_k=128) ** 2))(q)
    monkeypatch.setattr(fa_mod, "_CAUSAL_DMA_SKIP", False)
    out_dense = fa_mod.flash_attention(q, k, v, causal=True,
                                       block_q=128, block_k=128)
    g_dense = jax.grad(lambda a: jnp.sum(fa_mod.flash_attention(
        a, k, v, causal=True, block_q=128, block_k=128) ** 2))(q)
    np.testing.assert_array_equal(np.asarray(out_skip), np.asarray(out_dense))
    np.testing.assert_array_equal(np.asarray(g_skip), np.asarray(g_dense))


@pytest.mark.parametrize("causal", [True, False])
def test_bwd_tiles_independent_of_fwd_tiles(causal):
    """dq/dkv kernels accept their own tile sizes (the causal DMA-skip
    tables are rebuilt at bwd granularity): grads must be identical to the
    symmetric-tile run."""
    q, k, v = _qkv(jax.random.PRNGKey(22), B=1, S=256, H=2, D=64)

    def loss(bqb, bkb):
        def f(q, k, v):
            return jnp.sum(flash_attention(
                q, k, v, causal=causal, block_q=128, block_k=256,
                block_q_bwd=bqb, block_k_bwd=bkb) ** 2)
        return jax.grad(f, argnums=(0, 1, 2))(q, k, v)

    base = loss(0, 0)           # inherit fwd tiles (128, 256)
    asym = loss(256, 128)       # bwd q-tile 2x fwd, bwd k-tile HALF fwd —
    # both directions of the causal-table rebuild covered
    for g0, g1 in zip(base, asym):
        np.testing.assert_allclose(np.asarray(g0), np.asarray(g1),
                                   atol=2e-5)


def test_bwd_tiles_scope_and_config():
    """The scoped override carries the bwd pair, and a user block_mask pins
    bwd tiles to the layout granularity (grads still match the masked
    reference)."""
    from deepspeed_tpu.ops.pallas.flash_attention import block_sizes_scope

    q, k, v = _qkv(jax.random.PRNGKey(23), B=1, S=256, H=2, D=64)

    def g(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True) ** 2)

    base = jax.grad(g)(q, k, v)
    with block_sizes_scope(128, 128, 256, 128):
        scoped = jax.grad(g)(q, k, v)
    np.testing.assert_allclose(np.asarray(base), np.asarray(scoped),
                               atol=2e-5)

    # block_mask path: bwd tiles silently pinned to the mask granularity
    mask = np.tril(np.ones((2, 2), np.int32))
    def gm(q, k, v):
        return jnp.sum(flash_attention(
            q, k, v, causal=True, block_mask=mask,
            block_q=128, block_k=128, block_q_bwd=64, block_k_bwd=64) ** 2)
    out = jax.grad(gm)(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(base), atol=2e-5)
