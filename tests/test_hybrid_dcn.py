"""Hybrid DCN×ICI meshes (ISSUE 17): per-link cost pricing, the R13
stream classifier, the planner's knob-free 2-hop-vs-flat ranking, and
the hybrid mesh spellings carried by autoplan and the campaign ledger.

The R12/R13 fire/clean behavior itself rides the lint corpus
(tests/analysis_corpus/fixtures.py: dcn_flat_ring / dcn_unbudgeted_stream
and their clean twins) — here we pin the unit-level semantics the rules
and the planner build on."""

import importlib.util
import json
import os

import pytest

from deepspeed_tpu.analysis.cost.hardware import HardwareModel, topology_key
from deepspeed_tpu.analysis.cost.planner import (
    Plan,
    _reprice_links,
    scale_plan_micro,
    split_link_bytes,
)
from deepspeed_tpu.analysis.rules.dcn_overlap import dcn_stream_bytes
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import gpt2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _hw(dcn_bw=1e8):
    return HardwareModel(gen="test", peak_flops=1e12, hbm_bytes=16 << 30,
                         hbm_bw=1e12, ici_bw=1e9, host_bw=1e10,
                         dcn_bw=dcn_bw)


# ------------------------------------------------------ per-link pricing
def test_split_link_bytes_classifies_by_any_dcn_axis():
    ici_bytes = {"fsdp": 4.0, "dp": 2.0, "dp+fsdp": 3.0, "?": 1.0}
    ici, dcn = split_link_bytes(ici_bytes, {"dp": "dcn"})
    assert ici == {"fsdp": 4.0, "?": 1.0}
    # a ring touching ANY dcn axis is throttled end-to-end
    assert dcn == {"dp": 2.0, "dp+fsdp": 3.0}
    # no link metadata -> everything stays ICI (flat meshes)
    ici, dcn = split_link_bytes(ici_bytes, {})
    assert ici == ici_bytes and dcn == {}


def test_plan_prices_dcn_rings_at_dcn_bw():
    plan = Plan(source="t", hardware=_hw(dcn_bw=1e8), n_devices=8)
    plan.ici_bytes = {"fsdp": 1e9, "dp": 5e8}
    plan.link_kinds = {"dp": "dcn"}
    _, plan.dcn_bytes = split_link_bytes(plan.ici_bytes, plan.link_kinds)
    _reprice_links(plan)
    assert plan.ici_s == pytest.approx(1.0)    # 1 GB over 1 GB/s ICI
    assert plan.dcn_s == pytest.approx(5.0)    # 0.5 GB over 0.1 GB/s DCN
    assert plan.est_step_s == pytest.approx(5.0)
    # batch-linear scaling carries the dcn bucket and reprices it
    scaled = scale_plan_micro(plan, 2.0)
    assert scaled.dcn_bytes["dp"] == pytest.approx(1e9)
    assert scaled.dcn_s == pytest.approx(10.0)
    assert scaled.est_step_s == pytest.approx(10.0)
    # the serialized spelling carries both buckets
    d = plan.to_dict()
    assert d["dcn_bytes"] == {"dp": round(5e8)}
    assert d["dcn_s"] == pytest.approx(5.0)


def test_plan_without_dcn_bw_never_prices_dcn():
    plan = Plan(source="t", hardware=_hw(dcn_bw=0.0), n_devices=8)
    plan.ici_bytes = {"dp": 5e8}
    plan.link_kinds = {"dp": "dcn"}
    _, plan.dcn_bytes = split_link_bytes(plan.ici_bytes, plan.link_kinds)
    _reprice_links(plan)
    assert plan.dcn_s == 0.0


# --------------------------------------------------- R13 stream classifier
def test_dcn_stream_bytes_classification():
    kinds = {"dp": "dcn"}
    base = {"kind": "ici", "axes": ("dp",), "bytes_per_step": 10.0}
    # offload/hbm streams ride PCIe/HBM, never DCN
    assert dcn_stream_bytes(dict(base, kind="offload"), kinds) == 0.0
    assert dcn_stream_bytes(dict(base, kind="hbm"), kinds) == 0.0
    # ICI-only axes stay R8's problem
    assert dcn_stream_bytes(dict(base, axes=("fsdp",)), kinds) == 0.0
    assert dcn_stream_bytes({}, kinds) == 0.0
    # a flat stream crossing dp moves its full payload on DCN
    assert dcn_stream_bytes(base, kinds) == 10.0
    assert dcn_stream_bytes(
        dict(base, per_device_bytes_per_step=7.0), kinds) == 7.0
    # the hierarchical wire only ships the shrunk inter-group hop there
    assert dcn_stream_bytes(
        dict(base, hierarchical=True, inter_bytes_per_step=2.0), kinds
    ) == 2.0


# ------------------------------------------- planner: 2-hop beats flat
def test_planner_ranks_2hop_above_flat_on_hybrid(devices8):
    """On a hybrid mesh with dcn_bw ≪ ici_bw, per-link pricing alone —
    no new knob — must rank the hierarchical 2-hop grad reduce-scatter
    above the flat single-ring form."""
    from deepspeed_tpu.autotuning import PlannerSearch

    base = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 2, "grad_wire": "int8"},
        "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                       "tune_zero": False},
    }
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16,
                 hidden_size=32, num_layers=2, num_heads=2)
    search = PlannerSearch(model, base, None, top_k=1,
                           mesh_shapes=[(2, 4, 1)],
                           hardware=_hw(dcn_bw=1e6),
                           wire_codecs=("int8",))
    cands = search.candidates()
    assert {c.hier_wire for c in cands} == {False, True}
    two_hop = next(c for c in cands if c.hier_wire)
    cfg = search._candidate_config(two_hop)
    assert cfg["zero_optimization"]["hierarchical_wire"] is True
    assert cfg["topology"]["dcn_dp"] == 2
    assert "rs2hop" in two_hop.label() and "dcnx" in two_hop.label()

    res = search.search()
    ranked = [p for p in res.survivors if p.plan is not None]
    assert ranked, res.explain()
    best = res.survivors[0]
    assert best.cand.hier_wire is True, res.explain()
    # the flat twin at the same rung priced its full grad payload on DCN
    flat = next(p for p in res.planned
                if p.cand.hier_wire is False
                and p.cand.group_key()[:3] == best.cand.group_key()[:3]
                and p.cand.micro == best.cand.micro and p.plan is not None)
    assert sum(flat.plan.dcn_bytes.values()) > sum(
        best.plan.dcn_bytes.values())
    assert flat.plan.est_step_s > best.plan.est_step_s


# ------------------------------------------------- mesh spellings
def test_autoplan_parse_meshes_hybrid_syntax():
    spec = importlib.util.spec_from_file_location(
        "autoplan", os.path.join(REPO, "tools", "autoplan.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert mod.parse_meshes("8x1,4x2") == [(8, 1), (4, 2)]
    assert mod.parse_meshes("2*4x1,2*2x2") == [(2, 4, 1), (2, 2, 2)]


def test_topology_key_spells_hybrid_factorization(devices8):
    flat = MeshTopology(ParallelDims(dp=2, fsdp=4))
    hybrid = MeshTopology.hybrid(ParallelDims(dp=2, fsdp=4))
    assert topology_key(flat) == "dp2xfsdp4"
    assert topology_key(hybrid) == "dp2dcnxfsdp4"


def test_campaign_config_topology_carries_dcn(devices8):
    from deepspeed_tpu.autotuning.campaign import config_topology

    cfg = {
        "train_batch_size": 32,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "topology": {"dcn_dp": 2},
        "zero_optimization": {"stage": 2, "zero_hpz_partition_size": 4},
    }
    topo = config_topology(cfg)
    assert topo.sizes["dp"] == 2 and topo.sizes["fsdp"] == 4
    assert topo.link_kinds.get("dp") == "dcn"
    assert topology_key(topo) == "dp2dcnxfsdp4"
    # no topology section -> flat spelling, no dcn suffix
    del cfg["topology"]
    assert "dcn" not in topology_key(config_topology(cfg))


# ------------------------------------------------- parity pair gating
def test_hybrid_example_declares_2hop_parity_pair(devices8):
    from deepspeed_tpu.analysis import config_parity_pairs

    with open(os.path.join(REPO, "examples", "ds_config_hybrid.json")) as f:
        raw = json.load(f)
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16,
                 hidden_size=32, num_layers=2, num_heads=2)
    names = [p.name for p in config_parity_pairs(raw, model)]
    assert "train/grad-rs-2hop-vs-flat" in names
    # the pair is gated on the knob: a flat-wire config stays silent
    flat = dict(raw, zero_optimization=dict(
        raw["zero_optimization"], hierarchical_wire=False))
    names = [p.name for p in config_parity_pairs(flat, model)]
    assert "train/grad-rs-2hop-vs-flat" not in names
