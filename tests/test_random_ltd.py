"""Random-LTD end-to-end wiring (engine → apply_layer_stack).

Model: reference tests/unit/runtime/test_data_efficiency.py — the
data_efficiency.random_ltd config must actually change what the train step
computes (r2 verdict: the op existed but nothing consumed it)."""

import jax
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.data_pipeline import random_ltd as ltd_mod
from deepspeed_tpu.models import gpt2

SEQ = 16


def _cfg(enabled=True, min_value=8, max_value=SEQ, layer_ids=(1, 2)):
    return {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "gradient_clipping": 1.0,
        "steps_per_print": 1000,
        "data_efficiency": {
            "enabled": enabled,
            "data_routing": {
                "random_ltd": {
                    "enabled": enabled,
                    "random_ltd_layer_id": list(layer_ids),
                    "random_ltd_schedule": {
                        "min_value": min_value,
                        "max_value": max_value,
                        "seq_step": 4,
                        "total_layer_drop_step": 8,
                    },
                }
            },
        },
    }


def _model():
    return gpt2("gpt2-tiny", vocab_size=128, max_seq_len=SEQ, num_layers=4)


def _data(seed=0):
    return {"input_ids": np.random.RandomState(seed).randint(0, 128, size=(8, SEQ))}


def test_random_ltd_drop_active(devices8, monkeypatch):
    """The LTD layers must actually gather a smaller token subset."""
    seen_keeps = []
    orig = ltd_mod.sample_token_subset

    def spy(rng, batch, seq_len, keep):
        seen_keeps.append((seq_len, keep))
        return orig(rng, batch, seq_len, keep)

    monkeypatch.setattr(ltd_mod, "sample_token_subset", spy)
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg(min_value=8), rng=jax.random.PRNGKey(0)
    )
    assert engine.random_ltd is not None
    assert engine._ltd_layers == (1, 3)
    engine.train_batch(batch=_data())
    # 2 LTD layers traced, each sampling keep=8 of 16 tokens
    assert seen_keeps, "sample_token_subset never traced: LTD inactive"
    assert all(k == 8 and s == SEQ for s, k in seen_keeps)


def test_random_ltd_schedule_advances_to_full(devices8):
    """Keep count anneals to max_value; at keep >= seq the drop turns off
    (train_batch passes ltd_keep=None, no recompile churn)."""
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=_model(), config=_cfg(min_value=8), rng=jax.random.PRNGKey(0)
    )
    sched = engine.random_ltd
    assert sched.get_seq_len(0) == 8
    assert sched.get_seq_len(10**6) == SEQ


def test_random_ltd_convergence_smoke(devices8):
    """50-step convergence: training with token dropping still learns."""
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=_model(),
        config=_cfg(min_value=8, max_value=12),
        rng=jax.random.PRNGKey(0),
    )
    losses = [float(engine.train_batch(batch=_data())) for _ in range(50)]
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_random_ltd_noncontiguous_layer_ids_rejected(devices8):
    comm.destroy_process_group()
    with pytest.raises(deepspeed_tpu.DeepSpeedConfigError):
        deepspeed_tpu.initialize(
            model=_model(), config=_cfg(layer_ids=(0, 2)),
            rng=jax.random.PRNGKey(0),
        )


# --------------------------------------------------------------- analyzer
def test_data_analyzer_metrics():
    """Offline difficulty metrics (reference DataAnalyzer): seqlen counts
    non-pad tokens; vocabularyrarity ranks rare-token samples harder."""
    from deepspeed_tpu.data_pipeline.data_analyzer import analyze_dataset

    ids = np.array(
        [
            [1, 1, 1, 1],        # common tokens, full length
            [1, 1, -1, -1],      # short
            [7, 8, 9, 5],        # rare tokens
        ]
    )
    s = analyze_dataset(ids, pad_id=-1, vocab_size=16)
    np.testing.assert_array_equal(s["seqlen"], [4, 2, 4])
    # the rare-vocab sample must score strictly harder than the common one
    assert s["vocabularyrarity"][2] > s["vocabularyrarity"][0]


def test_data_analyzer_index_roundtrip(tmp_path):
    from deepspeed_tpu.data_pipeline.data_analyzer import (
        DataAnalyzer,
        load_index,
    )

    ids = np.random.RandomState(0).randint(0, 32, size=(16, 8))
    path = str(tmp_path / "difficulty.npz")
    scores = DataAnalyzer().run(ids, save_path=path)
    loaded = load_index(path)
    for k in scores:
        np.testing.assert_allclose(loaded[k], scores[k])


def test_curriculum_sampler_follows_pacing():
    """Early steps draw only from the easiest samples; late steps reach the
    whole set."""
    from deepspeed_tpu.data_pipeline.curriculum_scheduler import (
        CurriculumScheduler,
    )
    from deepspeed_tpu.data_pipeline.data_analyzer import CurriculumSampler

    sched = CurriculumScheduler(
        {
            "curriculum_type": "seqlen",
            "min_difficulty": 8,
            "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100,
                                "difficulty_step": 8},
        }
    )
    scores = np.arange(100, dtype=np.float64)  # sample i has difficulty i
    sampler = CurriculumSampler(scores, sched, seed=0)
    early = sampler.sample_indices(step=0, batch_size=16)
    late = sampler.sample_indices(step=100, batch_size=64)
    # early draws come from the easiest ~ (8/64) fraction (>= batch floor)
    assert early.max() <= 16
    assert late.max() > 50  # full range reachable at max difficulty
