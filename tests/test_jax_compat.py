"""utils/jax_compat shim branches, exercised directly on whatever jax the
image ships (ISSUE 2 satellite): the legacy 0.4.x fallbacks run for real
here; the modern branches are covered by monkeypatched stand-ins so the
dispatch logic is tested without a second jax install.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from deepspeed_tpu.utils import jax_compat

HAS_MODERN_SHARD_MAP = hasattr(jax, "shard_map")


def _mesh(shape, names):
    devs = np.array(jax.devices()[: int(np.prod(shape))]).reshape(shape)
    return Mesh(devs, names)


# ------------------------------------------------------------- shard_map
def test_shard_map_modern_branch_kwarg_translation(monkeypatch, devices8):
    """When jax.shard_map exists the shim must pass axis_names/check_vma
    through untranslated — verified against a recording stand-in (this
    image is 0.4.x, so the modern API is simulated)."""
    calls = {}

    def fake_shard_map(f, mesh, in_specs, out_specs, **kw):
        calls.update(kw, mesh=mesh, in_specs=in_specs, out_specs=out_specs)
        return f

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    mesh = _mesh((4, 2), ("dp", "tp"))
    fn = jax_compat.shard_map(
        lambda x: x, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        axis_names={"dp"}, check_vma=False,
    )
    assert fn(3) == 3  # the wrapped callable is returned as-is
    assert calls["axis_names"] == {"dp"}
    assert calls["check_vma"] is False
    assert calls["mesh"] is mesh


@pytest.mark.skipif(HAS_MODERN_SHARD_MAP, reason="legacy fallback absent")
def test_shard_map_legacy_full_manual_runs(devices8):
    """Full-manual legacy fallback actually computes (psum over dp)."""
    mesh = _mesh((4, 2), ("dp", "tp"))
    fn = jax_compat.shard_map(
        lambda x: jax.lax.psum(x, "dp"),
        mesh=mesh,
        in_specs=P("dp"),
        out_specs=P(),
        axis_names={"dp", "tp"},
        check_vma=False,
    )
    out = jax.jit(fn)(jnp.ones((8, 2)))
    np.testing.assert_allclose(np.asarray(out), np.full((2, 2), 4.0))


@pytest.mark.skipif(HAS_MODERN_SHARD_MAP, reason="legacy fallback absent")
def test_shard_map_legacy_refuses_partial_manual(devices8):
    """A LIVE auto axis beside manual axes must raise NotImplementedError
    (the 0.4.x SPMD partitioner would hard-abort in C++ instead)."""
    mesh = _mesh((4, 2), ("dp", "tp"))
    with pytest.raises(NotImplementedError, match="partial-manual"):
        jax_compat.shard_map(
            lambda x: x, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
            axis_names={"dp"},  # tp (size 2) stays auto → partial-manual
        )


@pytest.mark.skipif(HAS_MODERN_SHARD_MAP, reason="legacy fallback absent")
def test_shard_map_legacy_allows_size1_auto_axes(devices8):
    """Size-1 auto axes are type-irrelevant and must NOT trip the
    partial-manual refusal."""
    mesh = _mesh((8, 1), ("dp", "tp"))
    fn = jax_compat.shard_map(
        lambda x: jax.lax.psum(x, "dp"),
        mesh=mesh, in_specs=P("dp"), out_specs=P(),
        axis_names={"dp"}, check_vma=False,
    )
    out = jax.jit(fn)(jnp.ones((8,)))
    np.testing.assert_allclose(np.asarray(out), 8.0)


# ------------------------------------------------------ get_abstract_mesh
def test_get_abstract_mesh_branches(monkeypatch):
    if hasattr(jax.sharding, "get_abstract_mesh"):
        # modern: whatever jax returns passes through
        assert jax_compat.get_abstract_mesh() is not None or True
        monkeypatch.delattr(jax.sharding, "get_abstract_mesh")
        assert jax_compat.get_abstract_mesh() is None
    else:
        # legacy: no trace-time mesh context → None
        assert jax_compat.get_abstract_mesh() is None
        sentinel = object()
        monkeypatch.setattr(
            jax.sharding, "get_abstract_mesh", lambda: sentinel,
            raising=False,
        )
        assert jax_compat.get_abstract_mesh() is sentinel


# ---------------------------------------------------------------- axis_size
def test_axis_size_modern_branch(monkeypatch):
    monkeypatch.setattr(jax.lax, "axis_size", lambda a: 42, raising=False)
    assert jax_compat.axis_size("anything") == 42


@pytest.mark.skipif(hasattr(jax.lax, "axis_size"),
                    reason="legacy axis_frame fallback absent")
def test_axis_size_legacy_fallback_inside_mapped_body(devices8):
    sizes = {}

    def body(x):
        sizes["i"] = jax_compat.axis_size("i")
        return x

    jax.pmap(body, axis_name="i")(jnp.zeros((2, 2)))
    assert sizes["i"] == 2


# --------------------------------------------------------- bound_axis_names
def test_bound_axis_names_probe(devices8):
    if not hasattr(jax.core, "axis_frame"):
        assert jax_compat.bound_axis_names(("i", "j")) == set()
        return
    assert jax_compat.bound_axis_names(("i", "j")) == set()  # unbound

    seen = {}

    def body(x):
        seen["bound"] = jax_compat.bound_axis_names(("i", "nope"))
        return x

    jax.pmap(body, axis_name="i")(jnp.zeros((2, 2)))
    assert seen["bound"] == {"i"}


def test_bound_axis_names_without_axis_frame(monkeypatch):
    monkeypatch.delattr(jax.core, "axis_frame", raising=False)
    assert jax_compat.bound_axis_names(("i",)) == set()


# ----------------------------------------------- pallas CompilerParams shim
def test_pallas_compiler_params_resolves_without_patching():
    from jax.experimental.pallas import tpu as pltpu

    cls = jax_compat.pallas_tpu_compiler_params()
    assert cls is getattr(pltpu, "CompilerParams", None) or cls is getattr(
        pltpu, "TPUCompilerParams"
    )
    # the shim must NOT monkey-patch the module (the whole point)
    if not hasattr(pltpu, "CompilerParams"):
        assert jax_compat.pallas_tpu_compiler_params() is pltpu.TPUCompilerParams


def test_pallas_compiler_params_prefers_modern_name(monkeypatch):
    from jax.experimental.pallas import tpu as pltpu

    class Modern:  # stand-in for the renamed class
        pass

    monkeypatch.setattr(pltpu, "CompilerParams", Modern, raising=False)
    assert jax_compat.pallas_tpu_compiler_params() is Modern


# ------------------------------------- decomposed collective matmul branch
def test_tensor_overlap_is_full_manual_on_this_jax(devices8):
    """The decomposed collective matmul (parallel/tensor_overlap.py) is a
    FULL-manual shard_map program, so it must actually run through the
    legacy 0.4.x fallback on this image (a partial-manual formulation
    would be refused with NotImplementedError — never a C++ abort)."""
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
    from deepspeed_tpu.parallel.tensor_overlap import allgather_matmul

    topo = MeshTopology(dims=ParallelDims(tp=4, dp=2))
    x = jnp.asarray(np.random.RandomState(0).randn(2, 8, 16), jnp.float32)
    w = jnp.asarray(np.random.RandomState(1).randn(16, 8), jnp.float32)
    out = jax.jit(lambda a, b: allgather_matmul(a, b, topo))(x, w)
    np.testing.assert_array_equal(
        np.asarray(out), np.asarray(jnp.einsum("bsk,kn->bsn", x, w))
    )


def test_tensor_overlap_passes_full_axis_set_to_modern_shard_map(
    monkeypatch, devices8
):
    """On modern jax the shim forwards axis_names — the overlap wrapper
    must request EVERY mesh axis (full manual), which is also what makes
    the legacy fallback legal."""
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
    from deepspeed_tpu.parallel import tensor_overlap

    seen = {}

    def fake_shard_map(f, mesh, in_specs, out_specs, **kw):
        seen.update(kw)
        raise RuntimeError("stop after capture")

    monkeypatch.setattr(jax, "shard_map", fake_shard_map, raising=False)
    topo = MeshTopology(dims=ParallelDims(tp=4, dp=2))
    with pytest.raises(RuntimeError, match="stop after capture"):
        tensor_overlap.allgather_matmul(
            jnp.zeros((2, 8, 16)), jnp.zeros((16, 8)), topo
        )
    assert seen["axis_names"] == set(topo.mesh.axis_names)
    assert seen["check_vma"] is False
