"""MeshTopology tests. Model: reference tests/unit/runtime/pipe/test_topology.py."""

import numpy as np
import pytest

from deepspeed_tpu.comm.topology import (
    MeshTopology,
    ParallelDims,
    PipeModelDataParallelTopology,
)


def test_dp_inferred(devices8):
    topo = MeshTopology(ParallelDims(tp=2))
    assert topo.tp_size == 2
    assert topo.dp_size == 4
    assert topo.world_size == 8
    assert topo.mesh.shape["tp"] == 2


def test_bad_dims_raise(devices8):
    with pytest.raises(ValueError):
        MeshTopology(ParallelDims(dp=3, tp=2))
    with pytest.raises(ValueError):
        MeshTopology(ParallelDims(tp=3))


def test_rank_coord_roundtrip(devices8):
    topo = MeshTopology(ParallelDims(pp=2, tp=2))
    for rank in range(8):
        coord = topo.get_coord(rank)
        assert topo.get_rank(**coord) == rank


def test_axis_comm_lists_partition_world(devices8):
    topo = MeshTopology(ParallelDims(pp=2, tp=2))
    lists = topo.get_axis_comm_lists("tp")
    assert len(lists) == 4
    flat = sorted(r for lst in lists for r in lst)
    assert flat == list(range(8))
    for lst in lists:
        assert len(lst) == 2


def test_reference_topology_alias(devices8):
    topo = PipeModelDataParallelTopology(num_pp=2, num_mp=2, num_dp=2)
    assert topo.pp_size == 2 and topo.tp_size == 2 and topo.dp_size == 2
    # reference alias axes resolve
    assert topo.get_dim("pipe") == 2
    assert topo.get_dim("model") == 2
    assert topo.get_dim("data") == 2


def test_batch_spec(devices8):
    topo = MeshTopology(ParallelDims(fsdp=2, sp=2))
    spec = topo.batch_spec()
    assert spec[0] == ("dp", "fsdp")
    assert spec[1] == "sp"


def test_tp_innermost_adjacency(devices8):
    """tp groups must be adjacent device indices (ICI locality)."""
    topo = MeshTopology(ParallelDims(tp=2))
    grid = np.asarray(topo.mesh.devices)
    flat = grid.reshape(-1)
    for i in range(0, 8, 2):
        assert flat[i].id + 1 == flat[i + 1].id


# ------------------------------------------------------ hybrid DCN×ICI
def test_hybrid_construction_and_link_metadata(devices8):
    """MeshTopology.hybrid: dp rides DCN, everything else ICI; the DCN
    axis is outermost so each dp coordinate selects one contiguous
    (ICI-connected) pod of devices."""
    topo = MeshTopology.hybrid(ParallelDims(dp=2, fsdp=4))
    assert topo.is_hybrid
    assert topo.dcn_axes == ("dp",)
    assert topo.link_kinds["dp"] == "dcn"
    assert topo.link_kinds["fsdp"] == "ici"
    assert "dp=2[dcn]" in repr(topo)
    # each dp "pod" is a contiguous block of adjacent device ids
    grid = np.asarray(topo.mesh.devices)
    flat = grid.reshape(2, 4)
    for pod in range(2):
        ids = [d.id for d in flat[pod]]
        assert ids == list(range(ids[0], ids[0] + 4))


def test_hybrid_flat_meshes_stay_all_ici(devices8):
    topo = MeshTopology(ParallelDims(dp=8))
    assert not topo.is_hybrid
    assert topo.dcn_axes == ()
    assert set(topo.link_kinds.values()) == {"ici"}
    assert "[dcn]" not in repr(topo)


def test_hybrid_rejects_bad_axes(devices8):
    # an ICI axis preceding the DCN axis in the canonical order means the
    # DCN axis would not be slowest-varying over the device list
    with pytest.raises(ValueError, match="outermost"):
        MeshTopology.hybrid(ParallelDims(dp=2, tp=4), dcn_axes=("tp",))
    with pytest.raises(ValueError, match="unknown DCN axis"):
        MeshTopology.hybrid(ParallelDims(dp=2, fsdp=4), dcn_axes=("bogus",))
    with pytest.raises(ValueError, match="link_kinds"):
        MeshTopology(ParallelDims(dp=8), link_kinds={"dp": "fast"})
