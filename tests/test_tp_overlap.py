"""Decomposed collective matmul (ISSUE 3): CPU-mesh oracles prove the
ring forms match the pure-XLA reference path — BITWISE for the unquantized
unidirectional rings — plus engine/inference integration and the
overlap_comm config surface.

Kept inside the tier-1 budget: every oracle runs one small jitted program
per form; the heavyweight parameter grid lives in a handful of cases
(odd/even tp, uneven chunks) rather than a cross-product.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.models import llama
from deepspeed_tpu.models.sharding import use_topology
from deepspeed_tpu.parallel import tensor_overlap as to

pytestmark = pytest.mark.tp_overlap


def topo_for(tp: int) -> MeshTopology:
    """tp over the smallest device subset that also keeps a dp axis when
    possible; odd tp sizes use a truncated device list (8 has no odd
    divisor > 1)."""
    if 8 % tp == 0:
        return MeshTopology(dims=ParallelDims(tp=tp, dp=8 // tp))
    return MeshTopology(
        dims=ParallelDims(tp=tp, dp=1), devices=jax.devices()[:tp]
    )


def rand(shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape), jnp.float32)


# ----------------------------------------------------------------- oracles
@pytest.mark.parametrize("tp", [2, 4, 3])  # odd AND even ring sizes
def test_allgather_matmul_bitwise_vs_reference(tp, devices8):
    topo = topo_for(tp)
    dp = topo.dp_size
    B, S, K, N = 2 * dp, 12 * tp, 24, 8 * tp
    x, w = rand((B, S, K)), rand((K, N), seed=1)
    dense = jnp.einsum("bsk,kn->bsn", x, w)
    ref = jax.jit(
        lambda a, b: to.allgather_matmul(a, b, topo, reference=True)
    )(x, w)
    ring = jax.jit(lambda a, b: to.allgather_matmul(a, b, topo))(x, w)
    # the pure-XLA reference path itself equals the plain einsum bitwise
    # (row blocks of a dot are independent), and the unquantized
    # unidirectional ring matches it bitwise — the acceptance oracle
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(dense))
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(ref))


@pytest.mark.parametrize("tp", [2, 4, 3])
def test_matmul_reducescatter_bitwise_vs_reference(tp, devices8):
    topo = topo_for(tp)
    dp = topo.dp_size
    B, S, K, N = 2 * dp, 4 * tp, 16 * tp, 24
    x, w = rand((B, S, K)), rand((K, N), seed=2)
    dense = jnp.einsum("bsk,kn->bsn", x, w)
    ref = jax.jit(
        lambda a, b: to.matmul_reducescatter(a, b, topo, reference=True)
    )(x, w)
    ring = jax.jit(lambda a, b: to.matmul_reducescatter(a, b, topo))(x, w)
    # the reference reduces in pinned ring order (qgZ all-to-all form), so
    # ring == reference is bitwise; both match the dense einsum+psum path
    # to f32 tolerance (different fp32 summation orders)
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(ref))
    np.testing.assert_allclose(
        np.asarray(ring), np.asarray(dense), rtol=1e-4, atol=1e-4
    )


@pytest.mark.parametrize("bidirectional", [False, True])
def test_uneven_chunks_change_nothing(bidirectional, devices8):
    """chunks that don't divide the rows (and odd per-shard rows for the
    bidirectional halves) are pure scheduling — bitwise-identical."""
    tp = 4
    topo = topo_for(tp)
    B, S, K, N = 4, 5 * tp, 24, 8 * tp  # 5 rows/shard: 3 chunks split 2/2/1
    x, w = rand((B, S, K)), rand((K, N), seed=3)
    base = jax.jit(lambda a, b: to.allgather_matmul(a, b, topo))(x, w)
    got = jax.jit(
        lambda a, b: to.allgather_matmul(
            a, b, topo, chunks=3, bidirectional=bidirectional
        )
    )(x, w)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(base))
    # scatter side: uneven chunks + bidirectional halves, f32 tolerance
    # (the backward half accumulates in reverse ring order)
    x2, w2 = rand((B, S, K * tp), seed=4), rand((K * tp, N), seed=5)
    dense = jnp.einsum("bsk,kn->bsn", x2, w2)
    got2 = jax.jit(
        lambda a, b: to.matmul_reducescatter(
            a, b, topo, chunks=3, bidirectional=bidirectional
        )
    )(x2, w2)
    np.testing.assert_allclose(
        np.asarray(got2), np.asarray(dense), rtol=1e-4, atol=1e-4
    )


def test_bidirectional_gather_still_bitwise(devices8):
    """The two-stream gather writes each row from exactly one dot — still
    bitwise against the reference, odd and even ring sizes."""
    for tp in (4, 3):
        topo = topo_for(tp)
        x = rand((2, 3 * tp, 16), seed=6)  # 3 rows/shard → halves 2 + 1
        w = rand((16, 8 * tp), seed=7)
        ref = jax.jit(
            lambda a, b: to.allgather_matmul(a, b, topo, reference=True)
        )(x, w)
        got = jax.jit(
            lambda a, b: to.allgather_matmul(a, b, topo, bidirectional=True)
        )(x, w)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))


def test_quantized_hops(devices8):
    """Gather wires quantize once at the source: ring == reference
    BITWISE (same int8+scale payload either way) and within fake-quant
    error of the dense product. Scatter accumulators re-quantize per hop:
    tolerance grows with the ring (documented O(tp) error)."""
    tp = 4
    topo = topo_for(tp)
    x, w = rand((2, 4 * tp, 24), seed=8), rand((24, 8 * tp), seed=9)
    dense = jnp.einsum("bsk,kn->bsn", x, w)
    q_ring = jax.jit(
        lambda a, b: to.allgather_matmul(a, b, topo, quantized=True)
    )(x, w)
    q_ref = jax.jit(
        lambda a, b: to.allgather_matmul(
            a, b, topo, quantized=True, reference=True
        )
    )(x, w)
    np.testing.assert_array_equal(np.asarray(q_ring), np.asarray(q_ref))
    err = np.max(np.abs(np.asarray(q_ring) - np.asarray(dense)))
    assert err < 0.5, f"int8 gather-wire error too large: {err}"

    x2, w2 = rand((2, 4 * tp, 8 * tp), seed=10), rand((8 * tp, 24), seed=11)
    dense2 = jnp.einsum("bsk,kn->bsn", x2, w2)
    q_rs = jax.jit(
        lambda a, b: to.matmul_reducescatter(a, b, topo, quantized=True)
    )(x2, w2)
    rel = np.max(np.abs(np.asarray(q_rs) - np.asarray(dense2))) / (
        np.max(np.abs(np.asarray(dense2))) + 1e-9
    )
    assert rel < 0.2, f"int8 scatter-wire relative error too large: {rel}"
    # the quantized reference (per-block qgZ all-to-all) must trace, run
    # and stay within the same tolerance — it quantizes each partial once
    # where the ring re-quantizes the riding sum per hop, so the two are
    # compared to the dense product, not to each other
    q_rs_ref = jax.jit(
        lambda a, b: to.matmul_reducescatter(
            a, b, topo, quantized=True, reference=True
        )
    )(x2, w2)
    rel_ref = np.max(np.abs(np.asarray(q_rs_ref) - np.asarray(dense2))) / (
        np.max(np.abs(np.asarray(dense2))) + 1e-9
    )
    assert rel_ref < 0.2, f"quantized reference error too large: {rel_ref}"


def test_features_scatter_decode_form(devices8):
    """The S=1 decode form: feature-scatter + gather == plain matmul
    (decomposed all-reduce)."""
    tp = 4
    topo = topo_for(tp)
    x, w = rand((1, 1, 8 * tp), seed=12), rand((8 * tp, 16 * tp), seed=13)
    dense = jnp.einsum("bsk,kn->bsn", x, w)
    got = jax.jit(
        lambda a, b: to.matmul_reducescatter(
            a, b, topo, scatter="features", gather_result=True
        )
    )(x, w)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(dense), rtol=1e-4, atol=1e-4
    )


# ------------------------------------------------------------- ring bytes
def test_rings_are_logged_and_validated(devices8):
    """The rings go through comm.collectives.permute: hop bytes reach the
    comms-logger hook bus and a malformed hand-built perm raises at
    construction (satellite: the neighbor_chain contract, now enforced)."""
    seen = []
    comm.collectives.register_comm_hook(
        lambda op, axis, nbytes: seen.append((op, nbytes))
    )
    try:
        tp = 4
        topo = topo_for(tp)
        x, w = rand((2, 4 * tp, 16)), rand((16, 8 * tp))
        jax.jit(lambda a, b: to.allgather_matmul(a, b, topo))(x, w)
    finally:
        comm.collectives.clear_comm_hooks()
    hops = [n for op, n in seen if op == "ppermute"]
    assert len(hops) == tp - 1  # one wire per hop, traced unrolled
    assert all(n == hops[0] > 0 for n in hops)


# ------------------------------------------------------ engine integration
def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=32, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=4, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


def test_engine_loss_parity_and_ring_accounting(devices8):
    """tp=2 training with overlap on tracks the off run step-for-step, and
    the engine reports the analytic ring stream to the comms logger."""
    data = {"input_ids": np.random.RandomState(0).randint(0, 128, size=(8, 32))}

    def run(overlap):
        comm.destroy_process_group()
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "tensor_parallel": {
                "tp_size": 2,
                "overlap_comm": {"enabled": overlap, "chunks": 2,
                                 "bidirectional": True},
            },
            "comms_logger": {"enabled": True},
            "steps_per_print": 1000,
        }
        eng, *_ = deepspeed_tpu.initialize(model=tiny_llama(), config=cfg)
        losses = [float(eng.train_batch(batch=data)) for _ in range(2)]
        stream = eng.tp_overlap_stream
        logged = eng.comm_logger.ring_bytes
        pperm = eng.comm_logger.counts.get("ppermute", 0)
        eng.destroy()
        return losses, stream, logged, pperm

    l_off, s_off, logged_off, pp_off = run(False)
    l_on, s_on, logged_on, pp_on = run(True)
    np.testing.assert_allclose(l_off, l_on, rtol=2e-3, atol=2e-3)
    assert s_off is None and logged_off == 0
    assert s_on is not None and s_on["bytes_per_step"] > 0
    assert logged_on == 2 * s_on["bytes_per_step"]  # two recorded steps
    assert pp_on > pp_off  # ring hops hit the trace-time hook bus too


def test_inference_generate_parity_under_overlap(devices8):
    """Dense tp=4 serving with overlap_comm produces token-identical
    output to the unsharded engine (prefill takes the Megatron-SP pair
    when shapes divide; S=1 decode takes the feature-scatter ring)."""
    m = tiny_llama(num_kv_heads=2)
    p = m.init(jax.random.PRNGKey(0), dtype=jnp.float32)
    prompt = np.array([[5, 9, 11, 3]])
    e1 = deepspeed_tpu.init_inference(m, dtype=jnp.float32, params=p)
    out1 = e1.generate(prompt, max_new_tokens=6)
    topo = MeshTopology(dims=ParallelDims(tp=4, dp=2))
    e2 = deepspeed_tpu.init_inference(
        m, dtype=jnp.float32, params=p, topology=topo,
        tensor_parallel={
            "tp_size": 4,
            "overlap_comm": {"enabled": True, "bidirectional": True},
        },
    )
    out2 = e2.generate(prompt, max_new_tokens=6)
    np.testing.assert_array_equal(out1, out2)


def test_overlap_noop_outside_scope_and_inside_manual(devices8):
    """Without the scope the dispatchers are the plain projections; under
    an installed topology but inside a manual shard_map they fall back
    (the pipeline schedule case)."""
    topo = MeshTopology(dims=ParallelDims(tp=4, dp=2))
    x, w = rand((2, 8, 16)), rand((16, 8))
    with use_topology(topo):
        (y,) = to.tp_in_proj(x, (w,))  # no scope: plain einsum
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(jnp.einsum("bsk,kn->bsn", x, w)),
        rtol=1e-6, atol=1e-6,
    )
    assert to.current_overlap() is None
    cfg = DeepSpeedConfig(
        {"train_batch_size": 8,
         "tensor_parallel": {"tp_size": 4,
                             "overlap_comm": {"enabled": True}}}
    ).tensor_parallel.overlap_comm
    with to.overlap_scope(cfg):
        assert to.current_overlap() is cfg
        assert to._active(topo) is cfg
        # inside a manual mapped context the guard must refuse
        from deepspeed_tpu.utils.jax_compat import shard_map
        from jax.sharding import PartitionSpec as P

        flags = {}

        def body(a):
            with use_topology(topo):
                flags["active"] = to._active(topo)
            return a

        jax.jit(shard_map(
            body, mesh=topo.mesh, in_specs=P(("dp",)), out_specs=P("dp"),
            axis_names=set(topo.mesh.axis_names), check_vma=False,
        ))(jnp.ones((8,)))
        assert flags["active"] is None


# ------------------------------------------------------------------ config
def test_overlap_comm_config_surface():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "tensor_parallel": {
            "tp_size": 2,
            "overlap_comm": {"enabled": True, "chunks": 4,
                             "bidirectional": True, "quantized_hops": True},
        },
    })
    oc = cfg.tensor_parallel.overlap_comm
    assert (oc.enabled, oc.chunks, oc.bidirectional, oc.quantized_hops) == (
        True, 4, True, True,
    )
    # defaults: knob off, unit chunks
    oc2 = DeepSpeedConfig({"train_batch_size": 8}).tensor_parallel.overlap_comm
    assert (oc2.enabled, oc2.chunks) == (False, 1)
    # the autotp_size alias must not drop the rest of the section
    tp3 = DeepSpeedConfig({
        "train_batch_size": 8,
        "tensor_parallel": {"autotp_size": 2,
                            "overlap_comm": {"enabled": True}},
    }).tensor_parallel
    assert tp3.tp_size == 2 and tp3.overlap_comm.enabled
    # bare boolean (the zero_optimization.overlap_comm spelling) coerces
    tp4 = DeepSpeedConfig({
        "train_batch_size": 8,
        "tensor_parallel": {"tp_size": 2, "overlap_comm": True},
    }).tensor_parallel
    assert tp4.overlap_comm.enabled and tp4.overlap_comm.chunks == 1
    with pytest.raises(DeepSpeedConfigError, match="chunks"):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "tensor_parallel": {"overlap_comm": {"enabled": True,
                                                 "chunks": 0}},
        })
    with pytest.raises(DeepSpeedConfigError, match="pipeline"):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "pipeline": {"stages": 2},
            "tensor_parallel": {"tp_size": 2,
                                "overlap_comm": {"enabled": True}},
        })


def test_quantized_hops_training_gradients_flow(devices8):
    """quantized_hops is forward-only (straight-through backward): the
    engine must still move the loss — int8 casts inside the ring would
    otherwise zero every activation cotangent below the projection."""
    data = {"input_ids": np.random.RandomState(1).randint(0, 128, size=(8, 32))}
    comm.destroy_process_group()
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 5e-3}},
        "tensor_parallel": {
            "tp_size": 2,
            "overlap_comm": {"enabled": True, "quantized_hops": True},
        },
        "steps_per_print": 1000,
    }
    eng, *_ = deepspeed_tpu.initialize(model=tiny_llama(), config=cfg)
    first = float(eng.train_batch(batch=data))
    embed0 = np.asarray(eng.state.params["embed"]["tok"])
    for _ in range(3):
        last = float(eng.train_batch(batch=data))
    embed1 = np.asarray(eng.state.params["embed"]["tok"])
    eng.destroy()
    assert np.isfinite(first) and np.isfinite(last)
    # the embedding sits BELOW every ring: it only moves if cotangents
    # survive the quantized wires
    assert not np.allclose(embed0, embed1)
    assert last < first
