"""MoE gating/dispatch tests. Parity model: reference tests/unit/moe/test_moe.py."""

import jax
import jax.numpy as jnp
import numpy as np

from deepspeed_tpu.moe.sharded_moe import top_k_gating
from deepspeed_tpu.models import make_lm_batch, mixtral


def test_capacity_never_exceeded():
    rng = jax.random.PRNGKey(0)
    logits = jax.random.normal(rng, (64, 4))
    dispatch, combine, metrics = top_k_gating(logits, top_k=2, capacity=8, rng=None, train=True)
    per_expert = np.asarray(dispatch.sum(axis=(0, 2)))
    assert (per_expert <= 8).all()
    # each (expert, slot) holds at most one token
    slot_fill = np.asarray(dispatch.sum(axis=0))
    assert (slot_fill <= 1.0 + 1e-6).all()


def test_combine_weights_normalized():
    rng = jax.random.PRNGKey(1)
    logits = jax.random.normal(rng, (32, 4))
    dispatch, combine, _ = top_k_gating(logits, top_k=2, capacity=32, rng=None, train=True)
    sums = np.asarray(combine.sum(axis=(1, 2)))
    # ample capacity => every token fully routed, weights sum to 1
    np.testing.assert_allclose(sums, np.ones(32), atol=1e-5)


def test_top1_routes_to_argmax():
    logits = jnp.eye(4, dtype=jnp.float32) * 10.0  # token i loves expert i
    dispatch, combine, _ = top_k_gating(logits, top_k=1, capacity=4, rng=None, train=True)
    routed = np.asarray(dispatch.sum(axis=2))  # [N, E]
    np.testing.assert_allclose(routed, np.eye(4))


def test_aux_loss_uniform_vs_skewed():
    n = 128
    rng = jax.random.PRNGKey(2)
    uniform = jax.random.normal(rng, (n, 4)) * 0.01
    skewed = jnp.concatenate([jnp.full((n, 1), 5.0), jnp.full((n, 3), -5.0)], axis=1)
    _, _, m_u = top_k_gating(uniform, 1, n, None, True)
    _, _, m_s = top_k_gating(skewed, 1, n, None, True)
    # balanced routing => aux ~1; collapsed routing => aux ~E
    assert float(m_u["aux_loss"]) < float(m_s["aux_loss"])
    assert abs(float(m_u["aux_loss"]) - 1.0) < 0.2
    assert abs(float(m_s["aux_loss"]) - 4.0) < 0.2


def test_drop_fraction_with_tight_capacity():
    logits = jnp.zeros((64, 2))  # all tokens tie; capacity forces drops
    dispatch, _, metrics = top_k_gating(logits, top_k=1, capacity=4, rng=None, train=True)
    assert float(metrics["drop_fraction"]) > 0.8


def test_mixtral_trains_one_step():
    m = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32)
    rng = jax.random.PRNGKey(0)
    params = m.init(rng)
    batch = make_lm_batch(jax.random.randint(rng, (2, 16), 0, 64))
    (loss, metrics), grads = jax.value_and_grad(
        lambda p: m.loss(p, batch, rng=rng), has_aux=True
    )(params)
    assert np.isfinite(float(loss))
    assert float(metrics["moe_aux_loss"]) > 0
    router_g = grads["layers"]["mlp"]["router"]
    assert float(jnp.sum(jnp.abs(router_g))) > 0  # router learns


def test_residual_moe_trains_and_differs():
    """Residual/PR-MoE (reference: deepspeed/moe/layer.py use_residual):
    dense branch + learned coefficient must be present, trained, and change
    the output vs plain MoE."""
    m = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32,
                moe_use_residual=True)
    rng = jax.random.PRNGKey(0)
    params = m.init(rng)
    mlp = params["layers"]["mlp"]
    assert {"res_wi", "res_wo", "res_wg", "coef"} <= set(mlp)
    assert m.num_params() == sum(
        int(np.prod(l.shape)) for l in jax.tree_util.tree_leaves(params)
    )
    batch = make_lm_batch(jax.random.randint(rng, (2, 16), 0, 64))
    (loss, _), grads = jax.value_and_grad(
        lambda p: m.loss(p, batch, rng=rng), has_aux=True
    )(params)
    assert np.isfinite(float(loss))
    g = grads["layers"]["mlp"]
    assert float(jnp.sum(jnp.abs(g["res_wi"]))) > 0
    assert float(jnp.sum(jnp.abs(g["coef"]))) > 0

    # the dense branch must actually be mixed into the output: zeroing its
    # weights has to change the logits
    logits, _ = m.apply(params, batch["input_ids"])
    ablated = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy tree
    ablated["layers"] = dict(ablated["layers"])
    ablated["layers"]["mlp"] = dict(ablated["layers"]["mlp"])
    ablated["layers"]["mlp"]["res_wi"] = jnp.zeros_like(mlp["res_wi"])
    logits2, _ = m.apply(ablated, batch["input_ids"])
    assert float(jnp.max(jnp.abs(logits - logits2))) > 1e-4

    # specs tree matches the params tree (engine sharding requirement)
    specs = m.partition_specs()
    assert jax.tree_util.tree_structure(specs) == jax.tree_util.tree_structure(
        jax.tree.map(lambda _: 0, params)
    )


def test_residual_moe_convergence_smoke():
    m = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32,
                moe_use_residual=True)
    rng = jax.random.PRNGKey(1)
    params = m.init(rng)
    import optax

    tx = optax.adam(3e-3)
    opt = tx.init(params)
    batch = make_lm_batch(jax.random.randint(rng, (4, 16), 0, 64))

    @jax.jit
    def step(params, opt):
        (loss, _), grads = jax.value_and_grad(
            lambda p: m.loss(p, batch, rng=rng), has_aux=True
        )(params)
        upd, opt = tx.update(grads, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    losses = []
    for _ in range(30):
        params, opt, loss = step(params, opt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_moe_aux_loss_in_step_metrics(devices8):
    """The train step surfaces model metrics (reference: MoE aux loss is
    visible in DeepSpeed's step logging/monitor)."""
    import deepspeed_tpu
    from deepspeed_tpu.models import mixtral

    model = mixtral(
        "mixtral-tiny", vocab_size=256, max_seq_len=32, hidden_size=64,
        num_layers=2, num_heads=4, num_kv_heads=2, intermediate_size=128,
        num_experts=4, moe_top_k=2,
    )
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_batch_size": 16,
            "train_micro_batch_size_per_gpu": 1,
            "gradient_accumulation_steps": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        },
    )
    engine.train_batch(
        batch={"input_ids": np.random.RandomState(0).randint(0, 256, size=(16, 32))}
    )
    m = engine._metrics
    assert {"lm_loss", "moe_aux_loss", "tokens"} <= set(m)
    assert float(m["moe_aux_loss"]) > 0
    assert float(m["tokens"]) > 0


def test_gather_dispatch_matches_einsum_dispatch():
    """moe_dispatch="gather" replaces the one-hot dispatch/combine dots
    with index gathers; outputs and gradients (tokens AND router) must
    match the einsum formulation bit-for-bit-close."""
    from deepspeed_tpu.moe.sharded_moe import moe_layer

    m_e = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32)
    cfg_e = m_e.config
    import dataclasses

    cfg_g = dataclasses.replace(cfg_e, moe_dispatch="gather")

    rng = jax.random.PRNGKey(0)
    params = m_e.init(rng)
    # layer params are scan-stacked [L, ...]: take layer 0
    p = jax.tree.map(lambda a: a[0], params["layers"]["mlp"])
    x = jax.random.normal(jax.random.PRNGKey(1),
                          (2, 16, cfg_e.hidden_size), jnp.float32)

    def run(cfg, x):
        out, aux = moe_layer(cfg, p, x, rng=None, train=True)
        return out, aux

    out_e, aux_e = run(cfg_e, x)
    out_g, aux_g = run(cfg_g, x)
    np.testing.assert_allclose(np.asarray(out_g), np.asarray(out_e),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(float(aux_g), float(aux_e), rtol=1e-6)

    ge = jax.grad(lambda x: jnp.sum(run(cfg_e, x)[0] ** 2))(x)
    gg = jax.grad(lambda x: jnp.sum(run(cfg_g, x)[0] ** 2))(x)
    np.testing.assert_allclose(np.asarray(gg), np.asarray(ge),
                               rtol=1e-4, atol=1e-4)

    def router_loss(cfg, router):
        pp = dict(p, router=router)
        out, _ = moe_layer(cfg, pp, x, rng=None, train=True)
        return jnp.sum(out ** 2)

    gre = jax.grad(lambda r: router_loss(cfg_e, r))(p["router"])
    grg = jax.grad(lambda r: router_loss(cfg_g, r))(p["router"])
    np.testing.assert_allclose(np.asarray(grg), np.asarray(gre),
                               rtol=1e-4, atol=1e-4)


def test_gather_dispatch_trains_under_ep_mesh(devices8):
    """The gather formulation must GSPMD-compile and train on an ep mesh."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.comm import ParallelDims

    comm.destroy_process_group()
    topo = comm.init_distributed(dims=ParallelDims(dp=2, ep=4))
    model = mixtral(
        "mixtral-tiny", vocab_size=256, max_seq_len=32, num_experts=4,
        moe_dispatch="gather",
    )
    engine, _, _, _ = deepspeed_tpu.initialize(model=model, topology=topo, config={
        "train_batch_size": 4,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 2},
    })
    r = np.random.RandomState(0)
    batch = {"input_ids": r.randint(0, 256, size=(4, 16))}
    losses = [float(engine.train_batch(batch=batch)) for _ in range(4)]
    assert all(np.isfinite(l) for l in losses), losses
    assert losses[-1] < losses[0], losses
