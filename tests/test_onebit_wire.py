"""Wire-compressed 1-bit Adam/LAMB (SURVEY §2.1 "error-compensated
compressed collectives"; VERDICT r1 #6).

Oracles: bit-pack/unpack roundtrip; warmup phase tracks plain AdamW;
compressed phase still learns, keeps error-feedback state, and puts ~32×
fewer bytes on the wire than the dense fp32 all-reduce (comm-hook
accounting)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm import collectives
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.ops.onebit import OneBitWireState, _bitsign, _pack_bits, _unpack_bits


def test_bit_pack_roundtrip():
    x = jnp.asarray(np.random.RandomState(0).randn(64), jnp.float32)
    packed = _pack_bits(x)
    assert packed.dtype == jnp.uint8 and packed.shape == (8,)
    np.testing.assert_array_equal(np.asarray(_unpack_bits(packed)),
                                  np.asarray(_bitsign(x)))


BASE = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "bf16": {"enabled": True},
    "steps_per_print": 100,
}


def _run(opt, steps=4, hook=None, seed=0):
    comm.destroy_process_group()
    if hook is not None:
        collectives.register_comm_hook(hook)
    try:
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
            config=dict(BASE, optimizer=opt),
            rng=jax.random.PRNGKey(11),
        )
        data = {
            "input_ids": np.random.RandomState(seed).randint(0, 128, size=(16, 16))
        }
        losses = [float(engine.train_batch(batch=data)) for _ in range(steps)]
        return losses, engine
    finally:
        if hook is not None:
            collectives.unregister_comm_hook(hook)


def test_warmup_tracks_adamw(devices8):
    """Before freeze_step the wire optimizer is exact Adam(+wd) with a dense
    pmean — it must track plain adamw closely."""
    dense, _ = _run({"type": "adamw", "params": {"lr": 1e-3}})
    wire, engine = _run(
        {"type": "OneBitAdam", "params": {"lr": 1e-3, "freeze_step": 100}}
    )
    assert engine._stacked_grads_axes == ("dp",)
    assert isinstance(engine.state.opt_state, OneBitWireState)
    np.testing.assert_allclose(wire, dense, rtol=2e-3)


def test_compressed_phase_learns_and_keeps_error_state(devices8):
    losses, engine = _run(
        {"type": "OneBitAdam", "params": {"lr": 1e-3, "freeze_step": 2}},
        steps=8,
    )
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(losses))
    # error feedback engaged after the phase switch
    err_leaf = jax.tree_util.tree_leaves(engine.state.opt_state.error)[0]
    assert float(jnp.abs(err_leaf).max()) > 0.0
    # error leaves are stacked per-member and sharded over dp
    assert err_leaf.shape[0] == 8
    assert "dp" in str(err_leaf.sharding.spec)


def test_wire_bytes_are_32x_smaller(devices8):
    records = []
    _run(
        {"type": "OneBitAdam", "params": {"lr": 1e-3, "freeze_step": 2}},
        steps=3,
        hook=lambda op, axis, b: records.append((op, b)),
    )
    dense = [b for op, b in records if op == "all_reduce"]
    packed = [b for op, b in records if op == "all_to_all"]
    assert dense and packed
    # per-leaf: uint8 bit-packed payload vs fp32 dense payload → 32×
    assert max(dense) / max(packed) >= 31, (max(dense), max(packed))


def test_onebit_lamb_wire_runs(devices8):
    losses, engine = _run(
        {"type": "OneBitLamb", "params": {"lr": 1e-3, "freeze_step": 2}},
        steps=5,
    )
    assert losses[-1] < losses[0]
    assert engine._stacked_grads_axes


def test_fallback_without_data_axes():
    """tp-only topology → no dp wire to compress → numerics-only fallback."""
    comm.destroy_process_group()
    from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims

    topo = MeshTopology(ParallelDims(tp=8), devices=jax.devices()[:8])
    comm.set_topology(topo)
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        config=dict(
            BASE,
            optimizer={"type": "OneBitAdam", "params": {"lr": 1e-3}},
            train_batch_size=4,
            train_micro_batch_size_per_gpu=4,
        ),
        topology=topo,
    )
    assert engine._stacked_grads_axes is None
    loss = engine.train_batch(
        batch={"input_ids": np.random.RandomState(0).randint(0, 128, size=(4, 16))}
    )
    assert np.isfinite(float(loss))
