"""Engine tests. Model: reference tests/unit/runtime/test_ds_initialize.py +
half_precision tests. The ZeRO oracle: all stages are the same optimizer, so
trajectories must match bitwise-close across stages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.models import gpt2, llama

BASE_CFG = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
    "steps_per_print": 100,
}


def _model():
    return gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16)


def _data(n=16, seed=0):
    return {"input_ids": np.random.RandomState(seed).randint(0, 128, size=(n, 16))}


def _run_steps(cfg, steps=3, seed=0, model=None, vary_data=False):
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=model or _model(), config=dict(cfg), rng=jax.random.PRNGKey(42)
    )
    losses = []
    for i in range(steps):
        step_seed = seed + i if vary_data else seed
        losses.append(
            float(engine.train_batch(batch=_data(cfg["train_batch_size"], step_seed)))
        )
    return losses, engine


def test_initialize_returns_tuple(devices8):
    engine, opt, loader, sched = deepspeed_tpu.initialize(
        model=_model(), config=dict(BASE_CFG), training_data=_data(64)
    )
    assert engine is opt
    assert len(loader) == 4  # 64 / 16
    assert callable(sched)


@pytest.mark.parametrize("stage", [0, 1, 2, 3])
def test_zero_stages_train(stage, devices8):
    cfg = dict(BASE_CFG, zero_optimization={"stage": stage})
    losses, engine = _run_steps(cfg, steps=4)
    assert losses[-1] < losses[0], losses
    assert all(np.isfinite(losses))


def test_zero_stage_equivalence_oracle(devices8):
    """ZeRO-0/1/2/3 are the same math — trajectories must agree."""
    trajectories = {}
    for stage in [0, 1, 2, 3]:
        cfg = dict(BASE_CFG, zero_optimization={"stage": stage})
        trajectories[stage], _ = _run_steps(cfg, steps=3)
    for stage in [1, 2, 3]:
        np.testing.assert_allclose(
            trajectories[0], trajectories[stage], rtol=2e-2,
            err_msg=f"stage {stage} diverged from DDP",
        )


def test_zero3_params_actually_sharded(devices8):
    cfg = dict(BASE_CFG, zero_optimization={"stage": 3, "stage3_param_persistence_threshold": 0})
    _, engine = _run_steps(cfg, steps=1)
    wq = engine.state.params["layers"]["attn"]["wq"]
    assert "dp" in str(wq.sharding.spec)


def test_grad_accumulation_invariance(devices8):
    """accum=1 vs accum=4 on the same global batch → same trajectory."""
    cfg1 = dict(BASE_CFG, train_batch_size=64, gradient_accumulation_steps=1)
    cfg4 = dict(BASE_CFG, train_batch_size=64, gradient_accumulation_steps=4)
    del cfg1["train_micro_batch_size_per_gpu"], cfg4["train_micro_batch_size_per_gpu"]
    l1, _ = _run_steps(cfg1, steps=3)
    l4, _ = _run_steps(cfg4, steps=3)
    np.testing.assert_allclose(l1, l4, rtol=2e-2)


def test_fp16_runs_with_loss_scaling(devices8):
    cfg = dict(BASE_CFG)
    cfg.pop("bf16")
    cfg["fp16"] = {"enabled": True, "initial_scale_power": 8}
    losses, engine = _run_steps(cfg, steps=3)
    assert all(np.isfinite(losses))
    assert engine.loss_scale >= 1.0


def test_gradient_clipping_bounds_update(devices8):
    cfg = dict(BASE_CFG, gradient_clipping=1e-4)
    _, engine = _run_steps(cfg, steps=2)
    assert float(engine._metrics["grad_norm"]) >= 0


def test_imperative_forward_backward_step(devices8):
    cfg = dict(BASE_CFG, train_batch_size=32, gradient_accumulation_steps=2)
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg)
    # 2 microbatches of 16 (= micro 2 * dp 8), update applied at the boundary
    mb = _data(16)
    loss0 = engine(mb)
    engine.backward(loss0)
    assert engine.step() is None  # not at boundary yet
    loss1 = engine(_data(16, seed=1))
    engine.backward(loss1)
    final = engine.step()
    assert final is not None
    assert engine.global_steps == 1


def test_eval_batch_no_state_change(devices8):
    _, engine = _run_steps(dict(BASE_CFG), steps=1)
    step_before = int(engine.state.step)
    loss = engine.eval_batch(batch=_data(16))
    assert np.isfinite(float(loss))
    assert int(engine.state.step) == step_before


def test_wrong_batch_size_raises(devices8):
    _, engine = _run_steps(dict(BASE_CFG), steps=1)
    with pytest.raises(ValueError, match="train_batch_size"):
        engine.train_batch(batch=_data(12))


def test_tp_engine_trains(devices8):
    cfg = dict(BASE_CFG, tensor_parallel={"tp_size": 2})
    losses, engine = _run_steps(cfg, steps=3)
    assert engine.topology.tp_size == 2
    assert losses[-1] < losses[0]
    wq = engine.state.params["layers"]["attn"]["wq"]
    assert "tp" in str(wq.sharding.spec)


def test_tp_matches_dp_trajectory(devices8):
    l_dp, _ = _run_steps(dict(BASE_CFG), steps=3)
    l_tp, _ = _run_steps(dict(BASE_CFG, tensor_parallel={"tp_size": 2}), steps=3)
    np.testing.assert_allclose(l_dp, l_tp, rtol=2e-2)


def test_hpz_fsdp_subaxis(devices8):
    cfg = dict(
        BASE_CFG,
        zero_optimization={
            "stage": 3,
            "zero_hpz_partition_size": 2,
            "stage3_param_persistence_threshold": 0,
        },
    )
    losses, engine = _run_steps(cfg, steps=2)
    assert engine.topology.fsdp_size == 2
    wq = engine.state.params["layers"]["attn"]["wq"]
    spec = str(wq.sharding.spec)
    assert "fsdp" in spec and "'dp'" not in spec  # params shard only on sub-axis
    assert losses[-1] < losses[0]


def test_initialize_from_args_namespace(devices8, tmp_path):
    """Reference CLI pattern: deepspeed.initialize(args) where
    args.deepspeed_config points at a ds_config.json file."""
    import argparse
    import json

    cfg_path = tmp_path / "ds_config.json"
    cfg_path.write_text(json.dumps(BASE_CFG))
    args = argparse.Namespace(deepspeed_config=str(cfg_path), local_rank=0)
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(args=args, model=_model())
    loss = engine.train_batch(batch=_data())
    assert np.isfinite(float(loss))


def test_engine_module_train_eval_parity_shims():
    """DeepSpeedEngine nn.Module-ish surface: module/train/eval/zero_grad."""
    import deepspeed_tpu
    from deepspeed_tpu.models import gpt2

    model = gpt2("gpt2-tiny", vocab_size=128, max_seq_len=32, hidden_size=32,
                 num_layers=1, num_heads=2, intermediate_size=64)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}},
    )
    assert engine.module is model
    assert engine.training
    assert engine.eval() is engine and not engine.training
    assert engine.train() is engine and engine.training
    engine.zero_grad()  # documented no-op


def test_prepare_batch_staged_matches_host_path(devices8):
    """prepare_batch pre-stages a batch on device; repeated train_batch
    calls skip the per-step upload and produce a bit-identical trajectory
    to the host-dict path (the bench/tuner steady-state fast path)."""
    cfg = dict(BASE_CFG, train_batch_size=16,
               train_micro_batch_size_per_gpu=1,
               gradient_accumulation_steps=2)
    comm.destroy_process_group()
    e1, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(42)
    )
    host_losses = [float(e1.train_batch(batch=_data(16))) for _ in range(3)]

    comm.destroy_process_group()
    e2, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(42)
    )
    staged = e2.prepare_batch(_data(16))
    # staged fields are device arrays in the [accum, micro, ...] layout;
    # re-preparing them is a pass-through (same objects, no copy)
    again = e2._prepare_batch(staged)
    for k in staged:
        assert again[k] is staged[k], k
    staged_losses = [float(e2.train_batch(batch=staged)) for _ in range(3)]
    np.testing.assert_allclose(host_losses, staged_losses, rtol=0, atol=0)


def test_train_batch_chain_bitmatches_sequential(devices8):
    """A scanned N-step chain (one dispatch) must be bit-identical to the
    same N steps dispatched one train_batch call at a time: the chain
    carries the rng and splits per step exactly as next_rng() does."""
    cfg = dict(BASE_CFG, train_batch_size=16,
               train_micro_batch_size_per_gpu=1,
               gradient_accumulation_steps=2)
    comm.destroy_process_group()
    e1, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(7)
    )
    seq_losses = [float(e1.train_batch(batch=_data(16))) for _ in range(4)]

    comm.destroy_process_group()
    e2, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(7)
    )
    chain_losses = np.asarray(e2.train_batch_chain(batch=_data(16), steps=4))
    assert chain_losses.shape == (4,)
    np.testing.assert_allclose(seq_losses, chain_losses, rtol=0, atol=0)
    assert e2.global_steps == e1.global_steps == 4
    # final states identical too (params trajectory, not just losses)
    for a, b in zip(jax.tree.leaves(e1.state.params),
                    jax.tree.leaves(e2.state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # stacked metrics exposed; last step mirrors train_batch's metrics slot
    assert e2.last_chain_metrics["loss"].shape == (4,)


def test_train_batch_chain_data_iter_stacked(devices8):
    """data_iter chains upload N distinct batches as one stacked transfer;
    trajectory matches feeding the same batches sequentially."""
    cfg = dict(BASE_CFG, train_batch_size=16,
               train_micro_batch_size_per_gpu=1,
               gradient_accumulation_steps=2)
    batches = [_data(16, seed=s) for s in (1, 2, 3)]

    comm.destroy_process_group()
    e1, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(9)
    )
    seq = [float(e1.train_batch(batch=dict(b))) for b in batches]

    comm.destroy_process_group()
    e2, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(9)
    )
    chain = np.asarray(
        e2.train_batch_chain(data_iter=iter([dict(b) for b in batches]),
                             steps=3)
    )
    np.testing.assert_allclose(seq, chain, rtol=0, atol=0)


def test_train_batch_chain_falls_back_per_step(devices8):
    """Host-coupled features (random-LTD) disqualify the scanned chain;
    the call still works via per-step dispatch and returns stacked losses."""
    cfg = dict(BASE_CFG, train_batch_size=16,
               train_micro_batch_size_per_gpu=1,
               gradient_accumulation_steps=2,
               data_efficiency={
                   "enabled": True,
                   "data_routing": {
                       "enabled": True,
                       "random_ltd": {
                           "enabled": True,
                           "total_layer_num": 2,
                           "random_ltd_layer_num": 1,
                           "random_ltd_layer_id": [0],
                           "model_mask_name": None,
                           "model_type": "decoder",
                           "hidden_state_order": "batch_seq_dim",
                           "random_ltd_schedule": {
                               "min_value": 8,
                               "max_value": 16,
                               "schedule_type": "fixed_linear",
                               "schedule_config": {
                                   "require_steps": 10, "seq_per_step": 8,
                               },
                           },
                       },
                   },
               })
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=_model(), config=dict(cfg), rng=jax.random.PRNGKey(3)
    )
    if engine.random_ltd is None:
        pytest.skip("random-LTD config shape changed; fallback gate untested")
    losses = np.asarray(engine.train_batch_chain(batch=_data(16), steps=2))
    assert losses.shape == (2,)
    assert engine.last_chain_metrics is None  # fallback path
    assert engine.global_steps == 2


def test_bucketed_offload_update_matches_plain(devices8):
    """CPU-offloaded optimizer state steps per-layer inside a lax.scan
    (runtime/bucketed_opt.py, VERDICT r4 #2's enabler): the scanned update
    must be numerically identical to the whole-tree optax update, and the
    bucketed state must checkpoint/resume."""
    base = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
    }
    plain_losses, plain = _run_steps(
        {**base, "zero_optimization": {"stage": 3}}, steps=4, vary_data=True
    )
    off = {
        **base,
        "zero_optimization": {"stage": 3,
                              "offload_optimizer": {"device": "cpu"}},
    }
    buck_losses, buck = _run_steps(off, steps=4, vary_data=True)
    assert buck._bucketed_opt is not None
    assert plain._bucketed_opt is None
    np.testing.assert_allclose(plain_losses, buck_losses, rtol=1e-6)
    # params: atol covers degenerate near-zero leaves (k-bias) where
    # sqrt(v) ~ adam eps makes the update chaotic in summation order —
    # verified leaf-by-leaf: all diffs are O(1e-7) except such leaves
    for a, b in zip(jax.tree_util.tree_leaves(plain.state.params),
                    jax.tree_util.tree_leaves(buck.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
    # the bucketed {"rest", "layers"} state round-trips a checkpoint
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        buck.save_checkpoint(d)
        l_next = float(buck.train_batch(batch=_data(8, seed=99)))
        buck.load_checkpoint(d)
        l_again = float(buck.train_batch(batch=_data(8, seed=99)))
    np.testing.assert_allclose(l_next, l_again, rtol=1e-6)


def test_bucketed_double_buffer_matches_serial_and_plain(devices8):
    """The double-buffered layer stream (zero_optimization.
    offload_double_buffer) runs the same per-layer math in the same order
    as the serial bucketed scan — the CPU-mesh oracle demands trajectories
    identical to BOTH the serial bucketed path and the whole-tree optax
    update before the knob may ever default on."""
    base = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw",
                      "params": {"lr": 1e-2, "weight_decay": 0.01}},
        "gradient_clipping": 1.0,
    }
    plain_losses, plain = _run_steps(
        {**base, "zero_optimization": {"stage": 3}}, steps=4, vary_data=True
    )
    off = {"stage": 3, "offload_optimizer": {"device": "cpu"}}
    serial_losses, serial = _run_steps(
        {**base, "zero_optimization": dict(off)}, steps=4, vary_data=True
    )
    db_losses, db = _run_steps(
        {**base,
         "zero_optimization": dict(off, offload_double_buffer=True)},
        steps=4, vary_data=True,
    )
    assert db._bucketed_opt is not None and db._bucketed_opt.double_buffer
    assert serial._bucketed_opt is not None
    assert not serial._bucketed_opt.double_buffer
    # CPU meshes have no memory kinds: nothing streams, nothing recorded
    assert db.offload_stream is None
    # double-buffered == serial bucketed, leaf by leaf
    np.testing.assert_allclose(db_losses, serial_losses, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(serial.state.params),
                    jax.tree_util.tree_leaves(db.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # == the plain whole-tree update at f32 tolerance
    np.testing.assert_allclose(db_losses, plain_losses, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(plain.state.params),
                    jax.tree_util.tree_leaves(db.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)


def test_bucketed_survives_layer_dim_dp_sharded(devices8):
    """ADVICE r5 → ISSUE 2 fix: when L is the largest dp-divisible dim
    (tiny hidden sizes), add_data_axes shards the stacked leaves' dim 0.
    The PR-1 gate disabled bucketing for that shape; now _apply_update
    re-puts the scanned groups to their resting shardings after the layer
    scan, so bucketing stays ON, the trajectory matches the whole-tree
    update, and the chain's carry closure holds (shardlint R2 proves the
    same statically — tests/test_shardlint_suite.py)."""

    def _sharded_model():
        return gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16,
                    hidden_size=12, num_layers=8, num_heads=2,
                    intermediate_size=12)

    base = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    }
    zero = {"stage": 3, "stage3_param_persistence_threshold": 0}
    plain_losses, plain = _run_steps(
        {**base, "zero_optimization": dict(zero)},
        steps=3, vary_data=True, model=_sharded_model(),
    )
    off_losses, off = _run_steps(
        {**base, "zero_optimization": dict(
            zero, offload_optimizer={"device": "cpu"})},
        steps=3, vary_data=True, model=_sharded_model(),
    )
    # sanity: this config really produces a dim-0 (dp)-sharded stacked leaf
    assert any(
        tuple(spec) and tuple(spec)[0] is not None
        for spec in jax.tree_util.tree_leaves(
            off.param_specs["layers"],
            is_leaf=lambda x: hasattr(x, "index"),
        )
    )
    assert off._bucketed_opt is not None  # the gate is gone
    assert plain._bucketed_opt is None
    np.testing.assert_allclose(plain_losses, off_losses, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(plain.state.params),
                    jax.tree_util.tree_leaves(off.state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-4)
    # the closure in anger: a scanned 2-step chain must run AND return the
    # stacked leaves to their resting shardings
    off.train_batch_chain(batch=_data(8, seed=77), steps=2)
    for leaf, want in zip(
        jax.tree_util.tree_leaves(off.state.params["layers"]),
        jax.tree_util.tree_leaves(off.param_shardings["layers"]),
    ):
        assert leaf.sharding.spec == want.spec, (leaf.sharding, want)
    loss = float(off.train_batch(
        batch={"input_ids": np.random.RandomState(0).randint(
            0, 64, size=(8, 16))}))
    assert np.isfinite(loss)


def test_bucketed_step_with_placement_hooks_matches_plain(devices8):
    """The bucketed update with per-slice placement hooks installed (the
    TPU-offload configuration) is numerically identical to the hookless
    path CPU meshes take."""
    import optax

    from deepspeed_tpu.runtime.bucketed_opt import BucketedOptimizer

    r = np.random.RandomState(0)
    params = {
        "layers": {"w": jnp.asarray(r.randn(5, 8, 8), jnp.float32),
                   "b": jnp.asarray(r.randn(5, 8), jnp.float32)},
        "embed": jnp.asarray(r.randn(16, 8), jnp.float32),
    }
    grads = jax.tree.map(lambda x: jnp.asarray(
        np.random.RandomState(1).randn(*x.shape), jnp.float32), params)
    opt = BucketedOptimizer(optax.adamw(1e-2))
    st = jax.jit(opt.init)(params)
    ident = (lambda t: t, lambda t: t)
    p_scan, s_scan = jax.jit(opt.step)(grads, st, params)
    p_pipe, s_pipe = jax.jit(
        lambda g, s, p: opt.step(g, s, p, state_put=ident, param_put=ident)
    )(grads, st, params)
    for a, b in zip(jax.tree_util.tree_leaves((p_scan, s_scan)),
                    jax.tree_util.tree_leaves((p_pipe, s_pipe))):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=1e-6, atol=1e-7)


def test_bucketed_double_buffer_step_bitmatches_serial_scan():
    """Unit oracle for the software-pipelined step: with and without
    placement hooks, the two-slot rotating-buffer scan must produce
    exactly the serial scan's params and state (same math, same layer
    order — only the schedule differs)."""
    import optax

    from deepspeed_tpu.runtime.bucketed_opt import BucketedOptimizer

    r = np.random.RandomState(0)
    params = {
        "layers": {"w": jnp.asarray(r.randn(6, 8, 8), jnp.float32),
                   "b": jnp.asarray(r.randn(6, 8), jnp.float32)},
        "embed": jnp.asarray(r.randn(16, 8), jnp.float32),
    }
    grads = jax.tree.map(lambda x: jnp.asarray(
        np.random.RandomState(1).randn(*x.shape), jnp.float32), params)
    serial = BucketedOptimizer(optax.adamw(1e-2))
    pipelined = BucketedOptimizer(optax.adamw(1e-2), double_buffer=True)
    st = jax.jit(serial.init)(params)
    ident = (lambda t: t, lambda t: t)
    want = jax.jit(serial.step)(grads, st, params)
    for hooks in (None, ident):
        got = jax.jit(
            lambda g, s, p, h=hooks: pipelined.step(
                g, s, p, state_put=h, param_put=h
            )
        )(grads, st, params)
        for a, b in zip(jax.tree_util.tree_leaves(want),
                        jax.tree_util.tree_leaves(got)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
