"""fleetcheck oracle tests: the host-plane model checker.

Tier-1 ("not slow") keeps every preset's BFS under a few hundred
states — enough to cross the interesting structure (demotions,
handoffs, sheds) without the full frontier — plus both seeded-bug
mutants end-to-end (they counterexample in seconds by construction).
The slow tier re-runs every preset exhaustively at its shipped bounds,
which is what CI's fleetcheck job does via tools/fleetcheck.py.
"""

import dataclasses
import importlib.util
import os

import pytest

from deepspeed_tpu.analysis.modelcheck import (INVARIANTS, MUTATIONS,
                                               PRESETS, World, explore,
                                               fingerprint, preset,
                                               random_walk, replay)
from tests.analysis_corpus import modelcheck_fixtures

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool():
    spec = importlib.util.spec_from_file_location(
        "fleetcheck_tool", os.path.join(REPO, "tools", "fleetcheck.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _shrunk(sc, max_states=300, budget_s=30.0):
    return dataclasses.replace(sc, max_states=max_states,
                               budget_s=budget_s)


# ---------------------------------------------------------------------------
# presets: truncated tier-1 sweep + exhaustive slow sweep
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_clean_small(name):
    res = explore(_shrunk(preset(name)), stop_on_first=False)
    assert res.violations == [], res.format()
    assert res.states > 50  # the shrink must not make the run vacuous
    assert res.drains > 0


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(PRESETS))
def test_preset_clean_exhaustive(name):
    res = explore(preset(name))
    assert res.ok, res.format()
    # the shipped bounds are sized so default runs are EXHAUSTIVE for
    # their depth: bump max_states/budget_s when a scenario grows
    assert not res.truncated, res.format()


def test_unknown_preset_is_loud():
    with pytest.raises(KeyError):
        preset("oversubscriptoin")


# ---------------------------------------------------------------------------
# seeded-bug corpus: both mutants found, clean twins green
# ---------------------------------------------------------------------------
def test_promotion_livelock_mutant_found():
    sc, expect = modelcheck_fixtures.promotion_livelock()
    res = explore(sc)
    assert expect in [v.invariant for v in res.violations], res.format()
    v = next(v for v in res.violations if v.invariant == expect)
    # the minimal counterexample: a depth-bounded BFS prefix of
    # submits + ticks, then the deterministic all-EOS drain (events
    # with outcomes=None) entering the promote-2/steal-2 cycle
    bfs_prefix = [e for e in v.trace
                  if not (e[0] == "tick" and e[2] is None)]
    assert len(bfs_prefix) <= sc.max_depth
    assert len(bfs_prefix) < len(v.trace)  # the cycle shows in-drain
    assert "zero" in v.message or "cycle" in v.message
    # deterministic: the same exploration finds the same trace
    res2 = explore(sc)
    assert [tuple(v.trace) for v in res2.violations[:1]] == \
        [tuple(v.trace)]


def test_promotion_livelock_clean_twin():
    sc, expect = modelcheck_fixtures.promotion_livelock_clean()
    assert expect is None
    res = explore(_shrunk(sc, max_states=1500), stop_on_first=False)
    assert res.violations == [], res.format()


@pytest.mark.slow
def test_promotion_livelock_clean_twin_exhaustive():
    sc, _ = modelcheck_fixtures.promotion_livelock_clean()
    res = explore(sc)
    assert res.ok and not res.truncated, res.format()


def test_handoff_leak_mutant_found_and_twin_clean():
    sc, expect = modelcheck_fixtures.handoff_leak()
    res = explore(sc)
    found = [v.invariant for v in res.violations]
    assert expect in found, res.format()
    clean_sc, _ = modelcheck_fixtures.handoff_leak_clean()
    clean = explore(_shrunk(clean_sc, max_states=800),
                    stop_on_first=False)
    assert clean.violations == [], clean.format()


def test_violation_trace_replays():
    sc, expect = modelcheck_fixtures.handoff_leak()
    res = explore(sc)
    v = next(v for v in res.violations if v.invariant == expect)
    # the printed trace is a real program: replaying it (checks off)
    # reconstructs the violating world deterministically
    w1 = replay(sc, v.trace, check=False)
    w2 = replay(sc, v.trace, check=False)
    assert fingerprint(w1) == fingerprint(w2)
    assert v.invariant in INVARIANTS  # every id the checker emits is
    #   documented in the registry (docs/modelcheck.md table)


# ---------------------------------------------------------------------------
# determinism + canonical fingerprints
# ---------------------------------------------------------------------------
def test_seeded_walks_are_reproducible():
    sc = preset("fleet_shedding")
    a = random_walk(sc, seed=7, steps=48)
    b = random_walk(sc, seed=7, steps=48)
    # identically-seeded walks: identical event traces, identical
    # world event logs, identical terminal fingerprints
    assert a.trace == b.trace
    assert a.log == b.log
    assert a.final_fingerprint == b.final_fingerprint
    c = random_walk(sc, seed=8, steps=48)
    assert (a.trace != c.trace) or (a.final_fingerprint
                                    == c.final_fingerprint)


@pytest.mark.parametrize("name,seed",
                         [(n, s) for n in sorted(PRESETS)
                          for s in (1, 2)])
def test_random_walk_smoke(name, seed):
    res = random_walk(preset(name), seed=seed, steps=40)
    assert res.violation is None, res.violation.format()


def test_walk_trace_replays_to_same_fingerprint():
    sc = preset("tiered_cold_resume")
    walk = random_walk(sc, seed=3, steps=40)
    w = replay(sc, walk.trace, check=True)
    assert fingerprint(w) == walk.final_fingerprint


def test_fingerprint_anonymizes_free_pages():
    sc = preset("oversubscription")
    # first tick is a pure prefill chunk (no samplers); the second
    # finishes q0's prompt and samples once while q1's chunk rides
    w = replay(sc, [("submit", 0), ("tick", 0, ()), ("submit", 1),
                    ("tick", 0, ("tok",))])
    pool = w.scheduler(0).pool
    assert pool.free_count >= 2  # the permutation below must be real
    fp = fingerprint(w)
    pool._free.reverse()  # physical identity of FREE pages is dead
    assert fingerprint(w) == fp


def test_fingerprint_drops_absolute_time():
    sc = preset("spec_on")
    trace = [("submit", 0), ("tick", 0, ("tok",))]
    w1 = replay(sc, trace)
    w2 = replay(sc, trace)
    w1.clock.advance(1000.0)
    w2.clock.advance(2000.0)
    # no queue ages or retry deadlines live here, so wall-clock offset
    # alone must not split the state
    assert fingerprint(w1) == fingerprint(w2)


def test_fingerprint_splits_on_behavioral_difference():
    sc = preset("oversubscription")
    w1 = replay(sc, [("submit", 0)])
    w2 = replay(sc, [("submit", 1)])
    assert fingerprint(w1) != fingerprint(w2)
    w3 = replay(sc, [("submit", 0), ("tick", 0, ())])
    assert fingerprint(w1) != fingerprint(w3)


# ---------------------------------------------------------------------------
# world plumbing details the checker's soundness leans on
# ---------------------------------------------------------------------------
def test_exploration_counts_and_result_shape():
    sc = _shrunk(preset("disaggregated_handoff"), max_states=200)
    res = explore(sc)
    d = res.to_dict()
    assert d["scenario"] == sc.name
    assert d["states"] == res.states >= 1
    assert d["ok"] is True
    assert "exhaustive" in res.format() or "bounds" in res.format()


def test_cli_mutate_exits_one_and_names_invariant(capsys):
    tool = _load_tool()
    rc = tool.main(["--mutate", "handoff_leak"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "H3" in out
    rc = tool.main(["--clean-twin", "handoff_leak"])
    assert rc == 0


def test_cli_vacuous_run_fails():
    tool = _load_tool()
    with pytest.raises(SystemExit):
        tool.main([])  # no targets: argparse error, not a silent green
