"""Planner-driven autotuning (ISSUE 7): enumerate → R6-prune → rank →
compile only a top-k, with the drift ledger keeping the cost model
honest.

Acceptance exercised here on the CPU mesh with tiny models (the
full-size 410M drift gate is ``tools/autoplan.py --check``, wired into
CI): the planner search compiles at most top-k candidates yet selects
the same winner as the exhaustive compile-and-measure ladder, statically
pruned rungs carry their reasons, larger micro-batches at a pruned
(stage, remat) rung are derived without re-tracing, and every measured
survivor banks a (predicted, measured) pair."""

import json
import os

import numpy as np
import pytest

from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import gpt2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _model():
    return gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                num_layers=2, num_heads=2)


def _topo():
    return MeshTopology(dims=ParallelDims(dp=8))


def _search(base, **kw):
    from deepspeed_tpu.autotuning import PlannerSearch

    return PlannerSearch(_model(), base, _topo(), **kw)


# ------------------------------------------------------------ enumeration
def test_candidate_space_enumeration():
    """The full space: zero ladder × remat × micro when the zero section
    is untuned; a pinned section collapses the zero axis; tp>1 adds the
    overlap on/off axis; serving configs swap to the token_budget axis."""
    from deepspeed_tpu.autotuning import PlannerSearch
    from deepspeed_tpu.autotuning.autotuner import REMAT_POLICIES, ZERO_LADDER

    base = {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "autotuning": {"max_train_micro_batch_size_per_gpu": 4}}
    cands = _search(base).candidates()
    labels = {c.label() for c in cands}
    # per-rung axis multipliers: stage-3 rungs carry the layer-prefetch
    # on/off axis AND both wire-codec axes (grad x param, 2 codecs each);
    # stage-1/2 rungs carry the grad-wire axis only (ISSUE 12)
    def units(stage):
        if stage == 3:
            return 2 * 2 * 2  # z3pf x grad_wire x param_wire
        if stage >= 1:
            return 2          # grad_wire
        return 1
    ladder_units = sum(units(z["stage"]) for z in ZERO_LADDER)
    assert len(cands) == ladder_units * len(REMAT_POLICIES) * 3
    assert "z0/none/mb1" in labels and "z3off/full/mb4/z3pf" in labels
    assert "z3/none/mb1/z3pf/gw-int8/pw-int8" in labels
    assert {c.z3_prefetch for c in cands if c.stage == 3} == {False, True}
    assert all(c.z3_prefetch is None for c in cands if c.stage != 3)
    assert {c.grad_wire for c in cands if c.stage >= 1} == {"fp32", "int8"}
    assert all(c.grad_wire is None for c in cands if c.stage == 0)
    assert all(c.param_wire is None for c in cands if c.stage != 3)
    # the wire axis collapses on request (heavier tests keep trace
    # counts flat with wire_codecs=("fp32",))
    collapsed = _search(base, wire_codecs=("fp32",)).candidates()
    n_stage3 = sum(1 for z in ZERO_LADDER if z["stage"] == 3)
    assert len(collapsed) == (
        (len(ZERO_LADDER) + n_stage3) * len(REMAT_POLICIES) * 3
    )

    pinned = dict(base, zero_optimization={"stage": 1})
    cands = _search(pinned).candidates()
    assert len(cands) == len(REMAT_POLICIES) * 3 * 2  # x grad_wire
    assert all(c.zero is None for c in cands)

    tp = dict(pinned, tensor_parallel={"tp_size": 2})
    cands = _search(tp, wire_codecs=("fp32",)).candidates()
    assert len(cands) == len(REMAT_POLICIES) * 3 * 2
    assert {c.tp_overlap for c in cands} == {False, True}

    # expert parallelism adds the decomposed-a2a on/off axis (ISSUE 10)
    moe = dict(pinned, moe={"enabled": True, "ep_size": 2,
                            "num_experts": 4})
    cands = _search(moe, wire_codecs=("fp32",)).candidates()
    assert len(cands) == len(REMAT_POLICIES) * 3 * 2
    assert {c.moe_a2a for c in cands} == {False, True}
    assert any("a2aov" in c.label() for c in cands)

    serving = dict(base, serving={"enabled": True})
    cands = _search(serving, token_budgets=(8, 32)).candidates()
    assert [c.token_budget for c in cands] == [8, 32]


def test_new_overlap_axes_reach_plans_and_configs(devices8):
    """The ISSUE-10 axes are real: the built candidate config carries the
    flags, the abstract trace prices both settings (R6/R8 run before any
    compile), and the a2a-on plan declares the overlapped moe_a2a stream
    while the off leg declares it serial."""
    from deepspeed_tpu.autotuning import PlannerSearch
    from deepspeed_tpu.models import mixtral

    model = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=16,
                    num_experts=2)
    base = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "stage3_param_persistence_threshold": 1},
        "moe": {"enabled": True, "ep_size": 2, "num_experts": 2},
        "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                       "tune_zero": False},
    }
    search = PlannerSearch(model, base, None, top_k=1,
                           wire_codecs=("fp32",))
    cands = search.candidates()
    assert {(c.moe_a2a, c.z3_prefetch) for c in cands} == {
        (False, False), (False, True), (True, False), (True, True),
    }
    on = next(c for c in cands if c.moe_a2a and c.z3_prefetch)
    cfg = search._candidate_config(on)
    assert cfg["moe"]["overlap_a2a"]["enabled"]
    assert cfg["zero_optimization"]["stage3_layer_prefetch"]
    res = search.search()
    by_label = {p.cand.label(): p for p in res.planned}
    p_on = next(p for p in res.planned
                if p.cand.moe_a2a and p.cand.z3_prefetch)
    p_off = next(p for p in res.planned
                 if not p.cand.moe_a2a and not p.cand.z3_prefetch)
    assert p_on.plan is not None and p_off.plan is not None, by_label
    assert p_on.plan.streams["moe_a2a"]["overlapped"]
    assert p_on.plan.streams["zero3_prefetch"]["overlapped"]
    assert not p_off.plan.streams["moe_a2a"]["overlapped"]
    assert "zero3_prefetch" not in p_off.plan.streams


# --------------------------------------------------- prune + rank + explain
def test_static_prune_rank_and_explain(devices8):
    """A tight budget prunes fat rungs BEFORE any compile, every pruned
    rung names why it lost, survivors rank by predicted throughput, and
    the top-k respects k."""
    base = {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "autotuning": {"max_train_micro_batch_size_per_gpu": 8}}
    res = _search(base, top_k=2, hbm_budget_bytes=1_200_000,
                  wire_codecs=("fp32",)).search()
    assert res.pruned and res.survivors
    assert len(res.top_k) == 2
    for pc in res.pruned:
        assert "exceeds" in pc.reason or "GiB" in pc.reason, pc.reason
    tputs = [p.predicted_tput for p in res.survivors]
    assert tputs == sorted(tputs, reverse=True)
    text = res.explain()
    assert "pruned:" in text and "compile+measure" in text
    # machine-readable spelling carries the same evidence
    payload = res.to_dict()
    assert payload["n_traced"] == res.n_traced
    assert len(payload["pruned"]) == len(res.pruned)


def test_memoized_scaling_skips_retrace(devices8):
    """The _is_oom hardening: once a (stage, remat) rung is statically
    pruned at micro=m, larger micros derive their plan by scaling the
    traced one — never a second trace — and still land in pruned with
    the derivation recorded."""
    base = {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": 0},
            "autotuning": {"max_train_micro_batch_size_per_gpu": 8}}
    res = _search(base, hbm_budget_bytes=1_200_000,
                  wire_codecs=("fp32",)).search()
    by_group = {}
    for pc in res.planned:
        by_group.setdefault(pc.cand.group_key(), []).append(pc)
    derived = [p for p in res.planned if not p.traced]
    assert derived, "expected at least one derived (non-traced) candidate"
    for pcs in by_group.values():
        pruned_traced = [p.cand.micro for p in pcs if p.pruned and p.traced]
        if not pruned_traced:
            continue
        m = min(pruned_traced)
        for pc in pcs:
            if pc.cand.micro > m:
                assert not pc.traced, (
                    f"{pc.cand.label()} re-traced although mb={m} was "
                    "already statically pruned"
                )
                assert pc.derived_from_micro == m
                assert pc.pruned
    # a derived plan's batch-linear terms scaled, state did not
    d = derived[0]
    src = next(p for p in by_group[d.cand.group_key()]
               if p.cand.micro == d.derived_from_micro)
    f = d.cand.micro / src.cand.micro
    assert d.plan.act_peak_bytes == pytest.approx(
        src.plan.act_peak_bytes * f)
    assert d.plan.param_bytes == src.plan.param_bytes


# ------------------------------------------------------- tune() integration
def test_planner_tune_matches_exhaustive_winner(devices8, monkeypatch,
                                                tmp_path):
    """ISSUE 7 acceptance shape: with a deterministic measurement oracle
    the planner-driven tune (compile ≤ top-k) picks the same winner as
    the exhaustive compile-and-measure ladder."""
    from deepspeed_tpu.autotuning import Autotuner

    base = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "autotuning": {"max_train_micro_batch_size_per_gpu": 4,
                       "trials": 1, "top_k": 3,
                       "drift_ledger": str(tmp_path / "drift.jsonl")},
    }
    # measured truth the roofline agrees with directionally: bigger micro
    # amortizes overhead, lighter remat wins when it fits
    weight = {"none": 4.0, "dots_flash": 3.0, "attn_mlp": 2.0, "full": 1.0}

    def fake_measure(self, mb, pol, blocks=(0, 0), cfg=None):
        return 100.0 * mb * weight[pol]

    monkeypatch.setattr(Autotuner, "_measure", fake_measure)
    monkeypatch.setattr(Autotuner, "_flash_tunable", lambda self: False)

    exhaustive = Autotuner(_model(), dict(base), topology=_topo(),
                           sample_batch_fn=lambda g: None)
    exhaustive.planner = False
    best_ex = exhaustive.tune()

    planned = Autotuner(_model(), dict(base), topology=_topo(),
                        sample_batch_fn=lambda g: None)
    planned.planner = True
    best_pl = planned.tune()
    assert planned.last_search is not None
    assert len(planned.last_search.top_k) <= 3
    assert (best_pl["micro_batch"], best_pl["remat_policy"]) == (
        best_ex["micro_batch"], best_ex["remat_policy"])
    # planner recs carry the prediction they were ranked on
    assert best_pl["predicted_step_s"] > 0


def test_planner_tune_end_to_end_real_measure(devices8, tmp_path):
    """Planner mode with real compiles on the CPU mesh: at most top-k
    engines are built, the winner is the max measured record, the patch
    round-trips into a runnable config, and the drift ledger banks one
    (predicted, measured) pair per measured survivor."""
    import deepspeed_tpu
    from deepspeed_tpu.autotuning import Autotuner, result_to_config_patch

    ledger_path = str(tmp_path / "drift.jsonl")
    r = np.random.RandomState(0)
    base = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "autotuning": {
            "max_train_micro_batch_size_per_gpu": 2,
            "start_profile_step": 1, "end_profile_step": 2, "trials": 1,
            "planner": True, "top_k": 2, "drift_ledger": ledger_path,
        },
    }
    tuner = Autotuner(
        _model(), base, topology=_topo(),
        sample_batch_fn=lambda g: {
            "input_ids": r.randint(0, 64, size=(g, 16))
        },
    )
    best = tuner.tune()
    assert tuner.n_compiles <= 2  # the prune-before-compile contract
    assert tuner.last_search is not None
    top = max(tuner.results, key=lambda rec: rec["throughput"])
    assert best == top
    entries = [json.loads(line) for line in
               open(ledger_path).read().splitlines()]
    assert len(entries) == len(tuner.results)
    for e in entries:
        assert e["ratio"] and e["ratio"] > 0
        assert e["gen"] == "cpu"
        assert e["source"].startswith("autotune:")
    patch = result_to_config_patch(best)
    cfg = {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
           "zero_optimization": {"stage": 0}}
    cfg.update(patch)
    engine, *_ = deepspeed_tpu.initialize(model=_model(), config=cfg,
                                          topology=_topo())
    B = cfg["train_micro_batch_size_per_gpu"] * 8
    loss = float(engine.train_batch(batch={
        "input_ids": r.randint(0, 64, size=(B, 16))
    }))
    assert np.isfinite(loss)
    engine.destroy()


def test_planner_tune_measures_full_candidate_config(devices8, monkeypatch,
                                                     tmp_path):
    """The tp-overlap axis survives measurement: each top-k candidate is
    measured with its EXACT planned config (not a (micro, remat)-only
    rebuild), the winning record carries the full tensor_parallel
    section, and the patch round-trips it without wiping tp_size."""
    from deepspeed_tpu.autotuning import Autotuner, result_to_config_patch

    base = {
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 0},
        "tensor_parallel": {"tp_size": 2},
        "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                       "trials": 1, "top_k": 4, "planner": True,
                       "drift_ledger": str(tmp_path / "drift.jsonl")},
    }
    tuner = Autotuner(_model(), base, topology=None,
                      sample_batch_fn=lambda g: None)
    measured_cfgs = []

    def fake_measure(mb, pol, blocks=(0, 0), cfg=None):
        assert cfg is not None, "planner must pass the candidate's config"
        measured_cfgs.append(cfg)
        overlap = (cfg.get("tensor_parallel", {})
                   .get("overlap_comm", {}).get("enabled", False))
        return 100.0 + (7.0 if overlap else 0.0)

    monkeypatch.setattr(tuner, "_measure", fake_measure)
    monkeypatch.setattr(tuner, "_flash_tunable", lambda: False)
    best = tuner.tune()
    overlaps = [
        c.get("tensor_parallel", {}).get("overlap_comm", {}).get("enabled",
                                                                 False)
        for c in measured_cfgs
    ]
    assert True in overlaps and False in overlaps, overlaps
    assert best["tensor_parallel"]["overlap_comm"]["enabled"] is True
    assert best["tensor_parallel"]["tp_size"] == 2
    patch = result_to_config_patch(best)
    assert patch["tensor_parallel"]["tp_size"] == 2
    assert patch["tensor_parallel"]["overlap_comm"]["enabled"] is True


def test_planner_tune_refuses_serving_configs(devices8):
    """Serving token_budget search is static-only: planner-mode tune
    must refuse loudly instead of timing a train step per budget."""
    import pytest as _pytest

    from deepspeed_tpu.autotuning import Autotuner

    tuner = Autotuner(
        _model(),
        {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
         "serving": {"enabled": True},
         "autotuning": {"planner": True}},
        sample_batch_fn=lambda g: None,
    )
    with _pytest.raises(NotImplementedError, match="static-only"):
        tuner.tune()


def test_planner_tune_all_pruned_raises(devices8):
    """Every candidate statically over budget → a loud explain-carrying
    error, not a silent fallback to compiling doomed rungs."""
    from deepspeed_tpu.autotuning import Autotuner

    tuner = Autotuner(
        _model(),
        {"optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
         "zero_optimization": {"stage": 0},
         "autotuning": {"max_train_micro_batch_size_per_gpu": 1,
                        "planner": True}},
        topology=_topo(), sample_batch_fn=lambda g: None,
    )
    tuner.hbm_gb = 1e-6  # ~1 KiB: nothing fits
    with pytest.raises(RuntimeError, match="statically over the HBM"):
        tuner.tune()
    assert tuner.n_compiles == 0


# ------------------------------------------------------------ drift ledger
def test_drift_ledger_roundtrip_check_and_bands(tmp_path):
    from deepspeed_tpu.analysis.cost import drift

    path = str(tmp_path / "ledger.jsonl")
    ledger = drift.DriftLedger(path)
    ledger.append({"source": "a", "gen": "v5e", "ratio": 1.1,
                   "bound": "compute", "ts": 1.0})
    ledger.append({"source": "b", "gen": "v5e", "ratio": 0.9,
                   "bound": "compute", "ts": 2.0})
    rows = ledger.load(gen="v5e")
    assert len(rows) == 2
    ok, problems = drift.check(rows)
    assert ok, problems
    s = drift.summarize(rows)
    assert s["n"] == 2 and s["median_ratio"] == 1.0

    # out-of-band entry: named violation
    bad = rows + [{"source": "c", "gen": "v5e", "ratio": 3.0,
                   "bound": "compute"}]
    ok, problems = drift.check(bad)
    assert not ok and any("outside" in p for p in problems)
    # spread violation even when each entry is in its (wide cpu) band
    spread = [{"source": "d", "gen": "cpu", "ratio": 0.2, "bound": "compute"},
              {"source": "e", "gen": "cpu", "ratio": 4.0, "bound": "compute"}]
    ok, problems = drift.check(spread)
    assert not ok and any("relative pricing" in p for p in problems)
    # peak band rides along when present
    ok, problems = drift.check([{"source": "f", "gen": "v5e", "ratio": 1.0,
                                 "bound": "compute", "peak_ratio": 1.3}])
    assert not ok and any("HBM peak" in p for p in problems)
    assert drift.band_for("cpu")[1] > drift.band_for("v5e")[1]


def test_drift_recalibration_suggestion():
    """Systematic drift (median outside RECAL_BAND, >= 3 samples) names
    the binding cost/hardware.py constant and the centering value."""
    from deepspeed_tpu.analysis.cost import drift
    from deepspeed_tpu.analysis.cost.hardware import gen_defaults

    rows = [{"source": f"s{i}", "gen": "v5e", "ratio": 0.5,
             "bound": "compute"} for i in range(3)]
    note = drift.recalibration_suggestion(rows)
    assert note and "peak_flops" in note and "v5e" in note
    expected = gen_defaults("v5e")["peak_flops"] * 0.5
    assert f"{expected:.3g}" in note
    # hbm-bound drift points at hbm_bw instead
    rows = [{"source": f"s{i}", "gen": "v5e", "ratio": 2.0, "bound": "hbm"}
            for i in range(3)]
    assert "hbm_bw" in drift.recalibration_suggestion(rows)
    # centered ledgers stay quiet
    rows = [{"source": f"s{i}", "gen": "v5e", "ratio": 1.0,
             "bound": "compute"} for i in range(5)]
    assert drift.recalibration_suggestion(rows) is None


def test_scale_plan_micro_batch_linear_terms():
    from deepspeed_tpu.analysis.cost import HardwareModel, Plan, \
        scale_plan_micro

    hw = HardwareModel(gen="test", peak_flops=1e9, hbm_bytes=1 << 30,
                       hbm_bw=1e9, ici_bw=1e9, host_bw=1e9)
    plan = Plan(source="mb1", hardware=hw, param_bytes=100.0,
                opt_bytes=50.0, act_peak_bytes=10.0, peak_hbm_bytes=160.0,
                flops=1e9, hbm_traffic_bytes=5e8,
                ici_bytes={"dp": 2e8}, ici_hops={"dp": 7})
    plan.compute_s, plan.hbm_s, plan.ici_s = 1.0, 0.5, 0.2
    plan.est_step_s = 1.0
    scaled = scale_plan_micro(plan, 4.0)
    assert scaled.act_peak_bytes == 40.0
    assert scaled.peak_hbm_bytes == 160.0 + 30.0  # + act * (f - 1)
    assert scaled.param_bytes == 100.0 and scaled.opt_bytes == 50.0
    assert scaled.flops == 4e9 and scaled.hbm_traffic_bytes == 2e9
    assert scaled.ici_bytes == {"dp": 8e8}
    assert scaled.est_step_s == pytest.approx(4.0)  # compute-bound x4
    # the original is untouched (dataclasses.replace semantics)
    assert plan.act_peak_bytes == 10.0 and plan.flops == 1e9


# ----------------------------------------------------------------- the CLI
@pytest.mark.shardlint
def test_autoplan_cli_static_search(devices8, tmp_path):
    """tools/autoplan.py static mode on a shipped config: exit 0, ranked
    table, --json payload; a tiny --hbm-gb prunes and --explain says
    why."""
    import subprocess
    import sys

    cfg = os.path.join(REPO, "examples", "ds_config_zero3.json")
    out = tmp_path / "autoplan.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "autoplan.py"), cfg,
         "--max-micro", "2", "--top-k", "2", "--json", str(out)],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "compile+measure" in proc.stdout
    payload = json.loads(out.read_text())
    assert payload["survivors"] and len(payload["top_k"]) <= 2

    pruned = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "autoplan.py"), cfg,
         "--max-micro", "2", "--hbm-gb", "0.0001", "--explain"],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert "pruned: " in pruned.stdout
