"""Decomposed MoE all-to-all (ISSUE 10): CPU-mesh oracles prove the
chunked ppermute rings match the module's pure-XLA reference path BITWISE
for both dispatch modes, plus moe_layer/engine integration and the
moe.overlap_a2a config surface.

Kept inside the tier-1 budget: one tiny expert layer shared by the oracle
grid; the engine legs use 2-layer models and 2 steps.
"""

import dataclasses
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError
from deepspeed_tpu.models import mixtral
from deepspeed_tpu.models.sharding import use_topology
from deepspeed_tpu.moe.sharded_moe import (
    moe_layer,
    top_k_gating,
    top_k_gating_indices,
)
from deepspeed_tpu.parallel import a2a_overlap as a2a

pytestmark = pytest.mark.a2a_overlap


def topo_for(ep: int) -> MeshTopology:
    """ep over the smallest device subset, keeping a dp axis when
    possible (odd ep sizes truncate the device list — 8 has no odd
    divisor > 1)."""
    if 8 % ep == 0:
        return MeshTopology(dims=ParallelDims(dp=8 // ep, ep=ep))
    return MeshTopology(
        dims=ParallelDims(dp=1, ep=ep), devices=jax.devices()[:ep]
    )


def _case(ep, *, B=None, S_mult=4, E_mult=1, top_k=2, cap_factor=2.0,
          seed=0):
    """One oracle case: tokens, gating tensors/tables and expert weights
    sized to the ep mesh (B divides the dp axis). Returns everything
    both paths need."""
    topo = topo_for(ep)
    B = B or 2 * topo.dp_size
    D, F = 16, 32
    E = ep * E_mult
    S = S_mult * ep
    N = B * S
    capacity = max(4, int(math.ceil(cap_factor * top_k * N / E)))
    r = np.random.RandomState(seed)
    x = jnp.asarray(r.randn(B, S, D), jnp.float32)
    wi = jnp.asarray(r.randn(E, D, F) * 0.1, jnp.float32)
    wg = jnp.asarray(r.randn(E, D, F) * 0.1, jnp.float32)
    wo = jnp.asarray(r.randn(E, F, D) * 0.1, jnp.float32)
    logits = jnp.asarray(r.randn(N, E), jnp.float32)
    return topo, x, (wi, wg, wo), logits, E, capacity, top_k, B, S


def _einsum_gating(logits, top_k, capacity, B, S, E, dtype):
    dispatch, combine, _ = top_k_gating(logits, top_k, capacity, None, True)
    return ("einsum", dispatch.astype(dtype).reshape(B, S, E, capacity),
            combine.astype(dtype).reshape(B, S, E, capacity))


def _gather_gating(logits, top_k, capacity, B, S):
    tos, sv, sot, wot, _ = top_k_gating_indices(
        logits, top_k, capacity, None, True
    )
    return ("gather", tos, sv, sot.reshape(B, S, -1), wot.reshape(B, S, -1))


def _run(topo, x, gating, weights, **kw):
    with use_topology(topo):
        return jax.jit(
            lambda x, wi, wg, wo: a2a.moe_a2a_ffn(
                x, gating, (wi, wg, wo), topo, **kw
            )
        )(x, *weights)


# ----------------------------------------------------------------- oracles
@pytest.mark.parametrize("ep", [2, 4, 3])  # odd AND even ring sizes
@pytest.mark.parametrize("mode", ["einsum", "gather"])
def test_ring_bitwise_vs_reference(ep, mode, devices8):
    topo, x, w, logits, E, C, K, B, S = _case(ep)
    gating = (
        _einsum_gating(logits, K, C, B, S, E, x.dtype)
        if mode == "einsum" else _gather_gating(logits, K, C, B, S)
    )
    ref = _run(topo, x, gating, w, reference=True)
    ring = _run(topo, x, gating, w)
    np.testing.assert_array_equal(np.asarray(ring), np.asarray(ref))


def test_uneven_chunks_and_bidirectional_bitwise(devices8):
    """Capacity chunks that don't divide C, and the two-stream halves,
    pin the ring to the reference AT THE SAME chunking (the reference
    mirrors the local loop structure, only the wire differs), and
    moderate chunkings also reproduce the unchunked reference bitwise
    (top_k=2: a token's two combine terms commute). Degenerate width-1
    chunks (chunks > C) stay ring==reference but may drift an ulp from
    the unchunked shape — XLA picks a different dot kernel, reassociating
    the d-contraction — which is why the oracle is same-chunking."""
    topo, x, w, logits, E, C, K, B, S = _case(4, cap_factor=1.0)
    gating = _einsum_gating(logits, K, C, B, S, E, x.dtype)
    ref = _run(topo, x, gating, w, reference=True)
    for kw in (dict(chunks=3), dict(chunks=2, bidirectional=True),
               dict(chunks=C + 5, bidirectional=True)):
        got = _run(topo, x, gating, w, **kw)
        same = _run(topo, x, gating, w, reference=True, **kw)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(same),
                                      err_msg=str(kw))
        if kw["chunks"] <= C // 2:
            np.testing.assert_array_equal(np.asarray(got), np.asarray(ref),
                                          err_msg=str(kw))


def test_capacity_dropped_tokens_bitwise(devices8):
    """A tight capacity drops tokens: dropped slots are exact zeros in
    both paths and dropped tokens' outputs stay zero — ring == reference
    bitwise, both modes."""
    topo, x, w, logits, E, C, K, B, S = _case(2, cap_factor=0.25, seed=3)
    assert C < (B * S * K) // E  # capacity really binds
    for gating in (_einsum_gating(logits, K, C, B, S, E, x.dtype),
                   _gather_gating(logits, K, C, B, S)):
        ref = _run(topo, x, gating, w, reference=True)
        ring = _run(topo, x, gating, w, chunks=2)
        np.testing.assert_array_equal(np.asarray(ring), np.asarray(ref))


def test_dp_sharded_tokens_and_serial_parity(devices8):
    """With a live dp axis the per-chunk psum folds the dp token shards;
    the overlapped output matches the serial moe_layer expert path to
    fp32 tolerance (different GSPMD reduction orders), and the gather
    mode matches it exactly."""
    cfg = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32,
                  num_experts=4).config
    m = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32,
                num_experts=4)
    params = m.init(jax.random.PRNGKey(0))
    p = jax.tree.map(lambda a: a[0], params["layers"]["mlp"])
    topo = MeshTopology(dims=ParallelDims(dp=2, ep=4))
    B, S, D = 2, 16, cfg.hidden_size
    x = jax.random.normal(jax.random.PRNGKey(1), (B, S, D), jnp.float32)

    def serial(cfg_, x_):
        out, _ = moe_layer(cfg_, p, x_, rng=None, train=True)
        return out

    def overlapped(cfg_, x_):
        ov = DeepSpeedConfig({
            "train_batch_size": 8,
            "moe": {"enabled": True, "ep_size": 4,
                    "overlap_a2a": {"enabled": True, "chunks": 2}},
        }).moe.overlap_a2a
        with use_topology(topo), a2a.a2a_scope(ov):
            out, _ = moe_layer(cfg_, p, x_, rng=None, train=True)
            return out

    base = serial(cfg, x)
    got = jax.jit(lambda x_: overlapped(cfg, x_))(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               rtol=2e-5, atol=2e-5)
    cfg_g = dataclasses.replace(cfg, moe_dispatch="gather")
    base_g = serial(cfg_g, x)
    got_g = jax.jit(lambda x_: overlapped(cfg_g, x_))(x)
    np.testing.assert_allclose(np.asarray(got_g), np.asarray(base_g),
                               rtol=1e-6, atol=1e-6)


def test_gradients_flow_through_ring(devices8):
    """The rings are plain differentiable collectives (ppermute transpose
    = reversed ring): token and weight cotangents match the reference
    path's."""
    topo, x, w, logits, E, C, K, B, S = _case(2)
    gating = _einsum_gating(logits, K, C, B, S, E, x.dtype)

    def loss(ref):
        def f(x_, wi):
            with use_topology(topo):
                out = a2a.moe_a2a_ffn(x_, gating, (wi, w[1], w[2]), topo,
                                      chunks=2, reference=ref)
            return jnp.sum(out ** 2)
        return f

    gx_r, gw_r = jax.grad(loss(True), argnums=(0, 1))(x, w[0])
    gx, gw = jax.grad(loss(False), argnums=(0, 1))(x, w[0])
    np.testing.assert_allclose(np.asarray(gx), np.asarray(gx_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r),
                               rtol=1e-5, atol=1e-5)
    assert float(jnp.sum(jnp.abs(gx))) > 0


# ------------------------------------------------------ engine integration
def test_engine_loss_parity_and_stream_accounting(devices8):
    """ep=4 training with overlap on tracks the off run step-for-step;
    the moe_a2a stream is declared on BOTH runs (the serial path moves
    the same logical bytes — the ISSUE-10 fix), flips overlapped with
    the knob, and its bytes reach the comms logger's ring intake; the
    ring hops hit the trace-time hook bus only when the knob is on."""
    data = {"input_ids":
            np.random.RandomState(0).randint(0, 256, size=(8, 32))}

    def run(overlap):
        comm.destroy_process_group()
        cfg = {
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "moe": {"enabled": True, "ep_size": 4,
                    "overlap_a2a": {"enabled": overlap, "chunks": 2,
                                    "bidirectional": True}},
            "comms_logger": {"enabled": True},
            "steps_per_print": 1000,
        }
        model = mixtral("mixtral-tiny", vocab_size=256, max_seq_len=32,
                        num_experts=4)
        eng, *_ = deepspeed_tpu.initialize(model=model, config=cfg)
        losses = [float(eng.train_batch(batch=data)) for _ in range(2)]
        stream = eng.analytic_streams()["moe_a2a"]
        pperm = eng.comm_logger.counts.get("ppermute", 0)
        ring_logged = eng.comm_logger.ring_bytes
        eng.destroy()
        return losses, stream, pperm, ring_logged

    l_off, s_off, pp_off, rb_off = run(False)
    l_on, s_on, pp_on, rb_on = run(True)
    np.testing.assert_allclose(l_off, l_on, rtol=2e-3, atol=2e-3)
    assert not s_off["overlapped"] and s_on["overlapped"]
    assert s_on["bytes_per_step"] == s_off["bytes_per_step"] > 0
    assert rb_on == 2 * s_on["bytes_per_step"]  # two recorded steps
    assert pp_on > pp_off == 0


def test_fallback_outside_scope_and_on_undividable_shapes(devices8):
    """Without the scope moe_layer is untouched; with the scope active
    but shapes not dividing the mesh the applicability predicate refuses
    and the serial path runs (no shard_map in the trace)."""
    topo = MeshTopology(dims=ParallelDims(dp=2, ep=4))
    assert a2a.current_a2a() is None
    # E=3 does not divide ep=4; S=6 does not divide sp*ep
    assert not a2a.moe_a2a_applicable(topo, B=4, S=8 * 4, E=3, F=32)
    assert not a2a.moe_a2a_applicable(topo, B=4, S=6, E=4, F=32)
    assert not a2a.moe_a2a_applicable(topo, B=3, S=8, E=4, F=32)
    assert a2a.moe_a2a_applicable(topo, B=4, S=8, E=4, F=32)
    ep1 = MeshTopology(dims=ParallelDims(dp=8))
    assert not a2a.moe_a2a_applicable(ep1, B=8, S=8, E=4, F=32)
    # an engine whose knob is ON but whose shapes keep the rings from
    # engaging must NOT declare the stream overlapped (R8 would hide
    # wire that actually runs serialized) — bytes still declared
    comm.destroy_process_group()
    model = mixtral("mixtral-tiny", vocab_size=256, max_seq_len=30,
                    num_experts=4)  # S=30 % ep=4 != 0 → serial fallback
    eng, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "moe": {"enabled": True, "ep_size": 4,
                    "overlap_a2a": {"enabled": True}},
        },
        abstract_init=True,
    )
    s = eng.analytic_streams()["moe_a2a"]
    assert eng.moe_a2a is not None and not s["overlapped"]
    assert s["bytes_per_step"] > 0
    eng.destroy()


def test_malformed_ring_raises_at_construction(devices8):
    """The rings go through comm.collectives.permute: a malformed
    hand-built perm raises at trace time (the R3 contract), so no
    a2a-overlap program can ever carry a hang-shaped exchange."""
    topo = MeshTopology(dims=ParallelDims(dp=2, ep=4))
    from deepspeed_tpu.utils.jax_compat import shard_map
    from jax.sharding import PartitionSpec as P

    bad = [(0, 1), (1, 2), (2, 3), (3, 1)]

    def body(v):
        return comm.collectives.permute(v, "ep", bad)

    fn = shard_map(
        body, mesh=topo.mesh, in_specs=P("ep"), out_specs=P("ep"),
        axis_names=set(topo.mesh.axis_names), check_vma=False,
    )
    with pytest.raises(ValueError, match="malformed ppermute"):
        jax.make_jaxpr(fn)(jax.ShapeDtypeStruct((8,), jnp.float32))


def test_bytes_accounting_and_config_surface():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "moe": {"enabled": True, "ep_size": 2,
                "overlap_a2a": {"enabled": True, "chunks": 4,
                                "bidirectional": True}},
    })
    oa = cfg.moe.overlap_a2a
    assert (oa.enabled, oa.chunks, oa.bidirectional) == (True, 4, True)
    # bare boolean coerces; defaults off
    oa2 = DeepSpeedConfig({
        "train_batch_size": 8, "moe": {"enabled": True, "overlap_a2a": True},
    }).moe.overlap_a2a
    assert oa2.enabled and oa2.chunks == 1
    assert not DeepSpeedConfig(
        {"train_batch_size": 8}).moe.overlap_a2a.enabled
    with pytest.raises(DeepSpeedConfigError, match="chunks"):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "moe": {"overlap_a2a": {"enabled": True, "chunks": 0}},
        })
    with pytest.raises(DeepSpeedConfigError, match="pipeline"):
        DeepSpeedConfig({
            "train_batch_size": 8,
            "pipeline": {"stages": 2},
            "moe": {"enabled": True, "overlap_a2a": {"enabled": True}},
        })
    # analytic bytes: 2 exchanges/layer fwd, doubled for backward
    mcfg = mixtral("mixtral-tiny", vocab_size=64, max_seq_len=32,
                   num_experts=4).config
    topo = MeshTopology(dims=ParallelDims(dp=2, ep=4))
    s = a2a.moe_a2a_bytes_per_step(mcfg, topo, batch=4, seq=32, itemsize=4)
    C = s["capacity"]
    per_dir = (4 // 4) * C * mcfg.hidden_size * 4 * 3
    assert s["fwd_bytes_per_step"] == 2 * per_dir * mcfg.num_layers
    assert s["bytes_per_step"] == 2 * s["fwd_bytes_per_step"]
    assert a2a.moe_a2a_bytes_per_step(
        mcfg, MeshTopology(dims=ParallelDims(dp=8)), batch=4, seq=32
    ) is None
