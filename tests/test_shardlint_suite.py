"""shardlint over real engine configs: the suite's own, the shipped
examples, and the bench legs (via the CLI — the tier-1 flow hook).

conftest records every (config, model, topology) the suite constructs an
engine from; here each unique one is rebuilt as an abstract engine
(ShapeDtypeStruct state — no compute) and linted. Configs whose step
cannot trace on this jax image (legacy partial-manual shard_map) are
skipped loudly, never passed silently.
"""

import json
import os
import subprocess
import sys

import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.analysis import lint_engine
from deepspeed_tpu.models import gpt2

import conftest

pytestmark = pytest.mark.shardlint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# cap re-linted captured configs to keep the default suite fast; skipped
# ones are reported in the assertion message, not silently dropped
MAX_CAPTURED = 24

# configs the important subsystems run under — linted even when test
# selection (-k) means nothing was captured before this file executes
CURATED = [
    ("zero0-bf16", {
        "train_batch_size": 16, "train_micro_batch_size_per_gpu": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True},
        "zero_optimization": {"stage": 0},
    }),
    ("zero3-accum", {
        "train_batch_size": 32, "gradient_accumulation_steps": 2,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "bf16": {"enabled": True}, "gradient_clipping": 1.0,
        "zero_optimization": {"stage": 3},
    }),
    ("zero3-offload-serial", {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "offload_optimizer": {"device": "cpu"}},
    }),
    ("zero3-offload-double-buffer", {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {"stage": 3,
                              "offload_optimizer": {"device": "cpu"},
                              "offload_double_buffer": True},
    }),
    ("fp16-dynamic-scale", {
        "train_batch_size": 16,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "fp16": {"enabled": True},
        "zero_optimization": {"stage": 2},
    }),
]


def _lint_one(name, cfg, model, topology, failures, skipped):
    comm.destroy_process_group()
    try:
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=cfg, topology=topology, abstract_init=True
        )
    except NotImplementedError as e:
        skipped.append((name, str(e).splitlines()[0]))
        return
    try:
        report = lint_engine(engine, source=name)
    except NotImplementedError as e:  # legacy-jax shard_map trace refusal
        skipped.append((name, str(e).splitlines()[0]))
        return
    finally:
        engine.destroy()
    if not report.ok:
        failures.extend(f.format() for f in report.errors)


def test_curated_suite_configs_lint_clean(devices8):
    failures, skipped = [], []
    for name, cfg in CURATED:
        model = gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16)
        _lint_one(name, dict(cfg), model, None, failures, skipped)
    assert not failures, "\n".join(failures)
    assert not skipped, skipped  # curated configs must all trace on CPU


def test_dim0_sharded_stacked_leaves_lint_clean(devices8):
    """The PR-1 bug shape itself: L is the largest dp-divisible dim, so
    add_data_axes shards the stacked layer dim. With the resting re-put
    fix the bucketed scan must lint closed (R2) instead of being gated
    off."""
    model = gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16,
                 hidden_size=12, num_layers=8, num_heads=2,
                 intermediate_size=12)
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 3,
            "stage3_param_persistence_threshold": 0,
            "offload_optimizer": {"device": "cpu"},
        },
    }
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=cfg, abstract_init=True
    )
    assert engine._bucketed_opt is not None  # the gate is gone
    report = lint_engine(engine, source="dim0-sharded-bucketed")
    assert report.ok and not report.findings, report.format()


def test_captured_suite_configs_lint_clean(devices8):
    """Lint every unique engine config the suite constructed before this
    file ran (conftest.SHARDLINT_CAPTURE). Alphabetical file order means
    roughly half the suite has executed by now — the curated list above
    covers the rest deterministically."""
    captured = list(conftest.SHARDLINT_CAPTURE)
    if not captured:
        pytest.skip("no engine configs captured (selective run)")
    failures, skipped = [], []
    linted = 0
    for cfg_raw, model, topology in captured[:MAX_CAPTURED]:
        name = f"captured[{linted}]"
        _lint_one(name, dict(cfg_raw), model, topology, failures, skipped)
        linted += 1
    over = len(captured) - MAX_CAPTURED
    assert not failures, (
        "\n".join(failures)
        + (f"\n(+{over} configs beyond the lint cap)" if over > 0 else "")
    )
    # legacy-image skips are expected (partial-manual shard_map legs);
    # anything else skipping deserves eyes
    for name, why in skipped:
        assert "shard_map" in why or "abstract_init" in why, (name, why)


def test_cli_all_examples_clean_and_fast(devices8, tmp_path):
    """The tier-1 flow hook: tools/shardlint.py --all-examples must exit 0
    with zero findings on every shipped examples/ config and the bench.py
    410M/1.5B legs, each analyzed in < 30 s (ISSUE 2 acceptance)."""
    out = tmp_path / "shardlint.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "shardlint.py"),
         "--all-examples", "--json", str(out)],
        capture_output=True, text=True, timeout=540, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    payload = json.loads(out.read_text())
    assert payload["ok"]
    assert payload["findings"] == []
    names = [s["source"] for s in payload["sources"]]
    assert "examples/ds_config_zero3.json" in names
    assert "bench-410m" in names
    assert "bench-1b-offload" in names and "bench-1b-offload-db" in names
    for s in payload["sources"]:
        assert s.get("skipped") is None, s
        assert s["seconds"] < 30.0, s


def test_lint_config_rejects_modelless_call():
    from deepspeed_tpu.analysis import lint_config

    with pytest.raises(ValueError, match="model"):
        lint_config({"train_batch_size": 8})
