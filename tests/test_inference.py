"""Inference engine (SURVEY §2.6): cached decode == full re-forward greedy;
TP-sharded serving; weight-only quantization sanity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from deepspeed_tpu import init_inference
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import bloom, gpt2, llama
from deepspeed_tpu.models.decoding import forward_with_cache, init_cache
from deepspeed_tpu.ops.quantizer import (
    dequantize_blockwise,
    quantize_blockwise,
    quantize_dequantize,
)


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


def greedy_reference(model, params, prompt, n_new):
    """Decode by full re-forward each step (no cache) — the oracle."""
    ids = jnp.asarray(prompt)
    for _ in range(n_new):
        logits, _ = model.apply(params, ids, dtype=jnp.float32)
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return np.asarray(ids)


@pytest.mark.parametrize("family", ["llama", "gpt2", "bloom"])
def test_cached_decode_matches_full_forward(family):
    if family == "llama":
        model = tiny_llama()
    elif family == "gpt2":
        model = gpt2("gpt2-tiny", vocab_size=128, max_seq_len=64,
                     hidden_size=32, num_layers=2, num_heads=4)
    else:
        model = bloom("bloom-tiny", vocab_size=128, max_seq_len=64,
                      hidden_size=32, num_layers=2, num_heads=4)
    params = model.init(jax.random.PRNGKey(0), dtype=jnp.float32)
    cfg = model.config
    B, S = 2, 8
    ids = np.random.RandomState(0).randint(0, 128, size=(B, S))

    # full forward logits
    full_logits, _ = model.apply(params, jnp.asarray(ids), dtype=jnp.float32)

    # prefill in two chunks through the cache: same logits
    cache = init_cache(cfg, B, 16, jnp.float32)
    l1, cache = forward_with_cache(cfg, params, jnp.asarray(ids[:, :5]), cache, 0,
                                   dtype=jnp.float32)
    l2, cache = forward_with_cache(cfg, params, jnp.asarray(ids[:, 5:]), cache, 5,
                                   dtype=jnp.float32)
    got = np.concatenate([np.asarray(l1), np.asarray(l2)], axis=1)
    np.testing.assert_allclose(got, np.asarray(full_logits), rtol=2e-4, atol=2e-4)


def test_generate_greedy_matches_reference():
    model = tiny_llama()
    engine = init_inference(model, dtype=jnp.float32, max_tokens=64,
                            rng=jax.random.PRNGKey(1))
    prompt = np.random.RandomState(1).randint(0, 128, size=(2, 6))
    out = engine.generate(prompt, max_new_tokens=6, temperature=0.0)
    ref = greedy_reference(model, engine.params, prompt, 6)
    np.testing.assert_array_equal(out, ref)


def test_generate_eos_stops():
    model = tiny_llama()
    engine = init_inference(model, dtype=jnp.float32, max_tokens=64,
                            rng=jax.random.PRNGKey(2))
    prompt = np.random.RandomState(2).randint(0, 128, size=(1, 4))
    ref = greedy_reference(model, engine.params, prompt, 8)
    eos = int(ref[0, 5])  # force eos at the 2nd generated token
    out = engine.generate(prompt, max_new_tokens=8, temperature=0.0,
                          eos_token_id=eos)
    # after eos, everything is eos-padded
    assert (out[0, 6:] == eos).all()


def test_tp_sharded_serving():
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    topo = MeshTopology(dims=ParallelDims(tp=4, dp=2))
    engine = init_inference(model, topology=topo, dtype=jnp.float32,
                            rng=jax.random.PRNGKey(3))
    single = init_inference(model, dtype=jnp.float32, rng=jax.random.PRNGKey(3),
                            topology=MeshTopology(devices=jax.devices()[:1]))
    prompt = np.random.RandomState(3).randint(0, 128, size=(2, 5))
    out_tp = engine.generate(prompt, max_new_tokens=5)
    out_1 = single.generate(prompt, max_new_tokens=5)
    np.testing.assert_array_equal(out_tp, out_1)


def test_tp_packed_decode_streams_per_shard():
    """ADVICE r5 fix: tp>1 int8 decode must run the Pallas streaming
    matvec PER SHARD (packed_proj's shard_map wrapper), not dequantize
    full-width weights every step. Asserts STREAMING (the sharded kernel
    path traced), not just packed HBM residency — plus token parity with
    the unsharded packed engine."""
    from deepspeed_tpu.ops.pallas import quantized_matmul as qm
    from deepspeed_tpu.ops.quantizer import PackedWeight

    # hidden 256 so each tp=2 column shard keeps whole 128-lane tiles and
    # d = 2 quantization blocks so the row-parallel wo shards G evenly
    model = tiny_llama(hidden_size=256, num_heads=4, num_kv_heads=4,
                       intermediate_size=512, num_layers=1)
    params = model.init(jax.random.PRNGKey(5), dtype=jnp.float32)
    prompt = np.array([[5, 9, 11, 3]])
    ref = init_inference(model, dtype="int8", params=params)
    out_ref = ref.generate(prompt, max_new_tokens=4)
    topo = MeshTopology(dims=ParallelDims(tp=2, dp=1),
                        devices=jax.devices()[:2])
    qm.reset_streaming_trace_counts()
    eng = init_inference(model, dtype="int8", params=params, topology=topo,
                         tp_size=2)
    # HBM residency stays packed per shard (the old guarantee)…
    leaves = jax.tree_util.tree_leaves(
        eng.params, is_leaf=lambda x: isinstance(x, PackedWeight)
    )
    packed = [l for l in leaves if isinstance(l, PackedWeight)]
    assert packed and all(p.pspec is not None for p in packed)
    out_tp = eng.generate(prompt, max_new_tokens=4)
    # …and the decode matvec now actually STREAMS under tp (new): the
    # sharded kernel path traced at least once per packed projection
    counts = qm.streaming_trace_counts()
    assert counts["sharded"] > 0, (
        "tp>1 packed decode took the dequantize-then-dot fallback "
        f"(trace counts {counts})"
    )
    np.testing.assert_array_equal(out_ref, out_tp)


def test_sampling_modes_run():
    model = tiny_llama()
    engine = init_inference(model, dtype=jnp.float32, rng=jax.random.PRNGKey(4))
    prompt = np.random.RandomState(4).randint(0, 128, size=(2, 4))
    out = engine.generate(prompt, max_new_tokens=4, temperature=0.8, top_k=10,
                          rng=jax.random.PRNGKey(9))
    assert out.shape == (2, 8)
    assert (out >= 0).all() and (out < 128).all()


def test_quantizer_roundtrip():
    r = np.random.RandomState(0)
    w = jnp.asarray(r.randn(256, 64).astype(np.float32))
    qt = quantize_blockwise(w, block=128, bits=8)
    deq = dequantize_blockwise(qt, jnp.float32)
    # int8 symmetric: ~0.5 LSB error relative to per-block amax
    err = np.abs(np.asarray(deq) - np.asarray(w))
    scale = np.asarray(qt.scale)
    assert err.max() <= scale.max() * 0.51 + 1e-6
    # int4 coarser but bounded
    qt4 = quantize_blockwise(w, block=128, bits=4)
    deq4 = dequantize_blockwise(qt4, jnp.float32)
    assert np.abs(np.asarray(deq4) - np.asarray(w)).max() <= np.asarray(qt4.scale).max() * 0.51 + 1e-6


def test_quantized_inference_close_to_fp():
    model = tiny_llama(hidden_size=64, intermediate_size=128)
    eng_fp = init_inference(model, dtype=jnp.float32, rng=jax.random.PRNGKey(5),
                            topology=MeshTopology(devices=jax.devices()[:1]))
    eng_q = init_inference(model, dtype=jnp.float32, quantize_bits=8,
                           rng=jax.random.PRNGKey(5),
                           topology=MeshTopology(devices=jax.devices()[:1]))
    ids = np.random.RandomState(5).randint(0, 128, size=(1, 8))
    lf = np.asarray(eng_fp(ids))
    lq = np.asarray(eng_q(ids))
    # weight-only int8 keeps logits close
    assert np.abs(lf - lq).mean() < 0.15


def test_init_inference_loads_checkpoint(tmp_path):
    """init_inference(checkpoint=dir) serves the trained engine weights
    (ADVICE r1: the argument was silently discarded)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm

    comm.destroy_process_group()
    model = tiny_llama()
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-2}},
        },
    )
    engine.train_batch(
        batch={"input_ids": np.random.RandomState(0).randint(0, 64, size=(8, 16))}
    )
    engine.save_checkpoint(str(tmp_path))
    comm.destroy_process_group()

    eng = init_inference(model, dtype=jnp.float32, checkpoint=str(tmp_path))
    ids = np.random.RandomState(1).randint(0, 64, size=(2, 8))
    got = np.asarray(eng.forward(ids))
    want = np.asarray(
        model.apply(
            jax.tree.map(lambda x: np.asarray(x, np.float32), engine.state.params),
            jnp.asarray(ids),
            dtype=jnp.float32,
        )[0]
    )
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_init_inference_checkpoint_errors(tmp_path):
    model = tiny_llama()
    with pytest.raises(FileNotFoundError):
        init_inference(model, checkpoint=str(tmp_path / "nope"))
    with pytest.raises(ValueError, match="not both"):
        init_inference(
            model, checkpoint=str(tmp_path), params=model.init(jax.random.PRNGKey(0))
        )


# ---------------------------------------------------------------------------
# r3: fused decode attention kernel + int4 weight-only path
# ---------------------------------------------------------------------------
def test_decode_attention_kernel_matches_matvec():
    """Pallas cached-KV decode == masked fp32 matvec, incl. GQA + short cache
    in a long buffer (the predication case)."""
    import jax.numpy as jnp

    from deepspeed_tpu.ops.pallas.decode_attention import decode_attention_kernel

    B, Smax, H, KV, hd = 2, 512, 4, 2, 64
    r = np.random.RandomState(0)
    q = jnp.asarray(r.randn(B, 1, H, hd), jnp.float32)
    kc = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32)
    vc = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32)
    for cache_len in (0, 5, 130, 511):
        out = decode_attention_kernel(q, kc, vc, jnp.asarray(cache_len))
        # reference: expand GQA, mask beyond cache_len, fp32 softmax
        kf = jnp.repeat(kc, H // KV, axis=2).astype(jnp.float32)
        vf = jnp.repeat(vc, H // KV, axis=2).astype(jnp.float32)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32), kf)
        logits = logits / np.sqrt(hd)
        kpos = jnp.arange(Smax)[None, None, None, :]
        logits = jnp.where(kpos <= cache_len, logits, -1e30)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), vf)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), atol=2e-5,
            err_msg=f"cache_len={cache_len}",
        )


def test_generate_uses_decode_kernel(monkeypatch):
    """With kernel injection on, the while_loop decode must trace the Pallas
    decode kernel and produce the same tokens as the XLA matvec."""
    import deepspeed_tpu
    import deepspeed_tpu.ops.pallas.decode_attention as da
    from deepspeed_tpu.models import llama
    from deepspeed_tpu.ops.attention import attention_impl

    model = llama("llama-tiny", vocab_size=128, max_seq_len=128,
                  hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                  intermediate_size=128)
    eng_ref = deepspeed_tpu.init_inference(model, max_tokens=128)
    prompt = np.arange(8).reshape(1, 8) % 128
    ref_tokens = eng_ref.generate(prompt, max_new_tokens=8)

    called = {}
    orig = da.decode_attention_kernel

    def spy(*a, **kw):
        called["yes"] = True
        return orig(*a, **kw)

    monkeypatch.setattr(da, "decode_attention_kernel", spy)
    # kernel_inject pins "auto" (xla on the CPU suite), which would shadow
    # the forced scope — build a plain engine and force "flash" around the
    # trace instead, which is what injection resolves to on a real TPU
    eng = deepspeed_tpu.init_inference(
        model, max_tokens=128, params=eng_ref.params,
    )
    with attention_impl("flash"):  # force the kernel path on the CPU suite
        tokens = eng.generate(prompt, max_new_tokens=8)
    assert called.get("yes"), "decode kernel never traced"
    np.testing.assert_array_equal(tokens, ref_tokens)


def test_int4_weight_only_inference():
    """dtype="int4" → weight-only 4-bit quant; close to fp output (parity
    bound loose: 4-bit), and strictly coarser than int8."""
    import deepspeed_tpu
    from deepspeed_tpu.models import llama

    model = llama("llama-tiny", vocab_size=128, max_seq_len=64,
                  hidden_size=64, num_layers=2, num_heads=4, num_kv_heads=2,
                  intermediate_size=128)
    rng = jax.random.PRNGKey(3)
    eng_fp = deepspeed_tpu.init_inference(model, dtype=jnp.float32, rng=rng)
    eng_i4 = deepspeed_tpu.init_inference(model, dtype="int4", rng=rng)
    assert eng_i4.dtype == jnp.bfloat16  # compute dtype, weights int4-qdq

    ids = np.arange(16).reshape(1, 16) % 128
    lf = np.asarray(eng_fp(ids), np.float32)
    l4 = np.asarray(eng_i4(ids), np.float32)
    # same argmax on most positions; logits within a loose bound
    agree = (lf.argmax(-1) == l4.argmax(-1)).mean()
    assert agree > 0.7, agree
    assert np.max(np.abs(lf - l4)) < 2.0


def test_generate_top_p_and_repetition_penalty():
    """top-p keeps outputs in-vocab and deterministic seeds reproduce;
    repetition_penalty discourages repeats vs the unpenalized run."""
    import deepspeed_tpu

    model = tiny_llama()
    engine = deepspeed_tpu.init_inference(model, max_tokens=64)
    prompt = np.random.RandomState(0).randint(0, model.config.vocab_size,
                                              size=(2, 8))
    out1 = engine.generate(prompt, max_new_tokens=8, temperature=0.8,
                           top_p=0.9, rng=jax.random.PRNGKey(1))
    out2 = engine.generate(prompt, max_new_tokens=8, temperature=0.8,
                           top_p=0.9, rng=jax.random.PRNGKey(1))
    assert (out1 == out2).all()  # same seed, same nucleus
    assert out1.shape == (2, 16)
    assert (out1 >= 0).all() and (out1 < model.config.vocab_size).all()

    pen = engine.generate(prompt, max_new_tokens=8, temperature=0.0,
                          repetition_penalty=5.0)
    pen2 = engine.generate(prompt, max_new_tokens=8, temperature=0.0,
                           repetition_penalty=5.0)
    assert (pen == pen2).all()  # penalized greedy is deterministic
    assert (pen >= 0).all() and (pen < model.config.vocab_size).all()


def test_apply_repetition_penalty_math():
    """Unit math (HF convention): seen+positive divides, seen+negative
    multiplies, unseen untouched."""
    from deepspeed_tpu.inference.engine import apply_repetition_penalty

    logits = jnp.asarray([[2.0, -2.0, 1.0, -1.0]])
    seen = jnp.asarray([[True, True, False, False]])
    out = np.asarray(apply_repetition_penalty(logits, seen, 2.0))
    np.testing.assert_allclose(out, [[1.0, -4.0, 1.0, -1.0]])


def test_generate_max_new_tokens_zero_echoes_prompt():
    import deepspeed_tpu

    model = tiny_llama()
    engine = deepspeed_tpu.init_inference(model, max_tokens=32)
    prompt = np.random.RandomState(2).randint(0, model.config.vocab_size,
                                              size=(1, 8))
    out = engine.generate(prompt, max_new_tokens=0)
    assert (out == prompt).all()


def test_generate_top_p_zero_still_greedyish():
    """top_p=0 must keep the top-1 token (no silent uniform sampling)."""
    import deepspeed_tpu

    model = tiny_llama()
    engine = deepspeed_tpu.init_inference(model, max_tokens=32)
    prompt = np.random.RandomState(1).randint(0, model.config.vocab_size,
                                              size=(1, 8))
    greedy = engine.generate(prompt, max_new_tokens=6, temperature=0.0)
    nucleus0 = engine.generate(prompt, max_new_tokens=6, temperature=0.5,
                               top_p=0.0, rng=jax.random.PRNGKey(0))
    # with only the top-1 token surviving, sampling == greedy
    assert (nucleus0 == greedy).all()


def test_int8_kv_cache_decode_close_to_bf16():
    """int8 KV cache (kv_cache_dtype="int8"): generate runs end-to-end and
    per-step decode logits stay close to the full-precision cache."""
    import deepspeed_tpu
    from deepspeed_tpu.models.decoding import forward_with_cache, init_cache

    model = tiny_llama()
    cfg = model.config
    params = model.init(jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(3).randint(0, cfg.vocab_size, size=(2, 12))
    )

    # prefill + one decode step on both cache flavors
    def run(quantized):
        cache = init_cache(cfg, 2, 32, jnp.float32, quantized=quantized)
        logits, cache = forward_with_cache(
            cfg, params, prompt, cache, 0, dtype=jnp.float32
        )
        nxt = logits[:, -1].argmax(-1)[:, None]
        step_logits, cache = forward_with_cache(
            cfg, params, nxt, cache, 12, dtype=jnp.float32
        )
        return np.asarray(logits[:, -1]), np.asarray(step_logits[:, -1])

    pre_f, dec_f = run(False)
    pre_q, dec_q = run(True)
    # prefill attends with exact new k/v: identical
    np.testing.assert_allclose(pre_q, pre_f, rtol=1e-5, atol=1e-5)
    # decode reads the quantized cache: close, and top-1 agrees
    np.testing.assert_allclose(dec_q, dec_f, rtol=0.2, atol=0.15)
    assert (dec_q.argmax(-1) == dec_f.argmax(-1)).mean() >= 0.5

    # engine-level: int8 cache generates in-vocab tokens deterministically
    engine = deepspeed_tpu.init_inference(
        model, max_tokens=32, kv_cache_dtype="int8",
        replace_with_kernel_inject=True,
    )
    out = engine.generate(np.asarray(prompt), max_new_tokens=6)
    out2 = engine.generate(np.asarray(prompt), max_new_tokens=6)
    assert (out == out2).all()
    assert out.shape == (2, 18) and (out < cfg.vocab_size).all()


def test_int8_kv_cache_halves_cache_bytes():
    from deepspeed_tpu.models.decoding import init_cache

    from deepspeed_tpu.models import llama

    cfg = llama(
        "llama-tiny", vocab_size=256, max_seq_len=128, hidden_size=256,
        num_layers=2, num_heads=2, num_kv_heads=2, head_dim=128,
        intermediate_size=256,
    ).config
    full = init_cache(cfg, 1, 128, jnp.bfloat16, quantized=False)
    quant = init_cache(cfg, 1, 128, jnp.bfloat16, quantized=True)
    data_bytes = lambda c: c["k"].nbytes + c["v"].nbytes
    assert data_bytes(quant) == data_bytes(full) // 2
    # scale overhead (32B/token-head) stays small next to hd=128 int8 data
    scale_bytes = quant["k_scale"].nbytes + quant["v_scale"].nbytes
    assert scale_bytes == data_bytes(quant) // 4


def test_kv_cache_dtype_bf16_honored():
    """kv_cache_dtype="bf16" on an fp32 engine must actually store bf16."""
    import deepspeed_tpu
    from deepspeed_tpu.models.decoding import init_cache

    model = tiny_llama()
    engine = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, kv_cache_dtype="bf16", max_tokens=32
    )
    assert engine.kv_cache_storage_dtype == jnp.bfloat16
    prompt = np.random.RandomState(4).randint(0, model.config.vocab_size,
                                              size=(1, 8))
    out = engine.generate(prompt, max_new_tokens=4)
    assert out.shape == (1, 12)
    with pytest.raises(ValueError):
        deepspeed_tpu.init_inference(model, kv_cache_dtype="fp8")


def test_decode_attention_kernel_int8_scales_in_kernel():
    """The in-kernel dequant path (has_scales): Pallas output must match the
    dequantize-then-matvec reference, incl. GQA and cache predication."""
    from deepspeed_tpu.models.decoding import SCALE_LANES, _quantize_kv
    from deepspeed_tpu.ops.pallas.decode_attention import decode_attention_kernel

    B, Smax, H, KV, hd = 2, 512, 4, 2, 64
    r = np.random.RandomState(1)
    q = jnp.asarray(r.randn(B, 1, H, hd), jnp.float32)
    k_raw = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32)
    v_raw = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32)
    kq, ks = _quantize_kv(k_raw)
    vq, vs = _quantize_kv(v_raw)
    assert kq.dtype == jnp.int8 and ks.shape == (B, Smax, KV, SCALE_LANES)

    for cache_len in (5, 130, 511):
        # the kernel consumes scales in the cache's storage layout
        # [B, KV, Smax, SL] (models/decoding.init_cache)
        out = decode_attention_kernel(
            q, kq, vq, jnp.asarray(cache_len),
            k_scale=jnp.swapaxes(ks, 1, 2), v_scale=jnp.swapaxes(vs, 1, 2),
        )
        kf = kq.astype(jnp.float32) * ks[..., :1]
        vf = vq.astype(jnp.float32) * vs[..., :1]
        kf = jnp.repeat(kf, H // KV, axis=2)
        vf = jnp.repeat(vf, H // KV, axis=2)
        logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(hd)
        kpos = jnp.arange(Smax)[None, None, None, :]
        logits = jnp.where(kpos <= cache_len, logits, -1e30)
        ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), vf)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_decode_attention_kernel_mixed_storage_dtype():
    """bf16 cache vs fp32 queries (kv_cache_dtype="bf16" on an fp32 engine):
    the kernel casts storage to the query dtype before the matmul."""
    from deepspeed_tpu.ops.pallas.decode_attention import decode_attention_kernel

    B, Smax, H, KV, hd = 1, 256, 2, 2, 32
    r = np.random.RandomState(2)
    q = jnp.asarray(r.randn(B, 1, H, hd), jnp.float32)
    kc = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32).astype(jnp.bfloat16)
    vc = jnp.asarray(r.randn(B, Smax, KV, hd), jnp.float32).astype(jnp.bfloat16)
    out = decode_attention_kernel(q, kc, vc, jnp.asarray(64))
    kf = kc.astype(jnp.float32)
    vf = vc.astype(jnp.float32)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, kf) / np.sqrt(hd)
    kpos = jnp.arange(Smax)[None, None, None, :]
    logits = jnp.where(kpos <= 64, logits, -1e30)
    ref = jnp.einsum("bhqk,bkhd->bqhd", jax.nn.softmax(logits, -1), vf)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=5e-3)


def test_speculative_decode_matches_plain_greedy():
    """Greedy speculative decoding is exact: with ANY draft model, the
    output must be token-for-token identical to plain greedy decoding of
    the main model (acceptance only keeps verifier-approved tokens)."""
    import deepspeed_tpu

    main = tiny_llama()
    draft = llama(
        "llama-tiny", vocab_size=main.config.vocab_size, max_seq_len=64,
        hidden_size=32, num_layers=1, num_heads=2, num_kv_heads=2,
        head_dim=16, intermediate_size=64,
    )
    plain = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                         max_tokens=64)
    spec = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                        max_tokens=64, draft_model=draft)
    prompt = np.random.RandomState(5).randint(0, main.config.vocab_size,
                                              size=(1, 8))
    want = plain.generate(prompt, max_new_tokens=20)
    for k in (1, 3, 6):
        got = spec.generate(prompt, max_new_tokens=20, num_draft_tokens=k)
        assert (got == want).all(), (k, got.tolist(), want.tolist())


def test_speculative_ngram_matches_plain_greedy():
    """The "ngram" self-draft (prompt-lookup decoding) needs no draft
    model at all; the acceptance rule still makes the output token-exact
    vs plain greedy, whatever the lookup proposes."""
    import deepspeed_tpu

    main = tiny_llama()
    plain = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                         max_tokens=64)
    spec = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                        max_tokens=64, draft_model="ngram")
    for seed in (5, 11):
        prompt = np.random.RandomState(seed).randint(
            0, main.config.vocab_size, size=(1, 8))
        want = plain.generate(prompt, max_new_tokens=20)
        for k in (1, 3, 6):
            got = spec.generate(prompt, max_new_tokens=20,
                                num_draft_tokens=k)
            assert (got == want).all(), (seed, k, got.tolist(), want.tolist())


def test_speculative_ngram_repetitive_prompt_accepts():
    """On a repetitive prompt the n-gram lookup should land real
    acceptances: the verifier round count must come in well under the
    one-round-per-token worst case."""
    import deepspeed_tpu

    main = tiny_llama()
    spec = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                        max_tokens=64, draft_model="ngram")
    plain = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                         max_tokens=64)
    # an untrained model decoded greedily settles into a cycle quickly;
    # the lookup finds it. Seeded prompt with a repeated motif helps the
    # first rounds along.
    prompt = np.tile(np.asarray([[7, 3, 9, 7, 3, 9, 7, 3]]), (1, 1))
    new = 24
    want = plain.generate(prompt, max_new_tokens=new)
    got = spec.generate(prompt, max_new_tokens=new, num_draft_tokens=5)
    assert (got == want).all()
    assert spec.last_spec_rounds < new - 1, spec.last_spec_rounds


def test_speculative_decode_eos_and_fallback():
    """eos inside an accepted window stops generation; sampled/batched
    requests fall back to the normal decode loop."""
    import deepspeed_tpu

    main = tiny_llama()
    draft = tiny_llama()
    spec = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                        max_tokens=64, draft_model=draft)
    plain = deepspeed_tpu.init_inference(main, dtype=jnp.float32,
                                         max_tokens=64)
    prompt = np.random.RandomState(6).randint(0, main.config.vocab_size,
                                              size=(1, 8))
    want = plain.generate(prompt, max_new_tokens=16, eos_token_id=3)
    got = spec.generate(prompt, max_new_tokens=16, eos_token_id=3,
                        num_draft_tokens=3)
    assert (got == want).all()

    # batched (B=2) silently takes the plain path and still works
    p2 = np.random.RandomState(7).randint(0, main.config.vocab_size,
                                          size=(2, 8))
    out = spec.generate(p2, max_new_tokens=4)
    assert out.shape == (2, 12)

    # vocab mismatch is rejected up front
    import pytest as _pytest

    bad = llama("llama-tiny", vocab_size=main.config.vocab_size * 2,
                max_seq_len=64, hidden_size=32, num_layers=1, num_heads=2,
                num_kv_heads=2, head_dim=16, intermediate_size=64)
    with _pytest.raises(ValueError):
        deepspeed_tpu.init_inference(main, draft_model=bad)


def test_speculative_full_acceptance_round_count():
    """With draft params == main params, every proposal is accepted: the
    verifier must run only ceil((new-1)/k) rounds. Catches the draft-cache
    hole regression (an unwritten row after a fully-accepting round would
    desync the draft and inflate the round count)."""
    import math

    import deepspeed_tpu

    main = tiny_llama()
    params = main.init(jax.random.PRNGKey(0))
    spec = deepspeed_tpu.init_inference(
        main, dtype=jnp.float32, max_tokens=64, params=params,
        draft_model=main, draft_params=params,
    )
    prompt = np.random.RandomState(8).randint(0, main.config.vocab_size,
                                              size=(1, 8))
    new = 24
    for nd in (2, 4):
        k = nd + 1
        out = spec.generate(prompt, max_new_tokens=new, num_draft_tokens=nd)
        assert out.shape == (1, 8 + new)
        assert spec.last_spec_rounds == math.ceil((new - 1) / k), (
            nd, spec.last_spec_rounds
        )


def test_packed_int8_storage_and_token_parity():
    """Single-device int8 serving stores PACKED weights (int8 qdata lives
    in the params tree — the HBM stream the decode loop reads) and decodes
    the same tokens as the fake-quant roundtrip (identical q/dq values by
    construction)."""
    from deepspeed_tpu.ops.quantizer import PackedWeight, quantize_dequantize

    model = tiny_llama(hidden_size=64, intermediate_size=128)
    topo = MeshTopology(devices=jax.devices()[:1])
    eng_q = init_inference(model, dtype=jnp.float32, quantize_bits=8,
                           rng=jax.random.PRNGKey(7), topology=topo,
                           max_tokens=24)
    packed = [
        leaf for leaf in jax.tree_util.tree_leaves(
            eng_q.params,
            is_leaf=lambda x: isinstance(x, PackedWeight))
        if isinstance(leaf, PackedWeight)
    ]
    assert packed, "no PackedWeight leaves — int8 storage is not packed"
    assert all(leaf.qdata.dtype == jnp.int8 for leaf in packed)

    # reference: same weights through the fake-quant roundtrip (the same
    # name rule _quantize_weights uses)
    big = {"wq", "wk", "wv", "wo", "wi", "wg"}

    def fake_q(path, leaf):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name in big and leaf.ndim >= 2:
            return quantize_dequantize(leaf, block=128, bits=8)
        return leaf

    ref_params = jax.tree_util.tree_map_with_path(
        fake_q, model.init(jax.random.PRNGKey(7), dtype=jnp.float32)
    )
    eng_ref = init_inference(model, dtype=jnp.float32, params=ref_params,
                             topology=topo, max_tokens=24)
    ids = np.random.RandomState(7).randint(0, 128, size=(1, 8))
    out_q = np.asarray(eng_q.generate(ids, max_new_tokens=8, temperature=0.0))
    out_r = np.asarray(eng_ref.generate(ids, max_new_tokens=8,
                                        temperature=0.0))
    np.testing.assert_array_equal(out_q, out_r)


@pytest.mark.parametrize("bits", [8, 4])
def test_tp_packed_quantized_serving(bits):
    """tp>1 + weight quantization stores PACKED shards (VERDICT r4 #4):
    each device's HBM holds int8 (or nibble-packed int4) qdata sharded
    along the weight's own TP spec — not a bf16 fake-quant stream — and
    decode matches the single-device packed engine token-for-token."""
    from deepspeed_tpu.ops.quantizer import PackedWeight

    model = tiny_llama(hidden_size=256, intermediate_size=256,
                       num_heads=4, num_kv_heads=4)
    topo = MeshTopology(dims=ParallelDims(tp=2))
    eng_tp = init_inference(model, dtype=jnp.float32, quantize_bits=bits,
                            rng=jax.random.PRNGKey(5), topology=topo,
                            max_tokens=16)
    packed = {
        jax.tree_util.keystr(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(
            eng_tp.params,
            is_leaf=lambda x: isinstance(x, PackedWeight))[0]
        if isinstance(leaf, PackedWeight)
    }
    assert packed, "tp=2 quantized serving fell back to fake-quant"
    assert all(pw.qdata.dtype == jnp.int8 for pw in packed.values())
    # the device buffers themselves are int8 shards: a tp-sharded qdata's
    # per-device shard is half the global array (these params are the jit
    # inputs, so this IS what streams from HBM during decode)
    def spec_names(spec):
        names = []
        for e in tuple(spec):
            if e is not None:
                names.extend(e if isinstance(e, tuple) else (e,))
        return names

    tp_sharded = [
        pw for pw in packed.values()
        if "tp" in spec_names(pw.qdata.sharding.spec)
    ]
    assert tp_sharded, "no qdata leaf is sharded over tp"
    for pw in tp_sharded:
        shard = pw.qdata.addressable_shards[0].data
        assert shard.dtype == jnp.int8
        assert shard.size == pw.qdata.size // 2
        # scales shard along with their blocks
        assert pw.scale.addressable_shards[0].data.size == pw.scale.size // 2
    if bits == 4:
        assert any(pw.nibbles for pw in packed.values()), (
            "int4 under tp lost nibble packing"
        )
    # token parity vs the single-device packed engine (same rng → same
    # q/dq values)
    eng_1 = init_inference(model, dtype=jnp.float32, quantize_bits=bits,
                           rng=jax.random.PRNGKey(5), max_tokens=16,
                           topology=MeshTopology(devices=jax.devices()[:1]))
    prompt = np.random.RandomState(5).randint(0, 128, size=(1, 6))
    out_tp = np.asarray(eng_tp.generate(prompt, max_new_tokens=6,
                                        temperature=0.0))
    out_1 = np.asarray(eng_1.generate(prompt, max_new_tokens=6,
                                      temperature=0.0))
    np.testing.assert_array_equal(out_tp, out_1)


def test_tp_packed_fallback_when_geometry_does_not_divide():
    """A weight whose quant-block geometry can't shard over the mesh
    (hidden 32 → one block per contraction dim, G=1 < tp) falls back to
    the fake-quant roundtrip instead of failing — and still serves."""
    from deepspeed_tpu.ops.quantizer import PackedWeight

    model = tiny_llama()  # hidden 32: row-parallel wo/wo-mlp have G=1
    topo = MeshTopology(dims=ParallelDims(tp=2))
    eng = init_inference(model, dtype=jnp.float32, quantize_bits=8,
                         rng=jax.random.PRNGKey(6), topology=topo,
                         max_tokens=16)
    leaves = jax.tree_util.tree_leaves(
        eng.params, is_leaf=lambda x: isinstance(x, PackedWeight))
    # row-parallel leaves (wo) must have fallen back; column-parallel ones
    # (wq: shards the last dim, blocks untouched) still pack
    assert any(isinstance(l, PackedWeight) for l in leaves)
    prompt = np.random.RandomState(6).randint(0, 128, size=(1, 5))
    out = eng.generate(prompt, max_new_tokens=4, temperature=0.0)
    assert out.shape == (1, 9)


@pytest.mark.parametrize("cols", [16, 15])
def test_int4_nibble_packing_roundtrip(cols):
    """int4 packed storage nibble-packs blocks g and g+G/2 per byte plane
    (half the int8 bytes, column and in-block row layout untouched — the
    split-half pairing keeps the Pallas unpack a block-dim concat) and
    dequantizes bit-identically to the unpacked quantizer."""
    from deepspeed_tpu.ops.quantizer import (
        dequantize_blockwise, pack_quantize_blockwise, quantize_blockwise,
    )

    w = jnp.asarray(np.random.RandomState(11).randn(32, cols), jnp.float32)
    pw = pack_quantize_blockwise(w, block=16, bits=4)
    ref = dequantize_blockwise(quantize_blockwise(w, block=16, bits=4),
                               jnp.float32)
    np.testing.assert_array_equal(np.asarray(pw.dequantize()),
                                  np.asarray(ref))
    # 2 blocks of 16 rows → one byte plane [1, 16, cols]
    assert pw.nibbles
    assert pw.qdata.shape[-3:] == (1, 16, cols)


def test_int4_odd_block_falls_back_to_bytewise():
    """An odd block COUNT can't pair split-halves: one int4 per byte."""
    from deepspeed_tpu.ops.quantizer import (
        dequantize_blockwise, pack_quantize_blockwise, quantize_blockwise,
    )

    w = jnp.asarray(np.random.RandomState(3).randn(15, 8), jnp.float32)
    pw = pack_quantize_blockwise(w, block=16, bits=4)  # 15 % 16 → block 15
    assert not pw.nibbles and pw.qdata.shape[-2] == 15
    ref = dequantize_blockwise(quantize_blockwise(w, block=16, bits=4),
                               jnp.float32)
    np.testing.assert_array_equal(np.asarray(pw.dequantize()),
                                  np.asarray(ref))


def test_moe_quantized_serving_runs():
    """MoE + weight quantization: expert banks [L, E, d, f] PACK since
    ISSUE 14 (the decode dispatch path consumes PackedWeight through the
    per-expert Pallas matvec / dequantize-once fallback) — serving runs
    end-to-end with the banks resident as int8 bytes."""
    from deepspeed_tpu.models import mixtral
    from deepspeed_tpu.ops.quantizer import PackedWeight

    model = mixtral("mixtral-tiny", vocab_size=128, max_seq_len=64,
                    hidden_size=64, num_layers=2, num_heads=4,
                    num_kv_heads=2, intermediate_size=128, num_experts=4,
                    moe_top_k=2)
    eng = init_inference(model, dtype=jnp.float32, quantize_bits=8,
                         rng=jax.random.PRNGKey(9), max_tokens=24,
                         topology=MeshTopology(devices=jax.devices()[:1]))
    leaves = jax.tree_util.tree_leaves(
        eng.params, is_leaf=lambda x: isinstance(x, PackedWeight))
    packed = [l for l in leaves if isinstance(l, PackedWeight)]
    assert packed  # attention projections pack
    assert any(len(pw.shape) == 4 for pw in packed)  # expert banks too
    prompt = np.random.RandomState(9).randint(0, 128, size=(1, 6))
    out = eng.generate(prompt, max_new_tokens=6, temperature=0.0)
    assert out.shape == (1, 12)
    assert (np.asarray(out) < 128).all()


def test_matvec_max_rows_scope_switches_kernel_path(monkeypatch):
    """inference.matvec_max_rows (ADVICE r5 #2 follow-up): a 10-row
    projection — the k=9 speculative verify window — takes the dequantize
    path at the default threshold (8) and the Pallas streaming matvec
    once the threshold covers it."""
    from deepspeed_tpu.ops.pallas import quantized_matmul as qm
    from deepspeed_tpu.ops.quantizer import pack_quantize_blockwise

    w = np.random.RandomState(0).randn(128, 128).astype(np.float32)
    packed = pack_quantize_blockwise(jnp.asarray(w), block=128, bits=8)
    x = jnp.asarray(np.random.RandomState(1).randn(10, 128), jnp.float32)

    calls = []
    real = qm._packed_matvec

    def spy(x2d, qdata, scale, **kw):
        calls.append(x2d.shape)
        return real(x2d, qdata, scale, **kw)

    monkeypatch.setattr(qm, "_packed_matvec", spy)

    y_deq = qm.packed_proj(x, packed)  # default threshold 8 < 10 rows
    assert calls == []
    with qm.matvec_max_rows_scope(16):
        assert qm.matvec_max_rows() == 16
        y_stream = qm.packed_proj(x, packed)
    assert calls == [(10, 128)]
    assert qm.matvec_max_rows() == qm._MATVEC_MAX_ROWS  # scope restored
    # same numerics either path (fp32 kernel pins HIGHEST dot precision)
    np.testing.assert_allclose(np.asarray(y_stream), np.asarray(y_deq),
                               rtol=2e-5, atol=2e-5)


def test_speculative_verify_window_streams_with_configured_threshold(
    monkeypatch,
):
    """CPU-path end-to-end: with inference.matvec_max_rows=16 the k=9
    speculative verify forward (10 rows) engages the streaming kernel at
    trace time; at the default threshold it never does. Tokens match the
    unconfigured engine either way."""
    import deepspeed_tpu
    from deepspeed_tpu.ops.pallas import quantized_matmul as qm

    model = tiny_llama(hidden_size=128, intermediate_size=256)
    prompt = np.random.RandomState(3).randint(
        0, model.config.vocab_size, size=(1, 8))

    rows_seen = []
    real = qm._packed_matvec

    def spy(x2d, qdata, scale, **kw):
        rows_seen.append(x2d.shape[0])
        return real(x2d, qdata, scale, **kw)

    monkeypatch.setattr(qm, "_packed_matvec", spy)

    def run(**engine_kw):
        rows_seen.clear()
        eng = deepspeed_tpu.init_inference(
            model, dtype=jnp.float32, quantize_bits=8, max_tokens=64,
            draft_model="ngram", rng=jax.random.PRNGKey(0), **engine_kw,
        )
        out = eng.generate(prompt, max_new_tokens=12, num_draft_tokens=9)
        return eng, np.asarray(out), list(rows_seen)

    base_eng, base_out, base_rows = run()
    assert base_eng.matvec_max_rows is None
    assert 10 not in base_rows  # default threshold 8: verify dequantizes
    cfg_eng, cfg_out, cfg_rows = run(config={"matvec_max_rows": 16})
    assert cfg_eng.matvec_max_rows == 16  # the "inference." config spelling
    assert 10 in cfg_rows  # the verify window streams now
    np.testing.assert_array_equal(base_out, cfg_out)
