"""shardlint: seeded-bug corpus (ISSUE 2 acceptance) + rule unit tests.

The corpus (tests/analysis_corpus/fixtures.py) reintroduces the repo's
historical hazard classes as traceable programs; every hazard must be
flagged by its rule and every clean twin must lint clean.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.analysis import lint_engine, lint_jaxpr
from deepspeed_tpu.analysis.rules.topology import check_permutation
from deepspeed_tpu.models import gpt2

from analysis_corpus import fixtures as fx

pytestmark = pytest.mark.shardlint


@pytest.mark.parametrize("build", fx.HAZARDS, ids=lambda f: f.__name__)
def test_corpus_hazard_is_flagged(build, devices8):
    closed, kw, rule = build()
    findings = lint_jaxpr(closed, source=build.__name__, **kw)
    assert any(f.rule == rule and f.severity == "error" for f in findings), (
        f"{build.__name__}: expected a {rule} finding, got "
        f"{[f.format() for f in findings]}"
    )


@pytest.mark.parametrize("build", fx.CLEAN_TWINS, ids=lambda f: f.__name__)
def test_corpus_clean_twin_passes(build, devices8):
    closed, kw, _rule = build()
    findings = lint_jaxpr(closed, source=build.__name__, **kw)
    assert findings == [], [f.format() for f in findings]


def test_rule_subset_selection(devices8):
    closed, kw, _ = fx.missing_psum_grads()
    assert lint_jaxpr(closed, only=["R3"], **kw) == []
    assert lint_jaxpr(closed, only=["R1"], **kw)


def test_check_permutation_catalog():
    # legal: full ring, pipeline neighbor chain, empty perm
    assert check_permutation([(0, 1), (1, 2), (2, 3), (3, 0)], 4) == []
    assert check_permutation([(0, 1), (1, 2), (2, 3)], 4) == []
    assert check_permutation([], 4) == []
    # illegal shapes, one problem class each
    assert check_permutation([(0, 5)], 4)          # out of range
    assert check_permutation([(0, 1), (0, 2)], 4)  # dup src
    assert check_permutation([(0, 1), (2, 1)], 4)  # dup dst
    assert check_permutation([(1, 1)], 4)          # self-loop
    assert check_permutation([(0, 1), (1, 0), (2, 3), (3, 2)], 4)  # 2 rings
    assert check_permutation([(0, 1), (1, 0)], 4)  # partial ring
    assert check_permutation([(0, 1), (1, 0), (2, 0)], 4)  # ring + stray


def test_read_after_donate_pjit(devices8):
    """R4(b): a value consumed after an inner jit donated it."""
    import warnings

    g = jax.jit(lambda a: a + 1.0, donate_argnums=0)

    def prog(x):
        y = g(x)
        return y + x * 2.0

    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed = jax.make_jaxpr(prog)(jnp.zeros(4))
    findings = lint_jaxpr(closed, source="pjit-donate")
    assert any(f.rule == "R4" for f in findings)


# ---------------------------------------------------------- engine linting
BASE_CFG = {
    "train_batch_size": 16,
    "train_micro_batch_size_per_gpu": 2,
    "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
    "bf16": {"enabled": True},
    "gradient_clipping": 1.0,
}


def _abstract_engine(cfg, model=None):
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=model or gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        config=dict(cfg),
        abstract_init=True,
    )
    return engine


@pytest.mark.parametrize("stage", [0, 3])
def test_engine_lint_clean_across_zero_stages(stage, devices8):
    engine = _abstract_engine(
        dict(BASE_CFG, zero_optimization={"stage": stage})
    )
    report = lint_engine(engine)
    assert report.ok and not report.findings, report.format()


def test_engine_lint_clean_bucketed_offload_double_buffer(devices8):
    engine = _abstract_engine(dict(
        BASE_CFG,
        zero_optimization={
            "stage": 3,
            "offload_optimizer": {"device": "cpu"},
            "offload_double_buffer": True,
        },
    ))
    assert engine._bucketed_opt is not None
    assert engine._bucketed_opt.double_buffer
    report = lint_engine(engine)
    assert report.ok and not report.findings, report.format()


def test_abstract_engine_never_materializes_and_refuses_to_step(devices8):
    engine = _abstract_engine(dict(BASE_CFG, zero_optimization={"stage": 3}))
    leaves = jax.tree_util.tree_leaves(engine.state.params)
    assert leaves and all(
        isinstance(leaf, jax.ShapeDtypeStruct) for leaf in leaves
    )
    assert all(leaf.sharding is not None for leaf in leaves)
    batch = {"input_ids": np.zeros((16, 16), np.int32)}
    with pytest.raises(RuntimeError, match="abstract_init"):
        engine.train_batch(batch=batch)
    with pytest.raises(RuntimeError, match="abstract_init"):
        engine.train_batch_chain(batch=batch, steps=2)
    engine.destroy()  # must not raise on ShapeDtypeStruct state


def test_engine_lint_flags_planted_out_sharding_drift(devices8):
    """The engine-level R2 audit: a step whose out_shardings disagree with
    the resting state shardings (the chain-carry drift class) is caught
    without tracing anything."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    engine = _abstract_engine(dict(BASE_CFG, zero_optimization={"stage": 3}))
    bad = jax.tree.map(
        lambda s: NamedSharding(s.mesh, P()),
        engine._state_shardings[0],
    )
    engine._state_shardings = (bad, *engine._state_shardings[1:])
    report = lint_engine(engine)
    assert any(f.rule == "R2" for f in report.findings), report.format()


def test_lint_speed_budget(devices8):
    """ISSUE 2 acceptance: full analysis of one engine config < 30 s on
    CPU — measured on the heaviest shipped leg (1.5B double-buffered
    offload)."""
    import time

    import bench

    name, model, cfg = bench.lint_targets(len(jax.devices()))[-1]
    assert name == "bench-1b-offload-db"
    comm.destroy_process_group()
    t0 = time.time()
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=cfg, abstract_init=True
    )
    report = lint_engine(engine, source=name)
    elapsed = time.time() - t0
    assert report.ok and not report.findings, report.format()
    assert elapsed < 30.0, f"lint took {elapsed:.1f}s (budget 30s)"
