"""Differential parity prover (ISSUE 15): form pairs certify statically,
seeded mutations diverge with the offending op named, and the engines
declare their pairs through the ``parity_pairs()`` protocol.

The heavy CLI subprocess legs are marked slow (the 1-core tier-1 box);
the in-process proofs are seconds.
"""

import copy
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import pytest

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.analysis import config_parity_pairs, prove_parity
from deepspeed_tpu.analysis.parity import (FormPair, extract_anchors,
                                           _serving_trace_thunk)
from deepspeed_tpu.models import gpt2, llama

pytestmark = pytest.mark.shardlint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def tiny_llama():
    return llama("llama-tiny", vocab_size=64, max_seq_len=64,
                 hidden_size=32, num_layers=2, num_heads=4,
                 num_kv_heads=2, intermediate_size=64)


SERVING_CFG = {
    "serving": {"enabled": True, "max_slots": 2, "token_budget": 4,
                "max_tokens": 16, "paged": True, "page_size": 8},
}


# ---------------------------------------------------------------- proving
def test_paged_vs_contiguous_certifies(devices8):
    pairs = config_parity_pairs(copy.deepcopy(SERVING_CFG), tiny_llama())
    assert [p.name for p in pairs] == ["serving/paged-vs-contiguous"]
    cert = prove_parity(pairs[0])
    assert cert.ok, cert.format()
    assert cert.anchors_a and cert.anchors_b
    assert cert.seconds < 5.0, "ISSUE 15 acceptance: <5s per pair"
    d = cert.to_dict()
    assert d["ok"] and d["pair"] == "serving/paged-vs-contiguous"
    assert d["divergences"] == []


def test_mutated_form_diverges_with_named_op(devices8):
    """Seeded divergence: silently enabling spec on one form changes the
    verify window's sampling/RNG anchors — the prover must name them,
    and reduction-bucket divergences must carry rule R10."""
    model = tiny_llama()
    pairs = config_parity_pairs(copy.deepcopy(SERVING_CFG), model)
    pair = pairs[0]
    mut = copy.deepcopy(SERVING_CFG)
    mut["serving"]["spec"] = {"enabled": True, "max_draft": 2}
    mut["serving"].pop("paged")
    mut["serving"].pop("page_size")
    pair.trace_b = _serving_trace_thunk(mut, model)
    cert = prove_parity(pair)
    assert not cert.ok
    first = cert.first_divergence
    assert first is not None and first.op
    ops = {d.op for d in cert.divergences}
    assert ops & {"random_bits", "random_split", "sort", "argmax",
                  "reduce_sum", "cumsum"}, ops
    # both provenances named (a path or an explicit absence)
    assert first.where_a and first.where_b
    # a reduce-bucket divergence is a reduction-order (R10) finding
    for d in cert.divergences:
        if d.kind in ("reduce", "collective", "accum"):
            assert d.rule == "R10", d.format()
        else:
            assert d.rule == "parity", d.format()


def test_missing_reduction_is_r10(devices8):
    """A pair whose form B drops a psum: the divergent bucket is a
    collective and must be labeled R10 (the reassociation half)."""
    def with_psum(x):
        return jax.lax.psum(jnp.tanh(x).sum(axis=0, keepdims=True), "dp")

    def without(x):
        return jnp.tanh(x).sum(axis=0, keepdims=True)

    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np
    from deepspeed_tpu.utils.jax_compat import shard_map

    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("dp", "tp"))
    x = jax.ShapeDtypeStruct((8, 4), jnp.float32)
    fa = shard_map(with_psum, mesh=mesh, in_specs=P("dp"), out_specs=P(),
                   axis_names={"dp", "tp"}, check_vma=False)
    fb = shard_map(without, mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
                   axis_names={"dp", "tp"}, check_vma=False)
    pair = FormPair(
        name="unit/psum-dropped", contract="unit", form_a="a", form_b="b",
        trace_a=lambda: jax.make_jaxpr(fa)(x),
        trace_b=lambda: jax.make_jaxpr(fb)(x),
    )
    cert = prove_parity(pair)
    assert not cert.ok
    assert any(d.kind == "collective" and d.rule == "R10"
               for d in cert.divergences), cert.format()


def test_chunking_fold_unifies_split_dots(devices8):
    """Two half-width dots == one full dot under the chunking rewrite
    (mass-exact), and WITHOUT the rewrite they diverge."""
    def chunked(x, w):
        h1 = x @ w[:, :8]
        h2 = x @ w[:, 8:]
        return jnp.concatenate([h1, h2], axis=1).sum()

    def whole(x, w):
        return (x @ w).sum()

    x = jax.ShapeDtypeStruct((4, 16), jnp.float32)
    w = jax.ShapeDtypeStruct((16, 16), jnp.float32)

    def mk(rewrites):
        return FormPair(
            name="unit/chunked-dot", contract="unit", form_a="chunked",
            form_b="whole",
            trace_a=lambda: jax.make_jaxpr(chunked)(x, w),
            trace_b=lambda: jax.make_jaxpr(whole)(x, w),
            rewrites=frozenset(rewrites),
        )

    assert prove_parity(mk({"chunking"})).ok
    strict = prove_parity(mk(set()))
    assert not strict.ok
    assert strict.first_divergence.op == "dot_general"


def test_dim_alias_unifies_form_specific_extents(devices8):
    """The paged view extent vs the contiguous capacity are the same
    logical extent: aliasing both to one symbol matches the attention
    dots without smearing over unrelated dims that happen to match."""
    def attn(q, k):
        return jnp.einsum("bd,btd->bt", q, k).sum()

    q = jax.ShapeDtypeStruct((2, 8), jnp.float32)
    ka = jax.ShapeDtypeStruct((2, 24, 8), jnp.float32)
    kb = jax.ShapeDtypeStruct((2, 32, 8), jnp.float32)
    pair = FormPair(
        name="unit/aliased-extent", contract="unit", form_a="a",
        form_b="b",
        trace_a=lambda: jax.make_jaxpr(attn)(q, ka),
        trace_b=lambda: jax.make_jaxpr(attn)(q, kb),
        dim_aliases_a={24: "KV_EXT"},
        dim_aliases_b={32: "KV_EXT"},
    )
    assert prove_parity(pair).ok
    bare = FormPair(
        name="unit/unaliased", contract="unit", form_a="a", form_b="b",
        trace_a=lambda: jax.make_jaxpr(attn)(q, ka),
        trace_b=lambda: jax.make_jaxpr(attn)(q, kb),
    )
    assert not prove_parity(bare).ok


def test_extract_anchors_elides_layout_keeps_compute(devices8):
    def prog(x, w):
        h = jnp.transpose(x) @ w
        return jax.nn.softmax(h.reshape(-1, 4), axis=-1)

    closed = jax.make_jaxpr(prog)(
        jax.ShapeDtypeStruct((16, 8), jnp.float32),
        jax.ShapeDtypeStruct((16, 4), jnp.float32),
    )
    anchors = extract_anchors(closed, frozenset())
    ops = [a.op for a in anchors]
    assert "dot_general" in ops
    assert "transpose" not in ops and "reshape" not in ops


# --------------------------------------------------------------- protocol
def test_tpu_engine_declares_parity_pairs(devices8):
    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
        config={
            "train_batch_size": 16,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "bf16": {"enabled": True},
            "tensor_parallel": {"tp_size": 2, "overlap_comm": True},
            "zero_optimization": {"stage": 1, "grad_wire": "int8"},
        },
        abstract_init=True,
    )
    try:
        names = [p.name for p in engine.parity_pairs()]
    finally:
        engine.destroy()
    assert "train/tp-ring-vs-xla" in names
    assert "train/wire-codec-vs-full-width" in names


def test_serving_engine_declares_parity_pairs(devices8):
    comm.destroy_process_group()
    eng = deepspeed_tpu.init_inference(
        tiny_llama(), dtype=jnp.float32, max_tokens=16,
        rng=jax.random.PRNGKey(0),
    )
    from deepspeed_tpu.serving import ServingEngine

    srv = ServingEngine(engine=eng, serving=dict(SERVING_CFG["serving"],
                                                 enabled=True))
    pairs = srv.parity_pairs()
    assert [p.name for p in pairs] == ["serving/paged-vs-contiguous"]
    cert = prove_parity(pairs[0])
    assert cert.ok, cert.format()


# -------------------------------------------------------------------- CLI
@pytest.mark.slow
def test_cli_all_pairs_certifies(tmp_path, devices8):
    out = tmp_path / "parity.json"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "paritycheck.py"),
         "--all-pairs", "--json", str(out)],
        capture_output=True, text=True, timeout=540, cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json

    payload = json.loads(out.read_text())
    assert payload["ok"] and payload["pairs"]
    names = {p["pair"] for p in payload["pairs"]}
    assert "serving/paged-vs-contiguous" in names
    assert "train/tp-ring-vs-xla" in names
    assert "train/wire-codec-vs-full-width" in names
    for p in payload["pairs"]:
        assert p["seconds"] < 5.0, p  # ISSUE 15 acceptance


@pytest.mark.slow
def test_cli_seeded_divergence_exits_1(devices8):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "paritycheck.py"),
         "--mutate", os.path.join(REPO, "examples",
                                  "ds_config_serving.json")],
        capture_output=True, text=True, timeout=300, cwd=REPO,
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "DIVERGENT" in proc.stdout
    # the prover names the divergent sampling/rng ops
    assert any(op in proc.stdout for op in
               ("random_bits", "sort", "argmax")), proc.stdout
