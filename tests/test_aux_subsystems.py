"""Launcher, elasticity, curriculum, random-LTD, PLD (SURVEY §2.7)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.data_pipeline.curriculum_scheduler import CurriculumScheduler
from deepspeed_tpu.data_pipeline.random_ltd import (
    RandomLTDScheduler,
    gather_tokens,
    random_ltd_layer,
    sample_token_subset,
    scatter_tokens,
)
from deepspeed_tpu.elasticity import compute_elastic_config, get_compatible_gpus
from deepspeed_tpu.launcher.runner import (
    build_launch_env,
    build_ssh_command,
    main as launcher_main,
    parse_hostfile,
    parse_inclusion_exclusion,
)
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.progressive_layer_drop import (
    ProgressiveLayerDrop,
    layer_keep_probs,
)


# ---------------------------------------------------------------- launcher
def test_parse_hostfile():
    text = """
    # my cluster
    host1 slots=4
    host2 slots=8
    host3
    """
    res = parse_hostfile(text, is_text=True)
    assert res == {"host1": 4, "host2": 8, "host3": 1}
    with pytest.raises(ValueError):
        parse_hostfile("h slots=1\nh slots=2", is_text=True)


def test_include_exclude():
    res = {"a": 4, "b": 4, "c": 4}
    assert list(parse_inclusion_exclusion(res, include_str="a@c")) == ["a", "c"]
    assert list(parse_inclusion_exclusion(res, exclude_str="b")) == ["a", "c"]
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(res, include_str="zzz")
    with pytest.raises(ValueError):
        parse_inclusion_exclusion(res, exclude_str="a@b@c")


def test_launch_env_and_ssh_command():
    env = build_launch_env("host1", 29500, 4, 2, base_env={"PYTHONPATH": "/x"})
    assert env["DSTPU_COORDINATOR"] == "host1:29500"
    assert env["DSTPU_PROCESS_ID"] == "2"
    cmd = build_ssh_command("host2", env, ["python", "train.py"])
    assert cmd[0] == "ssh" and "host2" in cmd
    assert "DSTPU_COORDINATOR=host1:29500" in cmd[-1]
    assert "python train.py" in cmd[-1]


def test_launcher_dry_run(tmp_path, capsys):
    hf = tmp_path / "hosts"
    hf.write_text("h1 slots=4\nh2 slots=4\n")
    rc = launcher_main(
        ["--hostfile", str(hf), "--dry_run", "train.py", "--flag"]
    )
    assert rc == 0
    out = capsys.readouterr().out
    assert "[h1 rank 0]" in out and "[h2 rank 1]" in out


# --------------------------------------------------------------- elasticity
def test_get_compatible_gpus():
    gpus, batch = get_compatible_gpus(
        micro_batches=[2, 4], max_train_batch_size=64, min_gpus=1, max_gpus=16
    )
    assert batch <= 64
    for g in gpus:
        assert any(batch % (mb * g) == 0 for mb in [2, 4])


def test_compute_elastic_config():
    ds = {
        "elasticity": {
            "enabled": True,
            "max_train_batch_size": 100,
            "micro_batch_sizes": [2, 4],
            "min_gpus": 1,
            "max_gpus": 8,
        }
    }
    batch, valid, micro = compute_elastic_config(ds, world_size=4)
    assert 4 in valid and batch % (micro * 4) == 0 and micro in (2, 4)
    with pytest.raises(ValueError):
        compute_elastic_config({"elasticity": {"enabled": False}})


# --------------------------------------------------------------- curriculum
def test_curriculum_schedules():
    cs = CurriculumScheduler(
        {
            "curriculum_type": "seqlen",
            "min_difficulty": 8,
            "max_difficulty": 64,
            "schedule_type": "fixed_linear",
            "schedule_config": {"total_curriculum_step": 100, "difficulty_step": 8},
        }
    )
    assert cs.get_difficulty(0) == 8
    assert cs.get_difficulty(100) == 64
    mid = cs.get_difficulty(50)
    assert 8 <= mid <= 64 and mid % 8 == 0

    disc = CurriculumScheduler(
        {
            "curriculum_type": "seqlen",
            "min_difficulty": 8,
            "max_difficulty": 64,
            "schedule_type": "fixed_discrete",
            "schedule_config": {"difficulty": [8, 32, 64], "max_step": [10, 20, 30]},
        }
    )
    assert disc.get_difficulty(5) == 8
    assert disc.get_difficulty(15) == 32
    assert disc.get_difficulty(999) == 64


def test_curriculum_engine_truncates_seq():
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=64, max_seq_len=32, hidden_size=32,
                   num_layers=2, num_heads=2),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "data_efficiency": {
                "enabled": True,
                "data_sampling": {
                    "curriculum_learning": {
                        "enabled": True,
                        "curriculum_type": "seqlen",
                        "min_difficulty": 8,
                        "max_difficulty": 32,
                        "schedule_type": "fixed_linear",
                        "schedule_config": {"total_curriculum_step": 4,
                                            "difficulty_step": 8},
                    }
                },
            },
            "steps_per_print": 100,
        },
        topology=MeshTopology(dims=ParallelDims(dp=8)),
    )
    assert engine.curriculum is not None
    r = np.random.RandomState(0)
    for _ in range(5):
        loss = engine.train_batch(batch={"input_ids": r.randint(0, 64, size=(8, 32))})
        assert np.isfinite(float(loss))
    assert engine.curriculum.current_difficulty == 32


# --------------------------------------------------------------- random-LTD
def test_gather_scatter_roundtrip():
    r = np.random.RandomState(0)
    x = jnp.asarray(r.randn(2, 16, 4), jnp.float32)
    idx = sample_token_subset(jax.random.PRNGKey(0), 2, 16, 8)
    assert idx.shape == (2, 8)
    # sorted, unique
    assert all(np.all(np.diff(np.asarray(idx)[b]) > 0) for b in range(2))
    kept = gather_tokens(x, idx)
    back = scatter_tokens(x, kept, idx)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(x))


def test_random_ltd_layer_identity_for_dropped():
    r = np.random.RandomState(1)
    x = jnp.asarray(r.randn(2, 16, 4), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(16), (2, 16))
    out = random_ltd_layer(lambda xx, pp: xx * 2.0, x, pos, keep=8,
                           rng=jax.random.PRNGKey(1))
    doubled = np.isclose(np.asarray(out), 2 * np.asarray(x)).all(-1)
    same = np.isclose(np.asarray(out), np.asarray(x)).all(-1)
    assert doubled.sum() == 2 * 8  # exactly keep tokens processed per row
    assert (doubled | same).all()


def test_random_ltd_scheduler():
    class C:
        random_ltd_schedule = {"min_value": 64, "max_value": 512,
                               "total_layer_drop_step": 100, "seq_step": 64}
        total_layer_num = 12
        random_ltd_layer_id = [1, 2, 3]

    s = RandomLTDScheduler(C())
    assert s.get_seq_len(0) == 64
    assert s.get_seq_len(100) == 512
    assert s.get_seq_len(50) % 64 == 0


# ---------------------------------------------------------------------- PLD
def test_pld_theta_schedule():
    pld = ProgressiveLayerDrop(theta=0.5, gamma=0.01)
    assert float(pld.get_theta(0)) == pytest.approx(1.0)
    assert float(pld.get_theta(10_000)) == pytest.approx(0.5, abs=1e-3)
    probs = layer_keep_probs(jnp.asarray(0.5), 4)
    np.testing.assert_allclose(np.asarray(probs), [1.0, 0.875, 0.75, 0.625])


def test_pld_engine_trains():
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16, hidden_size=32,
                   num_layers=4, num_heads=2),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "progressive_layer_drop": {"enabled": True, "theta": 0.5,
                                       "gamma": 0.01},
            "steps_per_print": 100,
        },
        topology=MeshTopology(dims=ParallelDims(dp=8)),
    )
    assert engine.pld is not None
    r = np.random.RandomState(0)
    for _ in range(3):
        loss = engine.train_batch(batch={"input_ids": r.randint(0, 64, size=(8, 16))})
        assert np.isfinite(float(loss))


def test_wall_clock_breakdown_times_steps(devices8, caplog):
    """wall_clock_breakdown=True populates the engine's timer registry and
    logs a breakdown line at steps_per_print (r3: flag was parsed, unused)."""
    import deepspeed_tpu
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.models import gpt2

    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=gpt2("gpt2-tiny", vocab_size=64, max_seq_len=16),
        config={
            "train_batch_size": 8,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "wall_clock_breakdown": True,
            "steps_per_print": 2,
        },
    )
    data = {"input_ids": np.random.RandomState(0).randint(0, 64, size=(8, 16))}
    for _ in range(3):  # step 2 logs + resets; step 3 leaves counts visible
        engine.train_batch(batch=data)
    names = set(engine.timers.timers)
    assert {"batch_prep", "step_dispatch", "step_device"} <= names
    assert engine.timers("step_device").count >= 1


def test_launcher_failure_propagation():
    """One dead rank must take the job down (reference pdsh-runner job
    control): the launcher terminates surviving hosts instead of hanging."""
    import subprocess
    import sys
    import time

    from deepspeed_tpu.launcher.runner import wait_and_propagate

    t0 = time.monotonic()
    procs = [
        subprocess.Popen([sys.executable, "-c", "import time; time.sleep(60)"]),
        subprocess.Popen([sys.executable, "-c", "raise SystemExit(3)"]),
    ]
    rc = wait_and_propagate(procs, poll_s=0.1)
    assert rc == 3
    assert all(p.poll() is not None for p in procs)
    assert time.monotonic() - t0 < 30  # did not wait for the sleeper


def test_launcher_all_success():
    import subprocess
    import sys

    from deepspeed_tpu.launcher.runner import wait_and_propagate

    procs = [
        subprocess.Popen([sys.executable, "-c", "pass"]) for _ in range(2)
    ]
    assert wait_and_propagate(procs, poll_s=0.05) == 0


def test_zero_memory_estimators():
    """ZeRO stage memory math (reference: estimate_zero{2,3}_..._mem_needs):
    sharding divides exactly the states each stage shards."""
    from deepspeed_tpu.utils import (
        estimate_zero2_model_states_mem_needs,
        estimate_zero3_model_states_mem_needs,
        estimate_zero_model_states_mem_needs,
    )

    n, dp = 1_000_000, 8
    s0 = estimate_zero_model_states_mem_needs(n, stage=0, data_shards=dp)
    s1 = estimate_zero_model_states_mem_needs(n, stage=1, data_shards=dp)
    s2 = estimate_zero2_model_states_mem_needs(n, dp)
    s3 = estimate_zero3_model_states_mem_needs(n, dp)
    # stage 0: 2 + 4 + 12 bytes/param all resident
    assert s0["device_bytes"] == n * 18
    # stage 1 shards the 12B optimizer states
    assert s1["device_bytes"] == n * (2 + 4 + 12 / dp)
    # stage 2 also shards fp32 grads
    assert s2["device_bytes"] == n * (2 + 4 / dp + 12 / dp)
    # stage 3 shards everything
    assert abs(s3["device_bytes"] - n * 18 / dp) < 1
    # offload moves the sharded states to host
    s3o = estimate_zero3_model_states_mem_needs(
        n, dp, offload_optimizer=True, offload_params=True
    )
    assert s3o["host_bytes"] == s3o["host_gb"] * (1 << 30)
    assert s3o["device_bytes"] == n * 4 / dp  # only sharded grads stay


def test_see_memory_usage_runs():
    from deepspeed_tpu.utils import see_memory_usage

    out = see_memory_usage("unit-test", force=True)
    assert "bytes_in_use" in out and "host_rss" in out
    assert see_memory_usage("skipped", force=False) == {}


def test_memory_breakdown_config_wired(devices8, monkeypatch):
    """ds_config memory_breakdown must actually report (r1 advisor bug
    class: config parses then silently ignored)."""
    import deepspeed_tpu
    import deepspeed_tpu.utils.memory as mem
    from deepspeed_tpu.models import gpt2

    calls = []
    monkeypatch.setattr(
        mem, "see_memory_usage",
        lambda msg="", force=True: calls.append(msg) or {},
    )
    model = gpt2("gpt2-tiny", vocab_size=128, max_seq_len=32, hidden_size=32,
                 num_layers=1, num_heads=2, intermediate_size=64)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8, "steps_per_print": 1,
                "memory_breakdown": True,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}},
    )
    assert any("init" in c for c in calls)
    engine.train_batch(
        batch={"input_ids": np.random.RandomState(0).randint(0, 128, size=(8, 32))}
    )
    assert any(c.startswith("step") for c in calls)


def test_checkpointing_user_api():
    """deepspeed.checkpointing parity: configure() + checkpoint(fn, *args)
    runs fn under the selected remat policy with identical values/grads."""
    import deepspeed_tpu
    from deepspeed_tpu import checkpointing

    w = jnp.asarray(np.random.RandomState(0).randn(16, 16).astype(np.float32))
    x = jnp.asarray(np.random.RandomState(1).randn(4, 16).astype(np.float32))

    def f(w, x):
        return jnp.sum(jnp.tanh(x @ w) ** 2)

    try:
        checkpointing.configure(policy="dots_saveable")
        val = checkpointing.checkpoint(f, w, x)
        np.testing.assert_allclose(float(val), float(f(w, x)), rtol=1e-6)
        g1 = jax.grad(lambda w: checkpointing.checkpoint(f, w, x))(w)
        g2 = jax.grad(lambda w: f(w, x))(w)
        # remat replays the saved-dots policy in the backward, so the grad
        # is FP-reassociated vs the plain path — atol floors the near-zero
        # elements whose relative error is meaningless
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-5, atol=1e-6)

        # ds_config + checkpoint_in_cpu routing
        checkpointing.configure(
            deepspeed_config={"train_batch_size": 8,
                              "activation_checkpointing": {"policy": "attn_mlp"}}
        )
        assert checkpointing._config["policy"] == "attn_mlp"
        # section default "none" must not make checkpoint() an identity
        checkpointing.configure(
            deepspeed_config={"train_batch_size": 8,
                              "activation_checkpointing": {}}
        )
        assert checkpointing._config["policy"] == "full"
        # reference-style cpu_checkpointing key routes to offload_host
        checkpointing.configure(
            deepspeed_config={
                "train_batch_size": 8,
                "activation_checkpointing": {"cpu_checkpointing": True},
            }
        )
        assert checkpointing._config["policy"] in ("offload_host", "full")
        checkpointing.configure(checkpoint_in_cpu=True)
        assert checkpointing._config["policy"] in ("offload_host", "full")
        import pytest as _pytest

        with _pytest.raises(KeyError):
            checkpointing.configure(policy="not-a-policy")
        # rng tracker stubs exist (Megatron-style call sites)
        with checkpointing.get_cuda_rng_tracker().fork():
            pass
        assert checkpointing.is_configured()
    finally:
        checkpointing.reset()
    assert not checkpointing.is_configured()


def test_throughput_timer_wired_into_engine(devices8, monkeypatch):
    """The engine tracks samples/sec and surfaces it in the step log
    (reference: ThroughputTimer in the step loop)."""
    import deepspeed_tpu
    import deepspeed_tpu.runtime.engine as eng_mod
    from deepspeed_tpu.models import gpt2

    lines = []
    monkeypatch.setattr(
        eng_mod, "log_dist", lambda msg, *a, **k: lines.append(msg)
    )
    model = gpt2("gpt2-tiny", vocab_size=128, max_seq_len=32, hidden_size=32,
                 num_layers=1, num_heads=2, intermediate_size=64)
    engine, *_ = deepspeed_tpu.initialize(
        model=model,
        config={"train_batch_size": 8, "steps_per_print": 3,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}}},
    )
    batch = {"input_ids": np.random.RandomState(0).randint(0, 128, size=(8, 32))}
    for _ in range(6):
        engine.train_batch(batch=batch)
    assert engine.tput.step_count == 6
    assert engine.tput.avg_samples_per_sec > 0
    assert any("samples/sec=" in m for m in lines)  # step-6 log line


def test_get_accelerator_surface():
    """deepspeed.accelerator parity: device identity, memory stats,
    synchronize, functional rng seeding."""
    import jax

    from deepspeed_tpu import get_accelerator

    acc = get_accelerator()
    assert acc is get_accelerator()  # singleton
    assert acc.is_available() and acc.device_count() >= 1
    assert acc.device_name().lower() in ("cpu", "tpu", "axon")
    assert acc.device_name(0).endswith(":0")
    assert acc.communication_backend_name() == "xla"
    # memory stats are ints (0 on backends without allocator stats)
    assert isinstance(acc.memory_allocated(), int)
    assert acc.available_memory() >= 0
    acc.synchronize()  # must not raise
    key = acc.manual_seed(7)
    assert (jax.random.key_data(key) == jax.random.key_data(
        jax.random.PRNGKey(7))).all()
    x = jax.numpy.ones((2,))
    assert acc.on_accelerator(x) and not acc.on_accelerator([1, 2])
    assert acc.is_bf16_supported()


def test_accelerator_bad_index_raises():
    from deepspeed_tpu import get_accelerator

    acc = get_accelerator()
    with pytest.raises(ValueError, match="out of range"):
        acc.memory_allocated(acc.device_count() + 3)
    with pytest.raises(ValueError, match="out of range"):
        acc.synchronize(-1)
