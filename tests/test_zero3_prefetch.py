"""ZeRO-3 one-layer-ahead parameter prefetch (ISSUE 10): the rotating
two-slot gathered-params carry reproduces plain stage 3 (loss BITWISE —
same math, same layer order; the gather is a value-identity device_put),
plus the scope/fallback machinery, the analytic stream, and the config
surface.

Kept inside the tier-1 budget: one tiny llama, short step counts, one
engine pair.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import deepspeed_tpu
import deepspeed_tpu.comm as comm
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.config import DeepSpeedConfig
from deepspeed_tpu.models import llama
from deepspeed_tpu.runtime.zero import prefetch as zp

pytestmark = pytest.mark.zero3_prefetch


def tiny_llama(**kw):
    d = dict(vocab_size=256, max_seq_len=32, hidden_size=64, num_layers=4,
             num_heads=4, num_kv_heads=2, intermediate_size=176)
    d.update(kw)
    return llama("llama-tiny", **d)


def _engine(prefetch, **over):
    comm.destroy_process_group()
    cfg = {
        "train_batch_size": 8,
        "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        "zero_optimization": {
            "stage": 3,
            "stage3_param_persistence_threshold": 1,
            "stage3_layer_prefetch": prefetch,
        },
        "steps_per_print": 1000,
    }
    cfg.update(over)
    eng, *_ = deepspeed_tpu.initialize(model=tiny_llama(), config=cfg)
    return eng


DATA = {"input_ids": np.random.RandomState(0).randint(0, 256, size=(8, 32))}


# ------------------------------------------------------------------ oracle
def test_loss_parity_bitwise_vs_plain_stage3(devices8):
    """The acceptance oracle: prefetch-on losses equal plain stage 3
    EXACTLY while the two programs run from identical state, and the
    trajectories stay within gradient-reduction noise after — the put is
    value-identity, only the gather/scatter *scheduling* differs (the
    psum-vs-reduce-scatter reassociation in the weight-grad reduction is
    the one ulp source, and it needs two steps to surface through adam)."""
    def run(prefetch):
        eng = _engine(prefetch)
        losses = [float(eng.train_batch(batch=DATA)) for _ in range(4)]
        step1 = None
        params = jax.tree.map(np.asarray, eng.state.params)
        stream = eng.analytic_streams().get("zero3_prefetch")
        puts = eng._z3_prefetch_puts
        eng.destroy()
        return losses, params, stream, puts

    l_off, p_off, s_off, puts_off = run(False)
    l_on, p_on, s_on, puts_on = run(True)
    # first two losses are computed from bitwise-identical params
    assert l_off[:2] == l_on[:2]
    np.testing.assert_allclose(l_off, l_on, rtol=1e-6, atol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(p_off),
                    jax.tree_util.tree_leaves(p_on)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)
    assert puts_off is None and puts_on is not None
    assert s_off is None
    assert s_on["overlapped"] and s_on["kind"] == "ici"
    assert s_on["bytes_per_step"] > 0 and s_on["slots"] == 2


def test_scan_layers_matches_plain_scan_bitwise(devices8):
    """Unit oracle for the rotating carry itself: scan_layers over a toy
    body == lax.scan, bitwise, with the per-layer xs threading through."""
    topo = MeshTopology(dims=ParallelDims(dp=8))
    L, d = 5, 16
    layers = {"w": jnp.asarray(
        np.random.RandomState(0).randn(L, d), jnp.float32)}
    keys = jnp.arange(L, dtype=jnp.float32)
    x0 = jnp.ones((d,), jnp.float32)

    def body(carry, inp):
        # elementwise only: fusion differences cannot reassociate a
        # reduction, so any carry-mechanics bug (wrong layer order, a
        # stale slot, dropped xs) shows up as a hard value change
        layer, k = inp
        out = jnp.tanh(layer["w"] * carry) + k * 1e-3
        return out, jnp.sum(out)

    # jit both sides: an eager op-by-op run compiles each op separately
    # and can differ in ulps from the fused program for reasons that have
    # nothing to do with the carry structure under test
    plain, ys_plain = jax.jit(
        lambda l, k, x: jax.lax.scan(body, x, (l, k))
    )(layers, keys, x0)
    puts = {"w": jax.sharding.NamedSharding(topo.mesh, P())}
    pf, ys_pf = jax.jit(
        lambda l, k, x: zp.scan_layers(body, x, l, (k,), puts)
    )(layers, keys, x0)
    np.testing.assert_array_equal(np.asarray(pf), np.asarray(plain))
    np.testing.assert_array_equal(np.asarray(ys_pf), np.asarray(ys_plain))


def test_prefetch_with_remat_and_accum(devices8):
    """The gathered-slot carry composes with activation checkpointing and
    the grad-accumulation scan (the bench shape): finite losses, stream
    passes reflect the remat re-gather."""
    eng = _engine(
        True,
        train_batch_size=16,
        train_micro_batch_size_per_gpu=1,
        gradient_accumulation_steps=2,
        activation_checkpointing={"policy": "attn_mlp"},
    )
    data = {"input_ids":
            np.random.RandomState(1).randint(0, 256, size=(16, 32))}
    losses = [float(eng.train_batch(batch=data)) for _ in range(2)]
    s = eng.analytic_streams()["zero3_prefetch"]
    eng.destroy()
    assert all(np.isfinite(losses))
    assert s["passes"] == 3  # fwd + bwd + remat re-gather
    assert s["bytes_per_step"] % 2 == 0


# ------------------------------------------------------- scope / fallbacks
def test_knob_ignored_off_stage3_and_without_sharded_layers(devices8):
    """stage != 3 or a mesh where every stacked leaf stays replicated
    leaves the knob off (logged, no scope, no stream)."""
    eng = _engine(True, zero_optimization={
        "stage": 1, "stage3_layer_prefetch": True,
    })
    assert eng._z3_prefetch_puts is None
    assert "zero3_prefetch" not in eng.analytic_streams()
    eng.destroy()
    # persistence threshold above every leaf: nothing is data-sharded
    eng2 = _engine(True, zero_optimization={
        "stage": 3, "stage3_layer_prefetch": True,
        "stage3_param_persistence_threshold": 10**9,
    })
    assert eng2._z3_prefetch_puts is None
    eng2.destroy()


def test_build_layer_puts_and_wire_accounting(devices8):
    """build_layer_puts derives gathered (tp-only) layouts and the byte
    model prices exactly the data-sharded leaves at (n-1)/n."""
    topo = MeshTopology(dims=ParallelDims(dp=8))
    shapes = {
        "layers": {
            "w": jax.ShapeDtypeStruct((4, 64, 64), jnp.float32),
            "tiny": jax.ShapeDtypeStruct((4, 8), jnp.float32),
        },
        "embed": jax.ShapeDtypeStruct((256, 64), jnp.float32),
    }
    tp_specs = {"layers": {"w": P(None, None, None), "tiny": P(None, None)},
                "embed": P(None, None)}
    # stage-3 adds dp on the largest divisible dim of w; tiny persists
    p_specs = {"layers": {"w": P(None, "dp", None), "tiny": P(None, None)},
               "embed": P("dp", None)}
    puts = zp.build_layer_puts(shapes, tp_specs, p_specs, topo)
    assert puts is not None
    assert puts["w"].spec == P(None, None) and puts["tiny"].spec == P(None)
    s = zp.prefetch_wire_bytes_per_step(
        shapes, tp_specs, p_specs, topo, itemsize=4, remat=False
    )
    per_pass = 4 * 64 * 64 * 4 * (8 - 1) / 8  # only w streams
    assert s["fwd_bytes_per_step"] == int(per_pass)
    assert s["bytes_per_step"] == int(per_pass) * 2 and s["passes"] == 2
    # nothing sharded -> None (the engine logs and ignores the knob)
    assert zp.build_layer_puts(shapes, tp_specs, tp_specs, topo) is None
    assert zp.prefetch_wire_bytes_per_step(
        shapes, tp_specs, tp_specs, topo) is None


def test_config_alias_and_surface():
    cfg = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "zero3_prefetch": True},
    })
    assert cfg.zero_config.stage3_layer_prefetch
    cfg2 = DeepSpeedConfig({
        "train_batch_size": 8,
        "zero_optimization": {"stage": 3, "stage3_layer_prefetch": True},
    })
    assert cfg2.zero_config.stage3_layer_prefetch
    assert not DeepSpeedConfig(
        {"train_batch_size": 8, "zero_optimization": {"stage": 3}}
    ).zero_config.stage3_layer_prefetch
