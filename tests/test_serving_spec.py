"""Speculative decoding inside the slot serving engine (ISSUE 9).

The oracle: spec-on reproduces spec-off TOKEN-FOR-TOKEN — greedy and
sampled-with-shared-keys, contiguous and paged arenas, tp=2 and int8-KV —
because acceptance is sample-and-match against each slot's own
deterministic RNG chain (serving/spec.py). Drafts only change how many
verifier steps a generation needs, never its content. Plus: the
scheduler's k+1 budget-row accounting under a fake clock (k shrinks to 0
under pressure — plain decode is the graceful floor), paged-pool
refcount balance across rejection rollback and eviction, the shared
n-gram draft unit, spec metrics (honest multi-token TPOT), and the
shardlint serving trace with spec enabled.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import llama
from deepspeed_tpu.serving import (Request, RequestStatus, Scheduler,
                                   ServingEngine, ServingMetrics)


def tiny_llama(**kw):
    d = dict(vocab_size=128, max_seq_len=64, hidden_size=32, num_layers=2,
             num_heads=4, num_kv_heads=2, intermediate_size=64)
    d.update(kw)
    return llama("llama-tiny", **d)


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


def _submit(srv, rid, prompt, **kw):
    return srv.submit(Request(request_id=rid, prompt=prompt, **kw))


def _serve(eng, spec=True, **serving):
    d = dict(max_slots=3, token_budget=16, max_tokens=64)
    d.update(serving)
    d["spec"] = {"enabled": spec, "max_draft": 4}
    return ServingEngine(engine=eng, serving=d)


# repetitive prompts an untrained greedy model cycles on — the n-gram
# lookup finds the cycle, so drafts actually get accepted
REPETITIVE = [
    np.asarray([7, 3, 9, 7, 3, 9, 7, 3]),
    np.asarray([5, 11, 5, 11, 5, 11]),
    np.asarray([2, 2, 2, 2, 2, 2, 2, 2]),
]


# ---------------------------------------------------------------------------
# the losslessness oracle: spec-on == spec-off, bitwise
# ---------------------------------------------------------------------------
def test_spec_greedy_parity_and_acceptance():
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(1)
    )
    news = [24, 28, 24]
    off = _serve(eng, spec=False)
    on = _serve(eng, spec=True)
    sts_off, sts_on = [], []
    for srv, sts in ((off, sts_off), (on, sts_on)):
        for i, (p, n) in enumerate(zip(REPETITIVE, news)):
            sts.append(_submit(srv, f"r{i}", p, max_new_tokens=n))
        srv.run_until_idle()
    for a, b, p, n in zip(sts_off, sts_on, REPETITIVE, news):
        assert a.status is RequestStatus.DONE
        assert b.status is RequestStatus.DONE
        np.testing.assert_array_equal(a.output(), b.output())
        # and both match the lockstep single-request engine bitwise
        want = eng.generate(p[None, :], max_new_tokens=n, temperature=0.0)
        np.testing.assert_array_equal(b.output(), want[0])
    # ONE trace for the whole spec replay: per-slot draft counts are the
    # traced spec_len vector, never a shape
    assert on.step_traces == 1
    m = on.metrics
    assert m.draft_tokens_proposed > 0
    assert m.draft_tokens_accepted > 0, "no draft accepted on cycles"
    assert m.acceptance_rate > 0.0
    assert m.mean_accepted_tokens_per_step > 1.0
    # accepted drafts advance frontiers by >1/step: fewer decode steps
    assert on.metrics.steps < off.metrics.steps


def test_spec_sampled_parity_shared_keys():
    """Sampled decoding with per-request keys: sample-and-match keeps the
    RNG chain exactly where spec-off leaves it, so sampled outputs stay
    bitwise identical across temperature/top-k/top-p mixes — including a
    penalized request, which the scheduler never drafts for."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(2)
    )
    cases = [
        dict(temperature=0.8, top_k=10, top_p=1.0),
        dict(temperature=0.7, top_k=0, top_p=0.85),
        dict(temperature=0.9, top_k=20, top_p=0.9, repetition_penalty=1.3),
        dict(temperature=0.0),  # greedy rides in the same batch
    ]
    prompts = REPETITIVE + [np.asarray([7, 3, 9, 7, 3, 9])]
    keys = [jax.random.PRNGKey(200 + i) for i in range(len(cases))]
    outs = {}
    for spec in (False, True):
        srv = _serve(eng, spec=spec, max_slots=4)
        sts = [
            _submit(srv, f"s{i}", p, max_new_tokens=10, rng=keys[i], **c)
            for i, (p, c) in enumerate(zip(prompts, cases))
        ]
        srv.run_until_idle()
        outs[spec] = [st.output() for st in sts]
    for i, (a, b) in enumerate(zip(outs[False], outs[True])):
        np.testing.assert_array_equal(a, b, err_msg=f"case {i}")
        want = eng.generate(prompts[i][None, :], max_new_tokens=10,
                            rng=keys[i], **cases[i])
        np.testing.assert_array_equal(b, want[0], err_msg=f"lockstep {i}")


def test_spec_eos_clamps_advance():
    """An eos emitted mid-window must cut the advance (and the RNG chain)
    exactly where spec-off stops."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(3)
    )
    prompt = REPETITIVE[0]
    ref = eng.generate(prompt[None, :], max_new_tokens=16, temperature=0.0)
    eos = int(ref[0, prompt.size + 9])  # eos lands mid-generation
    want = eng.generate(prompt[None, :], max_new_tokens=16, temperature=0.0,
                        eos_token_id=eos)
    for spec in (False, True):
        srv = _serve(eng, spec=spec)
        st = _submit(srv, "e0", prompt, max_new_tokens=16, eos_token_id=eos)
        srv.run_until_idle()
        assert st.status is RequestStatus.DONE
        np.testing.assert_array_equal(st.output(), want[0],
                                      err_msg=f"spec={spec}")


def test_spec_tp2_int8_kv_parity():
    model = tiny_llama(num_heads=4, num_kv_heads=4)
    topo = MeshTopology(dims=ParallelDims(tp=2), devices=jax.devices()[:2])
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, topology=topo,
        kv_cache_dtype="int8", rng=jax.random.PRNGKey(4),
    )
    outs = {}
    for spec in (False, True):
        srv = _serve(eng, spec=spec, max_slots=2)
        sts = [
            _submit(srv, f"q{i}", p, max_new_tokens=18)
            for i, p in enumerate(REPETITIVE[:2])
        ]
        srv.run_until_idle()
        outs[spec] = [st.output() for st in sts]
        assert srv.step_traces == 1
    for a, b in zip(outs[False], outs[True]):
        np.testing.assert_array_equal(a, b)


def test_spec_paged_parity_and_page_invariants():
    """Paged arena + spec: rejected-window pages stay slot-owned (the
    scheduler's free+live==num_pages assertion runs every tick), outputs
    match the contiguous spec-off arena bitwise, prefix sharing and COW
    keep working underneath the verify windows."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(5)
    )
    news = [20, 24, 20]
    dense = _serve(eng, spec=False)
    paged = _serve(eng, spec=True, paged=True, page_size=8)
    outs = {}
    for key, srv in (("dense-off", dense), ("paged-on", paged)):
        sts = [
            _submit(srv, f"p{i}", p, max_new_tokens=n)
            for i, (p, n) in enumerate(zip(REPETITIVE, news))
        ]
        srv.run_until_idle()
        outs[key] = [st.output() for st in sts]
    for a, b in zip(outs["dense-off"], outs["paged-on"]):
        np.testing.assert_array_equal(a, b)
    assert paged.step_traces == 1
    # everything released: the pool drained back to fully free
    paged.scheduler.assert_page_invariants()
    assert paged.metrics.draft_tokens_proposed > 0


def test_spec_paged_pool_pressure_evicts_gracefully():
    """A pool too small for every spec window: draft growth shrinks under
    page pressure first; true starvation force-evicts the newest request
    (progress/RNG rewound) and the pool accounting stays balanced —
    resubmission reproduces the deterministic output."""
    model = tiny_llama()
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(6)
    )
    srv = ServingEngine(engine=eng, serving={
        "max_slots": 3, "token_budget": 16, "max_tokens": 48,
        "paged": True, "page_size": 8, "num_pages": 10,  # floor is 8
        "spec": {"enabled": True, "max_draft": 4},
    })
    sts = [
        _submit(srv, f"v{i}", p, max_new_tokens=16)
        for i, p in enumerate(REPETITIVE)
    ]
    finished = srv.run_until_idle()
    evicted = [st for st in sts if st.status is RequestStatus.EVICTED]
    done_first = [st for st in sts if st.status is RequestStatus.DONE]
    assert done_first, "nothing finished under pool pressure"
    srv.scheduler.assert_page_invariants()
    # evicted requests resubmit and reproduce the same tokens the
    # unpressured engine produces
    for st in evicted:
        assert st.retry_after is not None
        srv.scheduler.resubmit(st)
    srv.run_until_idle()
    srv.scheduler.assert_page_invariants()
    for st in sts:
        assert st.status is RequestStatus.DONE
        want = eng.generate(st.request.prompt[None, :], max_new_tokens=16,
                            temperature=0.0)
        np.testing.assert_array_equal(st.output(), want[0])


# ---------------------------------------------------------------------------
# scheduler budget accounting (fake clock, no device work)
# ---------------------------------------------------------------------------
def _sched(clock, **kw):
    d = dict(max_slots=3, token_budget=16, queue_limit=8,
             request_timeout_s=1e9, eviction_backoff_s=1.0, max_tokens=64,
             clock=clock, metrics=ServingMetrics(clock=clock),
             spec_max_draft=4)
    d.update(kw)
    return Scheduler(**d)


def _req(rid, plen=4, new=20, **kw):
    return Request(request_id=rid, prompt=np.arange(plen) % 7,
                   max_new_tokens=new, **kw)


def _to_decode(s, rid, **kw):
    """Fast-forward one request to mid-DECODE (prompt cached, first token
    sampled) — the spec-eligible state."""
    st = s.submit(_req(rid, **kw))
    assert st.status is RequestStatus.PREFILL
    st.prompt_pos = st.prompt_len
    st.transition(RequestStatus.DECODE)
    st.tokens.append(1)
    return st


def test_scheduler_spec_decode_claims_k_plus_one_rows():
    clock = FakeClock()
    s = _sched(clock, max_slots=2, token_budget=16)
    st0 = _to_decode(s, "a")
    st1 = _to_decode(s, "b")
    plan = s.plan()
    assert plan is not None
    # both decode slots got their feed + the full k=4 drafts: 5 rows each
    assert sorted(plan.num_new[plan.num_new > 0].tolist()) == [5, 5]
    assert plan.spec_len[st0.slot] == 4 and plan.spec_len[st1.slot] == 4
    assert plan.total_tokens == 10  # (k+1) * 2 <= budget
    for w in plan.work:
        assert w.spec_len == 4 and w.n_tokens == 5 and w.sample


def test_scheduler_spec_shrinks_k_under_budget_pressure():
    """budget < decodes * (k+1): every decode keeps its committed feed and
    the drafts shrink uniformly — down to plain decode (k=0) when the
    budget only covers the feeds. The fixed step shape never changes;
    only the traced spec_len vector does."""
    clock = FakeClock()
    # 3 decode slots, budget 6: feeds take 3, drafts get 3 → k=1 each
    s = _sched(clock, max_slots=3, token_budget=6)
    sts = [_to_decode(s, f"d{i}") for i in range(3)]
    plan = s.plan()
    assert plan.total_tokens == 6
    assert sorted(plan.num_new[plan.num_new > 0].tolist()) == [2, 2, 2]
    # budget 3 == decode count: graceful degradation to plain decode
    s2 = _sched(clock, max_slots=3, token_budget=3)
    for i in range(3):
        _to_decode(s2, f"p{i}")
    plan2 = s2.plan()
    assert plan2.total_tokens == 3
    assert plan2.spec_len.sum() == 0
    assert sorted(plan2.num_new[plan2.num_new > 0].tolist()) == [1, 1, 1]


def test_scheduler_spec_caps_at_remaining_allowance():
    """Drafts never extend past max_new_tokens - 1 remaining tokens, so
    the device can never emit beyond the allowance (the RNG chain stops
    exactly where spec-off would)."""
    clock = FakeClock()
    s = _sched(clock, max_slots=1, token_budget=16)
    st = _to_decode(s, "tail", new=3)  # 1 emitted, 2 remaining
    plan = s.plan()
    # window may emit at most remaining=2 tokens → at most 1 draft
    assert plan.num_new[st.slot] == 2 and plan.spec_len[st.slot] == 1


def test_scheduler_spec_skips_penalized_requests():
    clock = FakeClock()
    s = _sched(clock, max_slots=2, token_budget=16)
    st_pen = _to_decode(s, "pen", repetition_penalty=1.3)
    st_plain = _to_decode(s, "plain")
    plan = s.plan()
    assert plan.spec_len[st_pen.slot] == 0      # seen-matrix correctness
    assert plan.num_new[st_pen.slot] == 1
    assert plan.spec_len[st_plain.slot] == 4    # unaffected neighbor


def test_scheduler_spec_rejection_rollback_keeps_pages_balanced():
    """Paged + spec on a fake clock: a fully-rejected window (n_emit=1)
    leaves its draft pages slot-owned — no leak, no double free — and
    the rejected targets become the next step's draft fallback; eviction
    afterwards returns every page."""
    clock = FakeClock()
    s = _sched(clock, max_slots=2, token_budget=16, max_tokens=48,
               page_size=4, num_pages=26, pages_per_slot=13,
               prefix_cache=False)
    st = _to_decode(s, "rb", plen=6)
    plan = s.plan()
    k = int(plan.spec_len[st.slot])
    assert k > 0
    s.assert_page_invariants()
    # device says: everything rejected, one (bonus) token emitted
    fake = np.zeros((s.max_slots, 5), np.int64)
    fake[st.slot] = np.asarray([9, 8, 7, 6, 5])
    n_emit = np.zeros(s.max_slots, np.int64)
    n_emit[st.slot] = 1
    s.complete(plan, fake, None, n_emit=n_emit)
    assert st.tokens[-1] == 9 and len(st.tokens) == 2
    assert st.draft_tail == [8, 7, 6, 5][:k]
    s.assert_page_invariants()  # free + live == num_pages still holds
    held = len(st.pages)
    assert held >= 2  # frontier + draft margin pages stay slot-owned
    s._evict(st, clock(), "test eviction")
    s.assert_page_invariants()
    assert s.pool.free_count == s.pool.num_pages  # rollback freed all
    assert st.draft_tail == []  # eviction rewinds draft state too


def test_scheduler_legacy_1d_complete_still_works():
    """Pre-spec callers (and the scheduler unit tests) pass a 1-D token
    vector with no n_emit — one token per sampling slot."""
    clock = FakeClock()
    s = _sched(clock, max_slots=1, token_budget=8, spec_max_draft=0)
    st = s.submit(_req("legacy", plen=4, new=2))
    for _ in range(6):
        plan = s.plan()
        if plan is None:
            break
        s.complete(plan, np.zeros(s.max_slots, np.int64))
    assert st.status is RequestStatus.DONE


# ---------------------------------------------------------------------------
# shared draft + acceptance math units (serving/spec.py)
# ---------------------------------------------------------------------------
def test_ngram_propose_finds_cycle_and_falls_back():
    from deepspeed_tpu.serving.spec import ngram_propose, propose_drafts

    buf = np.asarray([7, 3, 9, 7, 3, 9, 7, 3, 0, 0, 0, 0], np.int32)
    # trailing 3-gram at pos=7 is (9, 7, 3); its earlier occurrence ends
    # at index 4 → continuation 9, 7, 3 ...
    out = np.asarray(ngram_propose(buf, 7, 3, 3))
    np.testing.assert_array_equal(out, [9, 7, 3])
    # no match → the slice past pos (the stale-predictions fallback)
    buf2 = np.asarray([1, 2, 3, 4, 5, 6, 42, 43, 44], np.int32)
    out2 = np.asarray(ngram_propose(buf2, 5, 3, 3))
    np.testing.assert_array_equal(out2, [42, 43, 44])
    # the host wrapper builds the same buffer from request state parts
    out3 = propose_drafts([7, 3, 9, 7], [3, 9, 7, 3], [], 3, 3)
    np.testing.assert_array_equal(out3, [9, 7, 3])
    # draft_tail seeds the fallback when nothing matches
    out4 = propose_drafts([1, 2, 3], [4, 5, 6], [42, 43, 44], 3, 3)
    np.testing.assert_array_equal(out4, [42, 43, 44])


def test_acceptance_math_units():
    from deepspeed_tpu.serving.spec import (clamp_advance_at_eos,
                                            longest_accepted_prefix)

    lap = lambda m: int(longest_accepted_prefix(jnp.asarray(m)))
    assert lap([True, True, False, True]) == 2
    assert lap([False, True, True]) == 0
    assert lap([True, True, True]) == 3
    assert lap(np.zeros((0,), bool)) == 0  # k=0 window (plain decode)
    # batched form agrees
    batched = longest_accepted_prefix(
        jnp.asarray([[True, False], [True, True]])
    )
    np.testing.assert_array_equal(np.asarray(batched), [1, 2])
    # eos clamp: eos at emitted index 1 cuts a 3-advance to 2
    targets = jnp.asarray([5, 9, 7])
    adv, has = clamp_advance_at_eos(targets, 3, 9)
    assert int(adv) == 2 and bool(has)
    # eos beyond the advance does not fire
    adv, has = clamp_advance_at_eos(targets, 2, 7)
    assert int(adv) == 2 and not bool(has)
    # eos_id -1 never matches (token ids are non-negative)
    adv, has = clamp_advance_at_eos(targets, 3, -1)
    assert int(adv) == 3 and not bool(has)


# ---------------------------------------------------------------------------
# metrics / config / lint / streams
# ---------------------------------------------------------------------------
def test_spec_metrics_counts_tokens_not_steps():
    """TPOT and tokens/s divide by tokens actually emitted: a verify
    window emitting 3 tokens books 3 on_token calls, and the acceptance
    counters aggregate per window."""
    clock = FakeClock()
    m = ServingMetrics(clock=clock)
    from deepspeed_tpu.serving.request import RequestState

    st = RequestState(request=_req("m0", new=8), arrival_t=0.0)
    clock.advance(1.0)
    st.first_token_t = clock()
    for _ in range(3):
        st.tokens.append(1)
        m.on_token(st, clock())
    m.on_spec(st, proposed=4, accepted=2, emitted=3)
    clock.advance(2.0)
    for _ in range(3):
        st.tokens.append(1)
        m.on_token(st, clock())
    m.on_spec(st, proposed=4, accepted=2, emitted=3)
    st.finish_t = clock()
    m.on_finish(st, clock())
    assert m.tokens_out == 6
    assert m.acceptance_rate == pytest.approx(0.5)
    assert m.mean_accepted_tokens_per_step == pytest.approx(3.0)
    # TPOT: 2.0s from first token to finish over (6 - 1) tokens
    assert m.tpot_s[-1] == pytest.approx(2.0 / 5)
    snap = m.snapshot()
    assert snap["draft_tokens_accepted"] == 4
    assert snap["mean_accepted_tokens_per_step"] == pytest.approx(3.0)


def test_spec_config_validation():
    from deepspeed_tpu.config import DeepSpeedConfig, DeepSpeedConfigError

    cfg = DeepSpeedConfig({
        "serving": {"enabled": True, "token_budget": 32,
                    "spec": {"enabled": True, "max_draft": 6}},
    })
    assert cfg.serving.spec.enabled and cfg.serving.spec.max_draft == 6
    with pytest.raises(DeepSpeedConfigError, match="max_draft"):
        DeepSpeedConfig({"serving": {
            "token_budget": 4, "spec": {"enabled": True, "max_draft": 4},
        }})
    with pytest.raises(DeepSpeedConfigError, match="draft"):
        DeepSpeedConfig({"serving": {
            "spec": {"enabled": True, "draft": "model"},
        }})


def test_spec_analytic_stream_and_lint():
    """The verify-window traffic is declared through analytic_streams
    (shardplan/R8 pricing) and the spec-enabled serving step lints clean
    on a tp=2 CPU mesh."""
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.analysis import lint_config

    model = tiny_llama(num_heads=4, num_kv_heads=4)
    eng = deepspeed_tpu.init_inference(
        model, dtype=jnp.float32, max_tokens=64, rng=jax.random.PRNGKey(7)
    )
    srv = _serve(eng, spec=True)
    streams = srv.analytic_streams()
    sv = streams["spec_verify"]
    assert sv["kind"] == "hbm" and sv["bytes_per_step"] > 0
    assert sv["max_draft"] == 4 and sv["spec"]
    # spec-off engines declare no spec stream
    assert "spec_verify" not in _serve(eng, spec=False).analytic_streams()

    comm.destroy_process_group()
    report = lint_config(
        {
            "tensor_parallel": {"tp_size": 2},
            "serving": {"enabled": True, "max_slots": 2, "token_budget": 8,
                        "max_tokens": 64, "kv_cache_dtype": "int8",
                        "spec": {"enabled": True, "max_draft": 3}},
        },
        model=model,
        source="serving-spec-unit",
    )
    assert report.ok, report.format()
