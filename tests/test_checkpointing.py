"""Checkpoint save/load exactness + universal reshape (SURVEY §4).

Model: DeepSpeed tests/unit/checkpoint/ — save → perturb → load → exact
equality; save on one dp size, load on another.
"""

import glob
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import deepspeed_tpu
from deepspeed_tpu.comm.topology import MeshTopology, ParallelDims
from deepspeed_tpu.models import gpt2
from deepspeed_tpu.runtime.checkpointing import list_checkpoints


def tiny_model():
    return gpt2(
        "gpt2-tiny",
        vocab_size=256,
        max_seq_len=32,
        hidden_size=32,
        num_layers=2,
        num_heads=2,
    )


def make_engine(zero_stage=1, dims=None, seed=7):
    n = 8
    if dims is not None and dims.dp:
        n = dims.dp
    topo = MeshTopology(dims=dims or ParallelDims(), devices=jax.devices()[:n])
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config={
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 8 // topo.data_shard_size,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            "zero_optimization": {"stage": zero_stage},
            "seed": seed,
        },
        topology=topo,
    )
    return engine


def batch(n=8, s=16, seed=0):
    r = np.random.RandomState(seed)
    return {"input_ids": r.randint(0, 256, size=(n, s))}


def trees_equal(a, b):
    oks = jax.tree_util.tree_leaves(
        jax.tree.map(lambda x, y: bool(jnp.array_equal(x, y)), a, b)
    )
    return all(oks)


def test_save_load_exact(tmp_path):
    engine = make_engine(zero_stage=1)
    engine.train_batch(batch=batch(seed=1))
    engine.train_batch(batch=batch(seed=2))
    path = engine.save_checkpoint(str(tmp_path), client_state={"epoch": 3})
    assert os.path.isdir(path)
    saved_params = jax.device_get(engine.state.params)
    saved_opt = jax.device_get(engine.state.opt_state)

    # perturb: more steps drift the state away
    engine.train_batch(batch=batch(seed=3))
    assert not trees_equal(saved_params, engine.state.params)

    lpath, client = engine.load_checkpoint(str(tmp_path))
    assert lpath == path
    assert client == {"epoch": 3}
    assert engine.global_steps == 2
    assert trees_equal(saved_params, engine.state.params)
    assert trees_equal(saved_opt, engine.state.opt_state)


def test_load_latest_tag_and_list(tmp_path):
    engine = make_engine()
    engine.train_batch(batch=batch())
    engine.save_checkpoint(str(tmp_path), tag="global_step1")
    engine.train_batch(batch=batch(seed=5))
    engine.save_checkpoint(str(tmp_path))
    assert list_checkpoints(str(tmp_path)) == ["global_step1", "global_step2"]
    with open(os.path.join(str(tmp_path), "latest")) as f:
        assert f.read().strip() == "global_step2"


def test_universal_reshape_dp4_to_dp2(tmp_path):
    """Save under dp=4/zero3, load under dp=2/zero1: same logical state."""
    e4 = make_engine(zero_stage=3, dims=ParallelDims(dp=4))
    e4.train_batch(batch=batch(seed=11))
    e4.save_checkpoint(str(tmp_path))
    ref_params = jax.device_get(e4.state.params)

    e2 = make_engine(zero_stage=1, dims=ParallelDims(dp=2), seed=99)
    assert not trees_equal(ref_params, e2.state.params)
    e2.load_checkpoint(str(tmp_path))
    assert trees_equal(ref_params, e2.state.params)
    assert e2.global_steps == e4.global_steps

    # and the restored engine still trains
    e2.train_batch(batch=batch(seed=12))


def test_resume_training_trajectory_exact(tmp_path):
    """ckpt-resume exactness: train 4; vs train 2 + save/load + train 2."""
    ea = make_engine(zero_stage=2, dims=ParallelDims(dp=2))
    for i in range(4):
        ea.train_batch(batch=batch(seed=100 + i))

    eb = make_engine(zero_stage=2, dims=ParallelDims(dp=2))
    for i in range(2):
        eb.train_batch(batch=batch(seed=100 + i))
    eb.save_checkpoint(str(tmp_path))
    ec = make_engine(zero_stage=2, dims=ParallelDims(dp=2), seed=1234)
    ec.load_checkpoint(str(tmp_path))
    for i in range(2, 4):
        ec.train_batch(batch=batch(seed=100 + i))

    a = jax.device_get(ea.state.params)
    c = jax.device_get(ec.state.params)
    leaves_a = jax.tree_util.tree_leaves(a)
    leaves_c = jax.tree_util.tree_leaves(c)
    for la, lc in zip(leaves_a, leaves_c):
        np.testing.assert_allclose(la, lc, rtol=1e-6, atol=1e-6)


def test_nvme_offload_matches_dense(tmp_path, devices8):
    """offload_optimizer.device=nvme: optimizer state lives on disk between
    steps (aio-backed swap) and the trajectory is bit-identical to the
    resident run (VERDICT r1 #4: offload wired end-to-end)."""
    import deepspeed_tpu.comm as comm
    from deepspeed_tpu.models import gpt2

    def run(extra, steps=4):
        comm.destroy_process_group()
        engine, *_ = deepspeed_tpu.initialize(
            model=gpt2("gpt2-tiny", vocab_size=128, max_seq_len=16),
            config={
                "train_batch_size": 16,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "bf16": {"enabled": True},
                "zero_optimization": {"stage": 2, **extra},
                "steps_per_print": 100,
            },
            rng=jax.random.PRNGKey(3),
        )
        data = {
            "input_ids": np.random.RandomState(0).randint(0, 128, size=(16, 16))
        }
        losses = [float(engine.train_batch(batch=data)) for _ in range(steps)]
        return losses, engine

    nvme_dir = str(tmp_path / "nvme")
    dense, _ = run({})
    offl, engine = run(
        {"offload_optimizer": {"device": "nvme", "nvme_path": nvme_dir}}
    )
    assert offl == dense, (offl, dense)
    # the state really went to disk and device memory was released
    import glob

    assert glob.glob(os.path.join(nvme_dir, "zero_opt_swap", "*.bin"))
    assert engine.state.opt_state is None

    # checkpoint round-trip while swapped out, then resume exactly
    save_dir = str(tmp_path / "ckpt")
    engine.save_checkpoint(save_dir)
    more_a = [
        float(engine.train_batch(batch={
            "input_ids": np.random.RandomState(9).randint(0, 128, size=(16, 16))
        }))
        for _ in range(2)
    ]
    engine.load_checkpoint(save_dir)
    more_b = [
        float(engine.train_batch(batch={
            "input_ids": np.random.RandomState(9).randint(0, 128, size=(16, 16))
        }))
        for _ in range(2)
    ]
    # rng stream restored by load → identical continuation
    assert more_a[0] == more_b[0]


# ---------------------------------------------------------------------------
# r3: shard-wise save, name-based leaf matching, legacy layout compat
# ---------------------------------------------------------------------------
def test_sharded_save_never_materializes_full_leaf(tmp_path):
    """ZeRO-3 fsdp=8 (persistence threshold 0 so every param is actually
    sharded): sharded params are written as >1 shard files per leaf, none
    of which is the full array (r2 verdict item 5)."""
    topo = MeshTopology(dims=ParallelDims(fsdp=8), devices=jax.devices()[:8])

    def build(seed):
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_model(),
            config={
                "train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {
                    "stage": 3,
                    "stage3_param_persistence_threshold": 0,
                },
                "seed": seed,
            },
            topology=topo,
        )
        return engine

    eng = build(7)
    eng.train_batch(batch=batch())
    path = eng.save_checkpoint(str(tmp_path), tag="ck")
    wq = eng.state.params["layers"]["attn"]["wq"]
    assert any(wq.sharding.spec), "wq unexpectedly replicated"
    full_bytes = int(np.prod(wq.shape)) * 4

    import json as _json

    with open(os.path.join(path, "metadata.json")) as f:
        names = _json.load(f)["components"]["params"]["leaf_names"]
    wq_i = next(i for i, n in enumerate(names) if "wq" in n)
    wq_shards = glob.glob(
        os.path.join(path, "params", f"leaf_{wq_i:05d}.shard.*.npy")
    )
    assert len(wq_shards) == 8, wq_shards
    assert all(os.path.getsize(f) < full_bytes for f in wq_shards)
    # and it loads back exactly into a fresh engine
    eng2 = build(99)
    eng2.load_checkpoint(str(tmp_path), tag="ck")
    assert trees_equal(eng.state.params, eng2.state.params)


def test_leaf_matching_by_name(tmp_path):
    """Leaves are matched by pytree path: a tree with one extra leaf loads
    the overlapping names under strict=False (r2: flat index mispaired)."""
    eng = make_engine(zero_stage=0)
    eng.train_batch(batch=batch())
    eng.save_checkpoint(str(tmp_path), tag="ck")

    import json

    with open(os.path.join(str(tmp_path), "ck", "metadata.json")) as f:
        meta = json.load(f)
    names = meta["components"]["params"]["leaf_names"]
    assert any("wq" in n for n in names)  # paths, not indices

    # strict=False + a differently-shaped head keeps current value for the
    # mismatch but still loads every other leaf by name
    eng2 = make_engine(zero_stage=0, seed=31)
    before = jax.device_get(eng2.state.params["layers"]["attn"]["wq"])
    eng2.load_checkpoint(str(tmp_path), tag="ck", strict=False)
    after = jax.device_get(eng2.state.params["layers"]["attn"]["wq"])
    saved = jax.device_get(eng.state.params["layers"]["attn"]["wq"])
    assert not np.array_equal(before, after)
    np.testing.assert_array_equal(after, saved)


def test_legacy_unsharded_layout_still_loads(tmp_path):
    """r2 checkpoints (one leaf_NNNNN.npy per leaf) remain readable."""
    eng = make_engine(zero_stage=1)
    eng.train_batch(batch=batch())
    path = eng.save_checkpoint(str(tmp_path), tag="ck")
    # rewrite the params component in the legacy layout
    import shutil

    from deepspeed_tpu.runtime.checkpointing import _assemble_leaf, _index_shard_files

    pdir = os.path.join(path, "params")
    files = _index_shard_files(pdir)
    full = {i: _assemble_leaf(entries) for i, entries in files.items()}
    shutil.rmtree(pdir)
    os.makedirs(pdir)
    for i, arr in full.items():
        np.save(os.path.join(pdir, f"leaf_{i:05d}.npy"), arr)

    eng2 = make_engine(zero_stage=1, seed=55)
    eng2.load_checkpoint(str(tmp_path), tag="ck")
    assert trees_equal(eng.state.params, eng2.state.params)


def test_orbax_checkpoint_engine(tmp_path):
    """checkpoint.engine="orbax": save via Orbax, exact reload, and
    universal reshape into a different dp size (r2: Orbax allowed, unused)."""
    def build(dims, seed):
        n = int(np.prod([dims.dp or 1, dims.fsdp, dims.sp, dims.tp, dims.pp, dims.ep]))
        topo = MeshTopology(dims=dims, devices=jax.devices()[:max(n, 1)])
        engine, *_ = deepspeed_tpu.initialize(
            model=tiny_model(),
            config={
                "train_batch_size": 8,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
                "zero_optimization": {"stage": 1},
                "checkpoint": {"engine": "orbax"},
                "seed": seed,
            },
            topology=topo,
        )
        return engine

    eng = build(ParallelDims(dp=4), seed=7)
    eng.train_batch(batch=batch())
    path = eng.save_checkpoint(str(tmp_path), tag="ck")
    assert os.path.isdir(os.path.join(path, "params", "orbax"))

    # exact reload on the same mesh
    eng2 = build(ParallelDims(dp=4), seed=31)
    eng2.load_checkpoint(str(tmp_path), tag="ck")
    assert trees_equal(eng.state.params, eng2.state.params)
    assert trees_equal(eng.state.opt_state, eng2.state.opt_state)

    # universal: restore into dp=2 with the target engine's shardings
    eng3 = build(ParallelDims(dp=2), seed=55)
    eng3.load_checkpoint(str(tmp_path), tag="ck")
    assert trees_equal(
        jax.device_get(eng.state.params), jax.device_get(eng3.state.params)
    )

    # and training continues identically from the restored state
    la = float(eng.train_batch(batch=batch(seed=3)))
    lb = float(eng2.train_batch(batch=batch(seed=3)))
    assert abs(la - lb) < 1e-6


def test_cross_format_resave_loads_fresh_state(tmp_path):
    """Saving native over a previous orbax checkpoint at the same tag must
    load the fresh native data, not the stale orbax tree."""
    eng = make_engine(zero_stage=1)
    eng.train_batch(batch=batch())
    # orbax save at tag "ck"
    eng.config.checkpoint.engine = "orbax"
    eng.save_checkpoint(str(tmp_path), tag="ck")
    stale = jax.device_get(eng.state.params)
    # drift, then native re-save at the same tag
    eng.train_batch(batch=batch(seed=9))
    eng.config.checkpoint.engine = "native"
    path = eng.save_checkpoint(str(tmp_path), tag="ck")
    fresh = jax.device_get(eng.state.params)
    assert not os.path.isdir(os.path.join(path, "params", "orbax"))

    eng2 = make_engine(zero_stage=1, seed=77)
    eng2.load_checkpoint(str(tmp_path), tag="ck")
    got = jax.device_get(eng2.state.params)
    assert trees_equal(got, fresh)
    assert not trees_equal(got, stale)


def test_zero_to_fp32_state_dict(tmp_path):
    """deepspeed.zero parity: assemble the full fp32 state dict from a
    sharded checkpoint without an engine (zero_to_fp32.py workflow)."""
    from deepspeed_tpu.zero import (
        convert_zero_checkpoint_to_fp32_state_dict,
        get_fp32_state_dict_from_zero_checkpoint,
    )

    eng = make_engine(zero_stage=3, dims=ParallelDims(dp=4))
    eng.train_batch(batch=batch())
    eng.save_checkpoint(str(tmp_path))

    sd = get_fp32_state_dict_from_zero_checkpoint(str(tmp_path))
    ref = jax.device_get(eng.state.params)
    wq = ref["layers"]["attn"]["wq"]
    key = next(k for k in sd if "wq" in k)
    np.testing.assert_allclose(sd[key], np.asarray(wq, np.float32))
    assert len(sd) == len(jax.tree_util.tree_leaves(ref))

    out = str(tmp_path / "fp32.npz")
    convert_zero_checkpoint_to_fp32_state_dict(str(tmp_path), out)
    loaded = np.load(out)
    np.testing.assert_allclose(loaded[key], sd[key])


def test_zero_shims(tmp_path):
    import deepspeed_tpu

    with deepspeed_tpu.zero.Init():
        eng = make_engine(zero_stage=3, dims=ParallelDims(dp=4))
    with deepspeed_tpu.zero.GatheredParameters(eng.state.params) as host:
        wq = host["layers"]["attn"]["wq"]
        assert isinstance(wq, np.ndarray)
        assert wq.shape == tuple(eng.state.params["layers"]["attn"]["wq"].shape)


def test_save_16bit_model(tmp_path):
    """save_16bit_model consolidates ZeRO-sharded weights into ONE bf16
    safetensors file under HF state_dict names (gpt2 here, so transformers
    could load it), tensors matching the live gathered params."""
    from deepspeed_tpu.integrations.hf import (
        export_hf_state_dict, read_safetensors,
    )
    from deepspeed_tpu.runtime.checkpointing import _to_host

    engine = make_engine(zero_stage=3)
    engine.train_batch(batch=batch())
    path = engine.save_16bit_model(str(tmp_path))
    got = read_safetensors(path)  # reader widens BF16 -> fp32
    assert got, "empty 16bit export"
    host = jax.tree.map(_to_host, engine.state.params)
    ref_sd = export_hf_state_dict(host, engine.model.config, "gpt2")
    assert set(got) == set(ref_sd)
    for name, arr in got.items():
        ref = np.asarray(ref_sd[name]).astype(jnp.bfloat16).astype(np.float32)
        np.testing.assert_array_equal(arr, ref, err_msg=name)
    engine.destroy()


def test_no_sync_parity_shim():
    """no_sync is a no-op under ZeRO<=1 (accumulation already defers the
    dp mean into the compiled step) and refuses under ZeRO>=2, like the
    reference."""
    engine = make_engine(zero_stage=1)
    with engine.no_sync():
        engine.train_batch(batch=batch())
    engine.destroy()
    engine = make_engine(zero_stage=2)
    with pytest.raises(RuntimeError, match="ZeRO stage >= 2"):
        with engine.no_sync():
            pass
    engine.destroy()


def test_initialize_accepts_mpu():
    """initialize(mpu=...) seeds the mesh from the Megatron mpu protocol."""
    import deepspeed_tpu.comm as comm

    class FakeMpu:
        def get_tensor_model_parallel_world_size(self):
            return 2

        def get_pipe_parallel_world_size(self):
            return 1

    comm.destroy_process_group()
    engine, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config={
            "train_batch_size": 4,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        },
        mpu=FakeMpu(),
    )
    assert engine.topology.tp_size == 2
    engine.train_batch(batch=batch(n=4))
    engine.destroy()
    comm.destroy_process_group()

    class PipeMpu(FakeMpu):
        def get_pipe_parallel_world_size(self):
            return 2

    with pytest.raises(ValueError, match="no pipeline section"):
        deepspeed_tpu.initialize(
            model=tiny_model(),
            config={
                "train_batch_size": 4,
                "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
            },
            mpu=PipeMpu(),
        )
    comm.destroy_process_group()


def test_safe_inspection_apis():
    """deepspeed.utils parity: safe_get/set_full_fp32_param,
    safe_get_full_optimizer_state, safe_get_full_grad — full (gathered)
    values under ZeRO-3 sharding, addressed by leaf-name substring."""
    from deepspeed_tpu.utils import (
        safe_get_full_fp32_param,
        safe_get_full_grad,
        safe_get_full_optimizer_state,
        safe_set_full_fp32_param,
    )

    engine = make_engine(zero_stage=3)
    b = batch()
    engine.train_batch(batch=b)

    w = safe_get_full_fp32_param(engine, "['embed']['tok']")
    assert w.dtype == np.float32 and w.shape == (256, 32)

    m = safe_get_full_optimizer_state(engine, "['embed']['tok']", "exp_avg")
    assert m.shape == w.shape and np.abs(m).sum() > 0  # stepped once

    # grads: None outside the backward window; real inside it
    assert safe_get_full_grad(engine, "['embed']['tok']") is None
    engine.train()
    engine.forward(b)
    engine.backward(batch=b)
    g = safe_get_full_grad(engine, "['embed']['tok']")
    assert g is not None and g.shape == w.shape and np.abs(g).sum() > 0
    engine.step()

    # set: patched value round-trips through the sharded tree
    patched = np.zeros_like(w)
    safe_set_full_fp32_param(engine, "['embed']['tok']", patched)
    np.testing.assert_array_equal(
        safe_get_full_fp32_param(engine, "['embed']['tok']"), patched
    )

    with pytest.raises(KeyError, match="ambiguous|no parameter"):
        safe_get_full_fp32_param(engine, "w")  # many leaves contain "w"
    engine.destroy()

    # partial accumulation window: accum=2, only ONE microbatch buffered —
    # grads over what's buffered, no batch-triangle complaint
    import deepspeed_tpu.comm as comm

    comm.destroy_process_group()
    topo = MeshTopology(dims=ParallelDims(dp=2), devices=jax.devices()[:2])
    eng2, *_ = deepspeed_tpu.initialize(
        model=tiny_model(),
        config={
            "train_batch_size": 8,
            "train_micro_batch_size_per_gpu": 2,
            "optimizer": {"type": "adamw", "params": {"lr": 1e-3}},
        },
        topology=topo,
    )
    eng2.train()
    mb = batch(n=4)
    eng2.forward(mb)
    eng2.backward(batch=mb)
    assert not eng2.is_gradient_accumulation_boundary()
    g = safe_get_full_grad(eng2, "['embed']['tok']")
    assert g is not None and g.shape == (256, 32) and np.abs(g).sum() > 0
    eng2.destroy()
    comm.destroy_process_group()


def test_zero_to_fp32_dropin_script(tmp_path):
    """save_checkpoint drops a runnable zero_to_fp32.py at the checkpoint
    root (reference layout); running it standalone assembles the full fp32
    weights from the sharded files."""
    import subprocess
    import sys

    engine = make_engine(zero_stage=3)
    engine.train_batch(batch=batch())
    engine.save_checkpoint(str(tmp_path))
    script = tmp_path / "zero_to_fp32.py"
    assert script.exists()
    out = tmp_path / "weights.npz"
    import pathlib

    pkg_root = str(pathlib.Path(deepspeed_tpu.__file__).resolve().parents[1])
    env = dict(
        os.environ,
        PYTHONPATH=os.pathsep.join(
            filter(None, [pkg_root, os.environ.get("PYTHONPATH", "")])
        ),
        JAX_PLATFORMS="cpu",
    )
    proc = subprocess.run(
        [sys.executable, str(script), str(tmp_path), str(out)],
        capture_output=True, text=True, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    got = np.load(out)
    from deepspeed_tpu.runtime.checkpointing import _to_host

    flat, _ = jax.tree_util.tree_flatten_with_path(engine.state.params)
    assert len(got.files) == len(flat)
    for path, leaf in flat:
        key = jax.tree_util.keystr(path)
        np.testing.assert_allclose(
            got[key], _to_host(leaf).astype(np.float32), err_msg=key
        )
    engine.destroy()
