"""ZeRO user-facing API shims.

Parity: deepspeed.zero (Init, GatheredParameters, register_external_parameter)
and deepspeed/utils/zero_to_fp32.py. In this framework parameters are one
logical sharded array per tensor, so most of the reference's machinery is a
no-op by construction:

- ``zero.Init``: the engine already materializes params sharded
  (``jax.jit(model.init, out_shardings=...)`` — see runtime/engine.py); the
  context exists so reference training scripts run unmodified.
- ``GatheredParameters``: gather-on-use is XLA-inserted; entering the
  context yields fully-gathered host copies when materialization is really
  wanted (export/debug), otherwise arrays are used as-is.
- ``get_fp32_state_dict_from_zero_checkpoint``: reads a checkpoint written
  by save_checkpoint (native shard files or Orbax) and returns the fp32
  params as one host state dict — no engine required, any mesh's shards.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Any, Dict, Optional

import numpy as np


@contextlib.contextmanager
def Init(*args, **kwargs):
    """Parity: deepspeed.zero.Init — module construction under ZeRO-3.

    Sharded construction happens inside ``initialize()`` here (params are
    born sharded via out_shardings), so the context is a documented no-op."""
    yield


@contextlib.contextmanager
def GatheredParameters(params, modifier_rank: Optional[int] = None, **kwargs):
    """Parity: deepspeed.zero.GatheredParameters.

    Yields host (numpy) copies of the given pytree — the explicit
    "materialize the full parameter" escape hatch. Writes back are the
    caller's responsibility (functional params have no in-place mutation):
    the reference's modifier_rank write-back contract cannot hold here, so
    passing it warns loudly."""
    import jax

    from .runtime.checkpointing import _to_host
    from .utils.logging import log_dist

    if modifier_rank is not None:
        log_dist(
            "warning: zero.GatheredParameters(modifier_rank=...) yields "
            "DETACHED host copies — in-context mutations are NOT written "
            "back to the sharded parameters (functional arrays); rebuild "
            "the param pytree and pass it to initialize(model_parameters=...)"
        )
    # _to_host handles multi-host non-addressable shards (all-gather) and
    # pinned_host offloaded leaves (device bounce) — plain device_get fails
    # on both
    yield jax.tree.map(_to_host, params)


def register_external_parameter(module, param) -> None:
    """Parity: deepspeed.zero.register_external_parameter — a no-op: XLA's
    sharding propagation already tracks every array used in the step."""


def get_fp32_state_dict_from_zero_checkpoint(
    checkpoint_dir: str, tag: Optional[str] = None
) -> Dict[str, Any]:
    """Parity: deepspeed.utils.zero_to_fp32 — assemble the full fp32 model
    state from a (possibly sharded) engine checkpoint, without an engine.

    Returns {pytree-path: np.ndarray}. Works for both the native shard-file
    layout and the Orbax layout."""
    from .runtime.checkpointing import (
        _ORBAX_SUBDIR,
        _assemble_leaf,
        _index_shard_files,
        resolve_tag,
    )

    path = resolve_tag(checkpoint_dir, tag)
    pdir = os.path.join(path, "params")

    if os.path.isdir(os.path.join(pdir, _ORBAX_SUBDIR)):
        import jax
        import orbax.checkpoint as ocp

        ckptr = ocp.StandardCheckpointer()
        odir = os.path.join(pdir, _ORBAX_SUBDIR)
        # restore against abstract shapes from the checkpoint's own metadata
        # (target-less restore is flagged unsafe by orbax)
        try:
            md = ckptr.metadata(odir)
            target = jax.tree.map(
                lambda m: jax.ShapeDtypeStruct(m.shape, m.dtype), md
            )
            tree = ckptr.restore(odir, target=target)
        except Exception:
            tree = ckptr.restore(odir)
        flat, _ = jax.tree_util.tree_flatten_with_path(tree)
        return {
            jax.tree_util.keystr(p): np.asarray(v, np.float32) for p, v in flat
        }

    files = _index_shard_files(pdir)
    names = None
    meta_path = os.path.join(path, "metadata.json")
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            names = (
                json.load(f).get("components", {}).get("params") or {}
            ).get("leaf_names")
    if names is None:  # pre-name-metadata checkpoints: positional keys
        names = [f"leaf_{i:05d}" for i in sorted(files)]
    out: Dict[str, Any] = {}
    for i, name in enumerate(names):
        entries = files.get(i)
        if not entries:
            raise FileNotFoundError(
                f"checkpoint missing shard files for leaf {name!r} (index {i})"
            )
        out[name] = np.asarray(_assemble_leaf(entries), np.float32)
    return out


def convert_zero_checkpoint_to_fp32_state_dict(
    checkpoint_dir: str, output_file: str, tag: Optional[str] = None
) -> None:
    """Parity: zero_to_fp32.py's CLI entry — write the assembled state dict
    to one .npz archive."""
    sd = get_fp32_state_dict_from_zero_checkpoint(checkpoint_dir, tag)
    np.savez(output_file, **sd)


if __name__ == "__main__":
    # python -m deepspeed_tpu.zero <ckpt_dir> <out.npz> [tag]
    import sys

    convert_zero_checkpoint_to_fp32_state_dict(
        sys.argv[1], sys.argv[2],
        tag=sys.argv[3] if len(sys.argv) > 3 else None,
    )
