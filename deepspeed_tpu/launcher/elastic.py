"""Elastic supervisor: preemption-resilient multi-process training.

Sits above the ``local`` launcher backend (one ``jax.distributed`` rank
per subprocess, DSTPU_* env from :func:`.runner.build_launch_env`) and
adds the recovery loop ROADMAP item 1 names:

- **detect**: poll the worker processes; any death (SIGTERM'd by a
  preemption, OOM-killed, nonzero exit) ends the round. The dying
  worker's own process runs the runtime/ckpt SIGTERM chain first —
  final sync save where possible, healthwatch postmortem always — the
  supervisor only observes the exit.
- **recompute**: tear down the surviving ranks (they would hang in
  their next collective against the dead peer), shrink the world to the
  survivors, and rebuild the launch env — a fresh coordinator port, a
  fresh ``jax.distributed`` job, a smaller mesh.
- **resume**: relaunch the same worker argv. Workers are resume-shaped
  by contract: on start they load the latest *committed* tag (torn
  saves are invisible — :mod:`...runtime.ckpt.manifest`) and reshard it
  onto whatever mesh the new world size gives them
  (:mod:`...runtime.ckpt.reshard`).

``tools/elastic_run.py`` is the reference worker + the preemption
oracle built on this class; the ci.yml ``preemption`` job drives it.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import time
from typing import Dict, List, Optional

from ..utils.logging import log_dist
from .runner import build_launch_env, spawn_local

#: exported to every worker: which recovery round it was launched in
#: (0 = the initial launch). Lets a worker scope fault injection
#: ("die in round 0 only") and log its lineage.
ROUND_ENV = "DSTPU_ELASTIC_ROUND"


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rc(code: int) -> int:
    """Popen returncode → 128+signal convention (launcher.runner's)."""
    return 128 - code if code < 0 else code


class ElasticSupervisor:
    """Run ``worker_argv`` as an elastic multi-process job.

    Each round spawns ``world`` local ranks on a fresh coordinator port.
    A clean round (all ranks exit 0) ends the job with 0. A worker death
    shrinks the world by the number of dead ranks and relaunches, until
    ``min_workers`` can't be met or ``max_rounds`` recoveries happened —
    then the last failure's exit code propagates."""

    def __init__(
        self,
        worker_argv: List[str],
        num_workers: int,
        min_workers: int = 1,
        max_rounds: int = 8,
        coordinator: str = "127.0.0.1",
        poll_s: float = 0.2,
        grace_s: float = 10.0,
        env: Optional[Dict[str, str]] = None,
    ):
        if num_workers < 1 or min_workers < 1:
            raise ValueError("num_workers and min_workers must be >= 1")
        self.worker_argv = list(worker_argv)
        self.num_workers = int(num_workers)
        self.min_workers = int(min_workers)
        self.max_rounds = int(max_rounds)
        self.coordinator = coordinator
        self.poll_s = float(poll_s)
        self.grace_s = float(grace_s)
        self.env = dict(env or {})
        self.rounds: List[Dict] = []  # per-round {world, rc, dead} records

    # ------------------------------------------------------------ round
    def _spawn_round(self, world: int, rnd: int) -> List[subprocess.Popen]:
        port = free_port()  # fresh jax.distributed job per round
        procs = []
        for pid in range(world):
            env = build_launch_env(
                self.coordinator, port, world, pid,
                base_env={**os.environ, **self.env, ROUND_ENV: str(rnd)},
            )
            procs.append(spawn_local(env, self.worker_argv))
        log_dist(
            f"elastic: round {rnd}: launched {world} worker(s) "
            f"(coordinator {self.coordinator}:{port})"
        )
        return procs

    def _teardown(self, procs: List[subprocess.Popen]) -> None:
        """terminate → grace → kill the still-running ranks. SIGTERM
        first on purpose: it gives each survivor its own ckpt/postmortem
        SIGTERM chain before the hard kill."""
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.grace_s
        for p in procs:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()
        # reap the late exits so the dead-count below is accurate
        for p in procs:
            if p.poll() is None:
                p.wait()

    def _babysit(self, procs: List[subprocess.Popen],
                 forwarded: List[int]) -> int:
        """Wait for the round to finish. Returns 0 on a clean round,
        else the first failure's mapped exit code."""
        while True:
            if forwarded:
                self._teardown(procs)
                return 128 + forwarded[0]
            codes = [p.poll() for p in procs]
            if all(c == 0 for c in codes):
                return 0
            failed = [c for c in codes if c not in (None, 0)]
            if failed:
                # let simultaneous deaths (a whole-host preemption) land
                # before counting survivors
                time.sleep(self.poll_s)
                self._teardown(procs)
                return _rc(failed[0])
            time.sleep(self.poll_s)

    # -------------------------------------------------------------- run
    def run(self) -> int:
        forwarded: List[int] = []

        def _forward(signum, frame):
            forwarded.append(signum)

        old = (signal.signal(signal.SIGINT, _forward),
               signal.signal(signal.SIGTERM, _forward))
        world = self.num_workers
        rc = 1
        try:
            for rnd in range(self.max_rounds + 1):
                procs = self._spawn_round(world, rnd)
                rc = self._babysit(procs, forwarded)
                dead = sum(
                    1 for p in procs if p.returncode not in (0, None)
                )
                self.rounds.append(
                    {"round": rnd, "world": world, "rc": rc, "dead": dead}
                )
                if rc == 0:
                    log_dist(f"elastic: round {rnd} completed cleanly")
                    return 0
                if forwarded:
                    log_dist("elastic: supervisor signalled; giving up")
                    return rc
                # shrink to the survivors, but never below the capacity
                # floor: a whole-job preemption (every rank SIGTERM'd)
                # restarts at min_workers rather than giving up — the
                # committed tags make the restart cheap either way
                survivors = max(world - max(dead, 1), self.min_workers)
                log_dist(
                    f"elastic: round {rnd} lost {max(dead, 1)} worker(s) "
                    f"(rc={rc}); resuming with world={survivors}"
                )
                world = survivors
            return rc
        finally:
            signal.signal(signal.SIGINT, old[0])
            signal.signal(signal.SIGTERM, old[1])
