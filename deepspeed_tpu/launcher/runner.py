"""Launcher CLI.

Parity: deepspeed/launcher/runner.py + launch.py (`deepspeed` command):
hostfile parsing, --include/--exclude filters, resource ordering, and
per-host process launch. TPU-native differences:

- Single host is pure SPMD: ONE process drives every local chip (the
  reference spawns one rank per GPU), so `deepspeed_tpu train.py` simply
  execs the script — jax discovers local devices.
- Multi-host runs one process per host (not per chip):
  `jax.distributed.initialize(coordinator, num_processes, process_id)` is
  driven by env vars this launcher exports (DSTPU_COORDINATOR etc.), and
  remote processes are started over ssh like the reference's pdsh runner.

Usage:
  deepspeed_tpu --hostfile hosts.txt train.py --deepspeed_config ds.json
  deepspeed_tpu train.py ...                      # single host
"""

from __future__ import annotations

import argparse
import os
import shlex
import subprocess
import sys
from collections import OrderedDict
from typing import Dict, List, Optional

DEFAULT_COORD_PORT = 29500


def parse_hostfile(path_or_text: str, is_text: bool = False) -> "OrderedDict[str, int]":
    """Parity: deepspeed/launcher/runner.py parse_resource_filter inputs.

    Lines: `<hostname> slots=<n>`; '#' comments; returns host → slot count
    (slots = chips on that host; informational on TPU, the process count is
    one per host)."""
    text = path_or_text if is_text else open(path_or_text).read()
    resources: "OrderedDict[str, int]" = OrderedDict()
    for raw in text.splitlines():
        line = raw.split("#")[0].strip()
        if not line:
            continue
        parts = line.split()
        host = parts[0]
        slots = 1
        for p in parts[1:]:
            if p.startswith("slots="):
                slots = int(p.split("=", 1)[1])
        if host in resources:
            raise ValueError(f"duplicate host {host} in hostfile")
        resources[host] = slots
    return resources


def parse_inclusion_exclusion(
    resources: Dict[str, int],
    include_str: str = "",
    exclude_str: str = "",
) -> "OrderedDict[str, int]":
    """Parity: deepspeed runner --include/--exclude (host[:slot,slot] syntax;
    slot filters are accepted but only whole-host filtering matters on TPU)."""

    def hosts_of(spec: str) -> List[str]:
        return [h.split(":")[0] for h in spec.split("@") if h]

    filtered = OrderedDict(resources)
    if include_str:
        keep = hosts_of(include_str)
        unknown = [h for h in keep if h not in resources]
        if unknown:
            raise ValueError(f"--include hosts not in hostfile: {unknown}")
        filtered = OrderedDict((h, resources[h]) for h in keep)
    for h in hosts_of(exclude_str):
        if h not in resources:
            raise ValueError(f"--exclude host not in hostfile: {h}")
        filtered.pop(h, None)
    if not filtered:
        raise ValueError("no hosts left after include/exclude filtering")
    return filtered


def build_launch_env(
    coordinator: str,
    port: int,
    num_processes: int,
    process_id: int,
    base_env: Optional[Dict[str, str]] = None,
) -> Dict[str, str]:
    env = dict(base_env if base_env is not None else os.environ)
    env.update(
        {
            "DSTPU_COORDINATOR": f"{coordinator}:{port}",
            "DSTPU_NUM_PROCESSES": str(num_processes),
            "DSTPU_PROCESS_ID": str(process_id),
        }
    )
    return env


def spawn_local(env: Dict[str, str], argv: List[str]) -> "subprocess.Popen":
    """The ``local`` launcher backend: one rank as a direct subprocess on
    this host (reference: the runner's no-ssh localhost path / launch.py
    spawning ranks directly). Used for same-box multi-process runs and for
    exercising the full jax.distributed path without an ssh daemon."""
    return subprocess.Popen(argv, env=env)


def build_ssh_command(host: str, env: Dict[str, str], argv: List[str]) -> List[str]:
    """The per-host remote command (reference: pdsh/OpenMPI runner)."""
    exports = " ".join(
        f"{k}={shlex.quote(v)}"
        for k, v in env.items()
        if k.startswith(("DSTPU_", "JAX_", "TPU_", "PYTHON"))
    )
    remote = f"cd {shlex.quote(os.getcwd())} && {exports} {shlex.join(argv)}"
    return ["ssh", "-o", "StrictHostKeyChecking=no", host, remote]


def wait_and_propagate(procs: List["subprocess.Popen"], poll_s: float = 1.0) -> int:
    """Babysit the per-host processes (reference: the pdsh runner's job
    control): if any host's process exits nonzero, terminate the rest —
    a multi-host SPMD job can't make progress with a dead rank, and the
    surviving ranks would hang in their next collective. SIGINT/SIGTERM
    to the launcher fan out to every host."""
    import signal
    import time

    signaled = []

    def _forward(signum, frame):
        signaled.append(signum)

    def _rc(c: int) -> int:
        """Map a Popen returncode to a launcher exit code, preserving the
        shell's 128+signal convention for signal deaths (Popen reports
        those as -signum) instead of folding them into regular codes."""
        return 128 - c if c < 0 else c

    def _shutdown(rc: int) -> int:
        """terminate → 10s grace → kill, so a rank that traps/ignores
        SIGTERM can't wedge the launcher."""
        for p in procs:
            if p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + 10.0
        for p in procs:
            timeout = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=timeout)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()  # reap promptly post-SIGKILL; no zombies
        return rc

    old = (signal.signal(signal.SIGINT, _forward),
           signal.signal(signal.SIGTERM, _forward))
    try:
        while True:
            if signaled:
                return _shutdown(128 + signaled[0])
            codes = [p.poll() for p in procs]
            if all(c is not None for c in codes):
                return max(_rc(c) for c in codes) if any(codes) else 0
            failed = [c for c in codes if c not in (None, 0)]
            if failed:
                return _shutdown(_rc(failed[0]))
            time.sleep(poll_s)
    finally:
        signal.signal(signal.SIGINT, old[0])
        signal.signal(signal.SIGTERM, old[1])


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="deepspeed_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("--hostfile", default=None)
    parser.add_argument("--include", default="")
    parser.add_argument("--exclude", default="")
    parser.add_argument("--num_nodes", type=int, default=-1)
    parser.add_argument("--master_addr", default=None)
    parser.add_argument("--master_port", type=int, default=DEFAULT_COORD_PORT)
    parser.add_argument("--launcher", default="ssh", choices=["ssh", "local"],
                        help="per-host backend: ssh (remote hosts, default) "
                        "or local (each hostfile entry spawns a rank on THIS "
                        "host; same-box multi-process)")
    parser.add_argument("--dry_run", action="store_true",
                        help="print the launch plan without executing")
    parser.add_argument("script", help="training script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    prog = [sys.executable, args.script, *args.script_args]

    if not args.hostfile:
        # single host, pure SPMD: exec in place
        if args.dry_run:
            print(f"[single-host] exec: {shlex.join(prog)}")
            return 0
        os.execvpe(prog[0], prog, os.environ.copy())

    resources = parse_hostfile(args.hostfile)
    resources = parse_inclusion_exclusion(resources, args.include, args.exclude)
    hosts = list(resources)
    if args.num_nodes > 0:
        hosts = hosts[: args.num_nodes]
    coordinator = args.master_addr or (
        "127.0.0.1" if args.launcher == "local" else hosts[0]
    )

    procs = []
    for pid, host in enumerate(hosts):
        env = build_launch_env(coordinator, args.master_port, len(hosts), pid)
        if args.launcher == "local":
            if args.dry_run:
                print(f"[{host} rank {pid} local] {shlex.join(prog)}")
                continue
            procs.append(spawn_local(env, prog))
            continue
        cmd = build_ssh_command(host, env, prog)
        if args.dry_run:
            print(f"[{host} rank {pid}] {shlex.join(cmd)}")
            continue
        procs.append(subprocess.Popen(cmd))
    if args.dry_run:
        return 0
    return wait_and_propagate(procs)


if __name__ == "__main__":
    raise SystemExit(main())
