from .elasticity import (  # noqa: F401
    compute_elastic_config,
    get_compatible_gpus,
    get_valid_gpus,
)
