"""Batch-size elasticity.

Parity: deepspeed/elasticity/elasticity.py — given candidate micro-batch
sizes and a max global batch, enumerate the chip counts ("gpus" in the
reference; TPU chips here) that can train with an *identical* global batch
size, so a job can scale up/down across preemptions without changing the
math. The algorithm is the reference's: valid global batches are
micro_batch x accumulation-step multiples; pick the batch with the most
compatible world sizes (prefer larger batch on ties per config).
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..config import ElasticityConfig


def get_valid_gpus(batch_size: int, micro_batches: List[int],
                   min_gpus: int, max_gpus: int) -> List[int]:
    """World sizes that divide batch/micro evenly for some micro batch."""
    valid = set()
    for mb in micro_batches:
        if batch_size % mb != 0:
            continue
        max_steps = batch_size // mb
        for gpus in range(min_gpus, max_gpus + 1):
            if max_steps % gpus == 0:
                valid.add(gpus)
    return sorted(valid)


def get_compatible_gpus(
    micro_batches: List[int],
    max_train_batch_size: int,
    min_gpus: int = 1,
    max_gpus: int = 10000,
    prefer_larger: bool = True,
) -> Tuple[List[int], int]:
    """Parity: elasticity._get_compatible_gpus → (valid world sizes, batch)."""
    candidate: Dict[int, List[int]] = {}
    for mb in sorted(micro_batches):
        # multiples of mb up to the cap
        b = (max_train_batch_size // mb) * mb
        while b > 0:
            gpus = get_valid_gpus(b, micro_batches, min_gpus, max_gpus)
            if gpus:
                candidate.setdefault(b, gpus)
            b -= mb
    if not candidate:
        raise ValueError(
            f"no valid batch size under {max_train_batch_size} for "
            f"micro_batches {micro_batches}"
        )
    best = max(
        candidate.items(),
        key=lambda kv: (len(kv[1]), kv[0] if prefer_larger else -kv[0]),
    )
    return best[1], best[0]


def compute_elastic_config(
    ds_config: dict, target_deepspeed_version: str = "", world_size: int = 0
) -> Tuple[int, List[int], int]:
    """Parity: deepspeed.elasticity.compute_elastic_config.

    Returns (final_batch_size, valid_world_sizes, micro_batch_for_world).
    """
    section = ds_config.get("elasticity", {})
    cfg = ElasticityConfig(**{
        k: v for k, v in section.items()
        if k in ElasticityConfig.__dataclass_fields__
    })
    if not cfg.enabled:
        raise ValueError("elasticity section not enabled in config")
    valid_gpus, batch = get_compatible_gpus(
        cfg.micro_batch_sizes,
        cfg.max_train_batch_size,
        cfg.min_gpus,
        cfg.max_gpus,
        cfg.prefer_larger_batch,
    )
    micro = 0
    if world_size:
        if world_size not in valid_gpus:
            raise ValueError(
                f"world size {world_size} incompatible with elastic batch "
                f"{batch} (valid: {valid_gpus})"
            )
        steps = batch // world_size
        for mb in sorted(cfg.micro_batch_sizes, reverse=True):
            if steps % mb == 0:
                micro = mb
                break
    return batch, valid_gpus, micro
