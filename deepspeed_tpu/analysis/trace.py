"""Jaxpr traversal + forward dataflow for shardlint rules.

Everything here is abstract: programs are walked as jaxprs (the IR
``jax.make_jaxpr`` returns), never executed. The dataflow engine is a
boolean forward may-analysis with structural handling of the control-flow
primitives (scan/while/cond/pjit/remat/shard_map/custom_*): loop carries
iterate to a fixpoint, branches join with OR. Rules subclass
:class:`DataflowAnalysis` and override the per-primitive transfer.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Tuple

import jax

_core = jax.core
Jaxpr = _core.Jaxpr
ClosedJaxpr = _core.ClosedJaxpr
Literal = _core.Literal

# primitives that wrap exactly one jaxpr consuming the eqn inputs 1:1
_CALL_LIKE_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def as_jaxpr(j) -> Jaxpr:
    return j.jaxpr if isinstance(j, ClosedJaxpr) else j


def _param_jaxprs(value) -> Iterator[Jaxpr]:
    if isinstance(value, (Jaxpr, ClosedJaxpr)):
        yield as_jaxpr(value)
    elif isinstance(value, (list, tuple)):
        for v in value:
            yield from _param_jaxprs(v)


def eqn_subjaxprs(eqn) -> List[Tuple[str, Jaxpr]]:
    """All (param_name, jaxpr) sub-programs an equation carries."""
    out = []
    for k, v in eqn.params.items():
        for j in _param_jaxprs(v):
            out.append((k, j))
    return out


def iter_jaxprs(root, path: str = "") -> Iterator[Tuple[Jaxpr, str]]:
    """Yield (jaxpr, path) for the program and every nested sub-program."""
    j = as_jaxpr(root)
    yield j, path
    for eqn in j.eqns:
        for k, sub in eqn_subjaxprs(eqn):
            sub_path = f"{path}/{eqn.primitive.name}"
            if k not in ("jaxpr",):
                sub_path += f".{k}"
            yield from iter_jaxprs(sub, sub_path)


def producers(jaxpr: Jaxpr) -> Dict[Any, Any]:
    """Var → producing eqn map for one jaxpr level."""
    out = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[v] = eqn
    return out


def scan_split(eqn):
    """(consts, carries, xs) operand index ranges of a scan eqn."""
    nc = eqn.params["num_consts"]
    ncar = eqn.params["num_carry"]
    return nc, ncar


def axis_names_of(param) -> Tuple[str, ...]:
    """Normalize a collective's axis-name param (str | tuple) to a tuple."""
    if param is None:
        return ()
    if isinstance(param, (tuple, list)):
        return tuple(str(a) for a in param)
    return (str(param),)


def collective_axes(eqn) -> Tuple[str, ...]:
    """Mesh axis names a named collective operates over (else ())."""
    p = eqn.params
    return axis_names_of(p.get("axes") or p.get("axis_name"))


def shard_map_manual_axes(eqn) -> Dict[str, int]:
    """{axis: size} the shard_map body is Manual over (mesh minus auto)."""
    mesh = eqn.params.get("mesh")
    auto = eqn.params.get("auto") or frozenset()
    if mesh is None:
        return {}
    try:
        shape = dict(mesh.shape)
    except Exception:  # noqa: BLE001 — AbstractMesh without concrete shape
        return {}
    return {a: n for a, n in shape.items() if a not in auto}


def names_spec_axes(names_entry) -> Tuple[str, ...]:
    """Flatten a shard_map in_names/out_names entry ({dim: (axes,)}) to
    the set of mesh axes the value is partitioned over."""
    axes: List[str] = []
    for dim_axes in (names_entry or {}).values():
        axes.extend(str(a) for a in dim_axes)
    return tuple(axes)


class DataflowAnalysis:
    """Boolean forward may-analysis over a jaxpr.

    Subclasses override :meth:`transfer` (plain primitives) and optionally
    :meth:`visit` (called for every eqn with its in/out values — the spot
    to emit findings). Control flow is handled structurally here.
    """

    MAX_FIXPOINT_ITERS = 16

    # -- overridables -------------------------------------------------------
    def transfer(self, eqn, in_vals: List[bool]) -> List[bool]:
        return [any(in_vals)] * len(eqn.outvars)

    def visit(self, eqn, in_vals: List[bool], out_vals: List[bool],
              path: str) -> None:
        pass

    # -- engine -------------------------------------------------------------
    def run(self, jaxpr: Jaxpr, in_vals: List[bool], path: str = "") -> List[bool]:
        env: Dict[Any, bool] = {}

        def read(a) -> bool:
            if isinstance(a, Literal):
                return False
            return env.get(a, False)

        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = bool(val)
        for cv in jaxpr.constvars:
            env[cv] = False
        for eqn in jaxpr.eqns:
            ivals = [read(a) for a in eqn.invars]
            ovals = self._eqn_out(eqn, ivals, path)
            self.visit(eqn, ivals, ovals, path)
            for v, val in zip(eqn.outvars, ovals):
                env[v] = bool(val)
        return [read(v) for v in jaxpr.outvars]

    def _eqn_out(self, eqn, ivals: List[bool], path: str) -> List[bool]:
        name = eqn.primitive.name
        sub = f"{path}/{name}"
        if name == "scan":
            body = as_jaxpr(eqn.params["jaxpr"])
            nc, ncar = scan_split(eqn)
            consts, carry = ivals[:nc], ivals[nc:nc + ncar]
            xs = ivals[nc + ncar:]
            outs = carry + [False] * (len(eqn.outvars) - ncar)
            for _ in range(self.MAX_FIXPOINT_ITERS):
                outs = self.run(body, consts + carry + xs, sub)
                new_carry = [c or o for c, o in zip(carry, outs[:ncar])]
                if new_carry == carry:
                    break
                carry = new_carry
            return [c or o for c, o in zip(carry, outs[:ncar])] + outs[ncar:]
        if name == "while":
            body = as_jaxpr(eqn.params["body_jaxpr"])
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            bconsts = ivals[cn:cn + bn]
            carry = ivals[cn + bn:]
            for _ in range(self.MAX_FIXPOINT_ITERS):
                outs = self.run(body, bconsts + carry, sub)
                new_carry = [c or o for c, o in zip(carry, outs)]
                if new_carry == carry:
                    break
                carry = new_carry
            return carry
        if name == "cond":
            branches = eqn.params["branches"]
            operands = ivals[1:]
            outs = None
            for br in branches:
                o = self.run(as_jaxpr(br), operands, sub)
                outs = o if outs is None else [a or b for a, b in zip(outs, o)]
            return outs if outs is not None else []
        if name == "shard_map":
            return self.run(as_jaxpr(eqn.params["jaxpr"]), ivals, sub)
        for key in _CALL_LIKE_KEYS:
            if key in eqn.params and isinstance(
                eqn.params[key], (Jaxpr, ClosedJaxpr)
            ):
                body = as_jaxpr(eqn.params[key])
                if len(body.invars) == len(ivals):
                    return self.run(body, ivals, sub)
                if len(body.invars) < len(ivals):
                    # call-like wrappers that prepend consts (custom_vjp):
                    # align the trailing operands
                    outs = self.run(body, ivals[-len(body.invars):], sub)
                    return outs
                break  # structure unknown — fall through to transfer
        return self.transfer(eqn, ivals)
