"""Differential jaxpr parity prover (ISSUE 15).

Every headline claim in this repo — paged == contiguous, ring == XLA
reference, moe_a2a stock == chunked, wire codec == full-width — is a
*bitwise-parity* contract between TWO FORMS of one program, proven at
runtime by CPU-mesh replay oracles. This module proves the structural
half statically, in seconds, from the two abstract traces alone:

    pair  = one FormPair (two trace thunks + declared rewrite classes)
    cert  = prove_parity(pair)   # ParityCertificate
    cert.ok → a static parity certificate: the two forms' COMPUTE
              ANCHORS (dots, reductions, sampling, RNG consumption,
              collectives, kernels) agree as multisets modulo the
              declared rewrite-equivalence classes
    else   → the first divergent op, with both provenances

What "modulo" means — the rewrite classes a pair may declare:

- ``addressing``   gather/scatter/dynamic-slice traffic is elided: the
  two forms address the same bytes differently (page tables vs
  contiguous regions). Content equality is the runtime oracle's job;
  R2/R4 cover the carry/donation structure.
- ``chunking``     a compute/reduce anchor may split into k
  same-shaped chunks (the decomposed-ring sub-matmuls): buckets that
  disagree are re-checked by per-(op, dtype) mass — count × element
  volume — which chunking preserves exactly.
- ``collective_decomposition``  a run of ppermute hops over axis A is
  one logical collective over A (the R3/R7 laws): collective anchors
  compare by axis-set presence, not by op spelling or hop count.
- ``codec``        a wire codec may add scale computations
  (``reduce_max`` amax chains) and move int8/int4 payloads where the
  full-width form moves floats: amax reductions are elided and
  collective payload dtypes are not compared (wire error bounds are
  the codec's own property-tested contract, docs/wires.md).
- ``implicit_collectives``  a GSPMD reference form's collectives are
  inserted at COMPILE time and invisible in its traced jaxpr (the
  planner's documented bias), so collective anchors present on only
  the explicit-collective side are folded — the reduction and compute
  anchors still compare.
- ``recompute``    a decomposed overlap form may REPLICATE compute to
  buy wire overlap (the moe a2a ride re-runs expert FFNs per dp
  member): a compute-family mass ratio up to ``recompute_bound`` is
  folded; beyond it (or a missing block of work) still diverges.

Anchors NEVER elided: dot_general mass/shape, reduce_sum/cumsum
grouping, scatter-add (accumulation into shared destinations), RNG
consumption counts (random_bits/random_split — the R9 chain), sampling
ops (sort/argmax/top_k), pallas kernel output signatures. A mismatch in
a reduction/collective/accumulation bucket is labeled rule R10
(reduction-order: the grouping changed); anything else is labeled
"parity".

Shapes are normalized: unit dims dropped, dim order sorted (transpose
normalization), and each form's ``dim_aliases`` map form-specific
extents (the paged arena's pages·page_size vs the contiguous capacity)
to shared symbols, so the SAME logical extent spelled differently never
reads as divergence.

Engines declare their pairs through ``parity_pairs()`` (next to
``analytic_streams()``); :func:`config_parity_pairs` builds the pairs a
ds_config declares without constructing a real engine. CLI:
``tools/paritycheck.py --all-pairs`` (exit 1 on divergence).
"""

from __future__ import annotations

import copy
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .trace import as_jaxpr, collective_axes, eqn_subjaxprs

# ---------------------------------------------------------------- anchors
# always-transparent ops: elementwise math, layout, casts, literals
_ELIDE = {
    "add", "sub", "mul", "div", "neg", "exp", "log", "log1p", "tanh",
    "logistic", "erf", "erf_inv", "rsqrt", "sqrt", "pow", "integer_pow",
    "max", "min", "clamp", "select_n", "eq", "ne", "lt", "le", "gt", "ge",
    "and", "or", "not", "xor", "sign", "floor", "ceil", "round",
    "is_finite", "abs", "rem", "convert_element_type",
    "bitcast_convert_type", "broadcast_in_dim", "reshape", "transpose",
    "squeeze", "expand_dims", "rev", "iota", "copy", "device_put",
    "stop_gradient", "shift_left", "shift_right_logical",
    "shift_right_arithmetic", "concatenate", "pad", "slice",
    "random_wrap", "random_unwrap", "nextafter", "population_count",
    "clz", "real", "imag", "square", "cbrt", "atan2", "exp2",
    # placement/control annotations — schedule shape, not compute
    "sharding_constraint", "axis_index", "optimization_barrier",
    # autodiff-inserted accumulation adds (the transpose of fan-out):
    # elementwise, present wherever a value has two consumers
    "add_any", "add_n",
}
_COMPUTE = {"dot_general", "conv_general_dilated"}
_REDUCE = {
    "reduce_sum", "reduce_prod", "cumsum", "cumprod", "cumlogsumexp",
}
_REDUCE_EXTREMA = {"reduce_max", "reduce_min", "reduce_and", "reduce_or",
                   "cummax", "cummin", "argmax", "argmin"}
_ACCUM = {"scatter-add", "scatter-mul"}
_SAMPLING = {"sort", "top_k"}
_RNG = {"random_bits", "random_split", "random_fold_in", "random_seed"}
_COLLECTIVE = {
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "reduce_scatter", "psum_scatter", "pgather", "pbroadcast",
}
_ADDRESSING = {
    "gather", "scatter", "scatter-max", "scatter-min",
    "dynamic_slice", "dynamic_update_slice",
}
_KERNEL = {"pallas_call"}


@dataclass
class Anchor:
    kind: str            # compute|reduce|accum|sampling|rng|collective|
    #                      addressing|kernel
    op: str
    sig: Tuple           # normalized signature (dtypes + aliased dims)
    path: str
    weight: int = 1      # scan-length multiplier
    mass: float = 0.0    # count-invariant volume (chunk folding)
    order: int = 0       # first appearance index (divergence reporting)


def _dims(aval) -> Tuple:
    """Sorted non-unit dims — RAW (numeric). Dim aliases apply at
    compare time (second pass), never at extraction, so a form-specific
    extent that happens to equal an unrelated model dim cannot smear the
    alias over anchors the strict pass already matches."""
    shape = tuple(getattr(aval, "shape", ()) or ())
    return tuple(sorted(int(d) for d in shape if d != 1))


def _volume(aval) -> float:
    """RAW element volume — like _dims, aliases never touch masses: an
    unrelated model dim that happens to equal one side's aliased extent
    must not skew that side's mass (masses only decide the chunking
    fold, whose families the alias pass has already had its shot at)."""
    v = 1.0
    for d in tuple(getattr(aval, "shape", ()) or ()):
        v *= float(d)
    return v


def _avals(vars_):
    sig, vol = [], 0.0
    for v in vars_:
        aval = getattr(v, "aval", None)
        if aval is None:
            continue
        sig.append((str(getattr(aval, "dtype", "?")), _dims(aval)))
        vol += _volume(aval)
    return tuple(sorted(sig)), vol


def alias_sig(sig, aliases: Dict[int, str]):
    """Apply a dim-alias map to a (nested-tuple) signature. Ints map to
    their shared symbols; every tuple is re-sorted by repr afterwards so
    both sides canonicalize identically."""
    if isinstance(sig, int):
        return aliases.get(sig, sig)
    if isinstance(sig, tuple):
        return tuple(sorted(
            (alias_sig(e, aliases) for e in sig), key=repr
        ))
    return sig


def extract_anchors(closed_jaxpr, rewrites: frozenset,
                    dim_aliases: Optional[Dict[int, str]] = None
                    ) -> List[Anchor]:
    """Flatten one traced program into its normalized anchor list.
    ``dim_aliases`` is accepted for signature stability but unused here:
    aliases apply only in prove_parity's second compare pass."""
    out: List[Anchor] = []
    counter = [0]

    def walk(jaxpr, path: str, weight: int) -> None:
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            sub_w = weight
            if name == "scan":
                length = eqn.params.get("length") or 1
                sub_w = weight * max(int(length), 1)
            elif name == "shard_map":
                # the body traces PER-MEMBER local shapes; every manual
                # member executes it, so logical mass scales by the
                # manual axis product (the GSPMD twin traces global
                # shapes once)
                from .trace import shard_map_manual_axes

                mult = 1
                for n in shard_map_manual_axes(eqn).values():
                    mult *= max(int(n), 1)
                sub_w = weight * mult
            if name in _KERNEL:
                # opaque: the kernel's OUTPUT signature is the anchor;
                # its body is a Mosaic program, not step structure
                sig, vol = _avals(eqn.outvars)
                _emit("kernel", name, sig, path, weight, vol)
                continue
            subs = eqn_subjaxprs(eqn)
            if subs:
                for k, sub in subs:
                    sub_path = f"{path}/{name}"
                    if k not in ("jaxpr",):
                        sub_path += f".{k}"
                    walk(sub, sub_path, sub_w)
                continue
            _classify(eqn, name, path, weight)

    def _emit(kind, op, sig, path, weight, vol):
        counter[0] += 1
        out.append(Anchor(kind=kind, op=op, sig=sig, path=path,
                          weight=weight, mass=vol * weight,
                          order=counter[0]))

    def _classify(eqn, name, path, weight):
        if name in _ELIDE:
            return
        where = f"{path}/{name}" if path else name
        if name in _COMPUTE:
            sig_in, _ = _avals(eqn.invars)
            sig_out, vol_out = _avals(eqn.outvars)
            # mass = FLOP proxy (out volume × contraction extent): exact
            # under both column-chunking (out splits) and row-chunking
            # (contraction splits), which plain volumes are not
            contract = 1.0
            dn = eqn.params.get("dimension_numbers")
            if name == "dot_general" and dn:
                (lc, _rc), _batch = dn
                shape = tuple(
                    getattr(getattr(eqn.invars[0], "aval", None),
                            "shape", ()) or ()
                )
                for d in lc:
                    if d < len(shape):
                        contract *= float(shape[d])
            _emit("compute", name, (sig_in, sig_out), where, weight,
                  vol_out * contract)
            return
        if name in _REDUCE or name in _REDUCE_EXTREMA:
            if "codec" in rewrites and name in ("reduce_max", "reduce_min"):
                return  # codec amax/scale chains
            kind = "reduce" if name in _REDUCE else "sampling"
            sig_in, vol_in = _avals(eqn.invars)
            sig_out, _ = _avals(eqn.outvars)
            _emit(kind, name, (sig_in, sig_out), where, weight, vol_in)
            return
        if name in _ACCUM:
            sig, vol = _avals(eqn.outvars)
            _emit("accum", name, sig, where, weight, vol)
            return
        if name in _SAMPLING:
            sig, vol = _avals(eqn.invars)
            _emit("sampling", name, sig, where, weight, vol)
            return
        if name in _RNG:
            sig, vol = _avals(eqn.outvars)
            _emit("rng", name, sig, where, weight, vol)
            return
        if name in _COLLECTIVE:
            axes = tuple(sorted(collective_axes(eqn)))
            if "collective_decomposition" in rewrites:
                # one logical collective over these axes, any spelling
                _emit("collective", "collective", (axes,), where, weight,
                      0.0)
            else:
                sig, vol = _avals(eqn.outvars)
                _emit("collective", name, (axes, sig), where, weight, vol)
            return
        if name in _ADDRESSING:
            if "addressing" in rewrites:
                return
            sig, vol = _avals(eqn.outvars)
            _emit("addressing", name, sig, where, weight, vol)
            return
        # unknown primitive: keep it visible (strict by default)
        sig, vol = _avals(eqn.outvars)
        _emit("other", name, sig, where, weight, vol)

    walk(as_jaxpr(closed_jaxpr), "", 1)
    return out


# ------------------------------------------------------------------ pairs
@dataclass
class FormPair:
    """One declared-bitwise form pair: two trace thunks + the rewrite
    classes under which their programs are expected to agree."""

    name: str
    contract: str                      # the runtime-proven claim
    form_a: str
    form_b: str
    trace_a: Callable[[], Any]         # -> closed_jaxpr (or (closed, ...))
    trace_b: Callable[[], Any]
    rewrites: frozenset = frozenset()
    dim_aliases_a: Dict[int, str] = field(default_factory=dict)
    dim_aliases_b: Dict[int, str] = field(default_factory=dict)
    # with the "recompute" rewrite: the largest compute-mass ratio the
    # decomposed form may pay for overlap (bounded — a missing block of
    # work still diverges)
    recompute_bound: float = 16.0
    note: str = ""


@dataclass
class Divergence:
    op: str
    kind: str
    sig: str
    count_a: int
    count_b: int
    where_a: str                       # provenance (or "<absent>")
    where_b: str
    rule: str                          # "R10" for reductions, else "parity"

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)

    def format(self) -> str:
        return (
            f"[{self.rule}] {self.op} ({self.kind}) {self.sig}: "
            f"{self.count_a}x @ {self.where_a} vs "
            f"{self.count_b}x @ {self.where_b}"
        )


@dataclass
class ParityCertificate:
    pair: str
    contract: str
    form_a: str
    form_b: str
    ok: bool
    rewrites: Tuple[str, ...]
    anchors_a: int
    anchors_b: int
    matched_buckets: int
    folded_buckets: int                # repaired by chunking/decomposition
    divergences: List[Divergence]
    seconds: float

    @property
    def first_divergence(self) -> Optional[Divergence]:
        return self.divergences[0] if self.divergences else None

    def to_dict(self) -> Dict[str, Any]:
        return {
            "pair": self.pair,
            "contract": self.contract,
            "forms": [self.form_a, self.form_b],
            "ok": self.ok,
            "rewrites": list(self.rewrites),
            "anchors": [self.anchors_a, self.anchors_b],
            "matched_buckets": self.matched_buckets,
            "folded_buckets": self.folded_buckets,
            "divergences": [d.to_dict() for d in self.divergences],
            "seconds": round(self.seconds, 3),
        }

    def format(self) -> str:
        if self.ok:
            folded = (
                f", {self.folded_buckets} folded" if self.folded_buckets
                else ""
            )
            return (
                f"paritycheck: {self.pair}: CERTIFIED "
                f"[{self.form_a} == {self.form_b} modulo "
                f"{','.join(self.rewrites) or 'nothing'}] "
                f"({self.matched_buckets} buckets{folded}, "
                f"{self.anchors_a}/{self.anchors_b} anchors, "
                f"{self.seconds:.2f}s)"
            )
        lines = [
            f"paritycheck: {self.pair}: DIVERGENT "
            f"[{self.form_a} vs {self.form_b}] "
            f"({len(self.divergences)} divergent bucket(s), "
            f"{self.seconds:.2f}s)"
        ]
        lines.extend("  " + d.format() for d in self.divergences[:8])
        return "\n".join(lines)


def _closed_of(traced):
    """Trace thunks may return a bare closed_jaxpr or a tuple whose
    first element is one (trace_serving_step/trace_train_step style)."""
    if isinstance(traced, tuple):
        return traced[0]
    return traced


def _bucket(anchors: Sequence[Anchor]):
    buckets: Dict[Tuple, Dict[str, Any]] = {}
    for a in anchors:
        key = (a.kind, a.op, a.sig)
        b = buckets.setdefault(key, {
            "count": 0, "mass": 0.0, "path": a.path, "order": a.order,
        })
        b["count"] += a.weight
        b["mass"] += a.mass
    return buckets


def prove_parity(pair: FormPair) -> ParityCertificate:
    """Trace both forms, normalize, compare anchor multisets modulo the
    pair's rewrite classes; certify or report the first divergent op."""
    t0 = time.time()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed_a = _closed_of(pair.trace_a())
        closed_b = _closed_of(pair.trace_b())
    anch_a = extract_anchors(closed_a, pair.rewrites, pair.dim_aliases_a)
    anch_b = extract_anchors(closed_b, pair.rewrites, pair.dim_aliases_b)
    ba, bb = _bucket(anch_a), _bucket(anch_b)

    matched = folded = 0
    mismatched_a: List[Tuple] = []
    mismatched_b: List[Tuple] = []
    for key in sorted(set(ba) | set(bb), key=str):
        ca = ba.get(key, {}).get("count", 0)
        cb = bb.get(key, {}).get("count", 0)
        if ca == cb:
            matched += 1
        else:
            if key in ba:
                mismatched_a.append(key)
            if key in bb:
                mismatched_b.append(key)

    # alias pass: only buckets the strict pass left over get their
    # form-specific extents mapped to shared symbols (KV_EXT …), so an
    # extent that coincides with an unrelated model dim can't collide
    def _canonize(keys, buckets, aliases):
        can: Dict[Tuple, Dict[str, Any]] = {}
        for key in keys:
            kind, op, sig = key
            ck = (kind, op, alias_sig(sig, aliases))
            tgt = can.setdefault(ck, {
                "count": 0, "mass": 0.0,
                "path": buckets[key]["path"],
                "order": buckets[key]["order"],
            })
            tgt["count"] += buckets[key]["count"]
            tgt["mass"] += buckets[key]["mass"]
            tgt["order"] = min(tgt["order"], buckets[key]["order"])
        return can

    ba2 = _canonize(mismatched_a, ba, pair.dim_aliases_a)
    bb2 = _canonize(mismatched_b, bb, pair.dim_aliases_b)
    mismatched: List[Tuple] = []
    for key in sorted(set(ba2) | set(bb2), key=str):
        ca = ba2.get(key, {}).get("count", 0)
        cb = bb2.get(key, {}).get("count", 0)
        if ca == cb:
            matched += 1
        else:
            mismatched.append(key)
    ba, bb = ba2, bb2  # divergence reporting reads the canonical view

    # chunking fold: a mismatched (kind, op) family whose per-side MASS
    # agrees is the same computation split differently — exactly what
    # chunked sub-matmuls/reductions do
    remaining: List[Tuple] = []
    if "chunking" in pair.rewrites and mismatched:
        fams: Dict[Tuple[str, str], List[Tuple]] = {}
        for key in mismatched:
            fams.setdefault((key[0], key[1]), []).append(key)
        for fam, keys in fams.items():
            mass_a = sum(ba[k]["mass"] for k in keys if k in ba)
            mass_b = sum(bb[k]["mass"] for k in keys if k in bb)
            if mass_a > 0 and abs(mass_a - mass_b) <= 1e-6 * max(
                mass_a, mass_b
            ):
                folded += len(keys)
            elif (
                "recompute" in pair.rewrites
                and fam[0] == "compute"
                and mass_a > 0 and mass_b > 0
                and max(mass_a, mass_b) / min(mass_a, mass_b)
                <= pair.recompute_bound
            ):
                # the decomposed form replicates compute to buy overlap
                # (expert FFNs re-run per dp member under the a2a ride)
                # — bounded, so a missing block of work still diverges
                folded += len(keys)
            else:
                remaining.extend(keys)
        mismatched = remaining
        remaining = []

    # collective-decomposition fold: hop-count differences over the same
    # axis set are one logical collective (the extract step already
    # unified spellings; here presence-on-both-sides is enough)
    if "collective_decomposition" in pair.rewrites and mismatched:
        for key in mismatched:
            if key[0] == "collective" and key in ba and key in bb:
                folded += 1
            else:
                remaining.append(key)
        mismatched = remaining
        remaining = []

    # implicit-collectives fold: a GSPMD reference form's collectives
    # are inserted at COMPILE time and invisible to the traced jaxpr
    # (the planner's documented bias), so an explicit-collective form
    # legitimately shows wires its twin cannot. Declared per pair; the
    # reduction/compute anchors still compare.
    if "implicit_collectives" in pair.rewrites and mismatched:
        for key in mismatched:
            if key[0] == "collective" and (key not in ba or key not in bb):
                folded += 1
            else:
                remaining.append(key)
        mismatched = remaining

    divergences: List[Divergence] = []
    for key in sorted(
        mismatched,
        key=lambda k: min(
            ba.get(k, {}).get("order", 1 << 30),
            bb.get(k, {}).get("order", 1 << 30),
        ),
    ):
        kind, op, sig = key
        rule = "R10" if kind in ("reduce", "collective", "accum") \
            else "parity"
        divergences.append(Divergence(
            op=op, kind=kind, sig=str(sig),
            count_a=ba.get(key, {}).get("count", 0),
            count_b=bb.get(key, {}).get("count", 0),
            where_a=ba.get(key, {}).get("path", "<absent>"),
            where_b=bb.get(key, {}).get("path", "<absent>"),
            rule=rule,
        ))
    return ParityCertificate(
        pair=pair.name,
        contract=pair.contract,
        form_a=pair.form_a,
        form_b=pair.form_b,
        ok=not divergences,
        rewrites=tuple(sorted(pair.rewrites)),
        anchors_a=len(anch_a),
        anchors_b=len(anch_b),
        matched_buckets=matched,
        folded_buckets=folded,
        divergences=divergences,
        seconds=time.time() - t0,
    )


# ----------------------------------------------------- pair constructors
def _serving_trace_thunk(cfg_dict, model):
    def thunk():
        from ..serving.engine import trace_serving_step

        return trace_serving_step(model, copy.deepcopy(cfg_dict))

    return thunk


def _train_trace_thunk(cfg_dict, model):
    def thunk():
        import deepspeed_tpu
        import deepspeed_tpu.comm as comm
        from .shardlint import trace_train_step

        comm.destroy_process_group()
        engine, *_ = deepspeed_tpu.initialize(
            model=model, config=copy.deepcopy(cfg_dict),
            abstract_init=True,
        )
        try:
            return trace_train_step(engine)
        finally:
            engine.destroy()

    return thunk


def _serving_kv_extents(ds, mcfg) -> Tuple[int, int]:
    """(contiguous capacity, paged per-slot view extent) for the
    paged-vs-contiguous dim aliasing."""
    from ..serving.engine import _align_cache

    srv = ds.serving
    max_tokens = min(int(srv.max_tokens), mcfg.max_seq_len)
    capacity = _align_cache(max_tokens + int(srv.token_budget))
    pages_per_slot = srv.pages_per_slot(max_tokens)
    return capacity, pages_per_slot * int(srv.page_size)


def config_parity_pairs(config, model) -> List[FormPair]:
    """The form pairs a ds_config declares (ISSUE 15): paged vs
    contiguous and moe stock vs chunked for serving configs; TP ring vs
    XLA reference, wire codec vs full-width, and moe_a2a overlapped vs
    stock for training configs. Each pair's thunks re-trace abstractly —
    no state, no compile."""
    from ..config import DeepSpeedConfig

    ds = (
        config if isinstance(config, DeepSpeedConfig)
        else DeepSpeedConfig(copy.deepcopy(config))
    )
    raw = copy.deepcopy(ds.raw if hasattr(ds, "raw") else config)
    pairs: List[FormPair] = []
    mcfg = getattr(model, "config", None)

    if ds.serving.enabled:
        # fleet routing is host-side (per-replica steps are identical);
        # the contiguous twin must also shed it — disaggregation
        # requires the paged arena by validation
        srv = {
            k: v for k, v in dict(raw.get("serving") or {}).items()
            if k != "fleet"
        }
        # ---- paged vs contiguous (always constructible) ----------------
        paged_raw = copy.deepcopy(raw)
        paged_raw["serving"] = dict(srv, paged=True)
        contig_raw = copy.deepcopy(raw)
        contig_raw["serving"] = {
            k: v for k, v in srv.items()
            if k not in ("paged", "page_size", "num_pages")
        }
        cap, paged_ext = _serving_kv_extents(
            DeepSpeedConfig(copy.deepcopy(paged_raw)), mcfg
        )
        pairs.append(FormPair(
            name="serving/paged-vs-contiguous",
            contract=(
                "the block-paged arena step emits token-for-token the "
                "contiguous arena step (tests/test_serving_paged.py, "
                "BITWISE)"
            ),
            form_a="paged",
            form_b="contiguous",
            trace_a=_serving_trace_thunk(paged_raw, model),
            trace_b=_serving_trace_thunk(contig_raw, model),
            rewrites=frozenset({"addressing", "chunking"}),
            dim_aliases_a={paged_ext: "KV_EXT"},
            dim_aliases_b={cap: "KV_EXT"},
            note="per-slot paged views vs the contiguous capacity are "
                 "the same logical KV extent (KV_EXT)",
        ))
        # ---- moe stock vs chunked (when the ring can actually run) -----
        if mcfg is not None and getattr(mcfg, "is_moe", False):
            from ..serving.engine import resolve_moe_a2a_form, \
                serving_ep_size
            from ..comm.topology import MeshTopology, ParallelDims
            import jax
            import jax.numpy as jnp

            ep = serving_ep_size(ds.moe, mcfg)
            if ep > 1:
                topo = MeshTopology(
                    dims=ParallelDims(
                        tp=max(int(ds.tensor_parallel.tp_size), 1), ep=ep
                    ),
                    devices=jax.devices()[
                        :max(int(ds.tensor_parallel.tp_size), 1) * ep
                    ],
                )
                resolved = resolve_moe_a2a_form(
                    "chunked", mcfg, topo, int(ds.serving.token_budget),
                    jnp.dtype(ds.compute_dtype).itemsize,
                    max_slots=int(ds.serving.max_slots),
                )
                if resolved == "chunked":
                    stock_raw = copy.deepcopy(raw)
                    stock_raw["serving"] = dict(srv, moe_a2a="stock")
                    chunk_raw = copy.deepcopy(raw)
                    chunk_raw["serving"] = dict(srv, moe_a2a="chunked")
                    pairs.append(FormPair(
                        name="serving/moe-a2a-stock-vs-chunked",
                        contract=(
                            "the chunked-ppermute expert combine ride "
                            "equals the stock-collectives exchange "
                            "(tests/test_serving_moe.py, BITWISE)"
                        ),
                        form_a="stock",
                        form_b="chunked",
                        trace_a=_serving_trace_thunk(stock_raw, model),
                        trace_b=_serving_trace_thunk(chunk_raw, model),
                        rewrites=frozenset({
                            "addressing", "chunking",
                            "collective_decomposition",
                            "implicit_collectives",
                        }),
                    ))
        return pairs

    # ---------------- training configs ----------------------------------
    tp_cfg = ds.tensor_parallel
    if getattr(tp_cfg, "overlap_comm", False) and \
            int(tp_cfg.tp_size) > 1:
        on_raw = copy.deepcopy(raw)
        off_raw = copy.deepcopy(raw)
        off_raw.setdefault("tensor_parallel", {})
        off_raw["tensor_parallel"] = dict(
            off_raw["tensor_parallel"], overlap_comm=False
        )
        pairs.append(FormPair(
            name="train/tp-ring-vs-xla",
            contract=(
                "the decomposed collective-matmul rings equal the "
                "GSPMD/XLA reference projections "
                "(tests/test_tp_overlap.py, BITWISE)"
            ),
            form_a="ring",
            form_b="xla",
            trace_a=_train_trace_thunk(on_raw, model),
            trace_b=_train_trace_thunk(off_raw, model),
            rewrites=frozenset({
                "addressing", "chunking", "collective_decomposition",
                "implicit_collectives",
            }),
        ))
    # gate on the RESOLVED flag: a dict-valued overlap_a2a section with
    # enabled=false must not declare a vacuous pair of identical forms
    _ov = getattr(ds.moe, "overlap_a2a", None)
    if bool(getattr(_ov, "enabled", _ov)):
        on_raw = copy.deepcopy(raw)
        off_raw = copy.deepcopy(raw)
        off_moe = dict(off_raw.get("moe") or {})
        ov = off_moe.get("overlap_a2a")
        if isinstance(ov, dict):
            off_moe["overlap_a2a"] = dict(ov, enabled=False)
        else:
            off_moe["overlap_a2a"] = False
        off_raw["moe"] = off_moe
        pairs.append(FormPair(
            name="train/moe-a2a-stock-vs-chunked",
            contract=(
                "the chunked-ppermute expert exchange equals the stock "
                "GSPMD all-to-alls (tests/test_moe_a2a_overlap.py, "
                "BITWISE)"
            ),
            form_a="chunked",
            form_b="stock",
            trace_a=_train_trace_thunk(on_raw, model),
            trace_b=_train_trace_thunk(off_raw, model),
            rewrites=frozenset({
                "addressing", "chunking", "collective_decomposition",
                "implicit_collectives", "recompute",
            }),
            note="the chunked ride recomputes expert FFNs per dp member "
                 "to hide the exchange — compute mass is traded for "
                 "wire (docs/overlap.md), bounded by recompute_bound",
        ))
    zero = raw.get("zero_optimization") or {}
    wired = [
        k for k in ("grad_wire", "param_wire")
        if str(zero.get(k, "fp32")).lower() not in ("fp32", "off", "none",
                                                    "false")
    ] or (["grad_wire"] if zero.get("zero_quantized_gradients") else []) \
        + (["param_wire"] if zero.get("zero_quantized_weights") else [])
    if wired:
        codec_raw = copy.deepcopy(raw)
        full_raw = copy.deepcopy(raw)
        fz = dict(full_raw.get("zero_optimization") or {})
        for k in ("grad_wire", "param_wire"):
            fz[k] = "fp32"
        fz.pop("zero_quantized_gradients", None)
        fz.pop("zero_quantized_weights", None)
        full_raw["zero_optimization"] = fz
        pairs.append(FormPair(
            name="train/wire-codec-vs-full-width",
            contract=(
                "the int8/int4 wire collectives carry the same "
                "reduction structure as the fp32 full-width baseline "
                "(tests/test_wires.py; error within the codec's "
                "property-tested bound)"
            ),
            form_a="codec",
            form_b="fp32",
            trace_a=_train_trace_thunk(codec_raw, model),
            trace_b=_train_trace_thunk(full_raw, model),
            rewrites=frozenset({
                "addressing", "chunking", "collective_decomposition",
                "implicit_collectives", "codec",
            }),
        ))
    if zero.get("hierarchical_wire"):
        hier_raw = copy.deepcopy(raw)
        flat_raw = copy.deepcopy(raw)
        flat_raw["zero_optimization"] = dict(
            flat_raw.get("zero_optimization") or {}, hierarchical_wire=False
        )
        pairs.append(FormPair(
            name="train/grad-rs-2hop-vs-flat",
            contract=(
                "the two-hop intra-then-inter grad reduce-scatter carries "
                "the same reduction structure as the flat single-ring RS "
                "over the joint data axes (tests/test_wires.py; codec "
                "forms within the property-tested bound)"
            ),
            form_a="2hop",
            form_b="flat",
            trace_a=_train_trace_thunk(hier_raw, model),
            trace_b=_train_trace_thunk(flat_raw, model),
            rewrites=frozenset({
                "addressing", "chunking", "collective_decomposition",
                "implicit_collectives", "codec",
            }),
            note="on a hybrid mesh the 2-hop form keeps the DCN hop to "
                 "1/intra-size of the payload; R12 flags the flat form "
                 "when a data axis is DCN-tagged",
        ))
    return pairs
