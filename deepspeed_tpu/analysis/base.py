"""Shared vocabulary of the shardlint subsystem: findings, context, report.

Kept separate from shardlint.py so rule modules (analysis/rules/*) can
import it without a circular import through the driver.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

ERROR = "error"
WARNING = "warning"


@dataclass
class Finding:
    """One hazard surfaced by a rule.

    rule: registry id ("R2"); severity: "error" | "warning"; where: a
    jaxpr path like "/scan/shard_map" locating the offending equation;
    source: which linted program produced it (engine/config/fixture name).
    """

    rule: str
    severity: str
    message: str
    where: str = ""
    source: str = ""

    def format(self) -> str:
        loc = f" @ {self.where}" if self.where else ""
        return f"[{self.rule}:{self.severity}] {self.source}{loc}: {self.message}"

    def to_dict(self) -> Dict[str, str]:
        return {
            "rule": self.rule,
            "severity": self.severity,
            "message": self.message,
            "where": self.where,
            "source": self.source,
        }


@dataclass
class LintContext:
    """Everything a rule may consult about one traced program.

    closed_jaxpr: the program (jax.core.ClosedJaxpr).
    mesh: the authoritative mesh the program is expected to run on
        (engine topology mesh); rules compare embedded shard_map meshes
        against it. None → skip mesh-agreement checks.
    arg_shardings: Var → sharding for top-level invars whose placement is
        known (from ShapeDtypeStruct shardings / engine state shardings).
        Duck-typed: rules only read ``.spec`` / ``.memory_kind``.
    master_pairs: (invar_index, outvar_index, label) triples naming f32
        master-state leaves that must round-trip the step at full
        precision (R5).
    source: display name for findings.

    Cost-planner evidence (analysis/cost — rules R6/R8):

    hbm_budget_bytes: per-device HBM capacity to check the plan's peak
        against; None disables R6 entirely (the default — only
        budget-aware drivers like tools/shardplan.py set it).
    streams: declared-overlapped analytic streams keyed by name, each
        ``{"kind": "offload"|"ici", "bytes_per_step": float,
        "per_device_bytes_per_step": float, "overlapped": bool, ...}``
        (engine.analytic_streams() produces them). R8 checks every
        ``overlapped`` stream against the step's compute window.
    hardware: a cost.HardwareModel (None → detect per-generation
        defaults + bench env overrides).
    link_kinds: mesh axis → "ici" | "dcn" (MeshTopology.link_kinds on
        hybrid meshes). The planner prices collectives whose ring
        traverses a DCN-tagged axis at ``hardware.dcn_bw``, and rules
        R12/R13 read it to spot flat collectives / overlap claims that
        ignore the slow fabric. Empty (the default) means an all-ICI
        mesh — R12/R13 are silent and pricing is unchanged.
    donated_invars: flat top-level invar indices donated at the jit
        boundary (the planner's buffer-reuse credit follows R4's
        donation reasoning).
    invar_groups: state-group name → flat invar index range, so the
        plan's byte columns split exactly like the engine state.

    RNG / trace-stability evidence (rules R9/R11 — armed by drivers):

    claims_keyfree: the traced program claims key-free bitwiseness (an
        eval/serving path whose outputs must not depend on any PRNG key
        — the PR-14 gating contract). When True, R9 flags EVERY
        key-consuming site; default False (training/sampling programs
        consume keys legitimately).
    required_traced: argument names that must be TRACED inputs of the
        step (per-request/per-tick host state — slot occupancy vectors,
        spec_len, cow_src). R11 checks each against ``traced_manifest``;
        empty disables R11 (the default — only the engine/serving trace
        drivers know the step's argument contract).
    traced_manifest: argument name → flat top-level invar index range
        actually traced (same layout as ``invar_groups``; the engine
        trace reuses invar_groups as its manifest).

    (Other donation hazards need no context field: R4 reads each pjit
    equation's own ``donated_invars`` param, and the jit-boundary
    donation audit lives in shardlint.lint_engine, which has the engine.)
    """

    closed_jaxpr: Any
    mesh: Any = None
    arg_shardings: Dict[Any, Any] = field(default_factory=dict)
    master_pairs: Sequence[Tuple[int, int, str]] = ()
    source: str = "<jaxpr>"
    hbm_budget_bytes: Optional[float] = None
    streams: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    hardware: Any = None
    link_kinds: Dict[str, str] = field(default_factory=dict)
    donated_invars: Sequence[int] = ()
    invar_groups: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    claims_keyfree: bool = False
    required_traced: Sequence[str] = ()
    traced_manifest: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    _plan: Any = field(default=None, repr=False, compare=False)

    @property
    def jaxpr(self):
        return self.closed_jaxpr.jaxpr

    def mesh_axis_sizes(self) -> Dict[str, int]:
        if self.mesh is None:
            return {}
        try:
            return dict(self.mesh.shape)
        except Exception:  # noqa: BLE001 — AbstractMesh et al.
            return {}


class Report:
    """Aggregated findings over one or more linted sources."""

    def __init__(self):
        self.findings: List[Finding] = []
        self.sources: List[Dict[str, Any]] = []
        self.plans: List[Any] = []  # cost.Plan rows (shardlint --report)

    def add_source(self, name: str, seconds: float, n_findings: int,
                   skipped: Optional[str] = None) -> None:
        self.sources.append({
            "source": name,
            "seconds": round(float(seconds), 3),
            "findings": int(n_findings),
            **({"skipped": skipped} if skipped else {}),
        })

    def extend(self, findings: Sequence[Finding]) -> None:
        self.findings.extend(findings)

    @property
    def errors(self) -> List[Finding]:
        return [f for f in self.findings if f.severity == ERROR]

    @property
    def ok(self) -> bool:
        return not self.errors

    def to_dict(self) -> Dict[str, Any]:
        out = {
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "sources": list(self.sources),
        }
        if self.plans:
            out["plans"] = [p.to_dict() for p in self.plans]
        return out

    def to_json(self, **kw) -> str:
        return json.dumps(self.to_dict(), **kw)

    def format(self) -> str:
        lines = []
        for s in self.sources:
            status = s.get("skipped") and f"SKIPPED ({s['skipped']})" or (
                f"{s['findings']} finding(s)"
            )
            lines.append(
                f"shardlint: {s['source']}: {status} in {s['seconds']:.2f}s"
            )
        lines.extend(f.format() for f in self.findings)
        if self.plans:
            from .cost import format_plan_table

            lines.append(format_plan_table(self.plans))
        lines.append(
            "shardlint: "
            + ("CLEAN" if self.ok else f"{len(self.errors)} error finding(s)")
        )
        return "\n".join(lines)


def sharding_fingerprint(s) -> Optional[Tuple[str, str]]:
    """Comparable identity of a sharding for closure checks: (spec,
    memory kind). None when ``s`` carries no partition spec (single-device
    shardings, raw Device objects) — those never participate in a
    closure comparison."""
    spec = getattr(s, "spec", None)
    if spec is None:
        return None
    return (str(spec), str(getattr(s, "memory_kind", None)))
