"""shardlint driver: trace → context → rules → report.

Three entry points, all CPU-cheap (abstract evaluation only):

- :func:`lint_jaxpr` — lint any program you already traced.
- :func:`lint_engine` — trace a constructed engine's jitted train step
  (works on ``abstract_init=True`` shells whose state is
  ShapeDtypeStructs) and lint it, plus engine-level closure/donation
  audits the jaxpr alone cannot express.
- :func:`lint_config` — ds_config (+ model) → abstract engine → lint.

The registry is R1–R13 (docs/shardlint.md); R9 (rng-discipline) and R10
(reduction-order) run on every program, R11 (trace-stability) arms when
the trace driver supplies the step's traced-argument manifest — both
entry points here do — and R12/R13 (DCN rules) arm when the topology
carries DCN-tagged link metadata (hybrid meshes).
"""

from __future__ import annotations

import time
import warnings
from typing import Any, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from .base import ERROR, WARNING, Finding, LintContext, Report, sharding_fingerprint
from .rules import run_rules


def lint_jaxpr(
    closed_jaxpr,
    *,
    mesh=None,
    arg_shardings: Optional[Dict[Any, Any]] = None,
    master_pairs: Sequence = (),
    source: str = "<jaxpr>",
    only: Optional[Sequence[str]] = None,
    hbm_budget_bytes: Optional[float] = None,
    streams: Optional[Dict[str, Any]] = None,
    hardware=None,
    link_kinds: Optional[Dict[str, str]] = None,
    donated_invars: Sequence[int] = (),
    invar_groups: Optional[Dict[str, Any]] = None,
    claims_keyfree: bool = False,
    required_traced: Sequence[str] = (),
    traced_manifest: Optional[Dict[str, Any]] = None,
) -> List[Finding]:
    """Run the rule registry over one traced program."""
    ctx = LintContext(
        closed_jaxpr=closed_jaxpr,
        mesh=mesh,
        arg_shardings=arg_shardings or {},
        master_pairs=tuple(master_pairs),
        source=source,
        hbm_budget_bytes=hbm_budget_bytes,
        streams=dict(streams or {}),
        hardware=hardware,
        link_kinds=dict(link_kinds or {}),
        donated_invars=tuple(donated_invars),
        invar_groups=dict(invar_groups or {}),
        claims_keyfree=claims_keyfree,
        required_traced=tuple(required_traced),
        traced_manifest=dict(traced_manifest or {}),
    )
    return run_rules(ctx, only=only)


# --------------------------------------------------------------- engine lint
def _leaf_sharding(leaf):
    return getattr(leaf, "sharding", None)


def _as_sds(leaf):
    """Array/ShapeDtypeStruct → ShapeDtypeStruct preserving sharding."""
    if isinstance(leaf, jax.ShapeDtypeStruct):
        return leaf
    return jax.ShapeDtypeStruct(
        leaf.shape, leaf.dtype, sharding=_leaf_sharding(leaf)
    )


def _batch_sds(engine):
    cfg = engine.config
    accum = cfg.gradient_accumulation_steps
    B = cfg.train_batch_size
    S = getattr(getattr(engine.model, "config", None), "max_seq_len", None)
    if B is None or S is None:
        raise ValueError(
            "lint_engine needs a resolved train_batch_size and a model "
            "config with max_seq_len to shape the abstract batch"
        )
    sharding = engine._batch_sharding(accum_leading=True)
    shape = (accum, B // accum, S)
    sds = jax.ShapeDtypeStruct(shape, jnp.int32, sharding=sharding)
    return {"input_ids": sds, "labels": sds}


def _flat_with_paths(tree):
    leaves, _ = jax.tree_util.tree_flatten(tree)
    paths = [
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]
    return leaves, paths


def trace_train_step(engine):
    """(closed_jaxpr, arg_shardings, master_pairs, out_shape, meta).

    Traces ``engine._train_step`` (the body of the jitted train step —
    same program the runtime compiles) with ShapeDtypeStruct state and
    batch: abstract evaluation, nothing touches devices.

    ``meta`` carries the jit-boundary evidence the cost planner needs:
    ``invar_groups`` (state-group name → flat invar index range) and
    ``donated_invars`` (the state leaves ``_jit_train`` donates — its
    ``donate_argnums=(0, 1, 2, 3)`` covers params/opt/scale/step).
    """
    from ..models.sharding import use_topology

    state = engine.state
    params = jax.tree.map(_as_sds, state.params)
    opt_state = jax.tree.map(_as_sds, state.opt_state)
    loss_scale = state.loss_scale
    step = jax.ShapeDtypeStruct((), jnp.int32)
    batch = _batch_sds(engine)
    rng = jax.random.PRNGKey(0)

    def fn(p, o, s, st, b, r):
        return engine._train_step(p, o, s, st, b, r, None)

    args = (params, opt_state, loss_scale, step, batch, rng)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with use_topology(engine.topology):
            closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args)

    flat_args, arg_paths = _flat_with_paths(args)
    invars = list(closed.jaxpr.invars)
    arg_shardings: Dict[Any, Any] = {}
    if len(flat_args) == len(invars):
        for v, leaf in zip(invars, flat_args):
            s = _leaf_sharding(leaf)
            if s is not None:
                arg_shardings[v] = s

    # master pairs: f32 params/opt leaves must round-trip at full precision
    master_pairs = []
    out_leaves = jax.tree_util.tree_leaves(out_shape)
    n_p = len(jax.tree_util.tree_leaves(params))
    n_o = len(jax.tree_util.tree_leaves(opt_state))
    if len(flat_args) == len(invars) and len(out_leaves) == len(
        closed.jaxpr.outvars
    ):
        # step outputs: (params, opt, scale, step, metrics) — same leading
        # structure as the inputs
        for i in range(n_p + n_o):
            leaf = flat_args[i]
            if leaf.dtype == jnp.float32 and out_leaves[i].dtype == jnp.float32:
                if leaf.shape == out_leaves[i].shape:
                    master_pairs.append((i, i, arg_paths[i]))

    # planner metadata: which flat invars are which state group, and which
    # the jitted step donates (donate_argnums=(0,1,2,3) — the whole state)
    n_s = len(jax.tree_util.tree_leaves(loss_scale))
    n_step = 1
    n_batch = len(jax.tree_util.tree_leaves(batch))
    bounds = [
        ("params", n_p), ("opt_state", n_o), ("loss_scale", n_s),
        ("step", n_step), ("batch", n_batch),
    ]
    invar_groups, lo = {}, 0
    for name, n in bounds:
        invar_groups[name] = (lo, lo + n)
        lo += n
    meta = (
        {
            "invar_groups": invar_groups,
            "donated_invars": tuple(range(n_p + n_o + n_s + n_step)),
        }
        if len(flat_args) == len(invars)
        else {"invar_groups": {}, "donated_invars": ()}
    )
    return closed, arg_shardings, master_pairs, out_shape, meta


def compiled_train_memory_peak(engine):
    """``(peak_bytes, memory_analysis)`` from XLA's own accounting of
    the engine's train step (peak = argument + temp + output − alias),
    via an abstract lower + compile — nothing materializes.
    ``(None, None)`` when the backend does not report memory analysis.
    This is the ONE definition of the cross-check anchor the planner's
    peak band is measured against (tests/test_shardplan.py,
    tools/autoplan.py --check)."""
    state = engine.state
    lowered = engine._jit_train.lower(
        jax.tree.map(_as_sds, state.params),
        jax.tree.map(_as_sds, state.opt_state),
        state.loss_scale,
        jax.ShapeDtypeStruct((), jnp.int32),
        _batch_sds(engine),
        jax.random.PRNGKey(0),
        None,
    )
    ma = lowered.compile().memory_analysis()
    if not getattr(ma, "temp_size_in_bytes", 0):
        return None, None
    peak = (
        ma.argument_size_in_bytes
        + ma.temp_size_in_bytes
        + ma.output_size_in_bytes
        - ma.alias_size_in_bytes
    )
    return peak, ma


def _engine_level_findings(engine, out_shape) -> List[Finding]:
    """Closure + donation audits at the jit boundary (not jaxpr-visible)."""
    findings: List[Finding] = []
    # R2: the chain scans the step — the step's out_shardings must equal
    # the state's resting shardings leaf-for-leaf
    state_tuple = engine.state.astuple()
    for name, tree, shardings in zip(
        ("params", "opt_state", "loss_scale", "step"),
        state_tuple,
        engine._state_shardings,
    ):
        in_leaves = jax.tree_util.tree_leaves(tree)
        out_leaves = jax.tree_util.tree_leaves(shardings)
        if len(in_leaves) != len(out_leaves):
            continue
        for leaf, out_s in zip(in_leaves, out_leaves):
            fp_in = sharding_fingerprint(_leaf_sharding(leaf))
            fp_out = sharding_fingerprint(out_s)
            if fp_in is not None and fp_out is not None and fp_in != fp_out:
                findings.append(Finding(
                    rule="R2",
                    severity=ERROR,
                    message=(
                        f"{name}: resting sharding {fp_in} != step "
                        f"out_sharding {fp_out} — train_batch_chain's scan "
                        "carry is not closed over the step"
                    ),
                    where="<jit boundary>",
                ))
    # R4: every donated input buffer should be consumable by some output
    # (shape/dtype/sharding match); an unusable donation silently doubles
    # peak memory for that leaf
    out_avals = {}
    for leaf in jax.tree_util.tree_leaves(out_shape):
        key = (tuple(leaf.shape), str(leaf.dtype))
        out_avals[key] = out_avals.get(key, 0) + 1
    for name, tree in zip(("params", "opt_state", "loss_scale", "step"),
                          state_tuple):
        for leaf in jax.tree_util.tree_leaves(tree):
            key = (tuple(leaf.shape), str(jnp.dtype(leaf.dtype)))
            if out_avals.get(key, 0) > 0:
                out_avals[key] -= 1
            else:
                findings.append(Finding(
                    rule="R4",
                    severity=WARNING,
                    message=(
                        f"donated {name} leaf {key[1]}{list(key[0])} has no "
                        "matching output buffer — the donation is unusable "
                        "and peak memory holds both copies"
                    ),
                    where="<jit boundary>",
                ))
    return findings


def lint_engine(engine, only: Optional[Sequence[str]] = None,
                source: Optional[str] = None,
                hbm_budget_bytes: Optional[float] = None,
                hardware=None,
                collect_plan: bool = False) -> Report:
    """Trace + lint one engine's train step. Seconds on CPU.

    ``hbm_budget_bytes`` arms rule R6 (static OOM-before-compile check);
    ``collect_plan`` attaches the cost plan (analysis/cost) to the
    report so drivers print the per-config budget table without tracing
    twice. The engine's declared analytic streams (offload
    double-buffer, decomposed-TP rings) feed rule R8 either way.
    """
    from .cost import plan_for_context

    report = Report()
    name = source or f"engine[{type(engine).__name__}]"
    t0 = time.time()
    closed, arg_shardings, master_pairs, out_shape, meta = trace_train_step(
        engine
    )
    streams = (
        engine.analytic_streams(include_potential=True)
        if hasattr(engine, "analytic_streams")
        else {}
    )
    ctx = LintContext(
        closed_jaxpr=closed,
        mesh=engine.topology.mesh,
        arg_shardings=arg_shardings,
        master_pairs=tuple(master_pairs),
        source=name,
        hbm_budget_bytes=hbm_budget_bytes,
        streams=streams,
        hardware=hardware,
        link_kinds=dict(getattr(engine.topology, "link_kinds", None) or {}),
        donated_invars=meta["donated_invars"],
        invar_groups=meta["invar_groups"],
        # R11: the train step must consume its per-step batch — a dead
        # batch input means the program was specialized on trace-time
        # data (the manifest IS the invar-group split)
        required_traced=("batch",) if meta["invar_groups"] else (),
        traced_manifest=meta["invar_groups"],
    )
    findings = run_rules(ctx, only=only)
    for f in _engine_level_findings(engine, out_shape):
        if only is None or f.rule in only:
            f.source = name
            findings.append(f)
    report.extend(findings)
    report.add_source(name, time.time() - t0, len(findings))
    if collect_plan:
        report.plans.append(plan_for_context(ctx))
    return report


def lint_serving_config(config, model=None, topology=None,
                        only: Optional[Sequence[str]] = None,
                        source: Optional[str] = None,
                        hbm_budget_bytes: Optional[float] = None,
                        hardware=None,
                        collect_plan: bool = False) -> Report:
    """Lint a SERVING config: trace the continuous-batching engine's one
    jitted slot step abstractly (serving.trace_serving_step — params and
    the KV arena are ShapeDtypeStructs with real shardings) and run the
    same R1–R13 registry over it (R11 armed by the
    trace's traced-args manifest). The declared analytic streams (the
    per-step KV-arena traffic) feed the planner and rule R8 exactly like
    the training engines' streams."""
    from ..config import DeepSpeedConfig
    from ..comm.topology import MeshTopology, ParallelDims
    from ..serving.engine import trace_serving_step
    from .cost import plan_for_context
    from .rules import run_rules

    if model is None:
        raise ValueError("lint_serving_config requires a model (the step "
                         "program is model-shaped)")
    ds = (
        config if isinstance(config, DeepSpeedConfig)
        else DeepSpeedConfig(config)
    )
    tp = max(int(ds.tensor_parallel.tp_size), 1)
    # MoE serving configs lint on the ep mesh they would serve on (the
    # expert exchange only exists in the traced program when the ep axis
    # does) — serving_ep_size is the ONE moe.ep_size clamp, shared with
    # trace_serving_step
    from ..serving.engine import serving_ep_size

    ep = serving_ep_size(ds.moe, getattr(model, "config", None))
    if topology is None:
        topology = MeshTopology(
            dims=ParallelDims(tp=tp, ep=ep),
            devices=jax.devices()[:tp * ep],
        )
    report = Report()
    name = source or "serving"
    t0 = time.time()
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed, arg_shardings, streams, meta = trace_serving_step(
            model, ds, topology
        )
    ctx = LintContext(
        closed_jaxpr=closed,
        mesh=topology.mesh,
        arg_shardings=arg_shardings,
        source=name,
        hbm_budget_bytes=hbm_budget_bytes,
        streams=streams,
        hardware=hardware,
        link_kinds=dict(getattr(topology, "link_kinds", None) or {}),
        required_traced=meta.get("required_traced", ()),
        traced_manifest=meta.get("traced_manifest", {}),
    )
    findings = run_rules(ctx, only=only)
    report.extend(findings)
    report.add_source(name, time.time() - t0, len(findings))
    if collect_plan:
        report.plans.append(plan_for_context(ctx))
    return report


def lint_config(config, model=None, topology=None,
                only: Optional[Sequence[str]] = None,
                source: Optional[str] = None,
                hbm_budget_bytes: Optional[float] = None,
                hardware=None,
                collect_plan: bool = False) -> Report:
    """Build an abstract engine (no state materialization) and lint it.

    ``config`` is anything DeepSpeedConfig accepts (dict / path). The
    caller owns comm state: an already-initialized topology is reused,
    else one is built from the config exactly like training would.
    Configs whose "serving" section is enabled lint the serving engine's
    slot step instead of a train step (:func:`lint_serving_config`).
    """
    import deepspeed_tpu
    from ..config import DeepSpeedConfig

    if model is None:
        raise ValueError("lint_config requires a model (the step program "
                         "is model-shaped); tools/shardlint.py picks one "
                         "from the config when run as a CLI")
    ds = (
        config if isinstance(config, DeepSpeedConfig)
        else DeepSpeedConfig(config)
    )
    if ds.serving.enabled:
        return lint_serving_config(
            ds, model=model, topology=topology, only=only, source=source,
            hbm_budget_bytes=hbm_budget_bytes, hardware=hardware,
            collect_plan=collect_plan,
        )
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=config, topology=topology, abstract_init=True
    )
    try:
        return lint_engine(
            engine, only=only, source=source,
            hbm_budget_bytes=hbm_budget_bytes, hardware=hardware,
            collect_plan=collect_plan,
        )
    finally:
        engine.destroy()
