"""R8 — overlap budget: a declared-overlapped stream must fit its window.

The engine's performance story for hidden streams — the double-buffered
ZeRO-offload prefetch (PR 1) and the decomposed-TP ring hops (PR 3) — is
an overlap *claim*: the stream's wall time hides under the compute the
step provides. PERF_NOTES round 7 states the ceiling analytically
(speedup ≈ 1/(1 − f·overlap_ratio) only while the hidden bytes fit the
window); this rule enforces it statically.

For every stream the engine declares as overlapped
(``engine.analytic_streams()`` → ``overlapped: True``), the per-device
stream seconds (bytes over the host-DMA or ICI link from the hardware
model) must not exceed the step's analytic roofline window — the larger
of the MXU-compute and HBM-traffic terms the planner extracts from the
same jaxpr. A stream that cannot be hidden even in the best case means
the knob buys nothing but complexity (and double-buffer slots): the
config should drop it or rebalance before a chip ever measures it.

No declared streams → silent (plain configs never see R8). A
materiality floor keeps toy configs quiet: the *exposed* stream time
(stream seconds beyond the window) must cost at least 10 ms per step —
below that the static claim is numerically meaningless (test-sized
models run whole steps in microseconds) and the finding would be noise.
"""

from __future__ import annotations

from typing import List

from ..base import ERROR, Finding, LintContext
from . import register_rule

_GIB = float(1 << 30)
_MIN_EXPOSED_S = 0.010  # findings only when the un-hideable tail is real


@register_rule("R8", "overlap-budget")
def overlap_budget(ctx: LintContext) -> List[Finding]:
    streams = {
        k: s for k, s in (ctx.streams or {}).items()
        if s and s.get("overlapped")
    }
    if not streams:
        return []
    from ..cost import plan_for_context

    plan = plan_for_context(ctx)
    hw = plan.hardware
    findings: List[Finding] = []
    for name, s in streams.items():
        nbytes = float(
            s.get("per_device_bytes_per_step")
            or s.get("bytes_per_step", 0.0)
        )
        if nbytes <= 0:
            continue
        kind = s.get("kind", "offload")
        if kind == "offload":
            bw = hw.host_bw
        elif kind == "hbm":  # serving KV-arena stream
            bw = hw.hbm_bw
        else:
            bw = hw.ici_bw
        stream_s = nbytes / bw if bw > 0 else 0.0
        # the window one step provides THIS stream: host-DMA and ICI
        # streams hide under the larger of the MXU and HBM roofline
        # terms, but an HBM stream shares the very link that produces
        # hbm_s — it can only hide under the MXU term, else it simply
        # extends the HBM-bound step
        window_s = (
            plan.compute_s if kind == "hbm"
            else max(plan.compute_s, plan.hbm_s)
        )
        if stream_s <= window_s or stream_s - window_s < _MIN_EXPOSED_S:
            continue
        findings.append(Finding(
            rule="R8",
            severity=ERROR,
            message=(
                f"stream '{name}' is declared overlapped but its "
                f"{nbytes / _GIB:.2f} GiB/step over the "
                f"{ {'offload': 'host DMA', 'hbm': 'HBM'}.get(kind, 'ICI') }"
                " link "
                f"({bw / 1e9:.0f} GB/s) needs {stream_s:.4f}s — more than "
                f"the {window_s:.4f}s compute window the step provides "
                f"(MXU {plan.compute_s:.4f}s, HBM {plan.hbm_s:.4f}s); the "
                "bytes cannot be hidden even at full overlap (the PERF_NOTES "
                "round-7 ceiling) — shrink the stream or drop the knob"
            ),
            where="<plan>",
        ))
    return findings
