"""R13 — dcn-overlap-budget: overlap claims must hold at DCN bandwidth.

R8 prices every declared-overlapped stream against the step's compute
window at its link's bandwidth — but it knows one wire speed. On a
hybrid mesh (ctx.link_kinds) a stream whose collective traverses a
DCN-tagged axis moves those bytes at ``hardware.dcn_bw``, an order of
magnitude under ICI: an overlap claim that only fits at ICI bandwidth
is a fiction the first multi-pod run exposes as a stalled step.

Evidence, per declared-overlapped mesh stream (``kind != offload/hbm``,
``engine.analytic_streams()``):

- ``axes``: the mesh axes its collective runs over (the engine declares
  them; streams without axes cannot be classified and stay R8-only);
- hierarchical wire streams additionally carry
  ``inter_bytes_per_step`` — only the shrunk inter-group hop rides DCN,
  which is exactly how the 2-hop form earns its clean bill;
- everything else crossing a DCN axis moves its FULL payload there (the
  flat ring synchronizes on the slowest link — R12's pricing corollary).

The DCN-priced seconds must fit the same roofline window R8 uses
(max of the MXU and HBM terms), with the same 10 ms materiality floor
on the exposed tail. Silent without DCN tags or declared streams.
"""

from __future__ import annotations

from typing import List

from ..base import ERROR, Finding, LintContext
from . import register_rule
from .overlap_budget import _MIN_EXPOSED_S

_GIB = float(1 << 30)


def dcn_stream_bytes(stream, link_kinds) -> float:
    """The per-step bytes of one analytic stream that cross a DCN-tagged
    axis; 0.0 when the stream is unclassifiable or stays on ICI."""
    if not stream or stream.get("kind") in ("offload", "hbm"):
        return 0.0
    axes = tuple(stream.get("axes") or ())
    if not any(link_kinds.get(a) == "dcn" for a in axes):
        return 0.0
    if stream.get("hierarchical"):
        return float(stream.get("inter_bytes_per_step", 0.0))
    return float(
        stream.get("per_device_bytes_per_step")
        or stream.get("bytes_per_step", 0.0)
    )


@register_rule("R13", "dcn-overlap-budget")
def dcn_overlap_budget(ctx: LintContext) -> List[Finding]:
    kinds = ctx.link_kinds or {}
    if not any(k == "dcn" for k in kinds.values()):
        return []
    streams = {
        k: s for k, s in (ctx.streams or {}).items()
        if s and s.get("overlapped")
    }
    if not streams:
        return []
    from ..cost import plan_for_context

    plan = plan_for_context(ctx)
    hw = plan.hardware
    dcn_bw = float(getattr(hw, "dcn_bw", 0.0) or 0.0)
    if dcn_bw <= 0:
        return []
    findings: List[Finding] = []
    for name, s in streams.items():
        nbytes = dcn_stream_bytes(s, kinds)
        if nbytes <= 0:
            continue
        stream_s = nbytes / dcn_bw
        window_s = max(plan.compute_s, plan.hbm_s)
        if stream_s <= window_s or stream_s - window_s < _MIN_EXPOSED_S:
            continue
        ici_s = nbytes / hw.ici_bw if hw.ici_bw else 0.0
        fits_at_ici = ici_s <= window_s
        dcn_axes = [a for a in (s.get("axes") or ())
                    if kinds.get(a) == "dcn"]
        findings.append(Finding(
            rule="R13",
            severity=ERROR,
            message=(
                f"stream '{name}' is declared overlapped but "
                f"{nbytes / _GIB:.2f} GiB/step of it crosses DCN ax"
                f"{'es' if len(dcn_axes) > 1 else 'is'} {dcn_axes} at "
                f"{dcn_bw / 1e9:.2f} GB/s — {stream_s:.4f}s against the "
                f"{window_s:.4f}s compute window (MXU {plan.compute_s:.4f}s,"
                f" HBM {plan.hbm_s:.4f}s)"
                + ("; the claim only holds at ICI bandwidth "
                   f"({ici_s:.4f}s) — the fabric under it is slower"
                   if fits_at_ici else "")
                + " — shrink the DCN hop (hierarchical 2-hop + wire codec) "
                  "or stop declaring the stream hidden"
            ),
            where="<plan>",
        ))
    return findings
