"""R4 — donation/aliasing.

Two statically visible read-after-overwrite classes around donated and
rotating buffers:

(a) stale slot read: inside a scan/while body, a loop-carried buffer that
    is overwritten in place (``dynamic_update_slice`` / scatter — the
    rotating-slot idiom of the double-buffered offload stream and the KV
    cache) must not be read again *after* the updating equation. In SSA
    form the stale pre-update variable is still nameable; XLA either
    inserts a defensive copy (defeating the rotation) or, for donated /
    host-pinned slots, serves the overwritten bytes.

(b) read-after-donate: a value consumed by an inner jit that donates it
    (``donated_invars``) is dead — any later use at the same jaxpr level
    reads a buffer the callee was free to overwrite.

Both only fire on evidence in the program itself; the engine-level
donation/aval audit lives in shardlint.lint_engine.
"""

from __future__ import annotations

from typing import List, Set

from ..base import ERROR, Finding, LintContext
from ..trace import Jaxpr, Literal, as_jaxpr, iter_jaxprs, scan_split
from . import register_rule

_INPLACE = {"dynamic_update_slice", "scatter", "scatter-add", "scatter-mul",
            "scatter-min", "scatter-max"}


def _loop_carry_invars(jaxpr: Jaxpr, eqn) -> Set:
    if eqn.primitive.name == "scan":
        body = as_jaxpr(eqn.params["jaxpr"])
        nc, ncar = scan_split(eqn)
        return set(body.invars[nc:nc + ncar])
    if eqn.primitive.name == "while":
        body = as_jaxpr(eqn.params["body_jaxpr"])
        bn = eqn.params["body_nconsts"]
        return set(body.invars[bn:])
    return set()


def _stale_slot_reads(body: Jaxpr, carries: Set, path: str) -> List[Finding]:
    findings = []
    overwritten = {}  # stale var -> index of the updating eqn
    for i, eqn in enumerate(body.eqns):
        for a in eqn.invars:
            if isinstance(a, Literal):
                continue
            if a in overwritten and not (
                eqn.primitive.name in _INPLACE and eqn.invars[0] is a
            ):
                findings.append(Finding(
                    rule="R4",
                    severity=ERROR,
                    message=(
                        f"loop-carried buffer is read by {eqn.primitive.name} "
                        f"after being overwritten in place (eqn "
                        f"#{overwritten[a]} {body.eqns[overwritten[a]].primitive.name}) "
                        "— a rotating slot served stale bytes (or forces a "
                        "defensive copy)"
                    ),
                    where=path,
                ))
        if eqn.primitive.name in _INPLACE and eqn.invars and not isinstance(
            eqn.invars[0], Literal
        ) and eqn.invars[0] in carries:
            overwritten.setdefault(eqn.invars[0], i)
    return findings


@register_rule("R4", "donation-aliasing")
def donation_aliasing(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for jaxpr, path in iter_jaxprs(ctx.closed_jaxpr):
        # (a) stale rotating-slot reads inside loop bodies
        for eqn in jaxpr.eqns:
            carries = _loop_carry_invars(jaxpr, eqn)
            if not carries:
                continue
            body = as_jaxpr(
                eqn.params["jaxpr"]
                if eqn.primitive.name == "scan"
                else eqn.params["body_jaxpr"]
            )
            findings.extend(_stale_slot_reads(
                body, carries, f"{path}/{eqn.primitive.name}"
            ))
        # (b) read-after-donate at this level
        donated_at = {}  # var -> eqn index that donated it
        for i, eqn in enumerate(jaxpr.eqns):
            for a in eqn.invars:
                if isinstance(a, Literal):
                    continue
                if a in donated_at:
                    findings.append(Finding(
                        rule="R4",
                        severity=ERROR,
                        message=(
                            f"value is used by {eqn.primitive.name} after "
                            f"being donated to an inner jit (eqn "
                            f"#{donated_at[a]}) — the callee may already "
                            "have overwritten the buffer"
                        ),
                        where=f"{path}/{eqn.primitive.name}",
                    ))
            if eqn.primitive.name == "pjit":
                for a, don in zip(eqn.invars,
                                  eqn.params.get("donated_invars") or ()):
                    if don and not isinstance(a, Literal):
                        donated_at.setdefault(a, i)
        for a in jaxpr.outvars:
            if not isinstance(a, Literal) and a in donated_at:
                findings.append(Finding(
                    rule="R4",
                    severity=ERROR,
                    message=(
                        "a donated value is returned from the enclosing "
                        "program — the caller receives a buffer the inner "
                        "jit was free to overwrite"
                    ),
                    where=path,
                ))
    return findings
