"""R10 — reduction-order.

Float addition is not associative: every declared-bitwise pair in this
repo (paged == contiguous, ring == XLA reference, wire == full-width)
implicitly asserts that the two forms GROUP their accumulations the same
way. R10 has two halves:

Single-program (this registry rule): the dequant-accumulate dtype
contract. A wire codec (int8/int4 — comm/wires.py) is only sound when
the decoded blocks ACCUMULATE IN F32 ("dequant-accumulate in f32" — the
qgZ law, the R5 master-path contract's accumulator-side twin). The
analysis runs a three-level taint over the program:

    0 clean · 1 decoded block (a convert from a sub-8-bit integer
    payload to float, still inside its scale-application/layout
    neighbourhood) · 2 accumulated blocks (an add of two level-≥1
    values — a partial-block sum)

and flags accumulation evidence executed below 32-bit float:

- a CHAINED accumulation — an ``add``/``sub`` in bf16/f16 folding a
  decoded block into an already-accumulated value (the hand-rolled
  wire-ring ``acc += deq(chunk)`` shape);
- a scan/while CARRY produced by a sub-f32 add of decoded blocks
  (cross-iteration accumulation in narrow float);
- ``reduce_sum``/``cumsum`` over decoded blocks with a sub-f32 result
  (jnp.sum auto-upcasts its accumulator — lax-level code does not);
- a cross-member ``psum`` of decoded blocks in sub-f32 (psum never
  upcasts).

Deliberately NOT flagged: a dot_general over dequantized weights
(compute, not wire accumulation — MXU accumulation is f32 and out of
jaxpr sight), a single add of two *different* decoded tensors
(``wte[ids] + wpe[pos]`` under an int8 ``param_wire`` is forward
policy), and anything after an upcast-and-sum in f32 — that IS the
contract, and downstream bf16 math is fine. A lone two-member
accumulate (one add) is below the chain threshold and relies on the
psum/reduce/carry checks instead.

Cross-form (the differential half): "grouping changes across the two
forms of a declared-bitwise pair" — psum vs reduce-scatter
reassociation, a scatter-add into shared destinations appearing on one
side only, chunked partial sums whose chunking is not a declared
rewrite. That evidence needs BOTH jaxprs, so it lives in
``analysis/parity.py``: ``prove_parity`` emits findings labeled R10
when the divergent anchor is a reduction/collective (docs/shardlint.md
"parity certificates").
"""

from __future__ import annotations

from typing import Dict, List

import jax.numpy as jnp

from ..base import ERROR, Finding, LintContext
from ..trace import ClosedJaxpr, Jaxpr, Literal, as_jaxpr, collective_axes, \
    scan_split
from . import register_rule

# payload dtypes whose decode marks a value as a level-1 wire block
_WIRE_INTS = ("int8", "uint8", "int4", "uint4")
# ops that keep a decoded block's level unconditionally (layout,
# masking, float casts) — anything unlisted clears to level 0
_FLOW = {
    "neg", "select_n", "copy",
    "device_put", "reshape", "transpose", "squeeze", "expand_dims",
    "broadcast_in_dim", "slice", "dynamic_slice", "concatenate", "pad",
    "rev", "gather", "dynamic_update_slice",
}
# scale application: mul/div (and clamping) keep the LARGER operand's
# level when the other is a broadcast scale (strictly fewer elements).
# An equal-size product — e.g. a backward cotangent times the decoded
# forward value — is new data, not a decoded block, and clears: bf16
# psums of ordinary gradients must stay R10-silent.
_SCALED = {"mul", "div", "max", "min", "clamp"}
_REDUCING = {"reduce_sum", "cumsum"}
_CROSS_MEMBER = {"psum"}
_CALL_LIKE_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _is_sub_f32_float(dtype) -> bool:
    return (
        dtype is not None
        and jnp.issubdtype(dtype, jnp.floating)
        and jnp.finfo(dtype).bits < 32
    )


def _is_wire_int(dtype) -> bool:
    return dtype is not None and str(dtype) in _WIRE_INTS


def _out_dtype(eqn):
    if not eqn.outvars:
        return None
    return getattr(getattr(eqn.outvars[0], "aval", None), "dtype", None)


class _Walk:
    """Recursive taint walk with the 0/1/2 lattice. Control flow mirrors
    analysis.trace.DataflowAnalysis; carries iterate to a small
    fixpoint so cross-iteration accumulators reach level 2."""

    MAX_ITERS = 4

    def __init__(self, emit):
        self.emit = emit
        self._reported = set()

    def _flag(self, path: str, name: str, message: str) -> None:
        key = (path, name)
        if key in self._reported:
            return
        self._reported.add(key)
        self.emit(Finding(
            rule="R10",
            severity=ERROR,
            message=(
                f"{message} — the dequant-accumulate contract "
                "(comm/wires.py: decode to f32 BEFORE any sum) is "
                "violated; the accumulated error depends on grouping and "
                "the declared-bitwise pair cannot hold"
            ),
            where=f"{path}/{name}",
        ))

    def run(self, jaxpr: Jaxpr, in_levels: List[int], path: str = ""
            ) -> List[int]:
        env: Dict[int, int] = {}

        def read(a) -> int:
            if isinstance(a, Literal):
                return 0
            return env.get(id(a), 0)

        for var, lv in zip(jaxpr.invars, in_levels):
            env[id(var)] = int(lv)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ivals = [read(a) for a in eqn.invars]
            outs = self._eqn(eqn, name, ivals, path)
            for v, lv in zip(eqn.outvars, outs):
                env[id(v)] = int(lv)
        return [read(v) for v in jaxpr.outvars]

    # ------------------------------------------------------------------
    def _eqn(self, eqn, name, ivals, path) -> List[int]:
        n_out = len(eqn.outvars)
        dtype = _out_dtype(eqn)
        if name == "convert_element_type":
            in_dtype = getattr(
                getattr(eqn.invars[0], "aval", None), "dtype", None
            )
            if _is_wire_int(in_dtype) and dtype is not None and \
                    jnp.issubdtype(dtype, jnp.floating):
                return [1] * n_out  # the decode itself
            if dtype is not None and jnp.issubdtype(dtype, jnp.floating):
                return [max(ivals or [0])] * n_out
            return [0] * n_out
        if name in ("add", "sub"):
            a, b = (ivals + [0, 0])[:2]
            if a >= 1 and b >= 1:
                if not _is_sub_f32_float(dtype):
                    # accumulated in >= f32: the contract is satisfied
                    # and the result is ordinary data from here on
                    return [0] * n_out
                if max(a, b) >= 2:
                    self._flag(path, name, (
                        "chained accumulation of wire-decoded blocks in "
                        f"{dtype} (acc += dequantized chunk)"
                    ))
                return [2] * n_out
            return [0] * n_out
        if name in _REDUCING:
            if max(ivals or [0]) >= 1 and _is_sub_f32_float(dtype):
                self._flag(path, name, (
                    f"{name} over wire-decoded blocks in {dtype}"
                ))
            return [0] * n_out
        if name in _CROSS_MEMBER:
            if max(ivals or [0]) >= 1 and _is_sub_f32_float(dtype):
                axes = ",".join(collective_axes(eqn)) or "?"
                self._flag(path, name, (
                    f"cross-member {name} over axis ({axes}) of "
                    f"wire-decoded blocks in {dtype}"
                ))
            return [0] * n_out
        if name in _FLOW:
            return [max(ivals or [0])] * n_out
        if name in _SCALED:
            sizes = [
                getattr(getattr(a, "aval", None), "size", 0)
                for a in eqn.invars
            ]
            if sizes:
                big = max(sizes)
                winners = [
                    lv for lv, sz in zip(ivals, sizes) if sz == big
                ]
                if len(winners) == 1 or name == "clamp":
                    return [max(winners)] * n_out
            return [0] * n_out
        # control flow ------------------------------------------------------
        if name == "scan":
            body = as_jaxpr(eqn.params["jaxpr"])
            nc, ncar = scan_split(eqn)
            consts = ivals[:nc]
            carry = ivals[nc:nc + ncar]
            xs = ivals[nc + ncar:]
            outs = [0] * len(body.outvars)
            for _ in range(self.MAX_ITERS):
                outs = self.run(body, consts + carry + xs, f"{path}/scan")
                new_carry = [max(c, o) for c, o in zip(carry, outs[:ncar])]
                if new_carry == carry:
                    break
                carry = new_carry
            self._carry_check(body, consts, ncar, xs, f"{path}/scan")
            return carry + outs[ncar:]
        if name == "while":
            body = as_jaxpr(eqn.params["body_jaxpr"])
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            bconsts = ivals[cn:cn + bn]
            carry = ivals[cn + bn:]
            for _ in range(self.MAX_ITERS):
                outs = self.run(body, bconsts + carry, f"{path}/while")
                new_carry = [max(c, o) for c, o in zip(carry, outs)]
                if new_carry == carry:
                    break
                carry = new_carry
            self._carry_check(body, bconsts, len(carry), (),
                              f"{path}/while")
            return carry
        if name == "cond":
            branches = eqn.params["branches"]
            operands = ivals[1:]
            outs = None
            for br in branches:
                o = self.run(as_jaxpr(br), list(operands), f"{path}/cond")
                outs = o if outs is None else [max(a, b)
                                               for a, b in zip(outs, o)]
            return outs if outs is not None else []
        if name == "shard_map":
            return self.run(as_jaxpr(eqn.params["jaxpr"]), ivals,
                            f"{path}/shard_map")
        for key in _CALL_LIKE_KEYS:
            if key in eqn.params and isinstance(
                eqn.params[key], (Jaxpr, ClosedJaxpr)
            ):
                body = as_jaxpr(eqn.params[key])
                sub = f"{path}/{name}"
                if len(body.invars) == len(ivals):
                    return self.run(body, ivals, sub)
                if len(body.invars) < len(ivals):
                    return self.run(body, ivals[-len(body.invars):], sub)
                break
        return [0] * n_out

    def _carry_check(self, body, consts, ncar, xs, path) -> None:
        """A loop carry fed by a sub-f32 add of decoded blocks:
        cross-iteration accumulation in narrow float (``carry += deq``).
        Carries are seeded at level 2 — *assume* the carry is an
        accumulator — and the flag fires only when the carry-producing
        equation is an add folding a level-≥1 block into it, so ordinary
        bf16 carries (residual streams, KV arenas) stay silent."""
        rec = _Recorder()
        rec.run(body, list(consts) + [2] * ncar + list(xs), path)
        producers = {}
        for eqn in body.eqns:
            for v in eqn.outvars:
                producers[id(v)] = eqn
        for ov in body.outvars[:ncar]:
            # hop back through pure-flow ops to the producing accumulate
            cur = ov
            eqn = producers.get(id(cur))
            for _ in range(8):
                if eqn is None or eqn.primitive.name not in _FLOW:
                    break
                nxt = max(
                    (a for a in eqn.invars if not isinstance(a, Literal)),
                    key=lambda a: rec.levels.get(id(a), 0),
                    default=None,
                )
                if nxt is None:
                    eqn = None
                    break
                cur = nxt
                eqn = producers.get(id(cur))
            if eqn is None or eqn.primitive.name not in ("add", "sub"):
                continue
            dtype = _out_dtype(eqn)
            if not _is_sub_f32_float(dtype):
                continue
            lv = [
                0 if isinstance(a, Literal) else rec.levels.get(id(a), 0)
                for a in eqn.invars
            ]
            if len(lv) >= 2 and max(lv[:2]) >= 2 and min(lv[:2]) >= 1:
                self._flag(path, eqn.primitive.name, (
                    "loop-carried accumulator folds wire-decoded blocks "
                    f"in {dtype}"
                ))


class _Recorder(_Walk):
    """Level recorder for the carry check: same walk, emission muted,
    per-var levels kept for operand inspection."""

    def __init__(self):
        super().__init__(lambda f: None)
        self.levels: Dict[int, int] = {}

    def run(self, jaxpr, in_levels, path=""):
        env: Dict[int, int] = {}

        def read(a):
            if isinstance(a, Literal):
                return 0
            return env.get(id(a), 0)

        for var, lv in zip(jaxpr.invars, in_levels):
            env[id(var)] = int(lv)
            self.levels[id(var)] = max(
                self.levels.get(id(var), 0), int(lv)
            )
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            ivals = [read(a) for a in eqn.invars]
            outs = self._eqn(eqn, name, ivals, path)
            for v, lv in zip(eqn.outvars, outs):
                env[id(v)] = int(lv)
                self.levels[id(v)] = max(self.levels.get(id(v), 0), int(lv))
        return [read(v) for v in jaxpr.outvars]


@register_rule("R10", "reduction-order")
def reduction_order(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    jaxpr = ctx.jaxpr
    _Walk(findings.append).run(jaxpr, [0] * len(jaxpr.invars), "")
    return findings
