"""R7 — redundant reshard: data movement the partitioner should never emit.

Three statically visible waste shapes, each a pure cost bug (the program
is correct, the bytes are not):

(a) transpose∘transpose composing to the identity permutation around a
    placement cast (``transpose → reshard → transpose⁻¹``), where the
    intermediates have no other consumer — the cast forces both copies
    to materialize. A *bare* adjacent pair is NOT flagged: autodiff
    emits those naturally and XLA's algebraic simplifier cancels them
    for free — only the reshard-pinned form actually moves bytes;
(b) back-to-back placement casts (``device_put`` / ``sharding_constraint``
    chains) where the second cast restores the sharding the value
    already had before the first (an A→B→A reshard ping-pong, each leg a
    collective on a sharded mesh) or repeats the same target twice;
(c) a degenerate gather-then-slice: an ``all_gather`` over a mesh axis
    whose only consumer is a slice that takes back exactly the
    pre-gather shard — (n−1)/n of the wire bytes bought nothing.

A deliberate *no-op* re-put (putting a value to the sharding evidence
says it already has, with no second cast — the engine's resting re-put
that keeps scan carries closed) is NOT flagged: XLA compiles it away and
R2 depends on it.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..base import ERROR, Finding, LintContext, sharding_fingerprint
from ..trace import Literal, iter_jaxprs
from . import register_rule

_PLACEMENT = ("device_put", "sharding_constraint")


def _consumers(jaxpr) -> Dict[Any, int]:
    """var → number of uses at this level (outvars count as a use)."""
    n: Dict[Any, int] = {}
    for eqn in jaxpr.eqns:
        for a in eqn.invars:
            if not isinstance(a, Literal):
                n[a] = n.get(a, 0) + 1
    for a in jaxpr.outvars:
        if not isinstance(a, Literal):
            n[a] = n.get(a, 0) + 1
    return n


def _is_own_shard_index(var, prod, _depth: int = 0) -> bool:
    """True when a dynamic-slice start operand is provably the device's
    OWN ``axis_index`` (allowing literal scaling/casting — shard-size
    multiples), i.e. the self-selection that makes a gather-then-slice
    degenerate. Neighbor arithmetic (±1, mod) or anything else we cannot
    prove disqualifies — a cross-shard fetch means the gather is
    load-bearing."""
    if _depth > 8:
        return False
    if isinstance(var, Literal):
        return True  # constant component of the start tuple
    e = prod.get(var)
    if e is None:
        return False
    n = e.primitive.name
    if n == "axis_index":
        return True
    if n in ("convert_element_type", "broadcast_in_dim", "reshape",
             "squeeze"):
        return _is_own_shard_index(e.invars[0], prod, _depth + 1)
    if n == "mul":
        nonlit = [a for a in e.invars if not isinstance(a, Literal)]
        if len(nonlit) == 1:
            return _is_own_shard_index(nonlit[0], prod, _depth + 1)
    if n == "select_n" and len(e.invars) == 3:
        # dynamic_slice's wrap-around normalization select(x<0, x+L, x)
        # is an identity for an in-range x — see through it when pred
        # and both branches root at the SAME base var
        pred, a0, a1 = e.invars
        pe = prod.get(pred)
        if (
            pe is not None
            and pe.primitive.name == "lt"
            and not isinstance(a0, Literal)
            and pe.invars
            and pe.invars[0] is a0
        ):
            ae = prod.get(a1)
            if ae is not None and ae.primitive.name == "add":
                nonlit = [v for v in ae.invars
                          if not isinstance(v, Literal)]
                if len(nonlit) == 1 and nonlit[0] is a0:
                    return _is_own_shard_index(a0, prod, _depth + 1)
    return False


def _placement_target(eqn, outvar) -> Optional[Any]:
    if eqn.primitive.name == "sharding_constraint":
        return eqn.params.get("sharding")
    if eqn.primitive.name == "device_put":
        devices = eqn.params.get("devices") or ()
        try:
            idx = list(eqn.outvars).index(outvar)
        except ValueError:
            return None
        if idx < len(devices):
            return devices[idx]
    return None


@register_rule("R7", "redundant-reshard")
def redundant_reshard(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for jaxpr, path in iter_jaxprs(ctx.closed_jaxpr):
        prod: Dict[Any, Any] = {}
        for eqn in jaxpr.eqns:
            for ov in eqn.outvars:
                prod[ov] = eqn
        uses = _consumers(jaxpr)
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            where = f"{path}/{name}"
            if not eqn.invars or isinstance(eqn.invars[0], Literal):
                continue
            src = eqn.invars[0]
            inner = prod.get(src)
            # (a) transpose(reshard(transpose(x))) == reshard(x): the
            # placement cast between the pair pins both copies — XLA
            # cannot cancel them. (A bare adjacent pair IS cancelled by
            # the algebraic simplifier, so it is not flagged.)
            if name == "transpose" and inner is not None \
                    and uses.get(src, 0) == 1:
                chain = inner
                saw_cast = False
                while (
                    chain is not None
                    and chain.primitive.name in _PLACEMENT
                    and chain.invars
                    and not isinstance(chain.invars[0], Literal)
                    and uses.get(chain.invars[0], 0) == 1
                ):
                    saw_cast = True
                    chain = prod.get(chain.invars[0])
                if (
                    saw_cast
                    and chain is not None
                    and chain.primitive.name == "transpose"
                ):
                    p_out = eqn.params["permutation"]
                    p_in = chain.params["permutation"]
                    if [p_in[p] for p in p_out] == list(range(len(p_out))):
                        findings.append(Finding(
                            rule="R7",
                            severity=ERROR,
                            message=(
                                "transpose∘reshard∘transpose composes to a "
                                f"resharded identity (inner {tuple(p_in)}, "
                                f"outer {tuple(p_out)}) with single-use "
                                "intermediates — the placement cast forces "
                                "two full copies of the tensor that a "
                                "reshard of the original would avoid"
                            ),
                            where=where,
                        ))
            # (b) placement-cast chains: A→B→A round trip or duplicate
            if (
                name in _PLACEMENT
                and inner is not None
                and inner.primitive.name in _PLACEMENT
                and uses.get(src, 0) == 1
            ):
                outer_t = _placement_target(eqn, eqn.outvars[0])
                inner_t = _placement_target(inner, src)
                inner_src = (
                    inner.invars[0]
                    if inner.invars and not isinstance(inner.invars[0], Literal)
                    else None
                )
                before = ctx.arg_shardings.get(inner_src)
                fp_outer = sharding_fingerprint(outer_t) if outer_t else None
                fp_inner = sharding_fingerprint(inner_t) if inner_t else None
                fp_before = sharding_fingerprint(before) if before else None
                if fp_outer is not None and fp_outer == fp_inner:
                    findings.append(Finding(
                        rule="R7",
                        severity=ERROR,
                        message=(
                            f"two chained placement casts to the same "
                            f"sharding {fp_outer[0]} (memory {fp_outer[1]}) "
                            "— the first is dead weight"
                        ),
                        where=where,
                    ))
                elif (
                    fp_outer is not None
                    and fp_before is not None
                    and fp_outer == fp_before
                    and fp_inner is not None
                    and fp_inner != fp_outer
                ):
                    findings.append(Finding(
                        rule="R7",
                        severity=ERROR,
                        message=(
                            f"reshard ping-pong: value resharded "
                            f"{fp_before[0]} → {fp_inner[0]} → {fp_outer[0]} "
                            "with no use in between — both legs are "
                            "wasted collectives"
                        ),
                        where=where,
                    ))
            # (c) all_gather whose only consumer dynamic-slices the
            # device's OWN shard back out. A static slice (fixed shard —
            # a broadcast) or a neighbor-indexed fetch keeps the gather
            # load-bearing and is NOT flagged.
            if name == "dynamic_slice" and inner is not None \
                    and inner.primitive.name == "all_gather" \
                    and uses.get(src, 0) == 1:
                out_aval = eqn.outvars[0].aval
                pre_aval = inner.invars[0].aval \
                    if inner.invars and not isinstance(
                        inner.invars[0], Literal
                    ) else None
                pre_gather = tuple(pre_aval.shape) if pre_aval is not None \
                    else None
                if (
                    pre_aval is not None
                    and out_aval.size == pre_aval.size
                    and out_aval.size < src.aval.size
                    and all(
                        _is_own_shard_index(a, prod)
                        for a in eqn.invars[1:]
                    )
                ):
                    findings.append(Finding(
                        rule="R7",
                        severity=ERROR,
                        message=(
                            "all_gather output is consumed only by a slice "
                            f"returning the pre-gather shard {pre_gather} — "
                            "the gather's wire bytes bought nothing "
                            "(degenerate gather-then-slice)"
                        ),
                        where=where,
                    ))
    return findings
