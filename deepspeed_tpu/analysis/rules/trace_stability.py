"""R11 — trace-stability.

The serving tier's whole performance story is ``step_traces == 1``: one
compiled program serves every arrival/occupancy/divergence mix. The
runtime proves it with a retrace counter AFTER hours of replay; R11
certifies the same contract statically, from the traced step alone.

The step closure's inputs partition into TRACED (jaxpr invars — their
values flow through the compiled program) and STATIC (python values
baked into the trace — a new value means a new trace). Per-request /
per-tick host state — slot occupancy (``num_new``), write frontiers
(``start_pos``), ``spec_len``, ``cow_src``, page tables, per-slot keys
— MUST be traced: baking any of them specializes the program on one
tick's scheduler state and every subsequent tick recompiles.

Evidence comes from the trace driver (``required_traced`` +
``traced_manifest`` on the LintContext — the drivers in
analysis/shardlint.py and serving.trace_serving_step know the step's
argument contract; the rule is silent without it, like R6 without a
budget). Two failure shapes per required name:

(a) BAKED — the name has no traced invars at all: its value was
    captured as a python constant / closure literal, so it is static
    and per-tick values force retraces (``step_traces`` grows without
    bound).

(b) DEAD — the name is traced but none of its invars feed any
    equation: the program no longer depends on the input, which means
    the host value was consulted at trace time instead (the
    traced-but-baked hybrid: no retrace, but every tick after the first
    runs with the FIRST tick's value).
"""

from __future__ import annotations

from typing import List, Set

from ..base import ERROR, Finding, LintContext
from . import register_rule


def _used_invars(jaxpr) -> Set[int]:
    """Indices of top-level invars that feed at least one equation. An
    invar that only ECHOES into the outputs does not count: a
    passed-through per-tick input is exactly the traced-but-baked
    hybrid shape (b) below — the compute never reads it."""
    used = set()
    for eqn in jaxpr.eqns:
        for a in eqn.invars:
            used.add(id(a))
    return {i for i, v in enumerate(jaxpr.invars) if id(v) in used}


@register_rule("R11", "trace-stability")
def trace_stability(ctx: LintContext) -> List[Finding]:
    if not ctx.required_traced:
        return []
    findings: List[Finding] = []
    jaxpr = ctx.jaxpr
    manifest = ctx.traced_manifest or ctx.invar_groups
    live = _used_invars(jaxpr)
    for name in ctx.required_traced:
        rng = manifest.get(name)
        if rng is None or rng[0] >= rng[1]:
            findings.append(Finding(
                rule="R11",
                severity=ERROR,
                message=(
                    f"per-tick input {name!r} is STATIC — it was baked "
                    "into the trace as a python value, so the compiled "
                    "step is specialized on one tick's host state and "
                    "every new value retraces (step_traces grows without "
                    "bound); trace it as a step input instead"
                ),
                where="<jit boundary>",
            ))
            continue
        lo, hi = int(rng[0]), int(rng[1])
        if not any(i in live for i in range(lo, hi)):
            findings.append(Finding(
                rule="R11",
                severity=ERROR,
                message=(
                    f"per-tick input {name!r} is traced but DEAD — no "
                    "equation consumes it, so the program was specialized "
                    "on the trace-time host value and every later tick "
                    "silently runs with the first tick's state; make the "
                    "computation read the traced input"
                ),
                where="<jit boundary>",
            ))
    return findings
