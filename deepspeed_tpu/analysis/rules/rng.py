"""R9 — rng-discipline.

Every bitwise-parity claim in this repo (spec-on == spec-off, serving ==
generate, ep-sharded == dense) rides on ONE rule about randomness: a PRNG
key is consumed exactly once, and the chain advances only where the
reference path advances it. The statically-visible violations:

(a) key reuse — the same key value is consumed by two sampling/split
    sites (``random_bits`` / ``random_split`` / ``random_fold_in``).
    Two draws from one key are correlated (identical, for equal shapes),
    and the replay chain desynchronizes from the reference the moment
    one path splits where the other samples.

(b) loop-invariant key — a key that enters a scan/while body as a
    loop-invariant (const) and is consumed inside the body: every
    iteration replays the SAME stream instead of chaining
    (split-per-iteration is the discipline; xs-sliced key arrays are
    fine — each iteration gets its own).

(c) trace-time seeding — ``random_seed`` from a literal inside the
    step: a host RNG read (or a bare ``PRNGKey(0)``) baked at trace
    time, so every invocation of the compiled step replays one stream.
    Keys must be threaded through the step's inputs.

(d) claimed-keyfree path — when the driver arms
    ``ctx.claims_keyfree`` (an eval/serving program that claims
    key-free bitwiseness — the PR-14 gating contract: gating at eval is
    bitwise with/without a key and never splits), ANY key-consuming
    site is a finding.

The analysis is a value-numbering walk: each key value gets an identity
rooted at its origin (invar / seed eqn) and refined by the derivation
chain (split → slice picks distinct subkeys; data-dependent selection
gets a fresh identity — conservative, never a false reuse). Consumption
sites under sibling ``cond`` branches are exclusive and never pair up.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax

from ..base import ERROR, Finding, LintContext
from ..trace import ClosedJaxpr, Jaxpr, Literal, as_jaxpr, scan_split
from . import register_rule

# primitives that CONSUME a key (advance/occupy its stream);
# random_fold_in is a DERIVATION, not a consumption — folding distinct
# data out of one key is the documented discipline (fold_in(key, step))
_CONSUMING = ("random_bits", "random_split")
# primitives through which a key keeps its identity
_IDENTITY = {
    "random_wrap", "random_unwrap", "copy", "squeeze", "expand_dims",
    "reshape", "broadcast_in_dim", "convert_element_type", "device_put",
    "transpose",
}
_CALL_LIKE_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")


def _is_key_like(aval) -> bool:
    """True for typed PRNG keys and raw uint32 key buffers."""
    dtype = getattr(aval, "dtype", None)
    if dtype is None:
        return False
    try:
        if jax.dtypes.issubdtype(dtype, jax.dtypes.prng_key):
            return True
    except (AttributeError, TypeError):
        pass
    return False


class _Site:
    """One key-consuming equation occurrence."""

    __slots__ = ("path", "prim", "pos")

    def __init__(self, path: str, prim: str, pos: int):
        self.path = path
        self.prim = prim
        self.pos = pos

    def where(self) -> str:
        return f"{self.path}/{self.prim}" if self.path else self.prim

    def key(self) -> Tuple[str, str, int]:
        return (self.path, self.prim, self.pos)


def _exclusive(a: _Site, b: _Site) -> bool:
    """Sites under sibling branches of the SAME cond equation never both
    execute (path segments ``cond[<eqn>]#<branch>`` — the eqn index
    keeps two different conds from reading as siblings)."""
    pa, pb = a.path.split("/"), b.path.split("/")
    for x, y in zip(pa, pb):
        if x != y:
            return (
                "#" in x and "#" in y
                and x.startswith("cond[")
                and x.split("#")[0] == y.split("#")[0]
            )
    return False


class _KeyWalk:
    """Value-numbering walk over key dataflow. ``keyid`` is a hashable
    identity; ``loop_keys`` marks identities that are loop-invariant in
    the jaxpr currently being walked."""

    def __init__(self):
        self._fresh = 0
        # keyid -> [ _Site ]  (consumption registry)
        self.consumed: Dict[Any, List[_Site]] = {}
        # keyid -> site of the loop-invariant consumption finding
        self.loop_hits: List[Tuple[Any, _Site]] = []
        self.seed_sites: List[_Site] = []

    def fresh(self) -> Tuple[str, int]:
        self._fresh += 1
        return ("fresh", self._fresh)

    @staticmethod
    def _invariant(keyid, loop_keys) -> bool:
        """True when the key value is the SAME on every loop iteration:
        a loop-invariant root, or a derivation of one whose every step
        is deterministic (split/slice at a fixed site; fold over literal
        data). A fold over traced data derives a fresh stream per value
        and is the legitimate in-loop pattern."""
        if keyid is None:
            return False
        if keyid in loop_keys:
            return True
        if not isinstance(keyid, tuple):
            return False
        tag = keyid[0]
        if tag in ("split", "slice"):
            return _KeyWalk._invariant(keyid[1], loop_keys)
        if tag == "fold":
            return keyid[2][0] == "lit" and _KeyWalk._invariant(
                keyid[1], loop_keys
            )
        return False

    def _consume(self, keyid, site: _Site, loop_keys) -> None:
        if keyid is None:
            return
        self.consumed.setdefault(keyid, []).append(site)
        if self._invariant(keyid, loop_keys):
            self.loop_hits.append((keyid, site))

    def run(self, jaxpr: Jaxpr, in_ids: List[Any], path: str = "",
            loop_keys=frozenset()) -> List[Any]:
        env: Dict[Any, Any] = {}

        def read(a):
            if isinstance(a, Literal):
                return None
            kid = env.get(a)
            if kid is None:
                # identity roots at the VALUE, minted lazily: the same
                # var consumed through two different sub-programs (two
                # cond equations, a branch wrap each) must resolve to
                # ONE key identity, not one per wrap site
                kid = ("rootvar", id(a))
                env[a] = kid
            return kid

        for var, kid in zip(jaxpr.invars, in_ids):
            if kid is not None:
                env[var] = kid
        for pos, eqn in enumerate(jaxpr.eqns):
            name = eqn.primitive.name
            ivals = [read(a) for a in eqn.invars]
            outs = self._eqn(eqn, name, ivals, path, pos, loop_keys)
            for v, kid in zip(eqn.outvars, outs):
                if kid is not None:
                    env[v] = kid
        return [read(v) for v in jaxpr.outvars]

    # ------------------------------------------------------------------
    def _eqn(self, eqn, name, ivals, path, pos, loop_keys):
        n_out = len(eqn.outvars)
        if name == "random_seed":
            if all(isinstance(a, Literal) for a in eqn.invars):
                self.seed_sites.append(_Site(path, name, pos))
            return [self.fresh()] * n_out
        if name in _CONSUMING:
            self._consume(ivals[0], _Site(path, name, pos), loop_keys)
            if name == "random_split":
                return [("split", ivals[0] or self.fresh(), path, pos)] * n_out
            return [None] * n_out  # bits: output is data, not a key
        if name == "random_fold_in":
            parent = ivals[0] or self.fresh()
            data_static = all(
                isinstance(a, Literal) for a in eqn.invars[1:]
            )
            mark = ("lit",) if data_static else ("dyn", path, pos)
            return [("fold", parent, mark)] * n_out
        if name == "random_wrap":
            # raw uint32 key words acquire identity here: two wraps of
            # the same buffer are the same key
            src = ivals[0]
            if src is None and not isinstance(eqn.invars[0], Literal):
                src = ("rootvar", id(eqn.invars[0]))
            return [src] * n_out
        if name in _IDENTITY:
            src = next((v for v in ivals if v is not None), None)
            return [src] * n_out
        if name == "slice" and ivals[0] is not None:
            params = (
                tuple(eqn.params.get("start_indices") or ()),
                tuple(eqn.params.get("limit_indices") or ()),
            )
            return [("slice", ivals[0], params)] * n_out
        # control flow -----------------------------------------------------
        if name == "scan":
            body = as_jaxpr(eqn.params["jaxpr"])
            nc, ncar = scan_split(eqn)
            length = eqn.params.get("length")
            looping = length is None or length > 1
            # consts keep (or mint) identity and become loop-invariant;
            # carries and xs get fresh per-iteration identities
            # (chained / per-iteration slices)
            consts = [
                c if c is not None else ("rootvar", id(v))
                for c, v in zip(ivals[:nc], eqn.invars[:nc])
            ]
            carries = ivals[nc:nc + ncar]
            body_in = (
                consts
                + [self.fresh() if c is not None else None for c in carries]
                + [self.fresh() if x is not None else None
                   for x in ivals[nc + ncar:]]
            )
            inner_loop = (
                loop_keys | set(consts) if looping else loop_keys
            )
            outs = self.run(body, body_in, f"{path}/scan[{pos}]", inner_loop)
            return outs[:ncar] + [None] * (n_out - ncar)
        if name == "while":
            body = as_jaxpr(eqn.params["body_jaxpr"])
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            bconsts = [
                c if c is not None else ("rootvar", id(v))
                for c, v in zip(ivals[cn:cn + bn], eqn.invars[cn:cn + bn])
            ]
            carries = ivals[cn + bn:]
            body_in = list(bconsts) + [
                self.fresh() if c is not None else None for c in carries
            ]
            inner_loop = loop_keys | set(bconsts)
            self.run(body, body_in, f"{path}/while[{pos}]", inner_loop)
            return [None] * n_out
        if name == "cond":
            branches = eqn.params["branches"]
            operands = ivals[1:]
            outs = [None] * n_out
            for i, br in enumerate(branches):
                o = self.run(as_jaxpr(br), list(operands),
                             f"{path}/cond[{pos}]#{i}", loop_keys)
                outs = [a if a is not None else b for a, b in zip(outs, o)]
            return outs
        if name == "shard_map":
            return self.run(as_jaxpr(eqn.params["jaxpr"]), ivals,
                            f"{path}/shard_map[{pos}]", loop_keys)
        for key in _CALL_LIKE_KEYS:
            if key in eqn.params and isinstance(
                eqn.params[key], (Jaxpr, ClosedJaxpr)
            ):
                body = as_jaxpr(eqn.params[key])
                sub = f"{path}/{name}[{pos}]"
                if len(body.invars) == len(ivals):
                    return self.run(body, ivals, sub, loop_keys)
                if len(body.invars) < len(ivals):
                    return self.run(body, ivals[-len(body.invars):], sub,
                                    loop_keys)
                break
        # any other op (gather, dynamic_slice with traced start, math on
        # raw key words): data-dependent derivation — fresh identity per
        # output, conservatively never a reuse
        if any(v is not None for v in ivals):
            return [self.fresh()] * n_out
        return [None] * n_out


@register_rule("R9", "rng-discipline")
def rng_discipline(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    jaxpr = ctx.jaxpr
    walk = _KeyWalk()
    seeds = [
        ("invar", i) if _is_key_like(getattr(v, "aval", None)) else None
        for i, v in enumerate(jaxpr.invars)
    ]
    # raw uint32 keys only acquire identity at random_wrap; typed-key
    # invars seed directly
    walk.run(jaxpr, seeds, "")

    # (a) reuse: one key identity, two non-exclusive consumption sites
    for keyid, sites in walk.consumed.items():
        uniq: List[_Site] = []
        seen = set()
        for s in sites:
            if s.key() not in seen:
                seen.add(s.key())
                uniq.append(s)
        live = [
            s for i, s in enumerate(uniq)
            if not all(_exclusive(s, t) for t in uniq[:i] + uniq[i + 1:])
        ] if len(uniq) > 1 else []
        if len(live) > 1:
            findings.append(Finding(
                rule="R9",
                severity=ERROR,
                message=(
                    "PRNG key consumed by "
                    f"{len(live)} sampling/split sites "
                    f"({', '.join(s.where() for s in live[:4])}) — draws "
                    "from one key are correlated and the replay chain "
                    "desynchronizes from the reference; split first, "
                    "consume each subkey once"
                ),
                where=live[0].where(),
            ))
    # (b) loop-invariant consumption
    reported = set()
    for keyid, site in walk.loop_hits:
        if site.key() in reported:
            continue
        reported.add(site.key())
        findings.append(Finding(
            rule="R9",
            severity=ERROR,
            message=(
                "loop-invariant PRNG key consumed inside a loop body — "
                "every iteration replays the same stream; chain the key "
                "through the carry (split per iteration) or feed an xs "
                "key array"
            ),
            where=site.where(),
        ))
    # (c) trace-time seeding
    for site in walk.seed_sites:
        findings.append(Finding(
            rule="R9",
            severity=ERROR,
            message=(
                "PRNG key seeded from a trace-time constant inside the "
                "traced step (a host RNG read or bare PRNGKey(n) baked "
                "at trace time) — every invocation of the compiled step "
                "replays one stream; thread keys through the step inputs"
            ),
            where=site.where(),
        ))
    # (d) claimed-keyfree path
    if ctx.claims_keyfree:
        sites = [s for ss in walk.consumed.values() for s in ss]
        sites += walk.seed_sites
        for site in sorted({s.key() for s in sites}):
            findings.append(Finding(
                rule="R9",
                severity=ERROR,
                message=(
                    "key-consuming site on a path that claims key-free "
                    "bitwiseness (the eval/serving gating contract: "
                    "bitwise with or without a key, never splits) — the "
                    "claim is statically false"
                ),
                where=f"{site[0]}/{site[1]}" if site[0] else site[1],
            ))
    return findings
