"""R12 — dcn-flat-collective: no flat ring across the slow inter-pod fabric.

On a hybrid DCN×ICI mesh (``MeshTopology.hybrid``, ctx.link_kinds) a
collective whose hop set spans BOTH link classes is the flat form ZeRO++
(arXiv:2306.10209) exists to kill: a joint ring over ``("dp", "fsdp")``
synchronizes every hop, so the whole full-width payload crawls at DCN
bandwidth even though only the 1/n_i inter-group slice had to. The
hierarchical 2-hop decomposition (``zero_optimization.hierarchical_wire``
→ ``wires.rs_wire_hier_local`` / ``ag_wire_hier_local``) is statically
distinguishable: it runs one single-axis collective per level — full
width over the ICI axis, a shrunk (and codec-compressed) payload over
the DCN axis — and stays clean here.

Two flagged shapes:

- a named collective (psum / all_gather / psum_scatter / all_to_all /
  pbroadcast / pmin / pmax) whose bound axis set mixes a DCN-tagged axis
  with an ICI axis — the joint flat ring;
- a ``ppermute`` FULL RING over a DCN-tagged axis — a decomposed
  ring-exchange (the TP-overlap / ring-flash pattern) streams n−1
  full-width hops across the slow fabric; chains (pipeline neighbor
  hops) are point-to-point and stay clean, as does a single-axis
  reduction over DCN (that IS the 2-hop form's inter hop).

Both carry a payload materiality floor (``_MIN_FLAT_BYTES``): a scalar
loss psum or a layer-norm grad reduction over the joint data axes is
latency-bound — decomposing it buys no bandwidth and costs a hop of
latency — so only operands from ~a wire bucket upward flag.

Silent without ``link_kinds`` DCN tags — flat meshes never see R12.
"""

from __future__ import annotations

from typing import Dict, List

from ..base import ERROR, Finding, LintContext
from ..trace import as_jaxpr, collective_axes, eqn_subjaxprs, shard_map_manual_axes
from . import register_rule
from .topology import check_permutation

_FLAT_COLLECTIVES = {
    "psum", "pmin", "pmax", "all_gather", "all_to_all", "psum_scatter",
    "pbroadcast",
}

#: below this the ring is latency-bound: the joint flat form costs one
#: synchronized ring, the 2-hop form costs two rings — for a scalar or a
#: layer-norm-sized reduction the decomposition is strictly worse
_MIN_FLAT_BYTES = 64 * 1024


def _operand_bytes(eqn) -> int:
    out = 0
    for v in eqn.invars:
        aval = getattr(v, "aval", None)
        size = getattr(aval, "size", None)
        if size is not None:
            out = max(out, int(size) * aval.dtype.itemsize)
    return out


def is_full_ring(perm, axis_size: int) -> bool:
    """True when ``perm`` is one well-formed cycle covering the whole
    axis — the shape whose every hop crosses the axis's links."""
    pairs = [tuple(p) for p in (perm or ())]
    if len(pairs) != axis_size or axis_size < 2:
        return False
    if check_permutation(pairs, axis_size):
        return False
    # well-formed + one edge per member == the single full ring
    return {s for s, _ in pairs} == set(range(axis_size))


def _walk(jaxpr, axis_env: Dict[str, int], path: str, ctx: LintContext,
          findings: List[Finding]) -> None:
    kinds = ctx.link_kinds
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_path = f"{path}/{name}"
        if name == "shard_map":
            _walk(as_jaxpr(eqn.params["jaxpr"]),
                  {**axis_env, **shard_map_manual_axes(eqn)},
                  sub_path, ctx, findings)
            continue
        if name in _FLAT_COLLECTIVES:
            live = [a for a in collective_axes(eqn)
                    if axis_env.get(a, 1) > 1]
            dcn = [a for a in live if kinds.get(a) == "dcn"]
            ici = [a for a in live if kinds.get(a) != "dcn"]
            if dcn and ici and _operand_bytes(eqn) >= _MIN_FLAT_BYTES:
                findings.append(Finding(
                    rule="R12",
                    severity=ERROR,
                    message=(
                        f"{name} runs one flat ring jointly over DCN axis"
                        f"{'es' if len(dcn) > 1 else ''} {dcn} and ICI "
                        f"ax{'es' if len(ici) > 1 else 'is'} {ici} — every "
                        "hop synchronizes on the slow inter-pod fabric, so "
                        "the full-width payload moves at DCN bandwidth; "
                        "decompose per level (hierarchical_wire over the "
                        f"factored ({', '.join(dcn + ici)}) pair: full "
                        "width intra-pod, the shrunk slice inter-pod)"
                    ),
                    where=sub_path,
                ))
        if name == "ppermute":
            for a in collective_axes(eqn):
                size = axis_env.get(a, 1)
                if kinds.get(a) == "dcn" and is_full_ring(
                    eqn.params.get("perm"), size
                ) and _operand_bytes(eqn) >= _MIN_FLAT_BYTES:
                    findings.append(Finding(
                        rule="R12",
                        severity=ERROR,
                        message=(
                            f"ppermute full ring over DCN-tagged axis "
                            f"{a!r} (size {size}) — a decomposed ring "
                            f"exchange streams {size - 1} full-width hops "
                            "across the inter-pod fabric; keep ring "
                            "decompositions on ICI axes and move the DCN "
                            "slice once (hierarchical 2-hop form)"
                        ),
                        where=sub_path,
                    ))
        for _k, sub in eqn_subjaxprs(eqn):
            _walk(sub, axis_env, sub_path, ctx, findings)


@register_rule("R12", "dcn-flat-collective")
def dcn_flat_collective(ctx: LintContext) -> List[Finding]:
    kinds = ctx.link_kinds or {}
    if not any(k == "dcn" for k in kinds.values()):
        return []
    findings: List[Finding] = []
    _walk(ctx.jaxpr, {}, "", ctx, findings)
    return findings
