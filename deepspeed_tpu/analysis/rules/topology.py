"""R3 — collective-topology.

ppermute is the pipeline's p2p fabric (runtime/pipe/schedule.py): on real
ICI a malformed permutation is not a wrong answer but a *hang* — a member
waiting on a source that never sends. Statically checkable properties of
the ``perm`` parameter:

- every (src, dst) within [0, axis_size);
- no duplicate sources or destinations (XLA requires a partial
  permutation; duplicates deadlock or drop data);
- no self-loops (a member sending to itself deadlocks some transports);
- cycle structure: a perm containing a cycle must be exactly ONE cycle
  covering the whole axis (a full ring). Disjoint sub-rings or a ring
  plus stray edges desynchronize members. Pure chains (the pipeline's
  neighbor hop, no wraparound) are legal.

Also checked: named collectives must use axes bound by the enclosing
shard_map, and every embedded shard_map mesh must agree with the
authoritative lint mesh (axis names and sizes) — a shard_map traced over
a stale mesh is invisible at runtime until the wrong collective fires.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

from ..base import ERROR, Finding, LintContext
from ..trace import (
    as_jaxpr,
    collective_axes,
    eqn_subjaxprs,
    shard_map_manual_axes,
)
from . import register_rule

_NAMED_COLLECTIVES = {
    "psum", "pmin", "pmax", "ppermute", "all_gather", "all_to_all",
    "psum_scatter", "pbroadcast", "axis_index",
}


def check_permutation(perm, axis_size: int) -> List[str]:
    """Problems with a ppermute permutation (empty list == well-formed).

    Exposed for reuse: runtime/pipe/schedule.py builds its neighbor hop
    against this contract.
    """
    problems: List[str] = []
    pairs = [tuple(p) for p in perm]
    srcs = [s for s, _ in pairs]
    dsts = [d for _, d in pairs]
    oob = [p for p in pairs if not (0 <= p[0] < axis_size
                                    and 0 <= p[1] < axis_size)]
    if oob:
        problems.append(f"out-of-range pairs {oob} for axis size {axis_size}")
    if len(set(srcs)) != len(srcs):
        problems.append("duplicate sources (a member sends twice)")
    if len(set(dsts)) != len(dsts):
        problems.append("duplicate destinations (two members send to one)")
    self_loops = [p for p in pairs if p[0] == p[1]]
    if self_loops:
        problems.append(f"self-loops {self_loops}")
    if problems:
        return problems
    # cycle structure: an injective partial map decomposes into disjoint
    # simple paths (legal: the pipeline's neighbor hop) and simple cycles
    nxt = dict(pairs)
    dsts_set = set(dsts)
    visited = set()
    for start in [s for s in nxt if s not in dsts_set]:  # chain starts
        cur = start
        while cur in nxt and cur not in visited:
            visited.add(cur)
            cur = nxt[cur]
    cycles = []
    for s in nxt:
        if s in visited:
            continue
        cyc, cur = [s], nxt[s]
        visited.add(s)
        while cur != s:
            visited.add(cur)
            cyc.append(cur)
            cur = nxt[cur]
        cycles.append(cyc)
    if len(cycles) > 1:
        problems.append(
            f"{len(cycles)} disjoint rings {sorted(cycles)} — members "
            "desynchronize across rings"
        )
    elif len(cycles) == 1 and len(pairs) != len(cycles[0]):
        problems.append(
            "a ring plus stray chain edges — malformed permutation"
        )
    elif len(cycles) == 1 and len(cycles[0]) != axis_size:
        problems.append(
            f"partial ring over {len(cycles[0])}/{axis_size} members "
            f"{sorted(cycles[0])} — the others never participate"
        )
    return problems


def _walk(jaxpr, axis_env: Dict[str, int], path: str, ctx: LintContext,
          findings: List[Finding]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_path = f"{path}/{name}"
        if name == "shard_map":
            manual = shard_map_manual_axes(eqn)
            lint_sizes = ctx.mesh_axis_sizes()
            if lint_sizes:
                mismatched = [
                    (a, n, lint_sizes.get(a))
                    for a, n in manual.items()
                    if lint_sizes.get(a) != n
                ]
                if mismatched:
                    findings.append(Finding(
                        rule="R3",
                        severity=ERROR,
                        message=(
                            "shard_map mesh disagrees with the engine mesh: "
                            + ", ".join(
                                f"axis {a!r} size {n} (engine: {m})"
                                for a, n, m in mismatched
                            )
                        ),
                        where=sub_path,
                    ))
            _walk(as_jaxpr(eqn.params["jaxpr"]), {**axis_env, **manual},
                  sub_path, ctx, findings)
            continue
        if name in _NAMED_COLLECTIVES:
            for a in collective_axes(eqn):
                if a not in axis_env:
                    findings.append(Finding(
                        rule="R3",
                        severity=ERROR,
                        message=(
                            f"{name} over axis {a!r} which is not bound by "
                            "any enclosing shard_map mesh (bound: "
                            f"{sorted(axis_env) or 'none'})"
                        ),
                        where=sub_path,
                    ))
            if name == "ppermute":
                axes: List[Tuple[str, int]] = [
                    (a, axis_env[a]) for a in collective_axes(eqn)
                    if a in axis_env
                ]
                for a, size in axes:
                    for problem in check_permutation(
                        eqn.params.get("perm") or (), size
                    ):
                        findings.append(Finding(
                            rule="R3",
                            severity=ERROR,
                            message=(
                                f"ppermute over {a!r}: {problem} — hangs "
                                "or deadlocks on real ICI"
                            ),
                            where=sub_path,
                        ))
        for _k, sub in eqn_subjaxprs(eqn):
            _walk(sub, axis_env, sub_path, ctx, findings)


@register_rule("R3", "collective-topology")
def collective_topology(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    _walk(ctx.jaxpr, {}, "", ctx, findings)
    return findings
