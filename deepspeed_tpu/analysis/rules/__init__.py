"""Shardlint rule registry.

A rule is a function ``(ctx: LintContext) -> list[Finding]`` registered
under a stable id. Adding a rule (docs/shardlint.md "adding a rule"):

    from ..base import Finding, LintContext
    from . import register_rule

    @register_rule("R9", "my-hazard")
    def my_rule(ctx: LintContext):
        return [...]

The built-in modules below self-register on import.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

from ..base import Finding, LintContext

_RULES: Dict[str, "RuleEntry"] = {}


class RuleEntry:
    def __init__(self, rule_id: str, title: str, fn: Callable):
        self.rule_id = rule_id
        self.title = title
        self.fn = fn

    def __call__(self, ctx: LintContext) -> List[Finding]:
        return list(self.fn(ctx))


def register_rule(rule_id: str, title: str):
    def deco(fn):
        if rule_id in _RULES:
            raise ValueError(f"shardlint rule {rule_id!r} already registered")
        _RULES[rule_id] = RuleEntry(rule_id, title, fn)
        return fn

    return deco


def registered_rules() -> Dict[str, RuleEntry]:
    return dict(_RULES)


def run_rules(ctx: LintContext,
              only: Optional[Sequence[str]] = None) -> List[Finding]:
    out: List[Finding] = []
    for rid, entry in sorted(_RULES.items()):
        if only is not None and rid not in only:
            continue
        for f in entry(ctx):
            f.source = f.source or ctx.source
            out.append(f)
    return out


# built-in rules (import order == catalog order)
from . import replica  # noqa: E402,F401  (R1)
from . import closure  # noqa: E402,F401  (R2)
from . import topology  # noqa: E402,F401  (R3)
from . import aliasing  # noqa: E402,F401  (R4)
from . import precision  # noqa: E402,F401  (R5)
from . import capacity  # noqa: E402,F401  (R6)
from . import reshard  # noqa: E402,F401  (R7)
from . import overlap_budget  # noqa: E402,F401  (R8)
from . import rng  # noqa: E402,F401  (R9)
from . import reduction_order  # noqa: E402,F401  (R10)
from . import trace_stability  # noqa: E402,F401  (R11)
from . import dcn_collective  # noqa: E402,F401  (R12)
from . import dcn_overlap  # noqa: E402,F401  (R13)
