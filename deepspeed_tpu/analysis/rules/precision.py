"""R5 — precision-policy.

(a) master-weight preservation: an f32 master leaf (params / optimizer
    m/v) must reach its step output along at least one path that never
    drops below 32-bit float. Casting masters to bf16 for *compute* is
    the policy (the result arrives back as an update term); rebuilding
    the stored master itself from a truncated copy is the bug — after
    ~1k steps the master is a bf16 weight in f32 clothing. The analysis
    computes the "preserved" set: values reachable from a master input
    through ops whose output keeps ≥ f32 float width; a master output
    outside the set has *every* path truncated.

(b) pinned-host compute: a value whose placement evidence says
    ``pinned_host`` may only flow through placement/slicing ops before an
    explicit copy to device memory; feeding it straight into compute
    (dot_general, elementwise math) either fails to compile or silently
    runs the op on the host CPU at host-DRAM speed.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from ..base import ERROR, Finding, LintContext
from ..trace import DataflowAnalysis
from . import register_rule

_F32_BITS = 32


def _is_wide_float(dtype) -> bool:
    return jnp.issubdtype(dtype, jnp.floating) and jnp.finfo(dtype).bits >= _F32_BITS


class _Preserved(DataflowAnalysis):
    """True == value carries a full-precision copy of some master leaf."""

    def transfer(self, eqn, in_vals: List[bool]) -> List[bool]:
        out = []
        for ov in eqn.outvars:
            dtype = getattr(getattr(ov, "aval", None), "dtype", None)
            ok = (
                any(in_vals)
                and dtype is not None
                and _is_wide_float(dtype)
            )
            out.append(ok)
        return out


# ops through which host-resident bytes may legally flow before the
# explicit device copy (placement, layout, slicing — no arithmetic)
_HOST_OK = {
    "device_put", "copy", "slice", "dynamic_slice", "squeeze", "reshape",
    "transpose", "broadcast_in_dim", "concatenate", "gather", "rev",
    "expand_dims", "pad",
}


class _PinnedHost(DataflowAnalysis):
    def __init__(self, emit, pinned_kinds=("pinned_host",)):
        self.emit = emit
        self.pinned_kinds = pinned_kinds
        self._reported = set()

    def _device_put_kinds(self, eqn) -> List[bool]:
        out = []
        for i, _ov in enumerate(eqn.outvars):
            devices = eqn.params.get("devices") or ()
            kind = (
                getattr(devices[i], "memory_kind", None)
                if i < len(devices)
                else None
            )
            out.append(kind in self.pinned_kinds)
        return out

    def transfer(self, eqn, in_vals: List[bool]) -> List[bool]:
        if eqn.primitive.name == "device_put":
            return self._device_put_kinds(eqn)
        return [any(in_vals)] * len(eqn.outvars)

    def visit(self, eqn, in_vals, out_vals, path) -> None:
        from ..trace import eqn_subjaxprs

        name = eqn.primitive.name
        if name in _HOST_OK or not any(in_vals):
            return
        if eqn_subjaxprs(eqn):
            return  # control-flow: the recursion checks the body eqns
        key = (path, name)
        if key in self._reported:
            return
        self._reported.add(key)
        self.emit(Finding(
            rule="R5",
            severity=ERROR,
            message=(
                f"pinned_host-resident value feeds {name} without an "
                "explicit copy to device memory — host-speed compute (or "
                "a compile failure) instead of a scheduled DMA"
            ),
            where=f"{path}/{name}",
        ))


@register_rule("R5", "precision-policy")
def precision_policy(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    jaxpr = ctx.jaxpr

    # (a) master preservation
    if ctx.master_pairs:
        invars = list(jaxpr.invars)
        seeds = [False] * len(invars)
        master_in = {}
        for in_idx, _out_idx, label in ctx.master_pairs:
            if 0 <= in_idx < len(seeds):
                seeds[in_idx] = True
                master_in[in_idx] = label
        out_vals = _Preserved().run(jaxpr, seeds, "")
        for in_idx, out_idx, label in ctx.master_pairs:
            if not (0 <= out_idx < len(out_vals)):
                continue
            ov = jaxpr.outvars[out_idx]
            dtype = getattr(getattr(ov, "aval", None), "dtype", None)
            if dtype is None or not _is_wide_float(dtype):
                continue  # not a wide-float output: out of scope
            if not out_vals[out_idx]:
                findings.append(Finding(
                    rule="R5",
                    severity=ERROR,
                    message=(
                        f"master-state leaf {label!r}: every path from the "
                        "f32 input to the f32 output passes through a "
                        "sub-32-bit float — the stored master is rebuilt "
                        "from truncated data (bf16-in-f32-clothing drift)"
                    ),
                    where="",
                ))

    # (b) pinned-host consumption
    seeds = []
    pinned_any = False
    for v in jaxpr.invars:
        s = ctx.arg_shardings.get(v)
        pinned = getattr(s, "memory_kind", None) == "pinned_host"
        pinned_any = pinned_any or pinned
        seeds.append(pinned)
    # even with no pinned inputs, device_put eqns can introduce pinned
    # values mid-program, so the pass always runs (it is cheap)
    _PinnedHost(findings.append).run(jaxpr, seeds, "")
    return findings
