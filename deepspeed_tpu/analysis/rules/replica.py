"""R1 — replica-divergence.

A shard_map output whose out_spec omits a live manual mesh axis claims
the value is identical on every member of that axis. With the replication
checker off (``check_vma=False`` — what the engine traces use), nothing
verifies the claim: a value derived from axis-partitioned data (e.g.
per-dp-member local gradients) that never crosses a reduction over that
axis silently diverges per replica — the exact "parameter update whose
gradient was never all-reduced" bug class. This rule fills that gap with
a taint analysis per (shard_map, axis):

- taint seeds: body inputs partitioned over the axis, and axis_index
  over the axis;
- reductions over the axis (psum/pmin/pmax/all_gather — value becomes
  member-identical) clear taint;
- a tainted value reaching an output that claims replication → finding.
"""

from __future__ import annotations

from typing import List

from ..base import ERROR, Finding, LintContext
from ..trace import (
    DataflowAnalysis,
    as_jaxpr,
    collective_axes,
    iter_jaxprs,
    names_spec_axes,
    shard_map_manual_axes,
)
from . import register_rule

# collectives whose output is identical on every member of the reduced
# axis (psum covers pmean: jax lowers pmean to psum + div)
_REDUCING = {"psum", "pmin", "pmax", "all_gather", "pgather"}
# per-member value sources even with untainted inputs
_MEMBER_VARYING = {"axis_index"}


class _AxisTaint(DataflowAnalysis):
    def __init__(self, axis: str):
        self.axis = axis

    def transfer(self, eqn, in_vals: List[bool]) -> List[bool]:
        name = eqn.primitive.name
        if name in _MEMBER_VARYING and self.axis in collective_axes(eqn):
            return [True] * len(eqn.outvars)
        if name in _REDUCING and self.axis in collective_axes(eqn):
            return [False] * len(eqn.outvars)
        return [any(in_vals)] * len(eqn.outvars)


@register_rule("R1", "replica-divergence")
def replica_divergence(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    for jaxpr, path in iter_jaxprs(ctx.closed_jaxpr):
        for eqn in jaxpr.eqns:
            if eqn.primitive.name != "shard_map":
                continue
            where = f"{path}/shard_map"
            body = as_jaxpr(eqn.params["jaxpr"])
            in_names = eqn.params.get("in_names") or ()
            out_names = eqn.params.get("out_names") or ()
            manual = shard_map_manual_axes(eqn)
            for axis, size in manual.items():
                if size <= 1:
                    continue  # one member: replication is vacuous
                seeds = [
                    axis in names_spec_axes(entry) for entry in in_names
                ]
                out_vals = _AxisTaint(axis).run(body, seeds, where)
                for i, (val, entry) in enumerate(zip(out_vals, out_names)):
                    if val and axis not in names_spec_axes(entry):
                        findings.append(Finding(
                            rule="R1",
                            severity=ERROR,
                            message=(
                                f"shard_map output #{i} claims replication "
                                f"over mesh axis {axis!r} (size {size}) but "
                                f"derives from {axis}-partitioned data with "
                                f"no reduction over {axis!r} — replicas "
                                "diverge (missing psum/pmean?)"
                            ),
                            where=where,
                        ))
    return findings
