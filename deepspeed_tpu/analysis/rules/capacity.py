"""R6 — HBM capacity: the static OOM-before-compile check.

The cost planner (analysis/cost) estimates the per-device HBM peak of
the traced step — state bytes from the ShapeDtypeStruct shardings,
activation live-set high-water mark, collective scratch. When the
context carries an HBM budget (``tools/shardplan.py --hbm-gb``, the
``SHARDPLAN_HBM_GB`` env, or an explicit ``hbm_budget_bytes``), a peak
above it is an error finding *before anything compiles* — the OOM that
used to surface minutes into a TPU run (or as a cryptic RESOURCE_EXHAUSTED
from the remote compile helper) becomes a one-second CPU lint.

No budget in the context → the rule is silent: generic lints (the test
suite's captured configs, ``shardlint --all-examples`` without flags)
never guess a machine size.
"""

from __future__ import annotations

import os
from typing import List

from ..base import ERROR, Finding, LintContext
from . import register_rule

_GIB = float(1 << 30)


def _armed_budget_bytes(ctx: LintContext):
    """Explicit context budget first, then the documented
    ``SHARDPLAN_HBM_GB`` env arm; None when neither is set."""
    if ctx.hbm_budget_bytes is not None:
        return float(ctx.hbm_budget_bytes)
    env = os.environ.get("SHARDPLAN_HBM_GB")
    if env:
        return float(env) * _GIB
    return None


@register_rule("R6", "hbm-capacity")
def hbm_capacity(ctx: LintContext) -> List[Finding]:
    budget_armed = _armed_budget_bytes(ctx)
    if budget_armed is None:
        return []
    from ..cost import plan_for_context

    plan = plan_for_context(ctx)
    budget = budget_armed
    if plan.peak_hbm_bytes <= budget:
        return []
    return [Finding(
        rule="R6",
        severity=ERROR,
        message=(
            f"estimated peak HBM {plan.peak_hbm_bytes / _GIB:.2f} GiB "
            f"exceeds the {budget / _GIB:.2f} GiB per-device budget "
            f"(params {plan.param_bytes / _GIB:.2f} + opt "
            f"{plan.opt_bytes / _GIB:.2f} + activations "
            f"{plan.act_peak_bytes / _GIB:.2f} + collective scratch "
            f"{plan.collective_scratch_bytes / _GIB:.2f} GiB) — this "
            "config OOMs before the first step; shard further, offload, "
            "or lower the micro-batch/remat policy"
        ),
        where="<plan>",
    )]
