"""R2 — sharding-closure.

A scanned step (grad-accum scan, bucketed per-layer optimizer scan,
train_batch_chain) is only correct if every loop carry comes back with
the sharding it went in with: a carry whose writeback restores a
*different* placement than the carry-in either forces a silent reshard
every tick or — with host memory kinds — migrates state off its resting
memory space (the PR-1 stacked-dim-0 drift class).

Statically visible sharding evidence is collected per jaxpr level:

- top-level invars with known arg shardings;
- ``device_put`` / ``sharding_constraint`` equation outputs (their
  sharding is an eqn param).

For every scan/while carry where BOTH the carry-in and the body's
carry-out producer have evidence, the two fingerprints (spec, memory
kind) must agree. Unknown placements are never flagged (XLA is free to
choose them consistently).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ..base import ERROR, Finding, LintContext, sharding_fingerprint
from ..trace import Jaxpr, Literal, as_jaxpr, producers, scan_split
from . import register_rule


def _lookup(known: Dict[Any, Any], var) -> Optional[Any]:
    if isinstance(var, Literal):
        return None
    return known.get(var)


def _eqn_out_sharding(eqn, outvar) -> Optional[Any]:
    """The sharding an eqn pins its output to, if it pins one."""
    name = eqn.primitive.name
    if name == "sharding_constraint":
        return eqn.params.get("sharding")
    if name == "device_put":
        devices = eqn.params.get("devices") or ()
        try:
            idx = list(eqn.outvars).index(outvar)
        except ValueError:
            return None
        if idx < len(devices):
            d = devices[idx]
            if sharding_fingerprint(d) is not None:
                return d
    return None


def _check_loop_carries(kind: str, body: Jaxpr, carry_invars,
                        body_carry_outvars, known: Dict[Any, Any],
                        sub_path: str, findings: List[Finding]) -> None:
    """Shared scan/while carry check: for each carry with evidence on
    BOTH ends, the fingerprints must match."""
    body_prod = producers(body)
    for k, (carry_in, body_out) in enumerate(
        zip(carry_invars, body_carry_outvars)
    ):
        s_in = _lookup(known, carry_in)
        out_eqn = body_prod.get(body_out)
        s_out = (
            _eqn_out_sharding(out_eqn, body_out)
            if out_eqn is not None
            else None
        )
        if s_in is None or s_out is None:
            continue
        fp_in = sharding_fingerprint(s_in)
        fp_out = sharding_fingerprint(s_out)
        if fp_in is not None and fp_out is not None and fp_in != fp_out:
            findings.append(Finding(
                rule="R2",
                severity=ERROR,
                message=(
                    f"{kind} carry #{k}: carry-in sharding {fp_in[0]} "
                    f"(memory {fp_in[1]}) != carry-out writeback "
                    f"{fp_out[0]} (memory {fp_out[1]}) — the loop "
                    "re-shards its state every tick (carry-in == "
                    "carry-out closure violated)"
                ),
                where=sub_path,
            ))


def _map_known(known: Dict[Any, Any], outer_vars, body_invars) -> Dict[Any, Any]:
    body_known: Dict[Any, Any] = {}
    for outer, inner in zip(outer_vars, body_invars):
        s = _lookup(known, outer)
        if s is not None:
            body_known[inner] = s
    return body_known


def _check_jaxpr(jaxpr: Jaxpr, known: Dict[Any, Any], path: str,
                 findings: List[Finding]) -> None:
    # extend the evidence map with this level's placement pins
    for eqn in jaxpr.eqns:
        for ov in eqn.outvars:
            s = _eqn_out_sharding(eqn, ov)
            if s is not None:
                known[ov] = s

    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        sub_path = f"{path}/{name}"
        if name == "scan":
            body = as_jaxpr(eqn.params["jaxpr"])
            nc, ncar = scan_split(eqn)
            _check_loop_carries(
                "scan", body, eqn.invars[nc:nc + ncar],
                body.outvars[:ncar], known, sub_path, findings,
            )
            _check_jaxpr(
                body,
                _map_known(known, eqn.invars[:nc + ncar], body.invars),
                sub_path, findings,
            )
        elif name == "while":
            body = as_jaxpr(eqn.params["body_jaxpr"])
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            ncar = len(eqn.invars) - cn - bn
            _check_loop_carries(
                "while", body, eqn.invars[cn + bn:],
                body.outvars[:ncar], known, sub_path, findings,
            )
            _check_jaxpr(
                body,
                _map_known(known, eqn.invars[cn:], body.invars),
                sub_path, findings,
            )
        else:
            for key in ("jaxpr", "call_jaxpr", "fun_jaxpr", "branches",
                        "cond_jaxpr"):
                v = eqn.params.get(key)
                subs = v if isinstance(v, (list, tuple)) else [v]
                for s in subs:
                    if s is None:
                        continue
                    body = as_jaxpr(s)
                    body_known = (
                        _map_known(known, eqn.invars, body.invars)
                        if len(body.invars) == len(eqn.invars)
                        else {}
                    )
                    _check_jaxpr(body, body_known, f"{sub_path}.{key}",
                                 findings)


@register_rule("R2", "sharding-closure")
def sharding_closure(ctx: LintContext) -> List[Finding]:
    findings: List[Finding] = []
    known = {
        v: s
        for v, s in ctx.arg_shardings.items()
        if s is not None and sharding_fingerprint(s) is not None
    }
    _check_jaxpr(ctx.jaxpr, known, "", findings)
    return findings
