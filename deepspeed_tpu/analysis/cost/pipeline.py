"""Pipeline-schedule activation-memory estimator (analysis.cost).

The closed-form side of what ``tools/pipe_memory.py`` *measures*: the
scan+ppermute schedule's stash growth per microbatch, per policy, in
"boundary activation" units (one microbatch's stage-boundary tensor,
``mb*S*D*itemsize``). Constants come from the committed measurement
(docs/pipe_memory.md, perf/pipe_memory.json); the tool now prints its
measured column next to this prediction, so drift between the model and
XLA's actual buffer assignment is visible the day it appears.

Folded here from the tool (one estimator, satellite of ISSUE 4):
``auto_chunk`` (the 1f1b default chunk), ``boundary_bytes``,
``stash_boundaries`` (per-policy growth law), ``pipeline_temp_bytes``
and ``growth_per_microbatch`` (the slope fit the tool reports).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

# measured per-policy constants (docs/pipe_memory.md, virtual 8-CPU mesh):
# base = M-independent recompute working set + schedule plumbing;
# slope = boundary activations stashed per extra microbatch
_POLICY_LAWS = {
    # policy-key: (base_boundaries, slope_per_microbatch)
    "none": (65.0, 45.6),       # full layer internals stored every tick
    "gpipe": (41.0, 2.0),       # per-tick remat: carry + ppermute pair
    # 1f1b's M-dependent term is slope*M on TOP of the chunk-boundary
    # carries (ticks/C + 2C) the branch below adds
    "1f1b": (47.0, 1.1),        # chunked checkpoint: sqrt-ish growth
}


def auto_chunk(pp: int, M: int) -> int:
    """The 1f1b default tick chunk C ≈ max(pp, sqrt(T/2)) (mirrors
    PipelineModule.pipeline_loss)."""
    ticks = M + pp - 1
    return max(pp, int(round((ticks / 2) ** 0.5)))


def boundary_bytes(mb: int, seq: int, hidden: int, itemsize: int = 4) -> int:
    """One stage-boundary activation: [mb, S, D] at ``itemsize``."""
    return int(mb) * int(seq) * int(hidden) * int(itemsize)


def stash_boundaries(pp: int, M: int, policy: str = "1f1b",
                     tick_chunk: Optional[int] = None) -> float:
    """Predicted peak stash in boundary-activation units.

    ``policy`` is "none" (no remat — O(M) with the full-internals
    constant), "gpipe" (per-tick remat, plain scan — 2/microbatch), or
    "1f1b" (chunked checkpoint — T/C + 2C boundaries of M-dependent
    stash). An explicit ``tick_chunk`` pins C (config
    ``pipeline.activation_checkpoint_interval``)."""
    if policy not in _POLICY_LAWS:
        raise ValueError(
            f"policy must be one of {sorted(_POLICY_LAWS)}, got {policy!r}"
        )
    base, slope = _POLICY_LAWS[policy]
    ticks = M + pp - 1
    if policy == "1f1b":
        c = tick_chunk or auto_chunk(pp, M)
        # chunk-boundary carries + one replayed chunk + input stream copy
        return base + ticks / max(c, 1) + 2 * c + slope * M
    return base + slope * M


def pipeline_temp_bytes(pp: int, M: int, mb: int, seq: int, hidden: int,
                        policy: str = "1f1b",
                        tick_chunk: Optional[int] = None,
                        itemsize: int = 4) -> float:
    """Predicted peak temp bytes of one fwd+bwd pipeline pass."""
    return stash_boundaries(pp, M, policy, tick_chunk) * boundary_bytes(
        mb, seq, hidden, itemsize
    )


def growth_per_microbatch(points: Sequence[Tuple[int, float]],
                          act_bytes: float) -> float:
    """Endpoint slope of (M, temp_bytes) in boundary-activation units —
    the figure the measurement tool prints per (pp, policy) leg."""
    (m0, t0), (m1, t1) = points[0], points[-1]
    if m1 == m0 or act_bytes <= 0:
        return 0.0
    return (t1 - t0) / (m1 - m0) / act_bytes
