"""analysis.cost — static HBM-capacity + collective-cost planner.

Walks the same traced jaxpr shardlint lints (no execution, CPU mesh) and
computes per device: state bytes from the ShapeDtypeStructs and their
shardings, the activation live-set high-water mark through
scan/remat/donation, collective scratch and offload double-buffer slots,
plus an ICI/FLOPs/HBM roofline step estimate. Rules R6 (capacity) and
R8 (overlap-budget) consume it; ``tools/shardplan.py`` is the CLI.
"""

from .drift import (
    DriftLedger,
    band_for,
    by_tag as drift_by_tag,
    check as drift_check,
    entry_tag as drift_entry_tag,
    make_entry as drift_entry,
    recalibration_suggestion,
    summarize as drift_summary,
)
from .hardware import (HardwareModel, gen_defaults, gen_from_device_kind,
                       load_knob_table, lookup_knob_row, model_class,
                       topology_key)
from .pipeline import (
    auto_chunk,
    boundary_bytes,
    growth_per_microbatch,
    pipeline_temp_bytes,
    stash_boundaries,
)
from .planner import (
    Plan,
    format_plan_table,
    plan_config,
    plan_engine,
    plan_for_context,
    plan_jaxpr,
    scale_plan_micro,
    split_link_bytes,
)
from .walk import JaxprWalker, WalkStats, device_bytes, dimspec_from_sharding

__all__ = [
    "DriftLedger",
    "HardwareModel",
    "JaxprWalker",
    "Plan",
    "WalkStats",
    "auto_chunk",
    "band_for",
    "boundary_bytes",
    "device_bytes",
    "dimspec_from_sharding",
    "drift_by_tag",
    "drift_check",
    "drift_entry",
    "drift_entry_tag",
    "drift_summary",
    "format_plan_table",
    "gen_defaults",
    "growth_per_microbatch",
    "pipeline_temp_bytes",
    "plan_config",
    "plan_engine",
    "plan_for_context",
    "plan_jaxpr",
    "recalibration_suggestion",
    "scale_plan_micro",
    "split_link_bytes",
    "stash_boundaries",
]
