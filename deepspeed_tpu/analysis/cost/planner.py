"""shardplan: static HBM-capacity + collective-cost plans from jaxprs.

The same traced step program shardlint lints (abstract evaluation, CPU
mesh, no state materialization) carries everything needed to budget a
config before anything compiles:

- parameter / optimizer / master-weight bytes come straight from the
  state ShapeDtypeStructs and their shardings (exact — the planner and
  the materialized state count the same shard shapes);
- the activation live-set high-water mark, collective scratch and
  offload double-buffer slots come from the sharding-aware liveness walk
  (:mod:`.walk`), which credits donated and rotating buffers the same
  way rule R4 reasons about them;
- every named collective is classified by mesh axis into ICI wire bytes
  and hop counts, and combined with MXU FLOPs and HBM traffic into an
  analytic roofline step time (ZeRO++ arXiv:2306.10209 and T3
  arXiv:2401.16677 both budget training as bytes-moved vs
  compute-available; this makes that budget a checkable artifact).

Rules R6 (capacity) and R8 (overlap-budget) consume plans through
:func:`plan_for_context`; ``tools/shardplan.py`` and ``tools/shardlint.py
--report`` print them as per-config tables.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .hardware import HardwareModel
from .walk import JaxprWalker, device_bytes, dimspec_from_sharding

_GIB = float(1 << 30)


def _leaf_device_bytes(aval, sharding, mesh_sizes) -> float:
    """Per-device bytes of one state leaf under its (known) sharding."""
    shape = tuple(getattr(aval, "shape", ()))
    dtype = getattr(aval, "dtype", np.float32)
    shard_shape = None
    if sharding is not None:
        try:
            shard_shape = sharding.shard_shape(shape)
        except Exception:  # noqa: BLE001 — duck-typed / abstract shardings
            shard_shape = None
    if shard_shape is not None:
        return float(np.prod(shard_shape, dtype=np.int64) or 1) * float(
            np.dtype(dtype).itemsize
        )
    spec = dimspec_from_sharding(sharding, len(shape), mesh_sizes) \
        if sharding is not None else (1,) * len(shape)
    return device_bytes(shape, dtype, spec)


@dataclass
class Plan:
    """One config's static per-device budget (bytes, flops, seconds)."""

    source: str = "<jaxpr>"
    hardware: HardwareModel = field(default_factory=HardwareModel)
    n_devices: int = 1
    # ---- per-device HBM bytes ------------------------------------------
    param_bytes: float = 0.0         # model parameter leaves (device)
    opt_bytes: float = 0.0           # optimizer-state leaves (device)
    master_bytes: float = 0.0        # f32 master subset of the above
    other_state_bytes: float = 0.0   # loss scale, step counter, ...
    host_state_bytes: float = 0.0    # pinned-host-resident state (not HBM)
    act_peak_bytes: float = 0.0      # live-set high-water beyond state
    collective_scratch_bytes: float = 0.0
    offload_inflight_bytes: float = 0.0   # double-buffer slots (informational)
    peak_hbm_bytes: float = 0.0
    # ---- per-device per-step cost --------------------------------------
    flops: float = 0.0
    hbm_traffic_bytes: float = 0.0
    ici_bytes: Dict[str, float] = field(default_factory=dict)
    ici_hops: Dict[str, int] = field(default_factory=dict)
    # the subset of ici_bytes whose ring traverses a DCN-tagged axis
    # (hybrid meshes — priced at hardware.dcn_bw, not ici_bw)
    dcn_bytes: Dict[str, float] = field(default_factory=dict)
    link_kinds: Dict[str, str] = field(default_factory=dict)
    compute_s: float = 0.0
    hbm_s: float = 0.0
    ici_s: float = 0.0
    dcn_s: float = 0.0
    est_step_s: float = 0.0
    streams: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    seconds: float = 0.0             # planner wall time

    @property
    def state_bytes(self) -> float:
        return self.param_bytes + self.opt_bytes + self.other_state_bytes

    @property
    def ici_bytes_total(self) -> float:
        return sum(self.ici_bytes.values())

    def to_dict(self) -> Dict[str, Any]:
        return {
            "source": self.source,
            "n_devices": self.n_devices,
            "param_bytes": round(self.param_bytes),
            "opt_bytes": round(self.opt_bytes),
            "master_bytes": round(self.master_bytes),
            "other_state_bytes": round(self.other_state_bytes),
            "host_state_bytes": round(self.host_state_bytes),
            "act_peak_bytes": round(self.act_peak_bytes),
            "collective_scratch_bytes": round(self.collective_scratch_bytes),
            "offload_inflight_bytes": round(self.offload_inflight_bytes),
            "peak_hbm_bytes": round(self.peak_hbm_bytes),
            "peak_hbm_gib": round(self.peak_hbm_bytes / _GIB, 3),
            "flops": self.flops,
            "hbm_traffic_bytes": round(self.hbm_traffic_bytes),
            "ici_bytes": {k: round(v) for k, v in self.ici_bytes.items()},
            "ici_hops": dict(self.ici_hops),
            "dcn_bytes": {k: round(v) for k, v in self.dcn_bytes.items()},
            "compute_s": round(self.compute_s, 6),
            "hbm_s": round(self.hbm_s, 6),
            "ici_s": round(self.ici_s, 6),
            "dcn_s": round(self.dcn_s, 6),
            "est_step_s": round(self.est_step_s, 6),
            "hbm_budget_gib": round(self.hardware.hbm_bytes / _GIB, 3),
            "seconds": round(self.seconds, 3),
        }


_TABLE_COLS = (
    ("config", 34), ("params", 9), ("opt", 9), ("acts", 9), ("peak", 9),
    ("budget", 9), ("ICI/step", 9), ("est step", 9),
)


def format_plan_table(plans: Sequence[Plan]) -> str:
    """The per-config table shardplan, shardlint --report and the bench
    legs all print: params / opt-state / activations / peak GiB, ICI
    GiB/step, est. step seconds."""
    head = "".join(
        f"{name:<{w}}" if i == 0 else f"{name:>{w}}"
        for i, (name, w) in enumerate(_TABLE_COLS)
    )
    lines = [head, "-" * len(head)]
    for p in plans:
        gib = lambda b: f"{b / _GIB:.2f}G"  # noqa: E731
        over = p.peak_hbm_bytes > p.hardware.hbm_bytes
        lines.append(
            f"{p.source[:33]:<34}"
            f"{gib(p.param_bytes):>9}"
            f"{gib(p.opt_bytes):>9}"
            f"{gib(p.act_peak_bytes):>9}"
            f"{gib(p.peak_hbm_bytes):>9}"
            f"{gib(p.hardware.hbm_bytes) + ('!' if over else ''):>9}"
            f"{gib(p.ici_bytes_total):>9}"
            f"{p.est_step_s:>8.4f}s"
        )
    return "\n".join(lines)


def split_link_bytes(
    ici_bytes: Dict[str, float], link_kinds: Dict[str, str]
) -> Tuple[Dict[str, float], Dict[str, float]]:
    """Classify each collective's wire bytes by the links its ring
    traverses: (ici-only, dcn-crossing). Keys are the walker's
    "+"-joined axis sets; a ring whose axis set touches ANY DCN-tagged
    axis is throttled end-to-end by the slow fabric (its hops are
    synchronized — the flat form's whole payload crawls at dcn_bw, which
    is exactly why the 2-hop decomposition wins). Axis-less keys ("?")
    stay ICI."""
    if not link_kinds:
        return dict(ici_bytes), {}
    ici: Dict[str, float] = {}
    dcn: Dict[str, float] = {}
    for key, b in ici_bytes.items():
        axes = key.split("+")
        bucket = dcn if any(link_kinds.get(a) == "dcn" for a in axes) else ici
        bucket[key] = b
    return ici, dcn


def _reprice_links(plan: Plan) -> None:
    """Recompute the wire seconds + roofline max from the plan's byte
    dicts (shared by plan_jaxpr and scale_plan_micro)."""
    hw = plan.hardware
    ici_only = {
        k: v for k, v in plan.ici_bytes.items() if k not in plan.dcn_bytes
    }
    plan.ici_s = max(
        (b / hw.ici_bw for b in ici_only.values()), default=0.0
    ) if hw.ici_bw else 0.0
    dcn_bw = float(getattr(hw, "dcn_bw", 0.0) or 0.0)
    plan.dcn_s = max(
        (b / dcn_bw for b in plan.dcn_bytes.values()), default=0.0
    ) if dcn_bw else 0.0
    plan.est_step_s = max(plan.compute_s, plan.hbm_s, plan.ici_s, plan.dcn_s)


def plan_jaxpr(
    closed_jaxpr,
    *,
    mesh=None,
    arg_shardings: Optional[Dict[Any, Any]] = None,
    donated_invars: Sequence[int] = (),
    invar_groups: Optional[Dict[str, Tuple[int, int]]] = None,
    streams: Optional[Dict[str, Dict[str, Any]]] = None,
    hardware: Optional[HardwareModel] = None,
    link_kinds: Optional[Dict[str, str]] = None,
    source: str = "<jaxpr>",
) -> Plan:
    """Budget one traced program. All inputs are the same evidence
    shardlint already collects (see LintContext); ``invar_groups`` maps
    state-group names ("params"/"opt_state"/...) to flat invar index
    ranges so the byte columns split exactly like the engine state."""
    t0 = time.time()
    hw = hardware or HardwareModel.detect()
    arg_shardings = arg_shardings or {}
    jaxpr = closed_jaxpr.jaxpr
    mesh_sizes: Dict[str, int] = {}
    if mesh is not None:
        try:
            mesh_sizes = {str(k): int(v) for k, v in dict(mesh.shape).items()}
        except Exception:  # noqa: BLE001
            mesh_sizes = {}
    n_devices = 1
    for v in mesh_sizes.values():
        n_devices *= v

    invars = list(jaxpr.invars)
    donated = set(int(i) for i in donated_invars)
    groups = invar_groups or {}

    def group_of(i: int) -> str:
        for name, (lo, hi) in groups.items():
            if lo <= i < hi:
                return name
        return "other"

    in_specs, host_flags, donated_flags = [], [], []
    device_state = 0.0  # state bytes the walk's live set holds on device
    plan = Plan(source=source, hardware=hw, n_devices=n_devices)
    for i, v in enumerate(invars):
        s = arg_shardings.get(v)
        nd = len(getattr(v.aval, "shape", ()))
        in_specs.append(
            dimspec_from_sharding(s, nd, mesh_sizes)
            if s is not None else (1,) * nd
        )
        is_host = getattr(s, "memory_kind", None) == "pinned_host"
        host_flags.append(is_host)
        # without explicit donation evidence, assume the caller keeps its
        # argument buffers resident (the conservative direction for an
        # OOM check) — only engine-traced donated_invars earn the credit
        donated_flags.append(i in donated)
        b = _leaf_device_bytes(v.aval, s, mesh_sizes)
        g = group_of(i)
        if is_host:
            plan.host_state_bytes += b
            continue
        if g == "params":
            plan.param_bytes += b
        elif g == "opt_state":
            plan.opt_bytes += b
        elif g in ("loss_scale", "step"):
            plan.other_state_bytes += b
        if g in ("params", "opt_state", "loss_scale", "step"):
            device_state += b
        if g in ("params", "opt_state") and str(
            getattr(v.aval, "dtype", "")
        ) == "float32":
            plan.master_bytes += b

    walker = JaxprWalker(mesh_sizes)
    peak, _ = walker.walk(
        jaxpr, in_specs, donated=donated_flags, host_resident=host_flags
    )
    st = walker.stats
    # the walk counts its own live inputs; state not in the walk's device
    # live set (host leaves) was handled above
    plan.collective_scratch_bytes = st.collective_scratch
    plan.peak_hbm_bytes = peak + st.collective_scratch
    plan.act_peak_bytes = max(peak - device_state, 0.0)
    plan.flops = st.flops
    plan.hbm_traffic_bytes = st.hbm_bytes
    plan.ici_bytes = dict(st.ici_bytes)
    plan.ici_hops = dict(st.ici_hops)
    plan.streams = dict(streams or {})
    for s in plan.streams.values():
        if s.get("kind") == "offload":
            plan.offload_inflight_bytes = max(
                plan.offload_inflight_bytes,
                float(s.get("per_device_inflight_bytes", 0.0)),
            )
    plan.link_kinds = dict(link_kinds or {})
    _, plan.dcn_bytes = split_link_bytes(plan.ici_bytes, plan.link_kinds)
    plan.compute_s = st.flops / hw.peak_flops if hw.peak_flops else 0.0
    plan.hbm_s = st.hbm_bytes / hw.hbm_bw if hw.hbm_bw else 0.0
    _reprice_links(plan)
    plan.seconds = time.time() - t0
    return plan


def scale_plan_micro(plan: Plan, factor: float,
                     source: Optional[str] = None) -> Plan:
    """Derive a larger-micro-batch Plan from a traced one by scaling the
    batch-linear terms (activation live set, flops, HBM traffic, ICI
    payloads) by ``factor`` while state bytes stay fixed.

    This is the autotuner's memoized fast-prune path: once micro=m at a
    (stage, remat) rung is statically over budget, every larger micro at
    the same rung is *at least* this plan scaled up — deriving it skips
    a second abstract trace, and the direction of every approximation
    (grad-reduce ICI does not actually grow with micro, collective
    scratch is held) only matters for rungs that are already doomed.
    Rank-bearing survivors are always traced, never scaled."""
    from dataclasses import replace

    f = float(factor)
    scaled = replace(
        plan,
        source=source or f"{plan.source} (x{f:g} micro, derived)",
        act_peak_bytes=plan.act_peak_bytes * f,
        peak_hbm_bytes=plan.peak_hbm_bytes
        + plan.act_peak_bytes * (f - 1.0),
        flops=plan.flops * f,
        hbm_traffic_bytes=plan.hbm_traffic_bytes * f,
        ici_bytes={k: v * f for k, v in plan.ici_bytes.items()},
        ici_hops=dict(plan.ici_hops),
        dcn_bytes={k: v * f for k, v in plan.dcn_bytes.items()},
        link_kinds=dict(plan.link_kinds),
        streams=dict(plan.streams),
        seconds=0.0,
    )
    hw = scaled.hardware
    scaled.compute_s = scaled.flops / hw.peak_flops if hw.peak_flops else 0.0
    scaled.hbm_s = scaled.hbm_traffic_bytes / hw.hbm_bw if hw.hbm_bw else 0.0
    _reprice_links(scaled)
    return scaled


def plan_for_context(ctx) -> Plan:
    """The plan for one LintContext (cached on the context — R6 and R8
    share a single walk)."""
    cached = getattr(ctx, "_plan", None)
    if cached is not None:
        return cached
    hw = ctx.hardware or HardwareModel.detect()
    if ctx.hbm_budget_bytes is not None:
        from dataclasses import replace

        hw = replace(hw, hbm_bytes=float(ctx.hbm_budget_bytes))
    plan = plan_jaxpr(
        ctx.closed_jaxpr,
        mesh=ctx.mesh,
        arg_shardings=ctx.arg_shardings,
        donated_invars=ctx.donated_invars,
        invar_groups=ctx.invar_groups,
        streams=ctx.streams,
        hardware=hw,
        link_kinds=getattr(ctx, "link_kinds", None),
        source=ctx.source,
    )
    ctx._plan = plan
    return plan


# ------------------------------------------------------------- engine plans
def plan_engine(engine, source: Optional[str] = None,
                hardware: Optional[HardwareModel] = None) -> Plan:
    """Trace one engine's train step (abstract — works on concrete and
    ``abstract_init=True`` engines alike) and budget it."""
    from ..shardlint import trace_train_step

    closed, arg_shardings, _pairs, _out, meta = trace_train_step(engine)
    streams = {}
    if hasattr(engine, "analytic_streams"):
        streams = engine.analytic_streams(include_potential=True)
    return plan_jaxpr(
        closed,
        mesh=engine.topology.mesh,
        arg_shardings=arg_shardings,
        donated_invars=meta.get("donated_invars", ()),
        invar_groups=meta.get("invar_groups", {}),
        streams=streams,
        hardware=hardware,
        link_kinds=getattr(engine.topology, "link_kinds", None),
        source=source or f"engine[{type(engine).__name__}]",
    )


def plan_config(config, model=None, topology=None,
                source: Optional[str] = None,
                hardware: Optional[HardwareModel] = None) -> Plan:
    """ds_config (+ model) → abstract engine → plan. Mirrors
    :func:`analysis.lint_config`; nothing materializes."""
    import deepspeed_tpu

    if model is None:
        raise ValueError("plan_config requires a model (the step program "
                         "is model-shaped)")
    engine, *_ = deepspeed_tpu.initialize(
        model=model, config=config, topology=topology, abstract_init=True
    )
    try:
        return plan_engine(engine, source=source, hardware=hardware)
    finally:
        engine.destroy()
