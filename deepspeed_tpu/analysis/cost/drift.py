"""Drift ledger: the predicted-vs-measured pairs that keep shardplan honest.

The planner's whole value is that a Plan's roofline can stand in for a
compile-and-measure probe (autotuning/planner_search.py prunes and ranks
on it). That substitution is only safe while predictions track reality,
so every measured survivor banks a ``(predicted, measured)`` pair here:

- ``bench.py`` appends one entry per BENCH run (``result["plan"]`` now
  carries the drift verdict alongside the prediction);
- the autotuner appends one entry per compiled top-k survivor;
- ``tools/autoplan.py --check`` is the CI regression gate: it re-runs
  the search on the reduced 410M leg, banks fresh pairs, and exits 1
  when any pair leaves the documented band.

Systematic drift — the *median* ratio of several same-generation entries
leaving the recalibration band — produces a concrete suggestion for the
``cost/hardware.py`` constant that is actually binding (peak_flops for
compute-bound steps, hbm_bw / ici_bw otherwise). The ledger never edits
the table itself: recalibration is a reviewed change, not a side effect.

Bands (documented in docs/autotuning.md):

- TPU generations: predicted/measured step time within [0.5, 2.0] —
  the roofline ignores launch overhead and imperfect overlap, so a
  factor-2 envelope is the honest claim.
- ``cpu`` generation (the lint/CI host mesh): [1/25, 25] — host speed
  varies wildly across machines; the band exists to catch cost-model
  breakage (flops or bytes off by orders of magnitude), not to grade
  the host envelope.
- Within ONE run, the survivor ratios must agree with each other to a
  factor of ``SPREAD_BAND`` — relative pricing (the thing ranking
  depends on) is machine-independent and held to a tighter standard.
- Peak-HBM predictions vs XLA's ``memory_analysis()``: [0.90, 1.10]
  (the re-tightened ISSUE-4 band).
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

BANDS: Dict[str, Tuple[float, float]] = {"cpu": (1 / 25.0, 25.0)}
DEFAULT_BAND: Tuple[float, float] = (0.5, 2.0)
SPREAD_BAND: float = 3.0
PEAK_BAND: Tuple[float, float] = (0.90, 1.10)
# the CI gate's anchor-program band: the ±10% claim is calibrated on the
# full 410M stage-0 leg (tier-1 test); the gate's reduced anchor leaves
# a little room for model-size and jax-version variation while still
# catching real liveness-model breakage
GATE_PEAK_BAND: Tuple[float, float] = (0.85, 1.15)
RECAL_BAND: Tuple[float, float] = (0.8, 1.25)
RECAL_MIN_SAMPLES: int = 3

_BOUND_CONSTANT = {"compute": "peak_flops", "hbm": "hbm_bw", "ici": "ici_bw"}


def band_for(gen: str) -> Tuple[float, float]:
    return BANDS.get(gen, DEFAULT_BAND)


def check_pair(predicted: Optional[float], measured: Optional[float],
               gen: str, *, ratio: Optional[float] = None,
               band: Optional[Tuple[float, float]] = None
               ) -> Dict[str, Any]:
    """ONE (predicted, measured) pair against its generation's band —
    THE definition of "drifted", shared by the offline ledger gate
    (:func:`check`), bench.py's per-run verdict and the healthwatch
    live drift alarm (profiling/healthwatch.py ``plan_drift``), so the
    band constants exist exactly once.

    Returns ``{"ok", "ratio", "band", "gen"}``; an unmeasurable pair
    (measured <= 0 / None) yields ``ratio None, ok False``. Callers
    holding a precomputed ratio (ledger rows) pass ``ratio=``; ``band=``
    overrides the generation lookup (the gate's --band flag)."""
    if ratio is None and predicted is not None and measured:
        try:
            if float(measured) > 0:
                ratio = float(predicted) / float(measured)
        except (TypeError, ValueError):
            ratio = None
    lo, hi = band if band is not None else band_for(gen)
    ok = isinstance(ratio, (int, float)) and lo <= ratio <= hi
    return {
        "ok": bool(ok),
        "ratio": round(ratio, 6) if isinstance(ratio, (int, float))
        else None,
        "band": (round(lo, 6), round(hi, 6)),
        "gen": gen,
    }


def default_ledger_path() -> str:
    """``SHARDPLAN_DRIFT_LEDGER`` env override, else a stable per-user
    cache location — NOT the cwd: planner-mode autotuning auto-engages
    for library callers, and a library must not scatter perf/ dirs
    wherever the process happens to run. bench.py and the CI gate pass
    explicit repo-anchored paths."""
    return os.environ.get(
        "SHARDPLAN_DRIFT_LEDGER",
        os.path.join(os.path.expanduser("~"), ".cache", "deepspeed_tpu",
                     "drift.jsonl"),
    )


def binding_term(plan) -> str:
    """Which roofline term set ``est_step_s`` — the constant a
    recalibration would touch."""
    terms = {"compute": plan.compute_s, "hbm": plan.hbm_s,
             "ici": plan.ici_s}
    return max(terms, key=terms.get)


def make_entry(plan, measured_step_s: float, *, source: str,
               extra: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    """One ledger row from a Plan and a wall clock. ``ratio`` is
    predicted/measured: < 1 means the machine ran slower than the
    envelope, > 1 means the plan over-charged the step."""
    measured = float(measured_step_s)
    entry: Dict[str, Any] = {
        "ts": round(time.time(), 1),
        "source": source,
        "gen": plan.hardware.gen,
        "predicted_step_s": round(float(plan.est_step_s), 6),
        "measured_step_s": round(measured, 6),
        "ratio": round(float(plan.est_step_s) / measured, 6)
        if measured > 0 else None,
        "bound": binding_term(plan),
        "predicted_peak_gib": round(plan.peak_hbm_bytes / (1 << 30), 3),
    }
    if extra:
        entry.update(extra)
    return entry


class DriftLedger:
    """Append-only JSONL of drift entries (one file, many runs)."""

    def __init__(self, path: Optional[str] = None):
        self.path = path or default_ledger_path()

    def append(self, entry: Dict[str, Any]) -> None:
        """Best-effort append: an unwritable ledger (read-only CI
        checkout, a path component that's a file, missing permissions)
        logs ONE warning and drops the entry — the ledger is evidence,
        and evidence-keeping must never crash a bench or tuner run."""
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            with open(self.path, "a") as f:
                f.write(json.dumps(entry) + "\n")
        except OSError as e:
            from ...utils.logging import logger

            logger.warning(
                f"drift ledger unwritable ({self.path}): {e} — entry "
                "dropped, run continues (set SHARDPLAN_DRIFT_LEDGER to "
                "a writable path to keep banking pairs)"
            )

    def load(self, gen: Optional[str] = None,
             source: Optional[str] = None,
             tag: Optional[str] = None) -> List[Dict[str, Any]]:
        """All parseable rows, newest last; unreadable lines are skipped
        (the ledger is evidence, never a point of failure). ``tag``
        filters on the entry's tag group (``entry_tag``): pass
        ``"campaign"`` for campaign rows, ``"adhoc"`` for everything
        untagged."""
        rows: List[Dict[str, Any]] = []
        try:
            with open(self.path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rows.append(json.loads(line))
                    except ValueError:
                        continue
        except OSError:
            # missing file, unreadable path, path component that's a
            # file — no evidence is just an empty ledger, never a crash
            return []
        if gen is not None:
            rows = [r for r in rows if r.get("gen") == gen]
        if source is not None:
            rows = [r for r in rows if r.get("source") == source]
        if tag is not None:
            rows = [r for r in rows if entry_tag(r) == tag]
        return rows


def entry_tag(entry: Dict[str, Any]) -> str:
    """The entry's band-bookkeeping group: campaign runs tag their rows
    (``"tag": "campaign"``, tools/autoplan.py --campaign), everything
    historical/ad-hoc is the ``"adhoc"`` group. Spread statistics never
    mix groups: a campaign's lattice legs are deliberately heterogeneous
    (different knob settings price differently — that's the point), so
    pooling them with ad-hoc single-config runs would poison the
    relative-pricing medians both gates rely on."""
    return str(entry.get("tag") or "adhoc")


def by_tag(entries: Sequence[Dict[str, Any]]
           ) -> Dict[str, List[Dict[str, Any]]]:
    """Entries grouped by their :func:`entry_tag`, insertion-ordered."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for r in entries:
        groups.setdefault(entry_tag(r), []).append(r)
    return groups


def summarize(entries: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    ratios = sorted(
        r["ratio"] for r in entries if isinstance(r.get("ratio"), (int, float))
    )
    if not ratios:
        return {"n": 0}
    mid = len(ratios) // 2
    median = (ratios[mid] if len(ratios) % 2
              else 0.5 * (ratios[mid - 1] + ratios[mid]))
    return {
        "n": len(ratios),
        "median_ratio": round(median, 4),
        "min_ratio": round(ratios[0], 4),
        "max_ratio": round(ratios[-1], 4),
        "spread": round(ratios[-1] / ratios[0], 4) if ratios[0] > 0 else None,
    }


def check(entries: Sequence[Dict[str, Any]],
          band: Optional[Tuple[float, float]] = None,
          spread_band: float = SPREAD_BAND) -> Tuple[bool, List[str]]:
    """The regression gate: every entry's ratio inside its generation's
    band, and the entries' ratios within ``spread_band`` of each other
    (relative pricing is what ranking rides on). Returns (ok, problems).
    Entries carrying ``peak_ratio`` are additionally held to PEAK_BAND."""
    problems: List[str] = []
    for r in entries:
        ratio = r.get("ratio")
        if not isinstance(ratio, (int, float)):
            problems.append(f"{r.get('source', '?')}: unmeasurable entry "
                            f"(ratio={ratio!r})")
            continue
        # the ONE drifted-pair predicate (shared with the healthwatch
        # live alarm and bench's per-run verdict)
        verdict = check_pair(None, None, r.get("gen", ""), ratio=ratio,
                             band=band)
        if not verdict["ok"]:
            lo, hi = verdict["band"]
            problems.append(
                f"{r.get('source', '?')}: predicted/measured step ratio "
                f"{ratio:.3f} outside [{lo:.3g}, {hi:.3g}] "
                f"({r.get('bound', '?')}-bound, gen {r.get('gen', '?')})"
            )
        pk = r.get("peak_ratio")
        if isinstance(pk, (int, float)) and not (
            PEAK_BAND[0] <= pk <= PEAK_BAND[1]
        ):
            problems.append(
                f"{r.get('source', '?')}: predicted/measured HBM peak "
                f"ratio {pk:.3f} outside "
                f"[{PEAK_BAND[0]}, {PEAK_BAND[1]}]"
            )
    # spread is judged PER TAG GROUP: campaign rows and ad-hoc rows keep
    # separate band bookkeeping (a campaign's lattice legs are
    # heterogeneous by design; pooling them with single-config runs
    # would manufacture false spread alarms — or mask real ones)
    for tag, rows in by_tag(entries).items():
        s = summarize(rows)
        if s.get("n", 0) >= 2 and s.get("spread") and (
            s["spread"] > spread_band
        ):
            problems.append(
                f"[{tag}] survivor ratios disagree by {s['spread']:.2f}x "
                f"(> {spread_band}x): relative pricing drifted — the "
                "ranking itself is suspect"
            )
    return not problems, problems


def recalibration_suggestion(entries: Sequence[Dict[str, Any]],
                             hardware=None) -> Optional[str]:
    """With enough same-generation samples whose *median* ratio leaves
    RECAL_BAND, name the binding ``cost/hardware.py`` constant and the
    value that would center the ledger (new = old × median ratio: the
    roofline term is constant-inverse, so scaling the constant by the
    ratio maps the median prediction onto the measurement)."""
    by_gen: Dict[str, List[Dict[str, Any]]] = {}
    for r in entries:
        if isinstance(r.get("ratio"), (int, float)):
            by_gen.setdefault(r.get("gen", "?"), []).append(r)
    for gen, rows in by_gen.items():
        if len(rows) < RECAL_MIN_SAMPLES:
            continue
        s = summarize(rows)
        med = s["median_ratio"]
        if RECAL_BAND[0] <= med <= RECAL_BAND[1]:
            continue
        bounds = [r.get("bound", "compute") for r in rows]
        bound = max(set(bounds), key=bounds.count)
        const = _BOUND_CONSTANT.get(bound, "peak_flops")
        old = None
        if hardware is not None and getattr(hardware, "gen", None) == gen:
            old = getattr(hardware, const, None)
        else:
            from .hardware import gen_defaults

            old = gen_defaults(gen).get(const)
        if not old:
            continue
        new = old * med
        return (
            f"systematic drift on gen '{gen}': median predicted/measured "
            f"{med:.2f} over {len(rows)} {bound}-bound samples — suggest "
            f"cost/hardware.py {const} {old:.3g} -> {new:.3g} "
            "(recalibrate, review, commit; the ledger never edits the "
            "table itself)"
        )
    return None
