"""Hardware envelope the planner prices programs against.

One dataclass, per-TPU-generation defaults (same table bench.py uses for
MFU), env-var overrides shared with the bench legs so a BENCH run and its
shardplan prediction price the same machine:

- ``PALLAS_AXON_TPU_GEN``    chip generation ("v4"/"v5e"/"v5p"/"v6e",
                             or "cpu" for the host-mesh envelope)
- ``BENCH_HOST_BW_GBS``      host<->HBM DMA link, GB/s (offload stream)
- ``BENCH_ICI_BW_GBS``       per-link ICI bandwidth, GB/s (ring hops)
- ``SHARDPLAN_HBM_GB``       per-device HBM capacity budget override

Everything is per *device*: the planner's byte and flop counts are
per-device too, so seconds fall straight out.

When no generation is pinned and the active jax backend is the CPU (the
lint/test/CI mesh), detection falls back to the ``cpu`` row — a
deliberately rough envelope of one virtual host device on a shared
8-device mesh, calibrated against measured 410M-family steps so the
drift ledger (:mod:`.drift`) compares a CPU prediction with a CPU wall
clock instead of pricing the host like a v5e.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Dict, Tuple

_GIB = float(1 << 30)

# (bf16 peak flops, HBM bytes, HBM GB/s) per generation. Peaks match
# bench.peak_flops_per_chip; HBM bandwidth is the published spec number.
# The "cpu" row is the virtual-host-device envelope: ~3 GF/s effective
# per device on a contended 8-device host mesh (measured, see
# docs/autotuning.md "Drift bands"), 16 GiB as a neutral budget column.
_GEN_TABLE = {
    "v4": (275e12, 32 * _GIB, 1228e9),
    "v5e": (197e12, 16 * _GIB, 819e9),
    "v5p": (459e12, 95 * _GIB, 2765e9),
    "v6e": (918e12, 32 * _GIB, 1640e9),
    "cpu": (3e9, 16 * _GIB, 3e9),
}

# per-generation (ici GB/s, host-DMA GB/s) defaults when the bench env
# overrides are unset; TPU gens share the historical 45/32 numbers
_LINK_TABLE = {"cpu": (1.0, 3.0)}
_LINK_DEFAULT = (45.0, 32.0)


def gen_defaults(gen: str) -> Dict[str, float]:
    """The raw table row for one generation (the constants the drift
    ledger's recalibration suggestion talks about)."""
    flops, hbm, hbm_bw = _GEN_TABLE.get(gen, _GEN_TABLE["v5e"])
    ici, host = _LINK_TABLE.get(gen, _LINK_DEFAULT)
    return {"peak_flops": flops, "hbm_bytes": hbm, "hbm_bw": hbm_bw,
            "ici_bw": ici * 1e9, "host_bw": host * 1e9}


def _local_backend_is_cpu() -> bool:
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — backend not initialisable here
        return False


@dataclass
class HardwareModel:
    """Per-device capability numbers the roofline and budget checks use."""

    gen: str = "v5e"
    peak_flops: float = 197e12        # bf16 MXU peak, flops/s
    hbm_bytes: float = 16 * _GIB      # HBM capacity (the default R6 budget)
    hbm_bw: float = 819e9             # HBM bandwidth, bytes/s
    ici_bw: float = 45e9              # per-link ICI bandwidth, bytes/s
    host_bw: float = 32e9             # host DMA link, bytes/s

    @classmethod
    def detect(cls) -> "HardwareModel":
        """Defaults for the local generation + the bench env overrides.

        ``PALLAS_AXON_TPU_GEN`` pins the generation; otherwise a live
        CPU backend selects the ``cpu`` envelope (so lint-mesh plans and
        drift checks price the machine that actually runs them) and
        anything else keeps the historical v5e default."""
        gen = os.environ.get("PALLAS_AXON_TPU_GEN")
        if not gen:
            gen = "cpu" if _local_backend_is_cpu() else "v5e"
        d = gen_defaults(gen)
        hbm = d["hbm_bytes"]
        hbm_gb = os.environ.get("SHARDPLAN_HBM_GB")
        if hbm_gb:
            hbm = float(hbm_gb) * _GIB
        ici_env = os.environ.get("BENCH_ICI_BW_GBS")
        host_env = os.environ.get("BENCH_HOST_BW_GBS")
        return cls(
            gen=gen,
            peak_flops=d["peak_flops"],
            hbm_bytes=hbm,
            hbm_bw=d["hbm_bw"],
            ici_bw=float(ici_env) * 1e9 if ici_env else d["ici_bw"],
            host_bw=float(host_env) * 1e9 if host_env else d["host_bw"],
        )
