"""Hardware envelope the planner prices programs against.

One dataclass, per-TPU-generation defaults (same table bench.py uses for
MFU), env-var overrides shared with the bench legs so a BENCH run and its
shardplan prediction price the same machine:

- ``PALLAS_AXON_TPU_GEN``    chip generation ("v4"/"v5e"/"v5p"/"v6e")
- ``BENCH_HOST_BW_GBS``      host<->HBM DMA link, GB/s (offload stream)
- ``BENCH_ICI_BW_GBS``       per-link ICI bandwidth, GB/s (ring hops)
- ``SHARDPLAN_HBM_GB``       per-device HBM capacity budget override

Everything is per *device*: the planner's byte and flop counts are
per-device too, so seconds fall straight out.
"""

from __future__ import annotations

import os
from dataclasses import dataclass

_GIB = float(1 << 30)

# (bf16 peak flops, HBM bytes, HBM GB/s) per generation. Peaks match
# bench.peak_flops_per_chip; HBM bandwidth is the published spec number.
_GEN_TABLE = {
    "v4": (275e12, 32 * _GIB, 1228e9),
    "v5e": (197e12, 16 * _GIB, 819e9),
    "v5p": (459e12, 95 * _GIB, 2765e9),
    "v6e": (918e12, 32 * _GIB, 1640e9),
}


@dataclass
class HardwareModel:
    """Per-device capability numbers the roofline and budget checks use."""

    gen: str = "v5e"
    peak_flops: float = 197e12        # bf16 MXU peak, flops/s
    hbm_bytes: float = 16 * _GIB      # HBM capacity (the default R6 budget)
    hbm_bw: float = 819e9             # HBM bandwidth, bytes/s
    ici_bw: float = 45e9              # per-link ICI bandwidth, bytes/s
    host_bw: float = 32e9             # host DMA link, bytes/s

    @classmethod
    def detect(cls) -> "HardwareModel":
        """Defaults for the local generation + the bench env overrides."""
        gen = os.environ.get("PALLAS_AXON_TPU_GEN", "v5e")
        flops, hbm, hbm_bw = _GEN_TABLE.get(gen, _GEN_TABLE["v5e"])
        hbm_gb = os.environ.get("SHARDPLAN_HBM_GB")
        if hbm_gb:
            hbm = float(hbm_gb) * _GIB
        return cls(
            gen=gen,
            peak_flops=flops,
            hbm_bytes=hbm,
            hbm_bw=hbm_bw,
            ici_bw=float(os.environ.get("BENCH_ICI_BW_GBS", 45)) * 1e9,
            host_bw=float(os.environ.get("BENCH_HOST_BW_GBS", 32)) * 1e9,
        )
