"""Hardware envelope the planner prices programs against.

One dataclass, per-TPU-generation defaults (same table bench.py uses for
MFU), env-var overrides shared with the bench legs so a BENCH run and its
shardplan prediction price the same machine:

- ``PALLAS_AXON_TPU_GEN``    chip generation ("v4"/"v5e"/"v5p"/"v6e",
                             or "cpu" for the host-mesh envelope)
- ``BENCH_HOST_BW_GBS``      host<->HBM DMA link, GB/s (offload stream)
- ``BENCH_ICI_BW_GBS``       per-link ICI bandwidth, GB/s (ring hops)
- ``BENCH_DCN_BW_GBS``       per-device inter-pod DCN bandwidth, GB/s
                             (hybrid-mesh hops over DCN-tagged axes)
- ``SHARDPLAN_HBM_GB``       per-device HBM capacity budget override

Everything is per *device*: the planner's byte and flop counts are
per-device too, so seconds fall straight out.

When no generation is pinned and the active jax backend is the CPU (the
lint/test/CI mesh), detection falls back to the ``cpu`` row — a
deliberately rough envelope of one virtual host device on a shared
8-device mesh, calibrated against measured 410M-family steps so the
drift ledger (:mod:`.drift`) compares a CPU prediction with a CPU wall
clock instead of pricing the host like a v5e.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

_GIB = float(1 << 30)

# (bf16 peak flops, HBM bytes, HBM GB/s) per generation. Peaks match
# bench.peak_flops_per_chip; HBM bandwidth is the published spec number.
# The "cpu" row is the virtual-host-device envelope: ~3 GF/s effective
# per device on a contended 8-device host mesh (measured, see
# docs/autotuning.md "Drift bands"), 16 GiB as a neutral budget column.
_GEN_TABLE = {
    "v4": (275e12, 32 * _GIB, 1228e9),
    "v5e": (197e12, 16 * _GIB, 819e9),
    "v5p": (459e12, 95 * _GIB, 2765e9),
    "v6e": (918e12, 32 * _GIB, 1640e9),
    "cpu": (3e9, 16 * _GIB, 3e9),
}

# per-generation (ici GB/s, host-DMA GB/s, dcn GB/s) defaults when the
# bench env overrides are unset; TPU gens share the historical 45/32
# numbers. The DCN figure is deliberately conservative: ~25 Gbit/s of
# per-device share on the inter-pod data-center network (a 4x-NIC host
# divided over its chips), an order of magnitude under any ICI link —
# the gap that makes the 2-hop hierarchical forms win.
_LINK_TABLE = {"cpu": (1.0, 3.0, 0.25)}
_LINK_DEFAULT = (45.0, 32.0, 3.125)


def gen_defaults(gen: str) -> Dict[str, float]:
    """The raw table row for one generation (the constants the drift
    ledger's recalibration suggestion talks about)."""
    flops, hbm, hbm_bw = _GEN_TABLE.get(gen, _GEN_TABLE["v5e"])
    ici, host, dcn = _LINK_TABLE.get(gen, _LINK_DEFAULT)
    return {"peak_flops": flops, "hbm_bytes": hbm, "hbm_bw": hbm_bw,
            "ici_bw": ici * 1e9, "host_bw": host * 1e9,
            "dcn_bw": dcn * 1e9}


def _local_backend_is_cpu() -> bool:
    try:
        import jax

        return jax.default_backend() == "cpu"
    except Exception:  # noqa: BLE001 — backend not initialisable here
        return False


# device_kind substrings, checked IN ORDER ("v5p" must win before the
# bare "v5" fallback; the lite parts report "TPU v5 lite"/"TPU v6 lite"
# or the short "v5e"/"v6e" spelling depending on the runtime version)
_DEVICE_KIND_GENS: Tuple[Tuple[str, str], ...] = (
    ("v6e", "v6e"),
    ("v6 lite", "v6e"),
    ("v6", "v6e"),
    ("v5e", "v5e"),
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5p", "v5p"),
    ("v5", "v5p"),
    ("v4", "v4"),
)
_WARNED_KINDS: set = set()


def gen_from_device_kind(kind: Optional[str]) -> Optional[str]:
    """Map ``jax.devices()[0].device_kind`` to a `_GEN_TABLE` generation.

    Returns None for kinds the table has no row for (v2/v3, emulators,
    future chips) — the caller falls back to v5e with a ONE-TIME warning
    per unknown kind, so a fleet of new chips prices consistently instead
    of spamming every engine build."""
    if not kind:
        return None
    k = str(kind).lower()
    for sub, gen in _DEVICE_KIND_GENS:
        if sub in k:
            return gen
    return None


def detect_gen() -> str:
    """The generation `HardwareModel.detect()` prices: the
    ``PALLAS_AXON_TPU_GEN`` env pin wins; a live TPU backend reads the
    real ``device_kind`` (unknown kinds → v5e + one-time warning); a CPU
    backend selects the ``cpu`` envelope; anything else keeps the
    historical v5e default."""
    gen = os.environ.get("PALLAS_AXON_TPU_GEN")
    if gen:
        return gen
    kind = None
    try:
        import jax

        backend = jax.default_backend()
        if backend == "cpu":
            return "cpu"
        if backend == "tpu":
            kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 — backend not initialisable here
        return "v5e"
    g = gen_from_device_kind(kind)
    if g is not None:
        return g
    if kind and kind not in _WARNED_KINDS:
        _WARNED_KINDS.add(kind)
        try:
            from ...utils.logging import logger

            logger.warning(
                f"hardware: unknown TPU device_kind {kind!r} — pricing as "
                "v5e (add a _GEN_TABLE row / _DEVICE_KIND_GENS entry for "
                "honest rooflines on this chip; the v5e fallback also "
                "supplies its DCN figure, so hybrid-mesh inter-pod hops "
                "price at the conservative default instead of this "
                "chip's real DCN share — set BENCH_DCN_BW_GBS to pin it)"
            )
        except Exception:  # noqa: BLE001 — never block detection on logging
            pass
    return "v5e"


# ---------------------------------------------------------------------------
# Per-topology knob default tables (tools/autoplan.py --campaign).
#
# A campaign measures the knob lattice on real hardware and emits a table
# of measured-best defaults keyed by (gen, mesh topology, model class).
# This module SHIPS that table as data (knob_defaults.json next to this
# file — empty until the first on-chip campaign lands its rows) and owns
# the lookup; config.resolve_auto_knobs() consults it whenever a knob is
# "auto" and applies the staleness gate (drift.check_pair on each entry's
# recorded evidence). The table is measured evidence, reviewed and
# committed like a recalibration — the resolver never writes it.
# ---------------------------------------------------------------------------

KNOB_TABLE_ENV = "DSTPU_KNOB_TABLE"
_PACKAGED_KNOB_TABLE = os.path.join(os.path.dirname(__file__),
                                    "knob_defaults.json")
# measurement-transfer chain: a gen with no measured row falls back to
# the nearest measured generation's row before giving up (v5e is the
# fleet's workhorse and the historical pricing default); "cpu" rows are
# plumbing evidence and never stand in for chips
GEN_FALLBACKS: Dict[str, Tuple[str, ...]] = {
    "v6e": ("v5e",),
    "v5p": ("v5e",),
    "v4": ("v5e",),
    "cpu": (),
}

_AXIS_ORDER = ("dp", "fsdp", "pp", "sp", "ep", "tp")


def topology_key(topology=None) -> str:
    """Canonical mesh spelling for table keys: the >1-sized axes in a
    fixed order ("dp4xtp2"); a topology-less session keys on the visible
    device count ("dp8"). DCN-tagged axes carry their link class in the
    spelling ("dp4dcnxfsdp2") so a hybrid 4×2 factorization can never
    share a table row with the flat all-ICI dp4xfsdp2 mesh — measured
    knob defaults are fabric-specific evidence."""
    if topology is None:
        try:
            import jax

            n = max(len(jax.devices()), 1)
        except Exception:  # noqa: BLE001
            n = 1
        return f"dp{n}"
    sizes = dict(getattr(topology, "sizes", None) or {})
    kinds = dict(getattr(topology, "link_kinds", None) or {})
    parts = [
        f"{a}{int(sizes[a])}" + ("dcn" if kinds.get(a) == "dcn" else "")
        for a in _AXIS_ORDER if int(sizes.get(a, 1)) > 1
    ]
    return "x".join(parts) or f"dp{int(getattr(topology, 'world_size', 1))}"


def model_class(mcfg) -> str:
    """Coarse model-class bucket for table keys: dense vs moe × analytic
    parameter-count bucket (s < 1e9 <= m < 1e10 <= l)."""
    if mcfg is None:
        return "unknown"
    moe = bool(getattr(mcfg, "is_moe", False))
    n = 0.0
    try:
        n = float(mcfg.num_params())
    except Exception:  # noqa: BLE001 — a config without the protocol
        pass
    bucket = "s" if n < 1e9 else ("m" if n < 1e10 else "l")
    return ("moe-" if moe else "dense-") + bucket


def load_knob_table(path: Optional[str] = None) -> Dict[str, Any]:
    """The default-knob table: explicit ``path``, else the
    ``DSTPU_KNOB_TABLE`` env override, else the packaged data file.
    Unreadable/corrupt tables are an EMPTY table, never a crash — the
    conservative off defaults then resolve everywhere."""
    p = path or os.environ.get(KNOB_TABLE_ENV) or _PACKAGED_KNOB_TABLE
    try:
        with open(p) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return {"version": 1, "entries": []}
    if not isinstance(table, dict) or not isinstance(
        table.get("entries"), list
    ):
        return {"version": 1, "entries": []}
    return table


def lookup_knob_row(table: Dict[str, Any], gen: str, topo_key: str,
                    mclass: str) -> Tuple[Optional[Dict[str, Any]], str]:
    """(row, provenance) for one (gen, topology, model_class) key. Exact
    gen first, then the GEN_FALLBACKS chain (v6e missing → the v5e row),
    topology and model class always exact — a measured dp4xtp2 row says
    nothing about dp8. provenance names where the row came from
    ("table:v5e/dp4xtp2/dense-s"); a miss is (None, "miss")."""
    entries = table.get("entries") or []

    def find(g: str) -> Optional[Dict[str, Any]]:
        for row in entries:
            if (row.get("gen") == g and row.get("topology") == topo_key
                    and row.get("model_class") == mclass):
                return row
        return None

    for g in (gen, *GEN_FALLBACKS.get(gen, ())):
        row = find(g)
        if row is not None:
            return row, f"table:{g}/{topo_key}/{mclass}"
    return None, "miss"


@dataclass
class HardwareModel:
    """Per-device capability numbers the roofline and budget checks use."""

    gen: str = "v5e"
    peak_flops: float = 197e12        # bf16 MXU peak, flops/s
    hbm_bytes: float = 16 * _GIB      # HBM capacity (the default R6 budget)
    hbm_bw: float = 819e9             # HBM bandwidth, bytes/s
    ici_bw: float = 45e9              # per-link ICI bandwidth, bytes/s
    host_bw: float = 32e9             # host DMA link, bytes/s
    dcn_bw: float = 3.125e9           # per-device inter-pod DCN, bytes/s

    @classmethod
    def detect(cls) -> "HardwareModel":
        """Defaults for the local generation + the bench env overrides.

        ``PALLAS_AXON_TPU_GEN`` pins the generation; otherwise a live
        TPU backend reads the real chip generation off
        ``jax.devices()[0].device_kind`` (unknown kinds fall back to v5e
        with a one-time warning), a live CPU backend selects the ``cpu``
        envelope (so lint-mesh plans and drift checks price the machine
        that actually runs them) and anything else keeps the historical
        v5e default."""
        gen = detect_gen()
        d = gen_defaults(gen)
        hbm = d["hbm_bytes"]
        hbm_gb = os.environ.get("SHARDPLAN_HBM_GB")
        if hbm_gb:
            hbm = float(hbm_gb) * _GIB
        ici_env = os.environ.get("BENCH_ICI_BW_GBS")
        host_env = os.environ.get("BENCH_HOST_BW_GBS")
        dcn_env = os.environ.get("BENCH_DCN_BW_GBS")
        return cls(
            gen=gen,
            peak_flops=d["peak_flops"],
            hbm_bytes=hbm,
            hbm_bw=d["hbm_bw"],
            ici_bw=float(ici_env) * 1e9 if ici_env else d["ici_bw"],
            host_bw=float(host_env) * 1e9 if host_env else d["host_bw"],
            dcn_bw=float(dcn_env) * 1e9 if dcn_env else d["dcn_bw"],
        )
