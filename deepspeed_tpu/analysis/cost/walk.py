"""Sharding-aware jaxpr walk: live-set peak, FLOPs, HBM and ICI traffic.

The planner's engine room. One recursive pass over a traced program
(abstract only — nothing executes) computes, per device:

- **activation live-set high-water mark**: a last-use liveness sweep over
  each jaxpr level, descending structurally into scan/while/cond/pjit/
  remat/shard_map bodies. Buffer-reuse credit mirrors XLA's assignment
  coarsely: an output may take over a buffer freed at the same equation
  (in-place elementwise, the rotating offload/KV slots) — for top-level
  inputs only when they were donated at the jit boundary, which is the
  R4 aliasing contract made quantitative.
- **per-value bytes** via a forward "dimspec" propagation: each value
  carries one divisor per array dimension (the product of mesh-axis
  sizes sharding that dim). Seeds are the known arg shardings plus every
  ``device_put``/``sharding_constraint`` pin; transfer rules cover the
  primitives that move real bytes (dot_general drops contracted-dim
  sharding — a dp-sharded activation contracted away yields a
  *replicated* gradient, which is exactly what XLA's psum produces).
  Inside ``shard_map`` bodies avals are already per-shard, so divisors
  reset to 1 and bytes are per-device by construction.
- **MXU FLOPs** (dot_general only: 2·|out|·K, divided by the output's
  AND the contracted dims' shard counts) and **HBM traffic** for the
  materializing primitives (dots, gathers/scatters, reductions,
  collectives — elementwise chains are assumed fused away).
- **ICI traffic**: every named collective classified by mesh axis into
  per-device wire bytes and hop counts with the standard ring factors
  (psum 2(n−1)/n, all_gather/reduce_scatter (n−1)/n·full, ppermute 1
  hop), multiplied through enclosing scan lengths.

Everything here is an *estimate with stated bias*: fusion makes the
traffic figure an upper bound, GSPMD-inserted resharding collectives are
not in the traced program (only explicitly written collectives are
visible), and while-loop trip counts default to 1.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..trace import (
    Jaxpr,
    Literal,
    as_jaxpr,
    axis_names_of,
    collective_axes,
    scan_split,
)

_CALL_KEYS = ("jaxpr", "call_jaxpr", "fun_jaxpr")

# ring collectives: (wire-bytes multiplier fn of (n, payload), hops fn)
_COLLECTIVES = {
    "psum": (lambda n, b: 2.0 * (n - 1) / n * b, lambda n: 2 * (n - 1)),
    "pmin": (lambda n, b: 2.0 * (n - 1) / n * b, lambda n: 2 * (n - 1)),
    "pmax": (lambda n, b: 2.0 * (n - 1) / n * b, lambda n: 2 * (n - 1)),
    "all_gather": (lambda n, b: float(n - 1) * b, lambda n: n - 1),
    "reduce_scatter": (lambda n, b: (n - 1) / n * b, lambda n: n - 1),
    "psum_scatter": (lambda n, b: (n - 1) / n * b, lambda n: n - 1),
    "all_to_all": (lambda n, b: (n - 1) / n * b, lambda n: 1),
    "ppermute": (lambda n, b: float(b), lambda n: 1),
    "pshuffle": (lambda n, b: float(b), lambda n: 1),
}

# primitives whose operands/results actually move through HBM in the
# fused program (elementwise chains between them are fused away)
_MATERIALIZING = {
    "dot_general", "conv_general_dilated", "gather", "scatter",
    "scatter-add", "dynamic_slice", "dynamic_update_slice", "sort",
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "argmax", "argmin", "cumsum", "cumlogsumexp", "concatenate",
} | set(_COLLECTIVES)

# reduction-family consumers XLA fuses INTO their producer (loop/epilogue
# fusion): a single-use intermediate between a fusable producer and one
# of these never materializes — charging both the producer's write and
# the consumer's read double-counted it (the documented PR-4 bias)
_FUSABLE_REDUCERS = {
    "reduce_sum", "reduce_max", "reduce_min", "reduce_prod",
    "argmax", "argmin", "cumsum", "cumlogsumexp",
}
# producers whose output an elementwise+reduce consumer fuses onto;
# collectives and scatter-family writes keep their charges (their outputs
# come out of dedicated buffers the consumer really reads back)
_FUSABLE_PRODUCERS = _MATERIALIZING - set(_COLLECTIVES) - {
    "scatter", "scatter-add", "dynamic_update_slice", "sort",
}


def _itemsize(dtype) -> float:
    try:
        return float(np.dtype(dtype).itemsize)
    except TypeError:  # extended dtypes (prng keys, int4)
        bits = getattr(dtype, "itemsize", None)
        return float(bits) if bits else 4.0


def _aval(v):
    return v.aval


def dimspec_from_sharding(s, ndim: int, mesh_sizes: Dict[str, int]
                          ) -> Tuple[int, ...]:
    """Per-dimension shard divisors of a (duck-typed) sharding."""
    spec = getattr(s, "spec", None)
    if spec is None:
        return (1,) * ndim
    try:
        sizes = dict(s.mesh.shape)
    except Exception:  # noqa: BLE001 — fall back to the context mesh
        sizes = mesh_sizes
    out = []
    for i in range(ndim):
        entry = spec[i] if i < len(spec) else None
        if entry is None:
            axes: Tuple = ()
        elif isinstance(entry, (tuple, list)):
            axes = tuple(entry)
        else:
            axes = (entry,)
        div = 1
        for a in axes:
            div *= int(sizes.get(str(a), 1))
        out.append(max(div, 1))
    return tuple(out)


def device_bytes(shape: Sequence[int], dtype, dimspec: Sequence[int]) -> float:
    """Per-device bytes of one value under its dimspec (ceil per dim; a
    short dimspec means the trailing dims are unsharded)."""
    n = _itemsize(dtype)
    for i, d in enumerate(shape):
        div = dimspec[i] if i < len(dimspec) else 1
        n *= math.ceil(d / max(div, 1))
    return n


def _ones(ndim: int) -> Tuple[int, ...]:
    return (1,) * ndim


@dataclass
class WalkStats:
    """Accumulated per-device cost counters for one walked program."""

    flops: float = 0.0                 # MXU (dot) flops
    hbm_bytes: float = 0.0             # post-fusion HBM traffic estimate
    ici_bytes: Dict[str, float] = field(default_factory=dict)
    ici_hops: Dict[str, int] = field(default_factory=dict)
    collective_scratch: float = 0.0    # largest per-device collective buffer
    peak_bytes: float = 0.0            # live-set high-water mark (device)
    host_bytes: float = 0.0            # pinned-host-resident input bytes

    def add_ici(self, axes: Tuple[str, ...], nbytes: float, hops: int,
                mult: float) -> None:
        key = "+".join(axes) if axes else "?"
        self.ici_bytes[key] = self.ici_bytes.get(key, 0.0) + nbytes * mult
        self.ici_hops[key] = self.ici_hops.get(key, 0) + int(hops * mult)

    def merge_max(self, other: "WalkStats") -> None:
        """Join a branch: costs take the max (one branch executes)."""
        self.flops = max(self.flops, other.flops)
        self.hbm_bytes = max(self.hbm_bytes, other.hbm_bytes)
        for k, v in other.ici_bytes.items():
            self.ici_bytes[k] = max(self.ici_bytes.get(k, 0.0), v)
        for k, v in other.ici_hops.items():
            self.ici_hops[k] = max(self.ici_hops.get(k, 0), v)
        self.collective_scratch = max(
            self.collective_scratch, other.collective_scratch
        )


@dataclass
class _Fusion:
    """Per-level producer-consumer coalescing evidence.

    ``reads[v]`` — v is a reducer operand whose read is fused with its
    producer chain: charge the chain root instead (or nothing when the
    root itself fuses away). ``outs`` — values a fusable producer never
    writes back to HBM (their only consumer is a fused reducer)."""

    reads: Dict[Any, Any] = field(default_factory=dict)
    outs: set = field(default_factory=set)


def _chain_link(eqn) -> bool:
    """True when ``eqn`` is a pure elementwise link a fused reducer reads
    *through*: exactly one non-literal input, no nested jaxpr, and not a
    primitive that materializes on its own."""
    if eqn.primitive.name in _MATERIALIZING:
        return False
    if any(k in eqn.params for k in _CALL_KEYS) or eqn.primitive.name in (
        "scan", "while", "cond", "shard_map"
    ):
        return False
    return sum(1 for a in eqn.invars if not isinstance(a, Literal)) == 1


def analyze_fusion(jaxpr: Jaxpr) -> _Fusion:
    """Coalesce producer→elementwise-chain→reducer triples at one jaxpr
    level. XLA fuses a reduction-family consumer into its producer when
    the intermediate is single-use, so the bytes between them never move
    through HBM; without this credit the walk charged the producer's
    write AND the consumer's read of the same value."""
    use_count: Dict[Any, int] = {}
    producer: Dict[Any, Any] = {}
    for eqn in jaxpr.eqns:
        for a in eqn.invars:
            if not isinstance(a, Literal):
                use_count[a] = use_count.get(a, 0) + 1
        for ov in eqn.outvars:
            producer[ov] = eqn
    for a in jaxpr.outvars:
        if not isinstance(a, Literal):
            # a level output materializes for the caller regardless
            use_count[a] = use_count.get(a, 0) + 1

    fusion = _Fusion()
    for eqn in jaxpr.eqns:
        if eqn.primitive.name not in _FUSABLE_REDUCERS:
            continue
        for v in eqn.invars:
            if isinstance(v, Literal) or use_count.get(v, 0) != 1:
                continue
            # walk back through single-use elementwise links to the root
            root = v
            while True:
                p = producer.get(root)
                if p is None or not _chain_link(p):
                    break
                root = next(a for a in p.invars
                            if not isinstance(a, Literal))
                if use_count.get(root, 0) != 1:
                    break  # multi-use root still materializes; it is the
                    # redirect target, not another link to walk through
            p = producer.get(root)
            if (p is not None and p.primitive.name in _FUSABLE_PRODUCERS
                    and use_count.get(root, 0) == 1):
                # the whole triple fuses: producer write + reducer read
                # of this value both vanish
                fusion.outs.add(root)
                fusion.reads[v] = None
            elif root is not v:
                # chain collapses onto a materialized root: the fused
                # kernel reads the root once, not the intermediate
                fusion.reads[v] = root
    return fusion


class JaxprWalker:
    """One pass: dimspec propagation + liveness peak + cost counters."""

    def __init__(self, mesh_sizes: Dict[str, int], while_trips: int = 1,
                 probe: bool = False):
        self.mesh_sizes = dict(mesh_sizes or {})
        self.while_trips = max(int(while_trips), 1)
        # a probe walker only settles dimspecs — its nested scans skip
        # their own settling pre-pass, keeping the total walk count
        # linear (not 2^depth) in scan-nesting depth
        self.probe = probe
        self.stats = WalkStats()

    # ------------------------------------------------------------ dimspecs
    def _pinned_sharding_spec(self, eqn, idx: int):
        """The sharding an eqn pins its output to (device_put/constraint)."""
        name = eqn.primitive.name
        if name == "sharding_constraint":
            return eqn.params.get("sharding")
        if name == "device_put":
            devices = eqn.params.get("devices") or ()
            if idx < len(devices):
                d = devices[idx]
                if getattr(d, "spec", None) is not None:
                    return d
        return None

    def _elementwise_spec(self, eqn, in_specs, out_aval) -> Tuple[int, ...]:
        """Right-aligned broadcast join: per out dim, max divisor among
        inputs whose matching dim has the same size."""
        out_shape = out_aval.shape
        nd = len(out_shape)
        spec = [1] * nd
        for v, s in zip(eqn.invars, in_specs):
            ish = _aval(v).shape
            off = nd - len(ish)
            if off < 0:
                continue
            for j, (d, dv) in enumerate(zip(ish, s)):
                if d == out_shape[off + j]:
                    spec[off + j] = max(spec[off + j], dv)
        return tuple(spec)

    def _dot_spec(self, eqn, in_specs) -> Tuple[int, ...]:
        (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
        ls, rs = in_specs[0], in_specs[1]
        l_free = [i for i in range(len(ls)) if i not in lc and i not in lb]
        r_free = [i for i in range(len(rs)) if i not in rc and i not in rb]
        out = [max(ls[i], rs[j]) for i, j in zip(lb, rb)]
        out += [ls[i] for i in l_free]
        out += [rs[j] for j in r_free]
        return tuple(out)

    def _reshape_spec(self, in_shape, in_spec, out_shape) -> Tuple[int, ...]:
        """Keep sharding on the untouched leading/trailing dims."""
        nd = len(out_shape)
        spec = [1] * nd
        i = 0
        while (i < nd and i < len(in_shape)
               and in_shape[i] == out_shape[i]):
            spec[i] = in_spec[i]
            i += 1
        j = 0
        while (j < nd - i and j < len(in_shape) - i
               and in_shape[-1 - j] == out_shape[-1 - j]):
            spec[-1 - j] = in_spec[-1 - j]
            j += 1
        return tuple(spec)

    def _gather_spec(self, eqn, in_specs, out_aval) -> Tuple[int, ...]:
        """Output batch dims (from the indices) inherit the indices'
        sharding; operand-sliced dims stay conservative (1)."""
        dn = eqn.params.get("dimension_numbers")
        if dn is None or len(eqn.invars) < 2:
            return _ones(len(out_aval.shape))
        offset = set(getattr(dn, "offset_dims", ()))
        idx_spec = in_specs[1]
        idx_shape = _aval(eqn.invars[1]).shape
        # indices' last dim is the index vector — not a batch dim
        batch_src = list(idx_spec[:len(idx_shape) - 1]) or []
        spec = []
        k = 0
        for d in range(len(out_aval.shape)):
            if d in offset:
                spec.append(1)
            else:
                spec.append(batch_src[k] if k < len(batch_src) else 1)
                k += 1
        return tuple(spec)

    def _out_specs_plain(self, eqn, in_specs) -> List[Tuple[int, ...]]:
        name = eqn.primitive.name
        outs = []
        for idx, ov in enumerate(eqn.outvars):
            aval = _aval(ov)
            nd = len(getattr(aval, "shape", ()))
            pinned = self._pinned_sharding_spec(eqn, idx)
            if pinned is not None:
                outs.append(dimspec_from_sharding(pinned, nd, self.mesh_sizes))
                continue
            if name == "dot_general":
                outs.append(self._dot_spec(eqn, in_specs))
            elif name == "transpose":
                perm = eqn.params["permutation"]
                outs.append(tuple(in_specs[0][p] for p in perm))
            elif name == "reshape":
                outs.append(self._reshape_spec(
                    _aval(eqn.invars[0]).shape, in_specs[0], aval.shape
                ))
            elif name == "broadcast_in_dim":
                bd = eqn.params["broadcast_dimensions"]
                in_shape = _aval(eqn.invars[0]).shape
                spec = [1] * nd
                for src, dst in enumerate(bd):
                    if (src < len(in_specs[0])
                            and in_shape[src] == aval.shape[dst]):
                        spec[dst] = in_specs[0][src]
                outs.append(tuple(spec))
            elif name in ("reduce_sum", "reduce_max", "reduce_min",
                          "reduce_prod", "reduce_and", "reduce_or",
                          "argmax", "argmin"):
                axes = set(eqn.params.get("axes", ()))
                outs.append(tuple(
                    dv for i, dv in enumerate(in_specs[0]) if i not in axes
                ))
            elif name == "squeeze":
                dims = set(eqn.params.get("dimensions", ()))
                outs.append(tuple(
                    dv for i, dv in enumerate(in_specs[0]) if i not in dims
                ))
            elif name in ("slice", "dynamic_slice", "pad"):
                in_shape = _aval(eqn.invars[0]).shape
                outs.append(tuple(
                    dv if i < len(in_shape) and in_shape[i] == aval.shape[i]
                    else 1
                    for i, dv in enumerate(in_specs[0])
                ))
            elif name in ("dynamic_update_slice", "scatter", "scatter-add"):
                outs.append(in_specs[0])
            elif name == "gather":
                outs.append(self._gather_spec(eqn, in_specs, aval))
            elif name == "concatenate":
                dim = eqn.params.get("dimension", 0)
                base = [min(s[i] if i < len(s) else 1 for s in in_specs)
                        for i in range(nd)]
                if dim < nd:
                    base[dim] = 1
                outs.append(tuple(base))
            elif name in _COLLECTIVES:
                # shard_map-internal collectives: stay per-shard (ones)
                outs.append(_ones(nd))
            elif nd == 0:
                outs.append(())
            else:
                outs.append(self._elementwise_spec(eqn, in_specs, aval))
        return outs

    # ------------------------------------------------------------- costing
    def _eqn_costs(self, eqn, in_specs, out_specs, mult: float,
                   fusion: Optional["_Fusion"] = None,
                   nbytes=None) -> None:
        name = eqn.primitive.name
        if name == "dot_general":
            (lc, rc), _ = eqn.params["dimension_numbers"]
            lhs = _aval(eqn.invars[0])
            k = 1
            for i in lc:
                k *= lhs.shape[i]
            out = _aval(eqn.outvars[0])
            # per-device flops: global work over BOTH the output's shard
            # count and the contracted dims' (a weight-grad dot contracts
            # the dp-sharded batch away — each device computes 1/dp of
            # the reduction and psums partials)
            shards = 1
            for dv in out_specs[0]:
                shards *= dv
            ls, rs = in_specs[0], in_specs[1]
            for i, j in zip(lc, rc):
                li = ls[i] if i < len(ls) else 1
                rj = rs[j] if j < len(rs) else 1
                shards *= max(li, rj)
            self.stats.flops += mult * 2.0 * out.size * k / max(shards, 1)
        if name in _COLLECTIVES:
            axes = collective_axes(eqn)
            if not axes:
                axes = axis_names_of(eqn.params.get("axis_name"))
            n = 1
            for a in axes:
                n *= int(self.mesh_sizes.get(a, 1))
            payload = sum(
                device_bytes(_aval(v).shape, _aval(v).dtype, s)
                for v, s in zip(eqn.invars, in_specs)
                if not isinstance(v, Literal)
            )
            if n > 1:
                wire_fn, hops_fn = _COLLECTIVES[name]
                self.stats.add_ici(axes, wire_fn(n, payload), hops_fn(n), mult)
                out_b = sum(
                    device_bytes(_aval(v).shape, _aval(v).dtype, s)
                    for v, s in zip(eqn.outvars, out_specs)
                )
                self.stats.collective_scratch = max(
                    self.stats.collective_scratch, max(payload, out_b)
                )
        if name in _MATERIALIZING:
            io = 0.0
            for v, s in zip(eqn.invars, in_specs):
                if isinstance(v, Literal):
                    continue
                if fusion is not None and v in fusion.reads:
                    root = fusion.reads[v]
                    if root is not None:
                        io += nbytes(root)  # the fused kernel reads the
                        # chain's root, not the elementwise intermediate
                    continue  # root fused away with its producer: 0 bytes
                io += device_bytes(_aval(v).shape, _aval(v).dtype, s)
            for v, s in zip(eqn.outvars, out_specs):
                if fusion is not None and v in fusion.outs:
                    continue  # consumed only by a fused reducer: never
                    # written back to HBM
                io += device_bytes(_aval(v).shape, _aval(v).dtype, s)
            self.stats.hbm_bytes += mult * io


    # ---------------------------------------------------------------- walk
    def walk(
        self,
        jaxpr: Jaxpr,
        in_specs: Sequence[Tuple[int, ...]],
        *,
        mult: float = 1.0,
        donated: Optional[Sequence[bool]] = None,
        host_resident: Optional[Sequence[bool]] = None,
    ) -> Tuple[float, List[Tuple[int, ...]]]:
        """Walk one jaxpr level. Returns (peak device bytes incl. live
        inputs, out dimspecs). ``donated[i]`` marks invars whose buffer
        may be reused once dead (jit-boundary donation); non-donated
        invars stay live to the end (the caller owns them).
        ``host_resident[i]`` marks pinned-host invars (0 HBM bytes)."""
        n_in = len(jaxpr.invars)
        donated = list(donated) if donated is not None else [True] * n_in
        host = list(host_resident) if host_resident is not None \
            else [False] * n_in
        specs: Dict[Any, Tuple[int, ...]] = {}
        for v, s in zip(jaxpr.invars, in_specs):
            specs[v] = tuple(s)[:len(_aval(v).shape)] or _ones(
                len(_aval(v).shape)
            )
        for cv in jaxpr.constvars:
            specs[cv] = _ones(len(_aval(cv).shape))

        def nbytes(v) -> float:
            if isinstance(v, Literal):
                return 0.0
            return device_bytes(
                _aval(v).shape, _aval(v).dtype,
                specs.get(v, _ones(len(_aval(v).shape))),
            )

        fusion = analyze_fusion(jaxpr)

        # ---- liveness: last equation index using each var ----------------
        last_use: Dict[Any, int] = {}
        for i, eqn in enumerate(jaxpr.eqns):
            for a in eqn.invars:
                if not isinstance(a, Literal):
                    last_use[a] = i
        INF = len(jaxpr.eqns) + 1
        for a in jaxpr.outvars:
            if not isinstance(a, Literal):
                last_use[a] = INF
        for v, don, hst in zip(jaxpr.invars, donated, host):
            if not don and not hst:
                last_use[v] = INF  # caller-owned buffer, live throughout

        live: Dict[Any, float] = {}
        for v, hst in zip(jaxpr.invars, host):
            if hst:
                self.stats.host_bytes += device_bytes(
                    _aval(v).shape, _aval(v).dtype, specs[v]
                )
                live[v] = 0.0
            else:
                live[v] = nbytes(v)
        for cv in jaxpr.constvars:
            live[cv] = nbytes(cv)
        live_sum = sum(live.values())
        peak = live_sum

        for i, eqn in enumerate(jaxpr.eqns):
            e_in_specs = [
                specs.get(a, _ones(len(_aval(a).shape)))
                if not isinstance(a, Literal) else ()
                for a in eqn.invars
            ]
            inner_extra, out_specs = self._descend(
                eqn, e_in_specs, mult
            )
            if out_specs is None:
                out_specs = self._out_specs_plain(eqn, e_in_specs)
            for ov, s in zip(eqn.outvars, out_specs):
                specs[ov] = s
            self._eqn_costs(eqn, e_in_specs, out_specs, mult,
                            fusion=fusion, nbytes=nbytes)

            freed = [
                a for a in {id(a): a for a in eqn.invars
                            if not isinstance(a, Literal)}.values()
                if last_use.get(a) == i and a in live
            ]
            freed_pool = sorted((live[a] for a in freed))
            out_bytes = [nbytes(ov) for ov in eqn.outvars]
            new_alloc = 0.0
            for b in sorted(out_bytes, reverse=True):
                taken = None
                for k, fb in enumerate(freed_pool):
                    if fb >= b:
                        taken = k
                        break
                if taken is not None:
                    freed_pool.pop(taken)  # reuse the freed buffer
                else:
                    new_alloc += b
            peak = max(peak, live_sum + new_alloc + inner_extra)
            for a in freed:
                live_sum -= live.pop(a)
            for ov, b in zip(eqn.outvars, out_bytes):
                live[ov] = b
                live_sum += b
            # drop outputs that are never used (dead code in the trace)
            for ov in list(eqn.outvars):
                if last_use.get(ov) is None and ov in live:
                    live_sum -= live.pop(ov)
            peak = max(peak, live_sum)

        out_specs = [
            specs.get(a, _ones(len(_aval(a).shape)))
            if not isinstance(a, Literal) else ()
            for a in jaxpr.outvars
        ]
        return peak, out_specs

    # ------------------------------------------------- structural descent
    def _descend(self, eqn, in_specs, mult: float):
        """(inner_extra_peak, out_specs|None) for control-flow equations.
        Returns (0, None) for plain primitives."""
        name = eqn.primitive.name
        if name == "scan":
            body = as_jaxpr(eqn.params["jaxpr"])
            nc, ncar = scan_split(eqn)
            length = max(int(eqn.params.get("length", 1)), 1)
            consts = in_specs[:nc]
            carry = list(in_specs[nc:nc + ncar])
            xs = [tuple(s[1:]) for s in in_specs[nc + ncar:]]
            # one settling pass for carry specs, then the costed pass
            # (skipped inside a probe — the outer costed walk re-settles)
            if not self.probe:
                probe = JaxprWalker(self.mesh_sizes, self.while_trips,
                                    probe=True)
                _, probe_out = probe.walk(body, consts + carry + xs,
                                          mult=0.0)
                carry = [
                    tuple(min(a, b) for a, b in zip(ci, bo))
                    for ci, bo in zip(carry, probe_out[:ncar])
                ]
            body_peak, body_out = self.walk(
                body, consts + carry + xs, mult=mult * length
            )
            in_bytes = self._specs_bytes(body.invars, consts + carry + xs)
            outs = list(body_out[:ncar]) + [
                (1,) + tuple(s) for s in body_out[ncar:]
            ]
            return max(body_peak - in_bytes, 0.0), outs
        if name == "while":
            body = as_jaxpr(eqn.params["body_jaxpr"])
            cn = eqn.params["cond_nconsts"]
            bn = eqn.params["body_nconsts"]
            bconsts = in_specs[cn:cn + bn]
            carry = in_specs[cn + bn:]
            body_peak, body_out = self.walk(
                body, list(bconsts) + list(carry),
                mult=mult * self.while_trips,
            )
            in_bytes = self._specs_bytes(
                body.invars, list(bconsts) + list(carry)
            )
            return max(body_peak - in_bytes, 0.0), list(body_out)
        if name == "cond":
            operands = in_specs[1:]
            extra, outs = 0.0, None
            base = self.stats
            best: Optional[WalkStats] = None
            for br in eqn.params["branches"]:
                self.stats = WalkStats()
                b = as_jaxpr(br)
                p, o = self.walk(b, operands, mult=mult)
                in_b = self._specs_bytes(b.invars, operands)
                extra = max(extra, p - in_b)
                outs = o if outs is None else [
                    tuple(min(x, y) for x, y in zip(a, bo))
                    for a, bo in zip(outs, o)
                ]
                if best is None:
                    best = self.stats
                else:
                    best.merge_max(self.stats)
            self.stats = base
            if best is not None:
                self.stats.flops += best.flops
                self.stats.hbm_bytes += best.hbm_bytes
                for k, v in best.ici_bytes.items():
                    self.stats.ici_bytes[k] = (
                        self.stats.ici_bytes.get(k, 0.0) + v
                    )
                for k, v in best.ici_hops.items():
                    self.stats.ici_hops[k] = (
                        self.stats.ici_hops.get(k, 0) + v
                    )
                self.stats.collective_scratch = max(
                    self.stats.collective_scratch, best.collective_scratch
                )
            return max(extra, 0.0), outs
        if name == "shard_map":
            body = as_jaxpr(eqn.params["jaxpr"])
            # body avals are per-shard — divisors reset to 1
            body_peak, _ = self.walk(
                body, [_ones(len(_aval(v).shape)) for v in body.invars],
                mult=mult,
            )
            in_bytes = self._specs_bytes(
                body.invars,
                [_ones(len(_aval(v).shape)) for v in body.invars],
            )
            outs = []
            for ov, names in zip(eqn.outvars, eqn.params.get("out_names")
                                 or [None] * len(eqn.outvars)):
                nd = len(_aval(ov).shape)
                spec = [1] * nd
                for dim, axes in (names or {}).items():
                    if dim < nd:
                        div = 1
                        for a in axes:
                            div *= int(self.mesh_sizes.get(str(a), 1))
                        spec[dim] = div
                outs.append(tuple(spec))
            return max(body_peak - in_bytes, 0.0), outs
        for key in _CALL_KEYS:
            sub = eqn.params.get(key)
            if sub is None or not isinstance(sub, (Jaxpr,)) and not hasattr(
                sub, "jaxpr"
            ):
                continue
            body = as_jaxpr(sub)
            if len(body.invars) == len(in_specs):
                aligned = list(in_specs)
            elif len(body.invars) < len(in_specs):
                aligned = list(in_specs[-len(body.invars):])
            else:
                aligned = list(in_specs) + [
                    _ones(len(_aval(v).shape))
                    for v in body.invars[len(in_specs):]
                ]
            body_peak, body_out = self.walk(body, aligned, mult=mult)
            in_bytes = self._specs_bytes(body.invars, aligned)
            return max(body_peak - in_bytes, 0.0), list(body_out)
        return 0.0, None

    def _specs_bytes(self, vs, specs) -> float:
        return sum(
            device_bytes(_aval(v).shape, _aval(v).dtype, s)
            for v, s in zip(vs, specs)
        )
