"""Static analysis of jitted step programs (shardlint).

The correctness contract of sharded training is a small set of checkable
invariants on the collective/partition structure of the step program
(ZeRO++ arXiv:2306.10209; automatic cross-replica sharding
arXiv:2004.13336). This package traces engine step functions to jaxprs —
abstract evaluation only, no device execution — and lints them against a
rule registry:

- R1 replica-divergence  (rules/replica.py)
- R2 sharding-closure    (rules/closure.py)
- R3 collective-topology (rules/topology.py)
- R4 donation/aliasing   (rules/aliasing.py)
- R5 precision-policy    (rules/precision.py)

Entry points: :func:`lint_jaxpr` (any program), :func:`lint_engine` (a
constructed engine, including ``abstract_init=True`` shells that never
materialized state), :func:`lint_config` (config → abstract engine →
lint). CLI: ``tools/shardlint.py``. Rule catalog: ``docs/shardlint.md``.
"""

from .base import Finding, LintContext, Report
from .rules import register_rule, registered_rules
from .shardlint import lint_config, lint_engine, lint_jaxpr

__all__ = [
    "Finding",
    "LintContext",
    "Report",
    "lint_config",
    "lint_engine",
    "lint_jaxpr",
    "register_rule",
    "registered_rules",
]
