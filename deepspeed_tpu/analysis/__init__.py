"""Static analysis of jitted step programs (shardlint).

The correctness contract of sharded training is a small set of checkable
invariants on the collective/partition structure of the step program
(ZeRO++ arXiv:2306.10209; automatic cross-replica sharding
arXiv:2004.13336). This package traces engine step functions to jaxprs —
abstract evaluation only, no device execution — and lints them against a
rule registry:

- R1 replica-divergence  (rules/replica.py)
- R2 sharding-closure    (rules/closure.py)
- R3 collective-topology (rules/topology.py)
- R4 donation/aliasing   (rules/aliasing.py)
- R5 precision-policy    (rules/precision.py)
- R6 hbm-capacity        (rules/capacity.py — needs an HBM budget)
- R7 redundant-reshard   (rules/reshard.py)
- R8 overlap-budget      (rules/overlap_budget.py — needs declared streams)
- R9 rng-discipline      (rules/rng.py)
- R10 reduction-order    (rules/reduction_order.py)
- R11 trace-stability    (rules/trace_stability.py — needs a traced-args
  manifest)

The sibling :mod:`.parity` module is the differential half of
R10/parity: :func:`prove_parity` structurally diffs the two traced
forms of a declared-bitwise pair (paged vs contiguous, moe stock vs
chunked, TP ring vs XLA reference, wire codec vs full-width) modulo a
small rewrite-equivalence set and emits either a static parity
certificate or the first divergent op with both provenances
(``tools/paritycheck.py``).

The sibling :mod:`.cost` package is the static HBM-capacity +
collective-cost planner rules R6/R8 consume: :func:`plan_engine` /
:func:`plan_config` / :func:`plan_jaxpr` budget a config's per-device
bytes, ICI traffic and roofline step time from the same traced jaxpr.

Entry points: :func:`lint_jaxpr` (any program), :func:`lint_engine` (a
constructed engine, including ``abstract_init=True`` shells that never
materialized state), :func:`lint_config` (config → abstract engine →
lint). CLIs: ``tools/shardlint.py``, ``tools/shardplan.py``. Rule
catalog: ``docs/shardlint.md``; planner semantics:
``docs/memory_planner.md``.
"""

from .base import Finding, LintContext, Report
from .cost import (
    HardwareModel,
    Plan,
    format_plan_table,
    plan_config,
    plan_engine,
    plan_jaxpr,
)
from .parity import (FormPair, ParityCertificate, config_parity_pairs,
                     prove_parity)
from .rules import register_rule, registered_rules
from .shardlint import (lint_config, lint_engine, lint_jaxpr,
                        lint_serving_config)

__all__ = [
    "Finding",
    "FormPair",
    "HardwareModel",
    "LintContext",
    "ParityCertificate",
    "Plan",
    "Report",
    "config_parity_pairs",
    "format_plan_table",
    "prove_parity",
    "lint_config",
    "lint_engine",
    "lint_jaxpr",
    "lint_serving_config",
    "plan_config",
    "plan_engine",
    "plan_jaxpr",
    "register_rule",
    "registered_rules",
]
