"""fleetcheck: exhaustive host-plane model checking.

The dynamic sibling of shardlint (static jaxpr rules) and paritycheck
(differential trace certificates): where those check the DEVICE
programs, fleetcheck checks the HOST control plane — scheduler, paging,
KV tiers, fleet routing/handoff — by exhaustively exploring event
interleavings of small bounded configs against safety invariants H1–H7
and a liveness (quiescence) obligation, with replayable minimal
counterexample traces.

The objects under test are the REAL production classes (Scheduler,
PagePool, PrefixCache, HostPageStore, PageSpiller, ReplicaHandle,
Router + handoff); only the device engine and the clock are nulled.
There is no model-vs-implementation gap to maintain: a scheduler
refactor is checked the moment it lands.

Entry points:

- :func:`explore` — bounded BFS over a :class:`Scenario`, → a
  :class:`CheckResult` (invariant ids, traces, state counts).
- :func:`random_walk` — one seeded deep walk (the randomized smoke and
  the determinism-audit regression).
- :func:`preset` / ``PRESETS`` — the curated scenario families the CLI
  and CI run (oversubscription, disaggregated_handoff,
  tiered_cold_resume, spec_on, fleet_shedding).
- ``MUTATIONS`` — the seeded-bug corpus (serving/faults.py seams) each
  with the invariant/liveness id fleetcheck MUST report.

CLI: ``tools/fleetcheck.py``. Catalog + theory: ``docs/modelcheck.md``.
"""

from .explore import CheckResult, Violation, WalkResult, explore, \
    random_walk
from .fingerprint import fingerprint
from .invariants import INVARIANTS, CheckFailure, check_world
from .scenarios import MUTATIONS, PRESETS, Mutation, RequestSpec, \
    Scenario, preset
from .world import World, replay

__all__ = [
    "explore", "random_walk", "CheckResult", "Violation", "WalkResult",
    "fingerprint", "INVARIANTS", "CheckFailure", "check_world",
    "PRESETS", "MUTATIONS", "Mutation", "RequestSpec", "Scenario",
    "preset", "World", "replay",
]
