"""fleetcheck world: the REAL host-plane objects under a null device.

A :class:`World` instantiates one scenario's control plane exactly as
production wires it — real :class:`~deepspeed_tpu.serving.scheduler.
Scheduler` (with real :class:`~deepspeed_tpu.serving.paging.PagePool`,
:class:`PrefixCache`, :class:`HostPageStore`, :class:`PageSpiller`),
real :class:`~deepspeed_tpu.serving.fleet.replica.ReplicaHandle` +
:func:`~deepspeed_tpu.serving.fleet.handoff.handoff`, and the real
:class:`~deepspeed_tpu.serving.fleet.router.Router` routing/shedding
methods — but with the device engine replaced by a null engine and the
clock replaced by a fake. The model checker then applies CONTROLLED
events:

- ``("submit", i)`` / ``("resubmit", i)`` — request ``i`` arrives /
  retries after eviction,
- ``("advance", k)`` — the fake clock jumps by ``advance_dts[k]``
  (enables timeout eviction and backoff expiry),
- ``("tick", rid, outcomes)`` — one scheduler tick on replica ``rid``:
  ``plan()``, the null device "executes" it, ``complete()`` folds it
  back. ``outcomes`` decides what each SAMPLING slot produced — a tuple
  of ``"tok" | "eos" | "acc"`` per sampler in plan order, an int
  bitmask (seeded random walks), or None (the all-EOS drain policy),
- ``("handoff",)`` — one router handoff pass (prefill→decode moves).

Everything else in the ISSUE's alphabet — timeout-evict, LRU-evict,
demote, promote, deferral — is a deterministic CONSEQUENCE of those
controlled events; the world observes them through the scheduler's own
metrics hooks and the prefix cache's listener seam and records them in
``world.log``, so counterexample traces show the full causal story.

Replay-from-scratch is the state model: a World is cheap to build, and
a trace of events reproduces a state bit-for-bit (the determinism the
satellite audit enforces). There is deliberately NO deepcopy anywhere.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ...serving import faults
from ...serving.paging import HostPageStore, PageSpiller
from ...serving.request import Request, RequestState, RequestStatus
from ...serving.scheduler import Scheduler
from .invariants import CheckFailure, check_event, check_world
from .scenarios import Scenario

__all__ = ["World", "FakeClock", "ReplayDrift", "build_world"]


class ReplayDrift(RuntimeError):
    """A trace replayed into a different state than it was recorded
    from — the determinism regression fleetcheck exists to prevent."""


class FakeClock:
    """Injectable monotonic clock: ticks cost nothing, "advance" events
    move it explicitly."""

    def __init__(self, t: float = 0.0):
        self.t = float(t)

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += float(dt)


class _NullEngine:
    """The slice of the ServingEngine surface the host plane touches:
    scheduler access, submit delegation, and page-payload export/import
    (the fleet handoff's device half — a no-op here; what fleetcheck
    verifies is the HOST-side page/slot accounting around it)."""

    def __init__(self, scheduler: Scheduler, spiller: Optional[PageSpiller]):
        self.scheduler = scheduler
        self.spiller = spiller

    def submit(self, request: Request) -> RequestState:
        return self.scheduler.submit(request)

    def export_kv_pages(self, page_ids: Sequence[int]):
        return {"pages": tuple(int(p) for p in page_ids)}

    def import_kv_pages(self, payload, dst_pages: Sequence[int]) -> None:
        del payload, dst_pages


def _null_export(page_ids: Sequence[int]) -> Dict[str, np.ndarray]:
    """PageSpiller export_fn: a tiny constant int8 leaf per page.
    Integer leaves take encode_page's RAW path — no codec math, no jax
    dispatch — while still exercising the real HostPageStore put/get/
    drop lifecycle and pinned-buffer recycling."""
    return {"kv": np.zeros((1, len(list(page_ids)), 2), np.int8)}


class _Recorder:
    """Duck-typed ServingMetrics consumer: turns the scheduler's metric
    hooks into observed-event log lines (admit, evict, demote, finish)
    and feeds the H6 backoff ledger. Every method the Scheduler or
    PageSpiller may call must exist here."""

    def __init__(self, world: "World", rid: int):
        self._w = world
        self._rid = rid

    # ---- lifecycle hooks the checker observes
    def on_admit(self, state, now, queue_depth=0):
        self._w.log.append(("admit", self._rid, self._w.req_index(state)))

    def on_evict(self, state, now):
        self._w.log.append((
            "evict", self._rid, self._w.req_index(state),
            state.evict_reason,
        ))
        self._w.record_backoff(state, now)

    def on_finish(self, state, now):
        self._w.log.append(("finish", self._rid,
                            self._w.req_index(state)))

    def on_spill(self, nbytes=0):
        self._w.log.append(("demote", self._rid))

    # ---- hooks observed elsewhere or not needed: keep as no-ops
    def on_submit(self, state, now, queue_depth=0):
        pass

    def on_plan(self, plan, now, queue_depth=0, occupancy=0):
        pass

    def on_token(self, state, now):
        pass

    def on_spec(self, state, proposed, accepted, emitted):
        pass

    def on_prefix_lookup(self, cached_tokens, prompt_len, host_tokens=0):
        pass

    def on_cow(self):
        pass

    def on_prefill_chunk(self, cached_tail=False):
        pass

    def on_pages(self, pool, cache_entries=0, host_resident=0):
        pass

    def on_page_in(self, pages=1, nbytes=0, stall_s=0.0):
        pass

    def cache_listener(self, event, kind, h, page):
        if event == "evict":
            self._w.log.append((f"lru-evict-{kind}", self._rid))


def _build_router(world: "World"):
    """A real Router driven headless: the routing/shedding/handoff
    methods are the production ones; only the heavyweight constructor
    (init_inference + ServingEngine replicas) is bypassed, since the
    world already built the replicas over null engines."""
    from ...config import FleetConfig, ServingConfig
    from ...serving.fleet.router import Router
    from ...serving.metrics import FleetMetrics

    sc = world.scenario
    r = Router.__new__(Router)
    serving = ServingConfig()
    serving.max_slots = sc.max_slots
    serving.queue_limit = sc.queue_limit
    serving.eviction_backoff_s = sc.eviction_backoff_s
    serving.max_tokens = sc.max_tokens
    r.serving = serving
    r.fleet = FleetConfig(
        enabled=True, replicas=sc.replicas,
        prefill_replicas=sc.prefill_replicas,
        routing=sc.routing, affinity=sc.affinity,
        queue_limit=sc.fleet_queue_limit,
    )
    r.clock = world.clock
    r.replicas = world.replicas
    r._intake = [rep for rep in world.replicas
                 if rep.role in ("prefill", "mixed")]
    r._decode = [rep for rep in world.replicas if rep.role == "decode"]
    r.index = None  # prefix routing needs the event-mirrored index; the
    #   presets route least_loaded/round_robin (GlobalPrefixIndex has its
    #   own unit suite)
    r.metrics = FleetMetrics([], clock=world.clock)
    r._sessions = {}
    r._rr = 0
    r.healthwatch = None
    r.tracer = None
    r.last_tick_durations = {}
    r.last_tick_overhead_s = 0.0
    return r


def build_world(scenario: Scenario) -> "World":
    return World(scenario)


class World:
    def __init__(self, scenario: Scenario):
        sc = scenario
        self.scenario = sc
        self.clock = FakeClock()
        self.log: List[tuple] = []        # observed consequences
        self.trace: List[tuple] = []      # controlled events applied
        self.backoff: Dict[Tuple[int, int], float] = {}  # (req, attempt)
        #   -> retry_after - now at eviction time (H6 ledger)
        self.tokens_emitted = 0
        self.tokens_scheduled = 0
        self.n_advances = 0
        self.resubmits = [0] * len(sc.requests)

        # ---- requests (numpy rng arrays: no jax dispatch per replay)
        self.requests: List[Request] = []
        self.states: List[Optional[RequestState]] = [None] * len(
            sc.requests
        )
        self._req_idx: Dict[str, int] = {}
        for i, spec in enumerate(sc.requests):
            rid = f"q{i}"
            self.requests.append(Request(
                request_id=rid,
                prompt=np.asarray(spec.prompt, np.int32),
                max_new_tokens=int(spec.max_new),
                repetition_penalty=float(spec.penalty),
                eos_token_id=int(sc.eos_token),
                rng=np.zeros(2, np.uint32),
                session_id=spec.session,
            ))
            self._req_idx[rid] = i

        # ---- replicas: real schedulers (+tiers) over null engines
        from ...serving.fleet.replica import (ROLE_DECODE, ROLE_MIXED,
                                              ROLE_PREFILL, ReplicaHandle)

        self.replicas: List[ReplicaHandle] = []
        self.stores: List[Optional[HostPageStore]] = []
        k = int(sc.prefill_replicas)
        for i in range(int(sc.replicas)):
            role = ROLE_PREFILL if i < k else (
                ROLE_DECODE if k else ROLE_MIXED
            )
            num_pages = sc.num_pages
            max_slots = sc.max_slots
            if role == ROLE_DECODE:
                if sc.decode_num_pages is not None:
                    num_pages = sc.decode_num_pages
                if sc.decode_max_slots is not None:
                    max_slots = sc.decode_max_slots
            recorder = _Recorder(self, i)
            spiller = None
            store = None
            if sc.host_pages > 0:
                store = HostPageStore(sc.host_pages, codec="fp32")
                spiller = PageSpiller(store, _null_export,
                                      metrics=recorder)
            sched = Scheduler(
                max_slots=max_slots,
                token_budget=sc.token_budget,
                queue_limit=sc.queue_limit,
                request_timeout_s=sc.request_timeout_s,
                eviction_backoff_s=sc.eviction_backoff_s,
                max_tokens=sc.max_tokens,
                clock=self.clock,
                metrics=recorder,
                page_size=sc.page_size,
                num_pages=num_pages,
                pages_per_slot=sc.pages_per_slot,
                # decode replicas never prefill (Router.__init__ rule)
                prefix_cache=sc.prefix_cache and role != ROLE_DECODE,
                spec_max_draft=sc.spec_max_draft,
                spiller=spiller,
            )
            if sched.prefix_cache is not None:
                sched.prefix_cache.listener = recorder.cache_listener
            self.replicas.append(
                ReplicaHandle(i, _NullEngine(sched, spiller), role)
            )
            self.stores.append(store)

        self.router = _build_router(self) if sc.replicas > 1 else None

    # ------------------------------------------------------------ helpers
    def req_index(self, state: RequestState) -> int:
        return self._req_idx[state.request.request_id]

    def record_backoff(self, state: RequestState, now: float) -> None:
        if state.retry_after is not None:
            self.backoff[(self.req_index(state), int(state.attempts))] = (
                float(state.retry_after) - float(now)
            )

    def scheduler(self, rid: int) -> Scheduler:
        return self.replicas[rid].engine.scheduler

    def replica_of(self, state: RequestState) -> Optional[int]:
        """Which replica's slots hold ``state`` (None = unslotted)."""
        owners = [
            rep.replica_id for rep in self.replicas
            if state.slot is not None
            and state.slot < len(rep.engine.scheduler.slots)
            and rep.engine.scheduler.slots[state.slot] is state
        ]
        if len(owners) > 1:
            raise CheckFailure(
                "H5", f"request {state.request.request_id} slotted on "
                      f"replicas {owners} simultaneously"
            )
        return owners[0] if owners else None

    def quiescent(self) -> bool:
        """All SUBMITTED requests terminal (DONE or EVICTED)."""
        return all(
            st is None or st.status in (RequestStatus.DONE,
                                        RequestStatus.EVICTED)
            for st in self.states
        )

    @property
    def progress(self) -> int:
        """Cumulative token progress: emitted + scheduled (prefill
        chunks count — a long prefill is progress even before its first
        sampled token; promote-only thrash is NOT)."""
        return self.tokens_emitted + self.tokens_scheduled

    # ----------------------------------------------------------- events
    def apply(self, ev: tuple, check: bool = True) -> None:
        """Apply one controlled event; with ``check``, run the H1–H7
        registry afterwards (raises :class:`CheckFailure`)."""
        kind = ev[0]
        self.trace.append(ev)
        if kind == "submit":
            self._submit(ev[1])
        elif kind == "resubmit":
            self._resubmit(ev[1])
        elif kind == "advance":
            self.clock.advance(self.scenario.advance_dts[ev[1]])
            self.n_advances += 1
        elif kind == "tick":
            self._tick(ev[1], ev[2], check=check)
        elif kind == "handoff":
            self._handoff(check=check)
        else:
            raise ValueError(f"unknown event {ev!r}")
        if check:
            check_world(self)

    def _submit(self, i: int) -> None:
        if self.states[i] is not None:
            raise ReplayDrift(f"request q{i} submitted twice")
        now = self.clock()
        if self.router is not None:
            st = self.router.submit(self.requests[i])
        else:
            st = self.scheduler(0).submit(self.requests[i])
        self.states[i] = st
        if st.status is RequestStatus.EVICTED:
            # router-level sheds never pass through a scheduler metrics
            # hook — ledger them here (idempotent keying covers the
            # scheduler-rejection path that already recorded)
            self.record_backoff(st, now)
            self.log.append(("shed", -1, i, st.evict_reason))

    def _resubmit(self, i: int) -> None:
        st = self.states[i]
        if st is None or st.status is not RequestStatus.EVICTED:
            raise ReplayDrift(f"resubmit of non-evicted q{i}")
        now = self.clock()
        self.resubmits[i] += 1
        if self.router is not None:
            st = self.router.resubmit(st)
        else:
            st = self.scheduler(0).resubmit(st)
        self.states[i] = st
        if st.status is RequestStatus.EVICTED:
            self.record_backoff(st, now)
            self.log.append(("shed", -1, i, st.evict_reason))

    def _outcomes_for(self, samplers, outcomes):
        """Normalize an outcomes operand to one symbol per sampler.
        Tuple = explicit (exhaustive BFS); int = 2 bits per sampler
        (seeded random walks: 00/01 tok, 10 eos, 11 acc-if-spec);
        None = all-EOS (the liveness drain policy)."""
        if outcomes is None:
            return ["eos"] * len(samplers)
        if isinstance(outcomes, int):
            out = []
            for j, w in enumerate(samplers):
                bits = (outcomes >> (2 * j)) & 0b11
                if bits == 0b10:
                    out.append("eos")
                elif bits == 0b11 and w.spec_len >= 1:
                    out.append("acc")
                else:
                    out.append("tok")
            return out
        if len(outcomes) != len(samplers):
            raise ReplayDrift(
                f"tick outcomes arity {len(outcomes)} != samplers "
                f"{len(samplers)} — non-deterministic replay"
            )
        return list(outcomes)

    def _tick(self, rid: int, outcomes, check: bool = True) -> None:
        sc = self.scenario
        rep = self.replicas[rid]
        sched = rep.engine.scheduler
        plan = sched.plan()
        if plan is None:
            if outcomes not in (None, ()) and outcomes != 0:
                raise ReplayDrift(f"idle tick on r{rid} got outcomes "
                                  f"{outcomes!r}")
            return
        # the engine's stage handling: decode each promoted page's blob
        # out of the store (real get + pinned-buffer path); the jitted
        # scatter itself is device work the null engine skips
        for s in plan.stage:
            rep.engine.spiller.load(s.key)
            self.log.append(("promote", rid, self.req_index(s.state)))
        samplers = [w for w in plan.work if w.sample]
        if check:
            check_event(self, rid, plan)
        syms = self._outcomes_for(samplers, outcomes)
        n_slots = sched.max_slots
        width = max(int(sched.spec_max_draft), 0) + 1
        next_tokens = np.zeros((n_slots, width), np.int32)
        n_emit = np.zeros(n_slots, np.int32)
        emitted = 0
        for w, sym in zip(samplers, syms):
            remaining = (w.state.request.max_new_tokens
                         - len(w.state.tokens))
            if sym == "eos":
                n = 1
                next_tokens[w.slot, 0] = sc.eos_token
            elif sym == "acc":
                # accept every draft + the bonus token (the planner caps
                # spec_len at remaining - 1, so this never overruns)
                n = min(w.spec_len + 1, remaining)
                next_tokens[w.slot, :n] = sc.tok_token
            else:
                n = 1
                next_tokens[w.slot, 0] = sc.tok_token
            n_emit[w.slot] = n
            emitted += n
        self.tokens_scheduled += plan.total_tokens
        sched.complete(plan, next_tokens, n_emit=n_emit)
        self.tokens_emitted += emitted

    def _handoff(self, check: bool = True) -> None:
        if self.router is None:
            raise ReplayDrift("handoff event without a fleet")
        before = {
            i: self.replica_of(st)
            for i, st in enumerate(self.states) if st is not None
        }
        moved = self.router._run_handoffs()
        if moved:
            self.log.append(("handoff", moved))
        else:
            self.log.append(("handoff-deferred",))
        if check:
            for i, st in enumerate(self.states):
                if st is None:
                    continue
                after = self.replica_of(st)
                if (after is not None and before.get(i) is not None
                        and after != before[i]
                        and st.request.repetition_penalty != 1.0):
                    raise CheckFailure(
                        "H7", f"penalized request q{i} was handed off "
                              f"(r{before[i]} -> r{after}) — the seen "
                              f"matrix cannot survive a handoff"
                    )

    # ------------------------------------------------- event enumeration
    def enabled_nontick(self) -> List[tuple]:
        """Controlled events enabled in THIS state, excluding ticks
        (tick arity needs a plan probe — explore.py owns that)."""
        sc = self.scenario
        evs: List[tuple] = []
        for i, st in enumerate(self.states):
            if st is None:
                evs.append(("submit", i))
            elif (st.status is RequestStatus.EVICTED
                  and self.resubmits[i] < sc.max_resubmits):
                evs.append(("resubmit", i))
        if self.n_advances < sc.max_advances:
            for k in range(len(sc.advance_dts)):
                evs.append(("advance", k))
        if self.router is not None and self.router._decode:
            if any(rep.role == "prefill" and rep.decode_candidates()
                   for rep in self.replicas):
                evs.append(("handoff",))
        return evs

    def tickable(self) -> List[int]:
        return [rep.replica_id for rep in self.replicas
                if rep.engine.scheduler.has_work]


def replay(scenario: Scenario, trace: Sequence[tuple],
           check: bool = False) -> World:
    """Reconstruct the state a trace leads to, from scratch. With
    ``check`` the invariant registry runs after every event — the
    counterexample round-trip mode."""
    with faults.arming(*scenario.mutations):
        w = World(scenario)
        for ev in trace:
            w.apply(ev, check=check)
    return w
