"""Canonical state fingerprints: the model checker's visited-set key.

Two worlds get the SAME fingerprint exactly when no enabled event can
tell them apart — the soundness condition for BFS dedup. That means:

- **Physical page identity is anonymized.** Pages are relabeled by
  first appearance (slot order, then prefix-cache LRU order); the free
  list contributes only its size. Permuting which physical pages are
  free must not split states (test_fleetcheck asserts this).
- **Host store keys are anonymized** the same way (keys are an
  allocation counter — logically meaningless).
- **Absolute time is dropped.** Only behavior-relevant RELATIVE times
  survive: queue age vs the timeout, retry_after distance. The plan
  tick counter is rank-normalized per replica (only the cold-victim
  ORDERING of ``last_planned`` matters, never its absolute value).
- **Progress meters, logs and metrics are excluded** — they grow
  monotonically and would make every state unique. (The liveness pass
  compares progress ACROSS visits of one fingerprint instead.)

Everything that CAN change a successor is included: slot contents +
page tables + host maps, queue order + ages, free-slot stack order,
decode round-robin cursor, promotion focus, prefix cache LRU (both
tiers) + pins, router cursor + session map, per-request lifecycle and
the remaining event allowances (advances, resubmits).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ...serving.request import RequestStatus

__all__ = ["fingerprint"]


def _rel(t: Optional[float], now: float) -> Optional[float]:
    return None if t is None else round(t - now, 9)


class _Canon:
    """First-appearance relabeling for one id namespace."""

    def __init__(self):
        self._map: Dict[int, int] = {}

    def __call__(self, raw: int) -> int:
        if raw == -1:
            return -1
        return self._map.setdefault(raw, len(self._map))


def _state_fp(world, st, now: float, cpage: _Canon, ckey: _Canon,
              rank: Dict[int, int]):
    return (
        world.req_index(st),
        st.status.value,
        st.prompt_pos,
        tuple(st.tokens),
        tuple(st.draft_tail),
        st.cached_tokens,
        st.owned_from,
        tuple(cpage(p) for p in st.pages),
        tuple((li, ckey(k), owned)
              for li, (k, owned) in sorted(st.host_pages.items())),
        rank.get(st.last_planned, -1),
        st.attempts,
    )


def _replica_fp(world, rep, now: float, ckey: _Canon):
    sched = rep.engine.scheduler
    cpage = _Canon()
    # rank-normalize last_planned across this replica's live states:
    # only the relative coldness ordering drives demotion victims
    lp = sorted({
        s.last_planned
        for s in list(sched.slots) + list(sched.queue) if s is not None
    })
    rank = {v: i for i, v in enumerate(lp)}

    slots = tuple(
        None if s is None else
        _state_fp(world, s, now, cpage, ckey, rank)
        + (s.slot in sched._fresh,)
        for s in sched.slots
    )
    queue = tuple(
        (_state_fp(world, s, now, cpage, ckey, rank),
         _rel(s.arrival_t, now))
        for s in sched.queue
    )
    cache_fp = ()
    if sched.prefix_cache is not None:
        cache = sched.prefix_cache
        cache_fp = (
            tuple((kind, h, cpage(page), toks)
                  for (kind, h, page, toks) in cache._lru),
            tuple((h, ckey(cache._host_full[h][0]))
                  for h in cache._host_lru),
            tuple(sorted((ckey(k), n)
                         for k, n in cache._host_pins.items())),
        )
    store = world.stores[rep.replica_id]
    store_fp = () if store is None else (
        store.host_count, store.disk_count,
        tuple(sorted((ckey(k), owned)
                     for k, owned in sched._inflight.items())),
    )
    return (
        slots,
        queue,
        tuple(sched._free),
        sched._decode_rr,
        sched._promote_focus,
        sched.pool.free_count if sched.paged else None,
        cache_fp,
        store_fp,
    )


def fingerprint(world):
    """Hashable canonical fingerprint of a :class:`World`."""
    now = world.clock()
    ckey = _Canon()  # host keys are per-replica stores, but a single
    #   first-appearance namespace keeps the relabeling deterministic
    reps = tuple(
        _replica_fp(world, rep, now, ckey) for rep in world.replicas
    )
    requests = tuple(
        (None if st is None else (
            st.status.value,
            st.attempts,
            world.resubmits[i],
            _rel(st.retry_after, now)
            if st.status is RequestStatus.EVICTED else None,
            len(st.tokens),
        ))
        for i, st in enumerate(world.states)
    )
    router_fp = None
    if world.router is not None:
        router_fp = (
            world.router._rr,
            tuple(sorted(world.router._sessions.items())),
        )
    return (reps, requests, router_fp, world.n_advances)
