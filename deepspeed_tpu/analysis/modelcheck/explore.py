"""fleetcheck exploration: bounded BFS over event interleavings.

State model: **replay-from-scratch**. A state IS its event trace; to
expand a node the explorer replays the trace into a fresh
:class:`~.world.World` (cheap — fake clock, null device, numpy-only),
applies one more event, checks H1–H7, fingerprints, dedups. No deepcopy
ever touches the live host objects, and every counterexample is a
replayable trace by construction. BFS order makes the first reported
counterexample a MINIMAL one (no shorter trace reaches a violation).

Per discovered state the explorer also runs the **liveness drain**: the
all-EOS policy (every sampler emits EOS, handoffs run, nothing else
arrives) must reach quiescence — all submitted requests DONE/EVICTED —
within ``drain_horizon`` ticks. A fingerprint recurring at unchanged
cumulative progress during the drain is a **LIVELOCK** (the PR 18
promotion-thrash class); horizon exhaustion is **NO_QUIESCENCE**. A
quiesce-cache of fingerprints already known to drain keeps the pass
near-linear.
"""

from __future__ import annotations

import itertools
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ...serving import faults
from .fingerprint import fingerprint
from .invariants import INVARIANTS, CheckFailure
from .scenarios import Scenario
from .world import World, replay

__all__ = ["explore", "random_walk", "CheckResult", "Violation"]

# cap on sampler-outcome combinations enumerated per tick event; the
# presets stay far under it (<= 4 samplers), it only guards pathology
_MAX_OUTCOME_COMBOS = 128


@dataclass
class Violation:
    invariant: str            # H1..H7 | LIVELOCK | NO_QUIESCENCE | ...
    message: str
    trace: Tuple[tuple, ...]  # replayable event trace reaching it
    replica: Optional[int] = None

    def format(self) -> str:
        what = INVARIANTS.get(self.invariant, "")
        lines = [f"VIOLATION {self.invariant}"
                 + (f" — {what}" if what else ""),
                 f"  {self.message}",
                 f"  trace ({len(self.trace)} events):"]
        for i, ev in enumerate(self.trace):
            lines.append(f"    {i + 1:2d}. {_fmt_event(ev)}")
        return "\n".join(lines)


@dataclass
class CheckResult:
    scenario: Scenario
    violations: List[Violation] = field(default_factory=list)
    states: int = 0
    transitions: int = 0
    max_depth_reached: int = 0
    truncated: bool = False
    drains: int = 0
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return not self.violations

    def format(self) -> str:
        sc = self.scenario
        head = (
            f"fleetcheck: {sc.describe()}\n"
            f"  explored {self.states} states / {self.transitions} "
            f"transitions to depth {self.max_depth_reached} "
            f"({'bounds hit' if self.truncated else 'exhaustive'}), "
            f"{self.drains} liveness drains, {self.elapsed_s:.2f}s"
        )
        if self.ok:
            return head + "\n  OK — H1-H7 hold and every state quiesces"
        return head + "\n" + "\n".join(
            v.format() for v in self.violations
        )

    def to_dict(self) -> dict:
        return {
            "scenario": self.scenario.name,
            "ok": self.ok,
            "states": self.states,
            "transitions": self.transitions,
            "max_depth_reached": self.max_depth_reached,
            "truncated": self.truncated,
            "drains": self.drains,
            "elapsed_s": round(self.elapsed_s, 3),
            "violations": [
                {"invariant": v.invariant, "message": v.message,
                 "trace": [list(map(str, ev)) for ev in v.trace]}
                for v in self.violations
            ],
        }


def _fmt_event(ev: tuple) -> str:
    kind = ev[0]
    if kind in ("submit", "resubmit"):
        return f"{kind} q{ev[1]}"
    if kind == "advance":
        return f"advance clock (dt index {ev[1]})"
    if kind == "handoff":
        return "handoff pass"
    if kind == "tick":
        out = ev[2]
        if out is None:
            return f"tick r{ev[1]} (all-EOS drain)"
        if isinstance(out, int):
            return f"tick r{ev[1]} (random mask {out:#x})"
        return f"tick r{ev[1]} outcomes [{', '.join(out) or 'promote-only'}]"
    return repr(ev)


def _tick_events(world: World) -> Tuple[List[tuple], List[Violation]]:
    """Enumerate tick events enabled in ``world``'s state, with every
    sampler-outcome combination. MUTATES world (plan() admits/evicts) —
    callers pass a throwaway probe replay."""
    events: List[tuple] = []
    violations: List[Violation] = []
    base = tuple(world.trace)
    for rid in world.tickable():
        sched = world.replicas[rid].engine.scheduler
        try:
            plan = sched.plan()
        except CheckFailure as e:
            violations.append(Violation(
                e.invariant, str(e), base + (("tick", rid, ()),), rid))
            continue
        except AssertionError as e:
            violations.append(Violation(
                "INTERNAL_ASSERT", str(e) or "assertion failed",
                base + (("tick", rid, ()),), rid))
            continue
        if plan is None:
            # plan() may still have evicted timeouts / admitted — the
            # idle tick is a real event; dedup absorbs true no-ops
            events.append(("tick", rid, ()))
            continue
        alphabets = []
        for w in plan.work:
            if not w.sample:
                continue
            syms = ["tok", "eos"]
            if w.spec_len >= 1:
                syms.append("acc")
            alphabets.append(syms)
        combos = itertools.islice(
            itertools.product(*alphabets), _MAX_OUTCOME_COMBOS
        )
        for outcomes in combos:
            events.append(("tick", rid, tuple(outcomes)))
    return events, violations


def _drain(world: World, quiesce_cache: Set) -> Optional[Violation]:
    """All-EOS liveness drain, in place. Returns a LIVELOCK /
    NO_QUIESCENCE violation or None (quiesced)."""
    sc = world.scenario
    seen: List = []
    progress_at: Dict = {}
    start_progress = world.progress
    for step in range(sc.drain_horizon):
        if world.quiescent():
            quiesce_cache.update(seen)
            return None
        fp = fingerprint(world)
        if fp in quiesce_cache:
            quiesce_cache.update(seen)
            return None
        if fp in progress_at and progress_at[fp] == world.progress:
            return Violation(
                "LIVELOCK",
                f"drain revisited a state after "
                f"{step - seen.index(fp)} ticks with zero token "
                f"progress — the system cycles without ever finishing "
                f"its {sum(1 for s in world.states if s is not None)} "
                f"live requests",
                tuple(world.trace),
            )
        progress_at[fp] = world.progress
        seen.append(fp)
        # one drain round: every busy replica ticks all-EOS, then one
        # handoff pass moves finished prefills so decode replicas drain
        for rid in world.tickable():
            world.apply(("tick", rid, None), check=False)
        if world.router is not None and world.router._decode:
            if any(rep.role == "prefill" and rep.decode_candidates()
                   for rep in world.replicas):
                world.apply(("handoff",), check=False)
    if world.quiescent():
        quiesce_cache.update(seen)
        return None
    return Violation(
        "NO_QUIESCENCE",
        f"still not quiescent after {sc.drain_horizon} all-EOS drain "
        f"ticks (progress {start_progress} -> {world.progress})",
        tuple(world.trace),
    )


def _safe_drain(world: World, quiesce_cache: Set) -> Optional[Violation]:
    """_drain, with production-side assertion trips surfaced as
    violations instead of crashing the exploration."""
    try:
        return _drain(world, quiesce_cache)
    except CheckFailure as e:
        return Violation(e.invariant, str(e), tuple(world.trace))
    except AssertionError as e:
        return Violation("INTERNAL_ASSERT", str(e) or "assertion failed",
                         tuple(world.trace))


def explore(scenario: Scenario, stop_on_first: bool = True
            ) -> CheckResult:
    """Exhaustive bounded exploration of one scenario. Arms the
    scenario's seeded faults for the whole run (clean scenarios arm
    nothing)."""
    t0 = time.monotonic()
    res = CheckResult(scenario)
    quiesce_cache: Set = set()

    def out_of_budget() -> bool:
        return (time.monotonic() - t0 > scenario.budget_s
                or res.states >= scenario.max_states)

    with faults.arming(*scenario.mutations):
        root = World(scenario)
        visited = {fingerprint(root)}
        res.states = 1
        lv = _safe_drain(root, quiesce_cache)
        if lv is not None:
            res.violations.append(lv)
            if stop_on_first:
                res.elapsed_s = time.monotonic() - t0
                return res
        frontier: deque = deque([()])
        while frontier:
            if out_of_budget():
                res.truncated = True
                break
            trace = frontier.popleft()
            if len(trace) >= scenario.max_depth:
                # the depth bound is part of the scenario's definition —
                # exploring every interleaving UP TO it is exhaustive
                continue
            probe = replay(scenario, trace)
            events = probe.enabled_nontick()
            tick_evs, tick_violations = _tick_events(probe)
            events.extend(tick_evs)
            for v in tick_violations:
                res.violations.append(v)
                if stop_on_first:
                    res.elapsed_s = time.monotonic() - t0
                    return res
            for ev in events:
                if out_of_budget():
                    res.truncated = True
                    break
                res.transitions += 1
                w = replay(scenario, trace)
                try:
                    w.apply(ev, check=True)
                except CheckFailure as e:
                    res.violations.append(Violation(
                        e.invariant, str(e), trace + (ev,)))
                    if stop_on_first:
                        res.elapsed_s = time.monotonic() - t0
                        return res
                    continue
                except AssertionError as e:
                    res.violations.append(Violation(
                        "INTERNAL_ASSERT", str(e) or "assertion failed",
                        trace + (ev,)))
                    if stop_on_first:
                        res.elapsed_s = time.monotonic() - t0
                        return res
                    continue
                fp = fingerprint(w)
                if fp in visited:
                    continue
                visited.add(fp)
                res.states += 1
                res.max_depth_reached = max(res.max_depth_reached,
                                            len(trace) + 1)
                frontier.append(trace + (ev,))
                res.drains += 1
                lv = _safe_drain(w, quiesce_cache)  # reuses w in place
                if lv is not None:
                    res.violations.append(lv)
                    if stop_on_first:
                        res.elapsed_s = time.monotonic() - t0
                        return res
    res.elapsed_s = time.monotonic() - t0
    return res


@dataclass
class WalkResult:
    trace: Tuple[tuple, ...]
    log: Tuple[tuple, ...]
    final_fingerprint: object
    violation: Optional[Violation] = None

    @property
    def ok(self) -> bool:
        return self.violation is None


def random_walk(scenario: Scenario, seed: int, steps: int = 64
                ) -> WalkResult:
    """One seeded random walk through the event space, invariants
    checked at every step. Deterministic in (scenario, seed) — the
    determinism-audit regression runs two and diffs their logs."""
    rng = np.random.RandomState(seed)
    with faults.arming(*scenario.mutations):
        world = World(scenario)
        violation = None
        for _ in range(steps):
            choices: List[tuple] = world.enabled_nontick()
            choices.extend(("tick", rid) for rid in world.tickable())
            if not choices:
                break
            ev = choices[int(rng.randint(len(choices)))]
            if ev[0] == "tick":
                ev = ("tick", ev[1], int(rng.randint(0, 256)))
            try:
                world.apply(ev, check=True)
            except CheckFailure as e:
                violation = Violation(e.invariant, str(e),
                                      tuple(world.trace))
                break
            except AssertionError as e:
                violation = Violation("INTERNAL_ASSERT",
                                      str(e) or "assertion failed",
                                      tuple(world.trace))
                break
        return WalkResult(
            trace=tuple(world.trace),
            log=tuple(world.log),
            final_fingerprint=fingerprint(world),
            violation=violation,
        )
